"""Mesh-scaling measurements for BASELINE configs #3/#4/#5.

Reference analog: the distributed benchmarks HPX runs per-locality-count
(partitioned_vector STREAM triad, collectives all_reduce, distributed
Jacobi — SURVEY.md §6 configs #3/#4/#5). Here a locality = a mesh
device; the same harness takes real multi-chip hardware unchanged (it
meshes over however many devices jax exposes) and falls back to a
virtual CPU mesh for development, where the numbers measure SCALING
SHAPE (collective/halo overhead vs device count), not absolute GB/s.

One command:  python -m hpx_tpu.run --bench-mesh 8
prints one JSON line per (config, device-count):
  pv_triad        — partitioned_vector a+s*b via the segmented algo
                    layer (config #3), elements/s
  all_reduce_1m   — 1M-float all_reduce over the mesh (config #4),
                    ops/s and algorithm bandwidth
  jacobi2d        — sharded 2-D Jacobi, halo exchange both axes
                    (config #5), Mcells/s
"""

from __future__ import annotations

import json
import time


def _emit(**kv) -> None:
    print(json.dumps(kv), flush=True)


def _time_loop(fn, iters: int, warm: int = 2) -> float:
    """Wall-seconds per iteration (mean of `iters` after warmup)."""
    import jax
    for _ in range(warm):
        out = fn()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = None
    for _ in range(iters):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def bench_pv_triad(ndev: int, devices) -> None:
    """Config #3: STREAM triad over a PartitionedVector via the
    segmented-algorithm dispatch (one sharded XLA program)."""
    import jax.numpy as jnp
    import numpy as np

    from hpx_tpu.algo import transform
    from hpx_tpu.containers.partitioned_vector import PartitionedVector
    from hpx_tpu.dist.distribution_policies import ContainerLayout
    from hpx_tpu.exec.policies import par
    from hpx_tpu.parallel import make_mesh

    mesh = make_mesh((ndev,), ("x",), devices[:ndev])
    layout = ContainerLayout(mesh=mesh)
    n = ndev * (1 << 20)                      # weak scaling: 1M/device
    rng = np.random.default_rng(0)
    a = PartitionedVector.from_array(
        jnp.asarray(rng.random(n, np.float32)), layout=layout)
    b = PartitionedVector.from_array(
        jnp.asarray(rng.random(n, np.float32)), layout=layout)
    s = jnp.float32(1e-7)

    def run():
        return transform(par, a, lambda x, y: x + s * y, b).data

    per = _time_loop(run, iters=10)
    _emit(metric="pv_triad", n_devices=ndev, elements=n,
          meps=round(n / per / 1e6, 1),
          gbs=round(3 * n * 4 / per / 1e9, 2),
          us_per_op=round(per * 1e6, 1))


def bench_all_reduce(ndev: int, devices) -> None:
    """Config #4: 1M-float all_reduce over the mesh (XLA psum over
    ICI on hardware). Algorithm bandwidth uses the ring-allreduce
    convention 2(P-1)/P * bytes."""
    import jax.numpy as jnp
    import numpy as np

    from hpx_tpu.collectives.device import all_reduce
    from hpx_tpu.parallel import make_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P
    import jax

    mesh = make_mesh((ndev,), ("x",), devices[:ndev])
    n = 1 << 20
    x = jax.device_put(
        jnp.asarray(np.random.default_rng(1).random(n, np.float32)),
        NamedSharding(mesh, P("x")))

    def run():
        return all_reduce(x, mesh, "x")

    per = _time_loop(run, iters=10)
    bw = 2 * (ndev - 1) / max(ndev, 1) * n * 4 / per / 1e9 if ndev > 1 \
        else 0.0
    _emit(metric="all_reduce_1m", n_devices=ndev, elements=n,
          us_per_op=round(per * 1e6, 1), algo_gbs=round(bw, 2))


def bench_jacobi(ndev: int, devices) -> None:
    """Config #5: sharded 2-D Jacobi, halos via ppermute on both mesh
    axes, all sweeps fused per dispatch."""
    import math

    from hpx_tpu.models.jacobi2d import JacobiParams, jacobi_sharded
    from hpx_tpu.parallel import make_mesh

    ax = 2 ** (int(math.log2(ndev)) // 2) if ndev > 1 else 1
    ay = ndev // ax
    mesh = make_mesh((ax, ay), ("x", "y"), devices[:ndev])
    n = 1024
    iters = 50
    p = JacobiParams(nx=n, ny=n, nb=1, iterations=iters)

    def run():
        u, res = jacobi_sharded(p, mesh)
        return res

    per = _time_loop(run, iters=5)
    cells = n * n * iters / per
    _emit(metric="jacobi2d", n_devices=ndev, grid=f"{n}x{n}",
          mesh=f"{ax}x{ay}", iterations=iters,
          mcells=round(cells / 1e6, 1))


def bench_fft(ndev: int, devices) -> None:
    """Distributed 1-D FFT (four-step, three all_to_alls) — the
    collectives workload HPX's published FFT study measures; weak
    scaling at 2^18 points/device."""
    import math

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from hpx_tpu.algo import fft as dfft
    from hpx_tpu.parallel import make_mesh

    mesh = make_mesh((ndev,), ("x",), devices[:ndev])
    # n = P^2 * m always satisfies the four-step factorability (P | n1
    # and P | n2); m sized for ~2^18 points per device
    n = ndev * ndev * max(1, (1 << 18) // ndev)
    rng = np.random.default_rng(1)
    v = jax.device_put(
        jnp.asarray((rng.standard_normal(n) + 1j * rng.standard_normal(n)
                     ).astype(np.complex64)),
        NamedSharding(mesh, P("x")))

    def run():
        return dfft.fft_sharded(v, mesh)

    per = _time_loop(run, iters=5)
    gflops = 5 * n * math.log2(n) / per / 1e9
    _emit(metric="fft_1d", n_devices=ndev, n=n,
          gflops=round(gflops, 2), ms=round(per * 1e3, 3))


def bench_sort(ndev: int, devices) -> None:
    """Distributed PSRS sample sort (weak scaling at 2^17 elems/device):
    collective-step count is constant in mesh size, so per-op time
    should stay flat as devices grow — the curve this table exists to
    show. The sample path is FORCED at every ndev (not the p<=4
    odd-even default) so the measured program is the pod-scale one."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from hpx_tpu.algo.sorting import sort_sharded
    from hpx_tpu.parallel import make_mesh

    mesh = make_mesh((ndev,), ("x",), devices[:ndev])
    n = ndev * (1 << 17)
    rng = np.random.default_rng(2)
    v = jax.device_put(
        jnp.asarray(rng.standard_normal(n).astype(np.float32)),
        NamedSharding(mesh, P("x")))
    if ndev > 1:
        run = lambda: sort_sharded(v, mesh, method="sample")  # noqa: E731
        method = "sample"
    else:
        run = lambda: jnp.sort(v)  # noqa: E731 — 1-dev reference program
        method = "jnp.sort"

    per = _time_loop(run, iters=5)
    _emit(metric="sort_sample", n_devices=ndev, elements=n,
          method=method,                     # self-describing: the
          melem_s=round(n / per / 1e6, 2),   # 1-dev row is a DIFFERENT
          ms=round(per * 1e3, 3))            # program (local reference)


def bench_paged_serving(ndev: int, devices) -> None:
    """Sharded paged serving: greedy continuous-batching decode over a
    (dp, tp) mesh — KV block pool sharded over tp on kv heads, slots
    and device block tables over dp. Weak in neither sense: the mix is
    FIXED, so the curve shows how decode latency absorbs devices (tp
    splits the attention/MLP math, dp splits the slots). The 1-device
    row runs the plain single-device paged server (a DIFFERENT
    program — the reference, like sort's jnp.sort row)."""
    import math

    import jax
    import numpy as np

    from hpx_tpu.models import transformer as tfm
    from hpx_tpu.models.serving import ContinuousServer
    from hpx_tpu.parallel import make_mesh

    cfg = tfm.TransformerConfig(vocab=256, d_model=64, n_heads=4,
                                head_dim=16, n_layers=2, d_ff=128)
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(3)
    reqs = [(rng.integers(1, 200, 16).tolist(), 24) for _ in range(6)]
    total = sum(m for _, m in reqs)

    if ndev == 1:
        mesh, dp, tp = None, 1, 1
    else:
        dp = 2 ** (int(math.log2(ndev)) // 2)
        tp = ndev // dp
        if cfg.n_heads % tp:            # tp must divide kv heads
            tp = math.gcd(tp, cfg.n_heads)
            dp = ndev // tp
        mesh = make_mesh((dp, tp), ("dp", "tp"), devices[:ndev])
    slots = max(4, dp)                  # dp | slots

    def run():
        srv = ContinuousServer(params, cfg, slots=slots, smax=64,
                               paged=True, mesh=mesh)
        for p, m in reqs:
            srv.submit(p, max_new=m)
        t0 = time.perf_counter()
        srv.run()
        return time.perf_counter() - t0

    run()                               # compile
    per = run()
    _emit(metric="paged_serving", n_devices=ndev, mesh=f"{dp}x{tp}",
          slots=slots, tokens=total,
          tokens_per_s=round(total / per, 1),
          ms_per_token=round(per * 1e3 / total, 3))


def sweep(max_devices: int) -> None:
    import jax
    devs = jax.devices()
    assert len(devs) >= max_devices, (
        f"need {max_devices} devices, have {len(devs)} — launch via "
        f"`python -m hpx_tpu.run --bench-mesh N` (it provisions a "
        f"virtual CPU mesh when hardware is short)")
    _emit(metric="mesh_info", platform=devs[0].platform,
          n_available=len(devs))
    counts = []
    k = 1
    while k <= max_devices:
        counts.append(k)
        k *= 2
    if counts[-1] != max_devices:       # non-power-of-two request: the
        counts.append(max_devices)      # asked-for scale must be measured
    for k in counts:
        bench_pv_triad(k, devs)
        bench_all_reduce(k, devs)
        bench_jacobi(k, devs)
        bench_fft(k, devs)
        bench_sort(k, devs)
        bench_paged_serving(k, devs)


if __name__ == "__main__":
    import argparse
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    args = ap.parse_args()
    import jax
    if os.environ.get("HPX_TPU_FORCE_PLATFORM"):
        try:
            jax.config.update(
                "jax_platforms", os.environ["HPX_TPU_FORCE_PLATFORM"])
        except Exception:  # noqa: BLE001
            pass
    sweep(args.devices)
