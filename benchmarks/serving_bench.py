"""Serving throughput harness: tokens/s for the three decode engines.

Measures, on whatever backend jax resolves (the real TPU on the bench
host; CPU for smoke runs with --cpu):

  1. generate            — batched uniform greedy decode
  2. ContinuousServer    — slot-based continuous batching over a ragged
                           request mix (the steady-state serving shape),
                           plus a mixed-UNBUCKETED-length wave reporting
                           cold-start compiles, TTFT, and decode-stall
                           p99 (the bucketed chunked-prefill case)
  3. speculative_generate — draft-assisted greedy (reports rounds too:
                           tokens per target window forward is the
                           speedup lever)
  4. paged_prefix_reuse  — ContinuousServer(paged=True) over a
                           prefix-heavy mix (many requests sharing one
                           long system prompt); reports radix cache hit
                           rate and the fraction of prefill tokens the
                           prefix cache eliminated
  5. serving_spec        — the speculation wave: the SAME mixed
                           repetitive + non-repetitive request mix
                           through a spec-off and a spec-on server
                           (prompt-lookup drafts, per-slot adaptive k);
                           reports acceptance rate, tokens per decode
                           step, warm tokens/s for both runs, and the
                           sha256 of every request's output — the
                           hashes MUST match, speculation only changes
                           how fast identical tokens appear
  6. paged_decode        — the decode-attention roofline wave: one
                           greedy mix through the paged server in each
                           (paged_kernel, kv_dtype) mode over
                           kv_dtype {bf16, int8, fp8} and kernel
                           {gather, fused, fused_online} (fused
                           kernels on TPU only — interpret-mode Pallas
                           is a test vehicle, not a serving path).
                           Reports warm tokens/s, decode-attention HBM
                           bytes/token (sampled at peak occupancy from
                           the /cache hbm-read-per-token feed, so the
                           int8/fp8 byte reductions are MEASURED, not
                           modeled) and the effective attention
                           GFLOP/s, plus a per-cell oracle-match gate:
                           bf16 cells (any kernel, incl. the
                           tolerance-budgeted fused_online) must match
                           the gather/bf16 oracle exactly; quantized
                           cells report their greedy match fraction

Prints one JSON line per engine. This is an operator harness, not part
of bench.py's driver metrics — serving throughput depends on the
request mix, so the mix is printed with the number.

With --trace-out PATH the whole run executes under the causal task
tracer (hpx_tpu.svc.tracing) and a Chrome trace-event JSON — serving
spans, flow arrows, /serving + /cache counter tracks — is written to
PATH, loadable directly in chrome://tracing or https://ui.perfetto.dev.

  7. serving_chaos       — the fault-injection wave (--chaos): the
                           SAME mixed paged+spec request mix through a
                           fault-free server and one with a seeded
                           deterministic fault schedule (decode,
                           chunked-prefill, spec-verify and
                           allocator-OOM faults; spec degrades to
                           sequential after repeated verify faults).
                           Reports goodput for both runs, restores per
                           fault class, restore p99, shed/degraded
                           counts, and the sha256 of every request's
                           output — the hashes MUST match: recovery
                           replays from slot checkpoints over
                           still-resident KV, so a faulted run emits
                           byte-identical tokens, just later. A second
                           overload sub-run (100% decode fault rate)
                           demonstrates typed shedding: the retry
                           budget exhausts and every request fails
                           into `srv.failed` instead of hanging.

  8. serving_disagg      — the disaggregated wave (--disagg): one
                           Poisson-arrival mix (Zipf-shared prefixes,
                           70/30 interactive/batch SLO classes)
                           through a colocated paged server and a
                           DisaggRouter (2 prefill + 2 decode
                           workers). Reports TTFT p50/p95/p99, decode
                           stall p50/p99 (inter-step gap while slots
                           are live) and goodput for BOTH topologies.
                           With --chaos as well, a sub-run kills one
                           worker of each role mid-flight (seeded
                           disagg.prefill/disagg.decode schedule) and
                           GATES on: sha-identical tokens to the
                           fault-free disagg run, >=1 failover per
                           role, zero leaked KV blocks.

  9. paged_mesh          — the sharded serving wave (--mesh): the
                           SAME greedy mix through the single-device
                           paged server and ContinuousServer(
                           paged=True, mesh=(dp, tp)) — KV block pool
                           sharded over tp on kv heads, slots and
                           device block tables over dp. Reports warm
                           tokens/s and decode-stall p50/p99 for BOTH
                           topologies plus the sha256 of every
                           request's output — the hashes MUST match:
                           sharding moves the same program onto more
                           chips, so a misplaced psum shows up here
                           as a sha mismatch, not a vibe. Needs >=4
                           devices (CPU smoke: XLA_FLAGS=
                           --xla_force_host_platform_device_count=8);
                           emits a skipped line otherwise.

  9b. serving_moe        — the expert-parallel MoE wave (--moe): one
                           greedy mix through a single-device MoE
                           paged server and the (dp, tp)-mesh one,
                           experts sharded over tp and decode routing
                           through moe_ffn's tiled all_to_all at the
                           drop-free auto capacity. Reports warm
                           tokens/s, decode-stall p50/p99 and the
                           overflow-drop rate from the /serving moe
                           counters (banked into --metrics-out), and
                           GATES on sha-identical tokens. Rows carry
                           an explicit onchip stamp; needs >=4
                           devices, emits a skipped line otherwise.

 10. serving_fleet      — the fleet wave (--fleet): the SAME warm
                           Zipf-shared-prefix Poisson mix through a
                           FleetRouter in placement=load (pure
                           least-loaded, the baseline) and
                           placement=prefix (digest-scored routing +
                           prefix-seeded prefills). Reports TTFT
                           p50/p99, decode-stall p50/p99, placement
                           counts by policy, and the prefill tokens
                           each mode ACTUALLY skipped on the measured
                           wave. GATES on: sha-identical tokens
                           between the two modes (placement moves
                           work, never changes it), the prefix mode
                           saving strictly more prefill tokens than
                           least-loaded, and zero leaked KV blocks.

 11. serving_tier       — the tiered-KV wave (--tier): one
                           prefix-heavy greedy+sampled mix, radix
                           budget deliberately smaller than the shared
                           chain so the tail demotes to the host-RAM
                           tier and later admissions promote it back
                           (crossover-gated restore). Runs tier-off
                           and tier-on and GATES on: sha-identical
                           outputs, tier-on saving strictly more
                           prefill tokens, zero leaked device blocks
                           and zero leaked host buffers at drain.

 12. serving_ladder      — the learned-ladder wave (--ladder): seed a
                           perfdb (svc/perfdb) from a live profiled
                           run of the mixed-unbucketed mix, re-derive
                           the prefill ladder offline with
                           benchmarks/ladder_search, then cold-boot
                           (program cache cleared) the hand-picked
                           and learned servers on the same mix.
                           Reports warm tok/s + cold compile count
                           for both, provenance-stamped, and GATES on
                           sha-identical outputs — the ROADMAP item 5
                           acceptance loop.

Usage: python benchmarks/serving_bench.py [--cpu] [--scale N]
                                          [--prefix-only] [--spec-only]
                                          [--paged-decode-only] [--mesh]
                                          [--moe] [--chaos] [--disagg]
                                          [--fleet] [--ladder]
                                          [--tier] [--alerts]
                                          [--trace-out PATH]
                                          [--metrics-out PATH]

With --metrics-out PATH the waves' live HistogramCounters (TTFT,
queue wait, KV transfer, decode stall, E2E — merged across workers
for disagg/fleet) are written as a hpx_tpu.metrics.v1 JSON artifact:
full mergeable snapshots plus derived p50/p95/p99.  When --trace-out
and --fleet combine, the router tracer and every worker's private
span ring are stitched by trace_export.merge_traces into ONE Perfetto
trace — per-worker pid rows, clock-aligned, with rid flow arrows
place → prefill → transfer → decode across processes.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

# --metrics-out artifact schema; tests/test_metrics.py smoke-checks it
METRICS_SCHEMA = "hpx_tpu.metrics.v1"


def metrics_artifact(histograms, counters=None,
                     quantiles=(0.5, 0.95, 0.99)):
    """JSON-safe SLO artifact from LIVE HistogramCounters: each
    histogram's full mergeable snapshot plus its derived quantiles
    (bounded-relative-error estimates, not a post-hoc sort of raw
    samples)."""
    hists = {}
    for name in sorted(histograms):
        h = histograms[name]
        hists[name] = {
            "snapshot": h.snapshot(),
            "quantiles": {f"p{round(q * 100.0, 4):g}": h.quantile(q)
                          for q in quantiles},
            "relative_error_bound": h.relative_error_bound(),
        }
    return {"schema": METRICS_SCHEMA, "histograms": hists,
            "counters": dict(counters or {})}


def _configured_perfdb():
    """The persistent perf store at ``hpx.perfdb.path``, or None when
    unset.  Schema errors stay loud — a corrupt store must fail the
    producer, not silently drop its medians."""
    from hpx_tpu.svc import perfdb
    return perfdb.configured_db()


def write_metrics_artifact(path, doc):
    """Atomic write (tmp + rename) so a watcher never reads a torn
    artifact."""
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, path)
    return doc


def main() -> int:
    import jax
    if "--cpu" in sys.argv:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from hpx_tpu.models import transformer as tfm
    from hpx_tpu.models.serving import ContinuousServer

    scale = int(sys.argv[sys.argv.index("--scale") + 1]) \
        if "--scale" in sys.argv else (4 if "--cpu" in sys.argv else 16)
    on_tpu = jax.default_backend() == "tpu"

    trace_out = sys.argv[sys.argv.index("--trace-out") + 1] \
        if "--trace-out" in sys.argv else None
    tracer = None
    if trace_out:
        from hpx_tpu.core.config import runtime_config
        from hpx_tpu.svc import tracing
        runtime_config().set("hpx.trace.enabled", "1")
        tracer = tracing.start_if_configured()

    metrics_out = sys.argv[sys.argv.index("--metrics-out") + 1] \
        if "--metrics-out" in sys.argv else None
    # --metrics-out implies the per-program profiler: the artifact's
    # "programs" section is the roofline/compile-time table ROADMAP
    # items 3/4 consume
    profiler = None
    if metrics_out:
        from hpx_tpu.svc import progprof
        profiler = progprof.start_profiling()
    # live HistogramCounters the waves hand to finish() for the
    # --metrics-out artifact, keyed "<bench>/<metric>"
    collected_hists = {}
    # scalar counters the waves bank for the artifact's "counters"
    # section (merged over the live registry snapshot), keyed
    # "<bench>/<name>" — e.g. the MoE wave's overflow-drop rate
    collected_counters = {}
    # per-wave cold/warm compile counts (utils/compilemon), keyed
    # "<bench>[/<leg>]" -> {"cold": n, "warm": n}.  compilemon was
    # already counting these for the JSON lines; the artifact used to
    # DROP them, which made ladder wins unauditable — finish() now
    # embeds the dict as the artifact's "compiles" section
    collected_compiles = {}
    # (label, chrome-doc) pairs from the fleet wave's worker rings —
    # finish() stitches them with the router tracer into ONE trace
    fleet_trace_docs = []

    d = 64 * scale
    cfg = tfm.TransformerConfig(
        vocab=1024, d_model=d, n_heads=8, head_dim=d // 8,
        n_layers=4, d_ff=4 * d,
        dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    draft_cfg = tfm.TransformerConfig(
        vocab=1024, d_model=d // 4, n_heads=2, head_dim=d // 8,
        n_layers=1, d_ff=d, dtype=cfg.dtype)
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    draft = tfm.init_params(draft_cfg, jax.random.PRNGKey(1))
    rng = np.random.default_rng(0)

    def emit(name, toks, secs, **extra):
        line = {"engine": name, "tokens": toks,
                "seconds": round(secs, 4),
                "tokens_per_s": round(toks / secs, 1)}
        line.update(extra)
        print(json.dumps(line), flush=True)

    # 4. paged KV cache with radix prefix reuse: 12 requests sharing a
    # 64-token system prompt with short unique tails — the agentic /
    # chat-assistant shape where prefix caching pays. The first request
    # through prefills the shared prefix; later admissions splice its
    # blocks straight from the radix tree.
    def paged_prefix_bench():
        shared = rng.integers(1, 1000, 64).tolist()
        preqs = [(shared + rng.integers(1, 1000, 8).tolist(),
                  int(rng.integers(16, 33))) for _ in range(12)]
        ptotal = sum(m for _, m in preqs)

        def run_paged():
            srv = ContinuousServer(params, cfg, slots=4, smax=160,
                                   paged=True)
            for p, m in preqs:
                srv.submit(p, max_new=m)
            t0 = time.perf_counter()
            srv.run()
            return srv, time.perf_counter() - t0

        run_paged()                                    # compile
        srv, secs = run_paged()
        st = srv.cache_stats()
        computed = st["prefill_tokens_computed"]
        saved = st["prefill_tokens_saved"]
        emit("paged_prefix_reuse", ptotal, secs,
             mix="12 reqs 64-tok shared prefix + 8-tok tail over 4 slots",
             cache_hit_rate=round(st["hit_rate"], 3),
             prefill_tokens_saved=saved,
             prefill_tokens_computed=computed,
             prefill_saved_frac=round(saved / (saved + computed), 3))

    # 4b. the tiered-KV wave (--tier): two 96-token shared prefixes
    # ALTERNATE over one slot under a 4-block radix budget — each
    # retire's budget sweep evicts the other (reader-free) chain
    # wholesale, so the next admission of that prefix is restorable
    # ONLY from the host tier. Tier-off this mix saves zero prefill
    # tokens (every chain dies before its reuse); tier-on the
    # crossover gate promotes the full prefix back each time.
    # Identity is gated against a HOT-RETENTION ORACLE (tier off,
    # UNBOUNDED radix budget: every reuse is a plain hot radix match):
    # a promoted block must be byte-for-byte what hot retention would
    # have served, so sha(tier-on) == sha(oracle) exactly. The
    # budget-constrained tier-off wave is NOT the identity baseline —
    # it never matches, and at fp8 a matched admission reads
    # dequantized (quantizer-roundtripped) prefix rows while a
    # recomputed one reads full-precision rows, a pre-existing
    # prefix-reuse asymmetry independent of the tier (bf16/int8 are
    # unaffected). That wave instead gates the strict-increase clause:
    # tier-on must save STRICTLY more prefill tokens than tier-off
    # with promotions actually observed, and zero leaked device blocks
    # AND zero leaked host buffers once the radix drains — in all
    # three waves.
    def tier_bench() -> None:
        import hashlib
        from hpx_tpu.core.config import runtime_config
        rc = runtime_config()
        prefixes = [rng.integers(1, 1000, 96).tolist(),
                    rng.integers(1, 1000, 96).tolist()]
        treqs = [(prefixes[i % 2] + rng.integers(1, 1000, 8).tolist(),
                  int(rng.integers(12, 25))) for i in range(8)]
        ttotal = sum(m for _, m in treqs)

        def run_wave(tier_on, budget=4):
            rc.set("hpx.cache.tier.enable", "1" if tier_on else "0")
            try:
                srv = ContinuousServer(params, cfg, slots=1, smax=160,
                                       paged=True, block_size=16,
                                       kv_dtype="fp8",
                                       radix_budget_blocks=budget)
                free0 = srv._alloc.stats()["free"]
                for i, (p, m) in enumerate(treqs):
                    if i % 3 == 2:
                        # sampled rows reuse per-index keys across the
                        # two runs — identity must hold beyond greedy
                        srv.submit(p, max_new=m, temperature=0.8,
                                   key=jax.random.PRNGKey(1000 + i))
                    else:
                        srv.submit(p, max_new=m)
                t0 = time.perf_counter()
                out = srv.run()
                secs = time.perf_counter() - t0
                st = srv.cache_stats()
                while sum(srv._radix.evict(1)):
                    pass                        # drain the tree
                dev_leak = free0 - srv._alloc.stats()["free"]
                host_leak = (srv._tier.leaked_buffers()
                             if srv._tier is not None else 0)
                sha = hashlib.sha256(json.dumps(
                    [out[r] for r in sorted(out)]).encode()).hexdigest()
                return secs, st, sha, dev_leak, host_leak
            finally:
                rc.set("hpx.cache.tier.enable", "0")

        run_wave(False)                        # compile
        run_wave(True)                         # compile (restore prog)
        off_secs, off_st, off_sha, off_dev, off_host = run_wave(False)
        (_, hot_st, hot_sha,
         hot_dev, hot_host) = run_wave(False, budget=None)  # oracle
        secs, st, sha, dev_leak, host_leak = run_wave(True)
        emit("serving_tier", ttotal, secs,
             mix="8 reqs alternating two 96-tok shared prefixes + "
                 "8-tok tails over 1 slot, radix budget 4 blocks, "
                 "fp8 KV",
             prefill_tokens_saved={
                 "off": off_st["prefill_tokens_saved"],
                 "on": st["prefill_tokens_saved"]},
             tier_demoted=st.get("tier_demoted", 0),
             tier_promoted=st.get("tier_promoted", 0),
             tier_declined=st.get("tier_declined", 0),
             baseline_tokens_per_s=round(ttotal / off_secs, 1),
             kv_blocks_leaked={"off": off_dev, "hot": hot_dev,
                               "on": dev_leak},
             host_buffers_leaked=host_leak + off_host + hot_host,
             output_sha=sha[:16],
             output_identical_to_hot_oracle=(sha == hot_sha))
        if (sha != hot_sha
                or st["prefill_tokens_saved"]
                <= off_st["prefill_tokens_saved"]
                or st["prefill_tokens_saved"]
                != hot_st["prefill_tokens_saved"]
                or not st.get("tier_promoted")
                or dev_leak or off_dev or hot_dev
                or host_leak or off_host or hot_host):
            print(json.dumps({
                "error": "tier gate failed",
                "hot_oracle_sha": hot_sha[:16], "on_sha": sha[:16],
                "prefill_tokens_saved": {
                    "off": off_st["prefill_tokens_saved"],
                    "hot": hot_st["prefill_tokens_saved"],
                    "on": st["prefill_tokens_saved"]},
                "kv_blocks_leaked": {"off": off_dev, "hot": hot_dev,
                                     "on": dev_leak},
                "host_buffers_leaked": (host_leak + off_host
                                        + hot_host)}),
                flush=True)
            raise SystemExit(2)

    # 5. the speculation wave: half the mix is repetitive (periodic
    # prompts whose continuations prompt-lookup nails), half is random
    # (drafts mostly rejected — the floor case). Byte-identity is
    # CHECKED here, not assumed: both servers' outputs are hashed.
    def spec_wave_bench():
        import hashlib
        rep = [(([11, 23, 7, 42] * 12)[:40], 48) for _ in range(4)]
        rnd = [(rng.integers(1, 1000, 24).tolist(),
                int(rng.integers(24, 49))) for _ in range(4)]
        sreqs = rep + rnd
        stotal = sum(m for _, m in sreqs)

        def run_wave(spec):
            srv = ContinuousServer(params, cfg, slots=4, smax=128,
                                   spec=spec, spec_k=4)
            for p, m in sreqs:
                srv.submit(p, max_new=m)
            srv.run()                                  # compile
            srv = ContinuousServer(params, cfg, slots=4, smax=128,
                                   spec=spec, spec_k=4)
            for p, m in sreqs:
                srv.submit(p, max_new=m)
            t0 = time.perf_counter()
            out = srv.run()
            secs = time.perf_counter() - t0
            sha = hashlib.sha256(json.dumps(
                [out[r] for r in sorted(out)]).encode()).hexdigest()
            return srv, secs, sha

        base_srv, base_secs, base_sha = run_wave(False)
        srv, secs, sha = run_wave(True)
        st = srv.spec_stats()
        emit("serving_spec", stotal, secs,
             mix="4 periodic + 4 random reqs new24-48 over 4 slots",
             draft="prompt", spec_k=4,
             acceptance_rate=round(st["acceptance_rate"], 3),
             tokens_per_step=round(st["tokens_per_step"], 2),
             baseline_tokens_per_s=round(stotal / base_secs, 1),
             output_sha=sha[:16],
             output_identical=(sha == base_sha))
        if sha != base_sha:
            print(json.dumps({"error": "spec output diverged",
                              "baseline_sha": base_sha[:16],
                              "spec_sha": sha[:16]}), flush=True)
            raise SystemExit(2)

    # 6. decode-attention roofline wave: the same greedy mix through
    # each (paged_kernel, kv_dtype) mode. bytes/token samples the
    # hbm_read_stats feed at PEAK table occupancy (mid-run max, not
    # the post-run zero), so the int8 ~2x / fp8 ~4x-vs-f32 reductions
    # are measured numbers; effective GFLOP/s models decode attention
    # as its two matmuls (QK^T + PV: 4 * S * n_heads * head_dim flops
    # per token per layer over the occupancy-derived S).
    def paged_decode_bench():
        dreqs = [(rng.integers(1, 1000, 24).tolist(), 48)
                 for _ in range(8)]
        dtotal = sum(m for _, m in dreqs)
        dtypes = ("bf16", "int8", "fp8")
        modes = [("gather", kvd) for kvd in dtypes]
        if on_tpu:
            modes += [(kern, kvd) for kern in ("fused", "fused_online")
                      for kvd in dtypes]

        def run_mode(kern, kvd):
            def run_once():
                srv = ContinuousServer(params, cfg, slots=4, smax=128,
                                       paged=True, paged_kernel=kern,
                                       kv_dtype=kvd,
                                       prefix_reuse=False)
                for p, m in dreqs:
                    srv.submit(p, max_new=m)
                t0 = time.perf_counter()
                peak = {"hbm_read_blocks_per_token": 0.0,
                        "hbm_read_bytes_per_token": 0.0}
                while srv.step():
                    st = srv.hbm_read_stats()
                    if (st["hbm_read_bytes_per_token"]
                            > peak["hbm_read_bytes_per_token"]):
                        peak = st
                secs = time.perf_counter() - t0
                out, srv._done = srv._done, {}
                return (secs, peak, [out[r] for r in sorted(out)],
                        srv.block_size)

            run_once()                                 # compile
            return run_once()

        results = {}
        for kern, kvd in modes:
            results[(kern, kvd)] = run_mode(kern, kvd)
        oracle_toks = results[("gather", "bf16")][2]
        bf16_bytes = results[("gather", "bf16")][1][
            "hbm_read_bytes_per_token"]
        for (kern, kvd), (secs, peak, toks, bs) in results.items():
            tps = dtotal / secs
            # occupancy-derived attended length: blocks/token * bs
            s_eff = peak["hbm_read_blocks_per_token"] * bs
            flops_tok = (4 * s_eff * cfg.n_heads * cfg.head_dim
                         * cfg.n_layers)
            match = sum(a == b for a, b in zip(toks, oracle_toks))
            emit(f"paged_decode_{kern}_{kvd}", dtotal, secs,
                 mix="8 reqs plen24 new48 over 4 slots",
                 hbm_blocks_per_token=round(
                     peak["hbm_read_blocks_per_token"], 2),
                 hbm_bytes_per_token=int(
                     peak["hbm_read_bytes_per_token"]),
                 bytes_vs_bf16=round(
                     peak["hbm_read_bytes_per_token"]
                     / bf16_bytes, 3) if bf16_bytes else None,
                 attn_gflops_per_s=round(flops_tok * tps / 1e9, 2),
                 outputs_match_bf16_oracle=f"{match}/{len(toks)}")
            if kvd == "bf16" and toks != oracle_toks:
                print(json.dumps({"error": "bf16 paged modes "
                                  "diverged", "mode": kern}),
                      flush=True)
                raise SystemExit(2)

    # 9. the sharded serving wave: the same greedy mix through the
    # single-device paged server and the (dp, tp)-mesh paged server
    # (pool over tp kv heads, slots + device block tables over dp).
    # Identity is CHECKED: sharding is a placement change, not an
    # algorithm change, so tokens must be byte-identical.
    def mesh_paged_bench():
        import hashlib
        ndev = len(jax.devices())
        if ndev < 4:
            print(json.dumps({
                "engine": "paged_mesh", "skipped": True,
                "reason": f"needs >=4 devices, have {ndev} (CPU smoke:"
                          " XLA_FLAGS=--xla_force_host_platform"
                          "_device_count=8)"}), flush=True)
            return
        tp = 4 if (ndev >= 8 and cfg.n_heads % 4 == 0) else 2
        dp = 2
        mesh = jax.sharding.Mesh(
            np.array(jax.devices()[:dp * tp]).reshape(dp, tp),
            ("dp", "tp"))
        wreqs = [(rng.integers(1, 1000, 24).tolist(), 48)
                 for _ in range(8)]
        wtotal = sum(m for _, m in wreqs)

        def run_once(m):
            srv = ContinuousServer(params, cfg, slots=4, smax=128,
                                   paged=True, mesh=m)
            for p, mx in wreqs:
                srv.submit(p, max_new=mx)
            t0 = time.perf_counter()
            stalls = []
            alive = True
            while alive:
                s0 = time.perf_counter()
                alive = srv.step()
                stalls.append(time.perf_counter() - s0)
            secs = time.perf_counter() - t0
            out, srv._done = srv._done, {}
            sha = hashlib.sha256(json.dumps(
                [out[r] for r in sorted(out)]).encode()).hexdigest()
            return secs, stalls, sha

        waves = [("paged_single_device", None),
                 (f"paged_mesh_dp{dp}_tp{tp}", mesh)]
        results = {}
        for name, m in waves:
            run_once(m)                                # compile
            results[name] = run_once(m)
        base_sha = results["paged_single_device"][2]
        for name, (secs, stalls, sha) in results.items():
            emit(name, wtotal, secs,
                 mix="8 reqs plen24 new48 over 4 slots, greedy",
                 decode_stall_p50_ms=round(
                     1e3 * float(np.percentile(stalls, 50)), 2),
                 decode_stall_p99_ms=round(
                     1e3 * float(np.percentile(stalls, 99)), 2),
                 output_sha=sha[:16],
                 output_identical=(sha == base_sha))
        if any(sha != base_sha for _, _, sha in results.values()):
            print(json.dumps({"error": "sharded paged output "
                              "diverged from single-device"}),
                  flush=True)
            raise SystemExit(2)

    # 9b. the expert-parallel MoE wave (--moe): the SAME greedy mix
    # through a single-device MoE paged server and the (dp, tp)-mesh
    # one — experts sharded over tp, decode routing through moe_ffn's
    # tiled all_to_all with the drop-free auto capacity. Identity is
    # CHECKED (sha gate): expert parallelism moves the exchange onto
    # more chips, never changes tokens. Reports warm tokens/s and
    # decode-stall p50/p99 for both topologies plus the overflow-drop
    # rate from the /serving moe counters (banked into --metrics-out);
    # rows carry an explicit onchip stamp so CPU-smoke numbers can
    # never masquerade as chip measurements. Needs >=4 devices;
    # emits a skipped line otherwise.
    def moe_bench():
        import hashlib
        ndev = len(jax.devices())
        if ndev < 4:
            print(json.dumps({
                "engine": "serving_moe", "skipped": True,
                "reason": f"needs >=4 devices, have {ndev} (CPU smoke:"
                          " XLA_FLAGS=--xla_force_host_platform"
                          "_device_count=8)"}), flush=True)
            return
        mesh = jax.sharding.Mesh(
            np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
        mcfg = tfm.TransformerConfig(
            vocab=1024, d_model=d, n_heads=8, head_dim=d // 8,
            n_layers=2, d_ff=2 * d, n_experts=4, moe_top_k=2,
            moe_capacity=4.0, dtype=cfg.dtype)
        mparams = tfm.init_params(mcfg, jax.random.PRNGKey(4))
        wreqs = [(rng.integers(1, 1000, 24).tolist(), 48)
                 for _ in range(8)]
        wtotal = sum(m for _, m in wreqs)

        def run_once(m):
            srv = ContinuousServer(mparams, mcfg, slots=4, smax=128,
                                   paged=True, mesh=m)
            for p, mx in wreqs:
                srv.submit(p, max_new=mx)
            t0 = time.perf_counter()
            stalls = []
            alive = True
            while alive:
                s0 = time.perf_counter()
                alive = srv.step()
                stalls.append(time.perf_counter() - s0)
            secs = time.perf_counter() - t0
            out, srv._done = srv._done, {}
            sha = hashlib.sha256(json.dumps(
                [out[r] for r in sorted(out)]).encode()).hexdigest()
            routed, dropped = srv._moe_routed, srv._moe_dropped
            drop_rate = dropped / max(routed + dropped, 1.0)
            return secs, stalls, sha, routed, dropped, drop_rate

        waves = [("serving_moe_single_device", None),
                 ("serving_moe_mesh_dp2_tp2", mesh)]
        results = {}
        for name, m in waves:
            run_once(m)                                # compile
            results[name] = run_once(m)
        base_sha = results["serving_moe_single_device"][2]
        for name, (secs, stalls, sha, routed, dropped,
                   drop_rate) in results.items():
            emit(name, wtotal, secs,
                 mix="8 reqs plen24 new48 over 4 slots, greedy, "
                     "4 experts top-2, auto capacity",
                 decode_stall_p50_ms=round(
                     1e3 * float(np.percentile(stalls, 50)), 2),
                 decode_stall_p99_ms=round(
                     1e3 * float(np.percentile(stalls, 99)), 2),
                 moe_tokens_routed=int(routed),
                 moe_tokens_dropped=int(dropped),
                 moe_overflow_drop_rate=round(drop_rate, 4),
                 onchip=on_tpu,
                 output_sha=sha[:16],
                 output_identical=(sha == base_sha))
            collected_counters[f"{name}/moe_tokens_routed"] = \
                int(routed)
            collected_counters[f"{name}/moe_tokens_dropped"] = \
                int(dropped)
            collected_counters[f"{name}/moe_overflow_drop_rate"] = \
                round(drop_rate, 6)
        if any(sha != base_sha
               for _, _, sha, _, _, _ in results.values()):
            print(json.dumps({"error": "expert-parallel MoE output "
                              "diverged from single-device"}),
                  flush=True)
            raise SystemExit(2)

    # 7. the chaos wave: fault-free vs seeded-fault-schedule runs of
    # one mixed paged+spec mix. The schedule is chosen so every fault
    # CLASS recovers at least once: two verify faults walk the spec
    # degradation ladder (speculation off, sequential decode takes
    # over — which is what lets the later decode faults fire), a
    # prefill fault restarts a pending chunked prefill while live
    # slots restore, and an alloc fault with nothing evictable
    # (prefix_reuse off) escalates to the step-level restore path.
    # Identity is CHECKED: both runs' outputs are hashed.
    def chaos_bench():
        import hashlib
        from hpx_tpu.svc import faultinject
        crng = np.random.default_rng(7)
        creqs = [(crng.integers(1, 1000,
                                int(crng.integers(6, 40))).tolist(),
                  int(crng.integers(16, 33))) for _ in range(10)]
        ctotal = sum(m for _, m in creqs)
        SCHEDULE = {"verify": {1, 2}, "prefill": {6},
                    "decode": {3, 11}, "alloc": {50}}

        def run_wave(fi=None):
            srv = ContinuousServer(params, cfg, slots=4, smax=128,
                                   paged=True, block_size=8,
                                   prefix_reuse=False, spec=True,
                                   prefill_chunk=8)
            for p, m in creqs:
                srv.submit(p, max_new=m)
            if fi is not None:
                faultinject.install(fi)
            t0 = time.perf_counter()
            try:
                out = srv.run()
            finally:
                faultinject.uninstall()
            secs = time.perf_counter() - t0
            sha = hashlib.sha256(json.dumps(
                [out[r] for r in sorted(out)]).encode()).hexdigest()
            return srv, out, secs, sha

        run_wave()                                     # compile
        base_srv, base_out, base_secs, base_sha = run_wave()
        free0 = base_srv._alloc.stats()["free"]
        srv, out, secs, sha = run_wave(
            faultinject.FaultInjector(seed=0, schedule=SCHEDULE))
        st = srv.fault_stats()
        goodput = sum(len(t) for t in out.values())
        emit("serving_chaos", goodput, secs,
             mix="10 reqs plen6-39 new16-32, paged+spec over 4 slots",
             fault_schedule={k: sorted(v)
                             for k, v in SCHEDULE.items()},
             faultfree_tokens_per_s=round(ctotal / base_secs, 1),
             injected=st["injected"], recovered=st["restored"],
             restored_by_site=st["restored_by_site"],
             restore_p99_ms=round(1e3 * st["restore_p99_s"], 3),
             shed=st["shed"], degraded=st["degraded"],
             kv_blocks_leaked=free0 - srv._alloc.stats()["free"],
             output_sha=sha[:16],
             output_identical=(sha == base_sha))
        missing = [s for s in ("decode", "prefill", "verify", "alloc")
                   if not st["restored_by_site"].get(s)]
        if sha != base_sha or missing:
            print(json.dumps({
                "error": "chaos gate failed",
                "baseline_sha": base_sha[:16], "chaos_sha": sha[:16],
                "classes_without_restore": missing}), flush=True)
            raise SystemExit(2)

        # overload sub-run: every decode dispatch faults, recovery
        # can never complete a step — the retry budget exhausts and
        # every request sheds TYPED instead of looping forever
        srv = ContinuousServer(params, cfg, slots=4, smax=128)
        for p, m in creqs[:4]:
            srv.submit(p, max_new=m)
        faultinject.install(faultinject.FaultInjector(
            seed=0, rate=1.0, sites=["decode"]))
        try:
            shed_out = srv.run()
        finally:
            faultinject.uninstall()
        print(json.dumps({
            "engine": "serving_chaos_overload",
            "completed": len(shed_out),
            "shed_typed": len(srv.failed),
            "errors": sorted({type(e).__name__
                              for e in srv.failed.values()}),
        }), flush=True)

    # 7b. the observability wave: exemplars + burn-rate alerting over
    # two sub-runs with identical request streams. The healthy run
    # must collect >=1 exemplar per recorded SLO histogram, each rid
    # resolving to a complete (submit..retire) timeline; the seeded
    # regression run (decode faults -> retry backoff inflates decode
    # stalls past the rule threshold) must fire EXACTLY one
    # flight-bundle-capturing alert and clear on recovery.
    def alerts_bench() -> None:
        import glob
        import tempfile
        from hpx_tpu.core.config import runtime_config
        from hpx_tpu.svc import faultinject
        rc = runtime_config()
        arng = np.random.default_rng(11)
        areqs = [(arng.integers(1, 1000,
                                int(arng.integers(6, 24))).tolist(),
                  int(arng.integers(16, 33))) for _ in range(8)]
        atotal = sum(m for _, m in areqs)
        fdir = tempfile.mkdtemp(prefix="hpx-alerts-")
        knobs = {
            "hpx.obs.exemplars": "1",
            "hpx.obs.exemplar_quantile": "0.9",
            "hpx.obs.alert_interval_s": "0.02",
            "hpx.flight.dir": fdir,
        }
        defaults = {
            "hpx.obs.exemplars": "0",
            "hpx.obs.exemplar_quantile": "0.95",
            "hpx.obs.alerts": "0",
            "hpx.obs.alert_rules": "",
            "hpx.obs.alert_fast_s": "300",
            "hpx.obs.alert_slow_s": "3600",
            "hpx.obs.alert_burn_fast": "14.4",
            "hpx.obs.alert_burn_slow": "6",
            "hpx.obs.alert_interval_s": "1.0",
            "hpx.flight.dir": "auto",
            "hpx.serving.retry_backoff_s": "0.005",
        }
        for k, v in knobs.items():
            rc.set(k, v)

        def run_wave(fi=None):
            srv = ContinuousServer(params, cfg, slots=4, smax=128)
            for p, m in areqs:
                srv.submit(p, max_new=m)
            if fi is not None:
                faultinject.install(fi)
            t0 = time.perf_counter()
            try:
                out = srv.run()
            finally:
                faultinject.uninstall()
            return srv, out, time.perf_counter() - t0

        try:
            # compile run doubles as cadence calibration: decode_stall
            # IS the inter-step gap, so the SLO threshold must sit
            # between this host's healthy step time and the injected
            # fault-retry stall (step + backoff) — absolute numbers
            # would fire spuriously on a loaded box and never fire on
            # a fast one.  The fast burn window spans several fault
            # periods so alternating good/bad steps can't flap the FSM
            # (a clear fires whenever the fast window drains).
            csrv, _, _ = run_wave()
            from hpx_tpu.svc.metrics import HistogramCounter as _HC
            cal = _HC.from_snapshot(
                csrv.hist["decode_stall"].snapshot())
            p50 = cal.quantile(0.5) if cal.count else 0.005
            thr = min(max(0.05, 3.0 * p50), 2.0)
            backoff = min(max(0.2, 3.0 * thr), 4.0)
            fast_s = 3.0 * (p50 + backoff)
            for k, v in {
                "hpx.obs.alerts": "1",
                "hpx.obs.alert_rules": f"decode_stall:{thr:.3f}:0.9",
                "hpx.obs.alert_fast_s": f"{fast_s:.3f}",
                "hpx.obs.alert_slow_s": f"{3.0 * fast_s:.3f}",
                "hpx.obs.alert_burn_fast": "3",
                "hpx.obs.alert_burn_slow": "1.5",
                "hpx.serving.retry_backoff_s": f"{backoff:.3f}",
            }.items():
                rc.set(k, v)
            srv, out, secs = run_wave()                 # healthy
            bad_exemplars = []
            exemplar_counts = {}
            for key in ("ttft", "queue_wait", "decode_stall", "e2e"):
                h = srv.hist[key]
                if not h.count:
                    continue
                collected_hists[f"alerts/{key}"] = h
                exs = h.snapshot().get("exemplars", [])
                resolved = 0
                for e in exs:
                    evs = srv.timeline.events(e["rid"]) \
                        if e["rid"] is not None else []
                    names = {ev["name"] for ev in evs}
                    if "submit" in names and "retire" in names:
                        resolved += 1
                if not resolved:
                    bad_exemplars.append(key)
                exemplar_counts[key] = [len(exs), resolved]
            healthy_fired = srv._alerts.fired

            pre_bundles = set(glob.glob(
                os.path.join(fdir, "flight-*-slo_alert.json")))

            # regression: a burst of decode faults, each retried with
            # the elevated backoff — every faulted step's inter-step
            # gap sits at >= backoff >= 3x the calibrated rule
            # threshold until the schedule runs dry
            fi = faultinject.FaultInjector(
                seed=0, schedule={"decode": set(range(2, 40, 2))})
            rsrv, rout, rsecs = run_wave(fi)
            fired, cleared = rsrv._alerts.fired, rsrv._alerts.cleared
            bundles = sorted(set(glob.glob(
                os.path.join(fdir, "flight-*-slo_alert.json")))
                - pre_bundles)
            emit("serving_alerts", atotal, secs,
                 mix="8 reqs plen6-23 new16-32 over 4 slots, "
                     "healthy + seeded decode regression",
                 exemplars={k: v[0] for k, v in
                            exemplar_counts.items()},
                 exemplars_resolved={k: v[1] for k, v in
                                     exemplar_counts.items()},
                 healthy_fired=healthy_fired,
                 calibration={"stall_p50_s": round(p50, 4),
                              "threshold_s": round(thr, 3),
                              "retry_backoff_s": round(backoff, 3),
                              "fast_window_s": round(fast_s, 3)},
                 regression_secs=round(rsecs, 4),
                 regression_fired=fired,
                 regression_cleared=cleared,
                 alert_bundles=len(bundles),
                 alert_state=rsrv._alerts.state()["rules"])
            if (bad_exemplars or healthy_fired
                    or fired != 1 or len(bundles) != 1):
                print(json.dumps({
                    "error": "alerts gate failed",
                    "hists_without_resolved_exemplar": bad_exemplars,
                    "healthy_fired": healthy_fired,
                    "regression_fired": fired,
                    "alert_bundles": [os.path.basename(b)
                                      for b in bundles],
                }), flush=True)
                raise SystemExit(2)
        finally:
            for k, v in defaults.items():
                rc.set(k, v)

    # 8. the disaggregated wave: Poisson arrivals over Zipf-shared
    # prefixes with a 70/30 interactive/batch SLO mix, measured twice —
    # colocated paged server vs DisaggRouter — with identical request
    # streams. Percentiles are wall-clock (TTFT = submit->first token;
    # decode stall = inter-step gap while any request is live), so this
    # wave is a latency-shape comparison, not a correctness gate —
    # except under --chaos, where a seeded kill of one worker per role
    # must leave tokens sha-identical and leak zero KV blocks.
    def disagg_bench(chaos: bool) -> None:
        import hashlib
        from hpx_tpu.models.disagg import DisaggRouter
        from hpx_tpu.svc import faultinject

        drng = np.random.default_rng(11)
        npfx = 6
        prefixes = [drng.integers(1, 1000, 32).tolist()
                    for _ in range(npfx)]
        # Zipf over the prefix pool: rank r drawn with weight 1/r
        zw = np.array([1.0 / (r + 1) for r in range(npfx)])
        zw /= zw.sum()
        nreq = 12
        arrivals = np.cumsum(drng.exponential(0.05, nreq))  # Poisson
        wave = []
        for i in range(nreq):
            pfx = prefixes[int(drng.choice(npfx, p=zw))]
            tail = drng.integers(1, 1000,
                                 int(drng.integers(4, 12))).tolist()
            slo = "interactive" if drng.random() < 0.7 else "batch"
            wave.append((pfx + tail, int(drng.integers(12, 25)),
                         slo, float(arrivals[i])))
        wtotal = sum(m for _, m, _, _ in wave)

        def pctl(xs, q):
            return round(float(np.percentile(xs, q)) * 1e3, 2) \
                if xs else None

        def drive(submit, step, ttft_of):
            """Poisson-paced open loop: submit at arrival offsets,
            step in between; returns (outputs, secs, stalls)."""
            t0 = time.perf_counter()
            pending = list(enumerate(wave))
            stalls, live, last = [], False, t0
            out = None
            while pending or out is None or out:
                now = time.perf_counter() - t0
                while pending and pending[0][1][3] <= now:
                    _, (p, m, slo, _) = pending.pop(0)
                    submit(p, m, slo)
                out = step()
                t = time.perf_counter()
                if live:
                    stalls.append(t - last)
                live, last = bool(out), t
            return time.perf_counter() - t0, stalls

        def run_colocated():
            srv = ContinuousServer(params, cfg, slots=4, smax=96,
                                   paged=True)
            secs, stalls = drive(
                lambda p, m, slo: srv.submit(p, max_new=m),
                srv.step, None)
            out = dict(srv._done)
            return out, dict(srv.ttft), secs, stalls, srv.hist

        def run_disagg(fi=None):
            if fi is not None:
                faultinject.install(fi)
            try:
                r = DisaggRouter(params, cfg, prefill_workers=2,
                                 decode_workers=2, slots=4, smax=96)
                secs, stalls = drive(
                    lambda p, m, slo: r.submit(p, m, slo=slo),
                    r.step, None)
                out = dict(r.results)
                st = r.stats()
                hists = r.merged_hist()
                r.close()
                leak = r.leaked_blocks()
            finally:
                if fi is not None:
                    faultinject.uninstall()
            return out, dict(r.ttft), secs, stalls, st, leak, hists

        def sha(out):
            return hashlib.sha256(json.dumps(
                [out[r] for r in sorted(out)]).encode()).hexdigest()

        def hq(h, q):
            return round(h.quantile(q) * 1e3, 2)

        run_colocated()                                # compile
        run_disagg()                                   # compile
        co_out, co_ttft, co_secs, co_stalls, co_hist = run_colocated()
        dg_out, dg_ttft, dg_secs, dg_stalls, dg_st, dg_leak, \
            dg_hist = run_disagg()
        for name, out, ttft, secs, stalls, hists, extra in (
                ("serving_colocated", co_out, co_ttft, co_secs,
                 co_stalls, co_hist, {}),
                ("serving_disagg", dg_out, dg_ttft, dg_secs,
                 dg_stalls, dg_hist,
                 {"workers": "2 prefill + 2 decode",
                  "failovers": dg_st["failovers"],
                  "kv_blocks_leaked": dg_leak})):
            goodput = sum(len(t) for t in out.values())
            ts = sorted(ttft.values())
            line = {"mix": f"{nreq} reqs, {npfx} Zipf prefixes, "
                           "70/30 interactive/batch, Poisson 50ms",
                    "ttft_p50_ms": pctl(ts, 50),
                    "ttft_p95_ms": pctl(ts, 95),
                    "ttft_p99_ms": pctl(ts, 99),
                    "decode_stall_p50_ms": pctl(stalls, 50),
                    "decode_stall_p99_ms": pctl(stalls, 99),
                    # live-histogram view (svc/metrics, merged across
                    # workers for disagg) of the same SLOs
                    "slo_hist_ms": {
                        k: {"p50": hq(hists[k], 0.5),
                            "p95": hq(hists[k], 0.95),
                            "p99": hq(hists[k], 0.99)}
                        for k in ("ttft", "queue_wait",
                                  "decode_stall")}}
            line.update(extra)
            emit(name, goodput, secs, **line)
            for k, h in hists.items():
                collected_hists[f"{name}/{k}"] = h
        if co_out != {r: t for r, t in dg_out.items()}:
            print(json.dumps({"error": "disagg diverged from "
                              "colocated"}), flush=True)
            raise SystemExit(2)
        if not chaos:
            return

        # chaos sub-run: one seeded kill per role mid-flight; gated
        base_sha = sha(dg_out)
        ch_out, _, ch_secs, _, ch_st, ch_leak = run_disagg(
            faultinject.FaultInjector(schedule={
                "disagg.prefill": {9}, "disagg.decode": {30}}))
        ch_sha = sha(ch_out)
        emit("serving_disagg_chaos",
             sum(len(t) for t in ch_out.values()), ch_secs,
             fault_schedule={"disagg.prefill": [9],
                             "disagg.decode": [30]},
             failovers=ch_st["failovers"],
             degraded=ch_st["degraded"],
             kv_blocks_leaked=ch_leak,
             output_sha=ch_sha[:16],
             output_identical=(ch_sha == base_sha))
        if (ch_sha != base_sha or ch_leak != 0
                or not ch_st["failovers"]["prefill"]
                or not ch_st["failovers"]["decode"]):
            print(json.dumps({
                "error": "disagg chaos gate failed",
                "baseline_sha": base_sha[:16],
                "chaos_sha": ch_sha[:16],
                "failovers": ch_st["failovers"],
                "kv_blocks_leaked": ch_leak}), flush=True)
            raise SystemExit(2)

    def fleet_bench() -> None:
        import hashlib
        from hpx_tpu.core.config import runtime_config
        from hpx_tpu.svc import metrics as svc_metrics
        from hpx_tpu.svc.fleet import FleetRouter

        frng = np.random.default_rng(17)
        npfx = 4
        prefixes = [frng.integers(1, 1000, 40).tolist()
                    for _ in range(npfx)]
        zw = np.array([1.0 / (r + 1) for r in range(npfx)])
        zw /= zw.sum()
        nreq = 12
        arrivals = np.cumsum(frng.exponential(0.05, nreq))
        wave = []
        for i in range(nreq):
            pfx = prefixes[int(frng.choice(npfx, p=zw))]
            tail = frng.integers(1, 1000,
                                 int(frng.integers(4, 12))).tolist()
            wave.append((pfx + tail, int(frng.integers(10, 20)),
                         float(arrivals[i])))

        def pctl(xs, q):
            return round(float(np.percentile(xs, q)) * 1e3, 2) \
                if xs else None

        def drive(r):
            t0 = time.perf_counter()
            pending = list(wave)
            stalls, live, last = [], False, t0
            busy = None
            while pending or busy is None or busy:
                now = time.perf_counter() - t0
                while pending and pending[0][2] <= now:
                    p, m, _ = pending.pop(0)
                    r.submit(p, m)
                busy = r.step()
                t = time.perf_counter()
                if live:
                    stalls.append(t - last)
                live, last = bool(busy), t
            return time.perf_counter() - t0, stalls

        def run_mode(mode):
            rc = runtime_config()
            old = {k: rc.get(k) for k in
                   ("hpx.serving.fleet.placement",
                    "hpx.serving.fleet.digest_refresh_s")}
            rc.set("hpx.serving.fleet.placement", mode)
            rc.set("hpx.serving.fleet.digest_refresh_s", "0.01")
            try:
                r = FleetRouter(params, cfg, prefill_workers=2,
                                decode_workers=2, slots=4, smax=96)
                # two cold passes (same mix, unpaced): the first
                # warms the decode workers' radix trees, the second
                # takes placement hits and compiles the SEEDED
                # prefill programs — so the measured wave is the
                # steady Zipf state placement is for
                for _ in range(2):
                    for p, m, _ in wave:
                        r.submit(p, m)
                    r.run()
                warm_stats = r.stats()
                secs, stalls = drive(r)
                out = dict(r.results)
                st = r.stats()
                merged = r.merged_hist()
                wsnaps = [{k: h.snapshot() for k, h in per.items()}
                          for per in r.whist.values()]
                if tracer is not None and mode == "prefix":
                    # harvest the worker rings BEFORE close() tears
                    # the handles down; finish() stitches them
                    fleet_trace_docs[:] = r.worker_trace_docs()
                ttft = {rid: r.ttft[rid] for rid in out
                        if rid in r.ttft}
                r.close()
                leak = r.leaked_blocks()
            finally:
                for k, v in old.items():
                    if v is None:
                        rc._data.pop(k, None)
                    else:
                        rc.set(k, v)
            saved = (st["prefill_tokens_saved"]
                     - warm_stats["prefill_tokens_saved"])
            placed = {"prefix": st["placed_prefix"]
                      - warm_stats["placed_prefix"],
                      "load": st["placed_load"]
                      - warm_stats["placed_load"]}
            return (out, ttft, secs, stalls, placed, saved, leak,
                    merged, wsnaps)

        def sha(out):
            return hashlib.sha256(json.dumps(
                [out[r] for r in sorted(out)]).encode()).hexdigest()

        def hq(h, q):
            return round(h.quantile(q) * 1e3, 2)

        results = {}
        for mode in ("load", "prefix"):
            out, ttft, secs, stalls, placed, saved, leak, merged, \
                wsnaps = run_mode(mode)
            results[mode] = (out, saved, leak)
            # fleet-wide == merge() of the per-worker histograms:
            # re-fold the per-worker SNAPSHOTS independently and
            # compare against the router's merged view
            refold = svc_metrics.latency_histograms()
            for snap in wsnaps:
                for k in refold:
                    refold[k] = refold[k].merge(
                        svc_metrics.HistogramCounter.from_snapshot(
                            snap[k]))
            merge_identity = all(
                refold[k].snapshot()["counts"]
                == merged[k].snapshot()["counts"]
                and refold[k].snapshot()["count"]
                == merged[k].snapshot()["count"]
                for k in refold)
            ts = sorted(ttft.values())
            emit(f"serving_fleet_{mode}",
                 sum(len(t) for t in out.values()), secs,
                 mix=f"{nreq} reqs, {npfx} Zipf prefixes, "
                     "Poisson 50ms, warm caches",
                 workers="2 prefill + 2 decode",
                 placement=placed,
                 prefill_tokens_saved=saved,
                 ttft_p50_ms=pctl(ts, 50),
                 ttft_p99_ms=pctl(ts, 99),
                 decode_stall_p50_ms=pctl(stalls, 50),
                 decode_stall_p99_ms=pctl(stalls, 99),
                 slo_hist_ms={
                     k: {"p50": hq(merged[k], 0.5),
                         "p95": hq(merged[k], 0.95),
                         "p99": hq(merged[k], 0.99)}
                     for k in ("ttft", "queue_wait", "decode_stall")},
                 hist_merge_identity=merge_identity,
                 kv_blocks_leaked=leak,
                 output_sha=sha(out)[:16])
            for k, h in merged.items():
                collected_hists[f"serving_fleet_{mode}/{k}"] = h
            if not merge_identity:
                print(json.dumps({
                    "error": "fleet-wide histograms != merge() of "
                             "per-worker histograms"}), flush=True)
                raise SystemExit(2)
        (lo, lo_saved, lo_leak) = results["load"]
        (pf, pf_saved, pf_leak) = results["prefix"]
        if (sha(lo) != sha(pf) or pf_saved <= lo_saved
                or lo_leak != 0 or pf_leak != 0):
            print(json.dumps({
                "error": "fleet gate failed",
                "load_sha": sha(lo)[:16],
                "prefix_sha": sha(pf)[:16],
                "prefill_tokens_saved": {"load": lo_saved,
                                         "prefix": pf_saved},
                "kv_blocks_leaked": {"load": lo_leak,
                                     "prefix": pf_leak}}),
                flush=True)
            raise SystemExit(2)

    # 8. the closed-loop tuner wave (--autotune): the same serving
    # mixes, each run twice — once with the hand-tuned settings the waves above
    # use, once from schema defaults with the online tuner live
    # (hpx.tune.enable=1, svc/autotune). Three gates per mix: output
    # byte-identity (the tuner moves only output-invariant knobs —
    # divergence exits 2), the tuner actually evaluated, and the
    # reported band check (auto warm tok/s and stall p99 within 5% of
    # hand-tuned). Stall histograms land in collected_hists so
    # --metrics-out feeds slo_gate.py --baseline.
    def autotune_bench():
        import hashlib

        from hpx_tpu.core.config import runtime_config
        from hpx_tpu.svc.metrics import HistogramCounter
        rc = runtime_config()

        mreqs = [(rng.integers(
                      1, 1000, int(rng.integers(5, 150))).tolist(),
                  int(rng.integers(16, 96))) for _ in range(12)]
        shared = rng.integers(1, 1000, 64).tolist()
        preqs = [(shared + rng.integers(1, 1000, 8).tolist(),
                  int(rng.integers(16, 33))) for _ in range(12)]
        sreqs = [(([11, 23, 7, 42] * 12)[:40], 48) for _ in range(4)] \
            + [(rng.integers(1, 1000, 24).tolist(),
                int(rng.integers(24, 49))) for _ in range(4)]
        mixes = [
            ("mixed", mreqs, dict(slots=4, smax=256),
             "12 reqs plen5-149 (unbucketed) new16-96 over 4 slots"),
            ("prefix", preqs, dict(slots=4, smax=160, paged=True),
             "12 reqs 64-tok shared prefix + 8-tok tail, paged"),
            ("spec", sreqs,
             dict(slots=4, smax=128, spec=True, spec_k=4),
             "4 periodic + 4 random reqs, prompt-lookup spec"),
        ]

        from hpx_tpu.utils.compilemon import count_compiles

        def run(reqs, srv_kw, tune):
            rc.set("hpx.tune.enable", "1" if tune else "0")
            rc.set("hpx.tune.interval_ticks", "4")
            try:
                def once():
                    srv = ContinuousServer(params, cfg, **srv_kw)
                    for p, m in reqs:
                        srv.submit(p, max_new=m)
                    t0 = time.perf_counter()
                    stalls = []
                    alive = True
                    while alive:
                        s0 = time.perf_counter()
                        alive = srv.step()
                        stalls.append(time.perf_counter() - s0)
                    secs = time.perf_counter() - t0
                    out = dict(srv._done)
                    srv._done.clear()
                    return out, secs, stalls, srv

                with count_compiles() as c_cold:
                    once()                             # compile
                with count_compiles() as c_warm:
                    res = once()                       # warm
                return res + (int(c_cold), int(c_warm))
            finally:
                rc.set("hpx.tune.enable", "0")

        def sha(out):
            return hashlib.sha256(json.dumps(
                [out[r] for r in sorted(out)]).encode()).hexdigest()

        for name, reqs, srv_kw, mix in mixes:
            total = sum(m for _, m in reqs)
            h_out, h_secs, h_stalls, _, h_cold, h_warm = \
                run(reqs, srv_kw, False)
            a_out, a_secs, a_stalls, a_srv, a_cold, a_warm = \
                run(reqs, srv_kw, True)
            collected_compiles[f"serving_autotune_{name}/hand"] = {
                "cold": h_cold, "warm": h_warm}
            collected_compiles[f"serving_autotune_{name}/auto"] = {
                "cold": a_cold, "warm": a_warm}
            # producer leg: with a store configured, the wave's warm
            # medians land in the perfdb under the server's key — the
            # "serving_bench --autotune" producer from ROADMAP item 5
            pdb = _configured_perfdb()
            if pdb is not None:
                pdb.observe(a_srv.perf_key(), "warm_tok_s",
                            total / a_secs,
                            source=f"serving_bench/autotune_{name}")
                pdb.save()
            t = a_srv._tuner
            hh, ha = HistogramCounter(), HistogramCounter()
            for s in h_stalls:
                hh.record(s)
            for s in a_stalls:
                ha.record(s)
            collected_hists[
                f"serving_autotune_{name}/decode_stall_hand"] = hh
            collected_hists[
                f"serving_autotune_{name}/decode_stall_auto"] = ha
            h_tps, a_tps = total / h_secs, total / a_secs
            h_p99 = float(np.percentile(h_stalls, 99))
            a_p99 = float(np.percentile(a_stalls, 99))
            identical = sha(a_out) == sha(h_out)
            within = (a_tps >= 0.95 * h_tps
                      and a_p99 <= 1.05 * max(h_p99, 1e-4))
            emit(f"serving_autotune_{name}", total, a_secs,
                 mix=mix,
                 hand_tokens_per_s=round(h_tps, 1),
                 hand_stall_p99_ms=round(1e3 * h_p99, 2),
                 auto_stall_p99_ms=round(1e3 * a_p99, 2),
                 tuner_evals=t.evals, tuner_probes=t.probes,
                 tuner_accepts=t.accepts, tuner_reverts=t.reverts,
                 tuned_knobs=t.knob_values(),
                 output_identical=identical,
                 within_band=within)
            if not identical:
                print(json.dumps({
                    "error": "autotuned output diverged",
                    "wave": name,
                    "hand_sha": sha(h_out)[:16],
                    "auto_sha": sha(a_out)[:16]}), flush=True)
                raise SystemExit(2)
            if t.evals == 0:
                print(json.dumps({
                    "error": "tuner never evaluated",
                    "wave": name}), flush=True)
                raise SystemExit(2)

        # disagg leg: the same contract through the router — every
        # in-proc worker gets its own tuner, joined to the router's
        # TuneArbiter for the shared budgets
        from hpx_tpu.models.disagg import DisaggRouter
        dreqs = [(rng.integers(
                      1, 1000, int(rng.integers(8, 64))).tolist(),
                  int(rng.integers(16, 49))) for _ in range(10)]
        dtotal = sum(m for _, m in dreqs)

        def run_disagg(tune):
            rc.set("hpx.tune.enable", "1" if tune else "0")
            rc.set("hpx.tune.interval_ticks", "4")
            try:
                def once():
                    r = DisaggRouter(params, cfg, prefill_workers=2,
                                     decode_workers=2, slots=4,
                                     smax=128)
                    for p, m in dreqs:
                        r.submit(p, m)
                    t0 = time.perf_counter()
                    out = r.run()
                    secs = time.perf_counter() - t0
                    hist = r.merged_hist()["decode_stall"]
                    r.close()
                    return out, secs, hist
                once()                                 # compile
                return once()                          # warm
            finally:
                rc.set("hpx.tune.enable", "0")

        h_out, h_secs, h_hist = run_disagg(False)
        a_out, a_secs, a_hist = run_disagg(True)
        collected_hists["serving_autotune_disagg/"
                        "decode_stall_hand"] = h_hist
        collected_hists["serving_autotune_disagg/"
                        "decode_stall_auto"] = a_hist
        h_tps, a_tps = dtotal / h_secs, dtotal / a_secs
        h_p99, a_p99 = h_hist.quantile(0.99), a_hist.quantile(0.99)
        identical = sha(a_out) == sha(h_out)
        emit("serving_autotune_disagg", dtotal, a_secs,
             mix="10 reqs plen8-63 new16-48, 2 prefill x 2 decode",
             hand_tokens_per_s=round(h_tps, 1),
             hand_stall_p99_ms=round(1e3 * h_p99, 2),
             auto_stall_p99_ms=round(1e3 * a_p99, 2),
             output_identical=identical,
             within_band=(a_tps >= 0.95 * h_tps
                          and a_p99 <= 1.05 * max(h_p99, 1e-4)))
        if not identical:
            print(json.dumps({
                "error": "autotuned output diverged",
                "wave": "disagg",
                "hand_sha": sha(h_out)[:16],
                "auto_sha": sha(a_out)[:16]}), flush=True)
            raise SystemExit(2)

    # 12. the learned-ladder wave (--ladder): the full offline loop
    # from ROADMAP item 5 in one wave. Seed a perfdb from a live
    # profiled run of the mixed-unbucketed mix (the compile-storm
    # shape), re-derive the ladder offline with
    # benchmarks/ladder_search, then COLD-BOOT (program cache
    # cleared) the hand-picked server and the learned one on the same
    # mix and compare: warm tokens/s, total cold compile count, and
    # sha-identical outputs (the ladder moves WORK, never tokens —
    # divergence exits 2). Off-TPU the derivation carries
    # builder-session provenance and is installed under
    # --allow-session semantics, stamped on the emitted line.
    def ladder_bench():
        import hashlib
        import tempfile

        from hpx_tpu.core.config import runtime_config
        from hpx_tpu.models.transformer import _PROGRAMS
        from hpx_tpu.svc import perfdb as pdbm
        from hpx_tpu.svc import progprof
        from hpx_tpu.utils.compilemon import count_compiles
        import ladder_search

        rc = runtime_config()
        db_path = (rc.get("hpx.perfdb.path", "") or "").strip() or \
            os.path.join(tempfile.mkdtemp(prefix="hpx_perfdb_"),
                         "perfdb.json")
        rc.set("hpx.perfdb.path", db_path)
        pdbm.reset_configured()

        lreqs = [(rng.integers(
                      1, 1000, int(rng.integers(5, 150))).tolist(),
                  int(rng.integers(16, 96))) for _ in range(12)]
        ltotal = sum(m for _, m in lreqs)

        def drive(srv):
            for p, m in lreqs:
                srv.submit(p, max_new=m)
            t0 = time.perf_counter()
            while srv.step():
                pass
            secs = time.perf_counter() - t0
            out = dict(srv._done)
            srv._done.clear()
            return out, secs

        def sha(out):
            return hashlib.sha256(json.dumps(
                [out[r] for r in sorted(out)]).encode()).hexdigest()

        # -- seed: a profiled cold run + a warm rerun bank the cost
        # surface. progprof's per-program build times undercount the
        # true minting cost wherever jit compiles lazily (first call,
        # not build), so the seed ALSO banks the honest wave-level
        # estimate: (cold - warm wall time) / programs minted —
        # exactly what the search's amortization term needs.
        own_prof = progprof.active_profiler() is None
        prof = progprof.start_profiling() if own_prof else \
            progprof.active_profiler()
        _PROGRAMS.clear()
        seed_srv = ContinuousServer(params, cfg, slots=4, smax=256)
        _, seed_cold_s = drive(seed_srv)
        warm_srv = ContinuousServer(params, cfg, slots=4, smax=256)
        _, seed_warm_s = drive(warm_srv)
        db = pdbm.configured_db()
        key = seed_srv.perf_key()
        pdbm.bank_profile(db, prof.profile_table(), key)
        misses = seed_srv._prog_misses
        if seed_cold_s > seed_warm_s and misses:
            db.observe(key, "compile_s",
                       (seed_cold_s - seed_warm_s) / misses,
                       n=misses, source="serving_bench/ladder_seed")
        db.observe(key, "warm_tok_s", ltotal / seed_warm_s,
                   source="serving_bench/ladder_seed")
        # prefill-only probe: the wall-clock share of a full run spent
        # prefilling is what the search's padded-work term scales by
        # (per-call exec timers see async dispatch, not compute, so
        # they cannot price padding — wall-clock can)
        probe_srv = ContinuousServer(params, cfg, slots=4, smax=256)
        for p, _ in lreqs:
            probe_srv.submit(p, max_new=1)
        t0 = time.perf_counter()
        while probe_srv.step():
            pass
        probe_s = time.perf_counter() - t0
        db.observe(key, "prefill_frac",
                   min(1.0, probe_s / seed_warm_s),
                   source="serving_bench/ladder_seed")
        # per-rung chunk-demand histogram: how many prefill chunks
        # this mix lands on each rung of the ladder it ran under.
        # The offline search re-prices candidate ladders against THIS
        # demand (a candidate rung's cost is the demand that rounds
        # up into it), not a uniform length assumption — remainder
        # chunks of long prompts pile onto the small rungs.
        demand = {}
        for p, _ in lreqs:
            n = len(p)
            while n > 0:
                step = min(n, seed_srv.prefill_chunk)
                rung = next(b for b in seed_srv.prefill_buckets
                            if b >= step)
                demand[rung] = demand.get(rung, 0) + 1
                n -= step
        for rung in sorted(demand):
            db.observe(key, "chunk_demand", float(demand[rung]),
                       program=f"r{rung}",
                       source="serving_bench/ladder_seed")
        db.save()
        if own_prof:
            progprof.stop_profiling()

        # -- offline search (the serving path never explores) --------
        search_argv = ["ladder_search", "--db", db_path,
                       "--key", seed_srv.perf_key(),
                       "--allow-session"]
        argv0 = sys.argv
        try:
            sys.argv = search_argv
            rcode = ladder_search.main()
        finally:
            sys.argv = argv0
        if rcode != 0:
            print(json.dumps({"error": "ladder_search derived "
                              "nothing", "exit": rcode}), flush=True)
            raise SystemExit(2)
        pdbm.reset_configured()
        proposal = pdbm.configured_db().ladder(seed_srv.perf_key())

        # -- cold-boot A/B: hand-picked vs learned -------------------
        # _PROGRAMS.clear() makes each leg a TRUE cold boot (the
        # seeding run would otherwise have pre-minted both ladders'
        # programs and the compile comparison would read 0 == 0)
        def leg(use_learned):
            rc.set("hpx.perfdb.use_learned_ladders",
                   "1" if use_learned else "0")
            rc.set("hpx.perfdb.allow_session", "1")
            _PROGRAMS.clear()
            with count_compiles() as c_cold:
                srv = ContinuousServer(params, cfg, slots=4, smax=256)
                out_cold, _ = drive(srv)
            srv = ContinuousServer(params, cfg, slots=4, smax=256)
            with count_compiles() as c_warm:
                out, secs = drive(srv)
            # warm tok/s = best of 3 drives: the noise-floor estimate
            # (identical deterministic work each drive; min wall time
            # is the least-perturbed sample)
            for _ in range(2):
                out2, secs2 = drive(srv)
                assert sha(out2) == sha(out)
                secs = min(secs, secs2)
            rc.set("hpx.perfdb.use_learned_ladders", "0")
            return (out, secs, int(c_cold), int(c_warm),
                    srv.prefill_buckets, out_cold)

        h_out, h_secs, h_cold, h_warm, h_buckets, h_out_c = leg(False)
        l_out, l_secs, l_cold, l_warm, l_buckets, l_out_c = leg(True)
        collected_compiles["serving_ladder/hand"] = {
            "cold": h_cold, "warm": h_warm}
        collected_compiles["serving_ladder/learned"] = {
            "cold": l_cold, "warm": l_warm}
        h_tps, l_tps = ltotal / h_secs, ltotal / l_secs
        identical = (sha(l_out) == sha(h_out)
                     and sha(l_out_c) == sha(h_out_c))
        stamps = pdbm._default_stamps()
        emit("serving_ladder", ltotal, l_secs,
             mix="12 reqs plen5-149 (unbucketed) new16-96 over 4 "
                 "slots, hand vs learned ladder",
             hand_tokens_per_s=round(h_tps, 1),
             learned_tokens_per_s=round(l_tps, 1),
             hand_compiles_cold=h_cold,
             learned_compiles_cold=l_cold,
             hand_buckets=list(h_buckets),
             learned_buckets=list(l_buckets),
             ladder_samples=proposal["samples"] if proposal else 0,
             learned_beats_default=(l_tps > h_tps
                                    and l_cold < h_cold),
             output_identical=identical,
             onchip=stamps["onchip"],
             provenance=stamps["provenance"])
        if not identical:
            print(json.dumps({
                "error": "learned-ladder output diverged",
                "hand_sha": sha(h_out)[:16],
                "learned_sha": sha(l_out)[:16]}), flush=True)
            raise SystemExit(2)

    def finish() -> int:
        if tracer is not None:
            from hpx_tpu.svc import tracing
            tracing.stop_tracing()
            if fleet_trace_docs:
                # stitch router + every worker ring into ONE trace:
                # per-worker pid rows, clock-aligned, rid flow arrows
                from hpx_tpu.svc.trace_export import (
                    merge_traces, to_chrome_trace, write_trace_doc)
                router_doc = to_chrome_trace(
                    tracer.snapshot(), tracer.thread_names(),
                    tracer.t0, tracer.dropped,
                    t0_wall=tracer.t0_wall)
                doc = merge_traces([("router", router_doc)]
                                   + fleet_trace_docs)
                write_trace_doc(trace_out, doc)
                print(json.dumps({
                    "trace": os.path.abspath(trace_out),
                    "trace_events": len(doc["traceEvents"]),
                    "dropped_events":
                        doc["otherData"]["dropped_events"],
                    "stitched_processes":
                        doc["otherData"]["processes"],
                    "stitched_rids": doc["otherData"]["stitched_rids"],
                    "rid_flow_arrows":
                        doc["otherData"]["rid_flow_arrows"],
                }), flush=True)
            else:
                doc = tracer.export(trace_out)
                print(json.dumps({
                    "trace": os.path.abspath(trace_out),
                    "trace_events": len(doc["traceEvents"]),
                    "dropped_events":
                        doc["otherData"]["dropped_events"],
                }), flush=True)
        if metrics_out:
            from hpx_tpu.svc import metrics as svc_metrics
            reg = svc_metrics.registry_snapshot("*")
            doc = metrics_artifact(
                collected_hists,
                counters={**reg["counters"], **collected_counters})
            doc["compiles"] = dict(collected_compiles)
            if profiler is not None:
                from hpx_tpu.svc import progprof
                doc["programs"] = profiler.profile_table()
                progprof.stop_profiling()
            write_metrics_artifact(metrics_out, doc)
            print(json.dumps({
                "metrics": os.path.abspath(metrics_out),
                "schema": doc["schema"],
                "histograms": len(doc["histograms"]),
                "programs": len(doc.get("programs", {})
                                .get("programs", []))
                if profiler is not None else 0,
            }), flush=True)
        return 0

    if "--prefix-only" in sys.argv:
        paged_prefix_bench()
        return finish()

    if "--tier" in sys.argv:
        tier_bench()
        return finish()

    if "--spec-only" in sys.argv:
        spec_wave_bench()
        return finish()

    if "--paged-decode-only" in sys.argv:
        paged_decode_bench()
        return finish()

    if "--mesh" in sys.argv:
        mesh_paged_bench()
        return finish()

    if "--moe" in sys.argv:
        moe_bench()
        return finish()

    if "--disagg" in sys.argv:
        disagg_bench("--chaos" in sys.argv)
        return finish()

    if "--fleet" in sys.argv:
        fleet_bench()
        return finish()

    if "--autotune" in sys.argv:
        autotune_bench()
        return finish()

    if "--ladder" in sys.argv:
        ladder_bench()
        return finish()

    if "--alerts" in sys.argv:
        alerts_bench()
        return finish()

    if "--chaos" in sys.argv:
        chaos_bench()
        return finish()

    # 1. uniform batched greedy
    B, plen, max_new = 8, 32, 64
    prompt = jnp.asarray(rng.integers(1, 1000, (B, plen)), jnp.int32)
    tfm.generate(params, cfg, prompt, max_new=4)       # compile
    t0 = time.perf_counter()
    out = tfm.generate(params, cfg, prompt, max_new=max_new)
    jax.block_until_ready(out)
    emit("generate", B * max_new, time.perf_counter() - t0,
         mix=f"B{B} plen{plen} new{max_new}")

    # 2. continuous batching over a ragged mix (pre-bucketed plens:
    # the legacy-friendly shape; the mixed_length wave below is the
    # hard case)
    reqs = [(rng.integers(1, 1000, 8 * int(rng.integers(1, 7))).tolist(),
             int(rng.integers(16, 96))) for _ in range(12)]
    total_new = sum(m for _, m in reqs)
    srv = ContinuousServer(params, cfg, slots=4, smax=160)
    for p, m in reqs[:1]:
        srv.submit(p, max_new=m)
    srv.run()                                          # compile slots
    srv = ContinuousServer(params, cfg, slots=4, smax=160)
    for p, m in reqs:
        srv.submit(p, max_new=m)
    t0 = time.perf_counter()
    srv.run()
    emit("continuous_batching", total_new, time.perf_counter() - t0,
         mix="12 reqs plen8-48(x8 buckets) new16-96 over 4 slots")

    # 2b. mixed UNBUCKETED prompt lengths — the compile-storm shape the
    # bucketed chunked prefill exists for. A manual step loop times
    # every step (decode-stall p99: a prefill blocking the batch shows
    # up here), TTFT comes straight from srv.ttft, and compile counts
    # from jax.monitoring — reported for the COLD server; throughput
    # and stalls for the warm one.
    def mixed_length_bench():
        from hpx_tpu.utils.compilemon import count_compiles
        mreqs = [(rng.integers(
                      1, 1000, int(rng.integers(5, 150))).tolist(),
                  int(rng.integers(16, 96))) for _ in range(12)]
        mtotal = sum(m for _, m in mreqs)

        def run_mixed():
            with count_compiles() as c:
                srv = ContinuousServer(params, cfg, slots=4, smax=256)
                for p, m in mreqs:
                    srv.submit(p, max_new=m)
                t0 = time.perf_counter()
                stalls = []
                alive = True
                while alive:
                    s0 = time.perf_counter()
                    alive = srv.step()
                    stalls.append(time.perf_counter() - s0)
                secs = time.perf_counter() - t0
            srv._done.clear()
            return srv, secs, stalls, int(c)

        cold_srv, _, _, cold_compiles = run_mixed()
        srv, secs, stalls, warm_compiles = run_mixed()
        collected_compiles["continuous_batching_mixed"] = {
            "cold": cold_compiles, "warm": warm_compiles}
        ttfts = list(srv.ttft.values())
        emit("continuous_batching_mixed", mtotal, secs,
             mix="12 reqs plen5-149 (unbucketed) new16-96 over 4 slots",
             compiles_cold=cold_compiles,
             programs_built=cold_srv._prog_misses,
             prefill_chunks=srv._chunks,
             ttft_mean_ms=round(1e3 * sum(ttfts) / len(ttfts), 2),
             ttft_max_ms=round(1e3 * max(ttfts), 2),
             decode_stall_p99_ms=round(
                 1e3 * float(np.percentile(stalls, 99)), 2))

    mixed_length_bench()
    spec_wave_bench()

    # 3. speculative greedy (single stream: the latency case)
    sp = jnp.asarray(rng.integers(1, 1000, (1, plen)), jnp.int32)
    tfm.speculative_generate(params, cfg, draft, draft_cfg, sp,
                             max_new=4, k=4)           # compile
    t0 = time.perf_counter()
    out, rounds = tfm.speculative_generate(
        params, cfg, draft, draft_cfg, sp, max_new=max_new, k=4,
        return_stats=True)
    jax.block_until_ready(out)
    emit("speculative", max_new, time.perf_counter() - t0,
         rounds=int(rounds),
         tokens_per_target_forward=round(max_new / int(rounds), 2))
    t0 = time.perf_counter()
    out = tfm.generate(params, cfg, sp, max_new=max_new)
    jax.block_until_ready(out)
    emit("generate_single_stream", max_new, time.perf_counter() - t0)

    paged_prefix_bench()
    paged_decode_bench()
    return finish()


if __name__ == "__main__":
    sys.exit(main())
