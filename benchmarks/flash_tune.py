"""Flash-attention forward block-size autotune (real TPU).

Sweeps (block_q, block_k) per shape class — S in {2k, 4k, 8k, 16k},
causal x non-causal at the bench head layout (N8 H128 bf16) — with the
same slope-timing discipline as bench.py, prints one JSON line per
measurement, and writes the winners to
hpx_tpu/ops/flash_blocks.json, which ops/attention_pallas.resolve_blocks
consults whenever callers don't pass blocks explicitly.

With --paged the sweep instead covers the PAGED DECODE knob grid —
cache block_size {8, 16, 32, 64} x kv_dtype {bf16, int8, fp8} x
kernel {gather, fused, fused_online} — on a serving-decode shape
(8 slots near a 2k horizon, N8 H128), and banks each kv_dtype's
winning block size (best across kernels) to
hpx_tpu/ops/paged_blocks.json keyed ``hd<head_dim>x<kv_dtype>``, which
`ops/attention_pallas.resolve_paged_block` (and through it
``hpx.cache.block_size=auto``) consults. An unknown kv_dtype string is
a hard error, never a silent fall-through to bf16 byte accounting.

Usage: python benchmarks/flash_tune.py [--quick] [--paged]
                                       [--perfdb PATH]
  --quick: S in {2k, 4k} only and fewer samples (smoke/dev loops).
  --paged: tune the paged decode kernel instead of flash forward.
  --perfdb PATH: with --paged, additionally bank every sweep point
    into the persistent perf store (svc/perfdb) as provenance-stamped
    observations, and each kv_dtype winner into its learned-blocks
    tier — the producer half of benchmarks/ladder_search.py.
"""

import functools
import json
import os
import sys
import time

# repo root (this file lives in benchmarks/), regardless of the cwd
sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from bench import slope_time  # noqa: E402 — one timing discipline


def measure(jax, jnp, flash, S, causal, bq, bk, samples=3):
    B, N, H = (2, 8, 128) if S <= 8192 else (1, 8, 128)
    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.standard_normal((B, S, N, H), np.float32), jnp.bfloat16)
    q, k, v = mk(), mk(), mk()
    f = jax.jit(functools.partial(flash, causal=causal, block_q=bq,
                                  block_k=bk))
    out = f(q, k, v)
    jax.block_until_ready(out)

    def chain(kk):
        qq = q
        t0 = time.perf_counter()
        for _ in range(kk):
            qq = f(qq, k, v)
        _ = float(qq[0, 0, 0, 0])
        return time.perf_counter() - t0

    pers = sorted(slope_time(chain, 4, 20) for _ in range(samples))
    per = pers[(samples - 1) // 2]     # median (odd) / faster-of-2
    flops = 4 * B * N * S * S * H * (0.5 if causal else 1.0)
    return flops / per / 1e12, (pers[-1] - pers[0]) / per


def _arg(name):
    if name in sys.argv:
        return sys.argv[sys.argv.index(name) + 1]
    return None


def _bank(table, blocks_file) -> int:
    """Merge `table` into the on-disk table atomically; returns total.
    Called after EVERY shape class: a tunnel wedge mid-sweep must not
    discard classes already tuned (same discipline as bench.py's
    incremental fallback banking)."""
    try:
        with open(blocks_file) as f:
            merged = json.load(f)
    except (OSError, ValueError):
        merged = {}
    merged.update(table)
    tmp = blocks_file + ".tmp"
    with open(tmp, "w") as f:
        json.dump(merged, f, indent=1, sort_keys=True)
    os.replace(tmp, blocks_file)
    return len(merged)


# Pool-row bytes per element by kv_dtype string. KeyError here is a
# BUG GUARD: an unrecognized dtype must fail the sweep, not silently
# get bf16 byte accounting (which would corrupt the banked winners).
_PAGED_ITEMSIZE = {"bf16": 2, "int8": 1, "fp8": 1}
_PAGED_KERNELS = ("gather", "fused", "fused_online")


def paged_measure(jax, jnp, S, bs, kvd, kern, samples=3):
    """Time one paged decode attention step at the serving shape:
    8 slots, every table fully mapped to DISTINCT pool blocks at a
    near-S horizon (the steady-state worst case — block-size effects
    show up as grid/tiling overhead, not masked work). `kern` picks
    the formulation: gather (XLA oracle), fused (bitwise Pallas), or
    fused_online (O(block)-scratch online softmax). Returns
    (HBM-read GB/s, us per call, spread)."""
    from hpx_tpu.ops.attention_pallas import (fused_paged_attention,
                                              fused_paged_online_attention)
    from hpx_tpu.ops.paged_attention import (gather_block_kv,
                                             quantize_blocks)
    try:
        itemsize = _PAGED_ITEMSIZE[kvd]
    except KeyError:
        raise ValueError(
            f"flash_tune --paged: unknown kv_dtype {kvd!r} (expected one "
            f"of {sorted(_PAGED_ITEMSIZE)}) — refusing to fall back to "
            "bf16 byte accounting") from None
    if kern not in _PAGED_KERNELS:
        raise ValueError(
            f"flash_tune --paged: unknown kernel {kern!r} (expected one "
            f"of {_PAGED_KERNELS})")
    B, nq, nkv, H = 8, 8, 8, 128
    maxb = S // bs
    nb = B * maxb + 1                  # + a trash-style spare block
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, 1, nq, H), np.float32),
                    jnp.bfloat16)
    kp = rng.standard_normal((nb, bs, nkv, H), np.float32)
    vp = rng.standard_normal((nb, bs, nkv, H), np.float32)
    table = jnp.asarray(
        np.arange(1, B * maxb + 1, dtype=np.int32).reshape(B, maxb))
    pos = jnp.full((B,), S - 1, jnp.int32)
    ks = vs = None
    if kvd == "bf16":
        kq = jnp.asarray(kp, jnp.bfloat16)
        vq = jnp.asarray(vp, jnp.bfloat16)
    else:
        pool_dt = jnp.int8 if kvd == "int8" else jnp.float8_e4m3fn
        kq, ks = quantize_blocks(jnp.asarray(kp, jnp.float32), pool_dt)
        vq, vs = quantize_blocks(jnp.asarray(vp, jnp.float32), pool_dt)
    if kern == "gather":
        g = nq // nkv

        def step(qq):
            kc = gather_block_kv(kq, table, ks, qq.dtype)
            vc = gather_block_kv(vq, table, vs, qq.dtype)
            qg = qq.reshape(B, 1, nkv, g, H)
            s = jnp.einsum("bqngh,bknh->bngqk", qg, kc) / (H ** 0.5)
            live = jnp.arange(kc.shape[1])[None, :] <= pos[:, None]
            s = jnp.where(live[:, None, None, None, :], s, -jnp.inf)
            p = jax.nn.softmax(s.astype(jnp.float32), axis=-1
                               ).astype(qq.dtype)
            return jnp.einsum("bngqk,bknh->bqngh", p, vc).reshape(
                B, 1, nq, H)

        f = jax.jit(step)
    else:
        fpa = (fused_paged_online_attention if kern == "fused_online"
               else fused_paged_attention)
        f = jax.jit(lambda qq: fpa(qq, kq, vq, table, pos,
                                   k_scale=ks, v_scale=vs))
    out = f(q)
    jax.block_until_ready(out)

    def chain(kk):
        qq = q
        t0 = time.perf_counter()
        for _ in range(kk):
            qq = f(qq.astype(q.dtype))
        _ = float(qq[0, 0, 0, 0])
        return time.perf_counter() - t0

    pers = sorted(slope_time(chain, 8, 50) for _ in range(samples))
    per = pers[(samples - 1) // 2]
    hbm = 2 * B * maxb * bs * nkv * H * itemsize    # K + V pool reads
    if kvd in ("int8", "fp8"):
        hbm += 2 * B * maxb * nkv * 4               # scale sidecars
    return hbm / per / 1e9, per * 1e6, (pers[-1] - pers[0]) / per


def paged_main(jax, jnp, quick: bool, perfdb_path=None) -> int:
    from hpx_tpu.ops.attention_pallas import _PAGED_BLOCKS_FILE
    db = None
    if perfdb_path:
        # producer mode: every sweep point lands in the perfdb
        # observation log (provenance-stamped from the live backend)
        # and each kv_dtype winner in its learned-blocks tier —
        # ladder_search re-derives the block table from these instead
        # of trusting only the seed json
        from hpx_tpu.svc.perfdb import PerfDB, PerfKey, device_kind
        db = PerfDB(perfdb_path)
    S = 1024 if quick else 2048
    samples = 2 if quick else 3
    kernels = ("fused", "fused_online") if quick else _PAGED_KERNELS
    H = 128
    table = {}
    for kvd in ("bf16", "int8", "fp8"):
        best = None                    # (us, block_size, kernel)
        nmeas = 0
        for kern in kernels:
            for bs in (8, 16, 32, 64):
                try:
                    gbs, us, spread = paged_measure(jax, jnp, S, bs,
                                                    kvd, kern,
                                                    samples=samples)
                except Exception as e:  # noqa: BLE001 — eg VMEM OOM
                    print(json.dumps({"S": S, "kv_dtype": kvd,
                                      "kernel": kern, "block_size": bs,
                                      "error": str(e)[:120]}),
                          flush=True)
                    continue
                print(json.dumps({"S": S, "kv_dtype": kvd,
                                  "kernel": kern, "block_size": bs,
                                  "hbm_gb_per_s": round(gbs, 1),
                                  "us_per_step": round(us, 1),
                                  "spread": round(spread, 3)}),
                      flush=True)
                nmeas += 1
                if db is not None:
                    db.observe(
                        PerfKey(device_kind(), f"paged.hd{H}.s{S}",
                                kvd, kern),
                        "paged_step_us", us, n=samples,
                        program=f"bs{bs}", source="flash_tune")
                if best is None or us < best[0]:
                    best = (us, bs, kern)
        if best:
            table[f"hd{H}x{kvd}"] = best[1]
            total = _bank(table, _PAGED_BLOCKS_FILE)
            if db is not None:
                from hpx_tpu.svc.perfdb import _default_stamps
                db.record_block(f"hd{H}x{kvd}", {
                    "block_size": best[1], "kernel": best[2],
                    "samples": nmeas, **_default_stamps()})
                db.save()   # after EVERY class — same incremental
                            # discipline as _bank above
            print(json.dumps({"kv_dtype": kvd, "winner": best[1],
                              "kernel": best[2],
                              "us_per_step": round(best[0], 1),
                              "banked": total}), flush=True)
    print(json.dumps({"wrote": _PAGED_BLOCKS_FILE, "new": len(table),
                      "perfdb": perfdb_path}))
    return 0


def main() -> int:
    quick = "--quick" in sys.argv
    # single-class mode for a flaky tunnel: tune ONE (S, causal) per
    # invocation, e.g. --shape 4096 --causal 1 (the bench shape)
    shape_only = _arg("--shape")
    causal_only = _arg("--causal")
    import jax
    # the sandbox sitecustomize forces jax_platforms to axon-first; honor
    # an explicit JAX_PLATFORMS env so the guard below can run (and fail
    # fast) without touching a possibly-wedged device tunnel
    env_plat = os.environ.get("JAX_PLATFORMS")
    if env_plat:
        jax.config.update("jax_platforms", env_plat)
    import jax.numpy as jnp
    from hpx_tpu.ops.attention_pallas import _BLOCKS_FILE, flash_attention

    if jax.default_backend() != "tpu":
        print(json.dumps({"error": "flash_tune needs a real TPU; "
                          f"backend={jax.default_backend()}"}))
        return 1

    if "--paged" in sys.argv:
        return paged_main(jax, jnp, quick, perfdb_path=_arg("--perfdb"))

    seqs = (2048, 4096) if quick else (2048, 4096, 8192, 16384)
    if shape_only:
        seqs = (int(shape_only),)
    causals = (True, False) if causal_only is None else \
        (bool(int(causal_only)),)
    cand = (256, 512, 1024, 2048)
    samples = 2 if quick else 3
    table = {}
    for S in seqs:
        for causal in causals:
            best = None
            for bq in cand:
                if bq > S:
                    continue
                for bk in cand:
                    if bk > S:
                        continue
                    try:
                        tf, spread = measure(jax, jnp, flash_attention,
                                             S, causal, bq, bk,
                                             samples=samples)
                    except Exception as e:  # noqa: BLE001 — eg VMEM OOM
                        print(json.dumps({"S": S, "causal": causal,
                                          "bq": bq, "bk": bk,
                                          "error": str(e)[:120]}),
                              flush=True)
                        continue
                    print(json.dumps({"S": S, "causal": causal,
                                      "bq": bq, "bk": bk,
                                      "tflops": round(tf, 1),
                                      "spread": round(spread, 3)}),
                          flush=True)
                    if best is None or tf > best[0]:
                        best = (tf, bq, bk)
            if best:
                table[f"{S}x{S}x{int(causal)}"] = [best[1], best[2]]
                total = _bank(table, _BLOCKS_FILE)
                print(json.dumps({"S": S, "causal": causal,
                                  "winner": best[1:],
                                  "tflops": round(best[0], 1),
                                  "banked": total}),
                      flush=True)

    print(json.dumps({"wrote": _BLOCKS_FILE, "new": len(table)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
