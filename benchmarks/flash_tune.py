"""Flash-attention forward block-size autotune (real TPU).

Sweeps (block_q, block_k) per shape class — S in {2k, 4k, 8k, 16k},
causal x non-causal at the bench head layout (N8 H128 bf16) — with the
same slope-timing discipline as bench.py, prints one JSON line per
measurement, and writes the winners to
hpx_tpu/ops/flash_blocks.json, which ops/attention_pallas.resolve_blocks
consults whenever callers don't pass blocks explicitly.

Usage: python benchmarks/flash_tune.py [--quick]
  --quick: S in {2k, 4k} only and fewer samples (smoke/dev loops).
"""

import functools
import json
import os
import sys
import time

# repo root (this file lives in benchmarks/), regardless of the cwd
sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from bench import slope_time  # noqa: E402 — one timing discipline


def measure(jax, jnp, flash, S, causal, bq, bk, samples=3):
    B, N, H = (2, 8, 128) if S <= 8192 else (1, 8, 128)
    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.standard_normal((B, S, N, H), np.float32), jnp.bfloat16)
    q, k, v = mk(), mk(), mk()
    f = jax.jit(functools.partial(flash, causal=causal, block_q=bq,
                                  block_k=bk))
    out = f(q, k, v)
    jax.block_until_ready(out)

    def chain(kk):
        qq = q
        t0 = time.perf_counter()
        for _ in range(kk):
            qq = f(qq, k, v)
        _ = float(qq[0, 0, 0, 0])
        return time.perf_counter() - t0

    pers = sorted(slope_time(chain, 4, 20) for _ in range(samples))
    per = pers[(samples - 1) // 2]     # median (odd) / faster-of-2
    flops = 4 * B * N * S * S * H * (0.5 if causal else 1.0)
    return flops / per / 1e12, (pers[-1] - pers[0]) / per


def _arg(name):
    if name in sys.argv:
        return sys.argv[sys.argv.index(name) + 1]
    return None


def _bank(table, blocks_file) -> int:
    """Merge `table` into the on-disk table atomically; returns total.
    Called after EVERY shape class: a tunnel wedge mid-sweep must not
    discard classes already tuned (same discipline as bench.py's
    incremental fallback banking)."""
    try:
        with open(blocks_file) as f:
            merged = json.load(f)
    except (OSError, ValueError):
        merged = {}
    merged.update(table)
    tmp = blocks_file + ".tmp"
    with open(tmp, "w") as f:
        json.dump(merged, f, indent=1, sort_keys=True)
    os.replace(tmp, blocks_file)
    return len(merged)


def main() -> int:
    quick = "--quick" in sys.argv
    # single-class mode for a flaky tunnel: tune ONE (S, causal) per
    # invocation, e.g. --shape 4096 --causal 1 (the bench shape)
    shape_only = _arg("--shape")
    causal_only = _arg("--causal")
    import jax
    # the sandbox sitecustomize forces jax_platforms to axon-first; honor
    # an explicit JAX_PLATFORMS env so the guard below can run (and fail
    # fast) without touching a possibly-wedged device tunnel
    env_plat = os.environ.get("JAX_PLATFORMS")
    if env_plat:
        jax.config.update("jax_platforms", env_plat)
    import jax.numpy as jnp
    from hpx_tpu.ops.attention_pallas import _BLOCKS_FILE, flash_attention

    if jax.default_backend() != "tpu":
        print(json.dumps({"error": "flash_tune needs a real TPU; "
                          f"backend={jax.default_backend()}"}))
        return 1

    seqs = (2048, 4096) if quick else (2048, 4096, 8192, 16384)
    if shape_only:
        seqs = (int(shape_only),)
    causals = (True, False) if causal_only is None else \
        (bool(int(causal_only)),)
    cand = (256, 512, 1024, 2048)
    samples = 2 if quick else 3
    table = {}
    for S in seqs:
        for causal in causals:
            best = None
            for bq in cand:
                if bq > S:
                    continue
                for bk in cand:
                    if bk > S:
                        continue
                    try:
                        tf, spread = measure(jax, jnp, flash_attention,
                                             S, causal, bq, bk,
                                             samples=samples)
                    except Exception as e:  # noqa: BLE001 — eg VMEM OOM
                        print(json.dumps({"S": S, "causal": causal,
                                          "bq": bq, "bk": bk,
                                          "error": str(e)[:120]}),
                              flush=True)
                        continue
                    print(json.dumps({"S": S, "causal": causal,
                                      "bq": bq, "bk": bk,
                                      "tflops": round(tf, 1),
                                      "spread": round(spread, 3)}),
                          flush=True)
                    if best is None or tf > best[0]:
                        best = (tf, bq, bk)
            if best:
                table[f"{S}x{S}x{int(causal)}"] = [best[1], best[2]]
                total = _bank(table, _BLOCKS_FILE)
                print(json.dumps({"S": S, "causal": causal,
                                  "winner": best[1:],
                                  "tflops": round(best[0], 1),
                                  "banked": total}),
                      flush=True)

    print(json.dumps({"wrote": _BLOCKS_FILE, "new": len(table)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
