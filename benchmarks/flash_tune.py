"""Flash-attention forward block-size autotune (real TPU).

Sweeps (block_q, block_k) per shape class — S in {2k, 4k, 8k, 16k},
causal x non-causal at the bench head layout (N8 H128 bf16) — with the
same slope-timing discipline as bench.py, prints one JSON line per
measurement, and writes the winners to
hpx_tpu/ops/flash_blocks.json, which ops/attention_pallas.resolve_blocks
consults whenever callers don't pass blocks explicitly.

Usage: python benchmarks/flash_tune.py [--quick]
  --quick: S in {2k, 4k} only and fewer samples (smoke/dev loops).
"""

import functools
import json
import os
import sys
import time

sys.path.insert(0, ".")

import numpy as np

from bench import slope_time  # noqa: E402 — one timing discipline


def measure(jax, jnp, flash, S, causal, bq, bk, samples=3):
    B, N, H = (2, 8, 128) if S <= 8192 else (1, 8, 128)
    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.standard_normal((B, S, N, H), np.float32), jnp.bfloat16)
    q, k, v = mk(), mk(), mk()
    f = jax.jit(functools.partial(flash, causal=causal, block_q=bq,
                                  block_k=bk))
    out = f(q, k, v)
    jax.block_until_ready(out)

    def chain(kk):
        qq = q
        t0 = time.perf_counter()
        for _ in range(kk):
            qq = f(qq, k, v)
        _ = float(qq[0, 0, 0, 0])
        return time.perf_counter() - t0

    pers = sorted(slope_time(chain, 4, 20) for _ in range(samples))
    per = pers[(samples - 1) // 2]     # median (odd) / faster-of-2
    flops = 4 * B * N * S * S * H * (0.5 if causal else 1.0)
    return flops / per / 1e12, (pers[-1] - pers[0]) / per


def main() -> int:
    quick = "--quick" in sys.argv
    import jax
    import jax.numpy as jnp
    from hpx_tpu.ops.attention_pallas import _BLOCKS_FILE, flash_attention

    if jax.default_backend() != "tpu":
        print(json.dumps({"error": "flash_tune needs a real TPU; "
                          f"backend={jax.default_backend()}"}))
        return 1

    seqs = (2048, 4096) if quick else (2048, 4096, 8192, 16384)
    cand = (256, 512, 1024, 2048)
    samples = 2 if quick else 3
    table = {}
    for S in seqs:
        for causal in (True, False):
            best = None
            for bq in cand:
                if bq > S:
                    continue
                for bk in cand:
                    if bk > S:
                        continue
                    try:
                        tf, spread = measure(jax, jnp, flash_attention,
                                             S, causal, bq, bk,
                                             samples=samples)
                    except Exception as e:  # noqa: BLE001 — eg VMEM OOM
                        print(json.dumps({"S": S, "causal": causal,
                                          "bq": bq, "bk": bk,
                                          "error": str(e)[:120]}),
                              flush=True)
                        continue
                    print(json.dumps({"S": S, "causal": causal,
                                      "bq": bq, "bk": bk,
                                      "tflops": round(tf, 1),
                                      "spread": round(spread, 3)}),
                          flush=True)
                    if best is None or tf > best[0]:
                        best = (tf, bq, bk)
            if best:
                table[f"{S}x{S}x{int(causal)}"] = [best[1], best[2]]
                print(json.dumps({"S": S, "causal": causal,
                                  "winner": best[1:],
                                  "tflops": round(best[0], 1)}),
                      flush=True)

    # MERGE into any existing table (a --quick smoke must not discard
    # previously tuned 8k/16k entries) and write atomically (a kill
    # mid-dump must not leave a truncated file that silently reads as
    # an empty table)
    try:
        with open(_BLOCKS_FILE) as f:
            merged = json.load(f)
    except (OSError, ValueError):
        merged = {}
    merged.update(table)
    tmp = _BLOCKS_FILE + ".tmp"
    with open(tmp, "w") as f:
        json.dump(merged, f, indent=1, sort_keys=True)
    os.replace(tmp, _BLOCKS_FILE)
    print(json.dumps({"wrote": _BLOCKS_FILE, "new": len(table),
                      "total": len(merged)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
