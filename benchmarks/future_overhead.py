"""future_overhead — task spawn/schedule throughput microbenchmark.

Reference analog: tests/performance/local/future_overhead.cpp (the
canonical HPX scheduler benchmark: spawn N null tasks, measure
tasks/second; literature magnitude O(10^6)/s/core — BASELINE.md).

Measures, per scheduler backend available:
  create_thread_hierarchical: async_ fan-out, wait_all
  post (fire-and-forget) with a latch
  sync-execute baseline (function call floor)

Prints one perftests-style JSON line per case (hpx::util::
perftests_report analog).

Usage: python benchmarks/future_overhead.py [num_tasks]
"""

import json
import sys
import time

sys.path.insert(0, ".")

import hpx_tpu as hpx  # noqa: E402


def null_fn() -> None:
    pass


def bench(name: str, n: int, fn, executor: str = "default-pool") -> dict:
    t0 = time.perf_counter()
    fn(n)
    dt = time.perf_counter() - t0
    row = {
        "name": name,
        "executor": executor,
        "tasks": n,
        "seconds": round(dt, 6),
        "tasks_per_s": round(n / dt, 1),
        "us_per_task": round(dt / n * 1e6, 3),
    }
    print(json.dumps(row))
    return row


def case_async_wait_all(n: int) -> None:
    hpx.wait_all([hpx.async_(null_fn) for _ in range(n)])


def case_post_latch(n: int) -> None:
    latch = hpx.Latch(n + 1)

    def hit() -> None:
        latch.count_down(1)

    for _ in range(n):
        hpx.post(hit)
    latch.arrive_and_wait()


def case_post_many_latch(n: int) -> None:
    """Batched fan-out: ONE submit_many crossing for all n tasks (the
    C-ABI amortization path — hpxrt_pool_submit_many)."""
    latch = hpx.Latch(n + 1)

    def hit() -> None:
        latch.count_down(1)

    hpx.post_many(hit, [()] * n)
    latch.arrive_and_wait()


def case_async_many_wait_all(n: int) -> None:
    hpx.wait_all(hpx.async_many(null_fn, [()] * n))


def case_sync_floor(n: int) -> None:
    for _ in range(n):
        null_fn()


def _hist_record_cases(n: int) -> None:
    """HistogramCounter.record() floor — the per-token cost every
    serving histogram charges the decode loop. Three states: bare
    (the pre-observability path), exemplars attached but value below
    the capture threshold (the common case: gate check only), and
    exemplars capturing on every record (worst case, top bucket)."""
    from hpx_tpu.svc.exemplars import ExemplarReservoir
    from hpx_tpu.svc.metrics import HistogramCounter

    def record_loop(h, v):
        def run(k):
            for _ in range(k):
                h.record(v)
        return run

    bare = HistogramCounter()
    bench("hist.record (bare)", n, record_loop(bare, 0.01),
          "histogram")
    below = HistogramCounter()
    below.record(10.0)  # pins the capture threshold to the top bucket
    below._ex = ExemplarReservoir(below, per_bucket=4, quantile=0.99,
                                  refresh=1 << 30)
    bench("hist.record (exemplars, below threshold)", n,
          record_loop(below, 0.01), "histogram")
    hot = HistogramCounter()
    hot._ex = ExemplarReservoir(hot, per_bucket=4, quantile=0.5,
                                refresh=1 << 30)
    hot.record(0.01)
    bench("hist.record (exemplars, capturing)", n,
          record_loop(hot, 0.01), "histogram")


def _native_cases(n: int) -> None:
    """Same spawn patterns straight on the C++ pool (the scheduler the
    reference's future_overhead exercises): per-task submits cross the
    C ABI n times; submit_many crosses ONCE."""
    try:
        import os
        from hpx_tpu.native.loader import NativePool
        # size to the host: every task re-enters the interpreter, so
        # extra C++ workers on few cores just fight over the GIL
        pool = NativePool(max(1, min(4, os.cpu_count() or 1)), "bench")
    except Exception as e:  # noqa: BLE001
        print(json.dumps({"name": "native pool unavailable",
                          "error": str(e)}))
        return
    try:
        def post_each(k):
            latch = hpx.Latch(k + 1)
            for _ in range(k):
                pool.submit(latch.count_down, 1)
            latch.arrive_and_wait()

        def post_batch(k):
            latch = hpx.Latch(k + 1)
            pool.submit_many([(latch.count_down, (1,), {})] * k)
            latch.arrive_and_wait()

        post_each(1000)                       # warm
        bench("post+latch", n, post_each, "native-pool")
        bench("post_many+latch (batched)", n, post_batch, "native-pool")
    finally:
        pool.shutdown()


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 50_000
    # warm the pool
    hpx.wait_all([hpx.async_(null_fn) for _ in range(100)])

    bench("async+wait_all", n, case_async_wait_all)
    bench("post+latch", n, case_post_latch)
    bench("post_many+latch (batched)", n, case_post_many_latch)
    bench("async_many+wait_all (batched)", n, case_async_many_wait_all)
    _native_cases(n)
    bench("call floor (no tasks)", n, case_sync_floor)
    _hist_record_cases(n)
    return 0


if __name__ == "__main__":
    sys.exit(main())
