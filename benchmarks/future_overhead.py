"""future_overhead — task spawn/schedule throughput microbenchmark.

Reference analog: tests/performance/local/future_overhead.cpp (the
canonical HPX scheduler benchmark: spawn N null tasks, measure
tasks/second; literature magnitude O(10^6)/s/core — BASELINE.md).

Measures, per scheduler backend available:
  create_thread_hierarchical: async_ fan-out, wait_all
  post (fire-and-forget) with a latch
  sync-execute baseline (function call floor)

Prints one perftests-style JSON line per case (hpx::util::
perftests_report analog).

Usage: python benchmarks/future_overhead.py [num_tasks]
"""

import json
import sys
import time

sys.path.insert(0, ".")

import hpx_tpu as hpx  # noqa: E402


def null_fn() -> None:
    pass


def bench(name: str, n: int, fn) -> dict:
    t0 = time.perf_counter()
    fn(n)
    dt = time.perf_counter() - t0
    row = {
        "name": name,
        "executor": "default-pool",
        "tasks": n,
        "seconds": round(dt, 6),
        "tasks_per_s": round(n / dt, 1),
        "us_per_task": round(dt / n * 1e6, 3),
    }
    print(json.dumps(row))
    return row


def case_async_wait_all(n: int) -> None:
    hpx.wait_all([hpx.async_(null_fn) for _ in range(n)])


def case_post_latch(n: int) -> None:
    latch = hpx.Latch(n + 1)

    def hit() -> None:
        latch.count_down(1)

    for _ in range(n):
        hpx.post(hit)
    latch.arrive_and_wait()


def case_sync_floor(n: int) -> None:
    for _ in range(n):
        null_fn()


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 50_000
    # warm the pool
    hpx.wait_all([hpx.async_(null_fn) for _ in range(100)])

    bench("async+wait_all", n, case_async_wait_all)
    bench("post+latch", n, case_post_latch)
    bench("call floor (no tasks)", n, case_sync_floor)
    return 0


if __name__ == "__main__":
    sys.exit(main())
