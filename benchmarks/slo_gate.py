#!/usr/bin/env python
"""SLO regression gate over two ``hpx_tpu.metrics.v1`` artifacts.

"p99 regressed" from eyeballing two JSON files is neither typed nor
sound: the histograms behind the artifacts answer quantiles with a
KNOWN relative error bound (``gamma**0.5 - 1``, ~4.4% at the default
8 subbuckets/octave), so two estimates within their combined bounds
are indistinguishable, not a regression.  This gate compares the two
artifacts quantile-by-quantile and flags a regression only when the
candidate's most-favorable true value still exceeds the baseline's
least-favorable one::

    cand_q / (1 + eb_cand)  >  base_q * (1 + eb_base)

Histograms are rebuilt from their mergeable snapshots (both the
serving_bench shape — snapshot + quantiles + relative_error_bound —
and bench.py's snapshot-only child shape load), so quantiles are
recomputed consistently even across artifacts written by different
quantile sets.

Usage::

    python benchmarks/slo_gate.py BASELINE CANDIDATE \
        [--quantiles 0.5,0.95,0.99] [--format text|json]

Exit status: 0 = no regression, 1 = at least one regression, 2 = bad
input.  ``bench.py --baseline PREV`` runs this automatically against
the round's ``--metrics-out`` artifact.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from hpx_tpu.svc.metrics import HistogramCounter  # noqa: E402

METRICS_SCHEMA = "hpx_tpu.metrics.v1"
DEFAULT_QUANTILES = (0.5, 0.95, 0.99)

# verdict kinds, worst first (report ordering)
KIND_REGRESSED = "regressed"
KIND_OK = "ok"
KIND_IMPROVED = "improved"
KIND_INCOMPARABLE = "incomparable"


@dataclasses.dataclass
class Verdict:
    """One (histogram, quantile) comparison — a typed, bounded-error
    statement, not a raw diff."""

    name: str
    quantile: str               # "p99"
    kind: str                   # regressed | ok | improved | incomparable
    baseline: float
    candidate: float
    error_bound: float          # combined relative bound used
    margin: float               # cand/base - 1 (0 when incomparable)
    note: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def load_artifact(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("schema") != METRICS_SCHEMA:
        raise ValueError(
            f"{path}: schema {doc.get('schema')!r} != {METRICS_SCHEMA!r}")
    if not isinstance(doc.get("histograms"), dict):
        raise ValueError(f"{path}: no histograms section")
    return doc


def _rebuild(entry: Dict[str, Any]) -> Optional[HistogramCounter]:
    snap = entry.get("snapshot") if isinstance(entry, dict) else None
    if not isinstance(snap, dict):
        return None
    try:
        return HistogramCounter.from_snapshot(snap)
    except Exception:  # noqa: BLE001 — malformed entry → incomparable
        return None


def _qlabel(q: float) -> str:
    return f"p{round(q * 100.0, 4):g}"


def compare(base_doc: Dict[str, Any], cand_doc: Dict[str, Any],
            quantiles: Tuple[float, ...] = DEFAULT_QUANTILES
            ) -> List[Verdict]:
    """Quantile-by-quantile verdicts over the union of histogram
    names.  Names present on only one side are ``incomparable`` info
    rows, never regressions (a renamed wave must not masquerade as a
    perf win)."""
    base_h = base_doc["histograms"]
    cand_h = cand_doc["histograms"]
    verdicts: List[Verdict] = []
    for name in sorted(set(base_h) | set(cand_h)):
        if name not in base_h or name not in cand_h:
            side = "baseline" if name not in cand_h else "candidate"
            verdicts.append(Verdict(
                name=name, quantile="*", kind=KIND_INCOMPARABLE,
                baseline=0.0, candidate=0.0, error_bound=0.0,
                margin=0.0, note=f"only in {side}"))
            continue
        hb = _rebuild(base_h[name])
        hc = _rebuild(cand_h[name])
        if hb is None or hc is None:
            verdicts.append(Verdict(
                name=name, quantile="*", kind=KIND_INCOMPARABLE,
                baseline=0.0, candidate=0.0, error_bound=0.0,
                margin=0.0, note="unreadable snapshot"))
            continue
        if not hb.count or not hc.count:
            verdicts.append(Verdict(
                name=name, quantile="*", kind=KIND_INCOMPARABLE,
                baseline=float(hb.count), candidate=float(hc.count),
                error_bound=0.0, margin=0.0,
                note="empty histogram"))
            continue
        eb = hb.relative_error_bound()
        ec = hc.relative_error_bound()
        for q in quantiles:
            vb = hb.quantile(q)
            vc = hc.quantile(q)
            margin = (vc / vb - 1.0) if vb > 0.0 else 0.0
            if vb > 0.0 and vc / (1.0 + ec) > vb * (1.0 + eb):
                kind = KIND_REGRESSED
            elif vb > 0.0 and vc * (1.0 + ec) < vb / (1.0 + eb):
                kind = KIND_IMPROVED
            else:
                kind = KIND_OK
            verdicts.append(Verdict(
                name=name, quantile=_qlabel(q), kind=kind,
                baseline=vb, candidate=vc,
                error_bound=(1.0 + eb) * (1.0 + ec) - 1.0,
                margin=margin))
    return verdicts


def regressions(verdicts: List[Verdict]) -> List[Verdict]:
    return [v for v in verdicts if v.kind == KIND_REGRESSED]


def render_text(verdicts: List[Verdict]) -> str:
    order = {KIND_REGRESSED: 0, KIND_IMPROVED: 1, KIND_OK: 2,
             KIND_INCOMPARABLE: 3}
    lines = []
    for v in sorted(verdicts, key=lambda v: (order.get(v.kind, 9),
                                             v.name, v.quantile)):
        if v.kind == KIND_INCOMPARABLE:
            lines.append(f"?  {v.name} {v.quantile}: {v.note}")
        else:
            mark = {"regressed": "✗", "improved": "✓", "ok": "="}[v.kind]
            lines.append(
                f"{mark}  {v.name} {v.quantile}: "
                f"{v.baseline:.6g} -> {v.candidate:.6g} "
                f"({v.margin:+.1%}, bound ±{v.error_bound:.1%}) "
                f"{v.kind}")
    n_reg = len(regressions(verdicts))
    lines.append(f"regressions: {n_reg}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="bounded-error SLO regression gate over two "
                    "hpx_tpu.metrics.v1 artifacts")
    ap.add_argument("baseline")
    ap.add_argument("candidate")
    ap.add_argument("--quantiles", default=None,
                    help="csv quantiles (default 0.5,0.95,0.99)")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    args = ap.parse_args(argv)
    try:
        base = load_artifact(args.baseline)
        cand = load_artifact(args.candidate)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"slo_gate: {e}", file=sys.stderr)
        return 2
    qs = DEFAULT_QUANTILES
    if args.quantiles:
        qs = tuple(float(p) for p in args.quantiles.split(",") if p)
    verdicts = compare(base, cand, qs)
    if args.format == "json":
        print(json.dumps({
            "baseline": args.baseline,
            "candidate": args.candidate,
            "regressions": len(regressions(verdicts)),
            "verdicts": [v.to_dict() for v in verdicts],
        }, indent=1))
    else:
        print(render_text(verdicts))
    return 1 if regressions(verdicts) else 0


if __name__ == "__main__":
    raise SystemExit(main())
