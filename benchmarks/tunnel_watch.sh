#!/bin/bash
# Round-5 tunnel watcher: probe the axon TPU tunnel periodically; when
# it answers, bank live bench metrics ONE AT A TIME (HPX_BENCH_ONLY +
# the incremental fallback record) in priority order, then tune the
# flash blocks for the bench shape. Each piece is separately bounded so
# a mid-measurement wedge costs one metric, not the run. Run from the
# repo root; logs to benchmarks/watch_<ts>.log.
set -u
cd "$(dirname "$0")/.."
ts=$(date -u +%Y%m%dT%H%M%S)
log="benchmarks/watch_${ts}.log"
# freshness floor for the exit check: full ISO second resolution, taken
# at watch START — a date-only floor would count metrics banked earlier
# the same day (before this watch) as fresh and exit without measuring
since=$(date -u +%Y-%m-%dT%H:%M:%S)
deadline=$(( $(date +%s) + ${HPX_WATCH_BUDGET_S:-32400} ))   # 9h default

metrics=(flash_attention_tflops transformer_step_ms \
         flash_attention_bwd_tflops stream_triad_gbs \
         1d_stencil_unfused_cell_updates fft_1d_gflops \
         1d_stencil_cell_updates)

echo "watch start $(date -u +%H:%M:%S)" | tee -a "$log"
while [ "$(date +%s)" -lt "$deadline" ]; do
    if ! timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1
    then
        echo "$(date -u +%H:%M:%S) probe: down" >> "$log"
        sleep "${HPX_WATCH_INTERVAL_S:-600}"
        continue
    fi
    echo "$(date -u +%H:%M:%S) probe: UP — banking metrics" | tee -a "$log"
    for m in "${metrics[@]}"; do
        echo "$(date -u +%H:%M:%S) metric $m" >> "$log"
        HPX_BENCH_ONLY="$m" HPX_BENCH_PROBE_BUDGET=120 \
            HPX_BENCH_CHILD_TIMEOUT=900 timeout 1100 \
            python bench.py >> "$log" 2>&1
    done
    echo "$(date -u +%H:%M:%S) tuning flash 4096/causal" >> "$log"
    timeout 1500 python benchmarks/flash_tune.py --quick \
        --shape 4096 --causal 1 >> "$log" 2>&1
    echo "$(date -u +%H:%M:%S) tuning flash 4096/non-causal" >> "$log"
    timeout 1500 python benchmarks/flash_tune.py --quick \
        --shape 4096 --causal 0 >> "$log" 2>&1
    # one more full pass with tuned blocks, then exit if it all banked
    HPX_BENCH_PROBE_BUDGET=120 HPX_BENCH_CHILD_TIMEOUT=2700 \
        timeout 3000 python bench.py >> "$log" 2>&1
    if HPX_WATCH_SINCE="$since" \
        python - <<'EOF'
import json, os, sys
try:
    rec = json.load(open("bench_fallback.local.json"))
except Exception:
    sys.exit(1)
since = os.environ["HPX_WATCH_SINCE"]
fresh = [l for l in rec.get("lines", [])
         if str(l.get("measured_at", "")) >= since]
sys.exit(0 if len(fresh) >= 7 else 1)
EOF
    then
        echo "$(date -u +%H:%M:%S) full fresh record banked — done" \
            | tee -a "$log"
        exit 0
    fi
    sleep "${HPX_WATCH_INTERVAL_S:-600}"
done
echo "watch budget exhausted $(date -u +%H:%M:%S)" | tee -a "$log"
