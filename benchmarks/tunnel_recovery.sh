#!/bin/bash
# One-shot measurement sequence for when the axon TPU tunnel recovers
# (round-5 plan: BASELINE.md "Round-5 status"). Run from the repo root.
#
#   1. bench.py             — full metric set incl. the new
#                             flash_attention_bwd_tflops and copy_ratio;
#                             writes bench_fallback.local.json
#   2. flash_tune --quick   — 2k/4k block sweep -> flash_blocks.json
#   3. bench.py (again)     — flash forward re-measured with tuned tiles
#
# Artifacts land in benchmarks/recovery_*.log; commit flash_blocks.json
# with `git add -f hpx_tpu/ops/flash_blocks.json` if the tuned table
# beats the 1024x1024 default.
set -u
cd "$(dirname "$0")/.."
ts=$(date -u +%Y%m%dT%H%M%S)

echo "== probe =="
if ! timeout 120 python -c "import jax; print(jax.devices())"; then
    echo "tunnel still down"; exit 1
fi

echo "== bench (pre-tune) ==" | tee "benchmarks/recovery_${ts}.log"
HPX_BENCH_PROBE_BUDGET=300 python bench.py 2>&1 | tee -a "benchmarks/recovery_${ts}.log"

echo "== flash tune (quick) ==" | tee -a "benchmarks/recovery_${ts}.log"
timeout 1800 python benchmarks/flash_tune.py --quick 2>&1 | tee -a "benchmarks/recovery_${ts}.log"

echo "== bench (post-tune) ==" | tee -a "benchmarks/recovery_${ts}.log"
HPX_BENCH_PROBE_BUDGET=300 python bench.py 2>&1 | tee -a "benchmarks/recovery_${ts}.log"

echo "done: benchmarks/recovery_${ts}.log"
