#!/usr/bin/env python
"""Offline shape search: re-derive the serving ladders from the perfdb.

The online AdaptiveTuner (svc/autotune) walks baked ladders one
bounded step at a time; this tool re-derives the ladders themselves —
the geometric prefill-bucket geometry, the paged block-size table, the
spec-k bounds and the per-knob ``Tunable(lo,hi,step)`` ranges — from
the cost surface the persistent perf store (svc/perfdb) banked across
runs.  Compile-heavy exploration happens HERE, offline; a serving
process only ever reads the winning ladder at boot
(``hpx.perfdb.use_learned_ladders=1``).

Search objective (per store key, deterministic — no clocks, no RNG):
candidate bucket ladders are the subsets of the geometric doubling
ladder ``{8, 16, ..., chunk}`` that contain the chunk.  Each candidate
``L`` is scored as a serving-time rate: predicted warm padding cost
plus amortized compile cost, both dimensionless fractions of the
serving horizon::

    score(L) = frac_prefill * E_len[cost_L(len)] / chunk  # padded work
             + |L| * c_compile / amortize_s               # ladder mint

``cost_L(len) = max(rung_L(len), 32)`` — the per-chunk cost floor:
below ~32 rows a chunk dispatch is overhead-bound (fixed XLA dispatch
cost on CPU, the 8x128 minimum MXU tile on TPU), so padding a tiny
prompt up to a 32-wide bucket is free in wall-clock terms and the
search correctly prunes sub-floor rungs without predicting a warm
regression.  ``frac_prefill`` is the fraction of warm wall-clock the
store attributes to prefill (the ``prefill_frac`` metric serving
bench's ladder seed banks from a prefill-only probe drive; falls back
to the per-program ``exec_p50_s`` share of chunk-tagged programs,
then to 1.0 — the never-prune direction) — a coarser ladder only
pads THAT slice of the run, which keeps the search from collapsing
to the single-rung ladder on decode-dominated mixes.  ``c_compile``
is the banked mean compile seconds per program (``compile_s``;
serving_bench's ladder seed banks the honest cold-minus-warm
wall-clock estimate), the expectation over lengths uses the banked
per-rung ``chunk_demand`` histogram when present (the measured
workload, remainder chunks included) and falls back to uniform on
``[1, chunk]``, and ``amortize_s`` is the same horizon the online
tuner charges compile-minting moves (hpx.tune.compile_amortize_s
semantics).  Lowest score wins; ties break toward FEWER rungs, then
lexicographically — so the proposal is a pure function of the store
and byte-identical across runs (pinned by tests/test_perfdb.py).

Paged block sizes: keys carrying flash_tune's ``paged_step_us``
sweeps (program = ``bs<N>``) get their per-(head_dim, kv_dtype)
winner re-derived by argmin mean microseconds and banked into the
store's learned-blocks tier.

Provenance: a ladder derived from builder-session-only samples is
REFUSED (printed, not installed) unless ``--allow-session`` — the
same honesty discipline as bench.py's on-chip medians.  Offline
arbitration: pass ``--gate-base``/``--gate-cand`` metrics artifacts
and the install is additionally gated on benchmarks/slo_gate.py
finding no bounded-error quantile regression.

Usage::

    python benchmarks/ladder_search.py --db PATH
        [--key KEY]            # default: every key in the store
        [--chunk 128] [--min-samples 3] [--amortize-s 30]
        [--allow-session] [--dry-run]
        [--gate-base BASELINE.json --gate-cand CANDIDATE.json]

Exit status: 0 = at least one ladder installed (or --dry-run),
1 = nothing derivable, 2 = bad input, 3 = slo gate refused.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from hpx_tpu.svc.perfdb import (  # noqa: E402
    PERFDB_SCHEMA, PerfDB, PerfDBSchemaError)

DEFAULT_CHUNK = 128
DEFAULT_AMORTIZE_S = 30.0

# per-chunk dispatch cost floor, in padded rows: below this width a
# chunk program is overhead-bound (fixed dispatch cost on CPU, the
# 8x128 minimum MXU tile on TPU), so rungs under the floor cost the
# same wall-clock as a floor-width rung
DISPATCH_FLOOR_ROWS = 32


def _geometric_ladder(chunk: int) -> List[int]:
    out, w = [], 8
    while w < chunk:
        out.append(w)
        w *= 2
    out.append(chunk)
    return out


def _candidates(chunk: int) -> List[Tuple[int, ...]]:
    """Every subset of the doubling ladder that keeps the chunk rung
    (the ladder contract: every chunk has a bucket), deterministic
    order."""
    rungs = _geometric_ladder(chunk)
    lower, out = rungs[:-1], []
    for mask in range(1 << len(lower)):
        cand = tuple(sorted(
            [r for i, r in enumerate(lower) if mask >> i & 1]
            + [chunk]))
        out.append(cand)
    return sorted(set(out), key=lambda c: (len(c), c))


def _expected_rung(ladder: Tuple[int, ...], chunk: int) -> float:
    """E[rung(len)] for len uniform on [1, chunk]: each rung serves
    the lengths between its predecessor and itself."""
    total, prev = 0.0, 0
    for r in ladder:
        total += (r - prev) * r
        prev = r
    return total / chunk


def _expected_cost(ladder: Tuple[int, ...], chunk: int,
                   floor: int = DISPATCH_FLOOR_ROWS) -> float:
    """E[cost(len)] for len uniform on [1, chunk], where a rung's
    per-chunk cost is max(rung, floor) padded rows — the dispatch
    cost floor makes sub-floor rungs equally priced, so the search
    sees pruning them as free."""
    total, prev = 0.0, 0
    for r in ladder:
        total += (r - prev) * max(r, floor)
        prev = r
    return total / chunk


def _padded_ratio(ladder: Tuple[int, ...],
                  demand: Dict[int, float],
                  floor: int = DISPATCH_FLOOR_ROWS) -> float:
    """Predicted prefill cost of ``ladder`` relative to the ladder
    the demand histogram was measured under: each measured rung's
    demand rounds up to the candidate's smallest rung that covers it,
    priced at max(rung, floor) padded rows."""
    base = sum(d * max(r, floor) for r, d in demand.items())
    if base <= 0:
        return 1.0
    cand = 0.0
    for r, d in demand.items():
        up = min((b for b in ladder if b >= r), default=ladder[-1])
        cand += d * max(up, floor)
    return cand / base


def score_ladder(ladder: Tuple[int, ...], chunk: int,
                 frac_prefill: float, c_compile: float,
                 amortize_s: float,
                 demand: Optional[Dict[int, float]] = None) -> float:
    if demand:
        padded = frac_prefill * _padded_ratio(ladder, demand)
    else:
        padded = frac_prefill * _expected_cost(ladder, chunk) / chunk
    mint = len(ladder) * c_compile / max(amortize_s, 1e-9)
    return padded + mint


def derive_ladder(db: PerfDB, key: str, chunk: int = DEFAULT_CHUNK,
                  min_samples: int = 3,
                  amortize_s: float = DEFAULT_AMORTIZE_S
                  ) -> Optional[Dict[str, Any]]:
    """The deterministic per-key derivation: ladder proposal dict, or
    None when the store lacks a usable cost model for ``key``.  The
    returned dict is a pure function of (store contents, args) — NO
    timestamps, NO environment reads — so the same DB always yields a
    byte-identical proposal (the determinism test pins this)."""
    comp = db.model(key, "compile_s")
    execm = db.model(key, "exec_p50_s")
    if comp.get("n", 0) < min_samples or execm.get("n", 0) < 1:
        return None
    c_compile = comp["mean"]
    # padding only costs the prefill slice of the run.  Preferred
    # source: the wall-clock prefill_frac the ladder seed banks from
    # a prefill-only probe (stable — async dispatch hides compute
    # from per-call timers).  Fallbacks: the per-program exec share
    # of chunk-tagged programs, then 1.0 — charge the whole run,
    # the safe never-prune direction for a sparse store.
    fracm = db.model(key, "prefill_frac")
    if fracm.get("n", 0) >= 1:
        frac_prefill = min(1.0, max(0.0, fracm["mean"]))
    else:
        progs = db.program_models(key, "exec_p50_s")
        chunk_s = sum(m["n"] * m["mean"] for p, m in progs.items()
                      if "chunk" in p)
        total_s = sum(m["n"] * m["mean"] for m in progs.values())
        frac_prefill = chunk_s / total_s if total_s > 0 else 1.0
    # the banked per-rung chunk-demand histogram (mean count per run)
    # re-prices candidates against the measured workload; without it
    # the uniform-length expectation stands in
    demand = {int(p[1:]): m["mean"] for p, m in
              db.program_models(key, "chunk_demand").items()
              if p.startswith("r") and p[1:].isdigit()}
    best: Optional[Tuple[float, Tuple[int, ...]]] = None
    for cand in _candidates(chunk):
        s = score_ladder(cand, chunk, frac_prefill, c_compile,
                         amortize_s, demand=demand)
        if best is None or s < best[0]:
            best = (s, cand)
    assert best is not None
    score, ladder = best
    n = comp["n"] + execm["n"]
    onchip_n = comp.get("onchip_n", 0) + execm.get("onchip_n", 0)
    onchip = onchip_n == n and n > 0
    spec_hi = max(1, ladder[-1] - 1)
    # spec-k bounds ride the derived ladder (the verify window is a
    # bucket); best stays the declared default clamped into range —
    # acceptance-rate adaptation remains the ONLINE tuner's job
    spec_k = {"lo": 1, "hi": min(16, spec_hi),
              "best": min(4, spec_hi)}
    return {
        "prefill_buckets": list(ladder),
        "prefill_chunk": chunk,
        "spec_k": spec_k,
        "tunables": {
            "hpx.serving.prefill_chunk": {
                "lo": ladder[0], "hi": chunk, "step": 2},
            "hpx.serving.spec.k": {
                "lo": spec_k["lo"], "hi": spec_k["hi"], "step": 1},
        },
        "samples": n,
        "onchip": onchip,
        "provenance": "on-chip" if onchip else "builder-session",
        "objective": {
            "score": round(score, 9),
            "prefill_frac": round(frac_prefill, 9),
            "c_compile_s": round(c_compile, 9),
            "amortize_s": amortize_s,
            "expected_rung": round(_expected_rung(ladder, chunk), 6),
            "expected_cost": round(_expected_cost(ladder, chunk), 6),
            "padded_ratio": round(_padded_ratio(ladder, demand), 6)
            if demand else None,
            "demand": {str(r): round(demand[r], 3)
                       for r in sorted(demand)} or None,
            "candidates": len(_candidates(chunk)),
        },
    }


def derive_blocks(db: PerfDB, min_samples: int = 3
                  ) -> Dict[str, Dict[str, Any]]:
    """Re-derive the paged block-size table from banked
    ``paged_step_us`` sweeps (flash_tune --paged --perfdb): for each
    (head_dim, kv_dtype) seen, argmin mean microseconds over the
    ``bs<N>`` programs.  Deterministic: ties break toward the smaller
    block."""
    out: Dict[str, Dict[str, Any]] = {}
    sweeps: Dict[str, Dict[int, Dict[str, Any]]] = {}
    for key in db.keys():
        parts = key.split("|")
        if len(parts) != 5 or not parts[1].startswith("paged.hd"):
            continue
        hd = parts[1].split(".")[1][2:]        # paged.hd128.s2048
        bkey = f"hd{hd}x{parts[2]}"
        for row in db.observations:
            if row["key"] != key or row["metric"] != "paged_step_us" \
                    or not str(row.get("program", "")).startswith("bs"):
                continue
            bs = int(str(row["program"])[2:])
            cur = sweeps.setdefault(bkey, {}).setdefault(
                bs, {"sum": 0.0, "n": 0, "onchip_n": 0})
            cur["sum"] += float(row["value"])
            cur["n"] += 1
            cur["onchip_n"] += 1 if row.get("onchip") else 0
    for bkey in sorted(sweeps):
        table = sweeps[bkey]
        total = sum(c["n"] for c in table.values())
        if total < min_samples:
            continue
        best_bs = min(sorted(table),
                      key=lambda b: (table[b]["sum"] / table[b]["n"], b))
        onchip = all(c["onchip_n"] == c["n"] for c in table.values())
        out[bkey] = {
            "block_size": best_bs, "samples": total,
            "onchip": onchip,
            "provenance": "on-chip" if onchip else "builder-session",
        }
    return out


def _slo_gate(base: str, cand: str) -> List[Any]:
    """Offline candidate arbitration via benchmarks/slo_gate.py:
    regressions between two metrics artifacts (bounded-error quantile
    compare).  Empty list = candidate admissible."""
    from slo_gate import compare, load_artifact, regressions
    return regressions(compare(load_artifact(base),
                               load_artifact(cand)))


def _arg(name: str) -> Optional[str]:
    if name in sys.argv:
        return sys.argv[sys.argv.index(name) + 1]
    return None


def main() -> int:
    db_path = _arg("--db")
    if not db_path:
        print(json.dumps({"error": "--db PATH is required"}))
        return 2
    try:
        db = PerfDB(db_path)
    except PerfDBSchemaError as e:
        print(json.dumps({"error": str(e), "schema": PERFDB_SCHEMA}))
        return 2
    chunk = int(_arg("--chunk") or DEFAULT_CHUNK)
    min_samples = int(_arg("--min-samples") or 3)
    amortize_s = float(_arg("--amortize-s") or DEFAULT_AMORTIZE_S)
    allow_session = "--allow-session" in sys.argv
    dry = "--dry-run" in sys.argv
    only_key = _arg("--key")

    gate_base, gate_cand = _arg("--gate-base"), _arg("--gate-cand")
    if gate_base and gate_cand:
        regs = _slo_gate(gate_base, gate_cand)
        if regs:
            for r in regs:
                print(json.dumps({"slo_gate": "regressed",
                                  **r.to_dict()}), flush=True)
            print(json.dumps({"error": "slo gate refused the "
                              "candidate artifact; not installing"}))
            return 3
        print(json.dumps({"slo_gate": "ok", "base": gate_base,
                          "cand": gate_cand}), flush=True)

    keys = [only_key] if only_key else \
        [k for k in db.keys() if not k.split("|")[1].startswith("paged.")]
    installed = 0
    for key in keys:
        prop = derive_ladder(db, key, chunk=chunk,
                             min_samples=min_samples,
                             amortize_s=amortize_s)
        if prop is None:
            print(json.dumps({"key": key, "skipped":
                              "insufficient cost model "
                              f"(need >= {min_samples} compile "
                              "samples and >= 1 exec sample)"}),
                  flush=True)
            continue
        if not prop["onchip"] and not allow_session:
            # the tunnel-backlog honesty gate: session-only costs may
            # not mint a "learned" ladder a cold boot silently trusts
            print(json.dumps({"key": key, "refused":
                              "builder-session-only samples; pass "
                              "--allow-session to install anyway",
                              "provenance": prop["provenance"],
                              "samples": prop["samples"]}),
                  flush=True)
            continue
        print(json.dumps({"key": key, "ladder": prop,
                          "installed": not dry}), flush=True)
        if not dry:
            db.record_ladder(key, prop)
            installed += 1

    blocks = derive_blocks(db, min_samples=min_samples)
    for bkey in sorted(blocks):
        entry = blocks[bkey]
        if not entry["onchip"] and not allow_session:
            print(json.dumps({"block": bkey, "refused":
                              "builder-session-only samples"}),
                  flush=True)
            continue
        print(json.dumps({"block": bkey, **entry,
                          "installed": not dry}), flush=True)
        if not dry:
            db.record_block(bkey, entry)
            installed += 1

    if installed and not dry:
        db.save()
        print(json.dumps({"wrote": os.path.abspath(db_path),
                          "installed": installed}), flush=True)
    return 0 if (installed or dry) else 1


if __name__ == "__main__":
    sys.exit(main())
