#!/usr/bin/env python
"""Benchmarks on the real TPU chip — one JSON line per metric.

Metrics (each with a DEFENSIBLE roofline as its vs_baseline):
  * stream_triad_gbs      — dispatch-level a+s*b (2 reads + 1 write per
                            element, buffers HBM-resident, output buffer
                            donated). Roof: 819 GB/s v5e HBM bandwidth.
  * 1d_stencil_unfused    — ONE heat step per dispatch (BASELINE config
                            #2's per-step shape): 8 bytes/cell-update.
                            Roof: HBM => 102.4 Gcells/s.
  * flash_attention_mfu   — pallas kernel, bf16 B2/S4096/N8/H128 causal.
                            Roof: 197 bf16 TFLOP/s (v5e MXU peak);
                            value = TFLOP/s, vs_baseline = MFU.
  * fft_1d_gflops         — 1-D complex64 FFT (2^22 pts) through
                            algo/fft's four-step program (the
                            distributed code path on a 1-chip mesh).
                            vs_baseline: HBM traffic model (~6 passes
                            of 8 B/pt) over measured time.
  * transformer_step_ms   — single-chip fwd+bwd+sgd on a 4-layer
                            d512/S1024 model; vs_baseline = achieved
                            model FLOP/s over MXU peak (MFU).
  * 1d_stencil_cell_updates (HEADLINE, printed last) — the fused
    1024-step in-VMEM path. Its honest roof is NOT the unfused HBM
    bound (it barely touches HBM): per-step work is ~3 VPU flops/cell,
    so the compute roof is vpu_flops/3. vs_baseline reports against
    that compute roof; the unfused-HBM ratio the round-1 bench used is
    reported alongside as `x_vs_unfused_hbm_roof` for continuity.

Timing: the axon tunnel adds a large fixed host<->device round trip and
block_until_ready does not reliably fence, so every number uses the
SLOPE method — time chains of K dependent dispatches ending in a scalar
materialization for two K values and divide the deltas. Chained inputs
evolve, so no dispatch can be deduplicated.

Robustness (round-4): the shared chip shows +-10-15% run-to-run drift
(interleaved A/B of identical kernels swings 0.60-0.81 of roof), so
every metric repeats its whole slope measurement SAMPLES times and
reports the MEDIAN, with `spread` = (max-min)/median alongside — a
metric whose spread rivals its delta hasn't moved.
"""

import functools
import json
import os
import sys
import time

import numpy as np

HBM_PEAK_GBS = 819.0      # TPU v5e HBM bandwidth
MXU_PEAK_BF16 = 197e12    # TPU v5e bf16 FLOP/s
# (the fused-stencil compute roof is MEASURED — see bench_vpu_rate —
# rather than derived from an unpublished VPU spec)


def slope_time(run_chain, k1: int, k2: int, repeats: int = 3):
    """Slope timing with min-of-N endpoints. The axon tunnel's fixed
    round-trip cost is ~60-80 ms and fluctuates by tens of ms, so the
    k2 chain must put well over 100 ms of real device work above the
    fixed cost — callers pick (k1, k2) so (k2-k1)*per_iter >> jitter."""
    run_chain(k1)                        # warm: pages, donation, caches
    t1 = min(run_chain(k1) for _ in range(repeats))
    t2 = min(run_chain(k2) for _ in range(repeats))
    return max(t2 - t1, 1e-9) / (k2 - k1)


SAMPLES = 3


def robust(per_fn, samples: int = 0):
    """Repeat a whole slope measurement; (median, (max-min)/median)."""
    samples = samples or SAMPLES
    ps = sorted(per_fn() for _ in range(samples))
    med = ps[samples // 2]
    return med, (ps[-1] - ps[0]) / med


_EMITTED = []          # every metric line of this run, for the fallback record
# anchored to the script dir, NOT cwd: the child writes with
# cwd=dirname(__file__), and a parent invoked from elsewhere must still
# find the record (an unreadable record here would recreate the exact
# evidence-free round this machinery exists to prevent).
# Two files: the committed SEED (curated, from BASELINE.md) and the
# gitignored LOCAL record each successful run rewrites — so bench runs
# never dirty the working tree, and reads prefer local over seed.
_FALLBACK_SEED = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "bench_fallback.json")
_FALLBACK_LOCAL = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "bench_fallback.local.json")


# canonical emission order (headline LAST — the driver parses the final
# stdout line as the headline metric)
_METRIC_ORDER = [
    "stream_triad_gbs", "copy_stream_elems",
    "1d_stencil_unfused_cell_updates", "flash_attention_tflops",
    "flash_attention_bwd_tflops", "transformer_step_ms", "fft_1d_gflops",
    "1d_stencil_cell_updates",
]


def emit(metric, value, unit, vs_baseline, **extra):
    line = {"metric": metric, "value": round(value, 3), "unit": unit,
            "vs_baseline": round(vs_baseline, 3)}
    line.update(extra)
    # EVERY live row carries provenance, not just fallbacks: a reader
    # (and the merge below) must be able to tell an on-chip
    # measurement from a builder-session re-emission without guessing
    # from which keys happen to be present
    line.setdefault("provenance", "on-chip")
    line.setdefault("onchip", True)
    _EMITTED.append(line)
    print(json.dumps(line), flush=True)
    # save after EVERY metric: on a tunnel that wedges mid-run (observed
    # r4/r5: answers one probe, runs ~one metric, hangs for 30+ min),
    # each partial run still banks its live wins — successive partial
    # runs ASSEMBLE a full fresh record metric by metric
    _save_fallback()


def _save_fallback() -> None:
    """Merge this run's results into the local record so a later run
    with a dead device tunnel can re-emit them labeled builder-session
    (the round-4 lesson: BENCH_r04.json was empty because the tunnel
    died and the bench had nothing to say — never be evidence-free
    again). Per-metric merge with per-line timestamps: the freshest
    measurement of each metric wins, whatever run it came from. Atomic
    write: a kill mid-dump must not clobber the previous good record."""
    import datetime
    now = datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")
    def _stamp(ln):
        # ISO timestamps compare lexicographically; "unknown" is oldest
        ts = ln.get("measured_at", "unknown")
        return "" if ts == "unknown" else ts

    def _onchip(ln):
        # explicit onchip flag wins; legacy lines with no provenance
        # stamp predate builder-session labeling and are on-chip
        if "onchip" in ln:
            return bool(ln["onchip"])
        return ln.get("provenance") != "builder-session"

    merged = {}
    for path in (_FALLBACK_SEED, _FALLBACK_LOCAL):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        for line in rec.get("lines", []):
            ln = dict(line)
            ln.setdefault("measured_at", rec.get("measured_at", "unknown"))
            prev = merged.get(ln.get("metric"))
            # a builder-session re-emission must NEVER displace an
            # on-chip measurement, whatever its timestamp says — the
            # BENCH_r05 silent-re-emission failure mode. Between rows
            # of equal provenance class, freshest wins regardless of
            # which file it came from (a re-curated seed must beat a
            # stale local record).
            if prev is not None and _onchip(prev) and not _onchip(ln):
                continue
            if prev is None or _stamp(ln) >= _stamp(prev) \
                    or (_onchip(ln) and not _onchip(prev)):
                merged[ln.get("metric")] = ln
    for line in _EMITTED:
        ln = dict(line)
        ln["measured_at"] = now
        merged[ln["metric"]] = ln
    # headline strictly LAST, unknown metric names before it — a future
    # emit not yet in _METRIC_ORDER must never land after the headline
    headline = _METRIC_ORDER[-1]
    order = [m for m in _METRIC_ORDER[:-1] if m in merged] + \
            [m for m in merged
             if m not in _METRIC_ORDER and m != headline] + \
            ([headline] if headline in merged else [])
    tmp = _FALLBACK_LOCAL + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump({"measured_at": now,
                       "lines": [merged[m] for m in order]}, f, indent=1)
        os.replace(tmp, _FALLBACK_LOCAL)
    except OSError:
        pass


def _load_fallback(skip=()):
    """Labeled fallback lines from the most recent record (local run
    record preferred, committed seed otherwise), minus `skip` metrics
    already measured live this run. Every re-emitted line is stamped
    ``"onchip": false`` — a fallback row is banked history, not a
    fresh on-device measurement, and downstream consumers must be able
    to tell without parsing provenance strings."""
    for path in (_FALLBACK_LOCAL, _FALLBACK_SEED):
        try:
            with open(path) as f:
                rec = json.load(f)
            break
        except (OSError, ValueError):
            continue
    else:
        return []
    out = []
    for line in rec.get("lines", []):
        if line.get("metric") in skip:
            continue
        fb = dict(line)
        fb["provenance"] = "builder-session"
        fb["onchip"] = False
        fb.setdefault("measured_at", rec.get("measured_at", "unknown"))
        out.append(fb)
    return out


def _emit_fallback(skip=(), lines=None) -> bool:
    if lines is None:
        lines = _load_fallback(skip)
    for line in lines:
        print(json.dumps(line), flush=True)
    return bool(lines)


def _fallback_age(lines=None):
    """How stale the builder-session medians being re-emitted are:
    oldest/newest per-line `measured_at` stamp plus the worst-case age
    in hours. A reader of a `bench_unavailable` record must be able to
    tell 2-hour-old numbers from 2-week-old ones without opening the
    fallback file. Lines with no usable stamp are skipped; an empty or
    stampless record reports unknown."""
    import datetime
    if lines is None:
        lines = _load_fallback()
    stamps = sorted(ln["measured_at"] for ln in lines
                    if ln.get("measured_at", "unknown") != "unknown")
    if not stamps:
        return {"fallback_measured_at": "unknown",
                "fallback_age_hours": -1}
    out = {"fallback_measured_at": stamps[0]}
    if stamps[-1] != stamps[0]:
        # assembled across runs: report the span, age from the oldest
        out["fallback_measured_at_newest"] = stamps[-1]
    try:
        oldest = datetime.datetime.fromisoformat(stamps[0])
        now = datetime.datetime.now(datetime.timezone.utc)
        if oldest.tzinfo is None:
            oldest = oldest.replace(tzinfo=datetime.timezone.utc)
        out["fallback_age_hours"] = round(
            (now - oldest).total_seconds() / 3600, 1)
    except ValueError:
        out["fallback_age_hours"] = -1
    return out


def bench_triad(jax, jnp):
    """Dispatch-level STREAM triad: b <- x + s*b, output donated."""
    m = 1 << 24

    @functools.partial(jax.jit, donate_argnums=(1,))
    def f(a, b):
        return a + jnp.float32(1e-7) * b

    x = jnp.asarray(np.random.default_rng(1).random(m, np.float32))
    b = jnp.asarray(np.random.default_rng(2).random(m, np.float32))
    b = f(x, b)
    _ = float(b[0])

    state = [b]

    def chain(k):
        bb = state[0]
        t0 = time.perf_counter()
        for _ in range(k):
            bb = f(x, bb)
        _ = float(bb[0])
        state[0] = bb
        return time.perf_counter() - t0

    per, spread = robust(lambda: slope_time(chain, 64, 640, repeats=5))
    gbs = 3 * m * 4 / per / 1e9
    emit("stream_triad_gbs", gbs, "GB/s", gbs / HBM_PEAK_GBS,
         spread=round(spread, 3))
    return gbs


def bench_stencil_unfused(jax, jnp, heat_step_best, copy_rate=None):
    """One heat step per dispatch: the HBM-bound per-step number (the
    blocked pallas kernel — ops/stencil.pallas_heat_step — which
    streams 8 B/cell where XLA's roll lowering moves ~4x that).
    `copy_rate` (elems/s of bench_copy_stream) adds the same-session
    normalized copy_ratio."""
    n = 1 << 24
    coef = jnp.float32(0.25)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(u):
        return heat_step_best(u, coef)

    u = jnp.asarray(np.random.default_rng(0).random(n, np.float32))
    u = step(u)
    _ = float(u[0])
    state = [u]

    def chain(k):
        uu = state[0]
        t0 = time.perf_counter()
        for _ in range(k):
            uu = step(uu)
        _ = float(uu[0])
        state[0] = uu
        return time.perf_counter() - t0

    per, spread = robust(lambda: slope_time(chain, 64, 640, repeats=5))
    cells = n / per
    roof = HBM_PEAK_GBS * 1e9 / 8.0          # read 4B + write 4B per cell
    extra = {}
    if copy_rate:
        # ratio vs the same-session copy stream: the drift-immune bar
        # (VERDICT r4 item 3 — done when >= 0.9 of copy OR >= 0.75 roof)
        extra["copy_ratio"] = round(cells / copy_rate, 3)
    emit("1d_stencil_unfused_cell_updates", cells / 1e6, "Mcells/s",
         cells / roof, spread=round(spread, 3), **extra)
    return cells


def bench_vpu_rate(jax, jnp):
    """Empirical VPU elementwise-op rate: an in-VMEM FMA chain with the
    same shape/loop structure as the fused stencil kernel but ONE vector
    op per element per iteration. This measured rate is the compute roof
    the fused stencil is judged against."""
    import functools as ft

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = 1 << 17        # whole array + 8 temporaries must fit scoped VMEM
    steps = 1024

    def kernel(u_ref, c_ref, o_ref):
        c = c_ref[0]

        def one(_i, u):
            # 8 independent FMAs + a 7-add reduction tree: enough ILP
            # that the VPU pipelines stay full (a single serial FMA
            # chain measures instruction LATENCY, not throughput).
            # Coefficients differ by ~1e-9 so nothing CSEs, while the
            # iteration map stays u' ~ 0.9999*u + 1 (bounded).
            ys = [u * (c + j * 1e-9) + (c + j * 1e-9) for j in range(8)]
            s1 = (ys[0] + ys[1]) + (ys[2] + ys[3])
            s2 = (ys[4] + ys[5]) + (ys[6] + ys[7])
            return (s1 + s2) * jnp.float32(0.125 * 0.9999)
        o_ref[:] = jax.lax.fori_loop(0, steps, one, u_ref[:])

    @jax.jit
    def run(u):
        u2 = u.reshape(n // 128, 128)
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(u2.shape, u2.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                      pl.BlockSpec(memory_space=pltpu.SMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        )(u2, jnp.asarray([0.9999999], jnp.float32))
        return out.reshape(n)

    u0 = jnp.asarray(np.random.default_rng(0).random(n, np.float32))
    u0 = run(u0)
    _ = float(u0[0])

    def chain(k):
        u = u0
        t0 = time.perf_counter()
        for _ in range(k):
            u = run(u)
        _ = float(u[0])
        return time.perf_counter() - t0

    per, _ = robust(lambda: slope_time(chain, 8, 72))
    return n * steps * 16 / per          # vector ops / s (8 FMA + 7 add
                                         # + 1 scale per element-iter)


# vector ops per cell-update in the fused pallas stencil kernel
# (ops/stencil._pallas_kernel): 2 lane rolls + 2 masked selects + 5
# arithmetic ops (mul, sub, add, mul, add)
_STENCIL_OPS_PER_CELL = 9.0


def bench_stencil_fused(jax, jnp, multistep):
    n = 1 << 19               # 512K cells: pallas in-VMEM path
    spd = 1024
    coef = jnp.float32(0.25)
    u0 = jnp.asarray(np.random.default_rng(0).random(n, np.float32))
    u0 = multistep(u0, coef, spd)
    _ = float(u0[0])

    def chain(k):
        u = u0
        t0 = time.perf_counter()
        for _ in range(k):
            u = multistep(u, coef, spd)
        _ = float(u[0])
        return time.perf_counter() - t0

    per, spread = robust(lambda: slope_time(chain, 8, 72))
    cells_per_s = n * spd / per
    hbm_roof = HBM_PEAK_GBS * 1e9 / 8.0
    return cells_per_s, hbm_roof, spread


def bench_attention(jax, jnp):
    from hpx_tpu.ops.attention_pallas import flash_attention
    B, S, N, H = 2, 4096, 8, 128
    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.standard_normal((B, S, N, H), np.float32), jnp.bfloat16)
    q, k, v = mk(), mk(), mk()
    f = jax.jit(functools.partial(flash_attention, causal=True))
    out = f(q, k, v)
    jax.block_until_ready(out)

    def chain(kk):
        qq = q
        t0 = time.perf_counter()
        for _ in range(kk):
            qq = f(qq, k, v)
        _ = float(qq[0, 0, 0, 0])
        return time.perf_counter() - t0

    per, spread = robust(lambda: slope_time(chain, 8, 48))
    flops = 4 * B * N * S * S * H * 0.5          # causal halves the work
    tf = flops / per / 1e12
    from hpx_tpu.ops.attention_pallas import resolve_blocks
    bq, bk = resolve_blocks(S, S, True)
    emit("flash_attention_tflops", tf, "TFLOP/s", tf * 1e12 / MXU_PEAK_BF16,
         shape=f"B{B} S{S} N{N} H{H} bf16 causal", spread=round(spread, 3),
         blocks=f"{bq}x{bk}")
    return tf


def bench_attention_bwd(jax, jnp):
    """Backward flash kernels (custom_vjp): time grad of sum(flash)
    w.r.t. (q, k, v). FLOP model: fwd 2 matmuls + bwd 5 matmuls per
    tile pair => total 3.5x the forward's 2; causal halves everything.
    Reported TFLOP/s covers the whole fwd+bwd step, which is what
    training sees; vs_baseline = that rate over MXU peak."""
    from hpx_tpu.ops.attention_pallas import flash_attention
    B, S, N, H = 2, 4096, 8, 128
    rng = np.random.default_rng(1)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.standard_normal((B, S, N, H), np.float32), jnp.bfloat16)
    q, k, v = mk(), mk(), mk()

    def loss(q, k, v):
        return flash_attention(q, k, v, causal=True).astype(
            jnp.float32).sum()

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    dq, dk, dv = g(q, k, v)
    jax.block_until_ready((dq, dk, dv))

    def chain(kk):
        qq = q
        t0 = time.perf_counter()
        for _ in range(kk):
            dq, _dk, _dv = g(qq, k, v)
            qq = dq.astype(jnp.bfloat16)        # chain dependency
        _ = float(qq[0, 0, 0, 0])
        return time.perf_counter() - t0

    per, spread = robust(lambda: slope_time(chain, 4, 24))
    flops = 3.5 * 4 * B * N * S * S * H * 0.5
    tf = flops / per / 1e12
    emit("flash_attention_bwd_tflops", tf, "TFLOP/s",
         tf * 1e12 / MXU_PEAK_BF16,
         shape=f"B{B} S{S} N{N} H{H} bf16 causal fwd+bwd",
         spread=round(spread, 3))
    return tf


def bench_copy_stream(jax, jnp):
    """Pure HBM copy stream (read 4B + write 4B per element — the same
    traffic shape as one unfused stencil step). Its measured rate is the
    SAME-SESSION normalizer for the stencil: chip-to-chip drift hits
    both equally, so stencil/copy_ratio stays meaningful when absolute
    numbers swing +-15% (BASELINE.md round-4 note)."""
    n = 1 << 24

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(u):
        # *c with c != 1: a real read->write pass XLA cannot alias away
        return u * jnp.float32(1.0000001)

    u = jnp.asarray(np.random.default_rng(3).random(n, np.float32))
    u = step(u)
    _ = float(u[0])
    state = [u]

    def chain(k):
        uu = state[0]
        t0 = time.perf_counter()
        for _ in range(k):
            uu = step(uu)
        _ = float(uu[0])
        state[0] = uu
        return time.perf_counter() - t0

    per, spread = robust(lambda: slope_time(chain, 64, 640, repeats=5))
    elems = n / per
    roof = HBM_PEAK_GBS * 1e9 / 8.0
    emit("copy_stream_elems", elems / 1e6, "Melem/s", elems / roof,
         spread=round(spread, 3))
    return elems


def bench_transformer(jax, jnp):
    from hpx_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab=32768, d_model=512, n_heads=8,
                                head_dim=64, n_layers=4, d_ff=2048,
                                lr=0.01, dtype=jnp.bfloat16)
    mesh1 = tfm.make_mesh_3d(1)
    params = tfm.shard_params(tfm.init_params(cfg, jax.random.PRNGKey(0)),
                              cfg, mesh1)
    step = tfm.make_train_step(cfg, mesh1)
    B, S = 8, 1024
    toks, tgts = tfm.sample_batch(cfg, batch=B, seq=S,
                                  key=jax.random.PRNGKey(1))
    toks, tgts = tfm.shard_batch(toks, tgts, mesh1)
    params, l0 = step(params, toks, tgts)
    _ = float(l0)

    state = [params]

    def chain(k):
        p = state[0]
        t0 = time.perf_counter()
        loss = None
        for _ in range(k):
            p, loss = step(p, toks, tgts)
        _ = float(loss)
        state[0] = p
        return time.perf_counter() - t0

    per, spread = robust(lambda: slope_time(chain, 2, 10))
    # model flops: 6 * params * tokens (fwd+bwd) + attention term
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
    attn_flops = 4 * B * cfg.n_heads * S * S * cfg.head_dim * \
        cfg.n_layers * 3 * 0.5            # qk^T+pv, fwd+2bwd, causal
    flops = 6 * n_params * B * S + attn_flops
    mfu = flops / per / MXU_PEAK_BF16
    emit("transformer_step_ms", per * 1e3, "ms", mfu,
         shape=f"L{cfg.n_layers} d{cfg.d_model} B{B} S{S} bf16",
         params=n_params, spread=round(spread, 3))
    return per


def _probe_device_once(timeout_s: float = 120.0) -> bool:
    """Check the accelerator answers at all — in a THROWAWAY subprocess,
    because a wedged device tunnel hangs jax.devices() forever inside
    whatever process asks (observed: the axon tunnel went down for hours
    mid-session). Failing fast with a message beats a silent hang."""
    import subprocess
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax, sys; sys.stdout.write(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s)
        return proc.returncode == 0 and bool(proc.stdout.strip())
    except Exception:
        return False


def _probe_device(total_budget_s: float = None) -> bool:
    """Retry the bounded probe with backoff for up to ~20 min: the axon
    tunnel has been observed to wedge for a while and come back, and one
    impatient probe cost round 4 its entire perf record. Each attempt is
    itself timeout-bounded, so a dead tunnel costs the budget, not
    forever. Budget overridable via HPX_BENCH_PROBE_BUDGET seconds."""
    if total_budget_s is None:
        total_budget_s = float(os.environ.get(
            "HPX_BENCH_PROBE_BUDGET", "1200"))
    deadline = time.monotonic() + total_budget_s
    sleep = 15.0
    attempt = 1
    while True:
        left = deadline - time.monotonic()
        if left <= 0:
            return False
        if _probe_device_once(timeout_s=min(120.0, max(left, 10.0))):
            return True
        left = deadline - time.monotonic()
        if left <= 1.0:
            return False
        print(f"# device probe attempt {attempt} failed; retrying in "
              f"{min(sleep, left):.0f}s ({left:.0f}s of budget left)",
              file=sys.stderr, flush=True)
        time.sleep(min(sleep, left))
        sleep = min(sleep * 2, 240.0)
        attempt += 1


def bench_fft(jax, jnp):
    """Single-chip 1-D FFT through algo/fft's four-step program (the
    degenerate 1-device mesh exercises the same code path the
    distributed transform compiles). FLOP model: 5*n*log2(n). The
    vs_baseline roof is an HBM traffic model — the transform is
    bandwidth-bound at this size: ~3 read+write passes of 8 B/point
    (stage FFTs + twiddle fold; the on-device transpose copies are
    layout changes XLA mostly fuses)."""
    import math as _m

    from jax.sharding import Mesh
    from hpx_tpu.algo import fft as dfft

    n = 1 << 22                     # 32 MiB complex64
    mesh = Mesh(np.array(jax.devices()[:1]), ("x",))
    rng = np.random.default_rng(0)
    v = jnp.asarray((rng.standard_normal(n) + 1j * rng.standard_normal(n)
                     ).astype(np.complex64))

    norm = jax.jit(lambda x: jnp.float32(
        jnp.sum(jnp.abs(x).astype(jnp.float32))))
    y = dfft.fft_sharded(v, mesh)
    _ = float(norm(y))
    state = [y]

    def chain(k):
        x = state[0]
        t0 = time.perf_counter()
        for _ in range(k):
            # alternate directions so chained dispatches stay dependent
            # without the values blowing up
            x = dfft.ifft_sharded(dfft.fft_sharded(x, mesh), mesh)
        _ = float(norm(x))
        state[0] = x
        return time.perf_counter() - t0

    per2, spread = robust(lambda: slope_time(chain, 8, 40))
    per = per2 / 2.0                 # one transform
    gflops = 5 * n * _m.log2(n) / per / 1e9
    roof_time = 6 * n * 8 / (HBM_PEAK_GBS * 1e9)
    emit("fft_1d_gflops", gflops, "GFLOP/s", roof_time / per,
         n=n, spread=round(spread, 3))
    return gflops


def _bench_main() -> None:
    """The actual measurements (runs in a bounded child process)."""
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, ".")
    from hpx_tpu.ops.stencil import heat_step_best, multistep

    # --trace-out travels from the parent as an env var (the child is
    # spawned without argv): run everything under the causal tracer and
    # write Chrome trace JSON next to the bench result at the end.
    tracer = None
    trace_out = os.environ.get(_TRACE_ENV)
    if trace_out:
        from hpx_tpu.core.config import runtime_config
        from hpx_tpu.svc import tracing
        runtime_config().set("hpx.trace.enabled", "1")
        tracer = tracing.start_if_configured()

    dev = jax.devices()[0]
    print(f"# device: {dev} platform={dev.platform}", file=sys.stderr)

    # HPX_BENCH_ONLY=m1,m2 measures just those metrics — the tool for a
    # flaky tunnel: one metric per invocation, banked incrementally into
    # the local record (see emit), assembles a full fresh set over time
    only = {m.strip() for m in
            os.environ.get("HPX_BENCH_ONLY", "").split(",") if m.strip()}

    def want(name):
        return not only or name in only

    if want("stream_triad_gbs"):
        bench_triad(jax, jnp)
    copy_rate = None
    if want("copy_stream_elems") or \
            want("1d_stencil_unfused_cell_updates"):
        # the copy stream is the unfused stencil's same-session
        # normalizer, so it rides along with it
        copy_rate = bench_copy_stream(jax, jnp)
    if want("1d_stencil_unfused_cell_updates"):
        bench_stencil_unfused(jax, jnp, heat_step_best,
                              copy_rate=copy_rate)
    if want("flash_attention_tflops"):
        bench_attention(jax, jnp)
    if want("flash_attention_bwd_tflops"):
        bench_attention_bwd(jax, jnp)
    if want("transformer_step_ms"):
        bench_transformer(jax, jnp)
    if want("fft_1d_gflops"):
        bench_fft(jax, jnp)

    if want("1d_stencil_cell_updates"):
        vpu_rate = bench_vpu_rate(jax, jnp)
        cells_per_s, hbm_roof, spread = bench_stencil_fused(jax, jnp,
                                                            multistep)
        # headline LAST so a last-line JSON parser picks it up. The
        # honest roof for the VMEM-resident kernel is COMPUTE: the
        # empirically measured VPU op rate divided by the kernel's 9
        # vector ops per cell-update. The unfused-HBM ratio is kept for
        # round-1 continuity.
        emit("1d_stencil_cell_updates", cells_per_s / 1e6, "Mcells/s",
             cells_per_s * _STENCIL_OPS_PER_CELL / vpu_rate,
             x_vs_unfused_hbm_roof=round(cells_per_s / hbm_roof, 3),
             vpu_rate_gops=round(vpu_rate / 1e9, 1),
             spread=round(spread, 3))
    _save_fallback()

    if tracer is not None:
        from hpx_tpu.svc import tracing
        tracing.stop_tracing()
        doc = tracer.export(trace_out)
        print(f"# trace written: {trace_out} "
              f"({len(doc['traceEvents'])} events, "
              f"{doc['otherData']['dropped_events']} dropped)",
              file=sys.stderr)

    # --metrics-out rides the same env channel as --trace-out: dump
    # the registered-counter plane (histograms as mergeable snapshots)
    # as a hpx_tpu.metrics.v1 artifact at the end of the child run.
    metrics_out = os.environ.get(_METRICS_ENV)
    if metrics_out:
        from hpx_tpu.svc import metrics as svc_metrics
        reg = svc_metrics.registry_snapshot("*")
        doc = {"schema": "hpx_tpu.metrics.v1",
               "histograms": {n: {"snapshot": s}
                              for n, s in reg["histograms"].items()},
               "counters": reg["counters"]}
        tmp = f"{metrics_out}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, metrics_out)
        print(f"# metrics written: {metrics_out} "
              f"({len(doc['counters'])} counters, "
              f"{len(doc['histograms'])} histograms)",
              file=sys.stderr)


_CHILD_ENV = "_HPX_BENCH_CHILD"
_TRACE_ENV = "_HPX_BENCH_TRACE_OUT"
_METRICS_ENV = "_HPX_BENCH_METRICS_OUT"


def _run_slo_gate(baseline: str) -> None:
    """--baseline: gate this round's --metrics-out artifact against a
    previous round's with benchmarks/slo_gate.py (bounded-error
    quantile comparison). Verdicts go to stderr — stdout stays a pure
    metric stream with the headline last — and a regression exits 1."""
    cand = os.environ.get(_METRICS_ENV)
    if not cand or not os.path.exists(cand):
        print("# --baseline given but no --metrics-out artifact to "
              "gate; skipped", file=sys.stderr)
        return
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    import slo_gate
    try:
        verdicts = slo_gate.compare(slo_gate.load_artifact(baseline),
                                    slo_gate.load_artifact(cand))
    except (OSError, ValueError) as e:
        print(f"# slo gate unreadable input: {e}", file=sys.stderr)
        return
    print(slo_gate.render_text(verdicts), file=sys.stderr)
    if slo_gate.regressions(verdicts):
        sys.exit(1)


def main() -> None:
    # parsed in the PARENT and forwarded via env — the bounded child is
    # spawned without argv
    if "--trace-out" in sys.argv:
        os.environ[_TRACE_ENV] = os.path.abspath(
            sys.argv[sys.argv.index("--trace-out") + 1])
    if "--metrics-out" in sys.argv:
        os.environ[_METRICS_ENV] = os.path.abspath(
            sys.argv[sys.argv.index("--metrics-out") + 1])
    baseline = os.path.abspath(
        sys.argv[sys.argv.index("--baseline") + 1]) \
        if "--baseline" in sys.argv else None
    if os.environ.get(_CHILD_ENV) == "1":
        return _bench_main()

    only = {m.strip() for m in
            os.environ.get("HPX_BENCH_ONLY", "").split(",") if m.strip()}
    unknown = only - set(_METRIC_ORDER)
    if unknown:
        # fail the typo loudly BEFORE probing: a silent no-op child
        # would be mislabeled as a tunnel death by the gap-fill path
        print(json.dumps({
            "metric": "bench_usage_error", "value": 0, "unit": "none",
            "vs_baseline": 0,
            "error": f"HPX_BENCH_ONLY names unknown metrics "
                     f"{sorted(unknown)}; known: {_METRIC_ORDER}"}),
            flush=True)
        sys.exit(2)

    if not _probe_device():
        fb_lines = _load_fallback()
        rec = {
            "metric": "bench_unavailable", "value": 0, "unit": "none",
            "vs_baseline": 0,
            "error": "device tunnel unresponsive (jax.devices() probe "
                     "retried with backoff for ~20 min in bounded "
                     "subprocesses); re-emitting most recent "
                     "builder-session medians below"}
        # stamp how stale the re-emitted medians are, so the record
        # carries its own trust signal
        rec.update(_fallback_age(fb_lines))
        print(json.dumps(rec), flush=True)
        if _emit_fallback(lines=fb_lines):
            sys.exit(0)        # labeled fallback data is still data
        sys.exit(1)

    # The tunnel answers — but it can die MID-bench (observed r4, hours
    # of outage starting mid-session), and a hung jax call never raises.
    # So the measurements run in a bounded child whose stdout is
    # STREAMED through (each metric line appears as it is measured, and
    # survives even if this parent is later killed); on child death the
    # parent re-emits builder-session numbers for whatever metrics the
    # child didn't reach.
    import select
    import subprocess
    env = dict(os.environ)
    env[_CHILD_ENV] = "1"
    deadline = time.monotonic() + float(
        os.environ.get("HPX_BENCH_CHILD_TIMEOUT", "2700"))
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)], env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)) or ".",
        stdout=subprocess.PIPE, stderr=sys.stderr)
    done = set()
    buf = b""
    timed_out = False
    live_headline = []      # the headline line, if the child emitted it

    def _flush_lines(data: bytes):
        for raw in data.split(b"\n"):
            if not raw:
                continue
            line = raw.decode(errors="replace")
            print(line, flush=True)
            try:
                metric = json.loads(line)["metric"]
            except (ValueError, KeyError, TypeError):
                continue
            done.add(metric)
            if metric == _METRIC_ORDER[-1]:
                live_headline[:] = [line]

    while True:
        left = deadline - time.monotonic()
        if left <= 0:
            timed_out = True
            break
        ready, _, _ = select.select([proc.stdout], [], [], min(left, 5.0))
        if not ready:
            continue
        chunk = proc.stdout.read1(65536)
        if not chunk:
            break                                  # EOF: child exited
        buf += chunk
        while b"\n" in buf:
            raw, buf = buf.split(b"\n", 1)
            _flush_lines(raw)

    if timed_out:
        proc.kill()
        # drain whatever the child managed to emit before the kill —
        # losing live-measured lines and replacing them with stale
        # fallback values would mislabel fresh data as old
        try:
            buf += proc.stdout.read() or b""
        except OSError:
            pass
    rc = proc.wait()
    if timed_out:
        rc = -1
    _flush_lines(buf)
    if rc == 0 and done:
        if only:
            # a successful PARTIAL run (HPX_BENCH_ONLY) still owes the
            # driver a complete, headline-LAST record: fill what was
            # filtered out from the banked fallback (live lines from
            # this run were already merged into it by the child)
            filled = False
            for line in _load_fallback(skip=done):
                print(json.dumps(line), flush=True)
                filled = True
            if filled and live_headline:
                # the child measured the headline live, but the gap
                # lines just pushed it off the last stdout line (the one
                # the driver parses) — re-emit it so fresh data wins
                print(live_headline[0], flush=True)
        if baseline:
            _run_slo_gate(baseline)
        return
    # child died or hung mid-run: fill the gaps from the last good run,
    # keeping the original emission order (headline last). The marker
    # line goes FIRST and only when fallback lines follow — the driver
    # parses the LAST stdout line as the headline metric, which must
    # never be the marker itself.
    gaps = _load_fallback(skip=done)
    note = (f"bench child exited rc={rc} mid-run (tunnel death "
            "mid-bench); missing metrics re-emitted from the most "
            "recent builder-session record below")
    if gaps:
        print(json.dumps({
            "metric": "bench_interrupted", "value": len(done),
            "unit": "metrics_measured", "vs_baseline": 0,
            "error": note}), flush=True)
        for line in gaps:
            print(json.dumps(line), flush=True)
        if live_headline:
            # headline was measured live before the child died; the gap
            # lines displaced it from the last stdout line — re-emit the
            # live measurement so the driver parses it, not a stale one
            print(live_headline[0], flush=True)
    elif done:
        # everything was measured live before the child died (e.g. it
        # was killed during its own bookkeeping): stdout already ends
        # with the headline metric; keep it that way.
        print(f"# {note}; all metrics were measured live", file=sys.stderr)
    else:
        print(json.dumps({
            "metric": "bench_unavailable", "value": 0, "unit": "none",
            "vs_baseline": 0, "error": note + "; no fallback record"}),
            flush=True)
        sys.exit(1)


if __name__ == "__main__":
    main()
