#!/usr/bin/env python
"""Flagship benchmark: 1d_stencil cell-updates/s on the real TPU chip.

BASELINE config #2 (examples/1d_stencil/1d_stencil_4.cpp analog). The
fused path (ops/stencil.multistep: 1024 steps per dispatch, pallas in-VMEM
where it fits) is the production configuration; STREAM-triad GB/s is
reported to stderr for context.

Timing methodology: the axon TPU tunnel adds a large fixed host<->device
round-trip to any value materialization, and block_until_ready does not
reliably fence. All measurements therefore use the SLOPE method — time a
chain of K dispatches ending in a scalar materialization for two values
of K and divide the work delta by the time delta. Inputs evolve across
iterations (chained state) so no dispatch can be deduplicated.

Prints ONE JSON line to stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline: measured cells/s over the HBM-bandwidth roof for an unfused
heat step (8 bytes/cell-update at v5e's ~819 GB/s => ~102.4 Gcells/s).
The reference publishes no numbers (BASELINE.md), so the hardware roof is
the honest denominator; 1.0 means the fused/pallas path delivers what a
perfectly HBM-bound implementation could at best.
"""

import json
import sys
import time

import numpy as np

HBM_PEAK_GBS = 819.0  # TPU v5e


def slope_time(run_chain, k1: int, k2: int, repeats: int = 3):
    """Time chains of k1 and k2 iterations (each ending in a host fence);
    return seconds per iteration from the slope. Min-of-N per point damps
    the tunnel's fixed-latency jitter, which is larger than a single
    dispatch."""
    t1 = min(run_chain(k1) for _ in range(repeats))
    t2 = min(run_chain(k2) for _ in range(repeats))
    return max(t2 - t1, 1e-9) / (k2 - k1)


def main() -> None:
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, ".")
    from hpx_tpu.models.stencil1d import StencilParams, print_time_results
    from hpx_tpu.ops.stencil import multistep

    dev = jax.devices()[0]
    print(f"# device: {dev} platform={dev.platform}", file=sys.stderr)

    # -- fused stencil (the headline number) --------------------------------
    n = 1 << 19              # 512K cells: pallas in-VMEM path
    spd = 1024               # steps per dispatch
    coef = jnp.float32(0.25)
    u0 = jnp.asarray(np.random.default_rng(0).random(n, np.float32))
    u0 = multistep(u0, coef, spd)          # warm: compile
    _ = float(u0[0])

    def stencil_chain(k: int) -> float:
        u = u0
        t0 = time.perf_counter()
        for _ in range(k):
            u = multistep(u, coef, spd)
        _ = float(u[0])                    # host fence
        return time.perf_counter() - t0

    per_dispatch = slope_time(stencil_chain, 8, 72)
    cells_per_s = n * spd / per_dispatch
    p = StencilParams(nx=n, np_=1, nt=spd)
    print_time_results("fused(tpu)", per_dispatch, p, file=sys.stderr)

    # -- STREAM triad (context, stderr) -------------------------------------
    m = 1 << 24
    x = jnp.asarray(np.random.default_rng(1).random(m, np.float32))
    y = jnp.asarray(np.random.default_rng(2).random(m, np.float32))
    import functools

    @functools.partial(jax.jit, static_argnames=("iters",))
    def triad_fused(a, b, s, iters):
        # pair-swap recurrence: each iteration is a genuine triad
        # (read 2 arrays, write 1) that XLA cannot strength-reduce the
        # way it collapses `z += s*y` repeated
        def body(_i, ab):
            a_, b_ = ab
            return b_, a_ + s * b_
        return jax.lax.fori_loop(0, iters, body, (a, b))

    TRIADS = 32
    z0 = triad_fused(x, y, jnp.float32(1e-7), TRIADS)
    _ = float(z0[1][0])

    def triad_chain(k: int) -> float:
        z = z0
        t0 = time.perf_counter()
        for _ in range(k):
            z = triad_fused(z[0], z[1], jnp.float32(1e-7), TRIADS)
        _ = float(z[1][0])
        return time.perf_counter() - t0

    per_triad = slope_time(triad_chain, 4, 36) / TRIADS
    triad_gbs = 3 * m * 4 / per_triad / 1e9
    print(f"# STREAM-triad: {triad_gbs:.0f} GB/s "
          f"({triad_gbs / HBM_PEAK_GBS:.0%} of HBM peak)", file=sys.stderr)

    bound_cells = HBM_PEAK_GBS * 1e9 / 8.0
    print(json.dumps({
        "metric": "1d_stencil_cell_updates",
        "value": round(cells_per_s / 1e6, 1),
        "unit": "Mcells/s",
        "vs_baseline": round(cells_per_s / bound_cells, 3),
    }))


if __name__ == "__main__":
    main()
