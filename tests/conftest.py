"""Test fixture: run the whole suite on a virtual 8-device CPU mesh.

Mirrors the reference's test strategy (SURVEY.md §4): HPX tests multi-locality
behavior with real processes on localhost; we test multi-chip behavior with
XLA's host-platform virtual devices. Benchmarks (bench.py) use the real TPU;
tests use CPU so they run anywhere and exercise the same sharding code.

Env vars MUST be set before jax is imported anywhere.
"""

import os

# force, don't setdefault: the sandbox pre-sets JAX_PLATFORMS=axon (TPU)
os.environ["JAX_PLATFORMS"] = "cpu"
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (
        prev + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402
import pytest  # noqa: E402

# The sandbox's sitecustomize forces jax_platforms to "axon,cpu" (TPU
# first) regardless of the env var; override it before any device query.
jax.config.update("jax_platforms", "cpu")


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def mesh1d(devices):
    from jax.sharding import Mesh
    import numpy as np
    return Mesh(np.array(devices), ("x",))


@pytest.fixture(scope="session")
def mesh2d(devices):
    from jax.sharding import Mesh
    import numpy as np
    return Mesh(np.array(devices).reshape(4, 2), ("x", "y"))


@pytest.fixture(autouse=True)
def _reset_test_counters():
    from hpx_tpu import testing
    testing.reset_errors()
    yield
    assert testing.report_errors() == 0, "HPX_TEST failures recorded"
