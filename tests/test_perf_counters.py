"""M9a tests: performance counters (SURVEY.md §2.5/§5.1)."""

import io
import os
import time

import pytest
import sys

import hpx_tpu as hpx
from hpx_tpu.svc import performance_counters as pc
from hpx_tpu.testing import HPX_TEST, HPX_TEST_EQ

REPO = os.path.join(os.path.dirname(__file__), "..")


class TestNaming:
    def test_parse_roundtrip(self):
        n = "/threads{locality#0/pool#default}/count/cumulative"
        p = pc.parse_counter_name(n)
        HPX_TEST_EQ(p.object, "threads")
        HPX_TEST_EQ(p.locality, "0")
        HPX_TEST_EQ(p.instance, "pool#default")
        HPX_TEST_EQ(p.counter, "count/cumulative")
        HPX_TEST_EQ(p.format(), n)

    def test_parse_wildcard_locality(self):
        p = pc.parse_counter_name("/x{locality#*/total}/y")
        HPX_TEST_EQ(p.locality, "*")

    def test_malformed_raises(self):
        for bad in ["threads/count", "/t{locality0/i}/c", "/t{}/c", ""]:
            with pytest.raises(hpx.HpxError):
                pc.parse_counter_name(bad)

    def test_counter_name_helper(self):
        HPX_TEST_EQ(pc.counter_name("parcels", "count/sent", locality=3),
                    "/parcels{locality#3/total}/count/sent")


class TestCounterKinds:
    def test_gauge(self):
        c = pc.GaugeCounter()
        c.add(5); c.add(2.5)
        HPX_TEST_EQ(c.get_value().value, 7.5)
        HPX_TEST_EQ(c.get_value(reset=True).value, 7.5)
        HPX_TEST_EQ(c.get_value().value, 0.0)

    def test_callback_with_software_reset(self):
        box = [10.0]
        c = pc.CallbackCounter(lambda: box[0])
        HPX_TEST_EQ(c.get_value(reset=True).value, 10.0)
        box[0] = 25.0
        HPX_TEST_EQ(c.get_value().value, 15.0)  # delta since reset

    def test_elapsed(self):
        c = pc.ElapsedTimeCounter()
        time.sleep(0.02)
        HPX_TEST(c.get_value().value >= 0.02)
        HPX_TEST(c.get_value(reset=True).value >= 0.02)
        HPX_TEST(c.get_value().value < 0.02)

    def test_average(self):
        c = pc.AverageCounter()
        for v in (1.0, 2.0, 3.0):
            c.sample(v)
        cv = c.get_value()
        HPX_TEST_EQ(cv.value, 2.0)
        HPX_TEST_EQ(cv.count, 3)


class TestRegistry:
    def test_register_discover_query(self):
        name = "/myobj{locality#0/total}/widgets"
        g = pc.register_counter(name, pc.GaugeCounter())
        try:
            g.add(42)
            HPX_TEST(name in pc.discover_counters("/myobj{*"))
            HPX_TEST_EQ(pc.query_counter(name).value, 42.0)
        finally:
            pc.unregister_counter(name)
        with pytest.raises(hpx.HpxError):
            pc.query_counter(name)

    def test_builtin_counters_exist(self):
        names = pc.discover_counters()
        for want in ("/threads{locality#0/pool#default}/count/cumulative",
                     "/threads{locality#0/pool#default}/count/stolen",
                     "/runtime{locality#0/total}/uptime",
                     "/tpu{locality#0/executor}/count/dispatches",
                     "/tpu{locality#0/executor}/count/compilations"):
            HPX_TEST(want in names, want)

    def test_thread_counter_advances_with_work(self):
        name = "/threads{locality#0/pool#default}/count/cumulative"
        before = pc.query_counter(name).value
        hpx.wait_all([hpx.async_(lambda: None) for _ in range(20)])
        # `executed` increments AFTER each task body, and wait_all
        # returns from inside the last body — poll briefly instead of
        # racing the counter (flaked under CPU contention)
        import time
        for _ in range(500):
            if pc.query_counter(name).value >= before + 20:
                break
            time.sleep(0.01)
        HPX_TEST(pc.query_counter(name).value >= before + 20)

    def test_dispatch_counter_advances(self):
        import jax.numpy as jnp
        name = "/tpu{locality#0/executor}/count/dispatches"
        before = pc.query_counter(name).value
        hpx.TpuExecutor().async_execute(lambda x: x + 1, jnp.float32(1)).get()
        HPX_TEST(pc.query_counter(name).value >= before + 1)

    def test_uptime_monotonic(self):
        name = "/runtime{locality#0/total}/uptime"
        a = pc.query_counter(name).value
        time.sleep(0.01)
        HPX_TEST(pc.query_counter(name).value > a)


class TestPrinting:
    def test_print_counters_format(self):
        buf = io.StringIO()
        pc.print_counters("/runtime{*", file=buf)
        lines = buf.getvalue().strip().splitlines()
        HPX_TEST_EQ(len(lines[0].split(",")), 4)
        # /runtime carries uptime, the process memory counters, and the
        # dropped-observer-callbacks diagnostic
        names = [ln.split(",")[0] for ln in lines]
        HPX_TEST("/runtime{locality#0/total}/memory/resident" in names)
        HPX_TEST("/runtime{locality#0/total}/uptime" in names)
        HPX_TEST("/runtime{locality#0/total}/memory/virtual" in names)
        HPX_TEST("/runtime{locality#0/total}/count/"
                 "dropped-observer-callbacks" in names)

    def test_interval_printer_stops(self):
        buf = io.StringIO()
        stop = pc.start_counter_printing(0.02, "/runtime{*", file=buf)
        time.sleep(0.08)
        stop()
        n = buf.getvalue().count("\n")
        HPX_TEST(n >= 2, n)
        time.sleep(0.05)
        HPX_TEST_EQ(buf.getvalue().count("\n"), n)  # really stopped


def test_multiprocess_remote_query():
    from hpx_tpu.run import launch
    rc = launch(os.path.join(REPO, "tests", "mp_scripts",
                             "perf_counters_smoke.py"),
                [], localities=2, timeout=420.0)
    assert rc == 0


class TestNativePoolCounters:
    """Native C++ pool scheduler counters surface through the registry
    (executed/stolen atomics + per-worker queue depths)."""

    def _native_pool(self):
        try:
            from hpx_tpu.native.loader import NativePool
            return NativePool(2, "natcnt")
        except Exception:
            pytest.skip("native runtime unavailable")

    def test_counters_discovered_and_advance(self):
        import threading
        pool = self._native_pool()
        try:
            base = "/threads{locality#0/pool#natcnt}"
            # prefix WITHOUT the closing brace so the per-worker
            # instances (whose brace closes after worker-thread#N) match
            names = pc.discover_counters("/threads{locality#0/pool#natcnt*")
            assert f"{base}/count/cumulative" in names, names
            assert f"{base}/count/stolen" in names
            assert f"{base}/queue/length" in names
            # per-worker depth counters exist for every worker
            for w in range(pool.num_threads):
                n = ("/threads{locality#0/pool#natcnt/"
                     f"worker-thread#{w}}}/queue/length")
                assert n in names, (n, names)

            before = pc.query_counter(f"{base}/count/cumulative").value
            done = threading.Event()
            k = 500
            seen = [0]
            lock = threading.Lock()

            def task():
                with lock:
                    seen[0] += 1
                    if seen[0] == k:
                        done.set()

            pool.submit_many([(task, (), {})] * k)
            assert done.wait(30)
            import time
            for _ in range(500):
                if pc.query_counter(
                        f"{base}/count/cumulative").value >= before + k:
                    break
                time.sleep(0.01)
            assert pc.query_counter(
                f"{base}/count/cumulative").value >= before + k
        finally:
            pool.shutdown()

    def test_counters_read_zero_after_shutdown(self):
        pool = self._native_pool()
        base = "/threads{locality#0/pool#natcnt}"
        pc.discover_counters(f"{base}*")      # force registration
        pool.shutdown()
        # callbacks hold weakrefs / check _shut: no crash, value >= 0
        v = pc.query_counter(f"{base}/queue/length").value
        assert v == 0.0

    def test_queue_lengths_shape(self):
        pool = self._native_pool()
        try:
            qs = pool.queue_lengths()
            assert len(qs) == pool.num_threads
            assert all(q >= 0 for q in qs)
        finally:
            pool.shutdown()

    def test_recreated_same_name_pool_reports_live_values(self):
        """Counters resolve the pool by NAME at read time: after a
        same-name pool is recreated, the counters track the NEW one
        instead of a dead instance (and a shut pool reads 0)."""
        import threading
        pool = self._native_pool()
        base = "/threads{locality#0/pool#natcnt}"
        pc.discover_counters(f"{base}*")
        pool.shutdown()
        assert pc.query_counter(f"{base}/count/cumulative").value == 0.0

        pool2 = self._native_pool()
        try:
            done = threading.Event()
            k = 50
            seen = [0]
            lock = threading.Lock()

            def task():
                with lock:
                    seen[0] += 1
                    if seen[0] == k:
                        done.set()

            pool2.submit_many([(task, (), {})] * k)
            assert done.wait(30)
            import time
            for _ in range(500):
                if pc.query_counter(
                        f"{base}/count/cumulative").value >= k:
                    break
                time.sleep(0.01)
            assert pc.query_counter(
                f"{base}/count/cumulative").value >= k
        finally:
            pool2.shutdown()


def test_default_pool_counter_survives_pool_reset():
    """Counters must track the CURRENT default pool: after
    reset_default_pool() the callbacks resolve the new pool instead of
    reading the dead one forever (full-suite-order flake regression)."""
    from hpx_tpu.runtime.threadpool import reset_default_pool
    name = "/threads{locality#0/pool#default}/count/cumulative"
    reset_default_pool()
    before = pc.query_counter(name).value
    hpx.wait_all([hpx.async_(lambda: None) for _ in range(10)])
    for _ in range(500):
        if pc.query_counter(name).value >= before + 10:
            break
        time.sleep(0.01)
    HPX_TEST(pc.query_counter(name).value >= before + 10)


def test_idle_rate_counters():
    """HPX_WITH_THREAD_IDLE_RATES analog: parked/total in [0, 1] for
    both the default pool and native pools."""
    name = "/threads{locality#0/pool#default}/idle-rate"
    v = pc.query_counter(name).value
    assert 0.0 <= v <= 1.0, v
    try:
        from hpx_tpu.native.loader import NativePool
        pool = NativePool(2, "idlecnt")
    except Exception:
        pytest.skip("native runtime unavailable")
    try:
        n = "/threads{locality#0/pool#idlecnt}/idle-rate"
        # give the workers a moment to park, then the rate should be
        # high on an idle pool
        deadline = time.time() + 10
        while time.time() < deadline:
            if pc.query_counter(n).value >= 0.5:
                break
            time.sleep(0.05)
        v = pc.query_counter(n).value
        assert 0.5 <= v <= 1.0, v     # an idle pool must READ as idle
    finally:
        pool.shutdown()


@pytest.mark.skipif(sys.platform != "linux",
                    reason="statm counters read 0 off-linux by design")
def test_host_memory_counters():
    """/runtime/memory/{resident,virtual}: the reference's process
    memory counters, read from /proc/self/statm."""
    from hpx_tpu.svc import performance_counters as pc
    res = pc.query_counter(
        "/runtime{locality#0/total}/memory/resident")
    virt = pc.query_counter(
        "/runtime{locality#0/total}/memory/virtual")
    assert res.value > 1_000_000    # a python process is >1 MB resident
    assert virt.value >= res.value


def test_rate_counter_windowed_rate():
    """RateCounter: events/sec over a sliding window — the serving
    tokens/rate shape. 10 events in a 2s window read as 5/s no matter
    how fast they were marked."""
    rc = pc.RateCounter(window_s=2.0)
    assert rc.get_value().value == 0.0
    for _ in range(10):
        rc.mark()
    v = rc.get_value()
    assert v.value == pytest.approx(10 / 2.0)
    assert v.count >= 1
    rc.mark(4.0)                      # weighted marks (4 tokens at once)
    assert rc.get_value().value == pytest.approx(14 / 2.0)


def test_rate_counter_events_expire():
    rc = pc.RateCounter(window_s=0.05)
    rc.mark(100.0)
    deadline = time.time() + 5
    while time.time() < deadline and rc.get_value().value > 0:
        time.sleep(0.01)
    assert rc.get_value().value == 0.0   # aged out of the window


def test_rate_counter_rate_decays_across_idle_gap():
    """rate(): the controller-facing read decays linearly with the gap
    since the newest event instead of holding the last windowed value
    for a full window_s — a tuner reading a just-idled stream must see
    the rate falling, not a step function (satellite fix: stale rate
    across idle gaps)."""
    rc = pc.RateCounter(window_s=0.4)
    assert rc.rate() == 0.0              # empty window
    rc.mark(40.0)
    r0 = rc.rate()
    assert r0 > 0.0
    time.sleep(0.1)                      # idle: no further marks
    r1 = rc.rate()
    assert r1 < r0                       # decayed, NOT the step function
    # get_value() keeps the legacy step semantics (dashboards pin it)
    assert rc.get_value().value == pytest.approx(100.0)
    deadline = time.time() + 5
    while time.time() < deadline and rc.rate() > 0:
        time.sleep(0.02)
    assert rc.rate() == 0.0              # fully decayed / expired


def test_rate_counter_rate_matches_get_value_when_fresh():
    rc = pc.RateCounter(window_s=10.0)
    rc.mark(20.0)
    # immediately after a mark the gap is ~0: both reads agree
    assert rc.rate() == pytest.approx(rc.get_value().value, rel=0.05)


def test_rate_counter_reset_clears_window():
    rc = pc.RateCounter(window_s=60.0)
    rc.mark(30.0)
    assert rc.get_value(reset=True).value == pytest.approx(0.5)
    assert rc.get_value().value == 0.0


def test_rate_counter_validates_window():
    with pytest.raises(ValueError):
        pc.RateCounter(window_s=0.0)


def test_rate_counter_registers_like_any_counter():
    rc = pc.RateCounter(window_s=10.0)
    name = pc.counter_name("test", "events/rate", "ratecounter-test")
    pc.register_counter(name, rc)
    try:
        rc.mark(20.0)
        assert pc.query_counter(name).value == pytest.approx(2.0)
    finally:
        pc.unregister_counter(name)
