"""Per-program continuous profiler (svc/progprof): the cached_program
build hook, the callable proxy's per-call histogram, XLA cost-analysis
capture, the /programs{...} counter namespace, the profile_table fold,
the memory watermark, and the <2% overhead contract asserted by
call-count accounting (the proxy adds exactly one perf_counter pair
and one histogram record per call — never an extra compile or an
extra execution).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hpx_tpu.core import programs as core_programs
from hpx_tpu.core.config import runtime_config
from hpx_tpu.svc import performance_counters as pc
from hpx_tpu.svc import progprof
from hpx_tpu.utils.compilemon import count_compiles


@pytest.fixture()
def profiler():
    """An installed profiler (no memory thread — tests sample
    directly), torn down even on failure so the module hook never
    leaks into other tests."""
    prof = progprof.start_profiling(sample_memory=False)
    try:
        yield prof
    finally:
        progprof.stop_profiling()


def _demo_cache_and_build(tag="demo"):
    cache = {}
    key = (tag, 8)

    def build():
        return jax.jit(lambda x: (x * 2.0 + 1.0).sum())

    return cache, key, build


# ---------------------------------------------------------------------------
# hook mechanics
# ---------------------------------------------------------------------------


def test_miss_wraps_hit_returns_same_proxy(profiler):
    cache, key, build = _demo_cache_and_build()
    p1 = core_programs.cached_program(cache, key, build)
    p2 = core_programs.cached_program(cache, key, build)
    assert p1 is p2                      # hit returns the stored proxy
    assert isinstance(p1, progprof._ProfiledProgram)
    (rec,) = profiler.records()
    assert rec.compiles == 1 and rec.compile_s > 0.0
    # passthrough: jit attributes still reachable through the proxy
    assert callable(p1.lower)


def test_no_profiler_no_wrapping():
    assert progprof.active_profiler() is None
    cache, key, build = _demo_cache_and_build()
    p = core_programs.cached_program(cache, key, build)
    assert not isinstance(p, progprof._ProfiledProgram)
    assert float(p(jnp.ones((8,)))) == pytest.approx(24.0)


def test_non_callable_build_product_passes_through(profiler):
    cache = {}
    plan = ("plan", 1, 2)
    out = core_programs.cached_program(cache, ("k",), lambda: plan)
    assert out is plan
    assert profiler.records() == []      # nothing to time per-call


# ---------------------------------------------------------------------------
# per-call accounting + overhead contract
# ---------------------------------------------------------------------------


def test_call_count_accounting_zero_extra_compiles(profiler):
    """The <2% overhead claim reduces to an exact accounting claim:
    N warm calls through the proxy cost N histogram records and ZERO
    additional compiles or executions — the proxy never re-traces,
    re-lowers, or double-calls the underlying program."""
    cache, key, build = _demo_cache_and_build()
    x = jnp.ones((8,))
    prog = core_programs.cached_program(cache, key, build)
    prog(x)                              # cold: compile + cost analysis
    (rec,) = profiler.records()
    warm0 = rec.calls
    n = 25
    with count_compiles() as c:
        for _ in range(n):
            prog(x)
    assert c.count == 0                  # zero extra compiles warm
    assert rec.calls == warm0 + n        # exactly one record per call
    assert rec.compiles == 1             # one build, ever
    assert rec.exec_hist.count == rec.calls
    assert rec.exec_hist.sum > 0.0


def test_results_identical_through_proxy(profiler):
    cache, key, build = _demo_cache_and_build()
    x = jnp.arange(8, dtype=jnp.float32)
    prog = core_programs.cached_program(cache, key, build)
    want = float(jax.jit(lambda x: (x * 2.0 + 1.0).sum())(x))
    assert float(prog(x)) == pytest.approx(want)


# ---------------------------------------------------------------------------
# cost analysis + roofline
# ---------------------------------------------------------------------------


def test_cost_analysis_captured_or_accounted(profiler):
    cache, key, build = _demo_cache_and_build()
    prog = core_programs.cached_program(cache, key, build)
    prog(jnp.ones((8,)))
    (rec,) = profiler.records()
    assert rec.cost_pending is False     # attempted exactly once
    if rec.flops is None:
        # unavailable on this backend: must be *accounted*, not silent
        assert profiler.cost_failures >= 0
    else:
        assert rec.flops > 0.0
        assert rec.achieved_gflops() > 0.0
    # CPU backend: no peak table entry -> roofline fraction reports 0
    assert profiler.peak_gflops == 0.0
    assert rec.roofline_fraction(profiler.peak_gflops) == 0.0


def test_roofline_fraction_with_configured_peak():
    cfg = runtime_config()
    cfg.set("hpx.prof.peak_gflops", "100")
    try:
        prof = progprof.start_profiling(sample_memory=False)
        try:
            assert prof.peak_gflops == 100.0
            cache, key, build = _demo_cache_and_build()
            prog = core_programs.cached_program(cache, key, build)
            for _ in range(3):
                prog(jnp.ones((8,)))
            (rec,) = prof.records()
            if rec.flops is not None:
                want = rec.achieved_gflops() / 100.0
                assert rec.roofline_fraction(100.0) == \
                    pytest.approx(want)
        finally:
            progprof.stop_profiling()
    finally:
        cfg.set("hpx.prof.peak_gflops", "0")


# ---------------------------------------------------------------------------
# counter namespace
# ---------------------------------------------------------------------------


def test_programs_counter_namespace(profiler):
    cache, key, build = _demo_cache_and_build()
    prog = core_programs.cached_program(cache, key, build)
    for _ in range(4):
        prog(jnp.ones((8,)))
    names = pc.discover_counters("/programs{locality#*/*}/*")
    # per-program planes + process-wide memory watermarks
    assert any(n.endswith("/time/execute-s") for n in names)
    assert any("/time/execute-s/p99" in n for n in names)
    assert any(n.endswith("/memory/hbm-peak-bytes") for n in names)
    calls = pc.query_counter(
        "/programs{locality#0/demo#0}/count/calls").value
    assert calls == 4.0
    compile_s = pc.query_counter(
        "/programs{locality#0/demo#0}/time/compile-s").value
    assert compile_s > 0.0


def test_counters_unregistered_on_stop():
    prof = progprof.start_profiling(sample_memory=False)
    cache, key, build = _demo_cache_and_build()
    core_programs.cached_program(cache, key, build)(jnp.ones((8,)))
    assert pc.discover_counters("/programs{locality#*/*}/*")
    progprof.stop_profiling()
    assert pc.discover_counters("/programs{locality#*/*}/*") == []
    assert core_programs.profile_hook() is None
    assert prof.records()                # table still readable after


# ---------------------------------------------------------------------------
# profile_table fold
# ---------------------------------------------------------------------------


def test_profile_table_shape_and_order(profiler):
    import json
    cache = {}
    fast = core_programs.cached_program(
        cache, ("fast", 1), lambda: jax.jit(lambda x: x + 1.0))
    slow = core_programs.cached_program(
        cache, ("slow", 1),
        lambda: jax.jit(lambda x: jnp.sort(x * 2.0)))
    x = jnp.ones((64,))
    fast(x)
    for _ in range(10):
        slow(x)
    table = profiler.profile_table()
    assert table["schema"] == progprof.PROFILE_SCHEMA
    assert table["cost_failures"] == profiler.cost_failures
    assert set(table["memory"]) == {"hbm_peak_bytes",
                                    "host_peak_bytes", "samples"}
    rows = table["programs"]
    totals = [r["total_s"] for r in rows]
    assert totals == sorted(totals, reverse=True)   # busiest first
    by_key = {r["key"]: r for r in rows}
    assert by_key["slow"]["calls"] == 10
    assert by_key["fast"]["calls"] == 1
    for r in rows:
        assert r["p99_s"] >= r["p50_s"] >= 0.0
        assert 0.0 < r["relative_error_bound"] < 0.1
        assert r["mean_s"] * r["calls"] == pytest.approx(r["total_s"])
    json.dumps(table)                    # JSON-safe, whole fold
    # module-level accessor answers the same fold while active
    assert progprof.profile_table()["schema"] == \
        progprof.PROFILE_SCHEMA


def test_module_profile_table_none_when_inactive():
    assert progprof.active_profiler() is None
    assert progprof.profile_table() is None


# ---------------------------------------------------------------------------
# lifecycle + config gate
# ---------------------------------------------------------------------------


def test_double_start_raises(profiler):
    with pytest.raises(RuntimeError):
        progprof.start_profiling()


def test_start_if_configured_gate():
    cfg = runtime_config()
    assert not cfg.get_bool("hpx.prof.programs", False)
    assert progprof.start_if_configured() is None
    cfg.set("hpx.prof.programs", "1")
    try:
        prof = progprof.start_if_configured()
        assert prof is not None
        assert progprof.start_if_configured() is prof   # idempotent
    finally:
        progprof.stop_profiling()
        cfg.set("hpx.prof.programs", "0")


# ---------------------------------------------------------------------------
# memory watermark
# ---------------------------------------------------------------------------


def test_memory_watermark_direct_sample():
    wm = progprof.MemoryWatermark()
    wm.sample()
    snap = wm.snapshot()
    assert snap["samples"] == 1
    assert snap["host_peak_bytes"] > 0           # procfs RSS
    assert snap["hbm_peak_bytes"] >= 0
    # high-water-mark: a second sample never lowers the peaks
    wm.sample()
    assert wm.host_peak_bytes >= snap["host_peak_bytes"]


def test_memory_watermark_thread_lifecycle():
    wm = progprof.MemoryWatermark(interval_s=0.002)
    wm.start()
    import time
    deadline = time.time() + 2.0
    while wm.samples == 0 and time.time() < deadline:
        time.sleep(0.005)
    wm.stop()
    assert wm.samples > 0
    assert wm._thread is None
    wm.stop()                                    # idempotent


# ---------------------------------------------------------------------------
# end-to-end: the real serving stack funnels through the hook
# ---------------------------------------------------------------------------


def test_serving_programs_profiled(profiler):
    """ContinuousServer's programs all flow through cached_program, so
    a fresh config's compiles land in the profiler (fresh d_ff keeps
    the shared transformer cache cold for this test)."""
    from hpx_tpu.models import transformer as tfm
    from hpx_tpu.models.serving import ContinuousServer
    cfg = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                head_dim=8, n_layers=2, d_ff=48)
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    srv = ContinuousServer(params, cfg, slots=2, smax=64)
    srv.submit([3, 1, 4, 1, 5], max_new=6)
    srv.submit([2, 7], max_new=4)
    out = srv.run()
    assert len(out) == 2
    rows = profiler.profile_table()["programs"]
    assert rows, "serving compiled no profiled programs"
    assert all(r["calls"] >= 1 for r in rows)
    labels = {r["key"] for r in rows}
    assert labels, labels
