"""Distributed unordered_map tests.

Reference analog: components/containers/unordered tests (SURVEY.md
§2.4). Single-locality partition routing + semantics here; the
cross-process path is tests/mp_scripts/unordered_smoke.py.
"""

import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

import hpx_tpu as hpx
from hpx_tpu.containers.unordered_map import stable_hash
from hpx_tpu.testing import HPX_TEST, HPX_TEST_EQ

REPO = os.path.join(os.path.dirname(__file__), "..")


class TestStableHash:
    def test_deterministic_across_processes(self):
        # run a child with a different hash seed; digests must agree
        code = ("import sys; sys.path.insert(0, %r); "
                "from hpx_tpu.containers.unordered_map import stable_hash; "
                "print(stable_hash('k1'), stable_hash((1, 'a', b'b', None, "
                "True)))" % REPO)
        env = dict(os.environ, PYTHONHASHSEED="12345")
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True, check=True)
        a, b = out.stdout.split()
        HPX_TEST_EQ(int(a), stable_hash("k1"))
        HPX_TEST_EQ(int(b), stable_hash((1, "a", b"b", None, True)))

    def test_distinct(self):
        keys = ["a", "b", "ab", b"a", 1, (1,), ("a",), None, True, 0]
        digests = {stable_hash(k) for k in keys}
        HPX_TEST_EQ(len(digests), len(keys))

    def test_unsupported_key_raises(self):
        with pytest.raises(hpx.HpxError):
            stable_hash([1, 2])
        with pytest.raises(hpx.HpxError):
            stable_hash(1.5)


class TestUnorderedMap:
    def test_basic_set_get(self):
        m = hpx.UnorderedMap()
        m["x"] = 1
        m[("compound", 2)] = {"nested": True}
        HPX_TEST_EQ(m["x"], 1)
        HPX_TEST_EQ(m[("compound", 2)], {"nested": True})
        HPX_TEST_EQ(len(m), 2)
        HPX_TEST("x" in m and "y" not in m)
        m.free().get()

    def test_missing_key(self):
        m = hpx.UnorderedMap()
        with pytest.raises(KeyError):
            m["missing"]
        HPX_TEST_EQ(m.get("missing", 42), 42)
        with pytest.raises(KeyError):
            del m["missing"]
        m.free().get()

    def test_erase(self):
        m = hpx.UnorderedMap()
        m["k"] = "v"
        HPX_TEST(m.erase("k") is True)
        HPX_TEST(m.erase("k") is False)
        HPX_TEST_EQ(len(m), 0)
        m.free().get()

    def test_bulk_update_items(self):
        m = hpx.UnorderedMap()
        m.update({f"k{i}": i for i in range(50)}).get()
        HPX_TEST_EQ(len(m), 50)
        HPX_TEST_EQ(sorted(v for _k, v in m.items()), list(range(50)))
        HPX_TEST_EQ(sorted(m.keys())[0], "k0")
        HPX_TEST_EQ(m.clear(), 50)
        HPX_TEST_EQ(len(m), 0)
        m.free().get()

    def test_async_spellings(self):
        m = hpx.UnorderedMap()
        hpx.wait_all([m.set_async(i, i * i) for i in range(10)])
        futs = [m.get_async(i) for i in range(10)]
        HPX_TEST_EQ([f.get() for f in futs], [i * i for i in range(10)])
        HPX_TEST_EQ(m.size_async().get(), 10)
        m.free().get()

    def test_jax_array_values(self):
        m = hpx.UnorderedMap()
        m["weights"] = jnp.arange(8, dtype=jnp.float32)
        got = m["weights"]
        np.testing.assert_array_equal(np.asarray(got),
                                      np.arange(8, dtype=np.float32))
        m.free().get()

    def test_register_connect_roundtrip(self):
        m = hpx.UnorderedMap()
        m["shared"] = 7
        m.register_as("unit-map").get()
        m2 = hpx.UnorderedMap.connect_to("unit-map")
        HPX_TEST_EQ(m2["shared"], 7)
        m2["from-peer"] = 8
        HPX_TEST_EQ(m["from-peer"], 8)
        m.free().get()


def test_multiprocess_unordered_map():
    from hpx_tpu.run import launch
    rc = launch(os.path.join(REPO, "tests", "mp_scripts",
                             "unordered_smoke.py"),
                [], localities=3, timeout=420.0)
    assert rc == 0


def test_num_partitions_round_robin_without_placement():
    from hpx_tpu.containers.unordered_map import UnorderedMap
    m = UnorderedMap(num_partitions=4)       # 1 locality: 4 partitions
    assert m.num_partitions == 4
    for i in range(20):
        m.set(i, i * 2)
    assert [m.get(i) for i in range(20)] == [i * 2 for i in range(20)]


def test_num_partitions_zero_rejected():
    import pytest
    from hpx_tpu.core.errors import HpxError
    from hpx_tpu.containers.unordered_map import UnorderedMap
    with pytest.raises(HpxError):
        UnorderedMap(num_partitions=0)
