"""SLO regression gate (benchmarks/slo_gate.py): self-comparison is
regression-free by construction, a perturbation beyond the combined
quantile error bound is flagged, one within it is not, and the CLI's
exit codes + bench.py's --baseline wiring hold.
"""

import copy
import json
import os
import subprocess
import sys

import pytest

from hpx_tpu.svc.metrics import HistogramCounter

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "benchmarks"))

import slo_gate  # noqa: E402


def _artifact(scales=(1.0,), names=("ttft",), n=400, seed=7):
    """A minimal hpx_tpu.metrics.v1 artifact: deterministic lognormal
    latencies per named histogram, scaled."""
    import numpy as np
    rng = np.random.default_rng(seed)
    doc = {"schema": slo_gate.METRICS_SCHEMA, "histograms": {}}
    for name, scale in zip(names, list(scales) * len(names)):
        h = HistogramCounter()
        for x in rng.lognormal(mean=-3.0, sigma=0.5, size=n):
            h.record(float(x) * scale)
        doc["histograms"][name] = {
            "snapshot": h.snapshot(),
            "relative_error_bound": h.relative_error_bound(),
        }
    return doc


def _kinds(verdicts):
    return {(v.name, v.quantile): v.kind for v in verdicts}


# ---------------------------------------------------------------------------
# compare() semantics
# ---------------------------------------------------------------------------


def test_self_compare_zero_regressions():
    doc = _artifact(names=("ttft", "decode_step", "e2e"))
    verdicts = slo_gate.compare(doc, copy.deepcopy(doc))
    assert verdicts                          # 3 names x 3 quantiles
    assert slo_gate.regressions(verdicts) == []
    assert all(v.kind == slo_gate.KIND_OK for v in verdicts)
    assert all(v.margin == 0.0 for v in verdicts)


def test_perturbed_p99_flagged():
    base = _artifact()
    # scale far beyond the combined bound ((1+e)^2-1 ~ 9% at default
    # resolution): every quantile regresses, p99 included
    cand = _artifact(scales=(1.5,))
    verdicts = slo_gate.compare(base, cand)
    kinds = _kinds(verdicts)
    assert kinds[("ttft", "p99")] == slo_gate.KIND_REGRESSED
    assert kinds[("ttft", "p50")] == slo_gate.KIND_REGRESSED
    reg = slo_gate.regressions(verdicts)
    assert reg and all(v.margin > 0.09 for v in reg)


def test_within_bound_shift_not_flagged():
    base = _artifact()
    h = HistogramCounter()
    bound = h.relative_error_bound()
    # a shift inside ONE histogram's bound can never clear the
    # combined two-sided bound — indistinguishable, so "ok"
    cand = _artifact(scales=(1.0 + bound * 0.9,))
    verdicts = slo_gate.compare(base, cand)
    assert slo_gate.regressions(verdicts) == []


def test_improvement_detected_not_a_regression():
    verdicts = slo_gate.compare(_artifact(), _artifact(scales=(0.5,)))
    assert slo_gate.regressions(verdicts) == []
    assert any(v.kind == slo_gate.KIND_IMPROVED for v in verdicts)


def test_one_sided_names_incomparable_never_regressed():
    base = _artifact(names=("ttft", "old_only"))
    cand = _artifact(names=("ttft", "new_only"), scales=(3.0,))
    verdicts = slo_gate.compare(base, cand)
    kinds = _kinds(verdicts)
    assert kinds[("old_only", "*")] == slo_gate.KIND_INCOMPARABLE
    assert kinds[("new_only", "*")] == slo_gate.KIND_INCOMPARABLE
    notes = {v.name: v.note for v in verdicts
             if v.kind == slo_gate.KIND_INCOMPARABLE}
    assert notes == {"old_only": "only in baseline",
                     "new_only": "only in candidate"}
    # the renamed-but-3x-slower "new_only" must not count as ok/win
    assert ("ttft", "p99") in kinds


def test_empty_and_malformed_histograms_incomparable():
    base = _artifact()
    cand = copy.deepcopy(base)
    empty = HistogramCounter()
    cand["histograms"]["ttft"] = {
        "snapshot": empty.snapshot(),
        "relative_error_bound": empty.relative_error_bound()}
    verdicts = slo_gate.compare(base, cand)
    assert _kinds(verdicts)[("ttft", "*")] == slo_gate.KIND_INCOMPARABLE
    cand["histograms"]["ttft"] = {"snapshot": "garbage"}
    verdicts = slo_gate.compare(base, cand)
    (v,) = verdicts
    assert v.kind == slo_gate.KIND_INCOMPARABLE
    assert v.note == "unreadable snapshot"
    assert slo_gate.regressions(verdicts) == []


def test_error_bound_is_combined_two_sided():
    doc = _artifact()
    (v, *_) = slo_gate.compare(doc, copy.deepcopy(doc))
    e = HistogramCounter().relative_error_bound()
    assert v.error_bound == pytest.approx((1 + e) * (1 + e) - 1)


def test_custom_quantiles():
    doc = _artifact()
    verdicts = slo_gate.compare(doc, copy.deepcopy(doc),
                                quantiles=(0.9,))
    assert [v.quantile for v in verdicts] == ["p90"]


# ---------------------------------------------------------------------------
# rendering + CLI exit codes
# ---------------------------------------------------------------------------


def test_render_text_summary_line():
    verdicts = slo_gate.compare(_artifact(), _artifact(scales=(1.5,)))
    txt = slo_gate.render_text(verdicts)
    assert txt.splitlines()[-1] == f"regressions: {len(verdicts)}"
    assert txt.splitlines()[0].startswith("✗")
    ok = slo_gate.render_text(
        slo_gate.compare(_artifact(), _artifact()))
    assert ok.splitlines()[-1] == "regressions: 0"


def _write(tmp_path, name, doc):
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


def test_cli_exit_codes(tmp_path, capsys):
    base = _write(tmp_path, "base.json", _artifact())
    same = _write(tmp_path, "same.json", _artifact())
    slow = _write(tmp_path, "slow.json", _artifact(scales=(2.0,)))
    bad = _write(tmp_path, "bad.json", {"schema": "nope"})
    assert slo_gate.main([base, same]) == 0
    assert slo_gate.main([base, slow]) == 1
    assert slo_gate.main([base, bad]) == 2
    capsys.readouterr()
    assert slo_gate.main([base, slow, "--format", "json"]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["regressions"] > 0
    assert all({"name", "quantile", "kind"} <= set(v)
               for v in out["verdicts"])


def test_cli_subprocess_entrypoint(tmp_path):
    # the gate must work as a standalone script too (CI usage)
    base = _write(tmp_path, "base.json", _artifact())
    slow = _write(tmp_path, "slow.json", _artifact(scales=(2.0,)))
    script = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks", "slo_gate.py")
    r = subprocess.run([sys.executable, script, base, slow],
                       capture_output=True, text=True, timeout=120,
                       env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 1, r.stderr
    assert "regressions:" in r.stdout


# ---------------------------------------------------------------------------
# bench.py wiring: --baseline gates the round's artifact
# ---------------------------------------------------------------------------


def _bench_module():
    import importlib.util
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_main", os.path.join(root, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_gate_exits_1_on_regression(tmp_path, monkeypatch,
                                          capsys):
    mod = _bench_module()
    base = _write(tmp_path, "base.json", _artifact())
    slow = _write(tmp_path, "slow.json", _artifact(scales=(2.0,)))
    monkeypatch.setenv(mod._METRICS_ENV, slow)
    with pytest.raises(SystemExit) as ei:
        mod._run_slo_gate(base)
    assert ei.value.code == 1
    cap = capsys.readouterr()
    # verdicts on stderr ONLY: stdout stays a pure metric stream
    assert cap.out == ""
    assert "regressions:" in cap.err


def test_bench_gate_passes_and_skips_cleanly(tmp_path, monkeypatch,
                                             capsys):
    mod = _bench_module()
    base = _write(tmp_path, "base.json", _artifact())
    same = _write(tmp_path, "same.json", _artifact())
    monkeypatch.setenv(mod._METRICS_ENV, same)
    mod._run_slo_gate(base)                 # no regression: returns
    assert "regressions: 0" in capsys.readouterr().err
    # no --metrics-out artifact: gate skips with a note, never exits
    monkeypatch.delenv(mod._METRICS_ENV)
    mod._run_slo_gate(base)
    assert "skipped" in capsys.readouterr().err
