"""bench.py resilience: a dead device tunnel must never leave a round
evidence-free again (the round-4 lesson — BENCH_r04.json was empty).

These tests drive the fallback machinery without any accelerator: the
probe/fallback paths never import jax in the parent process by design
(SURVEY.md §6: the baseline must be *measured*; when it can't be, the
most recent builder-session record is re-emitted, clearly labeled).
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _load_bench(tmp_path, lines):
    spec = importlib.util.spec_from_file_location("benchmod", BENCH)
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    m._FALLBACK_SEED = str(tmp_path / "bench_fallback.json")
    m._FALLBACK_LOCAL = str(tmp_path / "bench_fallback.local.json")
    if lines is not None:
        with open(m._FALLBACK_SEED, "w") as f:
            json.dump({"measured_at": "2026-01-01T00:00:00+00:00",
                       "lines": lines}, f)
    return m


def test_emit_fallback_labels_provenance(tmp_path, capsys):
    m = _load_bench(tmp_path, [
        {"metric": "stream_triad_gbs", "value": 700.0, "unit": "GB/s",
         "vs_baseline": 0.85},
        {"metric": "1d_stencil_cell_updates", "value": 98000.0,
         "unit": "Mcells/s", "vs_baseline": 0.75},
    ])
    assert m._emit_fallback()
    out = [json.loads(ln) for ln in
           capsys.readouterr().out.strip().splitlines()]
    assert len(out) == 2
    for line in out:
        assert line["provenance"] == "builder-session"
        assert line["onchip"] is False   # explicit: banked, not live
        assert line["measured_at"] == "2026-01-01T00:00:00+00:00"
    # emission order preserved: the headline metric stays LAST so the
    # driver's last-line parser picks it up
    assert out[-1]["metric"] == "1d_stencil_cell_updates"


def test_emit_fallback_without_record(tmp_path):
    m = _load_bench(tmp_path, None)
    assert not m._emit_fallback()


def test_fallback_age_stamps_the_unavailable_record(tmp_path):
    # probe-exhausted runs must say HOW OLD the medians they re-emit
    # are: oldest stamp + age in hours, span when assembled across runs
    m = _load_bench(tmp_path, [
        {"metric": "stream_triad_gbs", "value": 700.0, "unit": "GB/s",
         "vs_baseline": 0.85},
    ])
    age = m._fallback_age(m._load_fallback())
    assert age["fallback_measured_at"] == "2026-01-01T00:00:00+00:00"
    assert age["fallback_age_hours"] > 0
    mixed = m._fallback_age([
        {"measured_at": "2026-01-01T00:00:00+00:00"},
        {"measured_at": "2026-02-01T00:00:00+00:00"},
        {"measured_at": "unknown"},
    ])
    assert mixed["fallback_measured_at"] == "2026-01-01T00:00:00+00:00"
    assert mixed["fallback_measured_at_newest"] == \
        "2026-02-01T00:00:00+00:00"


def test_fallback_age_without_record(tmp_path):
    m = _load_bench(tmp_path, None)
    age = m._fallback_age([])
    assert age == {"fallback_measured_at": "unknown",
                   "fallback_age_hours": -1}


def test_save_fallback_roundtrip(tmp_path, capsys):
    m = _load_bench(tmp_path, None)
    m.emit("x_metric", 1.234, "u", 0.5, spread=0.01)
    m._save_fallback()
    capsys.readouterr()
    m._EMITTED.clear()
    assert m._emit_fallback()
    line = json.loads(capsys.readouterr().out.strip())
    assert line["metric"] == "x_metric" and line["value"] == 1.234
    assert line["provenance"] == "builder-session"
    assert line["onchip"] is False


def test_probe_budget_env_bounds_retries(tmp_path, monkeypatch):
    m = _load_bench(tmp_path, None)
    monkeypatch.setenv("HPX_BENCH_PROBE_BUDGET", "1")
    calls = []

    def fake_once(timeout_s):
        calls.append(timeout_s)
        return False
    m._probe_device_once = fake_once
    import time as _t
    t0 = _t.monotonic()
    assert not m._probe_device()
    assert _t.monotonic() - t0 < 30      # budget respected, no 20-min wait
    assert calls                          # at least one bounded attempt


@pytest.mark.slow
def test_cli_dead_tunnel_emits_labeled_fallback(tmp_path):
    """End-to-end: bench.py with an unreachable device must exit 0 and
    print bench_unavailable followed by labeled builder-session lines."""
    env = dict(os.environ)
    # a zero probe budget fails the probe DETERMINISTICALLY without
    # touching the device tunnel at all (the sandbox sitecustomize
    # overrides JAX_PLATFORMS in fresh interpreters, so pointing jax at
    # a bogus platform would not reliably fail)
    env["HPX_BENCH_PROBE_BUDGET"] = "0"
    proc = subprocess.run([sys.executable, BENCH], cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=300)
    lines = [json.loads(ln) for ln in proc.stdout.strip().splitlines()]
    assert lines[0]["metric"] == "bench_unavailable"
    rest = lines[1:]
    assert rest, proc.stdout
    assert all(ln.get("provenance") == "builder-session" for ln in rest)
    assert all(ln.get("onchip") is False for ln in rest)
    assert proc.returncode == 0


def test_incremental_merge_banks_partial_runs(tmp_path, monkeypatch):
    """Each emit() saves immediately, merging per-metric with the seed
    and with earlier partial runs — a wedging tunnel still banks every
    live metric it managed (round-5 machinery)."""
    import importlib
    m = importlib.import_module("bench")
    local = tmp_path / "fb.local.json"
    monkeypatch.setattr(m, "_FALLBACK_LOCAL", str(local))
    monkeypatch.setattr(m, "_EMITTED", [])
    m.emit("stream_triad_gbs", 777.0, "GB/s", 0.9)
    monkeypatch.setattr(m, "_EMITTED", [])   # a separate later run
    m.emit("fft_1d_gflops", 55.0, "GFLOP/s", 0.4)
    rec = json.loads(local.read_text())
    got = {ln["metric"]: ln for ln in rec["lines"]}
    assert got["stream_triad_gbs"]["value"] == 777.0   # first run kept
    assert got["fft_1d_gflops"]["value"] == 55.0       # second merged in
    assert "transformer_step_ms" in got                # seed rode along
    assert rec["lines"][-1]["metric"] == "1d_stencil_cell_updates"
    assert all("measured_at" in ln for ln in rec["lines"])
    # freshest wins on re-measure
    monkeypatch.setattr(m, "_EMITTED", [])
    m.emit("stream_triad_gbs", 800.0, "GB/s", 0.95)
    rec2 = json.loads(local.read_text())
    got2 = {ln["metric"]: ln for ln in rec2["lines"]}
    assert got2["stream_triad_gbs"]["value"] == 800.0
