"""communication_set (tree collectives) — hpx::collectives analog.

Sites here are threads within one locality (the Communicator contract
allows it), so the tree topology — leaf groups, recursive upper levels,
downward broadcast — is exercised exactly; distribution of the leaf
roots across real localities is covered by the 8-locality mp smoke
(tests/mp_scripts/comm_set_smoke.py).
"""

import operator
import threading

import pytest

from hpx_tpu.collectives.comm_set import CommunicationSet


def _run_sites(num_sites, arity, verb):
    """Run verb(site_comm) on every site concurrently; list of results."""
    results = [None] * num_sites
    errors = []

    def site(i):
        try:
            cs = CommunicationSet("t", num_sites, i, arity=arity,
                                  site_locality=lambda s: 0)
            results[i] = verb(cs, i).get(timeout=60)
        except BaseException as e:  # noqa: BLE001
            errors.append((i, e))

    ts = [threading.Thread(target=site, args=(i,))
          for i in range(num_sites)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(90)
    assert not errors, errors
    return results


@pytest.mark.parametrize("num_sites,arity", [
    (4, 2),      # two leaf groups + top
    (8, 2),      # recursive upper CommunicationSet (4 groups > arity)
    (16, 4),     # 4 groups of 4
    (9, 4),      # ragged tail group
    (3, 8),      # single group, no upper level
])
def test_all_reduce_sum(num_sites, arity):
    got = _run_sites(num_sites, arity,
                     lambda cs, i: cs.all_reduce(i + 1))
    want = num_sites * (num_sites + 1) // 2
    assert got == [want] * num_sites


def test_all_reduce_noncommutative_order():
    """Tree fold must respect site order for associative-but-
    noncommutative ops (string concat)."""
    got = _run_sites(9, 2, lambda cs, i: cs.all_reduce(
        str(i), op=operator.add))
    assert got == ["012345678"] * 9


def test_reduce_to_site0():
    got = _run_sites(8, 2, lambda cs, i: cs.reduce(i + 1))
    assert got[0] == 36
    assert got[1:] == [None] * 7


def test_broadcast_from_site0():
    got = _run_sites(16, 4,
                     lambda cs, i: cs.broadcast("root-data" if i == 0
                                                else None))
    assert got == ["root-data"] * 16


def test_barrier_releases_all():
    got = _run_sites(8, 2, lambda cs, i: cs.barrier())
    assert len(got) == 8


def test_fan_in_bounded_by_arity():
    """The point of the tree: no single communicator sees more than
    `arity` contributions."""
    cs = CommunicationSet("shape", 64, 0, arity=8,
                          site_locality=lambda s: 0)
    assert cs._leaf.num_sites <= 8
    assert cs._upper is not None and cs._upper.num_sites <= 8

    cs2 = CommunicationSet("shape2", 65, 0, arity=8,
                           site_locality=lambda s: 0)
    # 9 groups > arity: the upper level recurses
    assert isinstance(cs2._upper, CommunicationSet)
    assert cs2._upper._leaf.num_sites <= 8


def test_bad_args():
    with pytest.raises(ValueError):
        CommunicationSet("x", 4, 4)
    with pytest.raises(ValueError):
        CommunicationSet("x", 4, 0, arity=1)


@pytest.mark.slow
def test_multiprocess_comm_set_tree(monkeypatch):
    """Depth-2 tree over 7 real localities: verbs fold correctly and
    the root-side exchange state provably lands on group roots."""
    import os
    from hpx_tpu.run import launch
    # 7 interpreters importing jax on a loaded 1-core host have been
    # observed to exceed even the default 120 s bootstrap window
    # (core/config.py DEFAULTS) — give the table broadcast more room
    monkeypatch.setenv("HPX_TPU_STARTUP_TIMEOUT", "180")
    monkeypatch.setenv("HPX_TPU_BARRIER_TIMEOUT", "420")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "tests", "mp_scripts",
                          "comm_set_smoke.py")
    rc = launch(script, [], localities=7, timeout=420.0)
    if rc != 0:
        # contention retry — see test_multiprocess_binpacking's note
        rc = launch(script, [], localities=7, timeout=420.0)
    assert rc == 0
