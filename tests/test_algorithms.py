"""Parallel algorithm tests: systematic per-algorithm × policy matrix with
differential checks vs numpy (HPX's per-algorithm × policy × iterator
convention — libs/core/algorithms/tests/unit/algorithms/*).

Policies covered: seq (host reference), par (host chunked), par.task
(future-returning), par.on(TpuExecutor()) (device path, CPU backend in
tests — identical code path on real TPU).
"""

import operator

import jax.numpy as jnp
import numpy as np
import pytest

import hpx_tpu as hpx
from hpx_tpu.futures.future import Future

RNG = np.random.default_rng(42)


def device_policy():
    return hpx.par.on(hpx.TpuExecutor())


def policies():
    return [hpx.seq, hpx.par, device_policy()]


def unwrap(x):
    return x.get(timeout=60.0) if isinstance(x, Future) else x


def asnp(x):
    return np.asarray(unwrap(x))


# -- elementwise ------------------------------------------------------------

@pytest.mark.parametrize("pol_idx", range(3))
def test_for_each(pol_idx):
    pol = policies()[pol_idx]
    data = jnp.arange(16, dtype=jnp.float32) if pol_idx == 2 else \
        np.arange(16, dtype=np.float32)
    out = hpx.for_each(pol, data, lambda x: x * 2)
    np.testing.assert_allclose(asnp(out), np.arange(16) * 2)


@pytest.mark.parametrize("pol_idx", range(3))
def test_transform_unary_binary(pol_idx):
    pol = policies()[pol_idx]
    mk = jnp.asarray if pol_idx == 2 else np.asarray
    a = mk(np.arange(10, dtype=np.float32))
    b = mk(np.full(10, 3.0, np.float32))
    np.testing.assert_allclose(asnp(hpx.transform(pol, a, lambda x: x + 1)),
                               np.arange(10) + 1)
    np.testing.assert_allclose(
        asnp(hpx.transform(pol, a, lambda x, y: x * y, b)),
        np.arange(10) * 3.0)


def test_fill_generate_copy():
    for pol_idx in range(3):
        pol = policies()[pol_idx]
        mk = jnp.asarray if pol_idx == 2 else np.asarray
        a = mk(np.zeros(8, np.float32))
        np.testing.assert_allclose(asnp(hpx.fill(pol, a, 7.0)), np.full(8, 7.0))
        np.testing.assert_allclose(asnp(hpx.generate(pol, a, lambda: 2.0)),
                                   np.full(8, 2.0))
        c = hpx.copy(pol, a)
        np.testing.assert_allclose(asnp(c), np.asarray(a))


def test_copy_if_compaction():
    data = np.arange(20)
    out = hpx.copy_if(hpx.par, data, lambda x: x % 2 == 0)
    np.testing.assert_array_equal(asnp(out), np.arange(0, 20, 2))
    dev = hpx.copy_if(device_policy(), jnp.arange(20), lambda x: x % 2 == 0)
    np.testing.assert_array_equal(asnp(dev), np.arange(0, 20, 2))


def test_for_loop_device_and_host():
    hits = []
    hpx.for_loop(hpx.seq, 2, 6, hits.append)
    assert hits == [2, 3, 4, 5]
    out = hpx.for_loop(device_policy(), 0, 8, lambda i: i * i)
    np.testing.assert_array_equal(asnp(out), np.arange(8) ** 2)


# -- reductions -------------------------------------------------------------

@pytest.mark.parametrize("pol_idx", range(3))
def test_reduce(pol_idx):
    pol = policies()[pol_idx]
    mk = jnp.asarray if pol_idx == 2 else np.asarray
    a = mk(np.arange(100, dtype=np.float32))
    assert float(unwrap(hpx.reduce(pol, a, 0.0, operator.add))) == 4950.0


@pytest.mark.parametrize("pol_idx", range(3))
def test_transform_reduce_saxpy_dot(pol_idx):
    # config #1 shape: dot(x, y) via binary transform_reduce
    pol = policies()[pol_idx]
    mk = jnp.asarray if pol_idx == 2 else np.asarray
    x = mk(RNG.random(256).astype(np.float32))
    y = mk(RNG.random(256).astype(np.float32))
    got = float(unwrap(hpx.transform_reduce(
        pol, x, 0.0, operator.add, operator.mul, rng2=y)))
    np.testing.assert_allclose(got, float(np.dot(np.asarray(x), np.asarray(y))),
                               rtol=1e-4)


def test_transform_reduce_unary():
    a = np.arange(10, dtype=np.float64)
    got = hpx.transform_reduce(hpx.par, a, 0.0, operator.add,
                               lambda x: x * x)
    assert float(got) == float((a * a).sum())


@pytest.mark.parametrize("pol_idx", range(3))
def test_count_and_queries(pol_idx):
    pol = policies()[pol_idx]
    mk = jnp.asarray if pol_idx == 2 else np.asarray
    a = mk(np.array([1, 2, 3, 2, 2, 5]))
    assert int(unwrap(hpx.count(pol, a, 2))) == 3
    assert int(unwrap(hpx.count_if(pol, a, lambda x: x > 2))) == 2
    assert unwrap(hpx.all_of(pol, a, lambda x: x > 0))
    assert unwrap(hpx.any_of(pol, a, lambda x: x == 5))
    assert unwrap(hpx.none_of(pol, a, lambda x: x > 10))


@pytest.mark.parametrize("pol_idx", range(3))
def test_minmax(pol_idx):
    pol = policies()[pol_idx]
    mk = jnp.asarray if pol_idx == 2 else np.asarray
    a = mk(np.array([5.0, -2.0, 9.0, 0.5]))
    assert float(unwrap(hpx.min_element(pol, a))) == -2.0
    assert float(unwrap(hpx.max_element(pol, a))) == 9.0
    mm = unwrap(hpx.minmax_element(pol, a))
    assert float(mm[0]) == -2.0 and float(mm[1]) == 9.0


@pytest.mark.parametrize("pol_idx", range(3))
def test_equal_mismatch_find(pol_idx):
    pol = policies()[pol_idx]
    mk = jnp.asarray if pol_idx == 2 else np.asarray
    a = mk(np.array([1, 2, 3, 4]))
    b = mk(np.array([1, 2, 9, 4]))
    assert unwrap(hpx.equal(pol, a, a))
    assert not unwrap(hpx.equal(pol, a, b))
    assert unwrap(hpx.mismatch(pol, a, b)) == 2
    assert unwrap(hpx.mismatch(pol, a, a)) == -1
    assert unwrap(hpx.find(pol, a, 3)) == 2
    assert unwrap(hpx.find(pol, a, 42)) == -1
    assert unwrap(hpx.find_if(pol, a, lambda x: x > 2)) == 2


# -- scans ------------------------------------------------------------------

@pytest.mark.parametrize("pol_idx", range(3))
def test_scans(pol_idx):
    pol = policies()[pol_idx]
    mk = jnp.asarray if pol_idx == 2 else np.asarray
    a = mk(np.arange(1, 9, dtype=np.float32))
    np.testing.assert_allclose(asnp(hpx.inclusive_scan(pol, a)),
                               np.cumsum(np.arange(1, 9)))
    np.testing.assert_allclose(
        asnp(hpx.exclusive_scan(pol, a, 0.0)),
        np.concatenate([[0], np.cumsum(np.arange(1, 9))[:-1]]))
    np.testing.assert_allclose(
        asnp(hpx.inclusive_scan(pol, a, 10.0)),
        10.0 + np.cumsum(np.arange(1, 9)))


def test_transform_scans():
    a = np.arange(1, 6, dtype=np.float64)
    np.testing.assert_allclose(
        asnp(hpx.transform_inclusive_scan(hpx.par, a, 0.0, operator.add,
                                          lambda x: x * x)),
        np.cumsum(a * a))
    d = hpx.transform_inclusive_scan(device_policy(), jnp.asarray(a), 0.0,
                                     operator.add, lambda x: x * x)
    np.testing.assert_allclose(asnp(d), np.cumsum(a * a))


@pytest.mark.parametrize("pol_idx", range(3))
def test_adjacent_difference_and_find(pol_idx):
    pol = policies()[pol_idx]
    mk = jnp.asarray if pol_idx == 2 else np.asarray
    a = mk(np.array([1, 4, 9, 16], dtype=np.float32))
    np.testing.assert_allclose(asnp(hpx.adjacent_difference(pol, a)),
                               [1, 3, 5, 7])
    b = mk(np.array([1, 2, 2, 3]))
    assert unwrap(hpx.adjacent_find(pol, b)) == 1
    c = mk(np.array([1, 2, 3, 4]))
    assert unwrap(hpx.adjacent_find(pol, c)) == -1


# -- sorting / order --------------------------------------------------------

@pytest.mark.parametrize("pol_idx", range(3))
def test_sort(pol_idx):
    pol = policies()[pol_idx]
    mk = jnp.asarray if pol_idx == 2 else np.asarray
    a = mk(RNG.permutation(64).astype(np.float32))
    np.testing.assert_array_equal(asnp(hpx.sort(pol, a)), np.arange(64))
    assert unwrap(hpx.is_sorted(pol, mk(np.arange(10))))
    assert not unwrap(hpx.is_sorted(pol, a))


def test_sort_with_key():
    a = np.array([3.0, -5.0, 1.0, -2.0])
    out = hpx.sort(hpx.par, a, key=abs)
    np.testing.assert_array_equal(asnp(out), [1.0, -2.0, 3.0, -5.0])


@pytest.mark.parametrize("pol_idx", range(3))
def test_merge_reverse_rotate(pol_idx):
    pol = policies()[pol_idx]
    mk = jnp.asarray if pol_idx == 2 else np.asarray
    a, b = mk(np.array([1, 3, 5])), mk(np.array([2, 4, 6]))
    np.testing.assert_array_equal(asnp(hpx.merge(pol, a, b)),
                                  [1, 2, 3, 4, 5, 6])
    np.testing.assert_array_equal(asnp(hpx.reverse(pol, a)), [5, 3, 1])
    np.testing.assert_array_equal(
        asnp(hpx.rotate(pol, mk(np.arange(6)), 2)), [2, 3, 4, 5, 0, 1])


@pytest.mark.parametrize("pol_idx", range(3))
def test_unique_partition(pol_idx):
    pol = policies()[pol_idx]
    mk = jnp.asarray if pol_idx == 2 else np.asarray
    a = mk(np.array([1, 1, 2, 2, 2, 3, 1]))
    np.testing.assert_array_equal(asnp(hpx.unique(pol, a)), [1, 2, 3, 1])
    arr, point = unwrap(hpx.partition(pol, mk(np.arange(10)),
                                      lambda x: x % 2 == 0))
    assert point == 5
    np.testing.assert_array_equal(np.asarray(arr)[:5], [0, 2, 4, 6, 8])
    np.testing.assert_array_equal(np.asarray(arr)[5:], [1, 3, 5, 7, 9])


# -- task policy ------------------------------------------------------------

def test_task_policy_returns_future_host_and_device():
    a = np.arange(1000, dtype=np.float64)
    f = hpx.reduce(hpx.par.task, a, 0.0, operator.add)
    assert isinstance(f, Future)
    assert float(f.get(timeout=30.0)) == float(a.sum())

    d = hpx.transform(device_policy().task, jnp.arange(8, dtype=jnp.float32),
                      lambda x: x + 1)
    assert isinstance(d, Future)
    np.testing.assert_allclose(asnp(d), np.arange(8) + 1)


def test_chunked_host_policy_with_params():
    a = np.arange(100, dtype=np.float64)
    pol = hpx.par.with_(hpx.static_chunk_size(7))
    assert float(unwrap(hpx.reduce(pol, a, 0.0, operator.add))) == float(a.sum())


def test_empty_ranges():
    assert float(unwrap(hpx.reduce(hpx.par, np.array([]), 5.0))) == 5.0
    np.testing.assert_array_equal(asnp(hpx.sort(hpx.par, np.array([]))), [])
    assert unwrap(hpx.find(hpx.par, np.array([]), 1)) == -1


# -- regressions from review ------------------------------------------------

def test_reduce_device_nonidentity_init():
    # regression: lax.reduce would apply init per tile
    got = hpx.reduce(device_policy(), jnp.arange(1, 9, dtype=jnp.float32),
                     10.0, operator.add)
    assert float(unwrap(got)) == 46.0


def test_exclusive_scan_device_mul_init():
    # regression: device scan assumed 0 is the op identity
    got = hpx.exclusive_scan(device_policy(),
                             jnp.array([2.0, 3.0, 4.0]), 1.0, operator.mul)
    np.testing.assert_allclose(asnp(got), [1.0, 2.0, 6.0])
    host = hpx.exclusive_scan(hpx.par, np.array([2.0, 3.0, 4.0]), 1.0,
                              operator.mul)
    np.testing.assert_allclose(asnp(host), [1.0, 2.0, 6.0])


def test_copy_preserves_bool_dtype():
    out = hpx.copy(device_policy(), jnp.array([True, False]))
    assert asnp(out).dtype == np.bool_


def test_kwdefault_lambdas_not_conflated():
    def make(s):
        return lambda x, *, k=s: x * k
    a = hpx.transform(device_policy(), jnp.arange(4, dtype=jnp.float32),
                      make(2.0))
    b = hpx.transform(device_policy(), jnp.arange(4, dtype=jnp.float32),
                      make(3.0))
    np.testing.assert_allclose(asnp(a), np.arange(4) * 2.0)
    np.testing.assert_allclose(asnp(b), np.arange(4) * 3.0)


def test_for_loop_host_collects_results():
    out = hpx.for_loop(hpx.par, 0, 8, lambda i: i * i)
    assert out == [i * i for i in range(8)]
    assert hpx.for_loop(hpx.par, 0, 4, lambda i: None) is None


def test_reduce_device_builtin_min_max():
    # regression: builtin min/max as reduce op on the device path
    a = jnp.array([5.0, -2.0, 9.0])
    assert float(unwrap(hpx.reduce(device_policy(), a, 100.0, min))) == -2.0
    assert float(unwrap(hpx.reduce(device_policy(), a, -100.0, max))) == 9.0


def test_host_scan_widens_dtype():
    out = hpx.inclusive_scan(hpx.seq, np.array([1, 2, 3]), 0.5)
    np.testing.assert_allclose(asnp(out), [1.5, 3.5, 6.5])


def test_exclusive_scan_empty_device():
    out = hpx.exclusive_scan(device_policy(), jnp.array([], dtype=jnp.float32))
    assert asnp(out).shape == (0,)


# -- for_loop induction/reduction clauses -------------------------------------

def test_for_loop_reduction_host():
    import operator
    total = hpx.for_loop(hpx.par, 0, 100, lambda i: i,
                         hpx.reduction(0, operator.add))
    assert total == sum(range(100))


def test_for_loop_reduction_device():
    import operator
    total = hpx.for_loop(device_policy(), 0, 100,
                         lambda i: (i * i).astype(jnp.float32),
                         hpx.reduction(jnp.float32(0), operator.add))
    assert float(unwrap(total)) == sum(i * i for i in range(100))


def test_for_loop_induction_both_paths():
    import operator
    # sum of (10 + 2*j) for j in 0..9, via the induction clause
    want = sum(10 + 2 * j for j in range(10))
    got_h = hpx.for_loop(hpx.par, 5, 15, lambda i, x: x,
                         hpx.induction(10, 2),
                         hpx.reduction(0, operator.add))
    assert got_h == want
    got_d = hpx.for_loop(device_policy(), 5, 15,
                         lambda i, x: x.astype(jnp.float32),
                         hpx.induction(10, 2),
                         hpx.reduction(jnp.float32(0), operator.add))
    assert float(unwrap(got_d)) == want


def test_for_loop_multiple_reductions():
    import operator
    s, p = hpx.for_loop(hpx.par, 1, 6, lambda i: (i, i),
                        hpx.reduction(0, operator.add),
                        hpx.reduction(1, operator.mul))
    assert (s, p) == (15, 120)


def test_for_loop_empty_range_returns_identity():
    import operator
    assert hpx.for_loop(hpx.par, 3, 3, lambda i: i,
                        hpx.reduction(7, operator.add)) == 7


def test_for_loop_bad_clause_raises():
    import pytest as _pt
    with _pt.raises(hpx.HpxError):
        hpx.for_loop(hpx.par, 0, 3, lambda i: i, "not-a-clause")


# -- round-5 std additions ---------------------------------------------------

@pytest.mark.parametrize("pol_idx", range(3))
def test_remove_and_remove_if(pol_idx):
    from hpx_tpu.algo import remove, remove_if
    pol = policies()[pol_idx]
    mk = (lambda a: jnp.asarray(a)) if pol_idx == 2 else \
        (lambda a: np.asarray(a))
    data = mk(np.array([3, 1, 3, 4, 3, 5], np.int32))
    out = asnp(unwrap(remove(pol, data, 3)))
    np.testing.assert_array_equal(out, [1, 4, 5])
    out2 = asnp(unwrap(remove_if(pol, data, lambda x: x > 3)))
    np.testing.assert_array_equal(out2, [3, 1, 3, 3])


@pytest.mark.parametrize("pol_idx", range(3))
def test_replace_and_replace_if(pol_idx):
    from hpx_tpu.algo import replace, replace_if
    pol = policies()[pol_idx]
    mk = (lambda a: jnp.asarray(a)) if pol_idx == 2 else \
        (lambda a: np.asarray(a))
    # fresh array per call: the host path mutates in place (std
    # semantics, like fill/for_each)
    np.testing.assert_array_equal(
        asnp(unwrap(replace(pol, mk(np.array([3, 1, 3, 4], np.int32)),
                            3, 9))), [9, 1, 9, 4])
    np.testing.assert_array_equal(
        asnp(unwrap(replace_if(pol, mk(np.array([3, 1, 3, 4], np.int32)),
                               lambda x: x < 3, 0))),
        [3, 0, 3, 4])


@pytest.mark.parametrize("pol_idx", range(3))
def test_is_sorted_until_and_is_partitioned(pol_idx):
    from hpx_tpu.algo import is_partitioned, is_sorted_until
    pol = policies()[pol_idx]
    mk = (lambda a: jnp.asarray(a)) if pol_idx == 2 else \
        (lambda a: np.asarray(a))
    assert unwrap(is_sorted_until(pol, mk(
        np.array([1, 2, 5, 3, 4], np.int32)))) == 3
    assert unwrap(is_sorted_until(pol, mk(
        np.array([1, 2, 3], np.int32)))) == 3
    assert unwrap(is_partitioned(
        pol, mk(np.array([2, 4, 1, 3], np.int32)),
        lambda x: x % 2 == 0)) is True
    assert unwrap(is_partitioned(
        pol, mk(np.array([2, 1, 4], np.int32)),
        lambda x: x % 2 == 0)) is False


@pytest.mark.parametrize("pol_idx", range(3))
def test_lexicographical_compare(pol_idx):
    from hpx_tpu.algo import lexicographical_compare as lc
    pol = policies()[pol_idx]
    mk = (lambda a: jnp.asarray(a)) if pol_idx == 2 else \
        (lambda a: np.asarray(a))
    assert unwrap(lc(pol, mk(np.array([1, 2, 3])),
                     mk(np.array([1, 2, 4])))) is True
    assert unwrap(lc(pol, mk(np.array([1, 2, 4])),
                     mk(np.array([1, 2, 3])))) is False
    # equal prefix: the shorter range is the lesser
    assert unwrap(lc(pol, mk(np.array([1, 2])),
                     mk(np.array([1, 2, 0])))) is True
    assert unwrap(lc(pol, mk(np.array([1, 2])),
                     mk(np.array([1, 2])))) is False


@pytest.mark.parametrize("pol_idx", range(3))
def test_find_first_of(pol_idx):
    from hpx_tpu.algo import find_first_of
    pol = policies()[pol_idx]
    mk = (lambda a: jnp.asarray(a)) if pol_idx == 2 else \
        (lambda a: np.asarray(a))
    a = mk(np.array([7, 8, 2, 9], np.int32))
    assert unwrap(find_first_of(pol, a, mk(np.array([9, 2])))) == 2
    assert unwrap(find_first_of(pol, a, mk(np.array([5, 6])))) == -1


@pytest.mark.parametrize("pol_idx", range(3))
def test_new_queries_empty_and_single(pol_idx):
    """Edge shapes: empty and single-element ranges (static-shape
    guards in the device kernels — review regression)."""
    from hpx_tpu.algo import (find_first_of, is_sorted_until,
                              lexicographical_compare)
    pol = policies()[pol_idx]
    mk = (lambda a: jnp.asarray(a)) if pol_idx == 2 else \
        (lambda a: np.asarray(a))
    e = mk(np.array([], np.int32))
    one = mk(np.array([7], np.int32))
    assert unwrap(is_sorted_until(pol, e)) == 0
    assert unwrap(is_sorted_until(pol, one)) == 1
    assert unwrap(lexicographical_compare(pol, e, one)) is True
    assert unwrap(lexicographical_compare(pol, one, e)) is False
    assert unwrap(lexicographical_compare(pol, e, e)) is False
    assert unwrap(find_first_of(pol, e, one)) == -1
    assert unwrap(find_first_of(pol, one, e)) == -1


def test_replace_if_mutates_host_array_in_place():
    import hpx_tpu as hpx
    from hpx_tpu.algo import replace_if
    a = np.array([1, 2, 3, 4], np.int32)
    out = replace_if(hpx.seq, a, lambda x: x % 2 == 0, 0)
    np.testing.assert_array_equal(a, [1, 0, 3, 0])   # in place
    assert out is a


# -- round-5 batch 2: search family, set ops, selection, shifts --------------

@pytest.mark.parametrize("pol_idx", range(3))
def test_search_and_find_end(pol_idx):
    from hpx_tpu.algo import find_end, search
    pol = policies()[pol_idx]
    mk = (lambda a: jnp.asarray(a)) if pol_idx == 2 else \
        (lambda a: np.asarray(a))
    hay = mk(np.array([1, 2, 3, 1, 2, 3, 4], np.int32))
    assert unwrap(search(pol, hay, mk(np.array([2, 3], np.int32)))) == 1
    assert unwrap(find_end(pol, hay, mk(np.array([2, 3], np.int32)))) == 4
    assert unwrap(search(pol, hay, mk(np.array([3, 1], np.int32)))) == 2
    assert unwrap(search(pol, hay, mk(np.array([9], np.int32)))) == -1
    assert unwrap(find_end(pol, hay, mk(np.array([9], np.int32)))) == -1
    # empty needle: first match at 0, last at len
    assert unwrap(search(pol, hay, mk(np.array([], np.int32)))) == 0
    assert unwrap(find_end(pol, hay, mk(np.array([], np.int32)))) == 7
    # needle longer than haystack
    assert unwrap(search(pol, mk(np.array([1], np.int32)),
                         mk(np.array([1, 2], np.int32)))) == -1


@pytest.mark.parametrize("pol_idx", range(3))
def test_search_n(pol_idx):
    from hpx_tpu.algo import search_n
    pol = policies()[pol_idx]
    mk = (lambda a: jnp.asarray(a)) if pol_idx == 2 else \
        (lambda a: np.asarray(a))
    data = mk(np.array([5, 7, 7, 5, 7, 7, 7, 2], np.int32))
    assert unwrap(search_n(pol, data, 2, 7)) == 1
    assert unwrap(search_n(pol, data, 3, 7)) == 4
    assert unwrap(search_n(pol, data, 4, 7)) == -1
    assert unwrap(search_n(pol, data, 1, 2)) == 7
    assert unwrap(search_n(pol, data, 0, 9)) == 0
    assert unwrap(search_n(pol, data, -2, 9)) == 0  # count <= 0: first pos


@pytest.mark.parametrize("pol_idx", range(3))
def test_contains_family(pol_idx):
    from hpx_tpu.algo import (
        contains, contains_subrange, ends_with, starts_with)
    pol = policies()[pol_idx]
    mk = (lambda a: jnp.asarray(a)) if pol_idx == 2 else \
        (lambda a: np.asarray(a))
    data = mk(np.array([4, 8, 15, 16, 23, 42], np.int32))
    assert unwrap(contains(pol, data, 15)) is True
    assert unwrap(contains(pol, data, 17)) is False
    assert unwrap(contains_subrange(
        pol, data, mk(np.array([15, 16], np.int32)))) is True
    assert unwrap(contains_subrange(
        pol, data, mk(np.array([16, 15], np.int32)))) is False
    assert unwrap(starts_with(
        pol, data, mk(np.array([4, 8], np.int32)))) is True
    assert unwrap(starts_with(
        pol, data, mk(np.array([8], np.int32)))) is False
    assert unwrap(ends_with(
        pol, data, mk(np.array([23, 42], np.int32)))) is True
    assert unwrap(ends_with(
        pol, data, mk(np.array([23], np.int32)))) is False


@pytest.mark.parametrize("pol_idx", range(3))
def test_set_operations_multiset_semantics(pol_idx):
    from hpx_tpu.algo import (
        set_difference, set_intersection, set_symmetric_difference,
        set_union)
    pol = policies()[pol_idx]
    mk = (lambda a: jnp.asarray(a)) if pol_idx == 2 else \
        (lambda a: np.asarray(a))
    # multiplicities: a has {1:2, 2:1, 5:3}; b has {1:1, 2:2, 7:1}
    a = np.array([1, 1, 2, 5, 5, 5], np.int32)
    b = np.array([1, 2, 2, 7], np.int32)
    # union: max(m, n) of each
    np.testing.assert_array_equal(
        asnp(unwrap(set_union(pol, mk(a), mk(b)))),
        [1, 1, 2, 2, 5, 5, 5, 7])
    # intersection: min(m, n)
    np.testing.assert_array_equal(
        asnp(unwrap(set_intersection(pol, mk(a), mk(b)))), [1, 2])
    # difference: max(m - n, 0)
    np.testing.assert_array_equal(
        asnp(unwrap(set_difference(pol, mk(a), mk(b)))), [1, 5, 5, 5])
    np.testing.assert_array_equal(
        asnp(unwrap(set_difference(pol, mk(b), mk(a)))), [2, 7])
    # symmetric difference: |m - n|
    np.testing.assert_array_equal(
        asnp(unwrap(set_symmetric_difference(pol, mk(a), mk(b)))),
        [1, 2, 5, 5, 5, 7])
    # empty edge
    np.testing.assert_array_equal(
        asnp(unwrap(set_union(pol, mk(a[:0]), mk(b)))), b)


@pytest.mark.parametrize("pol_idx", range(3))
def test_includes(pol_idx):
    from hpx_tpu.algo import includes
    pol = policies()[pol_idx]
    mk = (lambda a: jnp.asarray(a)) if pol_idx == 2 else \
        (lambda a: np.asarray(a))
    a = mk(np.array([1, 1, 2, 3, 5, 8], np.int32))
    assert unwrap(includes(pol, a, mk(np.array([1, 3, 8], np.int32)))) \
        is True
    assert unwrap(includes(pol, a, mk(np.array([1, 1], np.int32)))) is True
    # multiplicity matters: three 1s are not included in two
    assert unwrap(includes(
        pol, a, mk(np.array([1, 1, 1], np.int32)))) is False
    assert unwrap(includes(pol, a, mk(np.array([4], np.int32)))) is False
    assert unwrap(includes(pol, a, mk(np.array([], np.int32)))) is True


@pytest.mark.parametrize("pol_idx", range(3))
def test_partial_sort_and_nth_element(pol_idx):
    from hpx_tpu.algo import nth_element, partial_sort
    pol = policies()[pol_idx]
    mk = (lambda a: jnp.asarray(a)) if pol_idx == 2 else \
        (lambda a: np.asarray(a))
    data = np.array([9, 1, 8, 2, 7, 3, 6], np.int32)
    out = asnp(unwrap(partial_sort(pol, mk(data), 3)))
    np.testing.assert_array_equal(out[:3], [1, 2, 3])
    assert sorted(out.tolist()) == sorted(data.tolist())
    out2 = asnp(unwrap(nth_element(pol, mk(data), 3)))
    assert out2[3] == np.sort(data)[3]
    assert (out2[:3] <= out2[3]).all() and (out2[4:] >= out2[3]).all()


@pytest.mark.parametrize("pol_idx", range(3))
def test_partial_sort_copy(pol_idx):
    from hpx_tpu.algo import partial_sort_copy
    pol = policies()[pol_idx]
    mk = (lambda a: jnp.asarray(a)) if pol_idx == 2 else \
        (lambda a: np.asarray(a))
    data = np.array([9.0, -1.5, 8.0, 2.0, 7.0], np.float32)
    np.testing.assert_allclose(
        asnp(unwrap(partial_sort_copy(pol, mk(data), 3))),
        [-1.5, 2.0, 7.0])
    # k > len clamps to a full sort; k == 0 is empty
    np.testing.assert_allclose(
        asnp(unwrap(partial_sort_copy(pol, mk(data), 99))),
        np.sort(data))
    assert len(asnp(unwrap(partial_sort_copy(pol, mk(data), 0)))) == 0
    # unsigned dtype takes the sort path (negation would wrap)
    np.testing.assert_array_equal(
        asnp(unwrap(partial_sort_copy(
            pol, mk(np.array([3, 1, 2], np.uint32)), 2))), [1, 2])


@pytest.mark.parametrize("pol_idx", range(3))
def test_shift_left_right(pol_idx):
    from hpx_tpu.algo import shift_left, shift_right
    pol = policies()[pol_idx]
    mk = (lambda a: jnp.asarray(a)) if pol_idx == 2 else \
        (lambda a: np.asarray(a))
    data = np.array([1, 2, 3, 4, 5], np.int32)
    out = asnp(unwrap(shift_left(pol, mk(data), 2)))
    np.testing.assert_array_equal(out[:3], [3, 4, 5])
    out2 = asnp(unwrap(shift_right(pol, mk(data), 2)))
    np.testing.assert_array_equal(out2[2:], [1, 2, 3])
    # n == 0 and n >= len are identity-shaped
    np.testing.assert_array_equal(
        asnp(unwrap(shift_left(pol, mk(data), 0))), data)
    np.testing.assert_array_equal(
        asnp(unwrap(shift_left(pol, mk(data), 9))), data)


@pytest.mark.parametrize("pol_idx", range(3))
def test_swap_ranges_and_partition_copy(pol_idx):
    from hpx_tpu.algo import partition_copy, swap_ranges
    pol = policies()[pol_idx]
    mk = (lambda a: jnp.asarray(a)) if pol_idx == 2 else \
        (lambda a: np.asarray(a))
    a = np.array([1, 2, 3], np.int32)
    b = np.array([4, 5, 6], np.int32)
    na, nb = unwrap(swap_ranges(pol, mk(a), mk(b)))
    np.testing.assert_array_equal(asnp(na), b)
    np.testing.assert_array_equal(asnp(nb), a)
    with pytest.raises(ValueError):
        swap_ranges(pol, mk(a), mk(b[:2]))
    t, f = unwrap(partition_copy(
        pol, mk(np.array([1, 2, 3, 4, 5], np.int32)),
        lambda x: x % 2 == 1))
    np.testing.assert_array_equal(asnp(t), [1, 3, 5])
    np.testing.assert_array_equal(asnp(f), [2, 4])


def test_functional_copy_aliases():
    from hpx_tpu import algo
    assert algo.unique_copy is algo.unique
    assert algo.remove_copy is algo.remove
    assert algo.remove_copy_if is algo.remove_if
    assert algo.move is algo.copy
    # replace_copy is NOT an alias: replace mutates on the host path
    # (std semantics), so the _copy form must be a copy-first wrapper
    assert algo.replace_copy is not algo.replace
    assert algo.replace_copy_if is not algo.replace_if


@pytest.mark.parametrize("pol_idx", range(3))
def test_replace_copy_preserves_input(pol_idx):
    from hpx_tpu.algo import replace_copy, replace_copy_if
    pol = policies()[pol_idx]
    mk = (lambda a: jnp.asarray(a)) if pol_idx == 2 else \
        (lambda a: np.asarray(a))
    src = mk(np.array([1, 2, 3, 2], np.int32))
    out = asnp(unwrap(replace_copy(pol, src, 2, 0)))
    np.testing.assert_array_equal(out, [1, 0, 3, 0])
    np.testing.assert_array_equal(asnp(src), [1, 2, 3, 2])  # untouched
    out2 = asnp(unwrap(replace_copy_if(pol, src, lambda x: x > 2, 9)))
    np.testing.assert_array_equal(out2, [1, 2, 9, 2])
    np.testing.assert_array_equal(asnp(src), [1, 2, 3, 2])


@pytest.mark.parametrize("pol_idx", range(3))
def test_partition_copy_empty_and_int_min_selection(pol_idx):
    from hpx_tpu.algo import partial_sort_copy, partition_copy
    pol = policies()[pol_idx]
    mk = (lambda a: jnp.asarray(a)) if pol_idx == 2 else \
        (lambda a: np.asarray(a))
    t, f = unwrap(partition_copy(pol, mk(np.array([], np.int32)),
                                 lambda x: x > 0))
    assert len(asnp(t)) == 0 and len(asnp(f)) == 0
    # INT_MIN must survive k-smallest selection (negation wraps)
    imin = np.iinfo(np.int32).min
    np.testing.assert_array_equal(
        asnp(unwrap(partial_sort_copy(
            pol, mk(np.array([imin, 5, 3], np.int32)), 2))), [imin, 3])


@pytest.mark.parametrize("pol_idx", range(3))
def test_reduce_by_key(pol_idx):
    from hpx_tpu.algo import reduce_by_key
    pol = policies()[pol_idx]
    mk = (lambda a: jnp.asarray(a)) if pol_idx == 2 else \
        (lambda a: np.asarray(a))
    ks = mk(np.array([1, 1, 2, 2, 2, 1, 3], np.int32))
    vs = mk(np.array([1., 2., 3., 4., 5., 6., 7.], np.float32))
    uk, rv = unwrap(reduce_by_key(pol, ks, vs))
    np.testing.assert_array_equal(asnp(uk), [1, 2, 1, 3])  # runs, not groups
    np.testing.assert_allclose(asnp(rv), [3., 12., 6., 7.])
    # generic path: an associative op that is not in the known-fold
    # table (a lambda misses the operator.add identity lookup)
    uk2, rv2 = unwrap(reduce_by_key(pol, ks, vs, op=lambda a, b: a + b))
    np.testing.assert_allclose(asnp(rv2), [3., 12., 6., 7.])
    # single run and empty
    uk3, rv3 = unwrap(reduce_by_key(pol, mk(np.array([9, 9], np.int32)),
                                    mk(np.array([2., 8.], np.float32))))
    np.testing.assert_array_equal(asnp(uk3), [9])
    np.testing.assert_allclose(asnp(rv3), [10.])
    uk4, rv4 = unwrap(reduce_by_key(pol, mk(np.array([], np.int32)),
                                    mk(np.array([], np.float32))))
    assert len(asnp(uk4)) == 0 and len(asnp(rv4)) == 0


@pytest.mark.parametrize("pol_idx", range(3))
def test_is_heap_and_until(pol_idx):
    from hpx_tpu.algo import is_heap, is_heap_until
    pol = policies()[pol_idx]
    mk = (lambda a: jnp.asarray(a)) if pol_idx == 2 else \
        (lambda a: np.asarray(a))
    heap = mk(np.array([9, 5, 8, 1, 2, 7], np.int32))
    assert unwrap(is_heap(pol, heap)) is True
    assert unwrap(is_heap_until(pol, heap)) == 6
    broken = mk(np.array([9, 5, 8, 6, 2, 7], np.int32))   # 6 > 5
    assert unwrap(is_heap(pol, broken)) is False
    assert unwrap(is_heap_until(pol, broken)) == 3
    assert unwrap(is_heap(pol, mk(np.array([4], np.int32)))) is True
    assert unwrap(is_heap_until(pol, mk(np.array([], np.int32)))) == 0
