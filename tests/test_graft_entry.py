"""Guard the driver entry points (__graft_entry__.py).

Round-1 regression: the driver ran ``dryrun_multichip(8)`` in an
environment with ONE visible device and the entry point died instead of
provisioning the virtual CPU mesh itself (MULTICHIP_r01.json ok:false).
These tests run the entry points the way the driver does — a fresh
subprocess whose environment does NOT pre-provision the mesh — so the
self-provisioning re-exec path is exercised end to end.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _driver_like_env():
    """Env resembling the driver's: no virtual-mesh XLA flag."""
    sys.path.insert(0, REPO)
    from __graft_entry__ import _strip_device_count_flag
    env = dict(os.environ)
    env.pop("_HPX_TPU_DRYRUN_CHILD", None)
    flags = _strip_device_count_flag(env.get("XLA_FLAGS", ""))
    if flags:
        env["XLA_FLAGS"] = flags
    else:
        env.pop("XLA_FLAGS", None)
    return env


def _run(code, timeout=900):
    return subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, env=_driver_like_env(),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        timeout=timeout)


def test_entry_compiles_and_runs():
    proc = _run(
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from __graft_entry__ import entry\n"
        "fn, args = entry()\n"
        "out = jax.jit(fn)(*args)\n"
        "jax.block_until_ready(out)\n"
        "print('ENTRY_OK', out.shape)\n")
    assert proc.returncode == 0, proc.stdout
    assert "ENTRY_OK" in proc.stdout, proc.stdout


@pytest.mark.parametrize("n", [2, 8])
def test_dryrun_multichip_self_provisions(n):
    # The child process sees 1 CPU device (no forced device count), so
    # dryrun_multichip MUST re-exec itself with a provisioned mesh.
    proc = _run(
        "from __graft_entry__ import dryrun_multichip\n"
        f"dryrun_multichip({n})\n")
    assert proc.returncode == 0, proc.stdout
    assert f"dryrun_multichip({n}): ok" in proc.stdout, proc.stdout
    assert "transformer train step" in proc.stdout, proc.stdout
    assert "MoE train step" in proc.stdout, proc.stdout
