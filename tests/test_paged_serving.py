"""Paged serving (ContinuousServer(paged=True)): the block-pool +
radix-prefix-reuse decode path must be BYTE-IDENTICAL to the dense
slot-cache path — same tokens for every request, greedy and sampled,
with or without shared prefixes — while actually reusing cached
prefix blocks (nonzero hit rate, prefill tokens saved)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hpx_tpu.models import transformer as tfm
from hpx_tpu.models.serving import ContinuousServer

CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4, head_dim=8,
                            n_layers=2, d_ff=64)
GQA_ROPE = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                 head_dim=8, n_layers=2, d_ff=64,
                                 n_kv_heads=2, rope=True)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.PRNGKey(0))


def _ref(params, cfg, prompt, max_new, eos_id=None):
    out = tfm.generate(params, cfg,
                       jnp.asarray([prompt], jnp.int32),
                       max_new=max_new, eos_id=eos_id)
    return [int(t) for t in np.asarray(out)[0]]


def _run_both(params, cfg, reqs, smax=64, slots=3, **paged_kw):
    """Submit the same mix to a dense and a paged server; returns
    ({rid: tokens} dense, {rid: tokens} paged, paged server). rids
    align because submission order is identical."""
    dense = ContinuousServer(params, cfg, slots=slots, smax=smax)
    paged = ContinuousServer(params, cfg, slots=slots, smax=smax,
                             paged=True, **paged_kw)
    for srv in (dense, paged):
        for r in reqs:
            srv.submit(**r)
    return dense.run(), paged.run(), paged


# -- equivalence -------------------------------------------------------------

def test_greedy_matches_dense_and_generate(params):
    reqs = [dict(prompt=[3, 1, 4], max_new=9),
            dict(prompt=[2, 7], max_new=5),
            dict(prompt=[5, 6, 7, 8, 9], max_new=12),
            dict(prompt=[1], max_new=7),
            dict(prompt=[9, 9, 2, 1], max_new=3),
            dict(prompt=[4, 4], max_new=10)]
    outd, outp, _ = _run_both(params, CFG, reqs)
    assert outd == outp
    for rid, r in enumerate(reqs):
        assert outp[rid] == _ref(params, CFG, r["prompt"], r["max_new"])


def test_sampled_matches_dense(params):
    """temperature > 0: the per-(position, row) fold_in sampling
    contract must survive the paged rewrite bit-for-bit."""
    reqs = [dict(prompt=[3, 1, 4], max_new=8, temperature=0.9,
                 key=jax.random.PRNGKey(7)),
            dict(prompt=[2, 7, 9], max_new=8, temperature=0.7,
                 key=jax.random.PRNGKey(8)),
            dict(prompt=[5, 5], max_new=6, temperature=1.3,
                 key=jax.random.PRNGKey(9))]
    outd, outp, _ = _run_both(params, CFG, reqs, slots=2)
    assert outd == outp


def test_gqa_rope_matches_dense():
    params = tfm.init_params(GQA_ROPE, jax.random.PRNGKey(5))
    reqs = [dict(prompt=[3, 1, 4, 1, 5], max_new=7),
            dict(prompt=[2, 7], max_new=5),
            dict(prompt=[1, 2, 3], max_new=6)]
    outd, outp, _ = _run_both(params, GQA_ROPE, reqs, smax=48, slots=2)
    assert outd == outp


def test_eos_matches_dense(params):
    probe = _ref(params, CFG, [3, 1, 4], 9)
    eos = probe[3]
    reqs = [dict(prompt=[3, 1, 4], max_new=9, eos_id=eos),
            dict(prompt=[2, 7], max_new=5)]
    outd, outp, _ = _run_both(params, CFG, reqs, slots=2)
    assert outd == outp
    assert outp[0] == _ref(params, CFG, [3, 1, 4], 9, eos_id=eos)


# -- prefix reuse ------------------------------------------------------------

def test_shared_prefix_hits_and_stays_identical(params):
    """Requests sharing a 2-block prefix: later admissions must match
    the published chain (saved prefill tokens) and still emit exactly
    the dense tokens."""
    pre = list(range(1, 33))                    # 32 = 2 blocks of 16
    reqs = [dict(prompt=pre + [40, 41], max_new=6),
            dict(prompt=pre + [50], max_new=6),
            dict(prompt=pre + [60, 61, 62], max_new=6)]
    outd, outp, srv = _run_both(params, CFG, reqs, slots=2)
    assert outd == outp
    st = srv.cache_stats()
    assert st["tokens_matched"] >= 32           # later reqs reused pre
    assert st["hit_rate"] > 0
    assert st["prefill_tokens_saved"] >= 32
    # conservation: every prompt position was either reused or computed
    total_prompt = sum(len(r["prompt"]) for r in reqs)
    assert (st["prefill_tokens_saved"]
            + st["prefill_tokens_computed"]) == total_prompt


def test_disjoint_prefixes_no_false_sharing(params):
    """Unrelated prompts must never match each other's chains — zero
    matched tokens, identical output."""
    reqs = [dict(prompt=[10 + i] * 20, max_new=5) for i in range(4)]
    outd, outp, srv = _run_both(params, CFG, reqs, slots=2)
    assert outd == outp
    assert srv.cache_stats()["tokens_matched"] == 0


def test_prefix_reuse_off_is_still_identical(params):
    pre = list(range(1, 33))
    reqs = [dict(prompt=pre + [40], max_new=5),
            dict(prompt=pre + [50], max_new=5)]
    outd, outp, srv = _run_both(params, CFG, reqs, slots=2,
                                prefix_reuse=False)
    assert outd == outp
    assert srv.cache_stats()["tokens_matched"] == 0
    assert srv.cache_stats()["prefill_tokens_saved"] == 0


def test_oom_evicts_and_recovers(params):
    """A pool with barely more than live demand: retained radix chains
    must be evicted on OOM and serving must complete correctly."""
    # smax=32 -> 2 blocks/seq; 2 slots live demand = 4 blocks; +trash.
    # 6 blocks leaves one spare for radix retention -> guaranteed OOM
    # churn across 6 sequential requests.
    reqs = [dict(prompt=[10 + i] * 20, max_new=5) for i in range(6)]
    outd, outp, srv = _run_both(params, CFG, reqs, smax=32, slots=2,
                                num_blocks=6)
    assert outd == outp
    st = srv.cache_stats()
    assert st["total_evictions"] > 0            # the retry path ran
    assert st["in_use"] <= 6


# -- construction contracts --------------------------------------------------

def test_paged_mesh_gate_restores_refusal(params):
    """paged=True + mesh= is SUPPORTED now (see
    test_sharded_paged_serving.py); hpx.serving.mesh.paged=0 is the
    operational escape hatch back to the old single-device refusal —
    it must fire before the mesh is even inspected."""
    from hpx_tpu.core.config import runtime_config
    rc = runtime_config()
    rc.set("hpx.serving.mesh.paged", "0")
    try:
        with pytest.raises(ValueError, match="mesh.paged"):
            ContinuousServer(params, CFG, slots=2, smax=64, paged=True,
                             mesh=object())
    finally:
        rc.set("hpx.serving.mesh.paged", "1")


def test_paged_rejects_misaligned_smax(params):
    with pytest.raises(ValueError, match="divisible"):
        ContinuousServer(params, CFG, slots=2, smax=50, paged=True,
                         block_size=16)


def test_paged_rejects_undersized_pool(params):
    # smax=64/bs=16 -> 4 blocks/seq; 4 (one request) + trash = 5 min
    with pytest.raises(ValueError, match="num_blocks"):
        ContinuousServer(params, CFG, slots=2, smax=64, paged=True,
                         num_blocks=4)


def test_dense_rejects_cache_stats(params):
    srv = ContinuousServer(params, CFG, slots=2, smax=64)
    with pytest.raises(ValueError, match="paged=True"):
        srv.cache_stats()


# -- instant retirement (admission re-scan) ----------------------------------

def test_one_token_burst_drains_without_decode_steps(params):
    """max_new == 1 requests retire during admission; the re-scan
    drains a whole burst through the slots in a single step() call
    with no decode dispatch at all."""
    srv = ContinuousServer(params, CFG, slots=2, smax=64, paged=True)
    reqs = {srv.submit([3 + i, 1, 4], max_new=1): [3 + i, 1, 4]
            for i in range(5)}
    steps = 0
    while srv.step():
        steps += 1
    assert steps == 0                 # first call admits+retires all
    out, srv._done = srv._done, {}
    for rid, p in reqs.items():
        assert out[rid] == _ref(params, CFG, p, 1)


def test_counters_registered_and_queryable(params):
    from hpx_tpu.svc import performance_counters as pc
    srv = ContinuousServer(params, CFG, slots=2, smax=64, paged=True)
    inst = srv.counter_instance
    srv.submit([3, 1, 4], max_new=4)
    srv.run()
    hit = pc.query_counter(
        pc.counter_name("cache", "hit-rate", inst)).value
    assert hit == srv._radix.hit_rate()
    used = pc.query_counter(
        pc.counter_name("cache", "blocks/in-use", inst)).value
    assert used == srv._alloc.in_use
    rate = pc.query_counter(
        pc.counter_name("serving", "tokens/rate", inst)).value
    assert rate > 0                   # 3 decode tokens inside the window
    # a collected server reads 0 and its names vanish on refresh
    name = pc.counter_name("cache", "blocks/in-use", inst)
    del srv
    import gc
    gc.collect()
    assert name not in pc.discover_counters("/cache{locality#*/*}/*")
