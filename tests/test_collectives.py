"""Collectives (M7): host-plane communicator semantics in-process (sites
as distinct Communicator instances), device-plane collectives on the
8-device CPU mesh, channels, distributed latch.

Reference analog: libs/full/collectives/tests/unit/*.cpp — per-verb
tests over num_sites participants.
"""

import operator

import numpy as np
import pytest

import hpx_tpu as hpx
from hpx_tpu.collectives import (
    all_gather, all_reduce, all_to_all, barrier, broadcast,
    exclusive_scan, gather, inclusive_scan, reduce, scatter,
)
from hpx_tpu.collectives import device as dev
from hpx_tpu.testing import HPX_TEST, HPX_TEST_EQ

N = 4


def comms(basename, n=N):
    return [hpx.create_communicator(basename, num_sites=n, this_site=i)
            for i in range(n)]


class TestCommunicator:
    def test_all_reduce(self):
        cs = comms("t_allreduce")
        futs = [all_reduce(c, i + 1) for i, c in enumerate(cs)]
        for f in futs:
            HPX_TEST_EQ(f.get(timeout=10.0), sum(range(1, N + 1)))

    def test_all_reduce_custom_op(self):
        cs = comms("t_allreduce_max")
        futs = [all_reduce(c, i * 7 % 5, op=max) for i, c in enumerate(cs)]
        expect = max(i * 7 % 5 for i in range(N))
        for f in futs:
            HPX_TEST_EQ(f.get(timeout=10.0), expect)

    def test_reduce_root_only(self):
        cs = comms("t_reduce")
        futs = [reduce(c, i + 1, root=2) for i, c in enumerate(cs)]
        results = [f.get(timeout=10.0) for f in futs]
        HPX_TEST_EQ(results[2], sum(range(1, N + 1)))
        for i in (0, 1, 3):
            HPX_TEST(results[i] is None)

    def test_all_gather(self):
        cs = comms("t_allgather")
        futs = [all_gather(c, f"s{i}") for i, c in enumerate(cs)]
        for f in futs:
            HPX_TEST_EQ(f.get(timeout=10.0), [f"s{i}" for i in range(N)])

    def test_gather(self):
        cs = comms("t_gather")
        futs = [gather(c, i * i, root=0) for i, c in enumerate(cs)]
        results = [f.get(timeout=10.0) for f in futs]
        HPX_TEST_EQ(results[0], [i * i for i in range(N)])
        assert all(r is None for r in results[1:])

    def test_broadcast(self):
        cs = comms("t_bcast")
        futs = [broadcast(c, "payload" if i == 1 else None, root=1)
                for i, c in enumerate(cs)]
        for f in futs:
            HPX_TEST_EQ(f.get(timeout=10.0), "payload")

    def test_scatter(self):
        cs = comms("t_scatter")
        parts = [f"part{i}" for i in range(N)]
        futs = [scatter(c, parts if i == 0 else None, root=0)
                for i, c in enumerate(cs)]
        for i, f in enumerate(futs):
            HPX_TEST_EQ(f.get(timeout=10.0), f"part{i}")

    def test_scatter_wrong_arity_raises_everywhere(self):
        cs = comms("t_scatter_bad")
        futs = [scatter(c, ["only", "three", "parts"] if i == 0 else None)
                for i, c in enumerate(cs)]
        for f in futs:
            with pytest.raises(ValueError):
                f.get(timeout=10.0)

    def test_all_to_all(self):
        cs = comms("t_a2a")
        futs = [all_to_all(c, [(i, j) for j in range(N)])
                for i, c in enumerate(cs)]
        for i, f in enumerate(futs):
            HPX_TEST_EQ(f.get(timeout=10.0), [(j, i) for j in range(N)])

    def test_scans(self):
        cs = comms("t_scan")
        inc = [inclusive_scan(c, i + 1) for i, c in enumerate(cs)]
        exc = [exclusive_scan(c, i + 1) for i, c in enumerate(cs)]
        got_inc = [f.get(timeout=10.0) for f in inc]
        got_exc = [f.get(timeout=10.0) for f in exc]
        HPX_TEST_EQ(got_inc, [1, 3, 6, 10])
        HPX_TEST(got_exc[0] is None)
        HPX_TEST_EQ(got_exc[1:], [1, 3, 6])

    def test_barrier(self):
        cs = comms("t_barrier")
        futs = [barrier(c) for c in cs[:-1]]
        HPX_TEST(not any(f.is_ready() for f in futs))
        last = barrier(cs[-1])
        for f in futs + [last]:
            HPX_TEST(f.get(timeout=10.0))

    def test_explicit_generation_fast_forwards_implicit(self):
        # regression: an explicit generation must advance the implicit
        # counter, or the next implicit round collides and hangs
        cs = comms("t_gen_explicit")
        r1 = [all_reduce(c, 1, generation=0) for c in cs]
        r2 = [all_reduce(c, 5) for c in cs]   # implicit: must be gen 1
        for f in r1:
            HPX_TEST_EQ(f.get(timeout=10.0), N)
        for f in r2:
            HPX_TEST_EQ(f.get(timeout=10.0), 5 * N)

    def test_generations_keep_rounds_separate(self):
        cs = comms("t_gen")
        r1 = [all_reduce(c, 1) for c in cs]
        r2 = [all_reduce(c, 10) for c in cs]
        for f in r1:
            HPX_TEST_EQ(f.get(timeout=10.0), N)
        for f in r2:
            HPX_TEST_EQ(f.get(timeout=10.0), 10 * N)

    def test_numpy_payload(self):
        cs = comms("t_np")
        futs = [all_reduce(c, np.full(8, float(i))) for i, c in enumerate(cs)]
        expect = np.full(8, float(sum(range(N))))
        for f in futs:
            np.testing.assert_allclose(f.get(timeout=10.0), expect)


class TestChannelCommunicator:
    def test_pairwise_fifo(self):
        cc = [hpx.create_channel_communicator("cc1", num_sites=3,
                                              this_site=i) for i in range(3)]
        cc[0].set(1, "a").get(timeout=10.0)
        cc[0].set(1, "b").get(timeout=10.0)
        cc[2].set(1, "c").get(timeout=10.0)
        HPX_TEST_EQ(cc[1].get(0).get(timeout=10.0), "a")
        HPX_TEST_EQ(cc[1].get(0).get(timeout=10.0), "b")
        HPX_TEST_EQ(cc[1].get(2).get(timeout=10.0), "c")

    def test_get_before_set(self):
        cc = [hpx.create_channel_communicator("cc2", num_sites=2,
                                              this_site=i) for i in range(2)]
        f = cc[1].get(0)
        HPX_TEST(not f.is_ready())
        cc[0].set(1, 42)
        HPX_TEST_EQ(f.get(timeout=10.0), 42)

    def test_out_of_range(self):
        cc = hpx.create_channel_communicator("cc3", num_sites=2, this_site=0)
        with pytest.raises(IndexError):
            cc.set(5, "x")

    def test_unawaited_gets_stay_fifo(self):
        # regression: racing un-awaited get() futures must pair in order
        cc = [hpx.create_channel_communicator("cc5", num_sites=2,
                                              this_site=i) for i in range(2)]
        n = 100
        gets = [cc[1].get(0) for _ in range(n)]   # issued before any set
        for k in range(n):
            cc[0].set(1, k)
        HPX_TEST_EQ([f.get(timeout=10.0) for f in gets], list(range(n)))
        cc[0].close()
        cc[1].close()

    def test_unawaited_sets_stay_fifo(self):
        # regression: racing un-awaited set() futures must not reorder
        cc = [hpx.create_channel_communicator("cc4", num_sites=2,
                                              this_site=i) for i in range(2)]
        n = 200
        futs = [cc[0].set(1, k) for k in range(n)]
        got = [cc[1].get(0).get(timeout=10.0) for _ in range(n)]
        HPX_TEST_EQ(got, list(range(n)))
        for f in futs:
            f.get(timeout=10.0)


class TestDistributedChannel:
    def test_create_connect_roundtrip(self):
        ch = hpx.DistributedChannel.create("dc1")
        other = hpx.DistributedChannel.connect("dc1")
        ch.set("hello").get(timeout=10.0)
        HPX_TEST_EQ(other.get().get(timeout=10.0), "hello")
        ch.unregister()

    def test_duplicate_name_raises(self):
        ch = hpx.DistributedChannel.create("dc2")
        with pytest.raises(ValueError):
            hpx.DistributedChannel.create("dc2")
        ch.unregister()

    def test_recreate_after_unregister_starts_empty(self):
        # regression: unregister must drop the hosted mailbox too
        ch = hpx.DistributedChannel.create("dc3")
        ch.set("stale").get(timeout=10.0)
        ch.unregister()
        ch2 = hpx.DistributedChannel.create("dc3")
        f = ch2.get()
        HPX_TEST(not f.is_ready())
        ch2.set("fresh").get(timeout=10.0)
        HPX_TEST_EQ(f.get(timeout=10.0), "fresh")
        ch2.unregister()


class TestDistributedLatch:
    def test_count_down_releases_waiters(self):
        latch = hpx.DistributedLatch("l1", 3)
        w = latch.wait()
        HPX_TEST(not w.is_ready())
        latch.count_down().get(timeout=10.0)
        latch.count_down(2).get(timeout=10.0)
        HPX_TEST(w.get(timeout=10.0))

    def test_wait_after_release_completes_immediately(self):
        # regression: the task pool may execute a wait() action AFTER the
        # count_down that released the latch; arrival-count semantics must
        # complete it immediately instead of re-creating the latch
        latch = hpx.DistributedLatch("l3", 2)
        latch.count_down(2).get(timeout=10.0)
        HPX_TEST(latch.wait().get(timeout=10.0))

    def test_arrive_and_wait(self):
        latch = hpx.DistributedLatch("l2", 2)
        f1 = latch.arrive_and_wait()
        HPX_TEST(not f1.is_ready())
        f2 = latch.arrive_and_wait()
        HPX_TEST(f1.get(timeout=10.0) and f2.get(timeout=10.0))


def test_multiprocess_collectives_3_localities():
    import os
    from hpx_tpu.run import launch
    repo = os.path.join(os.path.dirname(__file__), "..")
    rc = launch(os.path.join(repo, "tests", "mp_scripts",
                             "collectives_smoke.py"),
                [], localities=3, timeout=420.0)
    assert rc == 0


class TestDeviceCollectives:
    """Data-plane: sharded arrays over the 8-device CPU mesh."""

    def _sharded(self, mesh, n=64, dtype=np.float32, seed=0):
        from hpx_tpu.parallel.mesh import shard_1d
        import jax.numpy as jnp
        src = np.random.default_rng(seed).random(n).astype(dtype)
        return src, shard_1d(jnp.asarray(src), mesh, "x")

    def test_all_reduce_add(self, mesh1d):
        src, x = self._sharded(mesh1d)
        out = dev.all_reduce(x, mesh1d, "x", "add")
        expect = src.reshape(8, -1).sum(axis=0)
        np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5)
        # replicated result
        assert len(out.sharding.device_set) == 8

    def test_all_reduce_max(self, mesh1d):
        src, x = self._sharded(mesh1d)
        out = dev.all_reduce(x, mesh1d, "x", "max")
        np.testing.assert_allclose(
            np.asarray(out), src.reshape(8, -1).max(axis=0), rtol=1e-6)

    def test_all_gather(self, mesh1d):
        src, x = self._sharded(mesh1d)
        out = dev.all_gather(x, mesh1d, "x")
        np.testing.assert_allclose(np.asarray(out), src, rtol=1e-6)

    def test_broadcast(self, mesh1d):
        src, x = self._sharded(mesh1d)
        out = dev.broadcast(x, mesh1d, "x", root=3)
        np.testing.assert_allclose(
            np.asarray(out), src.reshape(8, -1)[3], rtol=1e-6)

    def test_all_to_all_is_transpose(self, mesh1d):
        # 8 devices x 8 blocks of 2: block (i, j) moves to (j, i)
        src = np.arange(8 * 8 * 2, dtype=np.float32)
        from hpx_tpu.parallel.mesh import shard_1d
        import jax.numpy as jnp
        x = shard_1d(jnp.asarray(src), mesh1d, "x")
        out = np.asarray(dev.all_to_all(x, mesh1d, "x"))
        blocks = src.reshape(8, 8, 2)
        expect = blocks.transpose(1, 0, 2).reshape(-1)
        np.testing.assert_allclose(out, expect)

    def test_reduce_scatter(self, mesh1d):
        src, x = self._sharded(mesh1d)
        out = np.asarray(dev.reduce_scatter(x, mesh1d, "x", "add"))
        expect = src.reshape(8, -1).sum(axis=0)
        np.testing.assert_allclose(out, expect, rtol=1e-5)

    def test_reduce_scatter_rejects_non_add(self, mesh1d):
        src, x = self._sharded(mesh1d)
        with pytest.raises(ValueError):
            dev.reduce_scatter(x, mesh1d, "x", "max")

    def test_ring_shift(self, mesh1d):
        src, x = self._sharded(mesh1d)
        out = np.asarray(dev.ring_shift(x, mesh1d, "x", 1))
        blocks = src.reshape(8, -1)
        expect = np.roll(blocks, 1, axis=0).reshape(-1)
        np.testing.assert_allclose(out, expect, rtol=1e-6)

    def test_barrier_runs(self, mesh1d):
        dev.barrier(mesh1d, "x")

    def test_all_reduce_grad(self, mesh1d):
        """AD through the device collectives must be exact. Round 1 ran
        shard_map with check_vma=False, whose legacy psum transpose
        over-counts cotangents by the axis size — these would fail."""
        import jax
        import jax.numpy as jnp
        src, x = self._sharded(mesh1d)

        def loss(x):
            return jnp.sum(dev.all_reduce(x, mesh1d, "x", "add"))

        g = jax.grad(loss)(x)
        # d(sum of all-reduce)/dx_i == 1 exactly, for every element
        np.testing.assert_allclose(np.asarray(g), np.ones_like(src))

    def test_ring_shift_grad(self, mesh1d):
        import jax
        import jax.numpy as jnp
        src, x = self._sharded(mesh1d)

        def loss(x):
            y = dev.ring_shift(x, mesh1d, "x", 1)
            return 0.5 * jnp.sum(y * y)

        g = jax.grad(loss)(x)
        # permutation preserves elements: grad == x elementwise
        np.testing.assert_allclose(np.asarray(g), src, rtol=1e-6)

    def test_all_gather_grad(self, mesh1d):
        import jax
        import jax.numpy as jnp
        src, x = self._sharded(mesh1d)
        w = jnp.arange(64, dtype=jnp.float32)

        def loss(x):
            return jnp.sum(dev.all_gather(x, mesh1d, "x") * w)

        g = jax.grad(loss)(x)
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-6)
