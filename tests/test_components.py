"""Component layer tests.

Reference analog: libs/full/components tests + runtime_components
(component creation via hpx::new_, client invocation, migration —
SURVEY.md §2.4). Single-locality semantics here (fast path, same as
HPX's one-locality unit runs); the full cross-process behavior is
tests/mp_scripts/components_smoke.py.
"""

import os
import threading

import pytest

import hpx_tpu as hpx
from hpx_tpu.dist import components as comp
from hpx_tpu.testing import HPX_TEST, HPX_TEST_EQ

REPO = os.path.join(os.path.dirname(__file__), "..")


@hpx.register_component_type
class Counter(hpx.Component):
    def __init__(self, start: int = 0) -> None:
        self.value = int(start)

    def add(self, n: int) -> int:
        self.value += n
        return self.value

    def get(self) -> int:
        return self.value


@hpx.register_component_type
class SlowBox(hpx.Component):
    def __init__(self) -> None:
        self.ev = threading.Event()
        self.entered = threading.Event()

    def hold(self) -> bool:
        self.entered.set()
        return self.ev.wait(10.0)

    # events aren't picklable; migration state is just nothing
    def __getstate__(self):
        return {}

    def __setstate__(self, st):
        self.ev = threading.Event()
        self.entered = threading.Event()


class TestIdType:
    def test_identity_and_pickle(self):
        import pickle
        a = comp.IdType(2, "t", 7)
        b = comp.IdType(2, "t", 7)
        HPX_TEST_EQ(a, b)
        HPX_TEST_EQ(hash(a), hash(b))
        HPX_TEST_EQ(pickle.loads(pickle.dumps(a)), a)
        HPX_TEST(a != comp.IdType(2, "t", 8))


class TestLocal:
    def test_new_and_invoke(self):
        c = hpx.new_(Counter, None, 5).get()
        HPX_TEST_EQ(c.sync("get"), 5)
        HPX_TEST_EQ(c.add(3).get(), 8)       # attribute sugar -> Future
        HPX_TEST_EQ(c.call("get").get(), 8)
        c.free().get()

    def test_new_sync_and_scope(self):
        with hpx.new_sync(Counter, None, 1) as c:
            HPX_TEST_EQ(c.sync("get"), 1)
        # freed on scope exit: further calls fail
        with pytest.raises(hpx.HpxError):
            c.sync("get")

    def test_unregistered_type_raises(self):
        class NotRegistered(hpx.Component):
            pass
        with pytest.raises(hpx.HpxError):
            hpx.new_(NotRegistered)

    def test_unknown_type_name_raises(self):
        with pytest.raises(hpx.HpxError):
            hpx.new_("no.such.type")

    def test_duplicate_registration_same_class_ok(self):
        # idempotent re-registration (module reloads)
        hpx.register_component_type(Counter)

    def test_client_is_serializable(self):
        from hpx_tpu.dist.serialization import deserialize, serialize
        c = hpx.new_sync(Counter, None, 9)
        c2 = deserialize(serialize(c))
        HPX_TEST_EQ(c2, c)
        HPX_TEST_EQ(c2.sync("get"), 9)
        c.free().get()

    def test_post_fire_and_forget(self):
        c = hpx.new_sync(Counter, None, 0)
        c.post("add", 4)
        # post has no future; poll
        for _ in range(200):
            if c.sync("get") == 4:
                break
            threading.Event().wait(0.005)
        HPX_TEST_EQ(c.sync("get"), 4)
        c.free().get()

    def test_where_and_colocated(self):
        c = hpx.new_sync(Counter, None, 0)
        HPX_TEST_EQ(c.where().get(), hpx.find_here())
        c.free().get()

    def test_free_twice_is_false(self):
        c = hpx.new_sync(Counter, None, 0)
        HPX_TEST(c.free().get() is True)
        HPX_TEST(c.free().get() is False)

    def test_exception_propagates(self):
        c = hpx.new_sync(Counter, None, 0)
        with pytest.raises(TypeError):
            c.sync("add", "not-an-int-but-str-concat-fails-no")
        # instance still alive and unpinned after the error
        HPX_TEST_EQ(c.sync("get"), 0)
        c.free().get()

    def test_migrate_to_self_is_noop(self):
        c = hpx.new_sync(Counter, None, 3)
        c2 = hpx.migrate(c, hpx.find_here()).get()
        HPX_TEST_EQ(c2.gid, c.gid)
        HPX_TEST_EQ(c2.sync("get"), 3)
        c.free().get()

    def test_migrate_waits_for_pins(self):
        # single-locality: only the pin-drain logic is exercised (a
        # running method blocks migration until it finishes)
        b = hpx.new_sync(SlowBox)
        f = b.call("hold")
        entry_key = b.gid.key()
        inst = comp._instances[entry_key].inst
        HPX_TEST(inst.entered.wait(5.0))
        # migration to self returns immediately even while pinned
        HPX_TEST_EQ(hpx.migrate(b, hpx.find_here()).get().gid, b.gid)
        inst.ev.set()
        HPX_TEST(f.get() is True)
        b.free().get()


    def test_free_waits_for_pins(self):
        # round-1 advisor finding: _free popped the instance without
        # draining pins, so a running invocation kept using a freed
        # component. free() must block until the method returns.
        b = hpx.new_sync(SlowBox)
        f = b.call("hold")
        inst = comp._instances[b.gid.key()].inst
        HPX_TEST(inst.entered.wait(5.0))
        ff = b.free()                       # must NOT complete yet
        threading.Event().wait(0.1)
        HPX_TEST(not ff.is_ready())
        inst.ev.set()
        HPX_TEST(f.get() is True)           # invocation saw a live object
        HPX_TEST(ff.get(timeout=10.0) is True)
        HPX_TEST(b.gid.key() not in comp._instances)


class TestBasenames:
    def test_register_find_roundtrip(self):
        c = hpx.new_sync(Counter, None, 11)
        hpx.register_with_basename("unit/ctr", c).get()
        got = hpx.find_from_basename("unit/ctr").get()
        HPX_TEST_EQ(got, c)
        HPX_TEST_EQ(got.sync("get"), 11)
        c.free().get()


def test_multiprocess_components():
    """Remote create/invoke/migrate/free across 3 real processes."""
    from hpx_tpu.run import launch
    rc = launch(os.path.join(REPO, "tests", "mp_scripts",
                             "components_smoke.py"),
                [], localities=3, timeout=420.0)
    assert rc == 0
