"""Transformer model family tests: the dp×sp×tp-sharded training step
compiles, runs, agrees with a single-device replica, and learns.
"""

import dataclasses
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hpx_tpu.models import transformer as tfm


CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4, head_dim=8,
                            n_layers=2, d_ff=64, lr=0.05)


@pytest.fixture(scope="module")
def mesh3d():
    return tfm.make_mesh_3d(8)


def test_mesh_factoring():
    m = tfm.make_mesh_3d(8)
    assert dict(m.shape) == {"dp": 2, "sp": 2, "tp": 2}
    m4 = tfm.make_mesh_3d(4)
    assert m4.shape["sp"] * m4.shape["tp"] * m4.shape["dp"] == 4


def test_train_step_runs_and_learns(mesh3d):
    key = jax.random.PRNGKey(0)
    params = tfm.init_params(CFG, key)
    params = tfm.shard_params(params, CFG, mesh3d)
    step = tfm.make_train_step(CFG, mesh3d)

    # one fixed tiny batch -> loss must drop when memorizing it
    toks, tgts = tfm.sample_batch(CFG, batch=4, seq=32,
                                  key=jax.random.PRNGKey(1))
    toks, tgts = tfm.shard_batch(toks, tgts, mesh3d)

    losses = []
    for _ in range(10):
        params, loss = step(params, toks, tgts)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses
    assert np.isfinite(losses).all()


def test_matches_single_device(mesh3d):
    """The sharded step must compute the SAME loss and updates as an
    unsharded replica of the math."""
    key = jax.random.PRNGKey(2)
    params = tfm.init_params(CFG, key)
    toks, tgts = tfm.sample_batch(CFG, batch=4, seq=16,
                                  key=jax.random.PRNGKey(3))

    # single-device oracle: same math, mesh of 1x1x1
    mesh1 = tfm.make_mesh_3d(1)
    p1 = tfm.shard_params(jax.tree.map(jnp.copy, params), CFG, mesh1)
    step1 = tfm.make_train_step(CFG, mesh1)
    t1, g1 = tfm.shard_batch(toks, tgts, mesh1)
    p1, loss1 = step1(p1, t1, g1)

    p8 = tfm.shard_params(jax.tree.map(jnp.copy, params), CFG, mesh3d)
    step8 = tfm.make_train_step(CFG, mesh3d)
    t8, g8 = tfm.shard_batch(toks, tgts, mesh3d)
    p8, loss8 = step8(p8, t8, g8)

    np.testing.assert_allclose(float(loss1), float(loss8), rtol=1e-4)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p8)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-5)


def test_optax_train_step(mesh3d):
    import optax
    opt = optax.adam(1e-2)
    params = tfm.shard_params(tfm.init_params(CFG, jax.random.PRNGKey(4)),
                              CFG, mesh3d)
    opt_state = tfm.make_opt_state(params, CFG, mesh3d, opt)
    step = tfm.make_train_step(CFG, mesh3d, optimizer=opt)
    toks, tgts = tfm.sample_batch(CFG, batch=4, seq=32,
                                  key=jax.random.PRNGKey(5))
    toks, tgts = tfm.shard_batch(toks, tgts, mesh3d)
    losses = []
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state, toks, tgts)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses
    # adam moments follow the params' tp sharding
    mu_w1 = opt_state[0].mu["layers"][0]["w1"]
    shard_shapes = {s.data.shape for s in mu_w1.addressable_shards}
    assert shard_shapes == {(CFG.d_model, CFG.d_ff // 2)}


def test_generate_greedy_decode():
    params = tfm.init_params(CFG, jax.random.PRNGKey(6))
    prompt = jnp.array([[1, 2, 3], [4, 5, 6]], dtype=jnp.int32)
    out = tfm.generate(params, CFG, prompt, max_new=5)
    assert out.shape == (2, 5)
    assert ((out >= 0) & (out < CFG.vocab)).all()
    # deterministic
    out2 = tfm.generate(params, CFG, prompt, max_new=5)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


def test_generate_consistent_with_forward():
    """The first generated token must equal the argmax of the full
    forward pass at the last prompt position (KV-cache correctness)."""
    params = tfm.init_params(CFG, jax.random.PRNGKey(7))
    prompt = jnp.array([[3, 1, 4, 1, 5, 9, 2, 6]], dtype=jnp.int32)
    out = tfm.generate(params, CFG, prompt, max_new=1)

    # full forward (mesh of 1): logits at the last position
    mesh1 = tfm.make_mesh_3d(1)
    sp = 1
    from hpx_tpu.models.transformer import _ln, _block
    from hpx_tpu.utils.jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P

    def fwd(p, toks):
        x = p["emb"][toks]
        for lp in p["layers"]:
            x, _aux = _block(x, lp, CFG, sp, 1)
        x = _ln(x, p["ln_f"])
        return jnp.einsum("bsd,vd->bsv", x, p["emb"])

    p1 = tfm.shard_params(params, CFG, mesh1)
    logits = jax.jit(shard_map(
        fwd, mesh=mesh1,
        in_specs=(tfm.param_specs(CFG), P("dp", "sp")),
        out_specs=P("dp", "sp")))(p1, prompt)
    want = int(jnp.argmax(logits[0, -1]))
    assert int(out[0, 0]) == want


def test_generate_matches_full_forward_oracle():
    """Greedy decode must equal token-by-token decoding with the full
    (uncached) forward pass, on a TRAINED model whose argmax varies by
    position. An untrained model's argmax is effectively constant, which
    masked a round-1 off-by-one (generate() emitted the step's own
    prediction, dropping the first generated token)."""
    mesh1 = tfm.make_mesh_3d(1)
    params = tfm.shard_params(tfm.init_params(CFG, jax.random.PRNGKey(8)),
                              CFG, mesh1)
    step = tfm.make_train_step(CFG, mesh1)
    toks, tgts = tfm.sample_batch(CFG, batch=4, seq=16,
                                  key=jax.random.PRNGKey(9))
    toks, tgts = tfm.shard_batch(toks, tgts, mesh1)
    for _ in range(30):
        params, _ = step(params, toks, tgts)

    prompt = jnp.array([[3, 1, 4, 1], [2, 7, 1, 8]], dtype=jnp.int32)
    max_new = 6
    out = tfm.generate(params, CFG, prompt, max_new=max_new)

    # oracle: grow the sequence one token at a time through the full
    # forward pass (same shard_map-on-mesh1 path the other tests use)
    from hpx_tpu.models.transformer import _ln, _block
    from hpx_tpu.utils.jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P

    def fwd(p, toks):
        x = p["emb"][toks]
        for lp in p["layers"]:
            x, _aux = _block(x, lp, CFG, 1, 1)
        x = _ln(x, p["ln_f"])
        return jnp.einsum("bsd,vd->bsv", x, p["emb"])

    run = jax.jit(shard_map(
        fwd, mesh=mesh1,
        in_specs=(tfm.param_specs(CFG), P("dp", "sp")),
        out_specs=P("dp", "sp")))

    seq = prompt
    want = []
    for _ in range(max_new):
        logits = run(params, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        want.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    want = jnp.stack(want, axis=1)

    # the test is only meaningful if decode is non-constant
    flat = np.asarray(want).reshape(-1).tolist()
    assert len(set(flat)) > 1, f"oracle decode degenerate: {flat}"
    np.testing.assert_array_equal(np.asarray(out), np.asarray(want))


def test_params_actually_sharded(mesh3d):
    params = tfm.shard_params(tfm.init_params(CFG, jax.random.PRNGKey(0)),
                              CFG, mesh3d)
    w1 = params["layers"][0]["w1"]
    # tp axis of the mesh has 2 shards; w1's column dim is split
    assert len(w1.sharding.device_set) == 8
    shard_shapes = {s.data.shape for s in w1.addressable_shards}
    assert shard_shapes == {(CFG.d_model, CFG.d_ff // 2)}


MOE_CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                head_dim=8, n_layers=2, d_ff=64, lr=0.05,
                                n_experts=4, moe_top_k=2,
                                moe_capacity=4.0)


def test_moe_train_step_runs_and_learns(mesh3d):
    """dp x sp x tp x EP: experts shard over the dp axis (GShard
    layout — tokens batch-sharded there exchange via all_to_all);
    the step must compile, run, and learn."""
    params = tfm.shard_params(tfm.init_params(MOE_CFG,
                                              jax.random.PRNGKey(11)),
                              MOE_CFG, mesh3d)
    # experts really are sharded 2-ways over dp
    w1 = params["layers"][0]["moe"]["w1"]
    shard_shapes = {s.data.shape for s in w1.addressable_shards}
    assert shard_shapes == {(MOE_CFG.n_experts // 2, MOE_CFG.d_model,
                             MOE_CFG.d_ff // 2)}   # dp- AND tp-sharded
    step = tfm.make_train_step(MOE_CFG, mesh3d)
    toks, tgts = tfm.sample_batch(MOE_CFG, batch=4, seq=32,
                                  key=jax.random.PRNGKey(12))
    toks, tgts = tfm.shard_batch(toks, tgts, mesh3d)
    losses = []
    for _ in range(10):
        params, loss = step(params, toks, tgts)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.85, losses


def test_moe_sharded_matches_single_device(mesh3d):
    """The ep-sharded MoE step computes the same loss as mesh(1,1,1)."""
    params = tfm.init_params(MOE_CFG, jax.random.PRNGKey(13))
    toks, tgts = tfm.sample_batch(MOE_CFG, batch=4, seq=16,
                                  key=jax.random.PRNGKey(14))
    mesh1 = tfm.make_mesh_3d(1)
    p1 = tfm.shard_params(jax.tree.map(jnp.copy, params), MOE_CFG, mesh1)
    _, loss1 = tfm.make_train_step(MOE_CFG, mesh1)(
        p1, *tfm.shard_batch(toks, tgts, mesh1))
    p8 = tfm.shard_params(jax.tree.map(jnp.copy, params), MOE_CFG, mesh3d)
    _, loss8 = tfm.make_train_step(MOE_CFG, mesh3d)(
        p8, *tfm.shard_batch(toks, tgts, mesh3d))
    np.testing.assert_allclose(float(loss1), float(loss8), rtol=2e-4)


def test_moe_generate():
    params = tfm.init_params(MOE_CFG, jax.random.PRNGKey(15))
    prompt = jnp.array([[1, 2, 3]], dtype=jnp.int32)
    out = tfm.generate(params, MOE_CFG, prompt, max_new=4)
    assert out.shape == (1, 4)
    assert ((out >= 0) & (out < MOE_CFG.vocab)).all()


def test_moe_generate_batch_independent():
    """Serving is drop-free (decode capacity = every claim fits), so a
    prompt's continuation must not depend on the rest of the batch."""
    params = tfm.init_params(MOE_CFG, jax.random.PRNGKey(16))
    p1 = jnp.array([[1, 2, 3]], dtype=jnp.int32)
    batch = jnp.array([[1, 2, 3], [9, 9, 9], [4, 5, 6], [7, 7, 7]],
                      dtype=jnp.int32)
    alone = tfm.generate(params, MOE_CFG, p1, max_new=5)
    together = tfm.generate(params, MOE_CFG, batch, max_new=5)
    np.testing.assert_array_equal(np.asarray(alone[0]),
                                  np.asarray(together[0]))


def test_train_checkpoint_resume(mesh3d, tmp_path):
    """Mid-training save/restore through svc/checkpoint reproduces the
    uninterrupted trajectory exactly (sharded params round-trip through
    the host serializer and come back with the same values; resharding
    is the caller's shard_params)."""
    import hpx_tpu as hpx

    params = tfm.shard_params(tfm.init_params(CFG, jax.random.PRNGKey(7)),
                              CFG, mesh3d)
    step = tfm.make_train_step(CFG, mesh3d)
    toks, tgts = tfm.sample_batch(CFG, batch=4, seq=32,
                                  key=jax.random.PRNGKey(8))
    toks, tgts = tfm.shard_batch(toks, tgts, mesh3d)

    for _ in range(3):
        params, _ = step(params, toks, tgts)

    path = tmp_path / "train.cp"
    hpx.save_checkpoint_to_file(path, {"step": 3},
                                jax.device_get(params)).get(timeout=60.0)

    # uninterrupted continuation
    p_cont, ref_losses = params, []
    for _ in range(3):
        p_cont, l = step(p_cont, toks, tgts)
        ref_losses.append(float(l))

    # resume from the file
    meta, host_params = hpx.restore_checkpoint_from_file(path)
    assert meta["step"] == 3
    p_res = tfm.shard_params(host_params, CFG, mesh3d)
    got_losses = []
    for _ in range(3):
        p_res, l = step(p_res, toks, tgts)
        got_losses.append(float(l))

    np.testing.assert_allclose(got_losses, ref_losses, rtol=1e-6)


def test_generate_sharded_matches_single_device(devices):
    """Megatron decode (heads/ffn/KV cache over tp, batch over dp) must
    emit the same greedy tokens as the single-device path."""
    from jax.sharding import Mesh
    mesh = Mesh(np.array(devices[:4]).reshape(2, 2), ("dp", "tp"))
    params = tfm.init_params(CFG, jax.random.PRNGKey(20))
    prompt = jnp.array([[1, 2, 3], [4, 5, 6], [7, 8, 9], [3, 1, 2]],
                       dtype=jnp.int32)
    ref = tfm.generate(params, CFG, prompt, max_new=8)
    sharded_params = tfm.shard_params(params, CFG, mesh)
    got = tfm.generate(sharded_params, CFG, prompt, max_new=8, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_generate_sharded_rejects_bad(devices):
    from jax.sharding import Mesh
    mesh = Mesh(np.array(devices[:4]).reshape(2, 2), ("dp", "tp"))
    params = tfm.init_params(CFG, jax.random.PRNGKey(21))
    bad_batch = jnp.ones((3, 4), jnp.int32)       # 3 % dp=2 != 0
    with pytest.raises(ValueError, match="divisible"):
        tfm.generate(params, CFG, bad_batch, max_new=2, mesh=mesh)
    # MoE decodes expert-parallel now; the remaining MoE refusal is
    # expert divisibility over the expert axis, with the remedy named
    import dataclasses
    odd = dataclasses.replace(MOE_CFG, n_experts=3, moe_top_k=2)
    with pytest.raises(ValueError, match=r"n_experts \(3\).*tp=2"):
        tfm.generate(tfm.init_params(odd, jax.random.PRNGKey(2)),
                     odd, jnp.ones((2, 4), jnp.int32), max_new=2,
                     mesh=mesh)


GQA_CFG = tfm.TransformerConfig(vocab=32, d_model=16, n_heads=4,
                                head_dim=8, n_layers=2, d_ff=32,
                                n_kv_heads=2, lr=0.05)


def test_gqa_train_step_learns(mesh3d):
    params = tfm.shard_params(tfm.init_params(GQA_CFG, jax.random.PRNGKey(0)),
                              GQA_CFG, mesh3d)
    step = tfm.make_train_step(GQA_CFG, mesh3d)
    toks, tgts = tfm.sample_batch(GQA_CFG, batch=4, seq=32,
                                  key=jax.random.PRNGKey(1))
    toks, tgts = tfm.shard_batch(toks, tgts, mesh3d)
    losses = []
    for _ in range(8):
        params, loss = step(params, toks, tgts)
        losses.append(float(loss))
    assert losses[-1] < losses[0] and np.isfinite(losses).all()
    # the kv projection really is smaller
    wkv = jax.tree.leaves({"w": params["layers"][0]["wkv"]})[0]
    assert wkv.shape == (2, 16, 2, 8)


def test_gqa_decode_cache_is_grouped():
    """KV caches hold n_kv_heads — the serving memory saving — and
    decode is batch-independent as before."""
    params = tfm.init_params(GQA_CFG, jax.random.PRNGKey(2))
    prompt = jnp.array([[1, 2, 3], [4, 5, 6]], dtype=jnp.int32)
    out = tfm.generate(params, GQA_CFG, prompt, max_new=6)
    assert out.shape == (2, 6)
    alone = tfm.generate(params, GQA_CFG, prompt[:1], max_new=6)
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(alone[0]))


def test_gqa_sharded_decode_matches(devices):
    from jax.sharding import Mesh
    mesh = Mesh(np.array(devices[:4]).reshape(2, 2), ("dp", "tp"))
    params = tfm.init_params(GQA_CFG, jax.random.PRNGKey(3))
    prompt = jnp.array([[1, 2, 3], [4, 5, 6], [7, 8, 9], [2, 2, 2]],
                       dtype=jnp.int32)
    ref = tfm.generate(params, GQA_CFG, prompt, max_new=6)
    got = tfm.generate(tfm.shard_params(params, GQA_CFG, mesh), GQA_CFG,
                       prompt, max_new=6, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_gqa_pipelined_train(devices):
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P
    cfg = tfm.TransformerConfig(vocab=32, d_model=16, n_heads=4,
                                head_dim=8, n_layers=4, d_ff=32,
                                n_kv_heads=2, lr=0.05)
    mesh = Mesh(np.array(devices[:4]).reshape(2, 2), ("dp", "pp"))
    stacked = tfm.shard_pipeline_params(
        tfm.stack_pipeline_params(tfm.init_params(cfg, jax.random.PRNGKey(4))),
        mesh)
    step = tfm.make_pipelined_train_step(cfg, mesh, 2)
    toks, tgts = tfm.sample_batch(cfg, batch=4, seq=8,
                                  key=jax.random.PRNGKey(5))
    sh = NamedSharding(mesh, P("dp", None))
    t, g = jax.device_put(toks, sh), jax.device_put(tgts, sh)
    _, l0 = step(stacked, t, g)
    stacked, _ = step(stacked, t, g)
    for _ in range(3):
        stacked, l1 = step(stacked, t, g)
    assert float(l1) < float(l0)


def test_remat_matches_non_remat(mesh3d):
    """cfg.remat changes memory, not math: losses and updated params
    must match the non-remat step."""
    base = tfm.TransformerConfig(vocab=32, d_model=16, n_heads=2,
                                 head_dim=8, n_layers=2, d_ff=32, lr=0.05)
    rem = dataclasses.replace(base, remat=True)
    toks, tgts = tfm.sample_batch(base, batch=4, seq=32,
                                  key=jax.random.PRNGKey(6))
    toks, tgts = tfm.shard_batch(toks, tgts, mesh3d)
    outs = []
    for cfg in (base, rem):
        params = tfm.shard_params(
            tfm.init_params(cfg, jax.random.PRNGKey(0)), cfg, mesh3d)
        step = tfm.make_train_step(cfg, mesh3d)
        params, loss = step(params, toks, tgts)
        outs.append((jax.device_get(params), float(loss)))
    (p1, l1), (p2, l2) = outs
    assert l1 == pytest.approx(l2, abs=1e-6)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-6)


ROPE_CFG = tfm.TransformerConfig(vocab=32, d_model=16, n_heads=2,
                                 head_dim=8, n_layers=2, d_ff=32,
                                 rope=True, lr=0.05)


def test_rope_positions_matter():
    """With RoPE, permuting prompt tokens changes the logits even in a
    fresh model — the position-free baseline can't tell (same-token
    prompts aside)."""
    params = tfm.init_params(ROPE_CFG, jax.random.PRNGKey(0))
    from hpx_tpu.models.transformer import _ln, _block
    from hpx_tpu.utils.jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P
    mesh1 = tfm.make_mesh_3d(1)
    sp = tfm.shard_params(params, ROPE_CFG, mesh1)

    def fwd(p, toks):
        x = p["emb"][toks]
        for lp in p["layers"]:
            x, _ = _block(x, lp, ROPE_CFG, 1, 1)
        return _ln(x, p["ln_f"])

    run = jax.jit(shard_map(fwd, mesh=mesh1,
                            in_specs=(tfm.param_specs(ROPE_CFG),
                                      P("dp", "sp")),
                            out_specs=P("dp", "sp")))
    a = run(sp, jnp.array([[5, 5, 5, 7]], jnp.int32))
    b = run(sp, jnp.array([[5, 5, 7, 5]], jnp.int32))
    # final-position outputs must differ: token 7 sat at different pos
    assert not np.allclose(np.asarray(a)[0, -1], np.asarray(b)[0, -1],
                           atol=1e-5)


def test_rope_sharded_matches_single_device(mesh3d):
    """RoPE under the sp ring (global positions per shard) computes the
    same loss as the 1-device mesh."""
    mesh1 = tfm.make_mesh_3d(1)
    toks, tgts = tfm.sample_batch(ROPE_CFG, batch=4, seq=32,
                                  key=jax.random.PRNGKey(1))
    losses = []
    for mesh in (mesh1, mesh3d):
        params = tfm.shard_params(
            tfm.init_params(ROPE_CFG, jax.random.PRNGKey(0)), ROPE_CFG,
            mesh)
        step = tfm.make_train_step(ROPE_CFG, mesh)
        t, g = tfm.shard_batch(toks, tgts, mesh)
        _p, loss = step(params, t, g)
        losses.append(float(loss))
    assert losses[0] == pytest.approx(losses[1], abs=2e-5)


def test_rope_generate_matches_forward_oracle():
    """Decode-path rotation (scalar write position, post-rope cache)
    agrees with the training-path rotation (vector positions)."""
    mesh1 = tfm.make_mesh_3d(1)
    params = tfm.shard_params(tfm.init_params(ROPE_CFG,
                                              jax.random.PRNGKey(2)),
                              ROPE_CFG, mesh1)
    step = tfm.make_train_step(ROPE_CFG, mesh1)
    toks, tgts = tfm.sample_batch(ROPE_CFG, batch=4, seq=16,
                                  key=jax.random.PRNGKey(3))
    toks, tgts = tfm.shard_batch(toks, tgts, mesh1)
    for _ in range(30):
        params, _ = step(params, toks, tgts)

    prompt = jnp.array([[3, 1, 4, 1], [2, 7, 1, 8]], dtype=jnp.int32)
    out = tfm.generate(params, ROPE_CFG, prompt, max_new=6)

    from hpx_tpu.models.transformer import _ln, _block
    from hpx_tpu.utils.jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P

    def fwd(p, toks):
        x = p["emb"][toks]
        for lp in p["layers"]:
            x, _ = _block(x, lp, ROPE_CFG, 1, 1)
        x = _ln(x, p["ln_f"])
        return jnp.einsum("bsd,vd->bsv", x, p["emb"])

    run = jax.jit(shard_map(fwd, mesh=mesh1,
                            in_specs=(tfm.param_specs(ROPE_CFG),
                                      P("dp", "sp")),
                            out_specs=P("dp", "sp")))
    seq = prompt
    want = []
    for _ in range(6):
        logits = run(params, seq)
        nxt = jnp.argmax(logits[:, -1, :], axis=-1)
        want.append(np.asarray(nxt))
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.stack(want, 1))


def test_rope_rejects_odd_head_dim(mesh3d):
    bad = dataclasses.replace(ROPE_CFG, head_dim=7)
    params = tfm.init_params(bad, jax.random.PRNGKey(0))
    step = tfm.make_train_step(bad, tfm.make_mesh_3d(1))
    toks, tgts = tfm.sample_batch(bad, batch=2, seq=8,
                                  key=jax.random.PRNGKey(1))
    with pytest.raises(ValueError, match="even head_dim"):
        step(tfm.shard_params(params, bad, tfm.make_mesh_3d(1)),
             *tfm.shard_batch(toks, tgts, tfm.make_mesh_3d(1)))


class TestSamplingDecode:
    def test_temperature_zero_is_greedy(self):
        params = tfm.init_params(CFG, jax.random.PRNGKey(30))
        prompt = jnp.array([[1, 2, 3], [4, 5, 6]], jnp.int32)
        a = tfm.generate(params, CFG, prompt, max_new=6)
        b = tfm.generate(params, CFG, prompt, max_new=6, temperature=0.0)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_sampling_deterministic_and_key_sensitive(self):
        params = tfm.init_params(CFG, jax.random.PRNGKey(31))
        prompt = jnp.array([[1, 2, 3]], jnp.int32)
        k1, k2 = jax.random.PRNGKey(0), jax.random.PRNGKey(1)
        a = tfm.generate(params, CFG, prompt, max_new=8, temperature=1.0,
                         key=k1)
        b = tfm.generate(params, CFG, prompt, max_new=8, temperature=1.0,
                         key=k1)
        c = tfm.generate(params, CFG, prompt, max_new=8, temperature=1.0,
                         key=k2)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    def test_sampled_sharded_matches_single_device(self, devices):
        """Global-row key folding: the sharded sampler draws the same
        tokens as the single-device one."""
        from jax.sharding import Mesh
        mesh = Mesh(np.array(devices[:4]).reshape(2, 2), ("dp", "tp"))
        params = tfm.init_params(CFG, jax.random.PRNGKey(32))
        prompt = jnp.array([[1, 2, 3], [4, 5, 6], [7, 8, 9], [2, 1, 2]],
                           jnp.int32)
        k = jax.random.PRNGKey(7)
        ref = tfm.generate(params, CFG, prompt, max_new=6,
                           temperature=0.8, top_k=8, key=k)
        got = tfm.generate(tfm.shard_params(params, CFG, mesh), CFG,
                           prompt, max_new=6, temperature=0.8, top_k=8,
                           key=k, mesh=mesh)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))

    def test_top_k_one_is_greedy(self):
        params = tfm.init_params(CFG, jax.random.PRNGKey(33))
        prompt = jnp.array([[3, 1, 4]], jnp.int32)
        greedy = tfm.generate(params, CFG, prompt, max_new=6)
        tk1 = tfm.generate(params, CFG, prompt, max_new=6,
                           temperature=0.5, top_k=1,
                           key=jax.random.PRNGKey(0))
        np.testing.assert_array_equal(np.asarray(greedy), np.asarray(tk1))

    def test_eos_pins_rows(self):
        """Force eos to be the argmax continuation by picking eos_id
        from a greedy run, then check everything after stays eos."""
        params = tfm.init_params(CFG, jax.random.PRNGKey(34))
        prompt = jnp.array([[1, 2, 3]], jnp.int32)
        free = np.asarray(tfm.generate(params, CFG, prompt, max_new=8))
        eos = int(free[0, 2])               # whatever it emits 3rd
        out = np.asarray(tfm.generate(params, CFG, prompt, max_new=8,
                                      eos_id=eos))
        hits = np.where(out[0] == eos)[0]
        assert hits.size
        first = hits[0]
        assert (out[0, first:] == eos).all()

    def test_requires_key_for_sampling(self):
        params = tfm.init_params(CFG, jax.random.PRNGKey(35))
        with pytest.raises(ValueError, match="PRNG key"):
            tfm.generate(params, CFG, jnp.ones((1, 3), jnp.int32),
                         max_new=2, temperature=1.0)


class TestQuantizedServing:
    def test_quantized_decode_runs_and_logits_close(self):
        from hpx_tpu.models import quant
        cfg = tfm.TransformerConfig(vocab=64, d_model=64, n_heads=4,
                                    head_dim=16, n_layers=2, d_ff=128)
        params = tfm.init_params(cfg, jax.random.PRNGKey(40))
        qp = quant.quantize_params(params)
        prompt = jnp.array([[1, 2, 3, 4]], jnp.int32)
        dense = tfm.generate(params, cfg, prompt, max_new=6)
        q = tfm.generate(qp, cfg, prompt, max_new=6)
        assert q.shape == dense.shape
        assert (np.asarray(q) >= 0).all() and \
            (np.asarray(q) < cfg.vocab).all()
        # real closeness check: full-sequence logits through the two
        # weight sets (a wrong scale axis would blow this up)
        from hpx_tpu.models.transformer import _ln, _qkv_proj, _dq
        from hpx_tpu.ops.attention import blockwise_attention

        def fwd(p, toks):
            x = p["emb"][toks]
            for lp in p["layers"]:
                h = _ln(x, lp["ln1"])
                qh, kh, vh = _qkv_proj(h, lp)
                att = blockwise_attention(qh, kh, vh, causal=True)
                x = x + jnp.einsum("bsnh,nhd->bsd", att,
                                   _dq(lp["wo"], att))
                h = _ln(x, lp["ln2"])
                x = x + jax.nn.gelu(h @ _dq(lp["w1"], h) + lp["b1"]) \
                    @ _dq(lp["w2"], h)
            return jnp.einsum("bsd,vd->bsv", _ln(x, p["ln_f"]), p["emb"])

        ld = np.asarray(fwd(params, prompt), np.float32)
        lq = np.asarray(fwd(qp, prompt), np.float32)
        rel = np.linalg.norm(ld - lq) / np.linalg.norm(ld)
        assert rel < 0.02, rel

    def test_quantization_error_bounded(self):
        """Per-channel int8 roundtrip error on each weight < 1%."""
        from hpx_tpu.models import quant
        cfg = tfm.TransformerConfig(vocab=32, d_model=64, n_heads=4,
                                    head_dim=16, n_layers=1, d_ff=128)
        params = tfm.init_params(cfg, jax.random.PRNGKey(41))
        qp = quant.quantize_params(params)
        for name in ("wqkv", "wo", "w1", "w2"):
            w = np.asarray(params["layers"][0][name], np.float32)
            wq = np.asarray(quant.dequant(qp["layers"][0][name],
                                          jnp.float32))
            rel = np.linalg.norm(w - wq) / np.linalg.norm(w)
            assert rel < 0.01, (name, rel)

    def test_memory_shrinks_4x(self):
        from hpx_tpu.models import quant
        cfg = tfm.TransformerConfig(vocab=32, d_model=128, n_heads=4,
                                    head_dim=32, n_layers=2, d_ff=512)
        params = tfm.init_params(cfg, jax.random.PRNGKey(42))
        dense_bytes = quant.quantized_bytes(params["layers"])
        q_bytes = quant.quantized_bytes(
            quant.quantize_params(params)["layers"])
        assert q_bytes < dense_bytes * 0.3       # f32 -> int8 + scales

    def test_gqa_quantized(self):
        from hpx_tpu.models import quant
        qp = quant.quantize_params(
            tfm.init_params(GQA_CFG, jax.random.PRNGKey(43)))
        out = tfm.generate(qp, GQA_CFG, jnp.array([[1, 2]], jnp.int32),
                           max_new=4)
        assert out.shape == (1, 4)

    # sharded quantized decode is now supported —
    # see TestQuantizedShardedDecode below for the bit-identity coverage


class TestBeamSearch:
    def _trained(self, seed=50):
        mesh1 = tfm.make_mesh_3d(1)
        params = tfm.shard_params(
            tfm.init_params(CFG, jax.random.PRNGKey(seed)), CFG, mesh1)
        step = tfm.make_train_step(CFG, mesh1)
        toks, tgts = tfm.sample_batch(CFG, batch=4, seq=16,
                                      key=jax.random.PRNGKey(seed + 1))
        toks, tgts = tfm.shard_batch(toks, tgts, mesh1)
        for _ in range(25):
            params, _ = step(params, toks, tgts)
        return jax.device_get(params)

    def test_beam_one_equals_greedy(self):
        params = self._trained()
        prompt = jnp.array([[3, 1, 4], [2, 7, 1]], jnp.int32)
        greedy = tfm.generate(params, CFG, prompt, max_new=8)
        beam1 = tfm.beam_search(params, CFG, prompt, max_new=8,
                                beam_width=1)
        np.testing.assert_array_equal(np.asarray(greedy),
                                      np.asarray(beam1))

    def test_beam_score_at_least_greedy(self):
        """The best beam's total logprob must be >= the greedy
        sequence's (greedy is in the search space of width >= 1)."""
        params = self._trained(seed=60)
        prompt = jnp.array([[1, 2, 3]], jnp.int32)
        max_new = 8
        greedy = np.asarray(tfm.generate(params, CFG, prompt,
                                         max_new=max_new))
        beams, scores = tfm.beam_search(params, CFG, prompt,
                                        max_new=max_new, beam_width=4,
                                        return_all=True)

        def seq_logprob(tokens):
            # teacher-force through THE decoder's own per-token forward
            from hpx_tpu.models.transformer import _decode_forward
            caches = [(jnp.zeros((1, 3 + max_new, CFG.kv_heads,
                                  CFG.head_dim), CFG.dtype),) * 2
                      for _ in range(CFG.n_layers)]
            total, seq = 0.0, [1, 2, 3] + list(tokens)
            for pos in range(len(seq) - 1):
                caches, logits = _decode_forward(
                    params, caches, jnp.array([seq[pos]]), pos, CFG)
                lp_ = jax.nn.log_softmax(logits[0])
                if pos >= 2:            # predictions beyond the prompt
                    total += float(lp_[seq[pos + 1]])
            return total

        g = seq_logprob(greedy[0].tolist())
        b = seq_logprob(np.asarray(beams)[0, 0].tolist())
        assert b >= g - 1e-4
        assert float(scores[0, 0]) == pytest.approx(b, abs=1e-3)

    def test_beam_shapes_and_sorted(self):
        params = tfm.init_params(CFG, jax.random.PRNGKey(70))
        prompt = jnp.array([[1, 2], [3, 4], [5, 6]], jnp.int32)
        beams, scores = tfm.beam_search(params, CFG, prompt, max_new=5,
                                        beam_width=3, return_all=True)
        assert beams.shape == (3, 3, 5) and scores.shape == (3, 3)
        s = np.asarray(scores)
        assert (s[:, :-1] >= s[:, 1:] - 1e-6).all()

    def test_beam_bf16_model(self):
        """Regression: the logits scan carry must stay f32 whatever the
        model dtype (bf16 once crashed the carry-type check)."""
        cfg = dataclasses.replace(CFG, dtype=jnp.bfloat16)
        params = tfm.init_params(cfg, jax.random.PRNGKey(80))
        out = tfm.beam_search(params, cfg,
                              jnp.array([[1, 2, 3]], jnp.int32),
                              max_new=4, beam_width=3)
        assert out.shape == (1, 4)


class TestQuantizedShardedDecode:
    """int8 serving under dp x tp: scales shard with their channels
    (quant.quantized_param_specs); output must be bit-identical to the
    single-device quantized decode."""

    def test_quantized_tp_decode_bit_identical(self, devices):
        from jax.sharding import Mesh
        from hpx_tpu.models import quant
        mesh = Mesh(np.array(devices[:4]).reshape(2, 2), ("dp", "tp"))
        cfg = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                    head_dim=8, n_layers=2, d_ff=64)
        qp = quant.quantize_params(
            tfm.init_params(cfg, jax.random.PRNGKey(50)))
        prompt = jnp.array([[1, 2, 3], [4, 5, 6], [7, 8, 9], [3, 1, 2]],
                           jnp.int32)
        ref = tfm.generate(qp, cfg, prompt, max_new=8)
        sharded = quant.shard_quantized(qp, cfg, mesh)
        got = tfm.generate(sharded, cfg, prompt, max_new=8, mesh=mesh)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))

    def test_quantized_gqa_tp_decode_bit_identical(self, devices):
        from jax.sharding import Mesh
        from hpx_tpu.models import quant
        mesh = Mesh(np.array(devices[:4]).reshape(2, 2), ("dp", "tp"))
        qp = quant.quantize_params(
            tfm.init_params(GQA_CFG, jax.random.PRNGKey(51)))
        prompt = jnp.array([[1, 2, 3], [4, 5, 6], [7, 8, 9], [2, 2, 2]],
                           jnp.int32)
        ref = tfm.generate(qp, GQA_CFG, prompt, max_new=6)
        got = tfm.generate(quant.shard_quantized(qp, GQA_CFG, mesh),
                           GQA_CFG, prompt, max_new=6, mesh=mesh)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))

    def test_scales_actually_sharded_with_channels(self, devices):
        from jax.sharding import Mesh
        from hpx_tpu.models import quant
        mesh = Mesh(np.array(devices[:4]).reshape(2, 2), ("dp", "tp"))
        cfg = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                    head_dim=8, n_layers=1, d_ff=64)
        sharded = quant.shard_quantized(
            quant.quantize_params(tfm.init_params(
                cfg, jax.random.PRNGKey(52))), cfg, mesh)
        lp = sharded["layers"][0]
        # wqkv q and its scales both split their head axis over tp
        q_sh = lp["wqkv"].q.sharding.spec
        s_sh = lp["wqkv"].s.sharding.spec
        assert "tp" in tuple(q_sh) and "tp" in tuple(s_sh), (q_sh, s_sh)
        # w2's contracted f axis is tp-sharded, its scales replicated
        assert tuple(lp["w2"].q.sharding.spec)[0] == "tp"
        assert all(a is None for a in tuple(lp["w2"].s.sharding.spec))


class TestQuantizedMoE:
    """int8 expert weights for MoE serving: w1/w2 quantized per
    (expert, output channel); router and biases stay dense."""

    MOE_Q_CFG = tfm.TransformerConfig(
        vocab=64, d_model=32, n_heads=4, head_dim=8, n_layers=2,
        d_ff=64, n_experts=4, moe_top_k=2, moe_capacity=4.0)

    def test_quantized_moe_decode_matches_dense(self):
        from hpx_tpu.models import quant
        params = tfm.init_params(self.MOE_Q_CFG, jax.random.PRNGKey(60))
        qp = quant.quantize_params(params)
        lp = qp["layers"][0]["moe"]
        assert isinstance(lp["w1"], quant.QTensor)
        assert isinstance(lp["w2"], quant.QTensor)
        assert not isinstance(lp["wg"], quant.QTensor)   # router dense
        prompt = jnp.array([[1, 2, 3, 4], [5, 6, 7, 8]], jnp.int32)
        dense = tfm.generate(params, self.MOE_Q_CFG, prompt, max_new=6)
        q = tfm.generate(qp, self.MOE_Q_CFG, prompt, max_new=6)
        # int8 rounding can flip a rare near-tie; anything below high
        # agreement means the scales are wrong
        agree = float((np.asarray(q) == np.asarray(dense)).mean())
        assert agree >= 0.9, agree
        assert q.shape == dense.shape

    def test_expert_weight_roundtrip_error_bounded(self):
        from hpx_tpu.models import quant
        params = tfm.init_params(self.MOE_Q_CFG, jax.random.PRNGKey(61))
        qp = quant.quantize_params(params)
        for name in ("w1", "w2"):
            w = np.asarray(params["layers"][0]["moe"][name], np.float32)
            wq = np.asarray(quant.dequant(
                qp["layers"][0]["moe"][name], jnp.float32))
            rel = np.linalg.norm(w - wq) / np.linalg.norm(w)
            assert rel < 0.01, (name, rel)

    def test_quantized_moe_specs_tree_matches(self):
        from jax.sharding import PartitionSpec
        from hpx_tpu.models import quant
        params = tfm.init_params(self.MOE_Q_CFG, jax.random.PRNGKey(62))
        qp = quant.quantize_params(params)
        specs = quant.quantized_param_specs(self.MOE_Q_CFG)
        # STRUCTURE equality (tree.map alone flattens specs only up to
        # qp's structure and would accept nested garbage), and every
        # spec leaf is an actual PartitionSpec — catches the
        # shared-moe-dict double-wrap regression
        assert (jax.tree.structure(qp)
                == jax.tree.structure(specs)), "tree mismatch"
        for leaf in jax.tree.leaves(specs):
            assert isinstance(leaf, PartitionSpec), leaf


# -- speculative decoding ----------------------------------------------------

class TestSpeculativeDecoding:
    """speculative_generate must emit generate(temperature=0)'s tokens
    — the draft changes throughput, never content. (Exact equality
    holds when no position's top-2 target logits are within the window
    vs sequential forward's ~1e-4 reassociation gap; these f32 models
    at fixed seeds have no such ties.)"""

    DRAFT = tfm.TransformerConfig(vocab=64, d_model=16, n_heads=2,
                                  head_dim=8, n_layers=1, d_ff=32)

    def test_window_forward_matches_sequential(self):
        """_decode_window == a scan of _decode_forward on the same
        tokens (validates the multi-token mask/rope generalization of
        _block_decode directly)."""
        params = tfm.init_params(CFG, jax.random.PRNGKey(3))
        toks = jnp.array([[5, 9, 11, 2], [7, 1, 3, 8]], jnp.int32)
        b, w = toks.shape
        smax = 16

        def fresh():
            return [(jnp.zeros((b, smax, CFG.kv_heads, CFG.head_dim),
                               CFG.dtype),
                     jnp.zeros((b, smax, CFG.kv_heads, CFG.head_dim),
                               CFG.dtype))
                    for _ in range(CFG.n_layers)]

        _, win_logits = tfm._decode_window(params, fresh(), toks, 0, CFG)
        caches = fresh()
        seq_logits = []
        for i in range(w):
            caches, lg = tfm._decode_forward(params, caches, toks[:, i],
                                             i, CFG)
            seq_logits.append(lg)
        np.testing.assert_allclose(np.asarray(win_logits),
                                   np.stack(seq_logits, axis=1),
                                   rtol=2e-4, atol=2e-4)

    def test_draft_equals_target_all_accepted(self):
        params = tfm.init_params(CFG, jax.random.PRNGKey(6))
        prompt = jnp.array([[1, 2, 3], [4, 5, 6]], jnp.int32)
        ref = tfm.generate(params, CFG, prompt, max_new=8)
        out = tfm.speculative_generate(params, CFG, params, CFG, prompt,
                                       max_new=8, k=3)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    @pytest.mark.parametrize("k", [1, 2, 4, 7])
    def test_small_draft_matches_greedy(self, k):
        params = tfm.init_params(CFG, jax.random.PRNGKey(6))
        draft = tfm.init_params(self.DRAFT, jax.random.PRNGKey(7))
        prompt = jnp.array([[1, 2, 3, 4], [9, 8, 7, 6],
                            [0, 0, 0, 0]], jnp.int32)
        ref = tfm.generate(params, CFG, prompt, max_new=11)
        out = tfm.speculative_generate(params, CFG, draft, self.DRAFT,
                                       prompt, max_new=11, k=k)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_rejects_bad_args(self):
        params = tfm.init_params(CFG, jax.random.PRNGKey(6))
        draft = tfm.init_params(self.DRAFT, jax.random.PRNGKey(7))
        prompt = jnp.array([[1, 2]], jnp.int32)
        with pytest.raises(ValueError, match="k must be"):
            tfm.speculative_generate(params, CFG, draft, self.DRAFT,
                                     prompt, max_new=4, k=0)
        bad = dataclasses.replace(self.DRAFT, vocab=32)
        with pytest.raises(ValueError, match="vocab"):
            tfm.speculative_generate(params, CFG, draft, bad, prompt,
                                     max_new=4)

    def test_full_acceptance_rounds_near_minimal(self):
        """Self-draft must accept ~k+1 tokens per round for the WHOLE
        run. Regression: a draft-cache KV hole after a fully-accepted
        round silently collapses later acceptance (outputs stay
        correct — only the round count shows it)."""
        import math as _math
        params = tfm.init_params(CFG, jax.random.PRNGKey(6))
        prompt = jnp.array([[1, 2, 3]], jnp.int32)
        max_new, k = 20, 3
        out, rounds = tfm.speculative_generate(
            params, CFG, params, CFG, prompt, max_new=max_new, k=k,
            return_stats=True)
        ref = tfm.generate(params, CFG, prompt, max_new=max_new)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
        # 19 tokens after tok0 at k+1=4 per round -> 5 rounds minimum;
        # allow +1 slack for a float argmax tie, never the collapse
        assert int(rounds) <= _math.ceil((max_new - 1) / (k + 1)) + 1, \
            f"acceptance collapsed: {int(rounds)} rounds"

    def test_sharded_matches_single_device(self, devices):
        """dp2/tp2 speculative decode emits the same tokens as the
        single-device run (per-dp-shard loops may diverge in trip
        count; content must not)."""
        from jax.sharding import Mesh
        cfg = dataclasses.replace(CFG, n_kv_heads=2, rope=True)
        params = tfm.init_params(cfg, jax.random.PRNGKey(6))
        draft = tfm.init_params(self.DRAFT, jax.random.PRNGKey(7))
        prompt = jnp.array([[1, 2, 3, 4], [9, 8, 7, 6],
                            [5, 5, 5, 5], [2, 4, 6, 8]], jnp.int32)
        single = tfm.speculative_generate(params, cfg, draft,
                                          self.DRAFT, prompt,
                                          max_new=9, k=3)
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                    ("dp", "tp"))
        sharded, rounds = tfm.speculative_generate(
            tfm.shard_params(params, cfg, mesh), cfg, draft,
            self.DRAFT, prompt, max_new=9, k=3, mesh=mesh,
            return_stats=True)
        np.testing.assert_array_equal(np.asarray(sharded),
                                      np.asarray(single))
        assert rounds.shape == (4,) and (np.asarray(rounds) >= 1).all()


class TestInt4Quantization:
    def test_pack_unpack_roundtrip(self):
        from hpx_tpu.models import quant
        rng = np.random.default_rng(0)
        for shape, axis in [((8, 6), 0), ((3, 8, 4), 1), ((2, 4, 6), 2)]:
            q = jnp.asarray(rng.integers(-7, 8, shape), jnp.int8)
            packed = quant._pack4(q, axis)
            assert packed.shape[axis] == shape[axis] // 2
            np.testing.assert_array_equal(
                np.asarray(quant._unpack4(packed, axis)), np.asarray(q))
        with pytest.raises(ValueError, match="even"):
            quant._pack4(jnp.zeros((3, 4), jnp.int8), 0)

    def test_int4_error_bounded_and_4x_smaller(self):
        from hpx_tpu.models import quant
        cfg = tfm.TransformerConfig(vocab=64, d_model=64, n_heads=4,
                                    head_dim=16, n_layers=2, d_ff=128)
        params = tfm.init_params(cfg, jax.random.PRNGKey(40))
        q4 = quant.quantize_params(params, bits=4)
        assert quant.quantized_bits(q4) == 4
        # per-element roundtrip error <= s/2 (15-level symmetric grid)
        w = params["layers"][0]["w1"]
        t4 = q4["layers"][0]["w1"]
        back = np.asarray(quant.dequant(t4, jnp.float32))
        err = np.abs(back - np.asarray(w, np.float32))
        assert (err <= np.asarray(t4.s) / 2 + 1e-6).all()
        # storage: ~4x smaller than f32 weights (scales add a little)
        dense_b = quant.quantized_bytes(params["layers"])
        q4_b = quant.quantized_bytes(q4["layers"])
        assert dense_b / q4_b > 3.0, (dense_b, q4_b)
        q8_b = quant.quantized_bytes(
            quant.quantize_params(params)["layers"])
        assert q8_b / q4_b > 1.6, (q8_b, q4_b)

    def test_int4_decode_runs_and_logits_close(self):
        from hpx_tpu.models import quant
        cfg = tfm.TransformerConfig(vocab=64, d_model=64, n_heads=4,
                                    head_dim=16, n_layers=2, d_ff=128)
        params = tfm.init_params(cfg, jax.random.PRNGKey(40))
        q4 = quant.quantize_params(params, bits=4)
        prompt = jnp.array([[1, 2, 3, 4]], jnp.int32)
        out = tfm.generate(q4, cfg, prompt, max_new=6)
        assert out.shape == (1, 6)
        assert (np.asarray(out) >= 0).all() and \
            (np.asarray(out) < cfg.vocab).all()

    def test_int4_tp_decode_bit_identical(self, devices):
        from jax.sharding import Mesh
        from hpx_tpu.models import quant
        mesh = Mesh(np.array(devices[:4]).reshape(2, 2), ("dp", "tp"))
        cfg = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                    head_dim=8, n_layers=2, d_ff=64)
        q4 = quant.quantize_params(
            tfm.init_params(cfg, jax.random.PRNGKey(50)), bits=4)
        prompt = jnp.array([[1, 2, 3], [4, 5, 6], [7, 8, 9], [3, 1, 2]],
                           jnp.int32)
        ref = tfm.generate(q4, cfg, prompt, max_new=8)
        sharded = quant.shard_quantized(q4, cfg, mesh)
        got = tfm.generate(sharded, cfg, prompt, max_new=8, mesh=mesh)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))

    def test_int4_moe_decode_runs(self):
        from hpx_tpu.models import quant
        cfg = tfm.TransformerConfig(vocab=32, d_model=32, n_heads=4,
                                    head_dim=8, n_layers=1, d_ff=64,
                                    n_experts=4)
        params = tfm.init_params(cfg, jax.random.PRNGKey(9))
        q4 = quant.quantize_params(params, bits=4)
        out = tfm.generate(q4, cfg,
                           jnp.array([[1, 2]], jnp.int32), max_new=4)
        assert out.shape == (1, 4)

    def test_int4_odd_local_heads_pack_unsharded_axis(self, devices):
        """wo packs head_dim, not the tp-sharded heads axis: n_heads=6
        with tp=2 (odd local head count) must shard + decode fine."""
        from jax.sharding import Mesh
        from hpx_tpu.models import quant
        mesh = Mesh(np.array(devices[:4]).reshape(2, 2), ("dp", "tp"))
        cfg = tfm.TransformerConfig(vocab=64, d_model=24, n_heads=6,
                                    head_dim=8, n_layers=1, d_ff=64)
        q4 = quant.quantize_params(
            tfm.init_params(cfg, jax.random.PRNGKey(51)), bits=4)
        prompt = jnp.array([[1, 2], [3, 4], [5, 6], [7, 8]], jnp.int32)
        ref = tfm.generate(q4, cfg, prompt, max_new=5)
        got = tfm.generate(quant.shard_quantized(q4, cfg, mesh), cfg,
                           prompt, max_new=5, mesh=mesh)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))

    def test_int4_sharded_pack_axis_validated(self, devices):
        """d_ff not a multiple of 2*tp: clear error, not a device_put
        shape failure."""
        from jax.sharding import Mesh
        from hpx_tpu.models import quant
        mesh = Mesh(np.array(devices[:4]).reshape(2, 2), ("dp", "tp"))
        cfg = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                    head_dim=8, n_layers=1, d_ff=66)
        q4 = quant.quantize_params(
            tfm.init_params(cfg, jax.random.PRNGKey(52)), bits=4)
        with pytest.raises(ValueError, match="nibble pairs"):
            quant.shard_quantized(q4, cfg, mesh)


class TestSpeculativeSampling:
    """speculative_sample: the exact acceptance-rejection algorithm.
    Emitted tokens must be distributed as target-only sampling."""

    SMALL = tfm.TransformerConfig(vocab=8, d_model=16, n_heads=2,
                                  head_dim=8, n_layers=1, d_ff=32)
    SDRAFT = tfm.TransformerConfig(vocab=8, d_model=8, n_heads=1,
                                   head_dim=8, n_layers=1, d_ff=16)

    def test_valid_and_deterministic(self):
        params = tfm.init_params(CFG, jax.random.PRNGKey(6))
        draft = tfm.init_params(
            TestSpeculativeDecoding.DRAFT, jax.random.PRNGKey(7))
        prompt = jnp.array([[1, 2, 3]], jnp.int32)
        out = tfm.speculative_sample(params, CFG, draft,
                                     TestSpeculativeDecoding.DRAFT,
                                     prompt, max_new=9, k=3,
                                     key=jax.random.PRNGKey(11))
        assert out.shape == (1, 9)
        assert (np.asarray(out) >= 0).all() and \
            (np.asarray(out) < CFG.vocab).all()
        out2 = tfm.speculative_sample(params, CFG, draft,
                                      TestSpeculativeDecoding.DRAFT,
                                      prompt, max_new=9, k=3,
                                      key=jax.random.PRNGKey(11))
        np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))

    def test_self_draft_accepts_nearly_everything(self):
        import math as _math
        params = tfm.init_params(CFG, jax.random.PRNGKey(6))
        prompt = jnp.array([[1, 2, 3]], jnp.int32)
        max_new, k = 20, 3
        _, rounds = tfm.speculative_sample(
            params, CFG, params, CFG, prompt, max_new=max_new, k=k,
            key=jax.random.PRNGKey(4), return_stats=True)
        # p == q (up to window/sequential reassociation), so the
        # acceptance probability is ~1 at every step
        assert int(rounds) <= _math.ceil((max_new - 1) / (k + 1)) + 2, \
            int(rounds)

    def test_rejects_bad_args(self):
        params = tfm.init_params(self.SMALL, jax.random.PRNGKey(0))
        draft = tfm.init_params(self.SDRAFT, jax.random.PRNGKey(1))
        two = jnp.array([[1, 2], [3, 4]], jnp.int32)
        one = jnp.array([[1, 2]], jnp.int32)
        with pytest.raises(ValueError, match="single-stream"):
            tfm.speculative_sample(params, self.SMALL, draft,
                                   self.SDRAFT, two, max_new=4,
                                   key=jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="PRNG key"):
            tfm.speculative_sample(params, self.SMALL, draft,
                                   self.SDRAFT, one, max_new=4)
        with pytest.raises(ValueError, match="temperature"):
            tfm.speculative_sample(params, self.SMALL, draft,
                                   self.SDRAFT, one, max_new=4,
                                   temperature=0.0,
                                   key=jax.random.PRNGKey(0))

    @pytest.mark.slow
    def test_distribution_matches_target_sampling(self):
        """Two-sample check: the SECOND emitted token (the first that
        exercises draft/accept/resample) must match target-only
        sampling's marginal. TV noise at n=1200, V=8 is ~0.08; the
        0.15 gate catches a wrong acceptance rule (which shifts mass
        by O(d_TV(p, q)) — large for this mismatched draft) while
        staying flake-free."""
        params = tfm.init_params(self.SMALL, jax.random.PRNGKey(0))
        draft = tfm.init_params(self.SDRAFT, jax.random.PRNGKey(1))
        prompt = jnp.array([[1, 2]], jnp.int32)
        n = 1200
        spec = np.zeros(8)
        ref = np.zeros(8)
        for i in range(n):
            o = tfm.speculative_sample(params, self.SMALL, draft,
                                       self.SDRAFT, prompt, max_new=2,
                                       k=2, key=jax.random.PRNGKey(i))
            spec[int(np.asarray(o)[0, 1])] += 1
            r = tfm.generate(params, self.SMALL, prompt, max_new=2,
                             temperature=1.0,
                             key=jax.random.PRNGKey(10_000 + i))
            ref[int(np.asarray(r)[0, 1])] += 1
        tv = 0.5 * np.abs(spec / n - ref / n).sum()
        assert tv < 0.15, (tv, spec, ref)


def test_speculative_eos_matches_generate():
    """eos pinning through speculative decode matches generate's
    done-row pinning exactly (greedy)."""
    params = tfm.init_params(CFG, jax.random.PRNGKey(6))
    draft = tfm.init_params(TestSpeculativeDecoding.DRAFT,
                            jax.random.PRNGKey(7))
    prompt = jnp.array([[1, 2, 3], [4, 5, 6]], jnp.int32)
    plain = np.asarray(tfm.generate(params, CFG, prompt, max_new=10))
    eos = int(plain[0, 2])            # a token greedy actually emits
    ref = tfm.generate(params, CFG, prompt, max_new=10, eos_id=eos)
    out = tfm.speculative_generate(params, CFG, draft,
                                   TestSpeculativeDecoding.DRAFT,
                                   prompt, max_new=10, k=3, eos_id=eos)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    # sampled path: tail after first eos is pinned
    o2 = np.asarray(tfm.speculative_sample(
        params, CFG, draft, TestSpeculativeDecoding.DRAFT, prompt[:1],
        max_new=10, k=3, key=jax.random.PRNGKey(3), eos_id=eos))
    hits = np.where(o2[0] == eos)[0]
    if hits.size:
        assert (o2[0, hits[0]:] == eos).all()


class TestStripedRingTraining:
    """cfg.striped_ring: the train step stripes the batch itself and
    runs the balanced causal ring — losses must match the contiguous
    run (same per-token terms, reordered) and training must learn."""

    def test_losses_match_contiguous(self, mesh3d):
        cfg_c = dataclasses.replace(CFG, rope=True)
        cfg_s = dataclasses.replace(CFG, rope=True, striped_ring=True)
        key = jax.random.PRNGKey(0)
        toks, tgts = tfm.sample_batch(cfg_c, batch=4, seq=32,
                                      key=jax.random.PRNGKey(1))
        toks, tgts = tfm.shard_batch(toks, tgts, mesh3d)
        losses = {}
        for name, cfg in (("contig", cfg_c), ("striped", cfg_s)):
            params = tfm.shard_params(tfm.init_params(cfg, key), cfg,
                                      mesh3d)
            step = tfm.make_train_step(cfg, mesh3d)
            ls = []
            for _ in range(3):
                params, lo = step(params, toks, tgts)
                ls.append(float(lo))
            losses[name] = ls
        np.testing.assert_allclose(losses["striped"], losses["contig"],
                                   rtol=2e-4)
        assert losses["striped"][-1] < losses["striped"][0]

    def test_pipelined_rejects_striped(self, mesh3d):
        cfg = dataclasses.replace(CFG, striped_ring=True)
        from jax.sharding import Mesh
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(1, 2, 2),
                    ("dp", "pp", "tp"))
        with pytest.raises(NotImplementedError, match="striped"):
            tfm.make_pipelined_train_step(cfg, mesh, n_microbatches=2)


def test_speculative_with_quantized_target():
    """int8 target through speculative decode == int8 greedy decode
    (the draft never changes which weights produce tokens). Same
    fixed-seed tie caveat as TestSpeculativeDecoding."""
    from hpx_tpu.models import quant
    qp = quant.quantize_params(tfm.init_params(CFG, jax.random.PRNGKey(2)))
    draft = tfm.init_params(TestSpeculativeDecoding.DRAFT,
                            jax.random.PRNGKey(3))
    prompt = jnp.array([[5, 6, 7]], jnp.int32)
    ref = tfm.generate(qp, CFG, prompt, max_new=8)
    out = tfm.speculative_generate(qp, CFG, draft,
                                   TestSpeculativeDecoding.DRAFT,
                                   prompt, max_new=8, k=3)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
