"""Collective-schedule assertions on the compiled multi-chip programs.

MULTICHIP_r*.json proves the sharded train steps run and converge; these
tests pin down WHAT the compiler was given — the collective schedule —
so a refactor that silently starts all-gathering sharded params, doubles
the ring hops, or breaks the pipeline schedule fails here instead of
only showing up as a pod-scale perf cliff (SURVEY.md §5.8: the data
plane must ride explicit XLA collectives, not accidental reshards).

Two layers of assertion:

* jaxpr walk (platform-independent, structural): counts of the
  collective primitives our shard_map bodies emit — psum / ppermute /
  all_to_all / all_gather — and the scan trip counts that encode the
  ring and pipeline schedules.
* compiled HLO (CPU backend, 8 virtual devices): no all-gather ops at
  all in the dense train step (sharded params must never be
  materialized), and the all-reduce count stays O(#param leaves) — the
  per-leaf grad psums plus a handful of scalar loss/count reductions.
  (The TPU backend's AllReduceCombiner then fuses those into one or
  two fused reduces; the CPU pipeline doesn't run it, so fusion itself
  is not asserted here.)
"""

from collections import Counter

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hpx_tpu.models import transformer as tfm


def _subjaxprs(v):
    out = []
    if hasattr(v, "eqns"):
        out.append(v)
    elif hasattr(v, "jaxpr"):
        out.append(v.jaxpr)
    elif isinstance(v, (list, tuple)):
        for x in v:
            out.extend(_subjaxprs(x))
    return out


def collective_counts(fn, *args):
    """(Counter of primitive names, list of scan trip counts), walking
    nested jaxprs (shard_map / scan / cond bodies)."""
    counts: Counter = Counter()
    scans = []

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            counts[eqn.primitive.name] += 1
            if eqn.primitive.name == "scan":
                scans.append(eqn.params.get("length"))
            for v in eqn.params.values():
                for sj in _subjaxprs(v):
                    walk(sj)

    walk(jax.make_jaxpr(fn)(*args).jaxpr)
    return counts, scans


def _psums(counts):
    return sum(v for k, v in counts.items() if k.startswith("psum"))


def _all_gathers(counts):
    return sum(v for k, v in counts.items() if k.startswith("all_gather"))


def _dense_setup(mesh, n_layers):
    cfg = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                head_dim=8, n_layers=n_layers, d_ff=32,
                                lr=0.05)
    params = tfm.shard_params(
        tfm.init_params(cfg, jax.random.PRNGKey(0)), cfg, mesh)
    step = tfm.make_train_step(cfg, mesh)
    dp, sp = mesh.shape["dp"], mesh.shape["sp"]
    toks, tgts = tfm.sample_batch(cfg, batch=2 * dp, seq=8 * sp,
                                  key=jax.random.PRNGKey(1))
    toks, tgts = tfm.shard_batch(toks, tgts, mesh)
    return cfg, params, step, toks, tgts


def test_dense_dp_sp_tp_schedule(devices):
    """dp2 x sp2 x tp2: ring-attention ppermutes scale with layers and
    nothing ever all-gathers or all-to-alls."""
    mesh = tfm.make_mesh_3d(8)
    sp = mesh.shape["sp"]
    per_layer = {}
    for n_layers in (2, 4):
        _, params, step, toks, tgts = _dense_setup(mesh, n_layers)
        counts, scans = collective_counts(step, params, toks, tgts)
        assert _all_gathers(counts) == 0, counts
        assert counts.get("all_to_all", 0) == 0, counts
        assert _psums(counts) > 0
        # every ring scan walks exactly the sp chunks
        ring_scans = [s for s in scans if s == sp]
        assert ring_scans, scans
        per_layer[n_layers] = counts.get("ppermute", 0)
    # ppermute sites come from the per-layer ring attention (fwd+bwd);
    # doubling layers must exactly double them — anything more means a
    # second unintended exchange crept in
    assert per_layer[4] == 2 * per_layer[2], per_layer
    assert per_layer[2] > 0


def test_moe_expert_all_to_all_schedule(devices):
    """dp/ep MoE: exactly one dispatch + one combine all_to_all per MoE
    layer per direction (fwd, bwd) — the GShard shape."""
    mesh = tfm.make_mesh_3d(8)
    dp, sp = mesh.shape["dp"], mesh.shape["sp"]
    for n_layers in (2, 4):
        cfg = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                    head_dim=8, n_layers=n_layers,
                                    d_ff=32, lr=0.05, n_experts=4,
                                    moe_top_k=2, moe_capacity=4.0)
        params = tfm.shard_params(
            tfm.init_params(cfg, jax.random.PRNGKey(2)), cfg, mesh)
        step = tfm.make_train_step(cfg, mesh)
        toks, tgts = tfm.sample_batch(cfg, batch=2 * dp, seq=8 * sp,
                                      key=jax.random.PRNGKey(3))
        toks, tgts = tfm.shard_batch(toks, tgts, mesh)
        counts, _ = collective_counts(step, params, toks, tgts)
        assert counts.get("all_to_all", 0) == 4 * n_layers, (
            n_layers, counts)
        assert _all_gathers(counts) == 0, counts


@pytest.mark.parametrize("interleave,n_micro", [(1, 4), (2, 4)])
def test_pipeline_schedule_length(devices, interleave, n_micro):
    """The pipeline scan trip count IS the schedule: M*V + P - 1 steps
    (GPipe at V=1, Megatron interleaved at V=2), once forward and once
    in the AD-reversed backward, with the stage handoff as ppermute
    sites (one static site per direction, executed per step)."""
    pp = 4
    mesh = Mesh(np.array(jax.devices()).reshape(2, pp, 1),
                ("dp", "pp", "tp"))
    cfg = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                head_dim=8, n_layers=2 * pp, d_ff=32,
                                lr=0.05)
    stacked = tfm.prepare_pipeline_params(
        tfm.init_params(cfg, jax.random.PRNGKey(4)), mesh,
        interleave=interleave)
    step = tfm.make_pipelined_train_step(cfg, mesh,
                                         n_microbatches=n_micro,
                                         interleave=interleave)
    toks, tgts = tfm.sample_batch(cfg, batch=2 * 2 * n_micro, seq=8,
                                  key=jax.random.PRNGKey(5))
    sh = NamedSharding(mesh, P("dp", None))
    toks, tgts = jax.device_put(toks, sh), jax.device_put(tgts, sh)
    counts, scans = collective_counts(step, stacked, toks, tgts)
    sched = n_micro * interleave + pp - 1
    assert scans.count(sched) == 2, (sched, scans)   # fwd + bwd scans
    assert counts.get("ppermute", 0) == 2, counts    # handoff + transpose
    assert _all_gathers(counts) == 0, counts
    assert counts.get("all_to_all", 0) == 0, counts


@pytest.mark.slow
def test_compiled_dp_grads_no_gather_bounded_reduces(devices):
    """Compiled (SPMD-partitioned) HLO of the dp-only train step: zero
    all-gather ops — sharded activations/params are never materialized
    — and the all-reduce count stays O(#param leaves): the per-leaf dp
    grad psums plus a few scalar loss/count reductions. A structural
    regression (e.g. a jit boundary resharding params) would show up
    here as all-gathers or a blow-up in reduce count."""
    mesh = Mesh(np.array(jax.devices()).reshape(8, 1, 1),
                ("dp", "sp", "tp"))
    _, params, step, toks, tgts = _dense_setup(mesh, 2)
    txt = jax.jit(step).lower(params, toks, tgts).compile().as_text()
    lines = txt.splitlines()
    n_ar = sum(1 for ln in lines
               if "all-reduce(" in ln or "all-reduce-start(" in ln)
    n_ag = sum(1 for ln in lines
               if "all-gather(" in ln or "all-gather-start(" in ln)
    n_leaves = len(jax.tree.leaves(params))
    assert n_ag == 0, n_ag
    assert 1 <= n_ar <= n_leaves + 6, (n_ar, n_leaves)


def test_striped_train_step_schedule(devices):
    """striped_ring: SAME ring collectives as contiguous (ppermute
    sites and ring scan lengths unchanged — striping must never add
    hops), plus the batch stripe before the shard_map (lowered by XLA
    from the reshape-transpose; asserted structurally: the jaxpr gains
    no extra collective primitives)."""
    import dataclasses
    mesh = tfm.make_mesh_3d(8)
    sp = mesh.shape["sp"]
    cfg_c = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                  head_dim=8, n_layers=2, d_ff=32,
                                  lr=0.05, rope=True)
    cfg_s = dataclasses.replace(cfg_c, striped_ring=True)
    results = {}
    for name, cfg in (("contig", cfg_c), ("striped", cfg_s)):
        params = tfm.shard_params(
            tfm.init_params(cfg, jax.random.PRNGKey(0)), cfg, mesh)
        step = tfm.make_train_step(cfg, mesh)
        dp = mesh.shape["dp"]
        toks, tgts = tfm.sample_batch(cfg, batch=2 * dp, seq=8 * sp,
                                      key=jax.random.PRNGKey(1))
        toks, tgts = tfm.shard_batch(toks, tgts, mesh)
        counts, scans = collective_counts(step, params, toks, tgts)
        results[name] = (counts, scans)
    cc, sc = results["contig"]
    cs, ss = results["striped"]
    assert cs.get("ppermute", 0) == cc.get("ppermute", 0), (cs, cc)
    assert [s for s in ss if s == sp] == [s for s in sc if s == sp]
    assert _all_gathers(cs) == _all_gathers(cc) == 0
    assert cs.get("all_to_all", 0) == cc.get("all_to_all", 0) == 0


def test_sharded_speculative_decode_schedule(devices):
    """dp x tp speculative decode: tp psums close the Megatron
    contractions; params are never all-gathered; NO collective crosses
    dp (each dp shard's acceptance loop runs free — a dp collective
    inside the loop would deadlock diverging trip counts)."""
    mesh = Mesh(np.array(devices[:4]).reshape(2, 2), ("dp", "tp"))
    cfg = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                head_dim=8, n_layers=2, d_ff=64)
    dcfg = tfm.TransformerConfig(vocab=64, d_model=16, n_heads=2,
                                 head_dim=8, n_layers=1, d_ff=32)
    params = tfm.shard_params(tfm.init_params(cfg, jax.random.PRNGKey(0)),
                              cfg, mesh)
    draft = tfm.init_params(dcfg, jax.random.PRNGKey(1))
    prompt = jnp.ones((4, 4), jnp.int32)

    def run(params, draft, prompt):
        return tfm.speculative_generate(params, cfg, draft, dcfg,
                                        prompt, max_new=6, k=2,
                                        mesh=mesh)

    counts, _ = collective_counts(run, params, draft, prompt)
    assert _all_gathers(counts) == 0, counts
    assert _psums(counts) > 0, counts        # Megatron tp closes
    assert counts.get("all_to_all", 0) == 0, counts
    # axis-name walk: every psum must name ONLY tp (dp-crossing
    # collectives inside diverging loops would deadlock)
    def axes_used(fn, *args):
        names = set()

        def walk(jaxpr):
            for eqn in jaxpr.eqns:
                ax = eqn.params.get("axes") or eqn.params.get(
                    "axis_name")
                if eqn.primitive.name.startswith(("psum", "ppermute",
                                                  "all_")):
                    if ax is not None:
                        names.update(ax if isinstance(ax, (tuple, list))
                                     else [ax])
                for v in eqn.params.values():
                    for sj in _subjaxprs(v):
                        walk(sj)

        walk(jax.make_jaxpr(fn)(*args).jaxpr)
        return names

    assert axes_used(run, params, draft, prompt) <= {"tp"}
