"""M9 tests: checkpoint/restore, resiliency, logging, iostreams,
profiler bridge (SURVEY.md §2.5, §5.1, §5.3, §5.4)."""

import io
import os
import threading

import jax.numpy as jnp
import numpy as np
import pytest

import hpx_tpu as hpx
from hpx_tpu.testing import HPX_TEST, HPX_TEST_EQ

REPO = os.path.join(os.path.dirname(__file__), "..")


# -- checkpoint ---------------------------------------------------------------

class TestCheckpoint:
    def test_roundtrip_basic_values(self):
        cp = hpx.save_checkpoint(1, "two", [3.0, {"four": 4}]).get()
        HPX_TEST_EQ(hpx.restore_checkpoint(cp), (1, "two", [3.0, {"four": 4}]))

    def test_futures_store_their_values(self):
        f = hpx.async_(lambda: 41 + 1)
        cp = hpx.save_checkpoint(f, "tag").get()
        HPX_TEST_EQ(hpx.restore_checkpoint(cp), (42, "tag"))

    def test_jax_arrays_roundtrip(self):
        u = jnp.arange(100, dtype=jnp.float32) * 1.5
        (v, n) = hpx.restore_checkpoint(hpx.save_checkpoint(u, 7).get())
        np.testing.assert_array_equal(np.asarray(v), np.asarray(u))
        HPX_TEST_EQ(n, 7)

    def test_partitioned_vector_roundtrip(self, mesh1d):
        layout = hpx.container_layout(8, mesh=mesh1d)
        pv = hpx.PartitionedVector.from_array(
            np.arange(64, dtype=np.float32), layout)
        (pv2,) = hpx.restore_checkpoint(hpx.save_checkpoint(pv).get())
        HPX_TEST(isinstance(pv2, hpx.PartitionedVector))
        HPX_TEST_EQ(pv2.num_partitions, 8)
        np.testing.assert_array_equal(pv2.to_numpy(), pv.to_numpy())

    def test_file_roundtrip(self, tmp_path):
        path = tmp_path / "state.ckpt"
        hpx.save_checkpoint_to_file(path, {"step": 10},
                                    jnp.ones(8)).get()
        state, arr = hpx.restore_checkpoint_from_file(path)
        HPX_TEST_EQ(state["step"], 10)
        np.testing.assert_array_equal(np.asarray(arr), np.ones(8))

    def test_stream_roundtrip(self):
        cp = hpx.save_checkpoint("x").get()
        buf = io.BytesIO()
        cp.write(buf)
        buf.seek(0)
        HPX_TEST_EQ(hpx.Checkpoint.read(buf), cp)

    def test_bad_stream_raises(self):
        with pytest.raises(ValueError):
            hpx.Checkpoint.read(io.BytesIO(b"not a checkpoint"))

    def test_truncated_after_magic_raises(self):
        cp = hpx.save_checkpoint("x").get()
        buf = io.BytesIO()
        cp.write(buf)
        whole = buf.getvalue()
        for cut in (12, 15, 25):  # after magic, mid-header, mid-payload
            with pytest.raises(ValueError):
                hpx.Checkpoint.read(io.BytesIO(whole[:cut]))

    def test_stencil_checkpoint_resume(self):
        # the reference's 1d_stencil checkpoint variant, in miniature:
        # run T steps, checkpoint, run T more, vs 2T straight
        from hpx_tpu.models.stencil1d import StencilParams, stencil_fused
        p1 = StencilParams(nx=64, np_=4, nt=10)
        u_mid = stencil_fused(p1)
        (r,) = hpx.restore_checkpoint(hpx.save_checkpoint(u_mid).get())
        u_res = stencil_fused(p1, u0=r)
        u_straight = stencil_fused(StencilParams(nx=64, np_=4, nt=20))
        np.testing.assert_allclose(np.asarray(u_res),
                                   np.asarray(u_straight), rtol=1e-5)


# -- resiliency ---------------------------------------------------------------

class _Flaky:
    """Fails the first k calls, then succeeds."""

    def __init__(self, k: int, value=123):
        self.k = k
        self.value = value
        self.calls = 0
        self._lock = threading.Lock()

    def __call__(self):
        with self._lock:
            self.calls += 1
            if self.calls <= self.k:
                raise RuntimeError(f"transient #{self.calls}")
        return self.value


class TestReplay:
    def test_succeeds_after_transient_failures(self):
        f = _Flaky(2)
        HPX_TEST_EQ(hpx.async_replay(4, f).get(), 123)
        HPX_TEST_EQ(f.calls, 3)

    def test_exhausted_raises_last_error(self):
        with pytest.raises(RuntimeError, match="transient #3"):
            hpx.async_replay(3, _Flaky(99)).get()

    def test_validate(self):
        box = [0]

        def step():
            box[0] += 1
            return box[0]

        HPX_TEST_EQ(
            hpx.async_replay_validate(5, lambda v: v >= 3, step).get(), 3)

    def test_validate_exhausted(self):
        with pytest.raises(hpx.ReplayValidationError):
            hpx.async_replay_validate(2, lambda v: False, lambda: 1).get()

    def test_abort_stops_replays(self):
        calls = [0]

        def f():
            calls[0] += 1
            raise hpx.AbortReplayException("fatal")

        with pytest.raises(hpx.AbortReplayException):
            hpx.async_replay(10, f).get()
        HPX_TEST_EQ(calls[0], 1)


class TestReplicate:
    def test_first_good_wins(self):
        HPX_TEST_EQ(hpx.async_replicate(3, lambda: 7).get(), 7)

    def test_tolerates_minority_failures(self):
        state = {"n": 0}
        lock = threading.Lock()

        def f():
            with lock:
                state["n"] += 1
                me = state["n"]
            if me == 1:
                raise RuntimeError("one bad replica")
            return 5

        HPX_TEST_EQ(hpx.async_replicate(3, f).get(), 5)

    def test_all_fail_raises(self):
        def boom():
            raise RuntimeError("dead")
        with pytest.raises(RuntimeError):
            hpx.async_replicate(3, boom).get()

    def test_vote_majority(self):
        state = {"n": 0}
        lock = threading.Lock()

        def f():
            with lock:
                state["n"] += 1
                me = state["n"]
            return 1 if me == 1 else 2   # minority says 1, majority 2

        HPX_TEST_EQ(
            hpx.async_replicate_vote(3, hpx.majority_vote, f).get(), 2)

    def test_vote_arrays(self):
        HPX_TEST_EQ(int(hpx.async_replicate_vote(
            3, hpx.majority_vote, lambda: jnp.float32(4)).get()), 4)

    def test_validate_filters(self):
        state = {"n": 0}
        lock = threading.Lock()

        def f():
            with lock:
                state["n"] += 1
                return state["n"]

        v = hpx.async_replicate_validate(4, lambda x: x % 2 == 0, f).get()
        HPX_TEST(v % 2 == 0)


class TestResiliencyExecutors:
    def test_replay_executor(self):
        f = _Flaky(1, "ok")
        ex = hpx.ReplayExecutor(3)
        HPX_TEST_EQ(ex.async_execute(f).get(), "ok")

    def test_replicate_executor_on_tpu_exec(self):
        ex = hpx.ReplicateExecutor(3, executor=hpx.TpuExecutor())
        out = ex.async_execute(lambda x: x * 2, jnp.float32(21)).get()
        HPX_TEST_EQ(float(out), 42.0)

    def test_replay_executor_on_tpu_exec(self):
        # regression: the replay LOOP must stay host-side; only the
        # attempt payload goes through the (compiling) wrapped executor
        ex = hpx.ReplayExecutor(3, executor=hpx.TpuExecutor())
        out = ex.async_execute(lambda x: x + 1, jnp.float32(41)).get()
        HPX_TEST_EQ(float(out), 42.0)
        HPX_TEST_EQ(float(ex.sync_execute(lambda x: x + 2,
                                          jnp.float32(40))), 42.0)


# -- logging / iostreams / profiling -----------------------------------------

class TestLogging:
    def test_get_logger_and_level(self):
        log = hpx.get_logger("test")
        hpx.set_log_level("debug")
        HPX_TEST(log.isEnabledFor(10))
        hpx.set_log_level("warning")
        HPX_TEST(not log.isEnabledFor(10))
        with pytest.raises(ValueError):
            hpx.set_log_level("nope")


class TestIostreams:
    def test_local_cout_writes_stdout(self, capsys):
        hpx.cout.println("hello from locality 0")
        hpx.cout.flush().get()
        assert "hello from locality 0" in capsys.readouterr().out

    def test_lshift_spelling(self, capsys):
        (hpx.cout << "a=" << 1 << "\n").flush().get()
        assert "a=1" in capsys.readouterr().out


class TestProfiling:
    def test_task_timing_collects(self):
        def named_work():
            return sum(range(100))

        with hpx.profiling.task_timing() as t:
            hpx.wait_all([hpx.async_(named_work) for _ in range(8)])
        rows = t.top()
        HPX_TEST(any("named_work" in name for name, _c, _t in rows), rows)
        name, count, total = [r for r in rows if "named_work" in r[0]][0]
        HPX_TEST(count >= 8)
        HPX_TEST(total >= 0.0)

    def test_observer_removed_after_scope(self):
        from hpx_tpu.runtime import threadpool
        with hpx.profiling.task_timing():
            pass
        HPX_TEST(threadpool._task_observer is None)

    def test_annotate_runs(self):
        with hpx.profiling.annotate("test-region"):
            pass

    def test_device_memory_stats_dict(self):
        HPX_TEST(isinstance(hpx.profiling.device_memory_stats(), dict))


def test_multiprocess_services():
    from hpx_tpu.run import launch
    rc = launch(os.path.join(REPO, "tests", "mp_scripts",
                             "services_smoke.py"),
                [], localities=2, timeout=420.0)
    assert rc == 0


class TestShardedStateCheckpoint:
    """save_sharded_state / restore_sharded_state: a train-state pytree
    of mesh-sharded arrays restores onto a DIFFERENT mesh shape (same
    axis names) with each leaf's PartitionSpec re-placed — the §5.4
    elasticity story in TPU-native form."""

    def _state(self, mesh):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        w = jax.device_put(
            jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
            NamedSharding(mesh, P("x", "y")))
        b = jax.device_put(jnp.arange(8, dtype=jnp.float32),
                           NamedSharding(mesh, P("y")))
        rep = jax.device_put(jnp.float32(0.1),
                             NamedSharding(mesh, P()))
        return {"params": {"w": w, "b": b}, "lr": rep,
                "step": 3, "tag": "adam"}

    def test_round_trip_same_mesh(self, mesh2d):
        state = self._state(mesh2d)
        cp = hpx.save_sharded_state(state).get()
        out = hpx.restore_sharded_state(cp, mesh=mesh2d)
        np.testing.assert_array_equal(np.asarray(out["params"]["w"]),
                                      np.asarray(state["params"]["w"]))
        assert out["step"] == 3 and out["tag"] == "adam"
        assert out["params"]["w"].sharding.spec == \
            state["params"]["w"].sharding.spec

    def test_restore_on_different_mesh_shape(self, devices):
        from jax.sharding import Mesh
        mesh_a = Mesh(np.array(devices).reshape(4, 2), ("x", "y"))
        mesh_b = Mesh(np.array(devices).reshape(2, 4), ("x", "y"))
        state = self._state(mesh_a)
        cp = hpx.save_sharded_state(state).get()
        out = hpx.restore_sharded_state(cp, mesh=mesh_b)
        np.testing.assert_array_equal(np.asarray(out["params"]["w"]),
                                      np.arange(64).reshape(8, 8))
        # re-placed onto mesh_b with the SAVED spec
        assert out["params"]["w"].sharding.mesh.shape == {"x": 2, "y": 4}
        assert str(out["params"]["b"].sharding.spec) in (
            "PartitionSpec('y',)", "PartitionSpec('y')")

    def test_file_round_trip_and_mesh_required(self, mesh2d, tmp_path):
        state = self._state(mesh2d)
        path = tmp_path / "state.ckpt"
        hpx.save_sharded_state_to_file(path, state).get(timeout=60)
        out = hpx.restore_sharded_state_from_file(path, mesh=mesh2d)
        np.testing.assert_array_equal(np.asarray(out["params"]["b"]),
                                      np.arange(8))
        cp = hpx.save_sharded_state(state).get()
        with pytest.raises(ValueError):
            hpx.restore_sharded_state(cp)   # sharded leaves need a mesh

    def test_training_continues_identically(self, mesh2d, devices):
        """Checkpoint mid-training, restore on a reshaped mesh, and the
        next step produces the SAME numbers as the uninterrupted run."""
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        def step(state, x):
            w = state["params"]["w"]
            g = jax.grad(lambda w: ((x @ w) ** 2).mean())(w)
            return {"params": {"w": w - state["lr"] * g},
                    "lr": state["lr"], "step": state["step"] + 1}

        jstep = jax.jit(step)
        x = jnp.ones((4, 8), jnp.float32)
        s0 = self._state(mesh2d)
        s0 = {"params": {"w": s0["params"]["w"]}, "lr": s0["lr"],
              "step": 0}
        s1 = jstep(s0, x)
        straight = jstep(s1, x)

        cp = hpx.save_sharded_state(s1).get()
        mesh_b = Mesh(np.array(devices).reshape(2, 4), ("x", "y"))
        resumed = jstep(hpx.restore_sharded_state(cp, mesh=mesh_b),
                        jax.device_put(x, NamedSharding(mesh_b, P())))
        np.testing.assert_allclose(np.asarray(resumed["params"]["w"]),
                                   np.asarray(straight["params"]["w"]),
                                   rtol=1e-6)
        assert int(resumed["step"]) == 2

    def test_plain_restore_rejects_sharded_file(self, mesh2d, tmp_path):
        path = tmp_path / "state2.ckpt"
        hpx.save_sharded_state_to_file(path,
                                       self._state(mesh2d)).get(timeout=60)
        with pytest.raises(ValueError, match="restore_sharded_state"):
            hpx.restore_checkpoint_from_file(path)

    def test_sharded_restore_rejects_plain_checkpoint(self, mesh2d):
        cp = hpx.save_checkpoint(42).get()
        with pytest.raises(ValueError, match="restore_checkpoint"):
            hpx.restore_sharded_state(cp, mesh=mesh2d)
