"""Plugin system (filters/coalescing) and pipeline parallelism tests."""

import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import hpx_tpu as hpx
from hpx_tpu.dist import plugins as plg
from hpx_tpu.parallel.pipeline import Pipeline
from hpx_tpu.testing import HPX_TEST, HPX_TEST_EQ

REPO = os.path.join(os.path.dirname(__file__), "..")


# -- plugin registry ---------------------------------------------------------

class TestRegistry:
    def test_register_get_list(self):
        plg.register_plugin("test_kind", "alpha", object(), replace=True)
        HPX_TEST(plg.get_plugin("test_kind", "alpha") is not None)
        HPX_TEST(("test_kind", "alpha") in plg.list_plugins("test_kind"))

    def test_duplicate_raises(self):
        plg.register_plugin("test_kind", "dup", 1, replace=True)
        with pytest.raises(hpx.HpxError):
            plg.register_plugin("test_kind", "dup", 2)

    def test_unknown_raises(self):
        with pytest.raises(hpx.HpxError):
            plg.get_plugin("nope", "nothing")


# -- binary filters ----------------------------------------------------------

class TestFilters:
    @pytest.mark.parametrize("name", ["zlib", "bzip2", "lzma", "zstd"])
    def test_roundtrip(self, name):
        try:
            f = plg.get_filter(name)
        except hpx.HpxError:
            pytest.skip(f"{name} not available")
        data = b"hello world " * 500
        packed = f.compress(data)
        HPX_TEST(len(packed) < len(data))
        HPX_TEST_EQ(f.decompress(packed), data)
        HPX_TEST(plg.get_filter(f.wire_id) is f)

    def test_payload_framing(self):
        f = plg.get_filter("zlib")
        big = b"abc" * 1000
        enc = plg.encode_payload(big, f)
        HPX_TEST(enc[0] == f.wire_id and len(enc) < len(big))
        HPX_TEST_EQ(plg.decode_payload(enc), big)

    def test_small_payload_stays_raw(self):
        f = plg.get_filter("zlib")
        small = b"tiny"
        enc = plg.encode_payload(small, f)
        HPX_TEST(enc[0] == 0)
        HPX_TEST_EQ(plg.decode_payload(enc), small)

    def test_incompressible_falls_back_to_raw(self):
        f = plg.get_filter("zlib")
        rnd = np.random.default_rng(0).bytes(4096)
        enc = plg.encode_payload(rnd, f)
        HPX_TEST(enc[0] == 0)      # compression would not win
        HPX_TEST_EQ(plg.decode_payload(enc), rnd)

    def test_no_filter(self):
        enc = plg.encode_payload(b"x" * 5000, None)
        HPX_TEST(enc[0] == 0)
        HPX_TEST_EQ(plg.decode_payload(enc), b"x" * 5000)


# -- coalescer ---------------------------------------------------------------

class TestCoalescer:
    def test_count_flush(self):
        sent = []
        c = plg.Coalescer(lambda d, batch: sent.append((d, batch)),
                          max_count=3, interval_s=10.0)
        for i in range(7):
            c.put(1, f"m{i}", 10)
        HPX_TEST_EQ(len(sent), 2)                  # two full batches
        HPX_TEST_EQ(sent[0], (1, ["m0", "m1", "m2"]))
        c.flush()
        HPX_TEST_EQ(len(sent), 3)
        HPX_TEST_EQ(sent[2], (1, ["m6"]))          # FIFO preserved
        c.close()

    def test_byte_flush(self):
        sent = []
        c = plg.Coalescer(lambda d, b: sent.append(b), max_count=1000,
                          max_bytes=100, interval_s=10.0)
        c.put(0, "a", 60)
        HPX_TEST_EQ(sent, [])
        c.put(0, "b", 60)                          # 120 > 100
        HPX_TEST_EQ(sent, [["a", "b"]])
        c.close()

    def test_interval_flush(self):
        sent = []
        ev = threading.Event()

        def send(d, b):
            sent.append(b)
            ev.set()

        c = plg.Coalescer(send, max_count=1000, interval_s=0.02)
        c.put(0, "late", 5)
        HPX_TEST(ev.wait(5.0))
        HPX_TEST_EQ(sent, [["late"]])
        c.close()

    def test_per_destination_queues(self):
        sent = []
        c = plg.Coalescer(lambda d, b: sent.append((d, b)),
                          max_count=2, interval_s=10.0)
        c.put(1, "a", 1)
        c.put(2, "x", 1)
        c.put(1, "b", 1)
        HPX_TEST_EQ(sent, [(1, ["a", "b"])])
        c.flush(2)
        HPX_TEST_EQ(sent[-1], (2, ["x"]))
        c.close()


def test_multiprocess_compressed_coalesced():
    """The full parcel plane with zlib compression + coalescing on."""
    from hpx_tpu.run import launch
    env_extra = {
        "HPX_TPU_PARCEL__COMPRESSION": "zlib",
        "HPX_TPU_PARCEL__COALESCING": "1",
    }
    old = {k: os.environ.get(k) for k in env_extra}
    os.environ.update(env_extra)
    try:
        rc = launch(os.path.join(REPO, "tests", "mp_scripts",
                                 "dist_smoke.py"),
                    [], localities=2, timeout=420.0)
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    assert rc == 0


# -- counter printing wiring --------------------------------------------------

def test_print_counter_at_finalize(capsys):
    hpx.finalize()      # drop any runtime an earlier test left behind
    hpx.init(overrides={"hpx.counters.print": "/runtime{*"})
    hpx.finalize()
    out = capsys.readouterr().out
    assert "/runtime{locality#0/total}/uptime" in out


# -- pipeline ----------------------------------------------------------------

def _mlp_stage(w_key, din, dout):
    w = jax.random.normal(jax.random.PRNGKey(w_key), (din, dout)) * 0.3

    def fn(params, x):
        return jnp.tanh(x @ params)
    return fn, w


class TestPipeline:
    def test_forward_matches_sequential(self, devices):
        s0, s1, s2 = (_mlp_stage(i, 8, 8) for i in range(3))
        pipe = Pipeline([s0, s1, s2], devices=devices[:3])
        mbs = [jnp.asarray(np.random.default_rng(i).random((4, 8),
                                                           np.float32))
               for i in range(5)]
        got = pipe.forward(mbs)
        for mb, y in zip(mbs, got):
            want = mb
            for fn, w in (s0, s1, s2):
                want = fn(w, want)
            np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                                       rtol=1e-5)

    def test_stages_on_distinct_devices(self, devices):
        pipe = Pipeline([_mlp_stage(0, 4, 4), _mlp_stage(1, 4, 4)],
                        devices=devices[:2])
        d0 = list(pipe.stages[0].params.devices())[0]
        d1 = list(pipe.stages[1].params.devices())[0]
        HPX_TEST(d0 != d1)

    def test_train_step_matches_unpipelined(self, devices):
        stages = [_mlp_stage(i, 6, 6) for i in range(2)]
        pipe = Pipeline(stages, devices=devices[:2])
        rng = np.random.default_rng(7)
        mbs = [jnp.asarray(rng.random((3, 6), np.float32))
               for _ in range(4)]
        tgts = [jnp.asarray(rng.random((3, 6), np.float32))
                for _ in range(4)]

        def loss_fn(y, t):
            return jnp.mean((y - t) ** 2)

        loss, grads = pipe.train_step(mbs, tgts, loss_fn)

        # unpipelined oracle
        def model(ws, x):
            for (fn, _w), w in zip(stages, ws):
                x = fn(w, x)
            return x

        def full_loss(ws):
            return sum(loss_fn(model(ws, mb), t)
                       for mb, t in zip(mbs, tgts)) / len(mbs)

        ws = [w for _fn, w in stages]
        want_loss, want_grads = jax.value_and_grad(full_loss)(ws)
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=1e-5)
        for g, wg in zip(grads, want_grads):
            np.testing.assert_allclose(np.asarray(g), np.asarray(wg),
                                       rtol=1e-4, atol=1e-6)

    def test_apply_grads_learns(self, devices):
        pipe = Pipeline([_mlp_stage(3, 4, 4), _mlp_stage(4, 4, 4)],
                        devices=devices[:2])
        rng = np.random.default_rng(0)
        mbs = [jnp.asarray(rng.random((4, 4), np.float32))]
        tgts = [jnp.asarray(rng.random((4, 4), np.float32))]

        def loss_fn(y, t):
            return jnp.mean((y - t) ** 2)

        l0, g = pipe.train_step(mbs, tgts, loss_fn)
        for _ in range(20):
            _l, g = pipe.train_step(mbs, tgts, loss_fn)
            pipe.apply_grads(g, lr=0.5)
        l1, _ = pipe.train_step(mbs, tgts, loss_fn)
        HPX_TEST(float(l1) < float(l0) * 0.5, (float(l0), float(l1)))
