"""Smoke for the mesh-scaling harness (BASELINE configs #3/#4/#5).

The conftest provisions the 8-device virtual CPU mesh, so the configs
run in-process here; `python -m hpx_tpu.run --bench-mesh N` wraps the
same functions for the one-command sweep (child-provisioned mesh).
"""

import json

import jax
import pytest


@pytest.mark.parametrize("ndev", [1, 2, 4])
def test_configs_run(ndev, capsys):
    from benchmarks import mesh_scaling as ms
    devs = jax.devices()
    ms.bench_pv_triad(ndev, devs)
    ms.bench_all_reduce(ndev, devs)
    ms.bench_jacobi(ndev, devs)
    lines = [json.loads(x) for x in
             capsys.readouterr().out.strip().splitlines()]
    metrics = {l["metric"] for l in lines}
    assert metrics == {"pv_triad", "all_reduce_1m", "jacobi2d"}
    for l in lines:
        assert l["n_devices"] == ndev
    triad = next(l for l in lines if l["metric"] == "pv_triad")
    assert triad["elements"] == ndev * (1 << 20)   # weak scaling
    assert triad["meps"] > 0


def test_run_flag_parses():
    """--bench-mesh must be a launcher flag, not a script arg."""
    from hpx_tpu.run import _split_argv
    flags, script, rest = _split_argv(
        ["-l", "2", "myscript.py", "--bench-mesh", "4"])
    assert script == "myscript.py"
    assert rest == ["--bench-mesh", "4"]
    # script-less launcher mode (both spellings)
    for argv in (["--bench-mesh", "8"], ["--bench-mesh=8"]):
        flags, script, rest = _split_argv(argv)
        assert script is None and rest == []


def test_sweep_covers_non_power_of_two(monkeypatch, capsys):
    """--bench-mesh 6 must measure AT 6 devices, not stop at 4."""
    from benchmarks import mesh_scaling as ms
    seen = []
    for name in ("bench_pv_triad", "bench_all_reduce", "bench_jacobi"):
        monkeypatch.setattr(ms, name,
                            lambda k, d, _n=name: seen.append(k))
    ms.sweep(6)
    capsys.readouterr()
    assert sorted(set(seen)) == [1, 2, 4, 6]
