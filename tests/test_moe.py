"""MoE FFN with expert parallelism (models/moe.py).

Oracles: with IDENTICAL expert weights and no capacity drops, top-1 MoE
must equal gate_prob * dense_ffn(x) exactly (Switch's output scaling),
and the ep-sharded run must equal the single-shard run bit-for-bit in
f32 (the all_to_all round trip is a permutation).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hpx_tpu.models.moe import (MoeConfig, init_moe_params, moe_ffn,
                                moe_param_specs)

T, D, F, E = 32, 16, 24, 4


def _params(cfg, identical=False, seed=0):
    p = init_moe_params(cfg, jax.random.PRNGKey(seed))
    if identical:
        for k in ("w1", "b1", "w2"):
            p[k] = jnp.broadcast_to(p[k][:1], p[k].shape)
    return p


def _dense(x, p):
    h = jax.nn.gelu(x @ p["w1"][0] + p["b1"][0])
    return h @ p["w2"][0]


def _x(seed=1):
    return jnp.asarray(np.random.default_rng(seed).standard_normal(
        (T, D), np.float32))


class TestSingleShard:
    def test_top1_identical_experts_equals_scaled_dense(self):
        cfg = MoeConfig(n_experts=E, top_k=1, capacity_factor=8.0,
                        d_model=D, d_ff=F)
        p = _params(cfg, identical=True)
        x = _x()
        out, aux = moe_ffn(x, p, cfg)
        gates = jax.nn.softmax(x @ p["wg"], axis=-1)
        want = jnp.max(gates, axis=-1, keepdims=True) * _dense(x, p)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)
        assert np.isfinite(float(aux))

    def test_top2_identical_experts(self):
        cfg = MoeConfig(n_experts=E, top_k=2, capacity_factor=8.0,
                        d_model=D, d_ff=F)
        p = _params(cfg, identical=True)
        x = _x(2)
        out, _ = moe_ffn(x, p, cfg)
        gates = jax.nn.softmax(x @ p["wg"], axis=-1)
        top2 = jnp.sort(gates, axis=-1)[:, -2:].sum(-1, keepdims=True)
        want = top2 * _dense(x, p)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_capacity_drops_are_finite_and_smaller(self):
        cfg_big = MoeConfig(n_experts=E, top_k=1, capacity_factor=8.0,
                            d_model=D, d_ff=F)
        cfg_tiny = MoeConfig(n_experts=E, top_k=1, capacity_factor=0.25,
                             d_model=D, d_ff=F)
        p = _params(cfg_big)
        x = _x(3)
        full, _ = moe_ffn(x, p, cfg_big)
        cut, _ = moe_ffn(x, p, cfg_tiny)
        assert np.isfinite(np.asarray(cut)).all()
        assert float(jnp.linalg.norm(cut)) < float(jnp.linalg.norm(full))

    def test_grads_reach_every_weight(self):
        cfg = MoeConfig(n_experts=E, top_k=2, capacity_factor=8.0,
                        d_model=D, d_ff=F)
        p = _params(cfg)
        x = _x(4)

        def loss(p):
            out, aux = moe_ffn(x, p, cfg)
            return jnp.sum(out ** 2) + 0.01 * aux

        g = jax.grad(loss)(p)
        for k in ("wg", "w1", "b1", "w2"):
            assert np.isfinite(np.asarray(g[k])).all(), k
            assert float(jnp.abs(g[k]).max()) > 0, k


class TestExpertParallel:
    @pytest.mark.parametrize("top_k", [1, 2])
    def test_sharded_matches_single_shard(self, top_k, devices):
        from hpx_tpu.utils.jaxcompat import shard_map
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        ep = 4
        mesh = Mesh(np.array(devices[:ep]), ("ep",))
        cfg = MoeConfig(n_experts=E, top_k=top_k, capacity_factor=8.0,
                        d_model=D, d_ff=F)
        p = _params(cfg, seed=7)
        xs = jnp.asarray(np.random.default_rng(8).standard_normal(
            (ep * T, D), np.float32))        # tokens sharded over ep

        # single-shard oracle: per token block (capacity is per-device,
        # so the oracle processes each device's block independently)
        outs, auxs = [], []
        for i in range(ep):
            o, a = moe_ffn(xs[i * T:(i + 1) * T], p, cfg)
            outs.append(o)
            auxs.append(a)
        want = jnp.concatenate(outs)

        specs = moe_param_specs("ep")
        ps = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
              for k, v in p.items()}
        xsh = jax.device_put(xs, NamedSharding(mesh, P("ep")))

        def body(xc, pc):
            out, aux = moe_ffn(xc, pc, cfg, axis="ep", axis_size=ep)
            return out, jax.lax.pmean(aux, "ep")

        got, aux = jax.jit(shard_map(
            body, mesh=mesh,
            in_specs=(P("ep"), specs),
            out_specs=(P("ep"), P())))(xsh, ps)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(float(aux), float(np.mean(auxs)),
                                   rtol=1e-5)

    def test_sharded_grads_match(self, devices):
        from hpx_tpu.utils.jaxcompat import shard_map
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        ep = 2
        mesh = Mesh(np.array(devices[:ep]), ("ep",))
        cfg = MoeConfig(n_experts=E, top_k=2, capacity_factor=8.0,
                        d_model=D, d_ff=F)
        p = _params(cfg, seed=9)
        xs = jnp.asarray(np.random.default_rng(10).standard_normal(
            (ep * T, D), np.float32))

        def loss_single(p):
            tot = 0.0
            for i in range(ep):
                o, _ = moe_ffn(xs[i * T:(i + 1) * T], p, cfg)
                tot = tot + jnp.sum(o ** 2)
            return tot

        want = jax.grad(loss_single)(p)

        specs = moe_param_specs("ep")
        ps = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
              for k, v in p.items()}
        xsh = jax.device_put(xs, NamedSharding(mesh, P("ep")))

        def loss_sharded(ps, xc):
            o, _ = moe_ffn(xc, ps, cfg, axis="ep", axis_size=ep)
            return jax.lax.psum(jnp.sum(o ** 2), "ep")

        got = jax.jit(shard_map(
            jax.grad(loss_sharded), mesh=mesh,
            in_specs=(specs, P("ep")),
            out_specs=specs))(ps, xsh)
        for k in ("wg", "w1", "b1", "w2"):
            np.testing.assert_allclose(
                np.asarray(got[k]), np.asarray(want[k]),
                rtol=3e-4, atol=3e-4, err_msg=k)

    def test_indivisible_experts_raises(self):
        cfg = MoeConfig(n_experts=3, d_model=D, d_ff=F)
        with pytest.raises(ValueError):
            moe_ffn(_x(), init_moe_params(cfg, jax.random.PRNGKey(0)),
                    cfg, axis="ep", axis_size=2)


class TestCapacityOverflow:
    def test_overflow_drops_deterministic_and_exact_zero(self):
        """Overflow routing is pure argmax over f32 gates — no RNG, no
        nondeterministic reduction — so two runs drop THE SAME tokens,
        and a dropped token (all its claims through the trash row)
        contributes exact-zero output, not merely small."""
        import math
        from hpx_tpu.models.moe import _top_k_dispatch
        cfg = MoeConfig(n_experts=E, top_k=1, capacity_factor=0.25,
                        d_model=D, d_ff=F)
        p = _params(cfg, seed=5)
        x = _x(6)
        out1, _, st1 = moe_ffn(x, p, cfg, return_stats=True)
        out2, _, st2 = moe_ffn(x, p, cfg, return_stats=True)
        np.testing.assert_array_equal(np.asarray(out1),
                                      np.asarray(out2))
        np.testing.assert_array_equal(np.asarray(st1),
                                      np.asarray(st2))
        routed, dropped = float(st1[0]), float(st1[1])
        assert dropped > 0            # the fixture actually overflows
        assert routed + dropped == T * cfg.top_k
        assert float(jnp.max(st1[2:])) <= 1.0 + 1e-6   # occupancy caps
        cap = max(1, math.ceil(T * cfg.top_k
                               * cfg.capacity_factor / E))
        gates = jax.nn.softmax(x @ p["wg"], axis=-1)
        disp, _, _ = _top_k_dispatch(gates, cfg.top_k, cap)
        lost = np.asarray(jnp.sum(disp, axis=(1, 2)) == 0)
        assert lost.any()
        assert (np.asarray(out1)[lost] == 0.0).all()

    def test_bf16_gating_agrees_with_f32(self):
        """Gating always runs in f32 (the xf upcast), so a bf16 expert
        compute makes the SAME routing and drop decisions as f32 —
        stats identical, outputs within bf16 rounding."""
        cfg32 = MoeConfig(n_experts=E, top_k=2, capacity_factor=1.0,
                          d_model=D, d_ff=F, dtype=jnp.float32)
        cfg16 = MoeConfig(n_experts=E, top_k=2, capacity_factor=1.0,
                          d_model=D, d_ff=F, dtype=jnp.bfloat16)
        p = _params(cfg32, seed=11)
        x = _x(12)
        out32, _, st32 = moe_ffn(x, p, cfg32, return_stats=True)
        out16, _, st16 = moe_ffn(x, p, cfg16, return_stats=True)
        np.testing.assert_array_equal(np.asarray(st32),
                                      np.asarray(st16))
        np.testing.assert_allclose(
            np.asarray(out16, np.float32), np.asarray(out32),
            rtol=0.1, atol=0.1)


def test_top_k_exceeding_experts_raises():
    cfg = MoeConfig(n_experts=2, top_k=3, d_model=D, d_ff=F)
    with pytest.raises(ValueError, match="top_k"):
        moe_ffn(_x(), init_moe_params(cfg, jax.random.PRNGKey(0)), cfg)
