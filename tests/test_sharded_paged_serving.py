"""Sharded paged serving: ContinuousServer(paged=True, mesh=(dp, tp))
must emit BYTE-IDENTICAL tokens to the single-device paged server —
greedy and sampled, with and without speculation, bf16 and int8 pools —
while the block pool shards kv-heads over tp, replicates the block axis
over dp, and the slot/page-table rows shard over dp (the shard_map
step: block tables stay per-shard int32, no cross-shard gathers).

Single-device paged == dense == generate() is already pinned by
test_paged_serving / test_spec_serving, so equality against the solo
paged server chains all the way back to the solo-generate() contract.
"""

import jax
import numpy as np
import pytest

from hpx_tpu.models import transformer as tfm
from hpx_tpu.models.serving import ContinuousServer

CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4, head_dim=8,
                            n_layers=2, d_ff=64)
GQA_ROPE = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                 head_dim=8, n_layers=2, d_ff=64,
                                 n_kv_heads=2, rope=True)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def mesh():
    return jax.sharding.Mesh(
        np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))


def _run_both(params, cfg, mesh, reqs, smax=64, slots=4, **kw):
    """The same mix through a single-device and a sharded paged
    server; rids align because submission order is identical."""
    solo = ContinuousServer(params, cfg, slots=slots, smax=smax,
                            paged=True, **kw)
    shard = ContinuousServer(params, cfg, slots=slots, smax=smax,
                             paged=True, mesh=mesh, **kw)
    for srv in (solo, shard):
        for r in reqs:
            srv.submit(**r)
    return solo.run(), shard.run(), shard


GREEDY = [dict(prompt=[3, 1, 4], max_new=9),
          dict(prompt=[2, 7], max_new=5),
          dict(prompt=[5, 6, 7, 8, 9], max_new=12),
          dict(prompt=[1], max_new=7),
          dict(prompt=[9, 9, 2, 1], max_new=3),
          dict(prompt=[4, 4], max_new=10)]


# -- equivalence -------------------------------------------------------------

def test_greedy_matches_single_device(params, mesh):
    outs, outm, _ = _run_both(params, CFG, mesh, GREEDY)
    assert outs == outm


def test_sampled_matches_single_device(params, mesh):
    """Per-slot sampling folds the request key, not the shard — the
    (key, pos, row=0) categorical draw must survive shard_map."""
    reqs = [dict(prompt=[3, 1, 4], max_new=8, temperature=0.9,
                 key=jax.random.PRNGKey(7)),
            dict(prompt=[2, 7, 9], max_new=8, temperature=0.7,
                 key=jax.random.PRNGKey(8)),
            dict(prompt=[5, 5], max_new=6, temperature=1.3,
                 key=jax.random.PRNGKey(9)),
            dict(prompt=[6, 1], max_new=6)]
    outs, outm, _ = _run_both(params, CFG, mesh, reqs)
    assert outs == outm


def test_gqa_rope_matches_single_device(mesh):
    """n_kv_heads=2 over tp=2: ONE kv head per shard — the sharpest
    per-shard head-slicing case the fused/gather kernels must get
    right."""
    p = tfm.init_params(GQA_ROPE, jax.random.PRNGKey(5))
    reqs = [dict(prompt=[3, 1, 4, 1, 5], max_new=7),
            dict(prompt=[2, 7], max_new=5),
            dict(prompt=[1, 2, 3], max_new=6)]
    outs, outm, _ = _run_both(p, GQA_ROPE, mesh, reqs, smax=48)
    assert outs == outm


def test_int8_matches_single_device(params, mesh):
    """int8 pools: the [num_blocks, nkv] scale sidecars shard over tp
    with their heads; per-head absmax quantization is shard-local, so
    quantized values are identical to the single-device pools."""
    outs, outm, _ = _run_both(params, CFG, mesh, GREEDY,
                              kv_dtype="int8")
    assert outs == outm


def test_spec_matches_single_device(params, mesh):
    """Speculative decode on the mesh: the shard_map verify window and
    per-shard rollback must accept exactly the drafts the solo server
    accepts (greedy + sampled mix)."""
    reqs = GREEDY[:4] + [dict(prompt=[3, 1, 4], max_new=8,
                              temperature=0.9,
                              key=jax.random.PRNGKey(7))]
    outs, outm, srv = _run_both(params, CFG, mesh, reqs,
                                spec=True, spec_k=3)
    assert outs == outm
    assert srv.spec_stats()["steps"] > 0


def test_spec_draft_model_matches_single_device(params, mesh):
    """Draft-model speculation: the draft shares the serving mesh
    (dense caches over cache_sh) while the target runs the shard_map
    paged path."""
    dcfg = tfm.TransformerConfig(vocab=64, d_model=16, n_heads=2,
                                 head_dim=8, n_layers=1, d_ff=32)
    dparams = tfm.init_params(dcfg, jax.random.PRNGKey(3))
    reqs = GREEDY[:3]
    outs, outm, _ = _run_both(params, CFG, mesh, reqs, spec=True,
                              spec_k=3, spec_draft="model",
                              draft_params=dparams, draft_cfg=dcfg)
    assert outs == outm


def test_prefix_reuse_across_dp_shards(params, mesh):
    """Requests sharing a prefix land on BOTH dp shards (4 slots over
    dp=2): the radix chain published by one shard's request must be
    reusable by slots on the other shard — the dp-replicated block
    axis (whole-block splice writes are identical on every replica) is
    what makes that sound."""
    pre = list(range(1, 33))                    # 2 blocks of 16
    # 8 requests over 4 slots: the first wave publishes the prefix
    # chain on retire, the second wave (admitting into slots on BOTH
    # dp shards) must match it
    reqs = [dict(prompt=pre + [40 + i], max_new=6) for i in range(8)]
    outs, outm, srv = _run_both(params, CFG, mesh, reqs)
    assert outs == outm
    st = srv.cache_stats()
    assert st["tokens_matched"] >= 32
    assert st["prefill_tokens_saved"] >= 32


def test_table_residency_replicated_matches(params, mesh):
    """hpx.serving.mesh.table_residency=replicated: same tokens, the
    device table is just placed replicated instead of row-sharded."""
    from hpx_tpu.core.config import runtime_config
    rc = runtime_config()
    rc.set("hpx.serving.mesh.table_residency", "replicated")
    try:
        outs, outm, srv = _run_both(params, CFG, mesh, GREEDY[:3])
        assert outs == outm
        assert srv._table_residency == "replicated"
    finally:
        rc.set("hpx.serving.mesh.table_residency", "sharded")


# -- validation / accounting -------------------------------------------------

def test_sharded_paged_validates(params, mesh):
    # slots must divide over dp (the shared decode-mesh contract,
    # reworded for slots)
    with pytest.raises(ValueError, match="slots"):
        ContinuousServer(params, CFG, slots=3, smax=64, paged=True,
                         mesh=mesh)
    # MoE decodes expert-parallel now; the remaining refusal is
    # expert-count divisibility over the expert axis
    moe = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                head_dim=8, n_layers=2, d_ff=64,
                                n_experts=3)
    mp = tfm.init_params(moe, jax.random.PRNGKey(1))
    with pytest.raises(ValueError, match=r"n_experts \(3\).*tp=2"):
        ContinuousServer(mp, moe, slots=4, smax=64, paged=True,
                         mesh=mesh)
    # bogus residency knob
    from hpx_tpu.core.config import runtime_config
    rc = runtime_config()
    rc.set("hpx.serving.mesh.table_residency", "bogus")
    try:
        with pytest.raises(ValueError, match="table_residency"):
            ContinuousServer(params, CFG, slots=4, smax=64, paged=True,
                             mesh=mesh)
    finally:
        rc.set("hpx.serving.mesh.table_residency", "sharded")


def test_per_dp_shard_occupancy(params, mesh):
    """cache_stats() breaks occupancy down by dp shard (slots map to
    shards by index range); totals reconcile with the global mapped
    count while requests are live."""
    srv = ContinuousServer(params, CFG, slots=4, smax=64, paged=True,
                           mesh=mesh)
    for i in range(4):
        srv.submit([10 + i] * 20, max_new=4)
    ticks = 0
    while srv.step():
        ticks += 1
        st = srv.cache_stats()
        from hpx_tpu.cache.page_table import occupancy
        assert (st["occupancy_dp0"] + st["occupancy_dp1"]
                == occupancy(srv._tables))
    assert ticks > 0
    st = srv.cache_stats()
    assert "occupancy_dp0" in st and "occupancy_dp1" in st
