"""Placement policies (dist/distribution_policies): binpacked /
colocated — the reference's binpacking_/colocating_distribution_policy
(SURVEY.md §2.4) on the locality plane."""

import os

import pytest

import hpx_tpu as hpx
from hpx_tpu.testing import HPX_TEST, HPX_TEST_EQ

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@hpx.register_component_type
class Gadget(hpx.Component):
    def __init__(self, tag: str = "") -> None:
        self.tag = tag

    def where_am_i(self) -> int:
        return hpx.find_here()


class TestSingleLocality:
    def test_binpacked_resolves_here(self):
        assert hpx.binpacked().resolve(1) == [0]
        assert hpx.binpacked().resolve(3) == [0, 0, 0]

    def test_new_with_binpacked(self):
        c = hpx.new_(Gadget, hpx.binpacked(), "a").get()
        HPX_TEST_EQ(c.sync("where_am_i"), 0)
        c.free().get()

    def test_colocated_follows_client(self):
        a = hpx.new_sync(Gadget, None, "anchor")
        c = hpx.new_(Gadget, hpx.colocated(a), "next").get()
        HPX_TEST_EQ(c.sync("where_am_i"), 0)
        a.free().get()
        c.free().get()

    def test_counter_based_load(self):
        pol = hpx.binpacked(counter=("runtime", "uptime"))
        assert pol.resolve(1) == [0]

    def test_counter_spec_validated(self):
        with pytest.raises(ValueError):
            hpx.binpacked(counter=("only-object",))

    def test_empty_candidates_rejected(self):
        with pytest.raises(ValueError):
            hpx.binpacked(localities=[]).resolve(1)

    def test_component_count_by_type(self):
        from hpx_tpu.dist.components import _component_count
        before = _component_count(
            Gadget.__dict__["_component_type_name"])
        cs = [hpx.new_sync(Gadget, None) for _ in range(3)]
        HPX_TEST_EQ(_component_count(
            Gadget.__dict__["_component_type_name"]), before + 3)
        HPX_TEST(_component_count() >= before + 3)
        for c in cs:
            c.free().get()


@pytest.mark.slow
def test_multiprocess_binpacking(monkeypatch):
    """Skewed-load rebalancing + colocation across 4 real processes."""
    from hpx_tpu.run import launch
    # fresh interpreters importing jax on a loaded 1-core host stagger
    # by minutes when the whole suite shares the core — widen the
    # bootstrap and barrier windows (same treatment as the comm_set
    # smoke)
    monkeypatch.setenv("HPX_TPU_STARTUP_TIMEOUT", "180")
    monkeypatch.setenv("HPX_TPU_BARRIER_TIMEOUT", "420")
    script = os.path.join(REPO, "tests", "mp_scripts",
                          "binpacking_smoke.py")
    rc = launch(script, [], localities=4, timeout=600.0)
    if rc != 0:
        # contention retry: 4 fresh jax interpreters on this single
        # shared core occasionally stagger past every window when the
        # rest of the suite has been grinding the box (standalone the
        # smoke is 3x-green); a genuine logic failure fails twice
        rc = launch(script, [], localities=4, timeout=600.0)
    assert rc == 0
