"""Examples run as smoke tests — the reference registers its examples/
binaries as CTest smoke tests (SURVEY.md §4); same idea: every example
must exit 0 on the CPU mesh, single-process and (where it applies)
multi-locality.
"""

import os
import subprocess
import sys

import pytest

# every test here shells out to a fresh interpreter (jax import + mesh
# compile each time) — the dominant share of suite wall-clock. Deselect
# in dev loops with -m 'not slow'; CI and the pre-round full run keep
# them.
pytestmark = pytest.mark.slow

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def run_example(name, *args, timeout=420):
    return subprocess.run(
        [sys.executable, os.path.join("examples", name),
         *args, "--cpu-mesh", "8"],
        cwd=REPO, capture_output=True, text=True, timeout=timeout)


def run_distributed(name, localities, timeout=480):
    # generous: the full suite serializes everything onto one sandbox
    # core, and each locality is a fresh interpreter + jax import —
    # under suite load they stagger by minutes, so widen the runtime's
    # bootstrap/barrier windows too
    env = dict(os.environ,
               HPX_TPU_STARTUP_TIMEOUT="180",
               HPX_TPU_BARRIER_TIMEOUT="420")
    return subprocess.run(
        [sys.executable, "-m", "hpx_tpu.run", "-l", str(localities),
         "--timeout", str(timeout - 20),
         os.path.join("examples", name)],
        cwd=REPO, capture_output=True, text=True, timeout=timeout,
        env=env)


@pytest.mark.parametrize("name,args", [
    ("fibonacci.py", ["15", "10"]),
    ("saxpy_tpu.py", ["16"]),
    ("1d_stencil.py", ["2048", "4", "8"]),
    ("transpose.py", ["128"]),
    ("hello_world_distributed.py", []),
    ("channel_demo.py", []),
    ("accumulator.py", []),
    ("jacobi2d.py", ["64", "4", "6"]),
    ("ring_attention_demo.py", ["128"]),
    ("checkpointed_stencil.py", ["128", "4", "8"]),
    ("fft_distributed.py", ["12", "14"]),
    ("pipeline_train.py", ["4"]),
    ("serving_demo.py", []),
    ("load_balancing.py", []),
    ("elastic_training.py", ["6"]),
])
def test_example_single(name, args):
    r = run_example(name, *args)
    assert r.returncode == 0, f"{name}: {r.stdout}\n{r.stderr}"


@pytest.mark.parametrize("name,localities", [
    ("hello_world_distributed.py", 2),
    ("channel_demo.py", 2),
    ("accumulator.py", 2),
    ("1d_stencil_distributed.py", 3),
    ("load_balancing.py", 2),
])
def test_example_distributed(name, localities):
    r = run_distributed(name, localities)
    if r.returncode != 0:
        # one contention retry (see the mp-smoke tests' note): a
        # genuine failure fails twice
        r = run_distributed(name, localities)
    assert r.returncode == 0, f"{name}: {r.stdout}\n{r.stderr}"


def test_future_overhead_benchmark():
    r = subprocess.run(
        [sys.executable, os.path.join("benchmarks", "future_overhead.py"),
         "2000"],
        cwd=REPO, capture_output=True, text=True, timeout=420)
    assert r.returncode == 0, r.stderr
    import json
    rows = [json.loads(line) for line in r.stdout.splitlines() if line]
    rows = [r_ for r_ in rows if "tasks_per_s" in r_]
    names = {(r_["name"], r_["executor"]) for r_ in rows}
    assert ("post+latch", "default-pool") in names, names
    assert ("post_many+latch (batched)", "default-pool") in names, names
    assert all(row["tasks_per_s"] > 0 for row in rows)


@pytest.mark.slow
def test_serving_benchmark_smoke():
    """benchmarks/serving_bench.py --cpu: all five engines report a
    tokens/s line and speculation reports its rounds."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, os.path.join("benchmarks", "serving_bench.py"),
         "--cpu", "--scale", "1"],
        cwd=REPO, capture_output=True, text=True, timeout=900, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    import json
    rows = [json.loads(ln) for ln in r.stdout.splitlines()
            if ln.startswith("{")]
    engines = {row["engine"] for row in rows}
    assert engines == {"generate", "continuous_batching", "speculative",
                       "generate_single_stream",
                       "paged_prefix_reuse"}, engines
    assert all(row["tokens_per_s"] > 0 for row in rows)
    spec = next(row for row in rows if row["engine"] == "speculative")
    assert spec["rounds"] >= 1


def test_paged_prefix_bench_smoke():
    """The prefix-heavy paged workload (--prefix-only keeps it in
    tier 1): the radix cache must actually hit — a nonzero hit rate and
    at least 30% of prefill tokens eliminated for the 12-requests-one-
    system-prompt mix."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, os.path.join("benchmarks", "serving_bench.py"),
         "--cpu", "--scale", "1", "--prefix-only"],
        cwd=REPO, capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    import json
    rows = [json.loads(ln) for ln in r.stdout.splitlines()
            if ln.startswith("{")]
    row = next(r_ for r_ in rows if r_["engine"] == "paged_prefix_reuse")
    assert row["cache_hit_rate"] > 0, row
    assert row["prefill_saved_frac"] >= 0.3, row
    assert row["tokens_per_s"] > 0, row
