import pytest

from hpx_tpu.core.errors import (
    Error, ErrorCode, HpxError, throw_exception, throws_or_sets,
)


def test_throw_exception_carries_code():
    with pytest.raises(HpxError) as ei:
        throw_exception(Error.bad_parameter, "bad arg", "test_fn")
    assert ei.value.get_error() == Error.bad_parameter
    assert "bad_parameter" in str(ei.value)


def test_error_code_out_param():
    ec = ErrorCode()
    assert not ec
    throws_or_sets(ec, Error.network_error, "down")
    assert ec and ec.value == Error.network_error
    ec.clear()
    assert not ec


def test_throws_when_no_ec():
    with pytest.raises(HpxError):
        throws_or_sets(None, Error.deadlock, "stuck")
