"""when_all/when_any/when_some/when_each + dataflow tests.

Reference analog: libs/core/async_combinators/tests/unit and
libs/core/pack_traversal dataflow tests.
"""

import threading

import pytest

import hpx_tpu as hpx


def test_when_all_varargs_and_iterable():
    a, b = hpx.make_ready_future(1), hpx.make_ready_future(2)
    done = hpx.when_all(a, b).get()
    assert [f.get() for f in done] == [1, 2]
    done2 = hpx.when_all([a, b]).get()
    assert [f.get() for f in done2] == [1, 2]


def test_when_all_empty():
    assert hpx.when_all().get() == []


def test_when_all_pending_then_fires():
    p1, p2 = hpx.Promise(), hpx.Promise()
    f = hpx.when_all(p1.get_future(), p2.get_future())
    assert not f.is_ready()
    p1.set_value(1)
    assert not f.is_ready()
    p2.set_value(2)
    assert f.is_ready()


def test_when_all_exceptional_inputs_do_not_throw_outer():
    bad = hpx.make_exceptional_future(ValueError("x"))
    ok = hpx.make_ready_future(1)
    res = hpx.when_all(bad, ok).get()  # outer get does not raise
    assert res[0].has_exception() and res[1].get() == 1


def test_when_any_first_ready_index():
    p1, p2 = hpx.Promise(), hpx.Promise()
    f = hpx.when_any(p1.get_future(), p2.get_future())
    p2.set_value("second")
    r = f.get(timeout=5.0)
    assert r.index == 1
    assert r.futures[1].get() == "second"


def test_when_some():
    ps = [hpx.Promise() for _ in range(4)]
    f = hpx.when_some(2, [p.get_future() for p in ps])
    ps[3].set_value(1)
    assert not f.is_ready()
    ps[1].set_value(1)
    assert sorted(f.get(timeout=5.0).indices) == [1, 3]


def test_when_each_and_wait_each():
    seen = []
    ps = [hpx.Promise() for _ in range(3)]
    f = hpx.when_each(lambda fut: seen.append(fut.get()),
                      [p.get_future() for p in ps])
    for i, p in enumerate(ps):
        p.set_value(i)
    f.get(timeout=5.0)
    assert sorted(seen) == [0, 1, 2]


def test_wait_all_values_coerced():
    # plain values are accepted (make_ready_future coercion)
    hpx.wait_all(hpx.make_ready_future(1), 2)


def test_split_future():
    p = hpx.Promise()
    a, b, c = hpx.split_future(p.get_future(), 3)
    p.set_value((10, 20, 30))
    assert (a.get(), b.get(), c.get()) == (10, 20, 30)


# -- dataflow ---------------------------------------------------------------

def test_dataflow_receives_ready_futures():
    a, b = hpx.make_ready_future(2), hpx.make_ready_future(3)
    f = hpx.dataflow(lambda x, y: x.get() + y.get(), a, b)
    assert f.get(timeout=5.0) == 5


def test_dataflow_unwrapping():
    a, b = hpx.make_ready_future(2), hpx.make_ready_future(3)
    f = hpx.dataflow(hpx.unwrapping(lambda x, y: x + y), a, b)
    assert f.get(timeout=5.0) == 5


def test_dataflow_does_not_block_on_pending():
    p = hpx.Promise()
    fired = threading.Event()
    f = hpx.dataflow(lambda fut: fired.set() or fut.get(), p.get_future())
    assert not fired.wait(0.05)      # must not run before dependency ready
    p.set_value(77)
    assert f.get(timeout=5.0) == 77


def test_dataflow_nested_containers():
    ps = [hpx.Promise() for _ in range(3)]
    futs = [p.get_future() for p in ps]
    f = hpx.dataflow(lambda lst: sum(x.get() for x in lst), futs)
    for i, p in enumerate(ps):
        p.set_value(i + 1)
    assert f.get(timeout=5.0) == 6


def test_dataflow_mixed_values_and_futures():
    f = hpx.dataflow(hpx.unwrapping(lambda x, y: x * y),
                     hpx.make_ready_future(6), 7)
    assert f.get(timeout=5.0) == 42


def test_dataflow_exception_propagates():
    bad = hpx.make_exceptional_future(KeyError("dep"))
    f = hpx.dataflow(hpx.unwrapping(lambda x: x), bad)
    with pytest.raises(KeyError):
        f.get(timeout=5.0)


def test_dataflow_chain_stencil_shape():
    # 1d_stencil_4-shaped DAG: U[t+1][i] = f(U[t][i-1], U[t][i], U[t][i+1])
    np_, nt = 5, 10
    u = [hpx.make_ready_future(float(i)) for i in range(np_)]
    heat = hpx.unwrapping(lambda l, m, r: 0.25 * l + 0.5 * m + 0.25 * r)
    for _t in range(nt):
        u = [hpx.dataflow(heat, u[(i - 1) % np_], u[i], u[(i + 1) % np_])
             for i in range(np_)]
    vals = [f.get(timeout=10.0) for f in u]
    assert abs(sum(vals) - sum(range(np_))) < 1e-9  # conservation
