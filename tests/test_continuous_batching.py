"""Continuous batching (models/serving.ContinuousServer): slot-based
serving with per-slot positions. The contract under test: every
request's tokens are EXACTLY transformer.generate()'s output for that
prompt alone — batching changes throughput, never content."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hpx_tpu.models import transformer as tfm
from hpx_tpu.models.serving import ContinuousServer

CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4, head_dim=8,
                            n_layers=2, d_ff=64)
GQA_ROPE = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                 head_dim=8, n_layers=2, d_ff=64,
                                 n_kv_heads=2, rope=True)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.PRNGKey(0))


def _ref(params, cfg, prompt, max_new, eos_id=None):
    out = tfm.generate(params, cfg,
                       jnp.asarray([prompt], jnp.int32),
                       max_new=max_new, eos_id=eos_id)
    return [int(t) for t in np.asarray(out)[0]]


def test_mixed_lengths_match_generate(params):
    """More requests than slots, heterogeneous prompt lengths and
    max_new — every result equals the solo generate() run."""
    reqs = [([3, 1, 4], 9), ([2, 7], 5), ([5, 6, 7, 8, 9], 12),
            ([1], 7), ([9, 9, 2, 1], 3), ([4, 4], 10)]
    srv = ContinuousServer(params, CFG, slots=3, smax=64)
    rids = {srv.submit(p, max_new=m): (p, m) for p, m in reqs}
    out = srv.run()
    assert set(out) == set(rids)
    for rid, (p, m) in rids.items():
        assert out[rid] == _ref(params, CFG, p, m), (rid, p, m)


def test_eos_retires_early_and_matches(params):
    probe = _ref(params, CFG, [3, 1, 4], 9)
    eos = probe[3]                    # a token greedy actually emits
    srv = ContinuousServer(params, CFG, slots=2, smax=64)
    a = srv.submit([3, 1, 4], max_new=9, eos_id=eos)
    b = srv.submit([2, 7], max_new=5)
    out = srv.run()
    assert out[a] == _ref(params, CFG, [3, 1, 4], 9, eos_id=eos)
    assert out[b] == _ref(params, CFG, [2, 7], 5)


def test_gqa_rope_model():
    params = tfm.init_params(GQA_ROPE, jax.random.PRNGKey(5))
    srv = ContinuousServer(params, GQA_ROPE, slots=2, smax=48)
    rids = {srv.submit(p, max_new=m): (p, m)
            for p, m in [([3, 1, 4, 1], 8), ([2], 6), ([7, 7, 7], 5)]}
    out = srv.run()
    for rid, (p, m) in rids.items():
        assert out[rid] == _ref(params, GQA_ROPE, p, m), (rid, p)


def test_slot_reuse_is_clean(params):
    """A slot freed by a short request must not leak stale cache rows
    into the next request admitted there."""
    srv = ContinuousServer(params, CFG, slots=1, smax=64)
    a = srv.submit([9, 8, 7, 6, 5, 4], max_new=4)   # long prompt first
    b = srv.submit([2, 7], max_new=5)               # then short
    out = srv.run()
    assert out[a] == _ref(params, CFG, [9, 8, 7, 6, 5, 4], 4)
    assert out[b] == _ref(params, CFG, [2, 7], 5)


def test_rejects_bad_submits(params):
    srv = ContinuousServer(params, CFG, slots=1, smax=16)
    with pytest.raises(ValueError, match="non-empty"):
        srv.submit([], max_new=4)
    with pytest.raises(ValueError, match="smax"):
        srv.submit([1, 2, 3], max_new=14)


def test_per_request_sampling_matches_solo(params):
    """Sampled requests reproduce their SOLO generate(temperature, key)
    tokens exactly (the key folds match), mixed in one batch with
    greedy requests."""
    k1, k2 = jax.random.PRNGKey(11), jax.random.PRNGKey(22)
    srv = ContinuousServer(params, CFG, slots=3, smax=64)
    a = srv.submit([3, 1, 4], max_new=8, temperature=0.8, key=k1)
    b = srv.submit([2, 7], max_new=6)                       # greedy
    c = srv.submit([5, 6, 7, 8], max_new=7, temperature=1.3, key=k2)
    out = srv.run()

    def solo(prompt, m, t=0.0, key=None):
        o = tfm.generate(params, CFG, jnp.asarray([prompt], jnp.int32),
                         max_new=m, temperature=t, key=key)
        return [int(x) for x in np.asarray(o)[0]]

    assert out[a] == solo([3, 1, 4], 8, 0.8, k1)
    assert out[b] == solo([2, 7], 6)
    assert out[c] == solo([5, 6, 7, 8], 7, 1.3, k2)


def test_sampling_requires_key(params):
    srv = ContinuousServer(params, CFG, slots=1, smax=32)
    with pytest.raises(ValueError, match="PRNG key"):
        srv.submit([1, 2], max_new=4, temperature=0.5)


def test_submit_arg_validation(params):
    srv = ContinuousServer(params, CFG, slots=1, smax=32)
    with pytest.raises(ValueError, match="max_new"):
        srv.submit([1, 2], max_new=0)
    with pytest.raises(ValueError, match="no effect"):
        srv.submit([1, 2], max_new=4, key=jax.random.PRNGKey(0))


def test_quantized_params_serve(params):
    """int8 weights through the slot server == int8 solo generate (the
    per-row block dequantizes at use like the scalar-position one)."""
    from hpx_tpu.models import quant
    qp = quant.quantize_params(params)
    srv = ContinuousServer(qp, CFG, slots=2, smax=48)
    rids = {srv.submit(p, max_new=m): (p, m)
            for p, m in [([3, 1, 4], 7), ([2, 7], 5), ([9, 9], 6)]}
    out = srv.run()
    for rid, (p, m) in rids.items():
        assert out[rid] == _ref(qp, CFG, p, m), (rid, p)


def test_sharded_server_matches_single_device(params):
    """GSPMD sharded serving (slots over dp, heads over tp): placement
    alone — identical step program — must reproduce the single-device
    server token for token."""
    from jax.sharding import Mesh
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
    reqs = [([3, 1, 4], 7), ([2, 7], 5), ([5, 6, 7, 8], 9), ([1], 4),
            ([9, 2], 6)]

    def serve(mesh_arg):
        srv = ContinuousServer(params, CFG, slots=4, smax=64,
                               mesh=mesh_arg)
        rids = {srv.submit(p, max_new=m): i
                for i, (p, m) in enumerate(reqs)}
        out = srv.run()
        return {rids[r]: out[r] for r in out}

    single = serve(None)
    sharded = serve(mesh)
    assert sharded == single


def test_sharded_server_validates(params):
    from jax.sharding import Mesh
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
    with pytest.raises(ValueError, match="slots"):
        ContinuousServer(params, CFG, slots=3, smax=32, mesh=mesh)
    # MoE decodes expert-parallel now; the only MoE refusal left is
    # expert-count divisibility over the expert axis, and it names
    # the counts and the remedy
    import dataclasses
    moe_cfg = dataclasses.replace(CFG, n_experts=3)
    moe_params = tfm.init_params(moe_cfg, jax.random.PRNGKey(8))
    with pytest.raises(ValueError, match=r"n_experts \(3\).*tp=2"):
        ContinuousServer(moe_params, moe_cfg, slots=4, smax=32,
                         mesh=mesh)


def test_one_token_burst_drains_in_admission(params):
    """Requests that retire instantly (max_new == 1) free their slot
    mid-admission; the same-pass re-scan pushes the next queued
    request through WITHOUT spending a decode step per request —
    the whole burst drains before the first (and only) step() call
    dispatches nothing."""
    srv = ContinuousServer(params, CFG, slots=2, smax=64)
    reqs = {srv.submit([3 + i, 1, 4], max_new=1): [3 + i, 1, 4]
            for i in range(5)}
    steps = 0
    while srv.step():
        steps += 1
    assert steps == 0
    out, srv._done = srv._done, {}
    for rid, p in reqs.items():
        assert out[rid] == _ref(params, CFG, p, 1)


def test_instant_eos_frees_slot_same_pass(params):
    """A request whose FIRST token is its eos retires during admission
    too; the re-scan lets a trailing request take the slot in the same
    pass and everything still matches generate()."""
    tok0 = _ref(params, CFG, [3, 1, 4], 1)[0]
    srv = ContinuousServer(params, CFG, slots=1, smax=64)
    a = srv.submit([3, 1, 4], max_new=5, eos_id=tok0)   # instant eos
    b = srv.submit([2, 7], max_new=4)
    out = srv.run()
    assert out[a] == _ref(params, CFG, [3, 1, 4], 5, eos_id=tok0)
    assert out[b] == _ref(params, CFG, [2, 7], 4)
