"""Attention ops tests: blockwise == reference; ring and Ulysses
sequence-parallel forms == reference on the 8-device mesh.

The reference (HPX) has no attention; these validate the long-context
capability built on the halo/all_to_all substrate (SURVEY.md §5.7).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hpx_tpu.ops.attention import (blockwise_attention, reference_attention,
                                   ring_attention, ulysses_attention)
from hpx_tpu.parallel import make_mesh

B, S, N, H = 2, 64, 4, 16


def _qkv(seed=0, dtype=jnp.float32, s=S):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.standard_normal((B, s, N, H), np.float32), dtype)
    return mk(), mk(), mk()


def _close(a, b, dtype):
    tol = 2e-2 if dtype == jnp.bfloat16 else 2e-5
    np.testing.assert_allclose(
        np.asarray(a, np.float32), np.asarray(b, np.float32),
        rtol=tol, atol=tol)


class TestBlockwise:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("block_k", [16, 23, 64, 512])
    def test_matches_reference(self, causal, block_k):
        q, k, v = _qkv()
        want = reference_attention(q, k, v, causal)
        got = blockwise_attention(q, k, v, causal, block_k=block_k)
        _close(got, want, jnp.float32)

    def test_bfloat16(self):
        q, k, v = _qkv(dtype=jnp.bfloat16)
        want = reference_attention(q, k, v, True)
        got = blockwise_attention(q, k, v, True, block_k=32)
        assert got.dtype == jnp.bfloat16
        _close(got, want, jnp.bfloat16)

    def test_long_seq_memory_shape(self):
        q, k, v = _qkv(s=256)
        out = blockwise_attention(q, k, v, block_k=64)
        assert out.shape == (B, 256, N, H)


class TestPallasFlash:
    """The pallas kernel runs in interpret mode on the CPU mesh — same
    kernel code the TPU compiles, validated here block-by-block."""

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("shape", [(2, 70, 2, 64), (1, 128, 4, 32)])
    def test_matches_reference(self, causal, shape):
        from hpx_tpu.ops.attention_pallas import flash_attention
        b, s, n, h = shape
        rng = np.random.default_rng(5)
        q, k, v = (jnp.asarray(
            rng.standard_normal((b, s, n, h), np.float32))
            for _ in range(3))
        want = reference_attention(q, k, v, causal)
        got = flash_attention(q, k, v, causal, block_q=32, block_k=16)
        _close(got, want, jnp.float32)

    def test_ragged_seq_padding(self):
        from hpx_tpu.ops.attention_pallas import flash_attention
        q, k, v = _qkv(seed=9, s=37)      # not a block multiple
        want = reference_attention(q, k, v, True)
        got = flash_attention(q, k, v, True, block_q=16, block_k=16)
        _close(got, want, jnp.float32)

    @pytest.mark.parametrize("sq,sk", [(16, 48), (48, 16), (37, 53)])
    def test_causal_cross_lengths(self, sq, sk):
        """causal with sq != sk must use bottom-right alignment
        (kj <= qi + (sk - sq)), matching reference/blockwise — the
        round-1 kernel used top-left and diverged. For sq > sk the
        leading rows see no keys; flash and blockwise both define those
        rows as 0 (reference's full softmax NaNs there), so that case
        compares flash against blockwise."""
        from hpx_tpu.ops.attention_pallas import flash_attention
        rng = np.random.default_rng(11)
        q = jnp.asarray(rng.standard_normal((B, sq, N, H), np.float32))
        k = jnp.asarray(rng.standard_normal((B, sk, N, H), np.float32))
        v = jnp.asarray(rng.standard_normal((B, sk, N, H), np.float32))
        want = (reference_attention(q, k, v, True) if sq <= sk else
                blockwise_attention(q, k, v, True, block_k=16))
        got = flash_attention(q, k, v, True, block_q=16, block_k=16)
        _close(got, want, jnp.float32)
        if sq <= sk:
            _close(blockwise_attention(q, k, v, True, block_k=16), want,
                   jnp.float32)

    def test_front_door_dispatch(self):
        from hpx_tpu.ops.attention import auto_attention
        q, k, v = _qkv(seed=10)
        _close(auto_attention(q, k, v, True),
               reference_attention(q, k, v, True), jnp.float32)


class TestRing:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal, mesh1d):
        mesh = make_mesh((8,), ("sp",))
        q, k, v = _qkv(seed=1)
        want = reference_attention(q, k, v, causal)
        got = ring_attention(q, k, v, mesh, "sp", causal)
        _close(got, want, jnp.float32)

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_chunk_ring_matches_reference(self, causal):
        """The pallas chunk kernel behind the TPU flash-ring path
        (ops/attention._ring_flash), validated by simulating the ring on
        the host: fold every rotating chunk with the traced global
        offset d, exactly as the device scan does. (pallas interpret
        mode cannot run INSIDE a vma-checked shard_map on CPU — the
        in-shard_map wiring is exercised on real TPU.)"""
        from hpx_tpu.ops.attention_pallas import flash_attention_chunk
        q, k, v = _qkv(seed=6)
        want = reference_attention(q, k, v, causal)
        nsh, sq = 4, S // 4
        outs = []
        for i in range(nsh):
            qc = jnp.moveaxis(q[:, i * sq:(i + 1) * sq], 2, 1
                              ).reshape(B * N, sq, H)
            acc = jnp.zeros((B * N, sq, H), jnp.float32)
            m = jnp.full((B * N, sq, 128), -1e30, jnp.float32)
            l = jnp.zeros((B * N, sq, 128), jnp.float32)
            for j in range(nsh):
                kc = jnp.moveaxis(k[:, j * sq:(j + 1) * sq], 2, 1
                                  ).reshape(B * N, sq, H)
                vc = jnp.moveaxis(v[:, j * sq:(j + 1) * sq], 2, 1
                                  ).reshape(B * N, sq, H)
                acc, m, l = flash_attention_chunk(
                    qc, kc, vc, acc, m, l, jnp.int32(i * sq - j * sq),
                    causal=causal, block_q=8, block_k=8)
            den = jnp.where(l[:, :, :1] > 0, l[:, :, :1], 1.0)
            o = (acc / den).reshape(B, N, sq, H)
            outs.append(jnp.moveaxis(o, 1, 2))
        got = jnp.concatenate(outs, axis=1).astype(q.dtype)
        _close(got, want, jnp.float32)

    def test_output_stays_sharded(self):
        mesh = make_mesh((8,), ("sp",))
        q, k, v = _qkv(seed=2)
        out = ring_attention(q, k, v, mesh, "sp")
        assert len(out.sharding.device_set) == 8

    def test_2d_mesh_dp_x_sp(self):
        # batch over dp, sequence over sp — the combined layout a
        # training step uses
        mesh = make_mesh((2, 4), ("dp", "sp"))
        q, k, v = _qkv(seed=3)
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh = NamedSharding(mesh, P("dp", "sp", None, None))
        q, k, v = (jax.device_put(x, sh) for x in (q, k, v))
        want = reference_attention(q, k, v, True)

        from hpx_tpu.utils.jaxcompat import shard_map
        import hpx_tpu.ops.attention as att

        def body(qc, kc, vc):
            # inside dp shard: ring over sp
            nshards = 4
            idx = jax.lax.axis_index("sp")
            b, sq, n, h = qc.shape
            q_pos = idx * sq + jnp.arange(sq)
            axes = ("dp", "sp")
            acc = att._pvary(jnp.zeros((b, sq, n, h), jnp.float32), axes)
            m = att._pvary(jnp.full((b, sq, n), -jnp.inf, jnp.float32),
                           axes)
            l = att._pvary(jnp.zeros((b, sq, n), jnp.float32), axes)

            def step(t, carry):
                acc, m, l, kc, vc = carry
                src = (idx - t) % nshards
                k_pos = src * sq + jnp.arange(sq)
                bias = jnp.where(k_pos[None, :] <= q_pos[:, None],
                                 0.0, -jnp.inf)
                acc, m, l = att._online_block(qc, kc, vc, acc, m, l, bias)
                perm = [(i, (i + 1) % nshards) for i in range(nshards)]
                kc = jax.lax.ppermute(kc, "sp", perm)
                vc = jax.lax.ppermute(vc, "sp", perm)
                return acc, m, l, kc, vc

            acc, m, l, _, _ = jax.lax.fori_loop(0, nshards, step,
                                                (acc, m, l, kc, vc))
            return att._finish(acc, l, qc.dtype)

        got = jax.jit(shard_map(
            body, mesh=mesh,
            in_specs=(P("dp", "sp"), P("dp", "sp"), P("dp", "sp")),
            out_specs=P("dp", "sp")))(q, k, v)
        _close(got, want, jnp.float32)


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        import jax as _j
        mesh = make_mesh((4,), ("sp",), _j.devices()[:4])
        q, k, v = _qkv(seed=4)
        want = reference_attention(q, k, v, causal)
        got = ulysses_attention(q, k, v, mesh, "sp", causal)
        _close(got, want, jnp.float32)

    def test_indivisible_heads_raises(self):
        import jax as _j
        mesh = make_mesh((8,), ("sp",), _j.devices())
        q, k, v = _qkv()          # N=4 heads < 8 shards
        with pytest.raises(ValueError):
            ulysses_attention(q, k, v, mesh, "sp")


class TestGqaXlaPaths:
    """GQA/MQA on the XLA formulations (oracle/fallback paths): fewer
    K/V heads broadcast per group (_expand_kv). The pallas kernels
    handle GQA natively (tests/test_attention_grad.py::TestGQA); these
    pin the non-TPU paths to the repeat-heads oracle."""

    def _gqa(self, nkv, seed=0):
        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.standard_normal((2, 64, 8, 16)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((2, 64, nkv, 16)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((2, 64, nkv, 16)), jnp.float32)
        return q, k, v

    @pytest.mark.parametrize("nkv", [1, 2, 4])
    def test_blockwise_matches_repeat_oracle(self, nkv):
        from hpx_tpu.ops.attention import (blockwise_attention,
                                           reference_attention)
        q, k, v = self._gqa(nkv)
        got = blockwise_attention(q, k, v, causal=True)
        kr = jnp.repeat(k, 8 // nkv, axis=2)
        vr = jnp.repeat(v, 8 // nkv, axis=2)
        want = reference_attention(q, kr, vr, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_rejects_non_divisible(self):
        from hpx_tpu.ops.attention import blockwise_attention
        q, k, v = self._gqa(3)
        with pytest.raises(ValueError, match="multiple"):
            blockwise_attention(q, k, v)

    def test_ring_sharded_gqa(self, devices):
        """GQA through the XLA ring path under a 4-shard sp mesh."""
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from hpx_tpu.utils.jaxcompat import shard_map
        from hpx_tpu.ops.attention import (reference_attention,
                                           ring_attention_sharded)
        mesh = Mesh(np.array(devices[:4]), ("sp",))
        q, k, v = self._gqa(2, seed=1)
        spec = P(None, "sp", None, None)

        def body(qc, kc, vc):
            return ring_attention_sharded(qc, kc, vc, "sp", 4,
                                          causal=True, use_flash=False)

        got = jax.jit(shard_map(body, mesh=mesh,
                                in_specs=(spec, spec, spec),
                                out_specs=spec))(q, k, v)
        kr = jnp.repeat(k, 4, axis=2)
        vr = jnp.repeat(v, 4, axis=2)
        want = reference_attention(q, kr, vr, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=3e-5, atol=3e-5)

    def test_ulysses_gqa_non_divisible_kv(self, devices):
        """kv heads (2) < shards (4): ulysses broadcasts KV up front."""
        from jax.sharding import Mesh
        from hpx_tpu.ops.attention import (reference_attention,
                                           ulysses_attention)
        mesh = Mesh(np.array(devices[:4]), ("sp",))
        q, k, v = self._gqa(2, seed=2)
        got = ulysses_attention(q, k, v, mesh, "sp", causal=True,
                                use_flash=False)
        kr = jnp.repeat(k, 4, axis=2)
        vr = jnp.repeat(v, 4, axis=2)
        want = reference_attention(q, kr, vr, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=3e-5, atol=3e-5)

    def test_ring_flash_gqa(self, devices):
        """GQA through the FLASH ring path (interpret on CPU): the
        library broadcasts grouped K/V before the chunk kernel —
        regression for the nshards>1 flash branch."""
        from jax.sharding import Mesh, PartitionSpec as P
        from hpx_tpu.utils.jaxcompat import shard_map
        from hpx_tpu.ops.attention import (reference_attention,
                                           ring_attention_sharded)
        mesh = Mesh(np.array(devices[:4]), ("sp",))
        q, k, v = self._gqa(2, seed=3)
        spec = P(None, "sp", None, None)

        def body(qc, kc, vc):
            return ring_attention_sharded(qc, kc, vc, "sp", 4,
                                          causal=True, use_flash=True)

        # check_vma=False: pallas interpret can't thread vma through
        # the chunk kernel (same caveat as tests/test_attention_grad);
        # the vma-checked wiring runs on real TPU via pytest -m tpu
        got = jax.jit(shard_map(body, mesh=mesh,
                                in_specs=(spec, spec, spec),
                                out_specs=spec,
                                check_vma=False))(q, k, v)
        kr = jnp.repeat(k, 4, axis=2)
        vr = jnp.repeat(v, 4, axis=2)
        want = reference_attention(q, kr, vr, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-3, atol=2e-3)


class TestBlockResolution:
    def test_default_blocks(self, monkeypatch):
        from hpx_tpu.ops import attention_pallas as ap
        monkeypatch.setattr(ap, "_blocks_table", {})   # no tuned table
        monkeypatch.delenv("HPX_FLASH_BLOCK_Q", raising=False)
        monkeypatch.delenv("HPX_FLASH_BLOCK_K", raising=False)
        assert ap.resolve_blocks(4096, 4096, True) == (1024, 1024)
        monkeypatch.setattr(ap, "_blocks_table", None)

    def test_env_override(self, monkeypatch):
        from hpx_tpu.ops import attention_pallas as ap
        monkeypatch.setenv("HPX_FLASH_BLOCK_Q", "256")
        monkeypatch.setenv("HPX_FLASH_BLOCK_K", "512")
        assert ap.resolve_blocks(4096, 4096, True) == (256, 512)

    def test_table_override(self, tmp_path, monkeypatch):
        import json
        from hpx_tpu.ops import attention_pallas as ap
        p = tmp_path / "flash_blocks.json"
        p.write_text(json.dumps({"4096x4096x1": [512, 1024]}))
        monkeypatch.setattr(ap, "_BLOCKS_FILE", str(p))
        monkeypatch.setattr(ap, "_blocks_table", None)   # drop cache
        assert ap.resolve_blocks(4096, 4096, True) == (512, 1024)
        assert ap.resolve_blocks(2048, 2048, True) == (1024, 1024)
        monkeypatch.setattr(ap, "_blocks_table", None)

    def test_explicit_blocks_still_honored(self):
        import numpy as np
        import jax.numpy as jnp
        from hpx_tpu.ops.attention_pallas import flash_attention
        from hpx_tpu.ops.attention import reference_attention
        rng = np.random.default_rng(7)
        q = jnp.asarray(rng.standard_normal((1, 64, 2, 8)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((1, 64, 2, 8)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((1, 64, 2, 8)), jnp.float32)
        out = flash_attention(q, k, v, causal=True, block_q=16,
                              block_k=32)
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-3)

    def test_partial_env_override_keeps_table_value(self, tmp_path,
                                                    monkeypatch):
        import json
        from hpx_tpu.ops import attention_pallas as ap
        p = tmp_path / "flash_blocks.json"
        p.write_text(json.dumps({"4096x4096x1": [512, 512]}))
        monkeypatch.setattr(ap, "_BLOCKS_FILE", str(p))
        monkeypatch.setattr(ap, "_blocks_table", None)
        monkeypatch.setenv("HPX_FLASH_BLOCK_Q", "256")
        monkeypatch.delenv("HPX_FLASH_BLOCK_K", raising=False)
        # q from env, k from the tuned table — not a hardcoded 1024
        assert ap.resolve_blocks(4096, 4096, True) == (256, 512)
        monkeypatch.setattr(ap, "_blocks_table", None)


class TestStripedRing:
    """Striped Attention: stripe_sequence layout + per-step offsets in
    {0, -1} balance causal ring work. Results must match the
    contiguous ring / reference exactly (same math, reordered)."""

    def test_stripe_roundtrip_and_layout(self):
        from hpx_tpu.ops.attention import (stripe_sequence,
                                           unstripe_sequence)
        x = jnp.arange(24).reshape(1, 24)
        y = stripe_sequence(x, 4)
        # shard r of 4 holds tokens r, r+4, ...
        np.testing.assert_array_equal(
            np.asarray(y)[0, :6], [0, 4, 8, 12, 16, 20])
        np.testing.assert_array_equal(np.asarray(
            unstripe_sequence(y, 4)), np.asarray(x))
        with pytest.raises(ValueError, match="divisible"):
            stripe_sequence(x, 5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_striped_ring_matches_reference(self, causal, mesh1d):
        from hpx_tpu.ops.attention import ring_attention
        mesh = make_mesh((8,), ("sp",))
        q, k, v = _qkv(seed=11)
        want = reference_attention(q, k, v, causal)
        got = ring_attention(q, k, v, mesh, "sp", causal, striped=True)
        _close(got, want, jnp.float32)

    def test_striped_flash_chunk_offsets(self):
        """The flash path's striped offsets, simulated on the host the
        same way test_flash_chunk_ring_matches_reference does: chunk
        (i, j) folds with d = 0 (j <= i) or -1 — the result, after
        unstriping, is the reference."""
        from hpx_tpu.ops.attention import (stripe_sequence,
                                           unstripe_sequence)
        from hpx_tpu.ops.attention_pallas import flash_attention_chunk
        q, k, v = _qkv(seed=12)
        want = reference_attention(q, k, v, True)
        nsh, sq = 4, S // 4
        qs = stripe_sequence(q, nsh)
        ks = stripe_sequence(k, nsh)
        vs = stripe_sequence(v, nsh)
        outs = []
        for i in range(nsh):
            qc = jnp.moveaxis(qs[:, i * sq:(i + 1) * sq], 2, 1
                              ).reshape(B * N, sq, H)
            acc = jnp.zeros((B * N, sq, H), jnp.float32)
            m = jnp.full((B * N, sq, 128), -1e30, jnp.float32)
            l = jnp.zeros((B * N, sq, 128), jnp.float32)
            for j in range(nsh):
                kc = jnp.moveaxis(ks[:, j * sq:(j + 1) * sq], 2, 1
                                  ).reshape(B * N, sq, H)
                vc = jnp.moveaxis(vs[:, j * sq:(j + 1) * sq], 2, 1
                                  ).reshape(B * N, sq, H)
                acc, m, l = flash_attention_chunk(
                    qc, kc, vc, acc, m, l,
                    jnp.int32(0 if j <= i else -1),
                    causal=True, block_q=8, block_k=8)
            den = jnp.where(l[:, :, :1] > 0, l[:, :, :1], 1.0)
            o = (acc / den).reshape(B, N, sq, H)
            outs.append(jnp.moveaxis(o, 1, 2))
        got = unstripe_sequence(
            jnp.concatenate(outs, axis=1), nsh).astype(q.dtype)
        _close(got, want, jnp.float32)
