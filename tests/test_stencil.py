"""1d_stencil workload tests (BASELINE config #2 parity).

Reference analog: examples/1d_stencil — correctness is cross-checked
between the serial, dataflow, fused-XLA, fused-pallas, and sharded-mesh
variants (all must agree bitwise-ish on the same physics), mirroring how
the reference's ladder validates against 1d_stencil_1.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import hpx_tpu as hpx
from hpx_tpu.models.stencil1d import (
    StencilParams, gather_dataflow_result, init_domain, stencil_dataflow,
    stencil_fused, stencil_serial,
)
from hpx_tpu.ops.stencil import heat_step, pallas_multistep, xla_multistep
from hpx_tpu.parallel import (
    make_mesh, shard_1d, sharded_heat_step, sharded_multistep,
)


def numpy_reference(p: StencilParams) -> np.ndarray:
    u = np.arange(p.total, dtype=np.float64)
    for _ in range(p.nt):
        u = u + p.coef * (np.roll(u, 1) - 2 * u + np.roll(u, -1))
    return u


def test_serial_matches_numpy():
    p = StencilParams(nx=64, np_=4, nt=20, k=0.25)
    got = np.asarray(stencil_serial(p), dtype=np.float64)
    np.testing.assert_allclose(got, numpy_reference(p), rtol=1e-4)


def test_dataflow_matches_serial():
    p = StencilParams(nx=32, np_=8, nt=15, k=0.25)
    u = stencil_dataflow(p)
    got = np.asarray(gather_dataflow_result(u))
    want = np.asarray(stencil_serial(p))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_fused_xla_matches_serial():
    p = StencilParams(nx=128, np_=4, nt=40, k=0.25)
    got = np.asarray(stencil_fused(p, steps_per_dispatch=10,
                                   use_pallas=False))
    want = np.asarray(stencil_serial(p))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_pallas_multistep_matches_xla():
    # pallas path needs length % 128 == 0; runs in interpreter-compatible
    # mode on CPU backend
    n, steps, coef = 512, 8, jnp.float32(0.25)
    u = jnp.arange(n, dtype=jnp.float32)
    try:
        got = pallas_multistep(u, coef, steps)
    except Exception as e:  # pallas-on-CPU unavailable in this jax build
        pytest.skip(f"pallas unavailable on CPU backend: {e}")
    want = xla_multistep(u, coef, steps)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)


def test_sharded_heat_step_matches_serial(mesh1d):
    n = 8 * 32
    u = jnp.arange(n, dtype=jnp.float32)
    us = shard_1d(u, mesh1d)
    step = sharded_heat_step(mesh1d, "x")
    coef = jnp.float32(0.25)
    got = us
    for _ in range(5):
        got = step(got, coef)
    want = u
    for _ in range(5):
        want = heat_step(want, coef)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)


def test_sharded_multistep_single_program(mesh1d):
    n = 8 * 64
    u = jnp.arange(n, dtype=jnp.float32)
    us = shard_1d(u, mesh1d)
    coef = jnp.float32(0.3)
    fn = sharded_multistep(mesh1d, "x", steps=12, halo_steps=3)
    got = fn(us, coef)
    want = u
    for _ in range(12):
        want = heat_step(want, coef)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4)
    # sharding preserved (no implicit gather)
    assert len(got.sharding.device_set) == 8


def test_sharded_wide_halo_equivalence(mesh1d):
    # halo_steps=4 (communication-avoiding) must equal halo_steps=1
    n = 8 * 64
    u = jnp.arange(n, dtype=jnp.float32)
    us = shard_1d(u, mesh1d)
    coef = jnp.float32(0.25)
    a = sharded_multistep(mesh1d, "x", steps=8, halo_steps=1)(us, coef)
    b = sharded_multistep(mesh1d, "x", steps=8, halo_steps=4)(us, coef)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)


def test_conservation():
    # periodic heat equation conserves the sum
    p = StencilParams(nx=64, np_=4, nt=50, k=0.4)
    u = stencil_fused(p, use_pallas=False)
    np.testing.assert_allclose(float(jnp.sum(u)),
                               float(jnp.sum(init_domain(p))), rtol=1e-3)


def test_pallas_heat_step_seams_interpret(monkeypatch):
    """The blocked kernel's in-kernel seam patch (r4: per-slab SMEM edge
    scalars replaced the host-side scatter): every slab-boundary element
    must get its TRUE global-periodic neighbors. Small slabs force
    multiple grid steps so all seam cases (interior + wraparound) hit."""
    from hpx_tpu.ops import stencil as st
    monkeypatch.setattr(st, "_BLOCK_ROWS", 8)
    n, coef = 8 * 128 * 4, jnp.float32(0.3)
    u = jnp.asarray(np.random.default_rng(7).random(n, np.float32))
    got = st.pallas_heat_step(u, coef, interpret=True)
    want = heat_step(u, coef)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)
