"""Bucketed + chunked prefill (models/serving.ContinuousServer).

The contract: chunking a prompt into fixed-width padded windows and
splicing the scratch cache changes WHICH programs run, never the
bytes — every request still equals its solo transformer.generate()
run, for prompt lengths straddling every bucket boundary, dense and
paged, greedy and sampled, async dispatch on and off.  Plus the
scheduling guarantees: the program cache stays O(buckets), and a
short prompt admitted behind a long prompt's chunked prefill
overtakes its tail chunks (ready-chunk ordering)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hpx_tpu.models import transformer as tfm
from hpx_tpu.models.serving import ContinuousServer, _resolve_buckets

CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4, head_dim=8,
                            n_layers=2, d_ff=64)

# ladder (4, 8): plens straddle every boundary (b-1, b, b+1) of both
# buckets AND the chunk boundary at 8 (9 and 15/16/17 need 2-3 chunks)
LADDER = "4,8"
CHUNK = 8
PLENS = [3, 4, 5, 7, 8, 9, 15, 16, 17]


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.PRNGKey(0))


def _prompt(plen, seed):
    r = np.random.RandomState(seed)
    return [int(t) for t in r.randint(1, CFG.vocab, size=plen)]


def _solo(params, prompt, m, t=0.0, key=None, eos_id=None):
    out = tfm.generate(params, CFG, jnp.asarray([prompt], jnp.int32),
                       max_new=m, temperature=t, key=key, eos_id=eos_id)
    return [int(x) for x in np.asarray(out)[0]]


def test_resolve_buckets():
    assert _resolve_buckets("auto", 128) == (8, 16, 32, 64, 128)
    assert _resolve_buckets("auto", 8) == (8,)
    assert _resolve_buckets("auto", 3) == (3,)
    # csv: clamped to the chunk, deduped, chunk width always present
    assert _resolve_buckets("64,16", 32) == (16, 32)
    assert _resolve_buckets("4, 8", 8) == (4, 8)
    with pytest.raises(ValueError, match=">= 1"):
        _resolve_buckets("0,4", 8)
    with pytest.raises(ValueError, match="nothing"):
        _resolve_buckets(" , ", 8)


@pytest.mark.parametrize("paged", [False, True], ids=["dense", "paged"])
@pytest.mark.parametrize("async_dispatch", [True, False],
                         ids=["async", "sync"])
def test_boundary_plens_match_generate(params, paged, async_dispatch):
    """Every bucket-boundary prompt length, greedy AND sampled mixed in
    one batch, byte-identical to the solo run."""
    srv = ContinuousServer(params, CFG, slots=3, smax=64, paged=paged,
                           prefill_chunk=CHUNK, prefill_buckets=LADDER,
                           async_dispatch=async_dispatch)
    want = {}
    for i, plen in enumerate(PLENS):
        p = _prompt(plen, seed=100 + plen)
        if i % 2:
            k = jax.random.PRNGKey(7 * i)
            rid = srv.submit(p, max_new=6, temperature=0.9, key=k)
            want[rid] = _solo(params, p, 6, t=0.9, key=k)
        else:
            rid = srv.submit(p, max_new=6)
            want[rid] = _solo(params, p, 6)
    out = srv.run()
    assert out == want


def test_program_cache_is_o_buckets(params):
    """After a mixed-length workload, the module program cache holds at
    most one chunk program PER LADDER WIDTH for this server shape —
    not one per prompt length."""
    srv = ContinuousServer(params, CFG, slots=3, smax=64,
                           prefill_chunk=CHUNK, prefill_buckets=LADDER)
    for plen in PLENS:
        srv.submit(_prompt(plen, seed=200 + plen), max_new=4)
    srv.run()
    chunk_keys = [k for k in tfm._PROGRAMS
                  if k[0] == "cb_chunk" and k[1] == CFG and k[3] == 64]
    assert 0 < len(chunk_keys) <= len(srv.prefill_buckets)
    widths = sorted(k[2] for k in chunk_keys)
    assert set(widths) <= set(srv.prefill_buckets)


def test_second_server_reuses_programs(params):
    """Same shapes on a fresh server: zero program builds (the cache
    key carries no per-request state)."""
    srv = ContinuousServer(params, CFG, slots=3, smax=64,
                           prefill_chunk=CHUNK, prefill_buckets=LADDER)
    for plen in PLENS:
        srv.submit(_prompt(plen, seed=300 + plen), max_new=4)
    srv.run()
    srv2 = ContinuousServer(params, CFG, slots=3, smax=64,
                            prefill_chunk=CHUNK, prefill_buckets=LADDER)
    # NEW lengths, same buckets
    for plen in [6, 10, 13]:
        srv2.submit(_prompt(plen, seed=400 + plen), max_new=4)
    out = srv2.run()
    assert srv2._prog_misses == 0
    assert srv2._prog_hits > 0
    for rid, plen in zip(sorted(out), [6, 10, 13]):
        assert out[rid] == _solo(params, _prompt(plen, 400 + plen), 4)


def test_short_prompt_overtakes_long_prefill(params):
    """Satellite: fairness. A long prompt's chunked prefill must not
    starve a short prompt admitted behind it — ready-chunk ordering
    advances the pending with the fewest remaining tokens first, so
    the short request SEEDS (ttft) before the long one."""
    srv = ContinuousServer(params, CFG, slots=2, smax=64,
                           prefill_chunk=4, prefill_buckets="4")
    long_p = _prompt(40, seed=1)     # 10 chunks of 4
    short_p = _prompt(6, seed=2)     # 2 chunks — but admitted second
    a = srv.submit(long_p, max_new=4)
    b = srv.submit(short_p, max_new=4)
    out = srv.run()
    # ttft insertion order == seeding order
    assert list(srv.ttft) == [b, a]
    assert out[a] == _solo(params, long_p, 4)
    assert out[b] == _solo(params, short_p, 4)


def test_inline_admit_bypasses_pending_queue(params):
    """A prompt that fits one chunk prefills inline at admission even
    while a long pending occupies another slot."""
    srv = ContinuousServer(params, CFG, slots=2, smax=64,
                           prefill_chunk=4, prefill_buckets="4")
    a = srv.submit(_prompt(30, seed=3), max_new=4)   # deferred
    b = srv.submit(_prompt(3, seed=4), max_new=4)    # inline
    srv.step()
    assert b in srv.ttft and a not in srv.ttft
    out = srv.run()
    assert out[a] == _solo(params, _prompt(30, 3), 4)
    assert out[b] == _solo(params, _prompt(3, 4), 4)


def test_equal_remaining_is_fifo(params):
    """Ready-chunk ties break by admission order."""
    srv = ContinuousServer(params, CFG, slots=2, smax=64,
                           prefill_chunk=4, prefill_buckets="4")
    a = srv.submit(_prompt(20, seed=5), max_new=3)
    b = srv.submit(_prompt(20, seed=6), max_new=3)
    out = srv.run()
    assert list(srv.ttft) == [a, b]
    assert out[a] == _solo(params, _prompt(20, 5), 3)
    assert out[b] == _solo(params, _prompt(20, 6), 3)


def test_chunked_prefill_with_eos(params):
    """eos retirement timing is unchanged by chunked prefill and async
    dispatch."""
    p = _prompt(19, seed=8)
    probe = _solo(params, p, 8)
    eos = probe[3]
    srv = ContinuousServer(params, CFG, slots=2, smax=64,
                           prefill_chunk=4, prefill_buckets="4")
    a = srv.submit(p, max_new=8, eos_id=eos)
    b = srv.submit(_prompt(2, seed=9), max_new=5)
    out = srv.run()
    assert out[a] == _solo(params, p, 8, eos_id=eos)
    assert out[b] == _solo(params, _prompt(2, 9), 5)


def test_paged_prefix_reuse_skips_chunks(params):
    """Paged + radix: the second request's matched prefix starts its
    chunk cursor past the shared blocks — fewer chunks, same bytes."""
    shared = _prompt(32, seed=10)
    p1 = shared + _prompt(4, seed=11)
    p2 = shared + _prompt(4, seed=12)
    srv = ContinuousServer(params, CFG, slots=1, smax=64, paged=True,
                           block_size=16, prefill_chunk=8,
                           prefill_buckets="8")
    a = srv.submit(p1, max_new=4)
    out1 = srv.run()
    chunks_first = srv._chunks
    b = srv.submit(p2, max_new=4)
    out2 = srv.run()
    assert srv._chunks - chunks_first < chunks_first  # prefix skipped
    assert srv.cache_stats()["prefill_tokens_saved"] >= 32
    assert out1[a] == _solo(params, p1, 4)
    assert out2[b] == _solo(params, p2, 4)


def test_async_buffer_caps_and_flushes(params):
    """max_async_steps bounds the buffer; results are unaffected."""
    srv = ContinuousServer(params, CFG, slots=1, smax=64,
                           async_dispatch=True)
    srv._max_async = 3
    p = _prompt(5, seed=13)
    a = srv.submit(p, max_new=20)
    out = srv.run()
    assert out[a] == _solo(params, p, 20)
    assert not srv._buf
