"""Multi-locality collectives workload (run under hpx_tpu.run).

Reference analog: libs/full/collectives/tests/unit run at LOCALITIES>1
(SURVEY.md §4). Exercises every verb + channels + latch across real
processes over the TCP parcelport; exit code 0 per locality on success.
"""

import operator
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import hpx_tpu as hpx
from hpx_tpu.collectives import (
    all_gather, all_reduce, all_to_all, barrier, broadcast,
    exclusive_scan, gather, inclusive_scan, reduce, scatter,
)
from hpx_tpu.testing import HPX_TEST, HPX_TEST_EQ, report_errors

T = 60.0


def main() -> int:
    rt = hpx.init()
    me = hpx.find_here()
    n = hpx.get_num_localities()
    HPX_TEST(n >= 2, "need multiple localities")
    comm = hpx.create_communicator("smoke", num_sites=n, this_site=me)

    HPX_TEST_EQ(all_reduce(comm, me + 1).get(timeout=T),
                n * (n + 1) // 2)
    HPX_TEST_EQ(all_gather(comm, me * 2).get(timeout=T),
                [2 * i for i in range(n)])

    got = reduce(comm, me, op=operator.add, root=1).get(timeout=T)
    if me == 1:
        HPX_TEST_EQ(got, n * (n - 1) // 2)
    else:
        HPX_TEST(got is None)

    HPX_TEST_EQ(broadcast(comm, "root-data" if me == 0 else None,
                          root=0).get(timeout=T), "root-data")
    HPX_TEST_EQ(scatter(comm, [f"p{i}" for i in range(n)]
                        if me == 0 else None).get(timeout=T), f"p{me}")
    HPX_TEST_EQ(all_to_all(comm, [(me, j) for j in range(n)]).get(timeout=T),
                [(j, me) for j in range(n)])
    HPX_TEST_EQ(inclusive_scan(comm, me + 1).get(timeout=T),
                (me + 1) * (me + 2) // 2)
    exc = exclusive_scan(comm, me + 1).get(timeout=T)
    HPX_TEST(exc is None if me == 0 else exc == me * (me + 1) // 2)

    # numpy payload across the wire
    arr = all_reduce(comm, np.full(16, float(me))).get(timeout=T)
    np.testing.assert_allclose(arr, np.full(16, float(n * (n - 1) / 2)))

    HPX_TEST(barrier(comm).get(timeout=T))

    # channel communicator: ring send
    cc = hpx.create_channel_communicator("ring", num_sites=n, this_site=me)
    cc.set((me + 1) % n, f"from-{me}")
    HPX_TEST_EQ(cc.get((me - 1) % n).get(timeout=T), f"from-{(me - 1) % n}")

    # distributed channel hosted on locality 0
    if me == 0:
        dch = hpx.DistributedChannel.create("mpchan")
    else:
        dch = hpx.DistributedChannel.connect("mpchan")
    dch.set(me * 100).get(timeout=T)
    total = sum(dch.get().get(timeout=T) for _ in range(n)) if me == 0 else 0
    if me == 0:
        HPX_TEST_EQ(total, 100 * n * (n - 1) // 2)

    # distributed latch: everyone arrives
    latch = hpx.DistributedLatch("mplatch", n)
    HPX_TEST(latch.arrive_and_wait().get(timeout=T))

    rt.barrier("collectives-done")
    hpx.finalize()
    return report_errors()


if __name__ == "__main__":
    sys.exit(main())
