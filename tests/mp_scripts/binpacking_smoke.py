"""Multi-locality binpacked/colocated placement smoke (4 localities).

Locality 1 is pre-loaded with components; binpacked() placement must
avoid it and spread new components across the others by argmin load,
and colocated() must follow a component through migration.

Reference analog: binpacking_distribution_policy /
colocating_distribution_policy tests (SURVEY.md §2.4
distribution_policies row).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import hpx_tpu as hpx
from hpx_tpu.testing import HPX_TEST, HPX_TEST_EQ, report_errors


@hpx.register_component_type
class Widget(hpx.Component):
    def __init__(self, tag: str = "") -> None:
        self.tag = tag

    def where_am_i(self) -> int:
        return hpx.find_here()


@hpx.register_component_type
class OtherKind(hpx.Component):
    pass


def main() -> int:
    hpx.init()
    here = hpx.find_here()
    n = hpx.get_num_localities()

    if here == 0:
        # skew the load: 6 Widgets pinned to locality 1, and some
        # OtherKind on locality 2 (must NOT count toward Widget load)
        heavy = [hpx.new_(Widget, 1, "ballast").get() for _ in range(6)]
        other = [hpx.new_(OtherKind, 2).get() for _ in range(3)]

        # binpacked avoids the loaded locality entirely
        placed = [hpx.new_(Widget, hpx.binpacked(), "bp").get()
                  for _ in range(3)]
        homes = sorted(c.sync("where_am_i") for c in placed)
        HPX_TEST(1 not in homes, f"binpacked placed on loaded loc: {homes}")

        # batch resolve spreads greedily instead of piling on one argmin
        locs = hpx.binpacked().resolve(
            n - 1, Widget.__dict__["_component_type_name"])
        HPX_TEST_EQ(len(set(locs)), n - 1)

        # per-type load: OtherKind's ballast on 2 is invisible to
        # Widget placement but visible to its own
        locs_other = hpx.binpacked().resolve(
            1, OtherKind.__dict__["_component_type_name"])
        HPX_TEST(locs_other[0] != 2, f"OtherKind ignored own load: "
                 f"{locs_other}")

        # candidate restriction is honored
        only12 = hpx.binpacked(localities=[1, 2]).resolve(
            1, Widget.__dict__["_component_type_name"])
        HPX_TEST_EQ(only12, [2])      # 1 carries the ballast

        # perf-counter-driven load (uptime is monotone > 0 everywhere;
        # just proves the remote counter path resolves)
        viacnt = hpx.new_(
            Widget, hpx.binpacked(counter=("runtime", "uptime")),
            "cnt").get()
        HPX_TEST(0 <= viacnt.sync("where_am_i") < n)

        # colocated follows the component, including through migration
        anchor = hpx.new_(Widget, 2, "anchor").get()
        c1 = hpx.new_(Widget, hpx.colocated(anchor), "neighbor").get()
        HPX_TEST_EQ(c1.sync("where_am_i"), 2)
        hpx.migrate(anchor, 3).get()
        c2 = hpx.new_(Widget, hpx.colocated(anchor), "neighbor2").get()
        HPX_TEST_EQ(c2.sync("where_am_i"), 3)

        for c in heavy + other + placed + [viacnt, anchor, c1, c2]:
            c.free().get()
        hpx.get_runtime().barrier("done")
    else:
        hpx.get_runtime().barrier("done")

    hpx.finalize()
    return report_errors()


if __name__ == "__main__":
    sys.exit(main())
