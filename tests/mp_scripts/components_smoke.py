"""Multi-locality components smoke (3 localities).

Exercises the cross-process component protocol end-to-end:
remote hpx::new_, client shipping through AGAS basenames, remote
invocation from a third locality, migration 1→2 with live invocations
chasing the forward, and remote free.

Reference analog: components/tests + examples/quickstart component
demos (SURVEY.md §2.4, §2.6).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import hpx_tpu as hpx
from hpx_tpu.testing import HPX_TEST, HPX_TEST_EQ, report_errors


@hpx.register_component_type
class Accumulator(hpx.Component):
    def __init__(self, start: int = 0) -> None:
        self.value = int(start)
        self.hosts = [hpx.find_here()]   # records where it has lived

    def add(self, n: int) -> int:
        self.value += n
        return self.value

    def where_am_i(self) -> int:
        return hpx.find_here()

    def history(self):
        return list(self.hosts)

    def on_migrated(self) -> None:
        self.hosts.append(hpx.find_here())


def main() -> int:
    hpx.init()
    here = hpx.find_here()

    if here == 0:
        # create on locality 1, publish for everyone
        acc = hpx.new_(Accumulator, 1, 100).get()
        HPX_TEST_EQ(acc.gid.home, 1)
        HPX_TEST_EQ(acc.sync("where_am_i"), 1)
        hpx.register_with_basename("smoke/acc", acc).get()

        # everyone contributes (below); wait for them
        hpx.get_runtime().barrier("contributed")
        HPX_TEST_EQ(acc.sync("add", 0), 100 + 1 + 2)

        # migrate 1 -> 2 while invoking concurrently
        futs = [acc.call("add", 0) for _ in range(8)]
        moved = hpx.migrate(acc, 2).get()
        HPX_TEST_EQ(moved.sync("where_am_i"), 2)
        for f in futs:
            HPX_TEST_EQ(f.get(), 103)    # adds of 0: value unchanged
        HPX_TEST_EQ(moved.sync("history"), [1, 2])
        # stale client (pre-migration handle) still resolves via forward
        HPX_TEST_EQ(acc.sync("where_am_i"), 2)
        hpx.get_runtime().barrier("migrated")
        hpx.get_runtime().barrier("checked")   # workers verified placement

        # free remotely; later use fails
        HPX_TEST(moved.free().get() is True)
        try:
            moved.sync("add", 1)
            HPX_TEST(False, "invoke after free must raise")
        except hpx.HpxError:
            pass
        hpx.get_runtime().barrier("done")
    else:
        acc = hpx.find_from_basename("smoke/acc").get()
        acc.sync("add", here)            # 1 and 2 each contribute
        hpx.get_runtime().barrier("contributed")
        hpx.get_runtime().barrier("migrated")
        # after migration every locality agrees on placement
        HPX_TEST_EQ(acc.sync("where_am_i"), 2)
        hpx.get_runtime().barrier("checked")
        hpx.get_runtime().barrier("done")

    hpx.finalize()
    return report_errors()


if __name__ == "__main__":
    sys.exit(main())
