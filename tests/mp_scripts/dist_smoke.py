"""Multi-locality smoke workload (run under hpx_tpu.run).

Exercises: bootstrap, remote actions with results and exceptions, AGAS
register/resolve rendezvous, fire-and-forget, barrier. Exit code 0 on
success per locality (the launcher maxes them).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import hpx_tpu as hpx
from hpx_tpu.dist import agas
from hpx_tpu.testing import HPX_TEST, HPX_TEST_EQ, report_errors


@hpx.plain_action
def square(x):
    return x * x


@hpx.plain_action
def whoami():
    return hpx.find_here()


@hpx.plain_action
def fail_with(msg):
    raise ValueError(msg)


def main() -> int:
    rt = hpx.init()
    here = hpx.find_here()
    n = hpx.get_num_localities()
    HPX_TEST(n >= 2, "need multiple localities")

    # every locality calls an action on every other
    futs = [hpx.async_action(square, loc, here * 10 + loc)
            for loc in hpx.find_all_localities()]
    for loc, f in enumerate(futs):
        HPX_TEST_EQ(f.get(timeout=30.0), (here * 10 + loc) ** 2)

    # identity: remote action runs remotely
    for loc in hpx.find_remote_localities():
        HPX_TEST_EQ(hpx.async_action(whoami, loc).get(timeout=30.0), loc)

    # exceptions propagate across the wire
    try:
        hpx.async_action(fail_with, (here + 1) % n, f"boom-{here}").get(
            timeout=30.0)
        HPX_TEST(False, "expected ValueError")
    except ValueError as e:
        HPX_TEST_EQ(str(e), f"boom-{here}")

    # AGAS rendezvous: everyone registers; everyone resolves everyone
    agas.register_name(f"value/{here}", here * 100).get(timeout=30.0)
    for loc in hpx.find_all_localities():
        got = agas.resolve_name(f"value/{loc}", wait=True).get(timeout=30.0)
        HPX_TEST_EQ(got, loc * 100)

    rt.barrier("smoke-done")
    hpx.finalize()
    return report_errors()


if __name__ == "__main__":
    sys.exit(main())
