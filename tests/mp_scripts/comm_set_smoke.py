"""Tree collectives at real multi-process scale (7 localities, arity 3).

A depth-2 communication_set over 7 sites: leaf groups {0,1,2} {3,4,5}
{6} with roots 0/3/6, and a flat top communicator over the roots at
locality 0. Exercises all_reduce / broadcast / barrier / reduce through
the tree and then PROVES the load-spreading the tree exists for: every
locality reports how many exchanges it hosted root state for
(collectives.hosted_count) — group roots must have hosted, non-roots
must have hosted none.

Reference analog: libs/full/collectives communication_set tests
(SURVEY.md §2.4 collectives row).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import hpx_tpu as hpx
from hpx_tpu.collectives.comm_set import create_communication_set
from hpx_tpu.collectives.communicator import hosted_exchange_count
from hpx_tpu.dist.actions import async_action
from hpx_tpu.testing import HPX_TEST, HPX_TEST_EQ, report_errors

ARITY = 3
ROUNDS = 3


def main() -> int:
    hpx.init()
    here = hpx.find_here()
    n = hpx.get_num_localities()
    cs = create_communication_set("smoke/tree", arity=ARITY)

    for r in range(ROUNDS):
        # all_reduce: sum of (site + r) over all sites
        got = cs.all_reduce(here + r).get(timeout=120)
        HPX_TEST_EQ(got, sum(range(n)) + n * r)

        # broadcast: everyone sees site 0's value
        val = f"round-{r}" if here == 0 else None
        HPX_TEST_EQ(cs.broadcast(val).get(timeout=120), f"round-{r}")

        # reduce: only site 0 gets the fold
        red = cs.reduce(1).get(timeout=120)
        HPX_TEST_EQ(red, n if here == 0 else None)

        cs.barrier().get(timeout=120)

    # placement check from locality 0: root state must live on the
    # group roots (0, 3, 6, ... plus the top at 0) and NOWHERE else
    cs.barrier().get(timeout=120)
    if here == 0:
        roots = {g * ARITY for g in range(-(-n // ARITY))}
        counts = {loc: async_action(hosted_exchange_count, loc
                                    ).get(timeout=120)
                  for loc in range(n)}
        for loc, c in counts.items():
            if loc in roots:
                HPX_TEST(c > 0, f"group root {loc} hosted nothing: "
                         f"{counts}")
            else:
                HPX_TEST_EQ((loc, c), (loc, 0))
        # fan-in genuinely spread: locality 0 did not host everything
        total = sum(counts.values())
        HPX_TEST(counts[0] < total, counts)
    hpx.get_runtime().barrier("counted")

    hpx.finalize()
    return report_errors()


if __name__ == "__main__":
    sys.exit(main())
