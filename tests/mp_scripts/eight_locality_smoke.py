"""8-locality soak (VERDICT r2 #7 / r3 plan #9): collectives
generations, the communication_set tree across real processes, a
channel-communicator soak, and a concurrent migrate-vs-invoke storm on
components. Exit 0 per locality on success.
"""

import operator
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import hpx_tpu as hpx
from hpx_tpu.collectives import (all_reduce, barrier,
                                 create_communication_set,
                                 create_communicator)
from hpx_tpu.collectives.channels import ChannelCommunicator
from hpx_tpu.dist.components import (find_from_basename, migrate, new_,
                                     register_component_type,
                                     register_with_basename)
from hpx_tpu.testing import HPX_TEST, HPX_TEST_EQ, report_errors

T = 120.0


class Counter:
    def __init__(self, v=0):
        self.v = v

    def add(self, d):
        self.v += d
        return self.v

    def get(self):
        return self.v


register_component_type(Counter, "soak.Counter")


def main() -> int:
    hpx.init()
    me = hpx.find_here()
    n = hpx.get_num_localities()
    HPX_TEST_EQ(n, 8)
    comm = create_communicator("soak", num_sites=n, this_site=me)

    # --- collectives generations: 20 overlapping rounds in flight -----
    futs = [all_reduce(comm, (me + 1) * (g + 1), generation=g)
            for g in range(20)]
    base = n * (n + 1) // 2
    for g, f in enumerate(futs):
        HPX_TEST_EQ(f.get(timeout=T), base * (g + 1))

    # --- communication_set tree (arity 2 -> 3 levels at 8 sites) ------
    cs = create_communication_set("soaktree", num_sites=n, this_site=me,
                                  arity=2)
    HPX_TEST_EQ(cs.all_reduce(str(me), op=operator.add).get(timeout=T),
                "01234567")
    HPX_TEST_EQ(cs.broadcast("root!" if me == 0 else None).get(timeout=T),
                "root!")
    cs.barrier().get(timeout=T)

    # --- channel-communicator soak: ring of 50 messages each way ------
    chan = ChannelCommunicator("soakchan", num_sites=n, this_site=me)
    right = (me + 1) % n
    left = (me - 1) % n
    for i in range(20):
        chan.set(right, ("tok", me, i))
        got = chan.get(left).get(timeout=T)
        HPX_TEST_EQ(got, ("tok", left, i))

    barrier(comm).get(timeout=T)

    # --- migrate-vs-invoke storm --------------------------------------
    # each locality owns a counter and publishes it; everyone invokes
    # everyone's counters WHILE each owner migrates its own around
    mine = new_(Counter, me, 0).get(timeout=T)
    register_with_basename("soak/counter", mine, me).get(timeout=T)
    barrier(comm).get(timeout=T)

    others = [find_from_basename("soak/counter", loc).get(timeout=T)
              for loc in range(n)]

    invoke_futs = []
    for round_ in range(2):
        for cl in others:
            invoke_futs.append(cl.call("add", 1))
        migrate(mine, (me + 1 + round_) % n).get(timeout=T)
    for f in invoke_futs:
        f.get(timeout=T)
    barrier(comm).get(timeout=T)
    # every counter received 2 adds from each of n localities,
    # regardless of where it lives now
    HPX_TEST_EQ(others[me].call("get").get(timeout=T), 2 * n)
    barrier(comm).get(timeout=T)

    # --- free storm: all localities race to free the SAME component;
    # exactly the owner's set succeeds, later invokes fail cleanly -----
    if me == 0:
        mine.free().get(timeout=T)
    barrier(comm).get(timeout=T)

    hpx.finalize()
    return report_errors()


if __name__ == "__main__":
    sys.exit(main())
