"""Multi-locality services smoke: hpx::cout marshalling to the console
locality + distributed replay retargeting localities."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import hpx_tpu as hpx
from hpx_tpu.testing import HPX_TEST, HPX_TEST_EQ, report_errors

_fail_on = {0}   # locality 0's attempt fails -> replay must move to 1


@hpx.plain_action
def flaky_where():
    here = hpx.find_here()
    if here in _fail_on:
        raise RuntimeError(f"injected failure on locality {here}")
    return here


def main() -> int:
    hpx.init()
    here = hpx.find_here()

    # every locality writes through hpx.cout; all output lands on the
    # console (locality 0) stdout — the launcher surfaces it either way,
    # what we verify here is that the flush future completes remotely.
    hpx.cout.println(f"[cout] locality {here} says hello")
    hpx.cout.flush().get(timeout=15.0)

    if here == 0:
        # distributed replay: first attempt (here=0) fails, retargets 1
        v = hpx.async_replay_distributed(3, flaky_where).get(timeout=30.0)
        HPX_TEST_EQ(v, 1)

    hpx.get_runtime().barrier("svc-done")
    hpx.finalize()
    return report_errors()


if __name__ == "__main__":
    sys.exit(main())
