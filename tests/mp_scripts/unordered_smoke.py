"""Multi-locality unordered_map smoke (3 localities).

Partitions land one per locality; every locality connects by name,
writes its own keys, and reads everyone else's. Reference analog:
components/containers/unordered distributed tests.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import hpx_tpu as hpx
from hpx_tpu.testing import HPX_TEST, HPX_TEST_EQ, report_errors


def main() -> int:
    hpx.init()
    here = hpx.find_here()
    nloc = hpx.get_num_localities()

    if here == 0:
        m = hpx.UnorderedMap()          # one partition per locality
        HPX_TEST_EQ(m.num_partitions, nloc)
        m.register_as("smoke-map").get()
        hpx.get_runtime().barrier("map-ready")
    else:
        hpx.get_runtime().barrier("map-ready")
        m = hpx.UnorderedMap.connect_to("smoke-map")

    # each locality writes 10 keys
    m.update({(here, i): here * 100 + i for i in range(10)}).get()
    hpx.get_runtime().barrier("written")

    # ... and reads every other locality's keys
    for loc in range(nloc):
        for i in range(10):
            HPX_TEST_EQ(m[(loc, i)], loc * 100 + i)
    HPX_TEST_EQ(len(m), nloc * 10)

    # partitions really are spread: each partition component lives on a
    # distinct locality
    wheres = sorted(p.where().get() for p in m._parts)
    HPX_TEST_EQ(wheres, list(range(nloc)))

    hpx.get_runtime().barrier("read")
    if here == 0:
        m.free().get()
    hpx.finalize()
    return report_errors()


if __name__ == "__main__":
    sys.exit(main())
