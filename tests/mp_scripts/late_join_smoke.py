"""Late-join (hpx::start + --hpx:connect analog) smoke.

Launched as a 2-locality job via hpx_tpu.run; locality 0 then spawns a
THIRD process with HPX_TPU_CONNECT=1 that attaches to the running job.
Checks: the joiner gets locality id 2, incumbents observe the grown
membership, and actions flow BOTH directions between incumbents and the
joiner. Exit 0 per process on success.
"""

import os
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import hpx_tpu as hpx
from hpx_tpu.dist.actions import async_action, plain_action
from hpx_tpu.testing import HPX_TEST, HPX_TEST_EQ, report_errors

T = 60.0

_done_n = [0]
_done_cv = threading.Condition()


@plain_action(name="lj.echo")
def echo(tag, caller):
    return (tag, caller, hpx.find_here())


@plain_action(name="lj.done")
def done():
    with _done_cv:
        _done_n[0] += 1
        _done_cv.notify_all()
    return True


def wait_members(n, timeout=T):
    deadline = time.monotonic() + timeout
    while hpx.get_num_localities() < n:
        HPX_TEST(time.monotonic() < deadline,
                 f"membership never reached {n}")
        time.sleep(0.05)


def main() -> int:
    hpx.init()
    if os.environ.get("HPX_TPU_CONNECT") == "1":
        # ---- the late joiner --------------------------------------------
        me = hpx.find_here()
        HPX_TEST_EQ(me, 2)
        HPX_TEST_EQ(hpx.get_num_localities(), 3)
        # joiner -> incumbents
        HPX_TEST_EQ(async_action("lj.echo", 0, "from-joiner", me
                                 ).get(timeout=T), ("from-joiner", 2, 0))
        HPX_TEST_EQ(async_action("lj.echo", 1, "from-joiner", me
                                 ).get(timeout=T), ("from-joiner", 2, 1))
        # leave only after BOTH incumbents have called into us
        with _done_cv:
            HPX_TEST(_done_cv.wait_for(lambda: _done_n[0] >= 2, T),
                     "incumbents never reached the joiner")
        # reverse handshake: tell each incumbent we are finished so it
        # can close — without this, an incumbent that finished its own
        # half early closes its endpoint while our echo to it is still
        # in flight (observed as "send to peer failed" under load)
        HPX_TEST_EQ(async_action("lj.done", 0).get(timeout=T), True)
        HPX_TEST_EQ(async_action("lj.done", 1).get(timeout=T), True)
        # orderly shutdown: finalize() barriers all three localities
        # and drains in-flight replies before any endpoint closes, so
        # no reply frame is stranded by an early close
        hpx.finalize()
        return report_errors()

    me = hpx.find_here()
    child = None
    if me == 0:
        env = dict(os.environ)
        env["HPX_TPU_CONNECT"] = "1"
        env.pop("HPX_TPU_LOCALITY", None)
        child = subprocess.Popen([sys.executable, __file__], env=env)
    wait_members(3)
    # incumbents -> joiner (route forms from the joiner's IDENT dial)
    HPX_TEST_EQ(async_action("lj.echo", 2, "to-joiner", me
                             ).get(timeout=T), ("to-joiner", me, 2))
    HPX_TEST_EQ(async_action("lj.done", 2).get(timeout=T), True)
    # wait for the joiner's reverse handshake before closing (it may
    # still be mid-exchange with us or the other incumbent)
    with _done_cv:
        HPX_TEST(_done_cv.wait_for(lambda: _done_n[0] >= 1, T),
                 "joiner never signaled completion")
    hpx.finalize()
    if child is not None:
        HPX_TEST_EQ(child.wait(timeout=T), 0)
    return report_errors()


if __name__ == "__main__":
    sys.exit(main())
