"""Disaggregated serving smoke over REAL localities (5 processes).

Topology: locality 0 runs the DisaggRouter; 1-2 register PrefillWorkers,
3-4 register DecodeWorkers (hpx.disagg.invoke reaches them by worker
id). Mid-flight the router hard-kills one worker of EACH role with the
``hpx.disagg.die`` action (os._exit — no goodbye), so the failure
detector must notice the honest way: heartbeat pong age or a failed
socket send promoting the locality to DEAD and failing pending parcels
with typed LocalityLost. The router fails over to the surviving worker
of each role, and the final tokens must equal single-process
``tfm.generate`` references exactly.

Run under hpx_tpu.run with 5 localities (the tier-1 slow test does).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from hpx_tpu.core.config import runtime_config

# fast failure detection + a finalize barrier that cannot hang on the
# two corpses (finalize swallows the barrier timeout)
runtime_config().set("hpx.dist.heartbeat_interval", "0.2")
runtime_config().set("hpx.barrier_timeout", "8")
# one decode step per router tick: the kill below must land while its
# victim still has decode work outstanding
runtime_config().set("hpx.serving.disagg.pump_steps", "1")

import hpx_tpu as hpx
from hpx_tpu.dist import agas
from hpx_tpu.dist.actions import post_action
from hpx_tpu.testing import HPX_TEST, HPX_TEST_EQ, report_errors

BS = 8          # one KV block grid for prefill framing + decode pools
SMAX = 64


def _model():
    import jax
    from hpx_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                head_dim=8, n_layers=2, d_ff=64)
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    return params, cfg


def _requests():
    import numpy as np
    rng = np.random.default_rng(3)
    return [([int(t) for t in rng.integers(1, 64,
                                           int(rng.integers(4, 18)))],
             12 + i) for i in range(5)]


def main() -> int:
    hpx.init()
    here = hpx.find_here()
    HPX_TEST_EQ(hpx.get_num_localities(), 5)
    params, cfg = _model()

    if here in (1, 2):
        from hpx_tpu.models.disagg import PrefillWorker, register_worker
        register_worker("pw", PrefillWorker(params, cfg, smax=SMAX,
                                            block_size=BS))
    elif here in (3, 4):
        from hpx_tpu.models.disagg import DecodeWorker, register_worker
        register_worker("dw", DecodeWorker(params, cfg, slots=2,
                                           smax=SMAX, block_size=BS))
    agas.register_name(f"disagg/up/{here}", 1).get(timeout=60.0)

    if here == 0:
        import jax
        import jax.numpy as jnp
        import numpy as np
        from hpx_tpu.models import transformer as tfm
        from hpx_tpu.models.disagg import DisaggRouter, RemoteHandle

        for loc in range(1, 5):
            agas.resolve_name(f"disagg/up/{loc}",
                              wait=True).get(timeout=60.0)

        reqs = _requests()
        refs = []
        for prompt, mn in reqs:
            out = tfm.generate(params, cfg,
                               jnp.asarray([prompt], jnp.int32),
                               max_new=mn)
            refs.append([int(t) for t in np.asarray(out)[0]])

        router = DisaggRouter(
            params, cfg, slots=2, smax=SMAX,
            server_kwargs={"block_size": BS},
            prefill_handles=[
                RemoteHandle("prefill", loc, "pw", timeout_s=20.0,
                             retries=2) for loc in (1, 2)],
            decode_handles=[
                RemoteHandle("decode", loc, "dw", timeout_s=20.0,
                             retries=2) for loc in (3, 4)])
        for prompt, mn in reqs:
            router.submit(prompt, mn)

        # one router tick starts prefills on 1 and 2 — then locality 1
        # dies mid-prefill, for real
        router.step()
        post_action("hpx.disagg.die", 1)
        # step until some request is actively DECODING on locality 3,
        # then kill it: with 12+ tokens left and one decode step per
        # tick, the death lands with work outstanding and the next
        # pump must fail over to locality 4
        h3 = router._decode[0]
        while not any(r.state == "decode" and r.decode_h is h3
                      for r in router._reqs.values()):
            if not router.step():
                break
        post_action("hpx.disagg.die", 3)
        out = router.run()

        st = router.stats()
        HPX_TEST(st["failovers"]["prefill"] >= 1,
                 f"no prefill failover: {st}")
        HPX_TEST(st["failovers"]["decode"] >= 1,
                 f"no decode failover: {st}")
        HPX_TEST(not st["degraded"],
                 f"degraded despite survivors: {st}")
        for rid, want in enumerate(refs):
            HPX_TEST_EQ(out.get(rid), want)
        router.close()
        HPX_TEST_EQ(router.leaked_blocks(), 0)
        agas.register_name("disagg/done", 1).get(timeout=60.0)
    else:
        # workers serve until the router reports done (the two killed
        # localities never reach this wait — or this line)
        agas.resolve_name("disagg/done", wait=True).get(timeout=240.0)

    hpx.finalize()
    return report_errors()


if __name__ == "__main__":
    sys.exit(main())
