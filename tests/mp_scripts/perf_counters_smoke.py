"""Multi-locality perf-counter smoke: remote counter query via actions.

Locality 0 queries locality 1's thread counter by name (the reference
queries any locality's counters the same way — SURVEY.md §2.5).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import hpx_tpu as hpx
from hpx_tpu.svc import performance_counters as pc
from hpx_tpu.testing import HPX_TEST, HPX_TEST_EQ, report_errors


def main() -> int:
    hpx.init()
    here = hpx.find_here()

    # generate some local pool work everywhere
    hpx.wait_all([hpx.async_(lambda: None) for _ in range(10)])
    # barrier: locality 0 must not query locality 1's thread counter
    # until locality 1 has actually executed its tasks
    hpx.get_runtime().barrier("pc-work-done")

    if here == 0:
        other = 1
        name = (f"/threads{{locality#{other}/pool#default}}"
                "/count/cumulative")
        v = pc.query_counter(name).value
        HPX_TEST(v >= 10, v)
        # parcel counters registered once the distributed runtime is up
        sent = pc.query_counter(
            f"/parcels{{locality#{here}/total}}/count/sent").value
        HPX_TEST(sent >= 1, sent)
        # remote uptime too
        up = pc.query_counter(
            f"/runtime{{locality#{other}/total}}/uptime").value
        HPX_TEST(up > 0)
    hpx.get_runtime().barrier("pc-done")
    hpx.finalize()
    return report_errors()


if __name__ == "__main__":
    sys.exit(main())
