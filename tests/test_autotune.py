"""Closed-loop adaptive executor (svc/autotune.AdaptiveTuner).

Two layers under test.  The CONTROLLER layer runs the tuner against
synthetic signal streams (a pure-python response surface standing in
for the serving loop) and pins convergence, bounds, hysteresis,
compile-cost charging, arbiter exclusivity, and replay determinism.
The INTEGRATION layer runs a real ContinuousServer with
``hpx.tune.enable=1`` and pins the differential contract: the tuner
may move throughput knobs, never tokens — tuned output is byte-equal
to the untuned server, and a no-op tuner (freeze="*") leaves the
program-cache counters identical to tune-off.
"""

import jax
import numpy as np
import pytest

from hpx_tpu.core.config import runtime_config
from hpx_tpu.core.config_schema import Tunable
from hpx_tpu.models import transformer as tfm
from hpx_tpu.models.serving import ContinuousServer
from hpx_tpu.svc.autotune import (
    AdaptiveTuner,
    KnobBinding,
    TuneArbiter,
    TuneSignals,
    replay,
)

CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4, head_dim=8,
                            n_layers=2, d_ff=64)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.PRNGKey(0))


# ---------------------------------------------------------------------------
# controller harness: a synthetic response surface
# ---------------------------------------------------------------------------

def _knob(cell, name="k", lo=1, hi=256, step=2, geometric=True,
          compiles=False):
    return KnobBinding(
        name, Tunable(lo=lo, hi=hi, step=step, geometric=geometric,
                      compiles=compiles),
        lambda: cell[name], lambda v: cell.__setitem__(name, v))


def _drive(tuner, cell, surface, evals, settle=True):
    """Run ``evals`` evaluations, sampling the synthetic response
    surface (a function of the CURRENT knob values) before each; then
    settle any in-flight probe so assertions see an accepted value,
    not a half-finished experiment (interval_ticks=1 harnesses)."""
    for _ in range(evals):
        tuner.maybe_tick(lambda: surface(cell))
    if settle and tuner._phase == "probe":
        tuner.maybe_tick(lambda: surface(cell))


def test_converges_to_peak_and_holds():
    """Unimodal surface peaked at k=64: the tuner climbs to the peak
    and then oscillation is bounded to probe/revert pairs around it —
    the value between evaluations never leaves {peak, one step}."""
    cell = {"k": 4}
    t = AdaptiveTuner([_knob(cell)], interval_ticks=1,
                      hysteresis_pct=1.0, cooldown_ticks=1)

    def surface(c):
        # peak 100 at k=64, falling off in log-distance
        k = c["k"]
        return TuneSignals(
            tok_rate=100.0 - 25.0 * abs(np.log2(k / 64.0)),
            stall_p99=0.0, queue_depth=0.0)

    _drive(t, cell, surface, 40)
    assert cell["k"] in (32, 64, 128)    # at/next to the peak
    assert t.accepts >= 4                # climbed 4 -> 64
    # late-phase: reverts happen (probes off the peak fail) but the
    # accepted value keeps returning to the peak
    settled = [d for d in t.decisions() if d["action"] == "revert"]
    assert settled, "expected failed probes around the optimum"
    for d in settled:
        assert cell["k"] >= 1


def test_step_change_retracks():
    """The optimum moves mid-run (64 -> 8): the controller walks back
    down after the phase change without a reset."""
    cell = {"k": 64}
    t = AdaptiveTuner([_knob(cell)], interval_ticks=1,
                      hysteresis_pct=1.0, cooldown_ticks=0)
    phase = {"peak": 64.0}

    def surface(c):
        return TuneSignals(
            tok_rate=100.0 - 25.0 * abs(np.log2(c["k"] / phase["peak"])),
            stall_p99=0.0, queue_depth=0.0)

    _drive(t, cell, surface, 6)
    assert cell["k"] in (32, 64, 128)
    phase["peak"] = 8.0
    _drive(t, cell, surface, 40)
    assert cell["k"] in (4, 8, 16), cell["k"]


def test_overload_backs_off_on_stall():
    """Stall p99 grows superlinearly with the knob (the overload
    regime): the stall term dominates the objective and the tuner
    walks the knob DOWN."""
    cell = {"k": 128}
    t = AdaptiveTuner([_knob(cell)], interval_ticks=1,
                      hysteresis_pct=1.0, cooldown_ticks=0)

    def surface(c):
        k = c["k"]
        return TuneSignals(tok_rate=10.0 + k * 0.01,
                           stall_p99=(k / 64.0) ** 2,
                           queue_depth=float(k))

    _drive(t, cell, surface, 30)
    assert cell["k"] <= 16, cell["k"]


def test_bounds_are_hard():
    """A monotone surface pushes the knob to a bound; every value the
    controller ever applied stays inside [lo, hi]."""
    cell = {"k": 16}
    t = AdaptiveTuner([_knob(cell, lo=4, hi=64)], interval_ticks=1,
                      hysteresis_pct=1.0, cooldown_ticks=0)
    _drive(t, cell, lambda c: TuneSignals(
        tok_rate=float(c["k"]), stall_p99=0.0, queue_depth=0.0), 30)
    for d in t.decisions():
        if d["new"] is not None:
            assert 4 <= d["new"] <= 64
    assert cell["k"] == 64
    # and the other direction
    cell2 = {"k": 16}
    t2 = AdaptiveTuner([_knob(cell2, lo=4, hi=64)], interval_ticks=1,
                       hysteresis_pct=1.0, cooldown_ticks=0)
    _drive(t2, cell2, lambda c: TuneSignals(
        tok_rate=-float(c["k"]), stall_p99=0.0, queue_depth=0.0), 30)
    assert cell2["k"] == 4
    for d in t2.decisions():
        if d["new"] is not None:
            assert 4 <= d["new"] <= 64


def test_hysteresis_rejects_sub_band_gains():
    """An oscillating surface whose swing stays under the hysteresis
    band: every probe reverts (no-thrash), and the knob always returns
    to its starting value between probe pairs."""
    cell = {"k": 32}
    t = AdaptiveTuner([_knob(cell)], interval_ticks=1,
                      hysteresis_pct=10.0, cooldown_ticks=0)
    flip = {"s": 1.0}

    def surface(c):
        flip["s"] = -flip["s"]          # +-1% oscillation, band is 10%
        return TuneSignals(tok_rate=100.0 + flip["s"],
                           stall_p99=0.0, queue_depth=0.0)

    _drive(t, cell, surface, 30)
    assert t.accepts == 0
    assert t.reverts >= 5
    assert cell["k"] in (16, 32, 64)    # never drifted past one step
    # every revert restored the pre-probe value
    for d in t.decisions():
        if d["action"] == "revert":
            assert d["old"] == 32


def test_cooldown_spaces_probes_per_knob():
    """After a revert the knob sits out cooldown_ticks evaluations —
    with one knob and cooldown=2 the action stream shows holds
    between probe pairs."""
    cell = {"k": 32}
    t = AdaptiveTuner([_knob(cell)], interval_ticks=1,
                      hysteresis_pct=50.0, cooldown_ticks=2)
    _drive(t, cell, lambda c: TuneSignals(
        tok_rate=100.0, stall_p99=0.0, queue_depth=0.0), 12)
    acts = [d["action"] for d in t.decisions()]
    i = acts.index("revert")
    assert acts[i + 1] == "hold" and acts[i + 2] == "hold"


def test_compile_cost_inflates_accept_threshold():
    """A compiles=True knob whose probe mints measured compile time:
    the gain must clear hysteresis + 100*charged/amortize.  A 20%
    gain with 15s charged against a 30s horizon (50% surcharge)
    reverts; the same gain with 0.6s charged (2%) accepts."""
    def run(compile_cost_s):
        cell = {"k": 32}
        t = AdaptiveTuner([_knob(cell, compiles=True)],
                          interval_ticks=1, hysteresis_pct=5.0,
                          cooldown_ticks=0, compile_amortize_s=30.0)
        comp = {"s": 1.0}
        probed = {"done": False}

        def surface(c):
            if c["k"] != 32 and not probed["done"]:
                probed["done"] = True
                comp["s"] += compile_cost_s    # the probe minted a program
            return TuneSignals(
                tok_rate=120.0 if c["k"] != 32 else 100.0,
                stall_p99=0.0, queue_depth=0.0,
                compile_s_total=comp["s"])

        _drive(t, cell, surface, 2)            # probe + settle
        return t

    assert run(15.0).reverts == 1              # 20% < 5% + 50%
    assert run(0.6).accepts == 1               # 20% >= 5% + 2%


def test_compile_knob_frozen_without_profiler():
    """compile_s_total=None (no profiler): a compiles=True knob is
    never probed — an unmeasurable compile cost cannot be charged."""
    cell = {"k": 32}
    t = AdaptiveTuner([_knob(cell, compiles=True)], interval_ticks=1,
                      hysteresis_pct=1.0)
    _drive(t, cell, lambda c: TuneSignals(
        tok_rate=float(c["k"]), stall_p99=0.0, queue_depth=0.0), 10)
    assert t.probes == 0 and cell["k"] == 32
    assert all(d["action"] == "hold" for d in t.decisions())


def test_freeze_list_and_wildcard():
    cell = {"a": 32, "b": 32}
    ka, kb = _knob(cell, "a"), _knob(cell, "b")
    t = AdaptiveTuner([ka, kb], interval_ticks=1, hysteresis_pct=1.0,
                      freeze="a")
    _drive(t, cell, lambda c: TuneSignals(
        tok_rate=float(c["a"] + c["b"]), stall_p99=0.0,
        queue_depth=0.0), 10)
    assert cell["a"] == 32 and cell["b"] > 32
    cell2 = {"a": 32, "b": 32}
    t2 = AdaptiveTuner([_knob(cell2, "a"), _knob(cell2, "b")],
                       interval_ticks=1, freeze="*")
    _drive(t2, cell2, lambda c: TuneSignals(
        tok_rate=1.0, stall_p99=0.0, queue_depth=0.0), 10)
    assert t2.probes == 0 and cell2 == {"a": 32, "b": 32}


def test_seed_rotates_probe_order_deterministically():
    def first_probe(seed):
        cell = {"a": 32, "b": 32, "c": 32}
        t = AdaptiveTuner([_knob(cell, n) for n in ("a", "b", "c")],
                          interval_ticks=1, seed=seed)
        t.maybe_tick(lambda: TuneSignals(
            tok_rate=1.0, stall_p99=0.0, queue_depth=0.0))
        return t.decisions()[0]["knob"]

    assert first_probe(0) == "a"
    assert first_probe(1) == "b"
    assert first_probe(2) == "c"
    assert first_probe(0) == first_probe(3)


def test_arbiter_grants_shared_budget_exclusively():
    """Two tuners share an arbiter over a SHARED_BUDGET knob: while
    one holds the probe, the other's attempt is denied (a hold), and
    the denial is recorded into its signal history for replay."""
    arb = TuneArbiter()
    shared = "hpx.cache.radix_budget_blocks"
    ca, cb = {shared: 64}, {shared: 64}
    ta = AdaptiveTuner([_knob(ca, shared, lo=8, hi=1 << 20)],
                       name="decode#0", interval_ticks=1,
                       hysteresis_pct=1.0, arbiter=arb)
    tb = AdaptiveTuner([_knob(cb, shared, lo=8, hi=1 << 20)],
                       name="decode#1", interval_ticks=1,
                       hysteresis_pct=1.0, arbiter=arb)
    sig = TuneSignals(tok_rate=1.0, stall_p99=0.0, queue_depth=0.0)
    ta.maybe_tick(lambda: sig)          # ta probes: holds the grant
    tb.maybe_tick(lambda: sig)          # tb denied -> hold
    assert ta.probes == 1
    assert tb.probes == 0 and tb.holds == 1
    assert tb.signal_history()[0]["denied"] == [shared]
    ta.maybe_tick(lambda: sig)          # ta settles: releases
    tb.maybe_tick(lambda: sig)          # now tb can probe
    assert tb.probes == 1
    # both histories replay exactly, including the denied round
    assert replay(ta.flight_state()) == ta.decisions()
    assert replay(tb.flight_state()) == tb.decisions()


def test_replay_reproduces_decisions():
    """The flight-recorder contract: rebuild from flight_state, feed
    the recorded signals, get the identical decision log — across
    accepts, reverts, holds, and interval_ticks > 1."""
    for interval in (1, 4):
        cell = {"k": 4}
        t = AdaptiveTuner([_knob(cell)], interval_ticks=interval,
                          hysteresis_pct=1.0, cooldown_ticks=1)

        def surface(c):
            k = c["k"]
            return TuneSignals(
                tok_rate=100.0 - 25.0 * abs(np.log2(k / 64.0)),
                stall_p99=0.0, queue_depth=0.0)

        _drive(t, cell, surface, 30 * interval, settle=False)
        assert t.evals == 30
        assert replay(t.flight_state()) == t.decisions()


def test_interval_gates_evaluations():
    cell = {"k": 32}
    t = AdaptiveTuner([_knob(cell)], interval_ticks=8)
    calls = {"n": 0}

    def collect():
        calls["n"] += 1
        return TuneSignals(tok_rate=1.0, stall_p99=0.0, queue_depth=0.0)

    for _ in range(17):
        t.maybe_tick(collect)
    assert t.ticks == 17 and t.evals == 2 and calls["n"] == 2


def test_validates_interval():
    with pytest.raises(ValueError):
        AdaptiveTuner([], interval_ticks=0)


# ---------------------------------------------------------------------------
# integration: real server, differential contract
# ---------------------------------------------------------------------------

_REQS = [dict(prompt=[3, 1, 4], max_new=9),
         dict(prompt=[2, 7], max_new=5),
         dict(prompt=[5, 6, 7, 8, 9], max_new=12),
         dict(prompt=[1], max_new=7),
         dict(prompt=[9, 9, 2, 1], max_new=3),
         dict(prompt=[4, 4], max_new=10)]


def _serve(params, *, tune, sampled=False, interval="2", freeze=None,
           **srv_kw):
    """One serving run; returns ({req index: tokens}, server)."""
    rc = runtime_config()
    saved = {k: rc.get(k) for k in
             ("hpx.tune.enable", "hpx.tune.interval_ticks",
              "hpx.tune.hysteresis_pct", "hpx.tune.freeze")}
    rc.set("hpx.tune.enable", "1" if tune else "0")
    rc.set("hpx.tune.interval_ticks", interval)
    rc.set("hpx.tune.hysteresis_pct", "1")
    if freeze is not None:
        rc.set("hpx.tune.freeze", freeze)
    try:
        srv = ContinuousServer(params, CFG, slots=3, smax=64, **srv_kw)
        rids = {}
        for i, r in enumerate(_REQS):
            kw = dict(r)
            if sampled and i % 2 == 0:
                kw.update(temperature=0.8, key=jax.random.PRNGKey(i))
            rids[srv.submit(**kw)] = i
        out = srv.run()
        return {rids[r]: v for r, v in out.items()}, srv
    finally:
        for k, v in saved.items():
            rc.set(k, v if v is not None else "")


@pytest.mark.parametrize("sampled", [False, True],
                         ids=["greedy", "sampled"])
@pytest.mark.parametrize("paged", [False, True],
                         ids=["dense", "paged"])
def test_tuned_output_sha_identical(params, sampled, paged):
    """The heart of the differential contract: with the tuner live and
    probing every 2 flushes, every request's tokens are byte-equal to
    the untuned run — the tuner moves only output-invariant knobs."""
    kw = dict(paged=True) if paged else {}
    base, _ = _serve(params, tune=False, sampled=sampled, **kw)
    tuned, srv = _serve(params, tune=True, sampled=sampled, **kw)
    assert srv._tuner is not None and srv._tuner.evals > 0
    assert tuned == base


def test_noop_tuner_counter_identical_to_disabled(params):
    """freeze="*" (the tuner ticks but never probes) against
    hpx.tune.enable=0: identical program-cache traffic and step
    counts — the tick path is observation-only."""
    base, s0 = _serve(params, tune=False)
    noop, s1 = _serve(params, tune=True, freeze="*")
    assert noop == base
    assert s1._tuner.probes == 0 and s1._tuner.evals > 0
    assert s1._prog_misses == s0._prog_misses
    assert s1._prog_hits == s0._prog_hits


def test_compile_guard_no_extra_programs(params):
    """With no profiler active the tuner cannot charge compile moves,
    so a live probing tuner mints ZERO extra programs over the
    untuned run (prefill_chunk stays frozen; the moved knobs are
    shape-invariant)."""
    _, s0 = _serve(params, tune=False)
    _, s1 = _serve(params, tune=True)
    assert s1._tuner.probes > 0
    assert s1._prog_misses == s0._prog_misses


def test_tune_counters_registered_and_advance(params):
    from hpx_tpu.svc import performance_counters as pc
    _, srv = _serve(params, tune=True)
    inst = srv.counter_instance
    names = pc.discover_counters(
        f"/serving{{locality#*/{inst}}}/tune/*")
    assert any(n.endswith("/tune/ticks") for n in names)
    got = {n.rsplit("/", 1)[-1]:
           pc.query_counter(n).value for n in names}
    assert got["ticks"] == srv._tuner.ticks > 0
    assert got["evals"] == srv._tuner.evals > 0
    assert (got["accepts"] + got["reverts"] + got["holds"]
            + srv._tuner.probes - got["probes"]) >= 0
    # tune-off servers register no tune counters
    _, s0 = _serve(params, tune=False)
    assert s0._tuner is None


def test_reload_knobs_applies_config_writes_at_flush(params):
    """The operator path: a runtime_config().set() of a tunable key is
    picked up by _reload_knobs (generation-gated), clamped to the
    server's ladders; constructor overrides survive unrelated
    writes."""
    rc = runtime_config()
    srv = ContinuousServer(params, CFG, slots=2, smax=64,
                           prefill_chunk=8)
    assert srv.prefill_chunk == 8
    saved = rc.get("hpx.serving.ckpt_every")
    try:
        # unrelated write: bumps the generation, must NOT clobber the
        # prefill_chunk=8 constructor override back to the default
        rc.set("hpx.serving.ckpt_every", "128")
        srv._reload_knobs()
        assert srv.prefill_chunk == 8
        assert srv._ckpt_every == 128
        # a write to the key itself IS applied, clamped to the ladder
        saved_pc = rc.get("hpx.serving.prefill_chunk")
        try:
            rc.set("hpx.serving.prefill_chunk", "1000000")
            srv._reload_knobs()
            assert srv.prefill_chunk == srv.prefill_buckets[-1]
        finally:
            rc.set("hpx.serving.prefill_chunk",
                   saved_pc if saved_pc is not None else "auto")
    finally:
        rc.set("hpx.serving.ckpt_every", saved if saved is not None
               else "16")


def test_disagg_workers_get_tuners_and_shared_arbiter(params):
    """Under a DisaggRouter with tuning on, every in-proc worker's
    embedded server carries its own tuner, all joined to ONE
    router-level arbiter with per-role names — and the routed output
    still matches the untuned router byte for byte."""
    from hpx_tpu.models.disagg import DisaggRouter
    rc = runtime_config()

    def run(tune):
        rc.set("hpx.tune.enable", "1" if tune else "0")
        rc.set("hpx.tune.interval_ticks", "2")
        try:
            r = DisaggRouter(params, CFG, prefill_workers=1,
                             decode_workers=2, slots=3, smax=64)
            for req in _REQS:
                r.submit(req["prompt"], req["max_new"])
            out = r.run()
            r.close()
            return out, r
        finally:
            rc.set("hpx.tune.enable", "0")

    base, _ = run(False)
    tuned, router = run(True)
    assert tuned == base
    tuners = []
    for h in router._decode + router._prefill:
        worker = getattr(h, "worker", None)
        srv = getattr(worker, "srv", None) or getattr(
            worker, "_eng", None)
        if getattr(srv, "_tuner", None) is not None:
            tuners.append(srv._tuner)
    assert len(tuners) == 3
    arbs = {id(t.arbiter) for t in tuners}
    assert arbs == {id(router._tune_arbiter)}
    names = {t.name for t in tuners}
    assert names == {"decode#0", "decode#1", "prefill#0"}


def test_flight_bundle_embeds_and_replays_tuner(params):
    """A flight bundle captured during a tuned run carries the tuner's
    decision log in its ``tune`` section, and that section replays to
    the identical decisions — the post-incident debugging loop."""
    import gc

    from hpx_tpu.svc import flight
    _, srv = _serve(params, tune=True)
    assert srv._tuner.evals > 0
    gc.collect()        # drop tuners of servers earlier tests freed
    doc = flight.build_bundle("manual", site="test")
    assert flight.validate_bundle(doc) == []
    assert any(k == "serving" or k.startswith("serving#")
               for k in doc["tune"])
    # other live servers in this test session also snapshot under
    # "serving[#N]" — find OUR tuner's slice by its decision log
    ours = [st for st in doc["tune"].values()
            if st["decisions"] == srv._tuner.decisions()]
    assert len(ours) == 1
    assert replay(ours[0]) == srv._tuner.decisions()
