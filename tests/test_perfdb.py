"""The persistent perf store (svc/perfdb) + offline ladder search.

Pins the contracts ISSUE 20 ships on: the versioned store refuses
corrupt/foreign schemas LOUDLY (naming both versions), concurrent
writers merge losslessly (union of observation logs, additive stats,
rev-winning ladders), compaction never double-counts through a stale
writer (folded-id tombstones), the offline derivation is a pure
function of the store (same DB -> byte-identical proposal), and the
serving boot consult is fail-safe: with ``hpx.perfdb.
use_learned_ladders=0`` or an empty store, a ContinuousServer is
byte-identical to the hand-picked defaults — same tokens, same
O(buckets) compile count."""

import json
import os

import jax
import pytest

from hpx_tpu.models import transformer as tfm
from hpx_tpu.models.serving import ContinuousServer
from hpx_tpu.core.config import runtime_config
from hpx_tpu.svc import perfdb as pdbm
from hpx_tpu.svc.perfdb import (
    PERFDB_SCHEMA,
    PerfDB,
    PerfDBSchemaError,
    PerfKey,
    shape_str,
)
from hpx_tpu.utils.compilemon import count_compiles

CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4, head_dim=8,
                            n_layers=2, d_ff=40)

KEY = "cpu|d32.h4.hd8.f40.l2.v64|-|dense|1"


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.PRNGKey(1))


@pytest.fixture
def rc_perfdb(tmp_path):
    """Point the configured store at a temp path; restore after."""
    rc = runtime_config()
    keys = ("hpx.perfdb.path", "hpx.perfdb.use_learned_ladders",
            "hpx.perfdb.allow_session", "hpx.perfdb.record")
    saved = {k: rc.get(k) for k in keys}
    path = str(tmp_path / "perfdb.json")
    rc.set("hpx.perfdb.path", path)
    pdbm.reset_configured()
    yield rc, path
    for k, v in saved.items():
        rc.set(k, v if v is not None else "")
    pdbm.reset_configured()


def _seed_costs(db, key=KEY, onchip=False):
    """A minimal derivable cost surface: >=3 compile samples, exec
    rows for two programs, and a chunk-demand histogram."""
    db.observe(key, "compile_s", 0.4, n=4, source="t",
               onchip=onchip)
    db.observe(key, "exec_p50_s", 0.002, n=50, program="cb_step",
               source="t", onchip=onchip)
    db.observe(key, "exec_p50_s", 0.003, n=10, program="cb_chunk",
               source="t", onchip=onchip)
    db.observe(key, "prefill_frac", 0.2, source="t", onchip=onchip)
    for rung, cnt in ((8, 1.0), (32, 4.0), (128, 6.0)):
        db.observe(key, "chunk_demand", cnt, program=f"r{rung}",
                   source="t", onchip=onchip)


# ---------------------------------------------------------------------------
# key grammar + store round trip
# ---------------------------------------------------------------------------

def test_perf_key_roundtrip():
    k = PerfKey("TPU v5e", shape_str(CFG), "int8", "fused", "dp2xtp4")
    assert PerfKey.parse(str(k)) == k
    assert shape_str(CFG) == "d32.h4.hd8.f40.l2.v64"
    # dense defaults: no kv dtype, dense kernel, single-device mesh
    assert str(PerfKey("cpu", shape_str(CFG))) == KEY


def test_store_roundtrip_and_model(tmp_path):
    p = str(tmp_path / "db.json")
    db = PerfDB(p)
    _seed_costs(db)
    db.save()
    back = PerfDB(p)
    m = back.model(KEY, "compile_s")
    assert m["n"] == 4 and m["mean"] == pytest.approx(0.4)
    pm = back.program_models(KEY, "exec_p50_s")
    assert set(pm) == {"cb_chunk", "cb_step"}
    assert pm["cb_step"]["n"] == 50
    assert back.metrics_for(KEY) == ["chunk_demand", "compile_s",
                                     "exec_p50_s", "prefill_frac"]


# ---------------------------------------------------------------------------
# merge-safety: concurrent writers, compaction tombstones
# ---------------------------------------------------------------------------

def test_concurrent_writers_merge_lossless(tmp_path):
    p = str(tmp_path / "db.json")
    a, b = PerfDB(p), PerfDB(p)
    a.observe(KEY, "compile_s", 0.5, n=2, source="writer_a")
    b.observe(KEY, "compile_s", 0.3, n=3, source="writer_b")
    b.observe(KEY, "warm_tok_s", 100.0, source="writer_b")
    a.save()
    b.save()         # merges a's rows from disk — nothing lost
    merged = PerfDB(p)
    assert merged.model(KEY, "compile_s")["n"] == 5
    assert merged.model(KEY, "warm_tok_s")["n"] == 1
    srcs = {r["source"] for r in merged.observations}
    assert srcs == {"writer_a", "writer_b"}


def test_ladder_rev_wins_merge(tmp_path):
    p = str(tmp_path / "db.json")
    a, b = PerfDB(p), PerfDB(p)
    a.record_ladder(KEY, {"prefill_buckets": [8, 128], "samples": 4})
    a.save()
    b.record_ladder(KEY, {"prefill_buckets": [32, 128], "samples": 9})
    b.record_ladder(KEY, {"prefill_buckets": [64, 128], "samples": 9})
    b.save()         # b's rev 2 beats a's rev 1
    assert PerfDB(p).ladder(KEY)["prefill_buckets"] == [64, 128]


def test_compaction_tombstones_survive_stale_writer(tmp_path):
    p = str(tmp_path / "db.json")
    db = PerfDB(p)
    for i in range(6):
        db.observe(KEY, "compile_s", 0.1 * (i + 1), source="t")
    db.save()
    stale = PerfDB(p)          # loaded BEFORE compaction
    folded = db.compact(keep=2)
    assert folded == 4
    db.save()
    stale.save()               # must not resurrect folded rows
    back = PerfDB(p)
    assert back.model(KEY, "compile_s")["n"] == 6   # not 10
    assert len(back.observations) == 2


# ---------------------------------------------------------------------------
# schema discipline: corrupt + foreign versions refuse loudly
# ---------------------------------------------------------------------------

def test_corrupt_store_refused_loudly(tmp_path):
    p = tmp_path / "db.json"
    p.write_text("{not json")
    with pytest.raises(PerfDBSchemaError, match="refusing"):
        PerfDB(str(p))


def test_old_schema_refused_naming_both_versions(tmp_path):
    p = tmp_path / "db.json"
    p.write_text(json.dumps({"schema": "hpx_tpu.perfdb.v0",
                             "observations": []}))
    with pytest.raises(PerfDBSchemaError) as ei:
        PerfDB(str(p))
    msg = str(ei.value)
    assert "hpx_tpu.perfdb.v0" in msg       # the version it found
    assert PERFDB_SCHEMA in msg             # the version it speaks


def test_save_never_clobbers_foreign_schema(tmp_path):
    p = tmp_path / "db.json"
    db = PerfDB(str(p))
    db.observe(KEY, "compile_s", 0.1, source="t")
    p.write_text(json.dumps({"schema": "hpx_tpu.perfdb.v99"}))
    with pytest.raises(PerfDBSchemaError):
        db.save()
    assert json.loads(p.read_text())["schema"] == "hpx_tpu.perfdb.v99"


# ---------------------------------------------------------------------------
# offline search: deterministic, provenance-gated
# ---------------------------------------------------------------------------

def test_ladder_derivation_is_byte_identical(tmp_path):
    from benchmarks import ladder_search
    p = str(tmp_path / "db.json")
    db = PerfDB(p)
    _seed_costs(db)
    db.save()
    props = [ladder_search.derive_ladder(PerfDB(p), KEY)
             for _ in range(2)]
    assert props[0] is not None
    blobs = {json.dumps(pr, sort_keys=True) for pr in props}
    assert len(blobs) == 1       # same DB -> byte-identical proposal
    # chunk rung always present; tunables ride the derived ladder
    lad = props[0]["prefill_buckets"]
    assert lad[-1] == 128
    assert props[0]["tunables"]["hpx.serving.prefill_chunk"]["lo"] \
        == lad[0]
    assert props[0]["provenance"] == "builder-session"


def test_session_only_ladder_refused_without_flag(tmp_path, capsys):
    import sys
    from benchmarks import ladder_search
    p = str(tmp_path / "db.json")
    db = PerfDB(p)
    _seed_costs(db, onchip=False)
    db.save()
    argv0 = sys.argv
    try:
        sys.argv = ["ladder_search", "--db", p]
        assert ladder_search.main() == 1          # nothing installed
        assert PerfDB(p).ladder(KEY) is None
        out = capsys.readouterr().out
        assert "builder-session-only" in out
        sys.argv = ["ladder_search", "--db", p, "--allow-session"]
        assert ladder_search.main() == 0
        assert PerfDB(p).ladder(KEY) is not None
    finally:
        sys.argv = argv0


def test_onchip_ladder_installs_without_flag(tmp_path):
    import sys
    from benchmarks import ladder_search
    p = str(tmp_path / "db.json")
    db = PerfDB(p)
    _seed_costs(db, onchip=True)
    db.save()
    argv0 = sys.argv
    try:
        sys.argv = ["ladder_search", "--db", p]
        assert ladder_search.main() == 0
        lad = PerfDB(p).ladder(KEY)
        assert lad["provenance"] == "on-chip" and lad["onchip"]
    finally:
        sys.argv = argv0


# ---------------------------------------------------------------------------
# serving boot consult: fail-safe byte-identity + learned override
# ---------------------------------------------------------------------------

def _run(srv, plens=(3, 9, 17, 23, 12), max_new=5):
    import numpy as np
    r = np.random.RandomState(7)
    for plen in plens:
        srv.submit([int(t) for t in r.randint(1, CFG.vocab, plen)],
                   max_new=max_new)
    out = srv.run()
    return [out[k] for k in sorted(out)]


def test_flag_off_and_empty_db_are_byte_identical(params, rc_perfdb):
    rc, path = rc_perfdb
    base_srv = ContinuousServer(params, CFG, slots=2, smax=64)
    base = _run(base_srv)
    # flag ON but the store is EMPTY: boot consult misses and falls
    # back to the hand-picked defaults — same ladder, same tokens,
    # same O(buckets) compile bound (the compile guard)
    rc.set("hpx.perfdb.use_learned_ladders", "1")
    with count_compiles() as c:
        srv = ContinuousServer(params, CFG, slots=2, smax=64)
        out = _run(srv)
    assert out == base
    assert srv.prefill_buckets == base_srv.prefill_buckets
    assert srv._ladder_source == "default"
    assert srv._prog_misses <= len(srv.prefill_buckets) + 3
    assert int(c) <= len(srv.prefill_buckets) + 22
    assert pdbm.perfdb_counts()["misses"] >= 1
    # flag OFF entirely: no store consult at all
    rc.set("hpx.perfdb.use_learned_ladders", "0")
    srv0 = ContinuousServer(params, CFG, slots=2, smax=64)
    assert _run(srv0) == base


def test_learned_ladder_overrides_and_output_identity(params,
                                                     rc_perfdb):
    rc, path = rc_perfdb
    base_srv = ContinuousServer(params, CFG, slots=2, smax=64)
    base = _run(base_srv)
    db = PerfDB(path)
    db.record_ladder(KEY, {
        "prefill_buckets": [16, 64], "prefill_chunk": 64,
        "samples": 8, "onchip": False,
        "provenance": "builder-session"})
    db.save()
    pdbm.reset_configured()
    rc.set("hpx.perfdb.use_learned_ladders", "1")
    # session-only ladder without allow_session: STALE, not applied
    srv_stale = ContinuousServer(params, CFG, slots=2, smax=64)
    assert srv_stale.prefill_buckets == base_srv.prefill_buckets
    assert srv_stale._ladder_source == "default"
    assert pdbm.perfdb_counts()["stale"] >= 1
    # allow_session: the learned geometry applies...
    rc.set("hpx.perfdb.allow_session", "1")
    srv = ContinuousServer(params, CFG, slots=2, smax=64)
    assert srv.prefill_buckets == (16, 64)
    assert srv.prefill_chunk == 64
    assert srv._ladder_source == "learned"
    assert pdbm.perfdb_counts()["hits"] >= 1
    # ...and the ladder is a PERFORMANCE knob: tokens match exactly
    assert _run(srv) == base
    # explicit constructor args always beat the store
    srv_exp = ContinuousServer(params, CFG, slots=2, smax=64,
                               prefill_chunk=32,
                               prefill_buckets="8,32")
    assert srv_exp.prefill_buckets == (8, 32)
    assert srv_exp._ladder_source == "default"


def test_perf_key_and_counters(params, rc_perfdb):
    rc, path = rc_perfdb
    srv = ContinuousServer(params, CFG, slots=2, smax=64)
    assert srv.perf_key() == KEY
    c = pdbm.perfdb_counts()
    assert set(c) == {"keys", "observations", "hits", "misses",
                      "stale"}
