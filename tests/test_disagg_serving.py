"""Disaggregated prefill/decode serving (models/disagg.py +
cache/transfer.py): the router's tokens must be BYTE-IDENTICAL to
single-server ``tfm.generate`` references in every topology state —
fault-free, after a decode-worker death (replay from transferred KV on
a survivor), after a prefill-worker death (suffix-only restart from
retained segments), and fully degraded to colocated — with zero KV
blocks leaked by any path, including close() with work in flight.

The transfer protocol itself (framing, checksums, idempotent
re-delivery) is tested at the KVSegment/TransferReceiver level, and
the dist-layer robustness additions (Runtime.finalize failing pending
parcels typed, resilient_action retry/timeout) ride along here.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hpx_tpu.core.errors import LocalityLost, NetworkError
from hpx_tpu.models import transformer as tfm
from hpx_tpu.models.disagg import (DecodeWorker, DisaggRouter,
                                   InProcHandle, PrefillWorker)
from hpx_tpu.models.serving import RequestShedError, ServerClosedError
from hpx_tpu.svc import faultinject

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4, head_dim=8,
                            n_layers=2, d_ff=64)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.PRNGKey(0))


def _ref(params, prompt, max_new, temperature=0.0, key=None,
         eos_id=None):
    out = tfm.generate(params, CFG, jnp.asarray([prompt], jnp.int32),
                       max_new=max_new, temperature=temperature,
                       key=key, eos_id=eos_id)
    return [int(t) for t in np.asarray(out)[0]]


def _mix(n=5, seed=7):
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        prompt = [int(t) for t in
                  rng.integers(1, 64, int(rng.integers(3, 24)))]
        temp = 0.8 if i % 2 else 0.0
        key = jax.random.PRNGKey(100 + i) if temp else None
        reqs.append((prompt, 6 + i, temp, key))
    return reqs


def _run_router(params, reqs, schedule=None, **router_kw):
    inj = None
    if schedule is not None:
        inj = faultinject.install(
            faultinject.FaultInjector(schedule=schedule))
    try:
        r = DisaggRouter(params, CFG, prefill_workers=2,
                         decode_workers=2, slots=3, smax=64,
                         **router_kw)
        for (p, mn, t, k) in reqs:
            r.submit(p, mn, temperature=t, key=k)
        out = r.run()
        stats = r.stats()
        r.close()
        leak = r.leaked_blocks()
    finally:
        if inj is not None:
            faultinject.uninstall()
    return out, stats, leak


# ---------------------------------------------------------------------------
# fault-free: disagg == generate, greedy and sampled
# ---------------------------------------------------------------------------

def test_disagg_matches_generate(params):
    reqs = _mix()
    out, stats, leak = _run_router(params, reqs)
    for rid, (p, mn, t, k) in enumerate(reqs):
        assert out[rid] == _ref(params, p, mn, temperature=t, key=k)
    assert stats["failovers"] == {"prefill": 0, "decode": 0}
    assert leak == 0


def test_disagg_single_workers_and_eos(params):
    # 1 prefill + 1 decode worker; eos early-exit must survive the
    # admit_prefilled path (seed token counts toward eos)
    r = DisaggRouter(params, CFG, prefill_workers=1, decode_workers=1,
                     slots=2, smax=64)
    prompt = [5, 9, 13, 21, 2]
    want = _ref(params, prompt, 12, eos_id=3)
    rid = r.submit(prompt, 12, eos_id=3)
    out = r.run()
    assert out[rid] == want
    r.close()
    assert r.leaked_blocks() == 0


# ---------------------------------------------------------------------------
# failover: one seeded kill per role -> identical tokens, no leak
# ---------------------------------------------------------------------------

def test_decode_worker_death_replays_identically(params):
    reqs = _mix()
    base, _, _ = _run_router(params, reqs)
    out, stats, leak = _run_router(
        params, reqs, schedule={"disagg.decode": {5}})
    assert out == base
    assert stats["failovers"]["decode"] >= 1
    assert not stats["degraded"]
    assert leak == 0


def test_prefill_worker_death_restarts_suffix_only(params):
    reqs = _mix()
    base, _, _ = _run_router(params, reqs)
    out, stats, leak = _run_router(
        params, reqs, schedule={"disagg.prefill": {7}})
    assert out == base
    assert stats["failovers"]["prefill"] >= 1
    assert not stats["degraded"]
    assert leak == 0


def test_both_roles_die_same_run(params):
    reqs = _mix()
    base, _, _ = _run_router(params, reqs)
    out, stats, leak = _run_router(
        params, reqs,
        schedule={"disagg.prefill": {3}, "disagg.decode": {9}})
    assert out == base
    assert stats["failovers"]["prefill"] >= 1
    assert stats["failovers"]["decode"] >= 1
    assert leak == 0


def test_total_role_loss_degrades_to_colocated(params):
    reqs = _mix()
    base, _, _ = _run_router(params, reqs)
    for schedule in ({"disagg.prefill": {2, 5}},
                     {"disagg.decode": {1, 3}}):
        out, stats, leak = _run_router(params, reqs,
                                       schedule=schedule)
        assert out == base, schedule
        assert stats["degraded"]
        assert leak == 0


# ---------------------------------------------------------------------------
# admission: SLO classes, bounded queue, typed shedding
# ---------------------------------------------------------------------------

def test_batch_sheds_before_interactive(params, monkeypatch):
    from hpx_tpu.core.config import runtime_config
    monkeypatch.setitem(runtime_config()._data,
                        "hpx.serving.disagg.max_queue", "2")
    r = DisaggRouter(params, CFG, prefill_workers=1, decode_workers=1,
                     slots=2, smax=64)
    r0 = r.submit([1, 2, 3], 4, slo="interactive")
    rb = r.submit([4, 5, 6], 4, slo="batch")
    # queue full: the BATCH request sheds to admit interactive work
    r2 = r.submit([7, 8, 9], 4, slo="interactive")
    assert isinstance(r.failed[rb], RequestShedError)
    # full of interactive work: the incoming interactive sheds itself
    r3 = r.submit([2, 4, 6], 4, slo="interactive")
    assert isinstance(r.failed[r3], RequestShedError)
    out = r.run()
    assert set(out) == {r0, r2}
    for rid, prompt in ((r0, [1, 2, 3]), (r2, [7, 8, 9])):
        assert out[rid] == _ref(params, prompt, 4)
    r.close()
    assert r.leaked_blocks() == 0


def test_submit_after_close_raises_typed(params):
    r = DisaggRouter(params, CFG, prefill_workers=1, decode_workers=1,
                     slots=2, smax=64)
    r.submit([1, 2, 3], 3)
    r.close()               # drains the in-flight request first
    with pytest.raises(ServerClosedError):
        r.submit([4, 5, 6], 3)
    assert r.leaked_blocks() == 0


def test_close_without_drain_sheds_typed_and_releases(params):
    r = DisaggRouter(params, CFG, prefill_workers=1, decode_workers=1,
                     slots=2, smax=64)
    rids = [r.submit([i + 1, i + 2, i + 3], 8) for i in range(4)]
    r.step()                # some prefills/transfers now in flight
    r.close(drain=False)
    for rid in rids:
        assert rid in r.results or isinstance(r.failed.get(rid),
                                              RequestShedError)
    assert r.leaked_blocks() == 0
    with pytest.raises(ServerClosedError):
        r.submit([9], 2)


def test_bad_slo_rejected(params):
    r = DisaggRouter(params, CFG, prefill_workers=1, decode_workers=1,
                     slots=2, smax=64)
    with pytest.raises(ValueError):
        r.submit([1, 2], 4, slo="best-effort")
    r.close()


# ---------------------------------------------------------------------------
# the transfer protocol: framing, checksums, idempotent re-delivery
# ---------------------------------------------------------------------------

def test_segment_checksum_and_idempotent_redelivery():
    from hpx_tpu.cache.transfer import (TransferCorruptError,
                                        TransferReceiver, make_segment)
    rows = np.arange(2 * 2 * 8 * 2 * 4, dtype=np.float32).reshape(
        2, 2, 8, 2, 4)
    recv = TransferReceiver()
    a = make_segment("r1", 0, 0, 12, rows)
    b = make_segment("r1", 1, 8, 12, rows[:, :, :4])
    assert recv.ingest(a)["dup"] is False
    # duplicate delivery (lost ACK): re-acked, not re-applied
    assert recv.ingest(a)["dup"] is True
    assert recv.stats()["dups"] == 1
    assert not recv.complete("r1")
    assert recv.ingest(b)["dup"] is False
    assert recv.complete("r1")
    got = recv.assemble("r1")
    assert got.shape == (2, 2, 12, 2, 4)
    np.testing.assert_array_equal(got[:, :, :8], rows)
    # corruption: a tampered payload fails verification loudly
    import dataclasses
    bad = dataclasses.replace(a, payload=rows + 1.0)
    with pytest.raises(TransferCorruptError):
        recv.ingest(bad)
    assert recv.stats()["corrupt"] == 1


def test_receiver_abort_drops_segments():
    from hpx_tpu.cache.transfer import TransferReceiver, make_segment
    rows = np.zeros((1, 2, 4, 2, 4), np.float32)
    recv = TransferReceiver()
    recv.ingest(make_segment("r9", 0, 0, 8, rows))
    recv.abort("r9")
    assert recv.pending() == []
    # late duplicate for an aborted rid: acked and dropped
    assert recv.ingest(make_segment("r9", 0, 0, 8, rows))["dup"] is True


def test_wire_faults_between_router_and_decode(params):
    # parcel.drop/dup-shaped trouble on the segment path: drops raise
    # through the resilient send (router re-ships), dups dedup — the
    # decode output stays byte-identical either way
    reqs = _mix(3)
    base, _, _ = _run_router(params, reqs)

    class FlakyHandle(InProcHandle):
        """Delivers every segment twice (duplicate ACK lost on the
        'wire'), and drops the first delivery of segment seq 1."""

        def __init__(self, worker):
            super().__init__("decode", worker)
            self.dropped = False

        def call(self, method, *args, **kwargs):
            if method == "ingest":
                seg = args[0]
                if seg.seq == 1 and not self.dropped:
                    self.dropped = True
                    raise LocalityLost(
                        0, "injected parcel drop", "FlakyHandle")
                out = super().call(method, *args, **kwargs)
                super().call(method, *args, **kwargs)   # duplicate
                return out
            return super().call(method, *args, **kwargs)

    # a dropped segment surfaces as a connectivity error -> the router
    # fails the handle over; with a second (clean) worker the run
    # completes identically
    flaky = FlakyHandle(DecodeWorker(params, CFG, slots=3, smax=64))
    clean = InProcHandle("decode",
                         DecodeWorker(params, CFG, slots=3, smax=64))
    bs = clean.call("block_size")
    r = DisaggRouter(
        params, CFG, prefill_workers=1, slots=3, smax=64,
        decode_handles=[flaky, clean])
    for (p, mn, t, k) in reqs:
        r.submit(p, mn, temperature=t, key=k)
    out = r.run()
    assert out == base
    assert r.stats()["failovers"]["decode"] >= 1
    r.close()
    assert r.leaked_blocks() == 0
    # the double-deliveries before the drop hit the flaky worker's
    # receiver and were deduplicated there, not re-applied
    assert flaky.worker.recv.stats()["dups"] >= 1


def test_prefill_segments_block_aligned(params):
    w = PrefillWorker(params, CFG, smax=64, block_size=4)
    prompt = list(range(1, 12))          # plen 11: cap = 8, final 8..11
    w.start("j", prompt)
    segs, seed = [], None
    while True:
        out = w.step("j")
        segs.extend(out["segments"])
        if out["done"]:
            seed = out["seed"]
            break
    assert [(s.start, s.ntok) for s in segs] == [(0, 4), (4, 4), (8, 3)]
    assert all(s.total == 11 for s in segs)
    assert [s.seq for s in segs] == [0, 1, 2]
    assert seed == _ref(params, prompt, 1)[0]
    for s in segs:
        s.verify()


# ---------------------------------------------------------------------------
# fault-site plumbing: deterministic streams for the chaos harness
# ---------------------------------------------------------------------------

def test_disagg_fault_sites_registered_and_deterministic():
    assert "disagg.prefill" in faultinject.SITES
    assert "disagg.decode" in faultinject.SITES
    for site in ("parcel.drop", "parcel.dup", "parcel.delay",
                 "net.partition"):
        assert site in faultinject.SITES

    def draws(seed):
        fi = faultinject.FaultInjector(seed=seed, rate=0.3,
                                       sites=["parcel.drop"])
        return [fi.fires("parcel.drop") for _ in range(40)]

    assert draws(1) == draws(1)          # same seed -> same stream
    assert draws(1) != draws(2)
    # injected losses are the REAL typed error (failover code paths
    # cannot tell injected from organic)
    fi = faultinject.install(faultinject.FaultInjector(
        schedule={"disagg.decode": {1}}))
    try:
        with pytest.raises(LocalityLost) as ei:
            faultinject.check("disagg.decode", locality=4)
        assert isinstance(ei.value, NetworkError)
        assert ei.value.locality == 4
    finally:
        faultinject.uninstall()


# ---------------------------------------------------------------------------
# dist-layer rides-along: finalize fails pending parcels typed
# ---------------------------------------------------------------------------

def test_finalize_fails_pending_parcels_typed():
    from hpx_tpu.dist.runtime import Runtime
    from hpx_tpu.futures.future import SharedState
    rt = Runtime.__new__(Runtime)      # no bootstrap: single-process
    import threading
    rt.locality = 0
    rt.num_localities = 1              # skips the barrier/drain path
    rt._stopped = False
    rt._hb_thread = None
    rt._hb_stop = threading.Event()
    rt._coalescer = None
    rt._endpoint = None
    rt._pending_lock = threading.Lock()
    st = SharedState()
    rt._pending = {7: st}
    rt._pending_dst = {7: 3}
    rt.finalize()
    with pytest.raises(LocalityLost) as ei:
        from hpx_tpu.futures.future import Future
        Future(st).get(timeout=1.0)
    assert ei.value.locality == 3


# ---------------------------------------------------------------------------
# export_prefix_rows / fetch_prefix round-trip on quantized pools
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kvd", ["fp8", "int8"])
def test_export_fetch_prefix_roundtrip_quantized(params, kvd):
    """A quantized pool's exported prefix rows must (a) be exactly the
    dequantized pool bytes (scale-sidecar path) and (b) survive the
    full wire round-trip: fetch_prefix → KVSegment framing → ingest →
    admit_prefilled, decoding the SAME tokens the publisher emitted."""
    from hpx_tpu.cache.transfer import make_segment
    from hpx_tpu.models.serving import ContinuousServer

    rng = np.random.default_rng(11)
    prompt = [int(t) for t in rng.integers(1, 64, 32)]

    src = DecodeWorker(params, CFG, slots=2, smax=64, kv_dtype=kvd,
                       block_size=8)
    srv = src.srv
    rid = srv.submit(prompt, max_new=6)
    base = srv.run()[rid]

    got = src.fetch_prefix(prompt)
    matched, rows = got["matched"], got["rows"]
    assert matched == len(prompt)
    assert rows.shape == (CFG.n_layers, 2, matched, CFG.kv_heads,
                          CFG.head_dim)

    # (a) rows == dequantized pool contents, bit-exact — same
    # elementwise ops the fused kernels apply
    assert srv._scales is not None           # fp8/int8 carry sidecars
    m2, bids = srv._radix.match(prompt)
    assert m2 == matched
    try:
        for li in range(CFG.n_layers):
            kp, vp = srv._pools[li]
            for side, pool in enumerate((kp, vp)):
                g = np.asarray(pool)[np.asarray(bids)]
                sc = np.asarray(srv._scales[li][side])[
                    np.asarray(bids)]
                ref = (g.astype(np.float32)
                       * sc[:, None, :, None]).reshape(
                           matched, CFG.kv_heads, CFG.head_dim)
                np.testing.assert_array_equal(
                    rows[li, side], ref.astype(rows.dtype))
    finally:
        for b in bids:
            srv._alloc.decref(b)

    # (b) ship through the segment framing into a fresh worker with
    # the same pool dtype: identical tokens out
    dst = DecodeWorker(params, CFG, slots=2, smax=64, kv_dtype=kvd,
                       block_size=8)
    dst.ingest(make_segment("rt:0", 0, 0, matched, rows))
    dst.admit("rt:0", prompt, base[0], 6)
    done = {}
    for _ in range(200):
        res = dst.pump(4)
        done.update(res["done"])
        if not res["busy"] and not res["live"]:
            break
    assert done["rt:0"] == base
    assert src.leaked_blocks() == 0
    assert dst.leaked_blocks() == 0


# ---------------------------------------------------------------------------
# multi-process: real localities, real deaths (slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_disagg_multiprocess_kill_one_worker_per_role():
    from hpx_tpu.run import launch
    rc = launch(os.path.join(REPO, "tests", "mp_scripts",
                             "disagg_smoke.py"),
                [], localities=5, timeout=540.0)
    assert rc == 0
