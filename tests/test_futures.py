"""Futures/promise/continuation tests.

Reference analog: libs/core/futures/tests/unit (future.cpp, shared_future.cpp,
future_then.cpp) — semantics: continuations, unwrapping, exception
propagation, promise protocol errors.
"""

import threading
import time

import pytest

import hpx_tpu as hpx
from hpx_tpu.core.errors import FutureError


def test_make_ready_future():
    f = hpx.make_ready_future(42)
    assert f.is_ready() and f.has_value()
    assert f.get() == 42
    assert f.get() == 42  # shared_future semantics: repeatable get


def test_promise_future_roundtrip():
    p = hpx.Promise()
    f = p.get_future()
    assert not f.is_ready()
    p.set_value("hi")
    assert f.is_ready()
    assert f.get() == "hi"


def test_promise_future_retrieved_once():
    p = hpx.Promise()
    p.get_future()
    with pytest.raises(FutureError):
        p.get_future()


def test_promise_already_satisfied():
    p = hpx.Promise()
    p.set_value(1)
    with pytest.raises(FutureError):
        p.set_value(2)


def test_exception_propagation():
    f = hpx.make_exceptional_future(ValueError("boom"))
    assert f.has_exception()
    with pytest.raises(ValueError, match="boom"):
        f.get()


def test_then_continuation_ready():
    f = hpx.make_ready_future(3)
    g = f.then(lambda fut: fut.get() * 2)
    assert g.get() == 6


def test_then_continuation_pending():
    p = hpx.Promise()
    g = p.get_future().then(lambda fut: fut.get() + 1)
    assert not g.is_ready()
    p.set_value(9)
    assert g.get() == 10


def test_then_chains_and_exceptions():
    p = hpx.Promise()
    g = (p.get_future()
         .then(lambda f: f.get() * 2)
         .then(lambda f: 1 / f.get()))
    p.set_value(0)
    with pytest.raises(ZeroDivisionError):
        g.get()


def test_future_unwrapping_in_set_value():
    # future<future<int>> collapses (HPX unwrapping semantics)
    p = hpx.Promise()
    inner = hpx.make_ready_future(7)
    p.set_value(inner)
    assert p.get_future().get() == 7


def test_then_returning_future_unwraps():
    f = hpx.make_ready_future(1)
    g = f.then(lambda fut: hpx.make_ready_future(fut.get() + 10))
    assert g.get() == 11


def test_packaged_task():
    pt = hpx.PackagedTask(lambda a, b: a + b)
    f = pt.get_future()
    pt(2, 3)
    assert f.get() == 5


def test_wait_timeout():
    p = hpx.Promise()
    f = p.get_future()
    assert f.wait(timeout=0.01) is False
    with pytest.raises(FutureError):
        f.get(timeout=0.01)


def test_concurrent_set_and_wait():
    # regression-style: waiter races the setter (HPX future races class)
    for _ in range(50):
        p = hpx.Promise()
        f = p.get_future()
        t = threading.Thread(target=lambda: p.set_value(123))
        t.start()
        assert f.get(timeout=5.0) == 123
        t.join()


def test_async_basic():
    f = hpx.async_(lambda x: x * x, 12)
    assert f.get(timeout=5.0) == 144


def test_async_exception():
    def bad():
        raise RuntimeError("task failed")
    with pytest.raises(RuntimeError, match="task failed"):
        hpx.async_(bad).get(timeout=5.0)


def test_async_unwraps_returned_future():
    f = hpx.async_(lambda: hpx.async_(lambda: 5))
    assert f.get(timeout=5.0) == 5


def test_launch_sync():
    order = []
    f = hpx.async_(lambda: order.append("ran"), policy=hpx.Launch.sync)
    assert order == ["ran"] and f.is_ready()


def test_launch_deferred():
    ran = []
    f = hpx.async_(lambda: ran.append(1) or 99, policy=hpx.Launch.deferred)
    assert ran == []           # not started
    assert f.get() == 99
    assert ran == [1]


def test_post_fire_and_forget():
    done = threading.Event()
    hpx.post(done.set)
    assert done.wait(5.0)


def test_sync_helper():
    assert hpx.sync(lambda: 3) == 3
    assert hpx.sync(lambda: hpx.make_ready_future(4)) == 4


def test_deferred_consumed_via_then_runs():
    # regression: deferred future consumed through the callback interface
    # (then/dataflow/when_all) must start its thunk
    f = hpx.async_(lambda: 5, policy=hpx.Launch.deferred)
    assert f.then(lambda fut: fut.get() + 1).get(timeout=5.0) == 6
    g = hpx.async_(lambda: 7, policy=hpx.Launch.deferred)
    assert hpx.when_all(g).get(timeout=5.0)[0].get() == 7


def test_raising_user_callback_does_not_poison_producer():
    # regression: a raising user callback must not escape into set_value
    # nor starve later continuations
    p = hpx.Promise()
    f = p.get_future()
    hpx.when_each(lambda fut: 1 / 0, f)      # user callback that raises
    g = f.then(lambda fut: fut.get() * 2)
    p.set_value(21)                           # must not raise
    assert g.get(timeout=5.0) == 42


class TestManyFanout:
    """post_many/async_many — the batched spawn path (one submit_many
    pool crossing; on the native pool one C-ABI call)."""

    def test_post_many_runs_all(self):
        import threading
        import hpx_tpu as hpx
        n = 2000
        latch = hpx.Latch(n + 1)
        seen = []
        lock = threading.Lock()

        def hit(i):
            with lock:
                seen.append(i)
            latch.count_down(1)

        hpx.post_many(hit, [(i,) for i in range(n)])
        latch.arrive_and_wait()
        assert sorted(seen) == list(range(n))

    def test_async_many_results_in_order(self):
        import hpx_tpu as hpx
        futs = hpx.async_many(lambda i: i * i, [(i,) for i in range(500)])
        assert [f.get() for f in futs] == [i * i for i in range(500)]

    def test_async_many_exception_isolated(self):
        import hpx_tpu as hpx

        def maybe(i):
            if i == 3:
                raise ValueError("boom")
            return i

        futs = hpx.async_many(maybe, [(i,) for i in range(6)])
        for i, f in enumerate(futs):
            if i == 3:
                try:
                    f.get()
                    raise AssertionError("expected ValueError")
                except ValueError:
                    pass
            else:
                assert f.get() == i

    def test_post_many_with_executor_object(self):
        import threading
        import hpx_tpu as hpx
        from hpx_tpu.exec.executors import ParallelExecutor
        n = 100
        latch = hpx.Latch(n + 1)
        hpx.post_many(lambda: latch.count_down(1), [()] * n,
                      executor=ParallelExecutor())
        latch.arrive_and_wait()

    def test_async_many_accepts_generator(self):
        import hpx_tpu as hpx
        futs = hpx.async_many(lambda i: i + 1, ((i,) for i in range(50)))
        assert [f.get(timeout=30) for f in futs] == list(range(1, 51))

    def test_post_many_accepts_generator(self):
        import hpx_tpu as hpx
        latch = hpx.Latch(21)
        hpx.post_many(lambda: latch.count_down(1),
                      (() for _ in range(20)))
        latch.arrive_and_wait()

    def test_mass_blocking_fanout_no_stack_overflow(self):
        """2000 tasks that BLOCK on externally-completed futures: the
        work-helping chain must stay depth-bounded (HELP_DEPTH_CAP)
        instead of recursing one Python/C call chain per nested help
        until stack overflow (regression: RecursionError at ~100)."""
        import threading
        import hpx_tpu as hpx
        from hpx_tpu.futures.future import Future, SharedState
        n = 2000
        states = [SharedState() for _ in range(n)]

        def completer():
            import time
            time.sleep(0.3)           # let the helpers dive first
            for st in states:
                st.set_value(1)

        threading.Thread(target=completer, daemon=True).start()
        futs = hpx.async_many(
            lambda i: Future(states[i]).get(timeout=60),
            [(i,) for i in range(n)])
        assert sum(f.get(timeout=120) for f in futs) == n
