"""Regression tests — one per fixed bug, reference-style (SURVEY.md §4:
'regression tests pin past bugs, esp. scheduler/future races; each is a
minimal repro').

Round-1 bugs, each with the commit theme that fixed it.
"""

import io
import threading
import time

import jax.numpy as jnp
import pytest

import hpx_tpu as hpx
from hpx_tpu.testing import HPX_TEST, HPX_TEST_EQ


def test_uptime_counter_never_zero_on_first_query():
    """uptime registered lazily at first (possibly remote) query used to
    read 0.0 when register+read landed in the same clock quantum."""
    from hpx_tpu.svc.performance_counters import ElapsedTimeCounter
    c = ElapsedTimeCounter()
    HPX_TEST(c.get_value().value > 0)


def test_replay_executor_does_not_compile_its_loop():
    """ReplayExecutor over TpuExecutor used to pass the replay LOOP into
    jax.jit (callables as traced args -> TypeError on every call)."""
    ex = hpx.ReplayExecutor(2, executor=hpx.TpuExecutor())
    HPX_TEST_EQ(float(ex.async_execute(lambda x: x * 3,
                                       jnp.float32(14)).get()), 42.0)


def test_hpx_error_pickle_roundtrip():
    """HpxError used default exception pickling: re-calling __init__
    with the formatted string as the `code` arg -> ValueError on the
    receiving locality."""
    import pickle
    from hpx_tpu.svc.resiliency import ReplayValidationError
    for e in [hpx.HpxError(hpx.Error.deadlock, "msg"),
              ReplayValidationError(3)]:
        e2 = pickle.loads(pickle.dumps(e))
        assert type(e2) is type(e) and e2.code == e.code
        assert str(e2) == str(e)
    # subclass attrs survive (used to be dropped by a narrow __reduce__)
    assert pickle.loads(pickle.dumps(ReplayValidationError(5))).attempts == 5


def test_freed_component_errors_not_loops():
    """Invoking a freed component used to forward-chase forever when a
    stale forward pointed back at a locality that also had a forward;
    the hop TTL plus forward retraction must produce a clean error."""
    @hpx.register_component_type
    class Tiny(hpx.Component):
        def ping(self):
            return "pong"

    c = hpx.new_sync(Tiny)
    c.free().get()
    t0 = time.monotonic()
    with pytest.raises(hpx.HpxError):
        c.sync("ping")
    assert time.monotonic() - t0 < 10.0    # error, not a chase loop


def test_unregistered_subclass_does_not_instantiate_base():
    """new_(DerivedUnregistered) used to inherit the base's
    _component_type_name and silently create the BASE class."""
    @hpx.register_component_type
    class Base(hpx.Component):
        pass

    class Derived(Base):
        pass

    with pytest.raises(hpx.HpxError):
        hpx.new_(Derived)


def test_migrate_failure_fails_the_future():
    """migrate() used to drop the migration error and hand back a
    Client as if it succeeded."""
    @hpx.register_component_type
    class M(hpx.Component):
        pass

    c = hpx.new_sync(M)
    with pytest.raises(hpx.HpxError):
        hpx.migrate(c, 99)     # no such locality
    c.free().get()


def test_iostreams_flush_waits_for_newline_writes():
    """Newline-triggered flushes used to drop their futures, so an
    explicit flush().get() returned without waiting for them."""
    from hpx_tpu.svc.iostreams import _DistStream
    s = _DistStream("cout")
    s.println("line")          # auto-flush path (console: sync write)
    HPX_TEST(s.flush().get(timeout=10.0) is True)


def test_checkpoint_truncated_header_raises():
    """A stream cut right after the magic used to yield an empty
    Checkpoint instead of an error."""
    cp = hpx.save_checkpoint("payload").get()
    buf = io.BytesIO()
    cp.write(buf)
    with pytest.raises(ValueError):
        hpx.Checkpoint.read(io.BytesIO(buf.getvalue()[:12]))


def test_empty_when_all_sender_completes():
    """sync_wait(when_all()) used to block forever."""
    from hpx_tpu.exec import p2300 as ex
    assert ex.sync_wait(ex.when_all(), timeout=5.0) is None


def test_native_pool_shutdown_from_worker_does_not_abort():
    """shutdown() from a pool's own worker used to pthread_join(self)
    and abort the process."""
    from hpx_tpu.native.loader import NativePool, native_lib
    if native_lib() is None:
        pytest.skip("native lib unavailable")
    p = NativePool(1)
    done = threading.Event()

    def self_shutdown():
        p.shutdown()           # runs ON the worker
        done.set()

    p.submit(self_shutdown)
    assert done.wait(10.0)
    for _ in range(200):       # reaper finishes asynchronously
        if p._shut:
            break
        time.sleep(0.01)
    assert p._shut


def test_batch_env_without_rank_stays_single_locality():
    """SLURM_NTASKS without SLURM_PROCID (bare salloc shell) used to
    configure 4 localities and hang bootstrap."""
    cfg = hpx.Configuration(environ={"SLURM_JOB_ID": "1",
                                     "SLURM_NTASKS": "4"})
    HPX_TEST_EQ(cfg.get_int("hpx.localities"), 1)


def test_ignore_batch_env_flag():
    cfg = hpx.Configuration(
        argv=["--hpx:ignore-batch-env"],
        environ={"SLURM_JOB_ID": "1", "SLURM_NTASKS": "4",
                 "SLURM_PROCID": "2"})
    HPX_TEST_EQ(cfg.get_int("hpx.localities"), 1)
    HPX_TEST_EQ(cfg.get_int("hpx.locality"), 0)
