"""task_group, P2300 senders/receivers, and spmd_block tests.

Reference analogs: libs/core/task_group tests, the P2300 pieces of
libs/core/execution tests (then/when_all/bulk/sync_wait/run_loop), and
the quickstart spmd_block demos (SURVEY.md §2.2, §2.9).
"""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

import hpx_tpu as hpx
from hpx_tpu.exec import p2300 as ex
from hpx_tpu.testing import HPX_TEST, HPX_TEST_EQ


# -- task_group -------------------------------------------------------------

class TestTaskGroup:
    def test_basic(self):
        out = []
        with hpx.task_group() as tg:
            for i in range(10):
                tg.run(out.append, i)
        HPX_TEST_EQ(sorted(out), list(range(10)))

    def test_explicit_wait_and_reuse(self):
        tg = hpx.TaskGroup()
        acc = []
        tg.run(acc.append, 1)
        tg.wait()
        HPX_TEST_EQ(acc, [1])
        tg.run(acc.append, 2)      # reusable after wait (reference)
        tg.wait()
        HPX_TEST_EQ(acc, [1, 2])

    def test_child_exception_rethrown(self):
        def boom():
            raise ValueError("child failed")
        done = threading.Event()
        with pytest.raises(ValueError):
            with hpx.task_group() as tg:
                tg.run(boom)
                tg.run(done.set)
        HPX_TEST(done.is_set())    # all children ran to completion

    def test_children_spawn_children(self):
        out = []
        tg = hpx.TaskGroup()

        def parent():
            out.append("p")
            tg.run(out.append, "c")

        tg.run(parent)
        tg.wait()
        HPX_TEST_EQ(sorted(out), ["c", "p"])

    def test_on_executor(self):
        tg = hpx.task_group(hpx.SequencedExecutor())
        out = []
        tg.run(out.append, 1)
        tg.run(out.append, 2)
        tg.wait()
        HPX_TEST_EQ(out, [1, 2])


# -- P2300 ------------------------------------------------------------------

class TestSenders:
    def test_just_then_sync_wait(self):
        s = ex.just(20) | ex.then(lambda v: v * 2) | ex.then(lambda v: v + 2)
        HPX_TEST_EQ(ex.sync_wait(s), 42)

    def test_just_multiple_values(self):
        s = ex.just(3, 4) | ex.then(lambda a, b: a * b)
        HPX_TEST_EQ(ex.sync_wait(s), 12)

    def test_schedule_thread_pool(self):
        ran_on = []
        s = (ex.schedule(ex.thread_pool_scheduler())
             | ex.then(lambda: ran_on.append(threading.get_ident()) or 7))
        HPX_TEST_EQ(ex.sync_wait(s), 7)
        HPX_TEST(ran_on and ran_on[0] != threading.get_ident())

    def test_error_channel_and_recovery(self):
        def boom():
            raise RuntimeError("nope")
        s = ex.just() | ex.then(boom)
        with pytest.raises(RuntimeError):
            ex.sync_wait(s)
        s2 = (ex.just() | ex.then(boom)
              | ex.upon_error(lambda e: f"recovered:{e}"))
        HPX_TEST(str(ex.sync_wait(s2)).startswith("recovered"))

    def test_just_error(self):
        with pytest.raises(KeyError):
            ex.sync_wait(ex.just_error(KeyError("k")))

    def test_stopped(self):
        HPX_TEST(ex.sync_wait(ex.just_stopped()) is None)

    def test_let_value(self):
        s = ex.just(5) | ex.let_value(lambda v: ex.just(v + 1))
        HPX_TEST_EQ(ex.sync_wait(s), 6)

    def test_when_all(self):
        s = ex.when_all(ex.just(1), ex.just(2) | ex.then(lambda v: v * 10),
                        ex.just(3))
        HPX_TEST_EQ(ex.sync_wait(s), (1, 20, 3))

    def test_when_all_error_wins(self):
        s = ex.when_all(ex.just(1), ex.just_error(ValueError("x")))
        with pytest.raises(ValueError):
            ex.sync_wait(s)

    def test_bulk(self):
        hits = []
        s = ex.just(10) | ex.bulk(4, lambda i, v: hits.append(i * v))
        HPX_TEST_EQ(ex.sync_wait(s), 10)    # bulk forwards the value
        HPX_TEST_EQ(sorted(hits), [0, 10, 20, 30])

    def test_continues_on(self):
        tids = []
        s = (ex.just(1)
             | ex.then(lambda v: (tids.append(threading.get_ident()), v)[1])
             | ex.continues_on(ex.thread_pool_scheduler())
             | ex.then(lambda v: (tids.append(threading.get_ident()),
                                  v + 1)[1]))
        HPX_TEST_EQ(ex.sync_wait(s), 2)
        HPX_TEST_EQ(len(tids), 2)

    def test_as_future_bridge(self):
        f = ex.as_future(ex.just(5) | ex.then(lambda v: v * 3))
        HPX_TEST(hpx.is_future(f))
        HPX_TEST_EQ(f.get(), 15)

    def test_start_detached(self):
        done = threading.Event()
        ex.start_detached(ex.schedule(ex.thread_pool_scheduler())
                          | ex.then(done.set))
        HPX_TEST(done.wait(5.0))

    def test_run_loop(self):
        loop = ex.run_loop()
        out = []
        ex.start_detached(ex.schedule(loop.get_scheduler())
                          | ex.then(lambda: out.append(1)))
        ex.start_detached(ex.schedule(loop.get_scheduler())
                          | ex.then(lambda: out.append(2)))
        loop.finish()
        loop.run()
        HPX_TEST_EQ(out, [1, 2])

    def test_then_on_device(self):
        s = (ex.just(jnp.arange(8, dtype=jnp.float32))
             | ex.then_on_device(lambda x: x * 2.0)
             | ex.then(lambda x: float(x.sum())))
        HPX_TEST_EQ(ex.sync_wait(s), 2.0 * sum(range(8)))

    def test_tpu_scheduler_pipeline(self):
        s = (ex.schedule(ex.tpu_scheduler())
             | ex.then(lambda: jnp.ones((4, 4), jnp.float32))
             | ex.then_on_device(lambda m: m @ m)
             | ex.then(lambda m: float(m[0, 0])))
        HPX_TEST_EQ(ex.sync_wait(s), 4.0)


# -- spmd_block -------------------------------------------------------------

class TestSpmdBlock:
    def test_host_images_and_barrier(self):
        phases = []
        lock = threading.Lock()

        def image(block):
            with lock:
                phases.append(("a", block.this_image()))
            block.sync_all()
            with lock:
                phases.append(("b", block.this_image()))
            return block.this_image() * 10

        res = hpx.define_spmd_block("t", 6, image).get()
        HPX_TEST_EQ(sorted(res), [0, 10, 20, 30, 40, 50])
        # every 'a' strictly before every 'b' (the barrier held)
        a_idx = [i for i, p in enumerate(phases) if p[0] == "a"]
        b_idx = [i for i, p in enumerate(phases) if p[0] == "b"]
        HPX_TEST(max(a_idx) < min(b_idx))

    def test_block_metadata(self):
        def image(block, extra):
            HPX_TEST_EQ(block.get_block_name(), "meta")
            HPX_TEST_EQ(block.get_num_images(), 2)
            return block.image_id() + extra

        res = hpx.define_spmd_block("meta", 2, image, 100).get()
        HPX_TEST_EQ(sorted(res), [100, 101])

    def test_device_plane(self, mesh1d):
        from jax.sharding import PartitionSpec as P

        def body(block, x):
            # rank-dependent update: image i adds i to its shard
            return x + block.this_image().astype(x.dtype)

        step = hpx.device_spmd_block(body, mesh1d, "x",
                                     in_specs=(P("x"),), out_specs=P("x"))
        x = jnp.zeros(16, jnp.float32)
        out = np.asarray(step(x))
        want = np.repeat(np.arange(8, dtype=np.float32), 2)
        np.testing.assert_array_equal(out, want)
