"""Distributed sort (algo/sorting.sort_sharded): one-shot PSRS sample
sort (default p>4) and odd-even transposition fallback (p<=4) — the
segmented sort over ppermute/all_to_all."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hpx_tpu.algo.sorting import (
    _build_odd_even,
    _build_sample_sort,
    _sharded_axis,
    sort_sharded,
)


def _mesh(devices, n):
    from jax.sharding import Mesh
    return Mesh(np.array(devices[:n]), ("x",))


def _put(x, mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P
    return jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("x")))


@pytest.mark.parametrize("method", ["sample", "odd_even"])
@pytest.mark.parametrize("p,n", [(8, 1024), (5, 200), (2, 64), (1, 32)])
def test_sort_sharded_matches_numpy(devices, p, n, method):
    if p == 1:
        pytest.skip("mesh.size <= 1 routes to plain jnp.sort")
    rng = np.random.default_rng(p)
    v = rng.standard_normal(n).astype(np.float32)
    mesh = _mesh(devices, p)
    got = sort_sharded(_put(v, mesh), mesh, method=method)
    np.testing.assert_array_equal(np.asarray(got), np.sort(v))


@pytest.mark.parametrize("method", ["sample", "odd_even"])
def test_sort_sharded_int_and_duplicates(devices, method):
    mesh = _mesh(devices, 8)
    rng = np.random.default_rng(0)
    v = rng.integers(0, 16, size=512).astype(np.int32)
    got = sort_sharded(_put(v, mesh), mesh, method=method)
    np.testing.assert_array_equal(np.asarray(got), np.sort(v))


@pytest.mark.parametrize("case", ["all_equal", "presorted", "reversed",
                                  "two_values", "max_vals"])
def test_sample_sort_adversarial(devices, case):
    """Inputs that stress the PSRS capacity bound: duplicate-heavy and
    pre-structured data must not overflow the static bucket capacity
    (they bucket by global id thanks to the lexicographic tiebreak)."""
    mesh = _mesh(devices, 8)
    n = 512
    if case == "all_equal":
        v = np.full(n, 3.5, np.float32)
    elif case == "presorted":
        v = np.arange(n, dtype=np.float32)
    elif case == "reversed":
        v = np.arange(n, dtype=np.float32)[::-1].copy()
    elif case == "two_values":
        v = np.where(np.arange(n) % 7 == 0, 1.0, -1.0).astype(np.float32)
    else:                                 # max_vals: collide with padding
        v = np.full(n, np.finfo(np.float32).max, np.float32)
        v[: n // 2] = -1.0
    got = sort_sharded(_put(v, mesh), mesh, method="sample")
    np.testing.assert_array_equal(np.asarray(got), np.sort(v))


def test_sample_sort_nan(devices):
    """NaNs must sort last like np.sort/jnp.sort — the IEEE partial
    order must not corrupt bucketing (total-order key regression)."""
    mesh = _mesh(devices, 8)
    rng = np.random.default_rng(1)
    v = rng.standard_normal(256).astype(np.float32)
    v[::17] = np.nan
    v[5] = -np.nan
    got = np.asarray(sort_sharded(_put(v, mesh), mesh, method="sample"))
    want = np.sort(v)                      # NaNs last
    assert np.array_equal(got, want, equal_nan=True), (got, want)


def test_sample_sort_negzero_inf(devices):
    mesh = _mesh(devices, 8)
    v = np.array([0.0, -0.0, np.inf, -np.inf] * 16, np.float32)
    got = np.asarray(sort_sharded(_put(v, mesh), mesh, method="sample"))
    np.testing.assert_array_equal(got, np.sort(v))


def test_sample_sort_bool_and_bf16(devices):
    mesh = _mesh(devices, 8)
    b = (np.arange(64) % 3 == 0)
    got = np.asarray(sort_sharded(_put(b, mesh), mesh, method="sample"))
    np.testing.assert_array_equal(got, np.sort(b))
    h = jnp.asarray(np.random.default_rng(2).standard_normal(128),
                    jnp.bfloat16)
    goth = np.asarray(sort_sharded(_put(np.asarray(h), mesh), mesh,
                                   method="sample").astype(jnp.float32))
    np.testing.assert_array_equal(
        goth, np.sort(np.asarray(h.astype(jnp.float32))))


def test_sort_sharded_rejects_unknown_method(devices):
    mesh = _mesh(devices, 8)
    v = _put(np.zeros(64, np.float32), mesh)
    with pytest.raises(ValueError, match="unknown method"):
        sort_sharded(v, mesh, method="samples")


@pytest.mark.parametrize("p,n", [(8, 72), (8, 24), (5, 35), (6, 42)])
def test_sample_sort_ragged_chunks(devices, p, n):
    """m = n/p not divisible by p: the padded-key path (dtype max,
    id >= n keys rank past n and get dropped by the final scatter)."""
    rng = np.random.default_rng(n)
    v = rng.standard_normal(n).astype(np.float32)
    mesh = _mesh(devices, p)
    got = sort_sharded(_put(v, mesh), mesh, method="sample")
    np.testing.assert_array_equal(np.asarray(got), np.sort(v))


def test_sample_sort_hlo_o1_exchanges(devices):
    """The whole point vs odd-even: the compiled collective count must
    not grow with mesh size. Compile at p=4 and p=8 and assert the
    all-to-all count is equal (and small); odd-even at p=8 by contrast
    carries >= p collective-permutes."""
    def count(hlo, op):
        # StableHLO op lines look like `%N = "stablehlo.all_to_all"(...`
        # or `%N = stablehlo.all_to_all(...`; count op applications,
        # not type/attribute mentions
        return sum(1 for ln in hlo.splitlines()
                   if op in ln and "=" in ln and "stablehlo" in ln)

    hlos = {}
    for p in (4, 8):
        mesh = _mesh(devices, p)
        v = _put(np.zeros(64, np.float32), mesh)
        prog = _build_sample_sort(mesh, "x")
        hlos[p] = prog.lower(v).as_text()
    a2a4 = count(hlos[4], "all_to_all")
    a2a8 = count(hlos[8], "all_to_all")
    assert a2a4 == a2a8, (a2a4, a2a8)
    assert 1 <= a2a8 <= 8, a2a8
    # the only all_gathers are the tiny sample/bucket-size ones
    assert count(hlos[8], "all_gather") <= 4
    mesh8 = _mesh(devices, 8)
    oe = _build_odd_even(mesh8, "x").lower(
        _put(np.zeros(64, np.float32), mesh8)).as_text()
    assert count(oe, "collective_permute") >= 8


def test_sharded_axis_detection(devices):
    mesh = _mesh(devices, 8)
    a = _put(np.arange(64, dtype=np.float32), mesh)
    det = _sharded_axis(a)
    assert det is not None and det[1] == "x"
    assert _sharded_axis(jnp.arange(8.0)) is None      # unsharded
    assert _sharded_axis(np.arange(8.0)) is None       # not a jax array


def test_algo_sort_routes_partitioned_vector(devices):
    """algo.sort(par, pv) sorts globally through the distributed path
    and rewraps into the pv layout."""
    from hpx_tpu.algo import sort
    from hpx_tpu.containers.partitioned_vector import PartitionedVector
    from hpx_tpu.dist.distribution_policies import ContainerLayout
    from hpx_tpu.exec.policies import par

    mesh = _mesh(devices, 8)
    lay = ContainerLayout(mesh=mesh, axis="x")
    rng = np.random.default_rng(3)
    v = rng.standard_normal(512).astype(np.float32)
    pv = PartitionedVector.from_array(v, layout=lay)
    out = sort(par, pv)
    assert isinstance(out, PartitionedVector)
    np.testing.assert_array_equal(out.to_numpy(), np.sort(v))


def test_algo_sort_with_key_still_works(devices):
    from hpx_tpu.algo import sort
    from hpx_tpu.exec.policies import par
    v = jnp.asarray(np.random.default_rng(4).standard_normal(64),
                    jnp.float32)
    out = sort(par, v, key=lambda x: -x)        # descending via key
    np.testing.assert_allclose(np.asarray(out),
                               np.sort(np.asarray(v))[::-1], rtol=1e-6)


class TestSortByKey:
    """Distributed by-key sort: values ride the PSRS exchanges as
    payload; STABLE via the global-id tiebreak."""

    def test_matches_numpy_stable_argsort(self, devices):
        from hpx_tpu.algo.sorting import sort_sharded_by_key
        mesh = _mesh(devices, 8)
        rng = np.random.default_rng(5)
        keys = rng.integers(0, 10, 512).astype(np.int32)   # many ties
        vals = np.arange(512, dtype=np.float32)            # identity
        got = np.asarray(sort_sharded_by_key(
            _put(keys, mesh), _put(vals, mesh), mesh))
        want = vals[np.argsort(keys, kind="stable")]
        np.testing.assert_array_equal(got, want)           # stability!

    @pytest.mark.parametrize("p,n", [(8, 72), (5, 200), (6, 42)])
    def test_ragged_and_float_keys(self, devices, p, n):
        from hpx_tpu.algo.sorting import sort_sharded_by_key
        mesh = _mesh(devices, p)
        rng = np.random.default_rng(n)
        keys = rng.standard_normal(n).astype(np.float32)
        vals = rng.integers(0, 1000, n).astype(np.int32)
        got = np.asarray(sort_sharded_by_key(
            _put(keys, mesh), _put(vals, mesh), mesh))
        np.testing.assert_array_equal(
            got, vals[np.argsort(keys, kind="stable")])

    def test_public_sort_with_key_on_sharded(self, devices):
        """algo.sort(par, sharded, key=...) now sorts distributed (it
        previously fell back to the gather path)."""
        from hpx_tpu.algo import sort
        from hpx_tpu.exec.policies import par
        mesh = _mesh(devices, 8)
        rng = np.random.default_rng(9)
        v = rng.standard_normal(256).astype(np.float32)
        out = sort(par, _put(v, mesh), key=lambda x: -x)   # descending
        np.testing.assert_allclose(np.asarray(out), np.sort(v)[::-1],
                                   rtol=1e-6)

    def test_bool_payload(self, devices):
        from hpx_tpu.algo.sorting import sort_sharded_by_key
        mesh = _mesh(devices, 8)
        keys = np.arange(64, dtype=np.int32)[::-1].copy()
        vals = (np.arange(64) % 2 == 0)
        got = np.asarray(sort_sharded_by_key(
            _put(keys, mesh), _put(vals, mesh), mesh))
        np.testing.assert_array_equal(got, vals[::-1])

    def test_payload_nan_bits_survive(self, devices):
        """Payload is bit transport, not ordering: NaN payload values
        survive byte-exactly."""
        from hpx_tpu.algo.sorting import sort_sharded_by_key
        mesh = _mesh(devices, 8)
        keys = np.arange(64, dtype=np.int32)[::-1].copy()
        vals = np.full(64, np.nan, np.float32)
        vals[::3] = 7.5
        got = np.asarray(sort_sharded_by_key(
            _put(keys, mesh), _put(vals, mesh), mesh))
        want = vals[::-1]
        np.testing.assert_array_equal(
            got.view(np.uint32), want.view(np.uint32))   # bit-exact
