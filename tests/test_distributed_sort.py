"""Distributed sort (algo/sorting.sort_sharded): odd-even transposition
on blocks over ppermute — the segmented sort."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hpx_tpu.algo.sorting import sort_sharded, _sharded_axis


def _mesh(devices, n):
    from jax.sharding import Mesh
    return Mesh(np.array(devices[:n]), ("x",))


def _put(x, mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P
    return jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("x")))


@pytest.mark.parametrize("p,n", [(8, 1024), (5, 200), (2, 64), (1, 32)])
def test_sort_sharded_matches_numpy(devices, p, n):
    if p == 1:
        pytest.skip("mesh.size <= 1 routes to plain jnp.sort")
    rng = np.random.default_rng(p)
    v = rng.standard_normal(n).astype(np.float32)
    mesh = _mesh(devices, p)
    got = sort_sharded(_put(v, mesh), mesh)
    np.testing.assert_array_equal(np.asarray(got), np.sort(v))


def test_sort_sharded_int_and_duplicates(devices):
    mesh = _mesh(devices, 8)
    rng = np.random.default_rng(0)
    v = rng.integers(0, 16, size=512).astype(np.int32)
    got = sort_sharded(_put(v, mesh), mesh)
    np.testing.assert_array_equal(np.asarray(got), np.sort(v))


def test_sharded_axis_detection(devices):
    mesh = _mesh(devices, 8)
    a = _put(np.arange(64, dtype=np.float32), mesh)
    det = _sharded_axis(a)
    assert det is not None and det[1] == "x"
    assert _sharded_axis(jnp.arange(8.0)) is None      # unsharded
    assert _sharded_axis(np.arange(8.0)) is None       # not a jax array


def test_algo_sort_routes_partitioned_vector(devices):
    """algo.sort(par, pv) sorts globally through the distributed path
    and rewraps into the pv layout."""
    from hpx_tpu.algo import sort
    from hpx_tpu.containers.partitioned_vector import PartitionedVector
    from hpx_tpu.dist.distribution_policies import ContainerLayout
    from hpx_tpu.exec.policies import par

    mesh = _mesh(devices, 8)
    lay = ContainerLayout(mesh=mesh, axis="x")
    rng = np.random.default_rng(3)
    v = rng.standard_normal(512).astype(np.float32)
    pv = PartitionedVector.from_array(v, layout=lay)
    out = sort(par, pv)
    assert isinstance(out, PartitionedVector)
    np.testing.assert_array_equal(out.to_numpy(), np.sort(v))


def test_algo_sort_with_key_still_works(devices):
    from hpx_tpu.algo import sort
    from hpx_tpu.exec.policies import par
    v = jnp.asarray(np.random.default_rng(4).standard_normal(64),
                    jnp.float32)
    out = sort(par, v, key=lambda x: -x)        # descending via key
    np.testing.assert_allclose(np.asarray(out),
                               np.sort(np.asarray(v))[::-1], rtol=1e-6)
