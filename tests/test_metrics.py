"""The SLO metrics plane: log-bucketed HistogramCounter accuracy and
algebra, counter-registry derivation, Prometheus rendering, request
timelines, dropped-span accounting, the serving_bench metrics
artifact, and cross-worker trace stitching on a live 2-worker fleet.

The quantile contract under test is the whole point of the design:
``quantile(q)`` is a nearest-rank estimate whose RELATIVE error is
bounded by ``sqrt(gamma) - 1`` (gamma = 2**(1/subbuckets)) regardless
of the distribution, and ``merge`` is exact (vector addition of
counts) and associative — so fleet-wide quantiles computed from merged
per-worker histograms carry the same bound as any single worker's.
"""

import json
import math
import os
import sys

import numpy as np
import pytest

from hpx_tpu.svc import metrics
from hpx_tpu.svc import performance_counters as pc
from hpx_tpu.svc import tracing
from hpx_tpu.svc.metrics import (
    HistogramCounter,
    RequestTimeline,
    latency_histograms,
    register_histogram,
)

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "benchmarks"))

# ---------------------------------------------------------------------------
# quantile accuracy vs the exact nearest-rank answer
# ---------------------------------------------------------------------------


def _exact_quantile(xs, q):
    """The nearest-rank quantile the histogram approximates."""
    xs = sorted(xs)
    k = max(1, math.ceil(q * len(xs)))
    return xs[k - 1]


def _check_bound(xs, quantiles=(0.5, 0.9, 0.95, 0.99)):
    h = HistogramCounter()
    for x in xs:
        h.record(x)
    bound = h.relative_error_bound()
    for q in quantiles:
        est, exact = h.quantile(q), _exact_quantile(xs, q)
        assert est == pytest.approx(exact, rel=bound + 1e-9), (
            f"q={q}: est {est} vs exact {exact} "
            f"(bound {bound:.4f})")


def test_quantile_accuracy_lognormal():
    rng = np.random.default_rng(7)
    _check_bound(np.exp(rng.normal(-3.0, 1.5, 5000)).tolist())


def test_quantile_accuracy_uniform():
    rng = np.random.default_rng(11)
    _check_bound(rng.uniform(1e-4, 2.0, 5000).tolist())


def test_quantile_adversarial_shapes():
    # constant: every quantile is the one observed value, and the
    # [vmin, vmax] clamp makes the estimate EXACT
    h = HistogramCounter()
    for _ in range(100):
        h.record(0.125)
    for q in (0.01, 0.5, 0.99):
        assert h.quantile(q) == 0.125
    # two-point mass straddling many octaves
    _check_bound([1e-5] * 90 + [10.0] * 10)
    # values pinned to bucket boundaries (powers of gamma), spanning
    # ~30 octaves but staying inside [lo, hi) where the bound holds
    g = 2.0 ** (1.0 / 8)
    _check_bound([1e-6 * g ** i for i in range(0, 240, 7)])
    # full dynamic range incl. under/overflow clamps
    h = HistogramCounter(lo=1e-3, hi=1.0)
    for v in (1e-6, 5e-4, 0.1, 50.0, 2000.0):
        h.record(v)
    assert h.quantile(0.0) >= 1e-6
    assert h.quantile(1.0) <= 2000.0 + 1e-9


def test_quantile_empty_and_mean():
    h = HistogramCounter()
    assert h.quantile(0.5) == 0.0
    h.record(2.0)
    h.record(4.0)
    assert h.mean() == pytest.approx(3.0)
    assert h.get_value().value == pytest.approx(3.0)


# ---------------------------------------------------------------------------
# merge: exact, associative, layout-checked
# ---------------------------------------------------------------------------


def _fill(seed, n):
    rng = np.random.default_rng(seed)
    h = HistogramCounter()
    for x in np.exp(rng.normal(-2.0, 2.0, n)):
        h.record(float(x))
    return h


def test_merge_associative_and_exact():
    a, b, c = _fill(1, 400), _fill(2, 300), _fill(3, 500)
    left = a.merge(b).merge(c)
    right = a.merge(b.merge(c))
    assert left.snapshot() == right.snapshot()
    assert left.count == a.count + b.count + c.count
    assert left.sum == pytest.approx(a.sum + b.sum + c.sum)
    # merge with an empty histogram is the identity
    assert a.merge(HistogramCounter()).snapshot() == a.snapshot()


def test_merge_layout_mismatch_raises():
    with pytest.raises(ValueError):
        HistogramCounter(subbuckets=8).merge(
            HistogramCounter(subbuckets=4))


def test_merge_quantile_equals_per_worker_fold():
    """The acceptance identity: quantiles of the merged histogram are
    what you get folding per-worker snapshots through from_snapshot —
    the fleet-wide view IS the merge of the worker views."""
    workers = [_fill(s, 250) for s in (5, 6, 7)]
    merged = workers[0].merge(workers[1]).merge(workers[2])
    refold = HistogramCounter()
    for w in workers:
        refold = refold.merge(
            HistogramCounter.from_snapshot(w.snapshot()))
    for q in (0.5, 0.95, 0.99):
        assert merged.quantile(q) == pytest.approx(
            refold.quantile(q), rel=1e-12)


# ---------------------------------------------------------------------------
# snapshot / delta / roundtrip
# ---------------------------------------------------------------------------


def test_snapshot_roundtrip():
    h = _fill(9, 600)
    snap = h.snapshot()
    json.dumps(snap)                     # JSON-safe by contract
    back = HistogramCounter.from_snapshot(snap)
    assert back.snapshot() == snap
    for q in (0.5, 0.99):
        assert back.quantile(q) == pytest.approx(
            h.quantile(q), rel=h.relative_error_bound())


def test_empty_snapshot_roundtrip():
    h = HistogramCounter()
    snap = h.snapshot()
    assert snap["min"] is None and snap["max"] is None
    back = HistogramCounter.from_snapshot(snap)
    assert back.count == 0 and back.quantile(0.5) == 0.0


def test_delta_window():
    h = HistogramCounter()
    h.record(0.1)
    prev = h.snapshot()
    h.record(0.2)
    h.record(0.4)
    d = h.delta(prev)
    assert d["count"] == 2
    assert d["sum"] == pytest.approx(0.6)
    win = HistogramCounter.from_snapshot(d)
    assert win.count == 2
    # delta counts + prev counts == current counts, bucket by bucket
    cur = h.snapshot()
    assert [p + w for p, w in zip(prev["counts"], d["counts"])] \
        == cur["counts"]


def test_record_timer_context():
    h = HistogramCounter()
    with h.record():
        pass
    assert h.count == 1
    assert h.vmin >= 0.0


# ---------------------------------------------------------------------------
# registry derivation + Prometheus rendering
# ---------------------------------------------------------------------------


def test_register_histogram_derives_quantile_counters():
    h = HistogramCounter()
    for v in (0.01, 0.02, 0.04, 0.08):
        h.record(v)
    names = register_histogram("serving", "latency/test-s", h,
                               instance="t0")
    try:
        base = "/serving{locality#0/t0}/latency/test-s"
        assert base in names
        assert f"{base}/p50" in names and f"{base}/p99" in names
        assert pc.query_counter(f"{base}/p99").value \
            == pytest.approx(h.quantile(0.99))
        # mean rides the base counter
        assert pc.query_counter(base).value == pytest.approx(h.mean())
        text = metrics.render_prometheus("/serving{locality#0/t0}/*")
        assert "hpx_serving_latency_test_s_bucket" in text
        assert 'le="+Inf"' in text
        assert 'hpx_serving_latency_test_s_count' \
               '{locality="0",instance="t0"} 4' in text
    finally:
        for n in names:
            pc.unregister_counter(n)


def test_registry_snapshot_shapes():
    h = HistogramCounter()
    h.record(0.5)
    names = register_histogram("serving", "latency/snap-s", h,
                               instance="t1")
    try:
        snap = metrics.registry_snapshot("/serving{locality#0/t1}/*")
        base = "/serving{locality#0/t1}/latency/snap-s"
        assert snap["histograms"][base]["count"] == 1
        assert f"{base}/p50" in snap["counters"]
        json.dumps(snap)
    finally:
        for n in names:
            pc.unregister_counter(n)


def test_dropped_spans_counter():
    tr = tracing.start_tracing(capacity=4, sample_counters=False)
    try:
        for i in range(32):
            with tracing.span(f"s{i}", "test"):
                pass
        got = pc.query_counter(
            "/runtime{locality#0/total}/trace/dropped-spans").value
        assert got > 0
    finally:
        tracing.stop_tracing()


# ---------------------------------------------------------------------------
# request timelines
# ---------------------------------------------------------------------------


def test_timeline_capacity_drop_oldest():
    tl = RequestTimeline(capacity=2)
    tl.event("r0", "submit")
    tl.event("r1", "submit")
    tl.event("r0", "retire", tokens=3)
    tl.event("r2", "submit")             # evicts r1 (oldest rid)
    assert tl.dropped == 1
    assert [e["name"] for e in tl.events("r0")] == ["submit",
                                                    "retire"]
    assert tl.events("r1") == []
    assert len(tl) == 2
    assert tl.events("r0")[1]["attrs"]["tokens"] == 3
    json.dumps(tl.snapshot())


# ---------------------------------------------------------------------------
# serving integration: live histograms + timeline on a tiny wave
# ---------------------------------------------------------------------------

import jax
from hpx_tpu.models import transformer as tfm

CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                            head_dim=8, n_layers=2, d_ff=64)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.PRNGKey(0))


def test_server_histograms_and_timeline(params):
    from hpx_tpu.models.serving import ContinuousServer
    srv = ContinuousServer(params, CFG, slots=2, smax=64)
    rids = [srv.submit([1, 2, 3, 4], max_new=4) for _ in range(3)]
    srv.run()
    assert srv.hist["ttft"].count == 3
    assert srv.hist["e2e"].count == 3
    assert srv.hist["queue_wait"].count == 3
    for rid in rids:
        names = [e["name"] for e in srv.timeline.events(rid)]
        assert names[0] == "submit" and names[-1] == "retire"
        assert "first_token" in names
    srv.shutdown()


def test_router_merged_hist_and_timeline(params):
    from hpx_tpu.models.disagg import DisaggRouter
    r = DisaggRouter(params, CFG, prefill_workers=1,
                     decode_workers=2, slots=2, smax=64)
    for i in range(4):
        r.submit([1 + i, 2, 3, 4, 5, 6], max_new=3)
    out = r.run()
    r.close()
    assert len(out) == 4
    merged = r.merged_hist()
    assert merged["ttft"].count == 4
    assert merged["e2e"].count == 4
    assert merged["queue_wait"].count == 4
    # fleet-wide == fold of per-worker (the acceptance identity)
    refold = latency_histograms()
    for per in r.whist.values():
        for k in refold:
            refold[k] = refold[k].merge(per[k])
    for k in refold:
        assert refold[k].snapshot() == merged[k].snapshot()
    names = [e["name"] for e in r.timeline.events("r0")]
    assert names[0] == "submit"
    assert "place" in names and "retire" in names
    st = r.stats()
    assert st["latency"]["ttft"]["p99"] == pytest.approx(
        merged["ttft"].quantile(0.99))


# ---------------------------------------------------------------------------
# cross-worker trace stitching on a live 2-decode-worker fleet
# ---------------------------------------------------------------------------


def test_merge_traces_stitches_fleet(params):
    from hpx_tpu.svc.fleet import FleetRouter
    from hpx_tpu.svc.trace_export import (merge_traces,
                                          to_chrome_trace,
                                          validate_chrome_trace)
    tracer = tracing.start_tracing(sample_counters=False)
    try:
        r = FleetRouter(params, CFG, prefill_workers=1,
                        decode_workers=2, slots=2, smax=64)
        for i in range(4):
            r.submit([1 + i, 2, 3, 4, 5, 6], max_new=3)
        out = r.run()
        worker_docs = r.worker_trace_docs()
        r.close()
    finally:
        tracing.stop_tracing()
    assert len(out) == 4
    assert len(worker_docs) >= 2          # 1 prefill + >=1 decode ring
    router_doc = to_chrome_trace(
        tracer.snapshot(), tracer.thread_names(), tracer.t0,
        tracer.dropped, t0_wall=tracer.t0_wall)
    doc = merge_traces([("router", router_doc)] + worker_docs)
    assert validate_chrome_trace(doc) == []
    evs = doc["traceEvents"]
    assert len({e["pid"] for e in evs}) >= 3
    # >=1 placed request's flow arrows cross worker pid rows
    flows = [e for e in evs if e.get("cat") == "rid"]
    starts = {e["id"]: e for e in flows if e["ph"] == "s"}
    crossing = [e for e in flows if e["ph"] == "f"
                and e["pid"] != starts[e["id"]]["pid"]]
    assert crossing, "no rid flow arrow crosses a worker pid row"
    assert doc["otherData"]["stitched_rids"] >= 4
    assert doc["otherData"]["processes"][0] == "router"
    # per-process clock alignment kept ts monotone overall (metadata
    # M rows carry no ts)
    ts = [e["ts"] for e in evs if e["ph"] != "M"]
    assert ts == sorted(ts)


# ---------------------------------------------------------------------------
# serving_bench --metrics-out artifact schema
# ---------------------------------------------------------------------------


def test_metrics_artifact_schema(tmp_path):
    import serving_bench
    h = _fill(21, 50)
    doc = serving_bench.metrics_artifact(
        {"wave/ttft": h}, counters={"/x{locality#0/total}/y": 1.0})
    assert doc["schema"] == serving_bench.METRICS_SCHEMA == \
        "hpx_tpu.metrics.v1"
    ent = doc["histograms"]["wave/ttft"]
    assert ent["quantiles"]["p99"] == pytest.approx(h.quantile(0.99))
    assert ent["relative_error_bound"] == pytest.approx(
        h.relative_error_bound())
    back = HistogramCounter.from_snapshot(ent["snapshot"])
    assert back.count == h.count
    path = tmp_path / "m.json"
    serving_bench.write_metrics_artifact(str(path), doc)
    assert json.load(open(path)) == json.loads(json.dumps(doc))


# ---------------------------------------------------------------------------
# Prometheus exposition edge cases (exact text-format contract)
# ---------------------------------------------------------------------------


def _prom_register(h, instance):
    name = pc.counter_name("test", "prom/edge-s", instance)
    pc.register_counter(name, h)
    return name


def test_prometheus_empty_registry_renders_empty():
    # no matches: empty string, no stray trailing newline
    assert metrics.render_prometheus("/no-such{locality#0/x}/*") == ""


def test_prometheus_empty_histogram_exact_text():
    # zero samples still expose the full histogram family — TYPE, the
    # unconditional +Inf bucket, _sum and _count, all zero — so a
    # scrape can tell "registered but idle" from "absent"
    name = _prom_register(HistogramCounter(), "pe0")
    try:
        text = metrics.render_prometheus(name)
    finally:
        pc.unregister_counter(name)
    m = "hpx_test_prom_edge_s"
    lab = '{locality="0",instance="pe0"}'
    assert text == (
        f"# TYPE {m} histogram\n"
        f'{m}_bucket{{le="+Inf",locality="0",instance="pe0"}} 0\n'
        f"{m}_sum{lab} 0\n"
        f"{m}_count{lab} 0\n")


def test_prometheus_single_sample_exact_text():
    h = HistogramCounter()
    h.record(0.25)
    (idx,) = [i for i, n in enumerate(h.counts) if n]
    le = h.bucket_upper(idx)
    name = _prom_register(h, "pe1")
    try:
        text = metrics.render_prometheus(name)
    finally:
        pc.unregister_counter(name)
    m = "hpx_test_prom_edge_s"
    lab = '{locality="0",instance="pe1"}'
    assert text == (
        f"# TYPE {m} histogram\n"
        f'{m}_bucket{{le="{le:.9g}",locality="0",instance="pe1"}} 1\n'
        f'{m}_bucket{{le="+Inf",locality="0",instance="pe1"}} 1\n'
        f"{m}_sum{lab} 0.25\n"
        f"{m}_count{lab} 1\n")


def test_prometheus_inf_bucket_cumulative():
    # bucket rows are cumulative and the +Inf row always equals the
    # total count — even though the overflow bucket itself is empty
    h = HistogramCounter()
    for v in (0.001, 0.001, 1.0, 100.0):
        h.record(v)
    name = _prom_register(h, "pe2")
    try:
        text = metrics.render_prometheus(name)
    finally:
        pc.unregister_counter(name)
    rows = [ln for ln in text.splitlines() if "_bucket{" in ln]
    cums = [int(ln.rsplit(" ", 1)[1]) for ln in rows]
    assert cums == sorted(cums)                  # cumulative
    assert cums == [2, 3, 4, 4]                  # 3 occupied + +Inf
    assert rows[-1].startswith(
        'hpx_test_prom_edge_s_bucket{le="+Inf"')
    # exactly one TYPE line, declared before any sample row
    assert text.splitlines()[0] == "# TYPE hpx_test_prom_edge_s " \
                                   "histogram"
    assert text.count("# TYPE") == 1
    assert text.endswith("\n")


# ---------------------------------------------------------------------------
# timeline LRU eviction counter
# ---------------------------------------------------------------------------


def test_timeline_dropped_entries_counter():
    metrics.reset_timeline_dropped()
    tl = RequestTimeline(capacity=2)
    for i in range(5):
        tl.event(f"rid{i}", "submit")
    assert tl.dropped == 3
    name = "/runtime{locality#0/total}/timeline/dropped-entries"
    assert pc.query_counter(name).value == 3.0
    # a second timeline adds to the same process-wide counter
    tl2 = RequestTimeline(capacity=1)
    tl2.event("a", "submit")
    tl2.event("b", "submit")
    assert pc.query_counter(name).value == 4.0
    # surfaced by registry_snapshot for artifacts/bundles
    snap = metrics.registry_snapshot(
        "/runtime{locality#0/total}/timeline/*")
    assert snap["counters"][name] == 4.0
    # reset=True routes to reset_timeline_dropped
    assert pc.query_counter(name, reset=True).value == 4.0
    assert pc.query_counter(name).value == 0.0
    assert metrics.timeline_dropped_entries() == 0


# ---------------------------------------------------------------------------
# TaskTimer.top() under concurrent mutation (regression)
# ---------------------------------------------------------------------------


def test_task_timer_top_concurrent_mutation():
    # top() must snapshot under the timer's lock: iterating stats
    # while on_stop() inserts new names from worker threads would
    # raise "dictionary changed size during iteration" (and could
    # tear a [count, total] pair mid-update)
    import threading
    from hpx_tpu.svc.profiling import TaskTimer

    t = TaskTimer()
    stop = threading.Event()
    errs = []

    def writer(wid):
        i = 0
        while not stop.is_set():
            def fn():
                pass
            fn.__qualname__ = f"task_{wid}_{i % 997}"
            t.on_stop(fn, 0.001)
            i += 1

    threads = [threading.Thread(target=writer, args=(w,))
               for w in range(4)]
    for th in threads:
        th.start()
    try:
        for _ in range(300):
            try:
                rows = t.top(k=5)
            except Exception as e:  # noqa: BLE001 — the regression
                errs.append(e)
                break
            assert len(rows) <= 5
            for _name, count, total in rows:
                assert count >= 1 and total > 0.0
    finally:
        stop.set()
        for th in threads:
            th.join(timeout=5.0)
    assert errs == []
    # totals stay consistent once quiescent: count * 1ms == total
    for _name, count, total in t.top(k=10**9):
        assert total == pytest.approx(count * 0.001)
