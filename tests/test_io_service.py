"""io_service helper pools (runtime/io_service.py) and execution
agents (exec/execution_base.py)."""

import threading
import time

import pytest

from hpx_tpu.exec.execution_base import (agent, suspend, this_task,
                                         yield_, yield_while)
from hpx_tpu.runtime.io_service import (IoServicePool, get_io_service_pool,
                                        io_pool_names,
                                        register_external_pool)
from hpx_tpu.runtime.threadpool import default_pool, reset_default_pool


# -- io_service pools --------------------------------------------------------

def test_io_pool_runs_and_returns_future():
    p = IoServicePool("t-basic", threads=2)
    try:
        f = p.async_execute(lambda a, b: a + b, 20, 22)
        assert f.get(timeout=10.0) == 42
    finally:
        p.stop()


def test_io_pool_propagates_exception():
    p = IoServicePool("t-exc")
    try:
        f = p.async_execute(lambda: 1 / 0)
        with pytest.raises(ZeroDivisionError):
            f.get(timeout=10.0)
    finally:
        p.stop()


def test_io_pool_post_fire_and_forget():
    p = IoServicePool("t-post")
    done = threading.Event()
    try:
        p.post(done.set)
        assert done.wait(10.0)
    finally:
        p.stop()


def test_io_pool_blocking_work_does_not_starve():
    """Blocking tasks occupy helper threads, not compute workers: a
    2-thread pool with 2 blockers still finishes queued work after the
    blockers release."""
    p = IoServicePool("t-block", threads=2)
    gate = threading.Event()
    try:
        blockers = [p.async_execute(gate.wait, 10.0) for _ in range(2)]
        f = p.async_execute(lambda: "queued")
        assert p.pending() >= 1          # queued behind the blockers
        gate.set()
        assert f.get(timeout=10.0) == "queued"
        for b in blockers:
            assert b.get(timeout=10.0)
    finally:
        p.stop()


def test_io_pool_submit_from_own_thread():
    p = IoServicePool("t-reentrant", threads=1)
    try:
        f = p.async_execute(
            lambda: p.async_execute(lambda: "inner"))
        # future<future<T>> collapses (HPX unwrap semantics)
        assert f.get(timeout=10.0) == "inner"
    finally:
        p.stop()


def test_named_registry_and_external_pools():
    io = get_io_service_pool("io")
    assert get_io_service_pool("io") is io
    assert io.size == 2                  # reference default
    register_external_pool("parcel", 1, "native/net.cpp epoll thread")
    assert "parcel" in io_pool_names()
    with pytest.raises(RuntimeError, match="native/net.cpp"):
        get_io_service_pool("parcel").post(lambda: None)


def test_stopped_pool_rejects():
    p = IoServicePool("t-stopped")
    p.async_execute(lambda: None).get(timeout=10.0)
    p.stop()
    with pytest.raises(RuntimeError, match="stopped"):
        p.post(lambda: None)


# -- execution agents --------------------------------------------------------

def test_agent_identity_external_vs_worker():
    assert not agent().in_worker
    assert agent().description() == "external-thread"
    pool = default_pool()
    out = {}
    done = threading.Event()

    def task():
        out["agent"] = agent()
        done.set()

    pool.submit(task)
    assert done.wait(10.0)
    assert out["agent"].in_worker


def test_yield_runs_queued_work_from_worker():
    """yield_() on a worker drains one queued task — the cooperative
    scheduling point the reference's this_thread::yield provides."""
    reset_default_pool()
    pool = default_pool()
    ran = []
    done = threading.Event()

    def spinner():
        # queue a second task, then yield until it has run
        pool.submit(lambda: ran.append("other"))
        ok = yield_while(lambda: not ran, timeout=10.0)
        ran.append("spinner-done" if ok else "timeout")
        done.set()

    pool.submit(spinner)
    assert done.wait(10.0)
    assert ran[0] == "other" and ran[-1] == "spinner-done"


def test_suspend_waits_at_least_duration():
    t0 = time.monotonic()
    suspend(0.05)
    assert time.monotonic() - t0 >= 0.05


def test_yield_while_timeout():
    assert not yield_while(lambda: True, timeout=0.05)
    assert yield_while(lambda: False, timeout=0.05)


def test_this_task_namespace():
    assert this_task.agent() is not None
    this_task.yield_()


def test_io_pool_counters_discoverable():
    from hpx_tpu.svc import performance_counters as pc
    get_io_service_pool("io")            # ensure the pool exists
    names = pc.discover_counters("/io{*}*")
    assert any("pool#io" in n and "queue/length" in n for n in names), names
    val = pc.query_counter([n for n in names if "pool#io" in n][0])
    assert val.value == 0.0


def test_timer_pool_registers_on_first_timer():
    from hpx_tpu.core.timing import async_after
    async_after(0.01, lambda: 7).get(timeout=10.0)
    assert "timer" in io_pool_names()
