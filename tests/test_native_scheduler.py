"""Lock-free native scheduler (native/scheduler.cpp, round 4).

The per-worker queues are Chase-Lev deques (Lê et al. PPoPP'13): the
owner pushes/takes lock-free at the bottom, thieves CAS-steal at the
top. Exposed standalone as loader.ChaseLevDeque for direct stress
testing — ctypes releases the GIL during calls, so the Python threads
below genuinely race the C code paths.
"""

import threading

import pytest

from hpx_tpu.native.loader import NativePool, native_lib

pytestmark = pytest.mark.skipif(native_lib() is None,
                                reason="native library unavailable")


def _deque():
    from hpx_tpu.native.loader import ChaseLevDeque
    return ChaseLevDeque()


class TestCLDeque:
    def test_owner_lifo_thief_fifo(self):
        d = _deque()
        for i in (1, 2, 3):
            d.push(i)
        assert len(d) == 3
        assert d.take() == 3          # owner end: LIFO
        assert d.steal() == 1         # thief end: FIFO
        assert d.take() == 2
        assert d.take() is None
        assert d.steal() is None
        d.close()

    def test_growth_past_initial_capacity(self):
        d = _deque()
        n = 10_000                    # initial cap 64: multiple doublings
        for i in range(1, n + 1):
            d.push(i)
        assert len(d) == n
        got = [d.take() for _ in range(n)]
        assert got == list(range(n, 0, -1))
        d.close()

    def test_owner_vs_thieves_stress(self):
        """One owner push/take thread races three stealers; every item
        must be claimed exactly once, none lost, none duplicated."""
        import time
        d = _deque()
        n = 10_000
        taken, stolen = [], [[] for _ in range(3)]
        stop = threading.Event()

        def owner():
            for i in range(1, n + 1):
                d.push(i)
                if i % 3 == 0:        # interleave owner takes
                    v = d.take()
                    if v is not None:
                        taken.append(v)
            while True:               # drain whatever the thieves left
                v = d.take()
                if v is None:
                    break
                taken.append(v)
            stop.set()

        def thief(out):
            while not stop.is_set() or len(d):
                v = d.steal()
                if v is not None:
                    out.append(v)
                else:
                    time.sleep(0)     # yield: don't starve the owner

        ts = [threading.Thread(target=thief, args=(s,)) for s in stolen]
        ot = threading.Thread(target=owner)
        for t in ts:
            t.start()
        ot.start()
        ot.join(120)
        for t in ts:
            t.join(120)
        assert not ot.is_alive() and not any(t.is_alive() for t in ts)
        # post-stop sweep: the owner may have set `stop` between a
        # thief's steal and its append; steal anything left
        while True:
            v = d.steal()
            if v is None:
                break
            taken.append(v)
        everything = sorted(taken + sum(stolen, []))
        assert everything == list(range(1, n + 1)), (
            len(everything), n)
        d.close()


class TestNativePoolLockFree:
    def test_all_tasks_run_exactly_once(self):
        p = NativePool(4)
        n = 20_000
        hits = []
        lock = threading.Lock()
        done = threading.Event()

        def task(i):
            with lock:
                hits.append(i)
                if len(hits) == n:
                    done.set()

        try:
            for i in range(n):
                p.submit(task, i)
            assert done.wait(60), f"only {len(hits)}/{n} ran"
            assert sorted(hits) == list(range(n))
            # `executed` increments AFTER the task body (done.set fires
            # inside the last body) — give the counter a beat to land
            import time
            for _ in range(500):
                if p.stats()["executed"] >= n:
                    break
                time.sleep(0.01)
            assert p.stats()["executed"] >= n
        finally:
            p.shutdown()

    def test_worker_submits_use_owner_fast_path(self):
        """Tasks that spawn subtasks from INSIDE workers exercise the
        lock-free owner push/take path (external submits only stage
        through the inbox)."""
        p = NativePool(2)
        total = 1 + 4 + 16
        count = [0]
        lock = threading.Lock()
        done = threading.Event()

        def spawn(depth):
            with lock:
                count[0] += 1
                if count[0] == total:
                    done.set()
            if depth < 2:
                for _ in range(4):
                    p.submit(spawn, depth + 1)

        try:
            p.submit(spawn, 0)
            assert done.wait(60), count[0]
            assert count[0] == total
        finally:
            p.shutdown()


class TestSubmitMany:
    def test_batch_runs_all_exactly_once(self):
        p = NativePool(4)
        n = 20_000
        hits = []
        lock = threading.Lock()
        done = threading.Event()

        def task(i):
            with lock:
                hits.append(i)
                if len(hits) == n:
                    done.set()

        try:
            p.submit_many([(task, (i,), {}) for i in range(n)])
            assert done.wait(60), f"only {len(hits)}/{n} ran"
            assert sorted(hits) == list(range(n))
        finally:
            p.shutdown()

    def test_batch_from_inside_worker_uses_owner_deque(self):
        p = NativePool(2)
        total = 1 + 64
        count = [0]
        lock = threading.Lock()
        done = threading.Event()

        def leaf():
            with lock:
                count[0] += 1
                if count[0] == total:
                    done.set()

        def root():
            leaf()
            p.submit_many([(leaf, (), {})] * 64)

        try:
            p.submit(root)
            assert done.wait(60), count[0]
        finally:
            p.shutdown()

    def test_empty_batch_is_noop(self):
        p = NativePool(1)
        try:
            p.submit_many([])
            assert p.stats()["pending"] == 0
        finally:
            p.shutdown()

    def test_batch_interleaves_with_single_submits(self):
        p = NativePool(4)
        n = 5_000
        seen = set()
        lock = threading.Lock()
        done = threading.Event()

        def task(i):
            with lock:
                seen.add(i)
                if len(seen) == 3 * n:
                    done.set()

        try:
            p.submit_many([(task, (i,), {}) for i in range(n)])
            for i in range(n, 2 * n):
                p.submit(task, i)
            p.submit_many([(task, (i,), {}) for i in range(2 * n, 3 * n)])
            assert done.wait(60), len(seen)
            assert seen == set(range(3 * n))
        finally:
            p.shutdown()
