"""Live observability (PR 18): exemplar reservoirs on the SLO
histograms, OpenMetrics exposition with exemplars, SLO burn-rate
alerting at the flush boundary, the alert-aware tuner hold, the
flight --list CLI, and the opsplane HTTP endpoint — including the
tier-1 smoke that boots the plane on an ephemeral port during a real
ContinuousServer run.
"""

import json
import re
import urllib.request

import jax
import pytest

from hpx_tpu.core import config_schema
from hpx_tpu.core.config import runtime_config
from hpx_tpu.core.config_schema import Tunable
from hpx_tpu.models import transformer as tfm
from hpx_tpu.models.serving import ContinuousServer
from hpx_tpu.svc import exemplars, faultinject, flight, metrics, opsplane
from hpx_tpu.svc.autotune import AdaptiveTuner, KnobBinding, TuneSignals
from hpx_tpu.svc.metrics import HistogramCounter
from hpx_tpu.svc.slo_alerts import (
    DEFAULT_RULES,
    SloAlerts,
    SloRule,
    parse_rules,
)

CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4, head_dim=8,
                            n_layers=2, d_ff=64)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.PRNGKey(0))


@pytest.fixture()
def knobs():
    """Set config knobs for one test; restore each touched key to its
    declared schema default afterwards."""
    cfg = runtime_config()
    touched = []

    def set_(key, value):
        touched.append(key)
        cfg.set(key, value)

    yield set_
    defaults = config_schema.all_keys()
    for key in touched:
        d = defaults[key].default
        cfg.set(key, "" if d is None else d)


# ---------------------------------------------------------------------------
# exemplar reservoirs
# ---------------------------------------------------------------------------

def _record_seq(h, seq):
    for rid, v in seq:
        h.record(v, rid=rid)


def test_reservoir_deterministic_replacement():
    """Same record sequence in, same exemplars out — slot n%per_bucket,
    no RNG. Two independent hist+reservoir pairs agree exactly on
    (rid, value, bucket)."""
    seq = [(f"r{i}", v) for i, v in enumerate(
        [0.01, 0.5, 2.0, 0.02, 3.0, 2.5, 0.03, 4.0, 2.2, 3.3] * 5)]
    got = []
    for _ in range(2):
        h = HistogramCounter()
        ex = exemplars.attach(h, per_bucket=2, quantile=0.8, refresh=4)
        _record_seq(h, seq)
        got.append([(e["rid"], e["value"], e["bucket"])
                    for e in ex.exemplars()])
    assert got[0] == got[1]
    assert got[0]                        # something was captured


def test_reservoir_ring_keeps_newest_per_bucket():
    h = HistogramCounter()
    ex = exemplars.attach(h, per_bucket=2, quantile=0.0, refresh=1)
    # five offers to one bucket: ring of 2 keeps the last two, ordered
    # oldest->newest; newest_per_bucket picks the final one
    for i in range(5):
        h.record(1.0, rid=f"r{i}")
    idx = h._index(1.0)
    rids = [e["rid"] for e in ex.exemplars()]
    assert rids == ["r3", "r4"]
    assert ex.newest_per_bucket()[idx]["rid"] == "r4"
    assert ex.captured == 5 and ex.offered == 5


def test_reservoir_threshold_skips_below_tail():
    """With 20% of mass in the top bucket and quantile=0.9, the p90
    lands in the top bucket — low-bucket records are not tail samples
    and are not captured."""
    h = HistogramCounter()
    ex = exemplars.attach(h, per_bucket=4, quantile=0.9, refresh=1)
    for i in range(80):
        h.record(0.001, rid=f"lo{i}")
    for i in range(20):
        h.record(4.0, rid=f"hi{i}")
    before = ex.captured
    h.record(0.001, rid="late-lo")       # below the p90 bucket
    assert ex.captured == before
    h.record(4.0, rid="late-hi")         # tail bucket
    assert ex.captured == before + 1
    assert all(not e["rid"].startswith("late-lo")
               for e in ex.exemplars())


def test_attach_from_config_gate(knobs):
    h = HistogramCounter()
    assert exemplars.attach_from_config({"e2e": h}) == []
    assert h._ex is None                 # off by default: no reservoir
    knobs("hpx.obs.exemplars", "1")
    knobs("hpx.obs.exemplars_per_bucket", "2")
    knobs("hpx.obs.exemplar_quantile", "0.5")
    attached = exemplars.attach_from_config({"e2e": h})
    assert len(attached) == 1 and h._ex is attached[0]
    assert h._ex.per_bucket == 2 and h._ex.quantile == 0.5


def test_snapshot_embeds_exemplars_and_stays_mergeable():
    h = HistogramCounter()
    exemplars.attach(h, per_bucket=2, quantile=0.0, refresh=1)
    h.record(0.25, rid="req-9")
    snap = h.snapshot()
    assert snap["exemplars"][0]["rid"] == "req-9"
    # the extra key must not break the snapshot algebra
    h2 = HistogramCounter.from_snapshot(snap)
    assert h2.count == 1
    d = h.delta(snap)
    assert d["count"] == 0 and "exemplars" not in d
    bare = HistogramCounter()
    bare.record(1.0)
    assert "exemplars" not in bare.snapshot()


# ---------------------------------------------------------------------------
# OpenMetrics exposition
# ---------------------------------------------------------------------------

def test_exposition_negotiation():
    om, ct = metrics.negotiate_exposition(
        "application/openmetrics-text; version=1.0.0")
    assert om and ct == metrics.OPENMETRICS_CONTENT_TYPE
    for accept in (None, "", "text/plain", "*/*"):
        om, ct = metrics.negotiate_exposition(accept)
        assert not om and ct == metrics.PROM_CONTENT_TYPE


def test_prom_escape_edge_cases():
    assert metrics._prom_escape('a\\b"c\nd') == 'a\\\\b\\"c\\nd'
    assert metrics._prom_escape("plain#0") == "plain#0"   # no-op


def test_exposition_exact_text_both_formats():
    """The pinned wire format: default v0.0.4 output is byte-stable
    (no exemplars, no # EOF); OpenMetrics adds the exemplar clause on
    the tail bucket row and terminates with # EOF."""
    import hpx_tpu.svc.performance_counters as pc
    h = HistogramCounter()
    ex = exemplars.attach(h, per_bucket=1, quantile=0.0, refresh=1)
    h.record(0.25, rid="req-42")
    idx = h._index(0.25)
    ex._slots[idx][0]["ts"] = 1234.5     # pin wall time for exact text
    names = metrics.register_histogram(
        "serving", "latency/obs-test-s", h, "obs#0", quantiles=())
    try:
        le = h.bucket_upper(idx)
        pat = "/serving{locality#*/obs#0}/latency/obs-test-s"
        plain = metrics.render_prometheus(pattern=pat)
        om = metrics.render_prometheus(pattern=pat, openmetrics=True)
        metric = "hpx_serving_latency_obs_test_s"
        bucket = (f'{metric}_bucket{{le="{le:.9g}",locality="0",'
                  f'instance="obs#0"}} 1')
        assert plain == (
            f"# TYPE {metric} histogram\n"
            f"{bucket}\n"
            f'{metric}_bucket{{le="+Inf",locality="0",'
            f'instance="obs#0"}} 1\n'
            f"{metric}_sum{{locality=\"0\",instance=\"obs#0\"}} 0.25\n"
            f"{metric}_count{{locality=\"0\",instance=\"obs#0\"}} 1\n")
        assert om == (
            f"# TYPE {metric} histogram\n"
            f'{bucket} # {{rid="req-42"}} 0.25 1234.500\n'
            f'{metric}_bucket{{le="+Inf",locality="0",'
            f'instance="obs#0"}} 1\n'
            f"{metric}_sum{{locality=\"0\",instance=\"obs#0\"}} 0.25\n"
            f"{metric}_count{{locality=\"0\",instance=\"obs#0\"}} 1\n"
            "# EOF\n")
    finally:
        for n in names:
            pc.unregister_counter(n)


# ---------------------------------------------------------------------------
# SLO burn-rate alerting
# ---------------------------------------------------------------------------

def test_parse_rules():
    rules = parse_rules("e2e:1.0:0.95, decode_stall:0.25:0.99")
    assert rules == (SloRule("e2e", 1.0, 0.95),
                     SloRule("decode_stall", 0.25, 0.99))
    assert parse_rules("") == DEFAULT_RULES


def _scripted_burn_run():
    """One scripted incident against synthetic clocks: a long good
    history, a brief spike the slow window gates, a sustained
    regression that fires once, then recovery that clears."""
    h = HistogramCounter()
    a = SloAlerts({"e2e": h}, rules=(SloRule("e2e", 1.0, 0.9),),
                  fast_s=10.0, slow_s=60.0,
                  burn_fast=3.0, burn_slow=2.0, interval_s=0.0,
                  clock=lambda: 0.0)
    t = 0.0
    # 60s of healthy traffic: 2 good samples / 5s
    for _ in range(12):
        h.record(0.1)
        h.record(0.2)
        t += 5.0
        a.evaluate(t)
    assert a.fired == 0
    # a brief spike: fast burn is high but the slow window still
    # averages it away — no fire (the flapping gate)
    for _ in range(4):
        h.record(5.0)
    t += 5.0
    a.evaluate(t)
    st = a.state()["rules"]["e2e<=1s@0.9"]
    assert st["state"] == "ok" and st["burn_fast"] >= 3.0
    # sustained regression: both windows burn — exactly one fire
    for _ in range(6):
        for _ in range(4):
            h.record(5.0)
        t += 5.0
        a.evaluate(t)
    assert a.fired == 1 and a.active() == 1
    # recovery: healthy samples drain the fast window — one clear
    for _ in range(4):
        for _ in range(8):
            h.record(0.1)
        t += 5.0
        a.evaluate(t)
    assert a.cleared == 1 and a.active() == 0
    assert a.fired == 1                  # never re-fired
    return a.decisions


def test_burn_rate_fsm_fires_once_and_is_deterministic():
    d1 = _scripted_burn_run()
    d2 = _scripted_burn_run()
    assert [e["action"] for e in d1] == ["fire", "clear"]
    assert d1 == d2


def test_bad_fraction_counts_threshold_bucket_as_good():
    h = HistogramCounter()
    base = h.snapshot()
    h.record(0.9)                        # same bucket as threshold 1.0
    h.record(8.0)                        # clearly bad
    frac, n = SloAlerts._bad_fraction(h, h.snapshot(), base, 1.0)
    assert n == 2 and frac == 0.5


def test_server_alert_fires_once_under_seeded_regression(
        params, knobs, tmp_path):
    """The live path: a seeded decode-fault burst inflates decode
    stalls (retry backoff) past the rule threshold — the flush-boundary
    evaluator fires EXACTLY once, captures a slo_alert flight bundle,
    and clears after recovery."""
    knobs("hpx.obs.alerts", "1")
    knobs("hpx.obs.alert_rules", "decode_stall:0.08:0.9")
    knobs("hpx.obs.alert_fast_s", "0.5")
    knobs("hpx.obs.alert_slow_s", "1.5")
    knobs("hpx.obs.alert_burn_fast", "3")
    knobs("hpx.obs.alert_burn_slow", "1.5")
    knobs("hpx.obs.alert_interval_s", "0.02")
    knobs("hpx.flight.dir", str(tmp_path))
    knobs("hpx.serving.retry_backoff_s", "0.2")
    srv = ContinuousServer(params, CFG, slots=2, smax=64)
    assert srv._alerts is not None
    for p, m in [([3, 1, 4], 24), ([2, 7], 24), ([5, 6], 24)]:
        srv.submit(p, max_new=m)
    faultinject.install(faultinject.FaultInjector(
        seed=0, schedule={"decode": set(range(2, 16, 2))}))
    try:
        srv.run()
    finally:
        faultinject.uninstall()
    assert srv._alerts.fired == 1
    bundles = [n for n in tmp_path.iterdir()
               if n.name.endswith("-slo_alert.json")]
    assert len(bundles) == 1
    doc = json.loads(bundles[0].read_text())
    assert doc["trigger"]["kind"] == "slo_alert"
    assert doc["extra"]["rule"].startswith("decode_stall")
    # recovery: once the fast window drains past the fault burst,
    # healthy samples clear the alert — and it never re-fires
    import time
    time.sleep(0.6)
    for _ in range(5):
        srv.hist["decode_stall"].record(0.001)
    srv._alerts.evaluate()
    assert srv._alerts.active() == 0
    assert srv._alerts.cleared == 1 and srv._alerts.fired == 1


def test_alerts_off_is_none(params):
    srv = ContinuousServer(params, CFG, slots=2, smax=64)
    assert srv._alerts is None           # zero-overhead gate
    assert srv.hist["e2e"]._ex is None


# ---------------------------------------------------------------------------
# alert-aware tuner hold
# ---------------------------------------------------------------------------

def test_tuner_hold_blocks_new_probes_only():
    cell = {"k": 8}
    knob = KnobBinding(
        "k", Tunable(lo=1, hi=256, step=2, geometric=True),
        lambda: cell["k"], lambda v: cell.__setitem__("k", v))
    t = AdaptiveTuner([knob], interval_ticks=1, cooldown_ticks=1)
    sig = TuneSignals(tok_rate=100.0, stall_p99=0.0, queue_depth=0.0)
    dec = t.evaluate(sig, hold=True)
    assert dec["action"] == "hold" and t.holds == 1
    assert t._phase != "probe" and cell["k"] == 8
    # without the hold a probe starts; a hold DURING the probe still
    # lets it settle (the in-flight experiment is not abandoned)
    dec = t.evaluate(sig)
    assert dec["action"] == "probe" and t._phase == "probe"
    moved = cell["k"]
    assert moved != 8
    dec = t.evaluate(sig, hold=True)
    assert dec["action"] in ("accept", "revert")
    assert t._phase != "probe"
    # the hold landed in the recorded sample stream for exact replay
    assert any(s.get("alert_hold") for s in t._signals)


# ---------------------------------------------------------------------------
# flight --list CLI
# ---------------------------------------------------------------------------

def test_flight_list_cli(knobs, tmp_path, capsys):
    knobs("hpx.flight.dir", str(tmp_path))
    flight.record_fault("slo_alert", site="slo/e2e<=1s@0.9")
    import time
    time.sleep(0.02)                     # distinct mtimes for the sort
    flight.record_fault("manual", site="cli")
    assert flight.main(["--list"]) == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 2
    # newest first; reason/site/schema on every line
    assert "reason=manual" in lines[0]
    assert "reason=slo_alert" in lines[1]
    assert "slo_alert" in lines[1].split()[0]   # kind survives sanitize
    assert all("schema=hpx_tpu.flight.v1" in ln for ln in lines)
    assert flight.main(["--list", "--tail", "1"]) == 0
    assert len(capsys.readouterr().out.strip().splitlines()) == 1
    # bundle_index carries the same rows /flightz serves
    idx = flight.bundle_index()
    assert [e["reason"] for e in idx] == ["manual", "slo_alert"]
    # no args: usage + exit 2, the dump subcommand still works
    assert flight.main([]) == 2


# ---------------------------------------------------------------------------
# opsplane smoke: ephemeral port during a real serving run
# ---------------------------------------------------------------------------

def _get(url, accept=None):
    req = urllib.request.Request(url)
    if accept:
        req.add_header("Accept", accept)
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.status, r.headers.get("Content-Type"), r.read().decode()


_PROM_LINE = re.compile(
    r'^[A-Za-z_:][A-Za-z0-9_:]*(\{[^}]*\})? \S+'
    r'( # \{[^}]*\} \S+ \S+)?$')


def test_opsplane_smoke_during_serving_run(params, knobs):
    """The CI tier-1 smoke: boot the plane on an ephemeral port, run a
    real ContinuousServer with exemplars+alerts on, and scrape every
    route while the process is live."""
    knobs("hpx.obs.port", "0")
    knobs("hpx.obs.exemplars", "1")
    knobs("hpx.obs.exemplar_quantile", "0.5")
    knobs("hpx.obs.alerts", "1")
    try:
        srv = ContinuousServer(params, CFG, slots=2, smax=64)
        plane = opsplane.active_opsplane()
        assert plane is not None and plane.port > 0
        a = srv.submit([3, 1, 4], max_new=6)
        b = srv.submit([2, 7], max_new=4)
        out = srv.run()
        assert set(out) == {a, b}

        # /varz default: every line parses as v0.0.4 text, no # EOF
        code, ctype, body = _get(f"{plane.url}/varz")
        assert code == 200 and ctype == metrics.PROM_CONTENT_TYPE
        lines = body.strip().splitlines()
        assert lines and "# EOF" not in body
        for ln in lines:
            assert ln.startswith("# ") or _PROM_LINE.match(ln), ln

        # /varz negotiated: OpenMetrics with terminator; exemplar rids
        # resolve to live request timelines
        code, ctype, body = _get(f"{plane.url}/varz",
                                 accept=metrics.OPENMETRICS_CONTENT_TYPE)
        assert code == 200 and ctype == metrics.OPENMETRICS_CONTENT_TYPE
        assert body.rstrip().endswith("# EOF")
        ex_rids = [int(m) for m in re.findall(r'# \{rid="(\d+)"\}', body)]
        assert ex_rids
        for rid in set(ex_rids):
            names = {e["name"] for e in srv.timeline.events(rid)}
            assert "submit" in names and "retire" in names

        # /statusz: valid JSON with the tune + tier flight snapshots
        # and this server's provider section
        code, _, body = _get(f"{plane.url}/statusz")
        doc = json.loads(body)
        assert code == 200 and "tune" in doc and "tier" in doc
        sect = doc["providers"][f"serving/{srv.counter_instance}"]
        assert sect["kind"] == "server" and sect["slots"] == 2
        assert sect["timeline_rids"] == 2 and sect["live_slots"] == 0
        assert "alerts" in sect

        # /healthz: ok (nothing fired), /tracez + /flightz respond,
        # unknown routes 404
        code, _, body = _get(f"{plane.url}/healthz")
        assert code == 200 and json.loads(body)["status"] == "ok"
        code, _, body = _get(f"{plane.url}/tracez")
        assert code == 200 and "spans" in json.loads(body)
        code, _, body = _get(f"{plane.url}/flightz")
        assert code == 200 and "bundles" in json.loads(body)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{plane.url}/nope")
        assert ei.value.code == 404

        # provider prunes after the server dies
        del srv, sect
        import gc
        gc.collect()
        code, _, body = _get(f"{plane.url}/statusz")
        assert not any(k.startswith("serving/")
                       for k in json.loads(body)["providers"])
    finally:
        opsplane.stop_opsplane()


def test_opsplane_off_by_default(params):
    assert opsplane.ensure_opsplane() is None
    assert opsplane.active_opsplane() is None
