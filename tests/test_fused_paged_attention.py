"""Fused Pallas paged decode attention vs the XLA gather oracle.

`ops/attention_pallas.fused_paged_attention` walks the block table
in-kernel; `ops/paged_attention`'s gather formulation is the DESIGNATED
oracle it is pinned against. The numerics contract (see the kernel's
section comment): bitwise-equal scores and softmax, final logits within
~1 ulp (the PV contraction is the kernel's 2-D dot vs XLA's batched
einsum), and therefore EXACT tokens — which the server-level tests here
assert across dense/paged-gather/paged-fused, greedy/sampled,
speculative/non-speculative, bf16 and int8 KV.

`fused_paged_online_attention` (paged_kernel="fused_online") carries a
WEAKER, tolerance-budgeted contract: the online-softmax recurrence
renormalizes per block, so logits drift O(eps * num_blocks) from the
oracle — a few f32 ulp at test extents — while greedy tokens stay
identical on the acceptance sweep. Its VMEM scratch is O(block): the
(acc, m, l) carry never allocates a sequence-extent array, which
`paged_online_scratch_shapes` makes checkable by construction.

fp8 (e4m3) KV pools reuse the int8 sidecar plumbing wholesale: same
per-(block, kv-head) absmax scales, same `*_q` scatter OOB-drop
semantics, same dequant-at-gather on both formulations — so fused
vs gather stays ulp-tight under fp8 even though fp8 vs full precision
is a lossy ~2^-4 relative grid. All kernel runs use interpret mode
off-TPU, so this file is CPU-CI green by construction.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hpx_tpu.models import transformer as tfm
from hpx_tpu.models.serving import ContinuousServer
from hpx_tpu.ops import attention_pallas as ap
from hpx_tpu.ops.paged_attention import (
    paged_decode_attention,
    paged_window_attention,
    quantize_blocks,
    scatter_window_q,
)

CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4, head_dim=8,
                            n_layers=2, d_ff=64)

REQS = [dict(prompt=[3, 1, 4], max_new=9),
        dict(prompt=[2, 7], max_new=5),
        dict(prompt=[5, 6, 7, 8, 9], max_new=12),
        dict(prompt=[1], max_new=7),
        dict(prompt=[9, 9, 2, 1], max_new=3),
        dict(prompt=[4, 4], max_new=10)]

SAMPLED = [dict(prompt=[3, 1, 4], max_new=8, temperature=0.9,
                key=jax.random.PRNGKey(7)),
           dict(prompt=[2, 7, 9], max_new=8, temperature=0.7,
                key=jax.random.PRNGKey(8)),
           dict(prompt=[5, 5], max_new=6, temperature=1.3,
                key=jax.random.PRNGKey(9))]


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.PRNGKey(0))


# -- op level: fused vs gather ----------------------------------------------

def _paged_state(bs, maxb, B=3, nkv=2, nq=4, hd=8, w=1,
                 dtype=jnp.float32, seed=0):
    """Random pools + a shuffled table (logical != physical) + ragged
    positions, one slot pinned to the partial-first-block corner."""
    rng = np.random.default_rng(seed)
    nb = B * maxb + 2
    kp = jnp.asarray(rng.standard_normal((nb, bs, nkv, hd)), dtype)
    vp = jnp.asarray(rng.standard_normal((nb, bs, nkv, hd)), dtype)
    perm = rng.permutation(np.arange(1, nb))[:B * maxb]
    table = jnp.asarray(perm.reshape(B, maxb).astype(np.int32))
    pos = rng.integers(0, maxb * bs - w, size=B).astype(np.int32)
    pos[0] = 1                              # nearly-empty slot
    pos = jnp.asarray(pos)
    q = jnp.asarray(rng.standard_normal((B, w, nq, hd)), dtype)
    knew = rng.standard_normal((B, nkv, hd) if w == 1
                               else (B, w, nkv, hd))
    vnew = rng.standard_normal((B, nkv, hd) if w == 1
                               else (B, w, nkv, hd))
    return (kp, vp, table, pos, q,
            jnp.asarray(knew, dtype), jnp.asarray(vnew, dtype))


@pytest.mark.parametrize("bs", [8, 16, 32])
def test_fused_decode_matches_gather(bs):
    kp, vp, table, pos, q, kn, vn = _paged_state(bs, maxb=3, seed=bs)
    ag, kg, vg = paged_decode_attention(q, kn, vn, kp, vp, table, pos)
    af, kf, vf = paged_decode_attention(q, kn, vn, kp, vp, table, pos,
                                        fused=True, interpret=True)
    # identical writes (same scatter either way), ulp-tight attention
    assert (np.asarray(kg) == np.asarray(kf)).all()
    assert (np.asarray(vg) == np.asarray(vf)).all()
    np.testing.assert_allclose(np.asarray(ag), np.asarray(af),
                               rtol=2e-6, atol=2e-6)


@pytest.mark.parametrize("bs", [8, 16])
def test_fused_window_matches_gather(bs):
    # W=4 verify window, GQA (4 q heads over 2 kv heads), ragged pos0
    kp, vp, table, pos, q, kn, vn = _paged_state(bs, maxb=3, w=4,
                                                 seed=100 + bs)
    ag, _, _ = paged_window_attention(q, kn, vn, kp, vp, table, pos)
    af, _, _ = paged_window_attention(q, kn, vn, kp, vp, table, pos,
                                      fused=True, interpret=True)
    np.testing.assert_allclose(np.asarray(ag), np.asarray(af),
                               rtol=2e-6, atol=2e-6)


def test_fused_bf16_stays_within_one_ulp():
    kp, vp, table, pos, q, kn, vn = _paged_state(16, maxb=2, seed=5,
                                                 dtype=jnp.bfloat16)
    ag, _, _ = paged_decode_attention(q, kn, vn, kp, vp, table, pos)
    af, _, _ = paged_decode_attention(q, kn, vn, kp, vp, table, pos,
                                      fused=True, interpret=True)
    # scores+softmax are bitwise-equal; the final bf16 PV cast may
    # differ by one bf16 ulp where the f32 dots rounded apart
    np.testing.assert_allclose(np.asarray(ag, np.float32),
                               np.asarray(af, np.float32),
                               rtol=1e-2, atol=1e-2)


@pytest.mark.parametrize("bs", [8, 16])
def test_fused_int8_matches_gather_int8(bs):
    kp, vp, table, pos, q, kn, vn = _paged_state(bs, maxb=3,
                                                 seed=200 + bs)
    kq, ks = quantize_blocks(kp)
    vq, vs = quantize_blocks(vp)
    ag, kg, vg, ksg, vsg = paged_decode_attention(
        q, kn, vn, kq, vq, table, pos, k_scale=ks, v_scale=vs)
    af, kf, vf, ksf, vsf = paged_decode_attention(
        q, kn, vn, kq, vq, table, pos, k_scale=ks, v_scale=vs,
        fused=True, interpret=True)
    # int8 pools and scales update identically; both paths dequantize
    # with the same elementwise ops, so attention stays ulp-tight
    assert (np.asarray(kg) == np.asarray(kf)).all()
    assert (np.asarray(ksg) == np.asarray(ksf)).all()
    assert (np.asarray(vsg) == np.asarray(vsf)).all()
    np.testing.assert_allclose(np.asarray(ag), np.asarray(af),
                               rtol=2e-6, atol=2e-6)


# -- op level: fused_online vs gather ---------------------------------------

@pytest.mark.parametrize("bs", [8, 16, 32])
def test_online_decode_matches_gather(bs):
    """The tolerance-budgeted contract: block-streamed online softmax
    drifts O(eps * num_blocks) from the oracle, a few f32 ulp here."""
    kp, vp, table, pos, q, kn, vn = _paged_state(bs, maxb=3,
                                                 seed=300 + bs)
    ag, kg, vg = paged_decode_attention(q, kn, vn, kp, vp, table, pos)
    ao, ko, vo = paged_decode_attention(q, kn, vn, kp, vp, table, pos,
                                        fused="online", interpret=True)
    assert (np.asarray(kg) == np.asarray(ko)).all()
    assert (np.asarray(vg) == np.asarray(vo)).all()
    np.testing.assert_allclose(np.asarray(ag), np.asarray(ao),
                               rtol=5e-6, atol=5e-6)


@pytest.mark.parametrize("bs", [8, 16])
def test_online_window_matches_gather(bs):
    # W=4 verify window, GQA (4 q heads over 2 kv heads), ragged pos0 —
    # the per-window-row horizon mask is shared with the bitwise kernel
    kp, vp, table, pos, q, kn, vn = _paged_state(bs, maxb=3, w=4,
                                                 seed=400 + bs)
    ag, _, _ = paged_window_attention(q, kn, vn, kp, vp, table, pos)
    ao, _, _ = paged_window_attention(q, kn, vn, kp, vp, table, pos,
                                      fused="online", interpret=True)
    np.testing.assert_allclose(np.asarray(ag), np.asarray(ao),
                               rtol=5e-6, atol=5e-6)


@pytest.mark.parametrize("bs", [8, 16])
def test_online_int8_matches_gather_int8(bs):
    kp, vp, table, pos, q, kn, vn = _paged_state(bs, maxb=3,
                                                 seed=500 + bs)
    kq, ks = quantize_blocks(kp)
    vq, vs = quantize_blocks(vp)
    ag, _, _, _, _ = paged_decode_attention(
        q, kn, vn, kq, vq, table, pos, k_scale=ks, v_scale=vs)
    ao, _, _, _, _ = paged_decode_attention(
        q, kn, vn, kq, vq, table, pos, k_scale=ks, v_scale=vs,
        fused="online", interpret=True)
    np.testing.assert_allclose(np.asarray(ag), np.asarray(ao),
                               rtol=5e-6, atol=5e-6)


def test_online_scratch_is_o_block():
    """The acceptance gate on the kernel's memory shape: the online
    kernel's VMEM scratch is the (acc, m, l) flash carry — a function
    of (padded q rows, head_dim) ONLY. No sequence extent reaches the
    allocation, by signature: a refactor that reintroduces an
    (S,)-shaped scratch has to change this function to get it."""
    import inspect
    sig = inspect.signature(ap.paged_online_scratch_shapes)
    assert list(sig.parameters) == ["wg_pad", "head_dim"]
    shapes = [tuple(s.shape)
              for s in ap.paged_online_scratch_shapes(8, 8)]
    assert shapes == [(8, 8), (8, 128), (8, 128)]
    # scratch does not grow with anything sequence-like
    assert shapes == [tuple(s.shape)
                      for s in ap.paged_online_scratch_shapes(8, 8)]
    big = [tuple(s.shape)
           for s in ap.paged_online_scratch_shapes(16, 128)]
    assert big == [(16, 128), (16, 128), (16, 128)]


# -- fp8 pools ---------------------------------------------------------------

def test_fp8_quantize_roundtrip():
    """e4m3 blocks under the per-(block, kv-head) absmax scale: the
    round-trip lands on the fp8 grid — relative error bounded by the
    format's 2^-4 mantissa step, never biased past one step."""
    rng = np.random.default_rng(9)
    rows = jnp.asarray(rng.standard_normal((4, 16, 2, 8)), jnp.float32)
    pq, sc = quantize_blocks(rows, jnp.float8_e4m3fn)
    assert pq.dtype == jnp.float8_e4m3fn
    assert sc.shape == (4, 2)                 # per-(block, kv-head)
    deq = (np.asarray(pq, np.float32)
           * np.asarray(sc)[:, None, :, None])
    orig = np.asarray(rows)
    err = np.abs(deq - orig)
    amax = np.abs(orig).max(axis=(1, 3), keepdims=True)
    assert (err <= np.abs(orig) * 2.0 ** -4 + amax * 2.0 ** -7).all()


def test_quantize_blocks_rejects_unknown_dtype():
    rows = jnp.zeros((1, 4, 1, 8), jnp.float32)
    with pytest.raises(ValueError, match="unsupported pool dtype"):
        quantize_blocks(rows, jnp.float16)


@pytest.mark.parametrize("bs", [8, 16])
def test_fused_fp8_matches_gather_fp8(bs):
    """Both formulations see the SAME e4m3 pools and dequantize with
    the same elementwise ops — fused vs gather stays ulp-tight even
    though fp8 vs full precision is lossy."""
    kp, vp, table, pos, q, kn, vn = _paged_state(bs, maxb=3,
                                                 seed=600 + bs)
    kq, ks = quantize_blocks(kp, jnp.float8_e4m3fn)
    vq, vs = quantize_blocks(vp, jnp.float8_e4m3fn)
    assert kq.dtype == jnp.float8_e4m3fn
    ag, kg, vg, ksg, vsg = paged_decode_attention(
        q, kn, vn, kq, vq, table, pos, k_scale=ks, v_scale=vs)
    assert kg.dtype == jnp.float8_e4m3fn      # frontier RMW kept fp8
    af, kf, vf, ksf, vsf = paged_decode_attention(
        q, kn, vn, kq, vq, table, pos, k_scale=ks, v_scale=vs,
        fused=True, interpret=True)
    ao, _, _, _, _ = paged_decode_attention(
        q, kn, vn, kq, vq, table, pos, k_scale=ks, v_scale=vs,
        fused="online", interpret=True)
    assert (np.asarray(kg, np.float32)
            == np.asarray(kf, np.float32)).all()
    assert (np.asarray(ksg) == np.asarray(ksf)).all()
    assert (np.asarray(vsg) == np.asarray(vsf)).all()
    np.testing.assert_allclose(np.asarray(ag), np.asarray(af),
                               rtol=2e-6, atol=2e-6)
    np.testing.assert_allclose(np.asarray(ag), np.asarray(ao),
                               rtol=5e-6, atol=5e-6)


# -- quantized scatter: OOB drop regression ---------------------------------

def test_scatter_window_q_oob_drops_rows_and_scales():
    """A window running past the table's extent must corrupt NOTHING:
    not the frontier block's content via a clamped write, and not any
    block's scale via the sidecar's own scatter."""
    bs, maxb, nkv, hd = 4, 2, 2, 8
    rng = np.random.default_rng(3)
    base = jnp.asarray(rng.standard_normal((3, bs, nkv, hd)),
                       jnp.float32)
    pq, sc = quantize_blocks(base)
    table = jnp.asarray([[0, 1]], jnp.int32)
    # pos0=6: rows 6,7 land in block 1; rows 8,9 are PAST the table
    vals = jnp.asarray(rng.standard_normal((1, 4, nkv, hd)), jnp.float32)
    npq, nsc = scatter_window_q(pq, sc, table, jnp.asarray([6]), vals)
    # unmapped/untouched blocks are bit-identical, scales included —
    # a clamped OOB write would have hit block 1's rows 0/1 instead
    assert (np.asarray(npq[0]) == np.asarray(pq[0])).all()
    assert (np.asarray(npq[2]) == np.asarray(pq[2])).all()
    assert (np.asarray(nsc[0]) == np.asarray(sc[0])).all()
    assert (np.asarray(nsc[2]) == np.asarray(sc[2])).all()
    deq = (np.asarray(npq[1], np.float32)
           * np.asarray(nsc[1])[None, :, None])
    orig = np.asarray(base[1])
    amax = np.abs(np.asarray(vals)).max() + np.abs(orig).max()
    tol = amax / 127 + 1e-6                 # one quantization step
    # the two in-range rows hold the window's first two values; the
    # block's pre-existing rows survive the RMW requantization
    np.testing.assert_allclose(deq[2], np.asarray(vals[0, 0]), atol=tol)
    np.testing.assert_allclose(deq[3], np.asarray(vals[0, 1]), atol=tol)
    np.testing.assert_allclose(deq[:2], orig[:2], atol=tol)


def test_scatter_window_q_oob_drops_fp8_rows_and_scales():
    """The same OOB-drop regression under fp8 pools: the sidecar
    plumbing is shared with int8, so a clamped write corrupting the
    frontier block (or its scale) would be a DTYPE-DISPATCH bug, not a
    new scatter bug — pin it anyway."""
    bs, maxb, nkv, hd = 4, 2, 2, 8
    rng = np.random.default_rng(13)
    base = jnp.asarray(rng.standard_normal((3, bs, nkv, hd)),
                       jnp.float32)
    pq, sc = quantize_blocks(base, jnp.float8_e4m3fn)
    table = jnp.asarray([[0, 1]], jnp.int32)
    vals = jnp.asarray(rng.standard_normal((1, 4, nkv, hd)),
                       jnp.float32)
    npq, nsc = scatter_window_q(pq, sc, table, jnp.asarray([6]), vals)
    assert npq.dtype == jnp.float8_e4m3fn
    assert (np.asarray(npq[0], np.float32)
            == np.asarray(pq[0], np.float32)).all()
    assert (np.asarray(npq[2], np.float32)
            == np.asarray(pq[2], np.float32)).all()
    assert (np.asarray(nsc[0]) == np.asarray(sc[0])).all()
    assert (np.asarray(nsc[2]) == np.asarray(sc[2])).all()
    deq = (np.asarray(npq[1], np.float32)
           * np.asarray(nsc[1])[None, :, None])
    orig = np.asarray(base[1])
    amax = np.abs(np.asarray(vals)).max() + np.abs(orig).max()
    tol = amax * 2.0 ** -4 + 1e-6           # one e4m3 grid step
    np.testing.assert_allclose(deq[2], np.asarray(vals[0, 0]), atol=tol)
    np.testing.assert_allclose(deq[3], np.asarray(vals[0, 1]), atol=tol)
    np.testing.assert_allclose(deq[:2], orig[:2], atol=tol)


# -- block-size resolution ---------------------------------------------------

def test_resolve_paged_block_order(monkeypatch):
    monkeypatch.setattr(ap, "_paged_blocks_table", {"hd8xint8": 32})
    monkeypatch.delenv("HPX_PAGED_BLOCK", raising=False)
    assert ap.resolve_paged_block(8, "int8") == 32     # measured table
    assert ap.resolve_paged_block(8, "bf16") == 16     # default
    monkeypatch.setenv("HPX_PAGED_BLOCK", "64")
    assert ap.resolve_paged_block(8, "int8") == 64     # env wins


def test_server_auto_block_size_honors_env(params, monkeypatch):
    monkeypatch.setenv("HPX_PAGED_BLOCK", "8")
    srv = ContinuousServer(params, CFG, slots=2, smax=64, paged=True)
    assert srv.block_size == 8


# -- server level: dense == gather == fused ---------------------------------

def _serve(params, reqs, **kw):
    srv = ContinuousServer(params, CFG, slots=3, smax=64, **kw)
    for r in reqs:
        srv.submit(**r)
    return srv.run(), srv


@pytest.mark.parametrize("reqs", [REQS, SAMPLED],
                         ids=["greedy", "sampled"])
def test_server_fused_matches_dense_and_gather(params, reqs):
    dense, _ = _serve(params, reqs)
    gather, _ = _serve(params, reqs, paged=True, paged_kernel="gather")
    fused, srv = _serve(params, reqs, paged=True, paged_kernel="fused")
    assert srv._paged_kernel == "fused"
    assert fused == gather == dense


@pytest.mark.parametrize("k", [1, 2])
def test_server_fused_spec_matches_nonspec(params, k):
    base, _ = _serve(params, REQS)
    spec, srv = _serve(params, REQS, paged=True, paged_kernel="fused",
                       spec=True, spec_k=k)
    assert spec == base
    assert srv.spec_stats()["emitted"] > 0


@pytest.mark.parametrize("reqs", [REQS, SAMPLED],
                         ids=["greedy", "sampled"])
def test_server_fused_online_matches_dense_and_gather(params, reqs):
    """The acceptance sweep's token gate: the online kernel's few-ulp
    logit drift never flips a token on this workload — greedy AND
    sampled, against BOTH the dense and the paged-gather servers."""
    dense, _ = _serve(params, reqs)
    gather, _ = _serve(params, reqs, paged=True, paged_kernel="gather")
    online, srv = _serve(params, reqs, paged=True,
                         paged_kernel="fused_online")
    assert srv._paged_kernel == "fused_online"
    assert srv._paged_fused == "online"
    assert online == gather == dense


@pytest.mark.parametrize("k", [1, 2])
def test_server_fused_online_spec_matches_nonspec(params, k):
    # spec-verify routes through the window entry point: the shared
    # per-window-row horizon mask must hold under the online carry too
    base, _ = _serve(params, REQS)
    spec, srv = _serve(params, REQS, paged=True,
                       paged_kernel="fused_online", spec=True, spec_k=k)
    assert spec == base
    assert srv.spec_stats()["emitted"] > 0


def test_server_int8_fused_matches_int8_gather_exactly(params):
    # the int8 hard contract: both formulations see the SAME quantized
    # pools and dequantize identically, so tokens are identical —
    # greedy AND sampled, speculative included
    for reqs in (REQS, SAMPLED):
        g, _ = _serve(params, reqs, paged=True, paged_kernel="gather",
                      kv_dtype="int8")
        f, _ = _serve(params, reqs, paged=True, paged_kernel="fused",
                      kv_dtype="int8")
        assert f == g
    gs, _ = _serve(params, REQS, paged=True, paged_kernel="gather",
                   kv_dtype="int8", spec=True, spec_k=2)
    fs, _ = _serve(params, REQS, paged=True, paged_kernel="fused",
                   kv_dtype="int8", spec=True, spec_k=2)
    assert fs == gs


def test_server_int8_greedy_matches_bf16(params):
    """Greedy token match under KV quantization on the fixed test
    workload — the ISSUE's acceptance workload. (Not a general
    guarantee: quantization MAY flip near-ties on other inputs; here
    the margins dominate one quantization step.)"""
    dense, _ = _serve(params, REQS)
    int8, srv = _serve(params, REQS, paged=True, kv_dtype="int8")
    assert srv._kv_dtype == "int8"
    assert int8 == dense


def test_server_int8_halves_hbm_read_bytes(params):
    """The tentpole's bandwidth claim at the accounting boundary:
    int8 blocks cost ~half of bf16 blocks (scale sidecars keep the
    ratio just above exactly 0.5), and the live hbm_read_stats()
    counters report exactly block_bytes() x mid-run occupancy for the
    pool dtype actually in use (f32 pools on CPU account as f32)."""
    from hpx_tpu.cache.block_allocator import block_bytes

    nkv, hd, nl = CFG.kv_heads, CFG.head_dim, CFG.n_layers
    stats = {}
    for kvd in ("bf16", "int8"):
        srv = ContinuousServer(params, CFG, slots=2, smax=64,
                               paged=True, kv_dtype=kvd)
        for r in REQS[:2]:
            srv.submit(**r)
        while srv.step():
            st = srv.hbm_read_stats()
            if st["hbm_read_bytes_per_token"]:
                stats.setdefault(kvd, (st, srv.block_size,
                                       srv._kv_acct_dtype()))
    for kvd in ("bf16", "int8"):
        st, bs, acct = stats[kvd]
        assert st["hbm_read_blocks_per_token"] > 0
        assert st["hbm_read_bytes_per_token"] == pytest.approx(
            st["hbm_read_blocks_per_token"]
            * block_bytes(bs, nkv, hd, acct, layers=nl))
    bs = stats["int8"][1]
    ratio = (block_bytes(bs, nkv, hd, "int8", layers=nl)
             / block_bytes(bs, nkv, hd, "bf16", layers=nl))
    assert 0.5 < ratio < 0.6


def test_server_fp8_kernels_agree_and_quarter_hbm_read_bytes(params):
    """The fp8 acceptance gates. Tokens: both kernels over the same
    e4m3 pools emit IDENTICAL tokens (fp8-vs-dense is lossy and makes
    no token claim — kernel-vs-kernel over shared pools is exact).
    Bytes: the live hbm_read_stats() counters account fp8 blocks at
    1 byte/elem + f32 sidecars; against this CPU run's f32 compute
    pools that is the tentpole's <= 0.30x bytes/token (on a bf16
    compute dtype the same pools sit at ~0.52x, like int8)."""
    from hpx_tpu.cache.block_allocator import block_bytes

    g, _ = _serve(params, REQS, paged=True, paged_kernel="gather",
                  kv_dtype="fp8")
    o, srv = _serve(params, REQS, paged=True,
                    paged_kernel="fused_online", kv_dtype="fp8")
    assert srv._kv_dtype == "fp8"
    assert o == g
    gs, _ = _serve(params, REQS, paged=True, paged_kernel="gather",
                   kv_dtype="fp8", spec=True, spec_k=2)
    os_, _ = _serve(params, REQS, paged=True,
                    paged_kernel="fused_online", kv_dtype="fp8",
                    spec=True, spec_k=2)
    assert os_ == gs

    nkv, hd, nl = CFG.kv_heads, CFG.head_dim, CFG.n_layers
    stats = {}
    for kvd in ("bf16", "fp8"):
        srv = ContinuousServer(params, CFG, slots=2, smax=64,
                               paged=True, kv_dtype=kvd)
        for r in REQS[:2]:
            srv.submit(**r)
        while srv.step():
            st = srv.hbm_read_stats()
            if st["hbm_read_bytes_per_token"]:
                stats.setdefault(kvd, (st, srv.block_size,
                                       srv._kv_acct_dtype()))
    for kvd in ("bf16", "fp8"):
        st, bs, acct = stats[kvd]
        assert st["hbm_read_blocks_per_token"] > 0
        assert st["hbm_read_bytes_per_token"] == pytest.approx(
            st["hbm_read_blocks_per_token"]
            * block_bytes(bs, nkv, hd, acct, layers=nl))
    assert stats["fp8"][2] == "fp8"
    bs, base_acct = stats["fp8"][1], stats["bf16"][2]
    ratio = (block_bytes(bs, nkv, hd, "fp8", layers=nl)
             / block_bytes(bs, nkv, hd, base_acct, layers=nl))
    if base_acct == "f32":                  # CPU CI: the 0.25x leg
        assert ratio <= 0.30
    else:                                   # bf16 pools: same as int8
        assert 0.5 < ratio < 0.6


def test_paged_kernel_knob_validation(params):
    with pytest.raises(ValueError, match="paged_kernel"):
        ContinuousServer(params, CFG, slots=2, smax=64, paged=True,
                         paged_kernel="nope")
    with pytest.raises(ValueError, match="kv_dtype"):
        ContinuousServer(params, CFG, slots=2, smax=64, paged=True,
                         kv_dtype="fp4")
    # near-miss dtype strings fail loudly, never silently serve bf16
    with pytest.raises(ValueError, match="kv_dtype"):
        ContinuousServer(params, CFG, slots=2, smax=64, paged=True,
                         kv_dtype="fp8_e5m2")
    # the knobs are paged-only
    with pytest.raises(ValueError):
        ContinuousServer(params, CFG, slots=2, smax=64,
                         paged_kernel="fused")
    with pytest.raises(ValueError):
        ContinuousServer(params, CFG, slots=2, smax=64, kv_dtype="int8")
    with pytest.raises(ValueError):
        ContinuousServer(params, CFG, slots=2, smax=64, kv_dtype="fp8")
