"""M8 tests: 2-D Jacobi ladder (config #5) + 2-D halo exchange +
BlockExecutor. All variants must agree with a numpy reference sweep."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hpx_tpu.models.jacobi2d import (JacobiParams, gather_blocks, init_grid,
                                     jacobi_dataflow, jacobi_serial,
                                     jacobi_sharded)
from hpx_tpu.testing import HPX_TEST, HPX_TEST_EQ


def numpy_jacobi(u0: np.ndarray, iterations: int) -> np.ndarray:
    u = u0.copy()
    for _ in range(iterations):
        new = u.copy()
        new[1:-1, 1:-1] = 0.25 * (u[:-2, 1:-1] + u[2:, 1:-1] +
                                  u[1:-1, :-2] + u[1:-1, 2:])
        u = new
    return u


@pytest.fixture(scope="module")
def params():
    return JacobiParams(nx=32, ny=24, nb=4, iterations=20)


@pytest.fixture(scope="module")
def expected(params):
    return numpy_jacobi(np.asarray(init_grid(params)), params.iterations)


def test_serial_matches_numpy(params, expected):
    got = np.asarray(jacobi_serial(params))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_dataflow_matches_numpy(params, expected):
    got = np.asarray(gather_blocks(jacobi_dataflow(params)))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_sharded_matches_numpy(params, expected, mesh2d):
    u, res = jacobi_sharded(params, mesh2d)
    np.testing.assert_allclose(np.asarray(u), expected, rtol=1e-5, atol=1e-6)
    HPX_TEST(float(np.asarray(res).reshape(-1)[0]) >= 0.0)
    # stays sharded over all 8 devices for the whole run
    HPX_TEST_EQ(len(u.sharding.device_set), 8)


def test_sharded_multiple_dispatches(params, expected, mesh2d):
    # 20 iterations in dispatches of 8 => 8+8+4 (remainder program)
    u, _ = jacobi_sharded(params, mesh2d, steps_per_dispatch=8)
    np.testing.assert_allclose(np.asarray(u), expected, rtol=1e-5, atol=1e-6)


def test_dataflow_single_block():
    # regression: nb=1 must keep BOTH Dirichlet rows fixed
    p = JacobiParams(nx=8, ny=8, nb=1, iterations=3)
    got = np.asarray(gather_blocks(jacobi_dataflow(p)))
    want = numpy_jacobi(np.asarray(init_grid(p)), p.iterations)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_residual_decreases(mesh2d):
    p = JacobiParams(nx=32, ny=24, iterations=1)
    from hpx_tpu.parallel.halo2d import sharded_jacobi_step
    from jax.sharding import NamedSharding, PartitionSpec as P
    u = jax.device_put(init_grid(p), NamedSharding(mesh2d, P("x", "y")))
    step = sharded_jacobi_step(mesh2d, p.grid)
    _, r1 = step(u)
    for _ in range(30):
        u, r = step(u)
    # Jacobi converges on Laplace: late residual < first residual
    HPX_TEST(float(np.asarray(r).reshape(-1)[0]) <
             float(np.asarray(r1).reshape(-1)[0]))


def test_edge_shift_zero_fills(mesh1d):
    """Non-periodic shift: boundary shard receives zeros (Dirichlet)."""
    from hpx_tpu.utils.jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P
    from hpx_tpu.parallel.halo2d import edge_shift

    x = jnp.arange(8, dtype=jnp.float32)

    def body(s):
        return edge_shift(s, "x", +1), edge_shift(s, "x", -1)

    fwd, bwd = jax.jit(shard_map(body, mesh=mesh1d, in_specs=P("x"),
                                 out_specs=(P("x"), P("x"))))(x)
    np.testing.assert_allclose(np.asarray(fwd), [0, 0, 1, 2, 3, 4, 5, 6])
    np.testing.assert_allclose(np.asarray(bwd), [1, 2, 3, 4, 5, 6, 7, 0])


class TestBlockExecutor:
    def test_round_robin_placement(self, devices):
        from hpx_tpu.exec.block import BlockExecutor
        from hpx_tpu.exec.tpu import Target
        ex = BlockExecutor([Target(d) for d in devices])
        HPX_TEST_EQ(ex.num_workers, 8)
        futs = ex.bulk_async_execute(lambda i: jnp.float32(i) * 2.0,
                                     list(range(16)))
        vals = [float(f.get()) for f in futs]
        HPX_TEST_EQ(vals, [2.0 * i for i in range(16)])

    def test_place_blocks(self, devices):
        from hpx_tpu.exec.block import place_blocks
        from hpx_tpu.exec.tpu import Target
        tgts = [Target(d) for d in devices[:4]]
        arrs = place_blocks([jnp.ones(4) * i for i in range(8)], tgts)
        for i, a in enumerate(arrs):
            assert next(iter(a.devices())) == devices[i % 4]

    def test_sync_and_async(self):
        from hpx_tpu.exec.block import BlockExecutor
        ex = BlockExecutor()
        HPX_TEST_EQ(float(ex.sync_execute(lambda: jnp.float32(7.0))), 7.0)
        HPX_TEST_EQ(float(ex.async_execute(
            lambda x: x + 1, jnp.float32(1.0)).get()), 2.0)
