"""Compile-count guard for the serving hot path.

The bucketed-prefill contract measured at the REAL boundary: jax's
``/jax/core/compile/backend_compile_duration`` monitoring event fires
per XLA backend compilation, so these tests pin the number of
compiles a mixed-length serving workload may trigger.  The bound is
O(buckets) + a constant (step/probe/splice programs plus first-touch
eager ops) — NOT O(distinct prompt lengths): pre-bucketing, 12
distinct lengths meant 12 prefill + 12 splice programs.

A dedicated config (d_ff=48) keeps these counts isolated from other
test modules warming the shared program cache in the same process."""

import jax
import numpy as np
import pytest

from hpx_tpu.models import transformer as tfm
from hpx_tpu.models.serving import ContinuousServer
from hpx_tpu.utils.compilemon import count_compiles

CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4, head_dim=8,
                            n_layers=2, d_ff=48)

PLENS = [3, 5, 9, 12, 17, 23, 4, 8, 16, 21, 6, 14]   # 12 mixed lengths


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.PRNGKey(1))


def _workload(srv, plens, seed):
    r = np.random.RandomState(seed)
    for plen in plens:
        srv.submit([int(t) for t in r.randint(1, CFG.vocab, plen)],
                   max_new=5)
    return srv.run()


def test_mixed_length_workload_compiles_o_buckets(params):
    with count_compiles() as c:
        srv = ContinuousServer(params, CFG, slots=4, smax=64,
                               prefill_chunk=8, prefill_buckets="4,8")
        out = _workload(srv, PLENS, seed=0)
    assert len(out) == len(PLENS)
    buckets = len(srv.prefill_buckets)
    # program builds: one chunk program per bucket + probe + splice +
    # step, NOT one per prompt length
    assert srv._prog_misses <= buckets + 3
    # total backend compiles: program builds plus a constant floor of
    # first-touch eager ops (argmax/sampling/zeros); 12 per-length
    # prefill+splice programs would blow far past this
    assert int(c) <= buckets + 22


def test_fused_paged_workload_compiles_o_buckets(params):
    """The fused-kernel paged server rides the same bucket ladder: the
    paged step/gather/splice programs are keyed on (kv_dtype,
    paged_kernel) — constants for a given server — so mixed-length
    traffic still compiles O(buckets), and flipping the pool dtype
    re-keys only the pool-dtype programs, never the bucket ladder."""
    with count_compiles() as c:
        srv = ContinuousServer(params, CFG, slots=4, smax=64,
                               prefill_chunk=8, prefill_buckets="4,8",
                               paged=True, paged_kernel="fused")
        out = _workload(srv, PLENS, seed=3)
    assert len(out) == len(PLENS)
    buckets = len(srv.prefill_buckets)
    # chunk program per bucket + probe + step + gather + splice
    assert srv._prog_misses <= buckets + 5
    assert int(c) <= buckets + 24
    # a fresh fused server, NEW prompt lengths: total reuse
    with count_compiles() as c2:
        srv2 = ContinuousServer(params, CFG, slots=4, smax=64,
                                prefill_chunk=8, prefill_buckets="4,8",
                                paged=True, paged_kernel="fused")
        _workload(srv2, [7, 11, 19, 22], seed=4)
    assert srv2._prog_misses == 0 and srv2._prog_hits > 0
    assert int(c2) <= 2
    # int8 pools: only the kv_dtype-keyed programs rebuild (step,
    # gather, splice); the bucket-ladder chunk programs are reused
    with count_compiles() as c3:
        srv3 = ContinuousServer(params, CFG, slots=4, smax=64,
                                prefill_chunk=8, prefill_buckets="4,8",
                                paged=True, paged_kernel="fused",
                                kv_dtype="int8")
        out3 = _workload(srv3, PLENS, seed=5)
    assert len(out3) == len(PLENS)
    assert srv3._prog_misses <= 5
    assert int(c3) <= 12
    # fp8 pools ride the SAME kv_dtype re-key budget — a new dtype
    # value, not a new keying dimension
    with count_compiles() as c4:
        srv4 = ContinuousServer(params, CFG, slots=4, smax=64,
                                prefill_chunk=8, prefill_buckets="4,8",
                                paged=True, paged_kernel="fused",
                                kv_dtype="fp8")
        out4 = _workload(srv4, PLENS, seed=5)
    assert len(out4) == len(PLENS)
    assert srv4._prog_misses <= 5
    assert int(c4) <= 12
    # fused_online: paged_kernel is already a key component, so the
    # online kernel re-keys the same <= 5 programs and rides the
    # bucket ladder untouched
    with count_compiles() as c5:
        srv5 = ContinuousServer(params, CFG, slots=4, smax=64,
                                prefill_chunk=8, prefill_buckets="4,8",
                                paged=True,
                                paged_kernel="fused_online")
        out5 = _workload(srv5, PLENS, seed=5)
    assert len(out5) == len(PLENS)
    assert srv5._prog_misses <= 5
    assert int(c5) <= 12


def test_sharded_paged_workload_compiles_o_buckets(params):
    """Sharded paged serving rides the SAME bucket ladder: the
    shard_map-wrapped step/verify and the mesh-keyed gather/splice
    programs are keyed on (kv_dtype, paged_kernel, mesh) — constants
    for a given server — so mixed-length traffic on a 2x2 (dp, tp)
    mesh still compiles O(buckets), fresh servers on the same mesh
    reuse everything, and flipping the pool dtype re-keys <= 5
    programs (the single-device budget carries over)."""
    mesh = jax.sharding.Mesh(
        np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
    with count_compiles() as c:
        srv = ContinuousServer(params, CFG, slots=4, smax=64,
                               prefill_chunk=8, prefill_buckets="4,8",
                               paged=True, mesh=mesh)
        out = _workload(srv, PLENS, seed=6)
    assert len(out) == len(PLENS)
    buckets = len(srv.prefill_buckets)
    # chunk program per bucket + probe + step + gather + splice
    assert srv._prog_misses <= buckets + 5
    assert int(c) <= buckets + 24
    # a fresh sharded server, NEW prompt lengths: total reuse
    with count_compiles() as c2:
        srv2 = ContinuousServer(params, CFG, slots=4, smax=64,
                                prefill_chunk=8, prefill_buckets="4,8",
                                paged=True, mesh=mesh)
        _workload(srv2, [7, 11, 19, 22], seed=7)
    assert srv2._prog_misses == 0 and srv2._prog_hits > 0
    assert int(c2) <= 2
    # int8 pools on the mesh: only the kv_dtype-keyed programs rebuild
    with count_compiles() as c3:
        srv3 = ContinuousServer(params, CFG, slots=4, smax=64,
                                prefill_chunk=8, prefill_buckets="4,8",
                                paged=True, mesh=mesh,
                                kv_dtype="int8")
        out3 = _workload(srv3, PLENS, seed=8)
    assert len(out3) == len(PLENS)
    assert srv3._prog_misses <= 5
    assert int(c3) <= 12


def test_new_lengths_reuse_everything(params, recwarn):
    # warm wave (may share compiles with the test above when it ran
    # first — irrelevant, we only pin the SECOND wave)
    srv = ContinuousServer(params, CFG, slots=4, smax=64,
                           prefill_chunk=8, prefill_buckets="4,8")
    _workload(srv, PLENS, seed=1)
    # fresh server, prompt lengths NOT seen above: zero new programs,
    # and (modulo jax-internal noise) zero backend compiles
    with count_compiles() as c:
        srv2 = ContinuousServer(params, CFG, slots=4, smax=64,
                                prefill_chunk=8, prefill_buckets="4,8")
        out = _workload(srv2, [7, 11, 19, 22], seed=2)
    assert len(out) == 4
    assert srv2._prog_misses == 0
    assert srv2._prog_hits > 0
    assert int(c) <= 2
