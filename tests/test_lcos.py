"""LCO + synchronization primitive tests.

Reference analogs: libs/core/lcos_local/tests/unit (channel.cpp,
receive_buffer.cpp, and_gate, guards), libs/core/synchronization/tests/unit
(latch.cpp, barrier.cpp, sliding_semaphore.cpp, stop_token).
"""

import threading
import time

import pytest

import hpx_tpu as hpx
from hpx_tpu.core.errors import DeadlockError, HpxError
from hpx_tpu.lcos import (
    AndGate, Channel, CompositeGuard, OneElementChannel, ReceiveBuffer,
    Trigger, run_guarded,
)


def test_channel_set_then_get():
    ch = Channel()
    ch.set(1)
    ch.set(2)
    assert ch.get().get() == 1
    assert ch.get().get() == 2


def test_channel_get_before_set():
    ch = Channel()
    f = ch.get()
    assert not f.is_ready()
    ch.set("x")
    assert f.get(timeout=5.0) == "x"


def test_channel_close_fails_pending_gets():
    ch = Channel()
    f = ch.get()
    n = ch.close()
    assert n == 1
    with pytest.raises(HpxError):
        f.get()
    with pytest.raises(HpxError):
        ch.set(1)


def test_channel_iteration():
    ch = Channel()
    for i in range(3):
        ch.set(i)
    ch.close()
    assert list(ch) == [0, 1, 2]


def test_channel_producer_consumer_threads():
    ch = Channel()
    out = []

    def producer():
        for i in range(100):
            ch.set(i)

    def consumer():
        for _ in range(100):
            out.append(ch.get().get(timeout=5.0))

    ts = [threading.Thread(target=producer), threading.Thread(target=consumer)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=10.0)
    assert out == list(range(100))


def test_one_element_channel():
    ch = OneElementChannel()
    ch.set(5)
    with pytest.raises(HpxError):
        ch.set(6)
    assert ch.get().get() == 5
    f = ch.get()
    ch.set(7)
    assert f.get() == 7


def test_receive_buffer_halo_pattern():
    rb = ReceiveBuffer()
    # consumer asks for step 3 before producer stores it
    f3 = rb.receive(3)
    rb.store_received(3, "halo3")
    rb.store_received(4, "halo4")   # producer ahead of consumer
    assert f3.get(timeout=5.0) == "halo3"
    assert rb.receive(4).get() == "halo4"
    assert rb._slots == {}          # slots reclaimed


def test_trigger():
    tr = Trigger()
    f = tr.get_future()
    assert not f.is_ready()
    tr.set()
    tr.set()  # idempotent
    assert f.is_ready()


def test_and_gate_generations():
    g = AndGate(3)
    f = g.get_future()
    g.set(0); g.set(2)
    assert not f.is_ready()
    g.set(1)
    assert f.get() == 0
    with pytest.raises(HpxError):
        g.set(1)  # duplicate within generation
    assert g.next_generation() == 1
    f2 = g.get_future()
    for i in range(3):
        g.set(i)
    assert f2.get() == 1


def test_composite_guard_serializes():
    guard = CompositeGuard()
    order = []

    def work(i):
        def body():
            order.append(("in", i))
            time.sleep(0.002)
            order.append(("out", i))
        return body

    fs = [guard.run(work(i)) for i in range(5)]
    hpx.wait_all(fs)
    # strictly serialized: every "in" immediately followed by its "out"
    for j in range(0, 10, 2):
        assert order[j][0] == "in" and order[j + 1][0] == "out"
        assert order[j][1] == order[j + 1][1]


def test_run_guarded_multiple_guards():
    g1, g2 = CompositeGuard(), CompositeGuard()
    counter = {"v": 0, "max_in": 0, "in": 0}
    lock = threading.Lock()

    def body():
        with lock:
            counter["in"] += 1
            counter["max_in"] = max(counter["max_in"], counter["in"])
        time.sleep(0.001)
        counter["v"] += 1
        with lock:
            counter["in"] -= 1

    fs = [run_guarded([g1, g2], body) for _ in range(8)]
    fs += [run_guarded([g1], body) for _ in range(4)]
    hpx.wait_all(fs, timeout=10.0)
    assert counter["v"] == 12


# -- synchronization --------------------------------------------------------

def test_latch():
    lt = hpx.Latch(3)
    assert not lt.try_wait()
    lt.count_down(2)
    assert not lt.try_wait()
    lt.count_down()
    assert lt.try_wait() and lt.wait(0.0)
    assert lt.get_future().is_ready()


def test_latch_threads():
    lt = hpx.Latch(4)
    for _ in range(4):
        threading.Thread(target=lt.count_down).start()
    assert lt.wait(timeout=5.0)


def test_barrier_cyclic():
    bar = hpx.Barrier(3)
    results = []

    def party(i):
        for phase in range(3):
            bar.arrive_and_wait(timeout=10.0)
            results.append((phase, i))

    ts = [threading.Thread(target=party, args=(i,)) for i in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=10.0)
    assert len(results) == 9
    # all phase-p arrivals complete before any phase-p+1 entry is recorded
    phases = [p for p, _ in results]
    assert phases == sorted(phases)


def test_barrier_completion_callback():
    hits = []
    bar = hpx.Barrier(2, on_completion=lambda: hits.append(1))
    f1 = bar.arrive()
    f2 = bar.arrive()
    hpx.wait_all(f1, f2)
    assert hits == [1]


def test_counting_semaphore():
    sem = hpx.CountingSemaphore(2)
    assert sem.try_acquire() and sem.try_acquire()
    assert not sem.try_acquire()
    sem.release()
    assert sem.try_acquire()


def test_sliding_semaphore_throttles():
    ss = hpx.SlidingSemaphore(max_difference=2, lower=0)
    assert ss.try_wait(2)
    assert not ss.try_wait(3)
    ss.signal(1)
    assert ss.try_wait(3)


def test_event():
    ev = hpx.Event()
    assert not ev.occurred()
    ev.set()
    assert ev.wait(0.0)
    ev.reset()
    assert not ev.occurred()


def test_stop_token():
    src = hpx.StopSource()
    tok = src.get_token()
    hits = []
    tok.on_stop(lambda: hits.append(1))
    assert not tok.stop_requested()
    assert src.request_stop()
    assert not src.request_stop()   # second request is a no-op
    assert tok.stop_requested() and hits == [1]
    tok.on_stop(lambda: hits.append(2))  # late registration fires inline
    assert hits == [1, 2]


def test_verify_locks_guard():
    hpx.enable_lock_verification(True)
    try:
        m = hpx.Mutex()
        with m:
            with pytest.raises(DeadlockError):
                hpx.Latch(1).wait(0.01)
        # outside the lock it's fine
        lt = hpx.Latch(0)
        assert lt.wait(0.01)
    finally:
        hpx.enable_lock_verification(False)


def test_run_guarded_concurrent_multiguard_no_deadlock():
    # regression: interleaved multi-guard tail swaps must not create a
    # circular future dependency
    g1, g2 = CompositeGuard(), CompositeGuard()
    fs = []
    def spam(order):
        for _ in range(20):
            fs.append(run_guarded(order, lambda: 1))
    t1 = threading.Thread(target=spam, args=([g1, g2],))
    t2 = threading.Thread(target=spam, args=([g1, g2],))
    t1.start(); t2.start(); t1.join(); t2.join()
    hpx.wait_all(fs, timeout=10.0)
    assert all(f.is_ready() for f in fs)


class TestSharedMutex:
    def test_readers_share_writer_excludes(self):
        import threading
        m = hpx.SharedMutex()
        m.lock_shared()
        assert m.try_lock_shared()       # second reader enters
        assert not m.try_lock()          # writer excluded
        m.unlock_shared()
        m.unlock_shared()
        assert m.try_lock()              # now exclusive
        assert not m.try_lock_shared()   # reader excluded
        m.unlock()

    def test_writer_preference_blocks_new_readers(self):
        import threading
        import time
        m = hpx.SharedMutex()
        m.lock_shared()
        got_write = threading.Event()

        def writer():
            m.lock()                     # waits on the reader
            got_write.set()
            m.unlock()

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        # poll until the writer is actually queued (a bare sleep races
        # thread scheduling on a loaded host)
        deadline = time.monotonic() + 10.0
        while m._writers_waiting == 0:
            assert time.monotonic() < deadline, "writer never queued"
            time.sleep(0.005)
        assert not m.try_lock_shared()   # new readers yield to writer
        m.unlock_shared()
        assert got_write.wait(5.0)
        t.join(5.0)
        with m.shared():                 # readers flow again
            pass

    def test_concurrent_reader_writer_consistency(self):
        import threading
        m = hpx.SharedMutex()
        state = {"v": 0}
        seen_torn = []
        stop = threading.Event()

        def reader():
            while not stop.is_set():
                with m.shared():
                    a = state["v"]
                    b = state["v"]
                    if a != b:
                        seen_torn.append((a, b))

        ts = [threading.Thread(target=reader) for _ in range(3)]
        for t in ts:
            t.start()
        for i in range(200):
            with m:
                state["v"] = i
                state["v"] = i           # readers must never see a torn pair
        stop.set()
        for t in ts:
            t.join(5.0)
        assert not seen_torn
