"""partitioned_vector + segmented algorithms (M6).

Reference analog: components/containers/partitioned_vector/tests/unit/
and tests/unit/modules/segmented_algorithms/ — construction, element
access, named registration, and per-algorithm segmented dispatch checked
against a host (numpy) oracle, on the 8-device CPU mesh.
"""

import numpy as np
import pytest

import hpx_tpu as hpx
from hpx_tpu.testing import HPX_TEST, HPX_TEST_EQ


def np_oracle(pv):
    return pv.to_numpy()


class TestConstruction:
    def test_fill_constructor(self, mesh1d):
        layout = hpx.container_layout(mesh=mesh1d)
        pv = hpx.partitioned_vector(64, value=3.5, layout=layout)
        HPX_TEST_EQ(len(pv), 64)
        HPX_TEST_EQ(pv.num_partitions, 8)
        assert np.allclose(pv.to_numpy(), 3.5)

    def test_from_array_even(self, mesh1d):
        layout = hpx.container_layout(mesh=mesh1d)
        src = np.arange(80, dtype=np.float32)
        pv = hpx.PartitionedVector.from_array(src, layout)
        assert np.array_equal(pv.to_numpy(), src)
        # sharded over all 8 devices
        assert len(pv.data.sharding.device_set) == 8

    def test_from_array_uneven_pads(self, mesh1d):
        layout = hpx.container_layout(mesh=mesh1d)
        src = np.arange(13, dtype=np.int32)
        pv = hpx.PartitionedVector.from_array(src, layout)
        HPX_TEST_EQ(pv.size, 13)
        assert pv.data.shape[0] % 8 == 0
        assert np.array_equal(pv.to_numpy(), src)

    def test_multiple_partitions_per_device(self, mesh1d):
        layout = hpx.container_layout(16, mesh=mesh1d)
        pv = hpx.partitioned_vector(64, value=0, dtype=np.float32,
                                    layout=layout)
        HPX_TEST_EQ(pv.num_partitions, 16)
        segs = pv.segments()
        HPX_TEST_EQ(len(segs), 16)
        # sharding is block-contiguous: consecutive partition pairs share
        # a device, and devices appear in axis order
        for k in range(0, 16, 2):
            assert segs[k].device == segs[k + 1].device
        assert len({s.device for s in segs}) == 8
        # segment devices must agree with the actual shard placement
        def device_at(pos):
            for shard in pv.data.addressable_shards:
                sl = shard.index[0]
                lo = sl.start or 0
                hi = sl.stop if sl.stop is not None else len(pv.data)
                if lo <= pos < hi:
                    return shard.device
            raise AssertionError(pos)
        for s in segs:
            assert s.device == device_at(s.begin), s

    def test_fewer_partitions_than_devices_spans(self, mesh1d):
        # 4 partitions over 8 devices: each segment spans 2 devices
        layout = hpx.container_layout(4, mesh=mesh1d)
        pv = hpx.PartitionedVector.from_array(
            np.arange(64, dtype=np.float32), layout)
        segs = pv.segments()
        HPX_TEST_EQ(len(segs), 4)
        for s in segs:
            assert len(s.devices) == 2
        assert len({d for s in segs for d in s.devices}) == 8
        # devices listed in axis order: segment k starts on device 2k
        axis_devices = list(mesh1d.devices.flat)
        for k, s in enumerate(segs):
            assert s.device == axis_devices[2 * k], (k, s)
            assert s.begin == k * 16 and s.end == (k + 1) * 16

    def test_incompatible_partition_count_raises(self, mesh1d):
        with pytest.raises(ValueError):
            hpx.container_layout(3, mesh=mesh1d)


class TestElementAccess:
    def test_get_set(self, mesh1d):
        pv = hpx.PartitionedVector.from_array(
            np.arange(16, dtype=np.float32),
            hpx.container_layout(mesh=mesh1d))
        HPX_TEST_EQ(pv.get(3), 3.0)
        HPX_TEST_EQ(pv[15], 15.0)
        HPX_TEST_EQ(pv[-1], 15.0)
        pv.set(3, 99.0)
        HPX_TEST_EQ(pv[3], 99.0)
        pv[4] = 123.0
        HPX_TEST_EQ(pv.get(4), 123.0)

    def test_get_async(self, mesh1d):
        pv = hpx.PartitionedVector.from_array(
            np.arange(8, dtype=np.float32),
            hpx.container_layout(mesh=mesh1d))
        f = pv.get_async(5)
        HPX_TEST(hpx.is_future(f))
        HPX_TEST_EQ(float(f.get()), 5.0)

    def test_out_of_range(self, mesh1d):
        pv = hpx.partitioned_vector(8, layout=hpx.container_layout(
            mesh=mesh1d))
        with pytest.raises(IndexError):
            pv.get(8)

    def test_iteration(self, mesh1d):
        src = np.arange(24, dtype=np.float32)
        pv = hpx.PartitionedVector.from_array(
            src, hpx.container_layout(mesh=mesh1d))
        assert list(pv) == list(src)


class TestSegmentsAndViews:
    def test_segments_cover_range(self, mesh1d):
        pv = hpx.PartitionedVector.from_array(
            np.arange(64, dtype=np.float32),
            hpx.container_layout(mesh=mesh1d))
        segs = pv.segments()
        HPX_TEST_EQ(len(segs), 8)
        assert segs[0].begin == 0 and segs[-1].end == 64
        for a, b in zip(segs, segs[1:]):
            HPX_TEST_EQ(a.end, b.begin)
        # distinct devices along the axis
        assert len({s.device for s in segs}) == 8

    def test_view_and_subview(self, mesh1d):
        src = np.arange(64, dtype=np.float32)
        pv = hpx.PartitionedVector.from_array(
            src, hpx.container_layout(mesh=mesh1d))
        v = pv.view(8, 24)
        HPX_TEST_EQ(len(v), 16)
        assert np.array_equal(v.to_numpy(), src[8:24])
        sub = v[4:8]
        assert np.array_equal(sub.to_numpy(), src[12:16])
        HPX_TEST_EQ(v[0], 8.0)

    def test_slice_returns_view(self, mesh1d):
        pv = hpx.PartitionedVector.from_array(
            np.arange(32, dtype=np.float32),
            hpx.container_layout(mesh=mesh1d))
        v = pv[4:12]
        assert isinstance(v, hpx.PartitionedVectorView)
        assert np.array_equal(v.to_numpy(), np.arange(4, 12, dtype=np.float32))


class TestRegistration:
    def test_register_resolve(self, mesh1d):
        pv = hpx.PartitionedVector.from_array(
            np.arange(16, dtype=np.float32),
            hpx.container_layout(mesh=mesh1d))
        HPX_TEST(pv.register_as("pvtest").get())
        other = hpx.PartitionedVector.connect_to("pvtest")
        assert other is pv
        HPX_TEST(pv.unregister("pvtest").get())


class TestSegmentedAlgorithms:
    """Each algorithm × partitioned_vector, vs numpy oracle."""

    def _pv(self, mesh, n=64, dtype=np.float32, seed=0):
        src = np.random.default_rng(seed).random(n).astype(dtype)
        return src, hpx.PartitionedVector.from_array(
            src, hpx.container_layout(mesh=mesh))

    def test_for_each(self, mesh1d):
        src, pv = self._pv(mesh1d)
        out = hpx.for_each(hpx.par, pv, lambda x: x * 2.0)
        assert isinstance(out, hpx.PartitionedVector)
        assert np.allclose(out.to_numpy(), src * 2.0)
        # sharding preserved — still distributed over 8 devices
        assert len(out.data.sharding.device_set) == 8

    def test_transform_binary(self, mesh1d):
        src, pv = self._pv(mesh1d)
        src2, pv2 = self._pv(mesh1d, seed=1)
        out = hpx.transform(hpx.par, pv, lambda a, b: a + b, pv2)
        assert isinstance(out, hpx.PartitionedVector)
        assert np.allclose(out.to_numpy(), src + src2)

    def test_fill_copy(self, mesh1d):
        _, pv = self._pv(mesh1d)
        filled = hpx.fill(hpx.par, pv, 7.0)
        assert isinstance(filled, hpx.PartitionedVector)
        assert np.allclose(filled.to_numpy(), 7.0)
        copied = hpx.copy(hpx.par, pv)
        assert isinstance(copied, hpx.PartitionedVector)
        assert np.allclose(copied.to_numpy(), pv.to_numpy())

    def test_reduce(self, mesh1d):
        src, pv = self._pv(mesh1d)
        got = float(hpx.reduce(hpx.par, pv, 0.0))
        assert np.isclose(got, src.sum(), rtol=1e-5)

    def test_transform_reduce_dot(self, mesh1d):
        import operator
        src, pv = self._pv(mesh1d)
        src2, pv2 = self._pv(mesh1d, seed=1)
        got = float(hpx.transform_reduce(
            hpx.par, pv, 0.0, operator.add, lambda a, b: a * b, rng2=pv2))
        assert np.isclose(got, np.dot(src, src2), rtol=1e-5)

    def test_count(self, mesh1d):
        src = np.array([1, 2, 1, 3, 1, 4, 1, 5] * 4, dtype=np.float32)
        pv = hpx.PartitionedVector.from_array(
            src, hpx.container_layout(mesh=mesh1d))
        HPX_TEST_EQ(int(hpx.count(hpx.par, pv, 1.0)), 16)

    def test_minmax(self, mesh1d):
        src, pv = self._pv(mesh1d)
        assert np.isclose(float(hpx.min_element(hpx.par, pv)), src.min())
        assert np.isclose(float(hpx.max_element(hpx.par, pv)), src.max())

    def test_inclusive_scan(self, mesh1d):
        src, pv = self._pv(mesh1d)
        out = hpx.inclusive_scan(hpx.par, pv)
        assert isinstance(out, hpx.PartitionedVector)
        assert np.allclose(out.to_numpy(), np.cumsum(src), rtol=1e-5)

    def test_sort(self, mesh1d):
        src, pv = self._pv(mesh1d, n=128)
        out = hpx.sort(hpx.par, pv)
        assert isinstance(out, hpx.PartitionedVector)
        assert np.array_equal(out.to_numpy(), np.sort(src))

    def test_uneven_size_reduce_masks_padding(self, mesh1d):
        src = np.arange(13, dtype=np.float32)
        pv = hpx.PartitionedVector.from_array(
            src, hpx.container_layout(mesh=mesh1d))
        got = float(hpx.reduce(hpx.par, pv, 0.0))
        HPX_TEST_EQ(got, float(src.sum()))

    def test_view_in_algorithm(self, mesh1d):
        src, pv = self._pv(mesh1d)
        got = float(hpx.reduce(hpx.par, pv.view(8, 24), 0.0))
        assert np.isclose(got, src[8:24].sum(), rtol=1e-5)

    def test_host_path_also_rewraps(self, mesh1d):
        # seq routes through the host (numpy) path; the result contract
        # (shape-preserving => PartitionedVector out) must still hold
        src, pv = self._pv(mesh1d, n=16)
        out = hpx.for_each(hpx.seq, pv, lambda x: x * 2.0)
        assert isinstance(out, hpx.PartitionedVector)
        assert np.allclose(out.to_numpy(), src * 2.0)

    def test_keyword_policy_accepted(self, mesh1d):
        src, pv = self._pv(mesh1d, n=16)
        got = float(hpx.reduce(hpx.par, pv, init=0.0))
        assert np.isclose(got, src.sum(), rtol=1e-5)

    def test_task_policy_returns_future_of_pv(self, mesh1d):
        src, pv = self._pv(mesh1d)
        fut = hpx.for_each(hpx.par.task, pv, lambda x: x + 1.0)
        HPX_TEST(hpx.is_future(fut))
        out = fut.get()
        assert isinstance(out, hpx.PartitionedVector)
        assert np.allclose(out.to_numpy(), src + 1.0)
