"""Distributed FFT (algo/fft.py): pencil 2-D and four-step 1-D over the
virtual 8-device mesh, vs numpy oracles."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hpx_tpu.algo import fft as dfft


def _rel(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return np.linalg.norm(a - b) / max(np.linalg.norm(b), 1e-30)


@pytest.fixture(scope="module")
def mesh8(devices):
    from jax.sharding import Mesh
    return Mesh(np.array(devices), ("x",))


def _sharded(x, mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P
    return jax.device_put(jnp.asarray(x),
                          NamedSharding(mesh, P(*["x"] + [None] * (x.ndim - 1))))


def test_fft2_matches_numpy(mesh8):
    rng = np.random.default_rng(0)
    a = (rng.standard_normal((64, 40)) +
         1j * rng.standard_normal((64, 40))).astype(np.complex64)
    got = dfft.fft2_sharded(_sharded(a, mesh8), mesh8)
    assert _rel(got, np.fft.fft2(a.astype(np.complex128))) < 1e-4
    # sharding preserved (row-sharded in, row-sharded out)
    assert got.sharding.spec == jax.device_put(
        got, got.sharding).sharding.spec


def test_ifft2_roundtrip(mesh8):
    rng = np.random.default_rng(1)
    a = (rng.standard_normal((32, 16)) +
         1j * rng.standard_normal((32, 16))).astype(np.complex64)
    x = _sharded(a, mesh8)
    back = dfft.ifft2_sharded(dfft.fft2_sharded(x, mesh8), mesh8)
    assert _rel(back, a) < 1e-5


@pytest.mark.parametrize("n", [1024, 8192])
def test_fft1d_matches_numpy(mesh8, n):
    rng = np.random.default_rng(2)
    v = (rng.standard_normal(n) +
         1j * rng.standard_normal(n)).astype(np.complex64)
    got = dfft.fft_sharded(_sharded(v, mesh8), mesh8)
    ref = np.fft.fft(v.astype(np.complex128))
    assert _rel(got, ref) < 1e-4


def test_ifft1d_matches_numpy_and_roundtrip(mesh8):
    rng = np.random.default_rng(3)
    v = (rng.standard_normal(2048) +
         1j * rng.standard_normal(2048)).astype(np.complex64)
    x = _sharded(v, mesh8)
    inv = dfft.ifft_sharded(x, mesh8)
    assert _rel(inv, np.fft.ifft(v.astype(np.complex128))) < 1e-4
    assert _rel(dfft.ifft_sharded(dfft.fft_sharded(x, mesh8), mesh8),
                v) < 1e-5


def test_fft1d_real_signal_spectrum(mesh8):
    """A pure tone lands all energy in its bin (end-to-end sanity that
    the four-step index mapping X[k2*N1+k1] was undone correctly)."""
    n, tone = 4096, 129
    t = np.arange(n)
    v = np.exp(2j * np.pi * tone * t / n).astype(np.complex64)
    got = np.asarray(dfft.fft_sharded(_sharded(v, mesh8), mesh8))
    peak = np.argmax(np.abs(got))
    assert peak == tone
    assert abs(got[peak]) == pytest.approx(n, rel=1e-4)
    rest = np.abs(got).sum() - abs(got[peak])
    assert rest < 1e-2 * n


def test_fft_partitioned_vector(mesh8):
    """Segmented surface: fft(pv) -> pv with the same layout."""
    from hpx_tpu.containers.partitioned_vector import PartitionedVector
    from hpx_tpu.dist.distribution_policies import ContainerLayout
    rng = np.random.default_rng(5)
    v = (rng.standard_normal(1024) +
         1j * rng.standard_normal(1024)).astype(np.complex64)
    lay = ContainerLayout(mesh=mesh8, axis="x")
    pv = PartitionedVector.from_array(v, layout=lay)
    out = dfft.fft(pv)
    assert isinstance(out, PartitionedVector)
    assert out.layout is lay
    assert _rel(out.to_numpy(), np.fft.fft(v.astype(np.complex128))) < 1e-4
    back = dfft.ifft(out)
    assert _rel(back.to_numpy(), v) < 1e-5


def test_fft1d_rejects_unfactorable(mesh8):
    v = jnp.zeros((8 * 17,), jnp.complex64)   # 136 = 8*17: n2 can't
    with pytest.raises(ValueError, match="factor"):
        dfft.fft_sharded(_sharded(v, mesh8), mesh8)


def test_fft2_gradients_flow(mesh8):
    """FFT is linear; grads through the sharded program must match the
    conjugate-transpose action (spot check via a scalar loss)."""
    rng = np.random.default_rng(4)
    a = (rng.standard_normal((16, 8)) +
         1j * rng.standard_normal((16, 8))).astype(np.complex64)

    def loss_np(x):
        return float(np.abs(np.fft.fft2(x)).sum())

    def loss(x):
        return jnp.abs(dfft.fft2_sharded(x, mesh8)).sum()

    g = jax.grad(lambda x: loss(x).real, holomorphic=False)(
        _sharded(a, mesh8))
    # finite-difference check on one element
    eps = 1e-2
    e = np.zeros_like(a)
    e[3, 5] = eps
    fd = (loss_np(a + e) - loss_np(a - e)) / (2 * eps)
    assert np.real(np.asarray(g)[3, 5]) == pytest.approx(fd, rel=5e-2)


def test_fft2_2d_mesh_matches_numpy(devices):
    """Both dims sharded over a 2x4 mesh; intra-axis pencil transposes."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.array(devices).reshape(2, 4), ("x", "y"))
    rng = np.random.default_rng(7)
    a = (rng.standard_normal((32, 64)) +
         1j * rng.standard_normal((32, 64))).astype(np.complex64)
    x = jax.device_put(jnp.asarray(a),
                       NamedSharding(mesh, P("x", "y")))
    got = dfft.fft2_sharded_2d(x, mesh)
    assert _rel(got, np.fft.fft2(a.astype(np.complex128))) < 1e-4
    back = dfft.ifft2_sharded_2d(got, mesh)
    assert _rel(back, a) < 1e-5


def test_fft2_2d_rejects_untileable(devices):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.array(devices).reshape(2, 4), ("x", "y"))
    a = jnp.zeros((12, 64), jnp.complex64)    # 12 % 8 != 0
    x = jax.device_put(a, NamedSharding(mesh, P("x", "y")))
    with pytest.raises(ValueError, match="tileable"):
        dfft.fft2_sharded_2d(x, mesh)
