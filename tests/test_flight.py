"""Fault flight recorder (svc/flight): schema-validated bundles on
injected faults through the real serving shed path, zero-cost when
disarmed (capture-count accounting, compile-guard style), bundle
pruning, the never-raises contract, and the dump CLI.
"""

import contextlib
import json
import os

import jax
import pytest

from hpx_tpu.core.config import runtime_config
from hpx_tpu.models import transformer as tfm
from hpx_tpu.models.serving import ContinuousServer, RequestShedError
from hpx_tpu.svc import faultinject, flight, metrics, progprof, tracing

CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4, head_dim=8,
                            n_layers=2, d_ff=64)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.PRNGKey(0))


@pytest.fixture()
def flight_dir(tmp_path):
    """Point hpx.flight.dir at a per-test directory, reset capture
    accounting, and restore both afterwards."""
    cfg = runtime_config()
    old = cfg.get("hpx.flight.dir", "auto")
    cfg.set("hpx.flight.dir", str(tmp_path))
    flight.reset_counts()
    try:
        yield str(tmp_path)
    finally:
        cfg.set("hpx.flight.dir", old)
        flight.reset_counts()


def _bundles(d):
    return sorted(n for n in os.listdir(d)
                  if n.startswith("flight-") and n.endswith(".json"))


def _load(d, name):
    with open(os.path.join(d, name)) as f:
        return json.load(f)


@contextlib.contextmanager
def _inject(**kw):
    fi = faultinject.install(faultinject.FaultInjector(**kw))
    try:
        yield fi
    finally:
        faultinject.uninstall()


# ---------------------------------------------------------------------------
# direct capture: every section present and schema-valid
# ---------------------------------------------------------------------------


def test_record_fault_full_bundle(flight_dir):
    tl = metrics.RequestTimeline(capacity=16)
    tl.event("r7", "submit")
    tl.event("r7", "shed", reason="test")
    tracing.start_tracing(capacity=64, sample_counters=False)
    prof = progprof.start_profiling(sample_memory=False)
    try:
        with tracing.span("warmup", "test"):
            pass
        path = flight.record_fault(
            "shed", site="test", rid="r7",
            error=RequestShedError("r7", "oom"), timeline=tl)
    finally:
        progprof.stop_profiling()
        tracing.stop_tracing()
    assert path is not None and os.path.dirname(path) == flight_dir
    doc = _load(flight_dir, os.path.basename(path))
    assert flight.validate_bundle(doc) == []
    assert doc["schema"] == flight.FLIGHT_SCHEMA
    trig = doc["trigger"]
    assert trig["kind"] == "shed" and trig["site"] == "test"
    assert trig["rid"] == "r7"
    assert trig["error_type"] == "RequestShedError"
    assert any(ev["name"] == "warmup" for ev in doc["spans"])
    assert isinstance(doc["counters"]["histograms"], dict)
    assert doc["counters"]["counters"]          # live registry folded
    assert doc["config"]["hpx.flight.enabled"] == "1"
    assert doc["programs"]["schema"] == progprof.PROFILE_SCHEMA
    assert [e["name"] for e in doc["timeline"]] == ["submit", "shed"]
    assert flight.capture_count() == 1
    assert "shed" in os.path.basename(path)     # kind in the filename


def test_bundle_with_counter_sample_events(flight_dir):
    # "C" events carry a bare float where span events carry an args
    # dict — the span decoder must not choke on them (regression:
    # captures under a live counter sampler silently dropped)
    tr = tracing.start_tracing(capacity=64, sample_counters=False)
    try:
        with tracing.span("work", "test"):
            pass
        tr.counter("/x{locality#0/total}/y", 42.0)
        path = flight.record_fault("shed", site="test")
    finally:
        tracing.stop_tracing()
    assert path is not None, "capture dropped"
    assert flight.dropped_count() == 0
    doc = _load(flight_dir, os.path.basename(path))
    assert flight.validate_bundle(doc) == []
    (c,) = [ev for ev in doc["spans"] if ev["ph"] == "C"]
    assert c["args"] == 42.0


def test_bundle_without_optionals_still_valid(flight_dir):
    # no tracer, no profiler, no timeline: sections degrade to
    # empty/null but the bundle stays schema-valid
    path = flight.record_fault("degrade", site="disagg")
    doc = _load(flight_dir, os.path.basename(path))
    assert flight.validate_bundle(doc) == []
    assert doc["spans"] == [] and doc["timeline"] == []
    assert doc["programs"] is None


def test_validate_bundle_catches_damage(flight_dir):
    path = flight.record_fault("shed", site="test")
    doc = _load(flight_dir, os.path.basename(path))
    assert flight.validate_bundle(doc) == []
    doc.pop("counters")
    doc["schema"] = "bogus"
    problems = flight.validate_bundle(doc)
    assert any("schema" in p for p in problems)
    assert any("counters" in p for p in problems)
    assert flight.validate_bundle("nope") == ["bundle is not an object"]


# ---------------------------------------------------------------------------
# acceptance: an injected serving fault persists a valid bundle
# ---------------------------------------------------------------------------


def test_injected_shed_writes_valid_bundle(params, flight_dir):
    # the admit-OOM ladder exhausts and sheds typed; the shed path
    # must leave a post-mortem bundle behind
    srv = ContinuousServer(params, CFG, slots=2, smax=64, paged=True,
                           block_size=8, num_blocks=64,
                           prefix_reuse=False)
    rid = srv.submit([3, 1, 4], max_new=4)
    with _inject(rate=1.0, sites=["alloc"], seed=1):
        out = srv.run()
    assert out == {}
    assert isinstance(srv.failed[rid], RequestShedError)
    assert flight.capture_count() >= 1
    names = _bundles(flight_dir)
    assert names
    doc = _load(flight_dir, names[-1])
    assert flight.validate_bundle(doc) == []
    assert doc["trigger"]["kind"] == "shed"
    assert doc["trigger"]["site"] == "serving"
    assert doc["trigger"]["error_type"] == "RequestShedError"


def test_retry_exhaustion_one_aggregate_bundle(params, flight_dir):
    # _shed_everything sheds EVERY in-flight request but must record
    # ONE aggregate retry-exhausted bundle, not one per request
    srv = ContinuousServer(params, CFG, slots=2, smax=64)
    rids = [srv.submit([3, 1, 4], max_new=4),
            srv.submit([2, 7], max_new=4),
            srv.submit([5, 5, 5], max_new=4)]
    with _inject(rate=1.0, sites=["decode"], seed=3):
        out = srv.run()
    assert out == {}
    assert all(isinstance(srv.failed[r], RequestShedError)
               for r in rids)
    names = _bundles(flight_dir)
    kinds = [_load(flight_dir, n)["trigger"]["kind"] for n in names]
    assert kinds.count("retry-exhausted") == 1
    assert "shed" not in kinds               # per-request sheds muted


# ---------------------------------------------------------------------------
# zero-cost when disarmed
# ---------------------------------------------------------------------------


def test_fault_free_run_captures_nothing(params, flight_dir):
    # compile-guard-style accounting: a clean serving run must not
    # touch the recorder at all — zero captures, zero files
    srv = ContinuousServer(params, CFG, slots=2, smax=64)
    srv.submit([3, 1, 4, 1, 5], max_new=6)
    srv.submit([2, 7], max_new=4)
    out = srv.run()
    assert len(out) == 2 and srv.failed == {}
    assert flight.capture_count() == 0
    assert flight.dropped_count() == 0
    assert _bundles(flight_dir) == []


def test_disabled_records_nothing(flight_dir):
    cfg = runtime_config()
    cfg.set("hpx.flight.enabled", "0")
    try:
        assert flight.record_fault("shed", site="test") is None
    finally:
        cfg.set("hpx.flight.enabled", "1")
    assert flight.capture_count() == 0
    assert _bundles(flight_dir) == []


# ---------------------------------------------------------------------------
# robustness: pruning + the never-raises contract
# ---------------------------------------------------------------------------


def test_prune_keeps_max_bundles(flight_dir):
    cfg = runtime_config()
    cfg.set("hpx.flight.max_bundles", "2")
    try:
        paths = [flight.record_fault("shed", site="t")
                 for _ in range(5)]
    finally:
        cfg.set("hpx.flight.max_bundles", "8")
    assert all(p is not None for p in paths)
    names = _bundles(flight_dir)
    assert len(names) == 2
    # the survivors are the newest captures
    assert os.path.basename(paths[-1]) in names


def test_broken_dir_never_raises(tmp_path):
    cfg = runtime_config()
    blocker = tmp_path / "not-a-dir"
    blocker.write_text("")
    old = cfg.get("hpx.flight.dir", "auto")
    cfg.set("hpx.flight.dir", str(blocker))
    flight.reset_counts()
    try:
        assert flight.record_fault("shed", site="t") is None
        assert flight.dropped_count() == 1
        assert flight.capture_count() == 0
    finally:
        cfg.set("hpx.flight.dir", old)
        flight.reset_counts()


def test_auto_dir_resolves_to_tmpdir():
    import tempfile
    cfg = runtime_config()
    assert cfg.get("hpx.flight.dir", "auto") == "auto"
    assert flight.flight_dir() == os.path.join(
        tempfile.gettempdir(), "hpx_tpu_flight")


# ---------------------------------------------------------------------------
# dump CLI
# ---------------------------------------------------------------------------


def test_cli_dump_to_out(flight_dir, tmp_path, capsys):
    out = tmp_path / "manual.json"
    rc = flight.main(["dump", "--out", str(out)])
    assert rc == 0
    assert capsys.readouterr().out.strip() == str(out)
    with open(out) as f:
        doc = json.load(f)
    assert flight.validate_bundle(doc) == []
    assert doc["trigger"] == {"kind": "manual", "site": "cli",
                              "rid": None, "error_type": None,
                              "error": None}


def test_cli_dump_default_dir(flight_dir, capsys):
    rc = flight.main(["dump"])
    assert rc == 0
    path = capsys.readouterr().out.strip()
    assert os.path.dirname(path) == flight_dir
    assert flight.validate_bundle(_load(
        flight_dir, os.path.basename(path))) == []
