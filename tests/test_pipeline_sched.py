"""Schedule algebra of parallel/pipeline_spmd across (P, V, M) shapes,
with synthetic stages — cheap enough to sweep combinations the
transformer parity tests can't afford.

Stage s applies y = x * 2 + s, so a microbatch x that has traversed
stages 0..S-1 in order carries a unique closed-form value:
    f_S(x) = x * 2^S + sum_{s<S} s * 2^(S-1-s)
Any routing error (wrong chunk, wrong order, dropped/duplicated
microbatch) lands on a different value.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from hpx_tpu.utils.jaxcompat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from hpx_tpu.ops.attention import _pvary
from hpx_tpu.parallel.pipeline_spmd import (pipeline_run,
                                            pipeline_run_interleaved)


def _expected(xs, n_stages):
    val = np.asarray(xs, np.float64)
    for s in range(n_stages):
        val = val * 2 + s
    return val


def _run(devices, p, v, m):
    mesh = Mesh(np.array(devices[:p]), ("pp",))
    mbs = jnp.arange(1.0, m + 1.0)          # microbatch payloads

    def body(_dummy):
        def collect(buf, y, t_out, valid):
            upd = jax.lax.dynamic_update_index_in_dim(buf, y, t_out, 0)
            return jnp.where(valid, upd, buf)

        def feed(t):
            return mbs[t]

        acc0 = _pvary(jnp.zeros((m,)), ("pp",))
        x0s = _pvary(jnp.zeros(() if v == 1 else (v,)), ("pp",))
        idx = jax.lax.axis_index("pp")
        if v == 1:
            def stage_fn(x):
                return x * 2 + idx
            buf = pipeline_run("pp", p, m, stage_fn, feed, collect,
                               acc0, x0s)
        else:
            def stage_fn(chunk, x):
                return x * 2 + (chunk * p + idx)     # stage id
            buf = pipeline_run_interleaved("pp", p, v, m, stage_fn,
                                           feed, collect, acc0, x0s)
        # results live on the last device only; replicate for P() out
        return jax.lax.psum(buf, "pp")

    dummy = jax.device_put(
        jnp.zeros((p,)), jax.sharding.NamedSharding(mesh, P("pp")))
    out = jax.jit(shard_map(body, mesh=mesh, in_specs=(P("pp"),),
                            out_specs=P()))(dummy)
    return np.asarray(out)


@pytest.mark.parametrize("p,m", [(2, 1), (2, 4), (4, 4), (8, 8), (3, 5)])
def test_plain_schedule(devices, p, m):
    got = _run(devices, p, 1, m)
    want = _expected(np.arange(1.0, m + 1.0), p)
    np.testing.assert_allclose(got, want)


@pytest.mark.parametrize("p,v,m", [
    (2, 2, 2), (2, 2, 4), (2, 3, 4), (2, 4, 8),
    (4, 2, 4), (4, 2, 8), (4, 3, 4), (8, 2, 8), (3, 2, 3),
])
def test_interleaved_schedule(devices, p, v, m):
    got = _run(devices, p, v, m)
    want = _expected(np.arange(1.0, m + 1.0), p * v)
    np.testing.assert_allclose(got, want)


def test_interleaved_requires_m_divisible(devices):
    with pytest.raises(ValueError, match="divisible"):
        _run(devices, 4, 2, 6)
