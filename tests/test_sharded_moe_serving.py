"""Expert-parallel MoE decode on the mesh: ContinuousServer with an
MoE model and mesh=(dp, tp) must emit BYTE-IDENTICAL tokens to the
single-device MoE server — greedy and sampled, dense and paged, spec
on and off.  Experts shard over the "tp" axis (no dedicated "ep" axis
in the default serving mesh); decode routing rides moe_ffn's tiled
all_to_all with the drop-free auto capacity (cf = n_experts), so
token identity is exact, not approximate.

Also pinned here: the /serving{...}/moe/* counters advance from real
decode stats, the capacity-factor knob re-keys at most the decode
step/verify programs (compile guard), and the declared
hpx.serving.moe.capacity_factor tunable accepts a probe and replays
deterministically from its flight state.
"""

import jax
import numpy as np
import pytest

from hpx_tpu.core.config import runtime_config
from hpx_tpu.models import transformer as tfm
from hpx_tpu.models.serving import ContinuousServer

MOE = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4, head_dim=8,
                            n_layers=2, d_ff=64, n_experts=4,
                            moe_top_k=2, moe_capacity=4.0)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(MOE, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def mesh():
    return jax.sharding.Mesh(
        np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))


GREEDY = [dict(prompt=[3, 1, 4], max_new=9),
          dict(prompt=[2, 7], max_new=5),
          dict(prompt=[5, 6, 7, 8, 9], max_new=12),
          dict(prompt=[1], max_new=7)]

SAMPLED = [dict(prompt=[3, 1, 4], max_new=8, temperature=0.9,
                key=jax.random.PRNGKey(7)),
           dict(prompt=[2, 7, 9], max_new=8, temperature=0.7,
                key=jax.random.PRNGKey(8)),
           dict(prompt=[6, 1], max_new=6)]


def _run_both(params, mesh, reqs, **kw):
    solo = ContinuousServer(params, MOE, slots=4, smax=64, **kw)
    shard = ContinuousServer(params, MOE, slots=4, smax=64, mesh=mesh,
                             **kw)
    for srv in (solo, shard):
        for r in reqs:
            srv.submit(**r)
    return solo.run(), shard.run(), shard


# -- token identity ----------------------------------------------------------

@pytest.mark.parametrize("paged", [False, True], ids=["dense", "paged"])
def test_greedy_matches_single_device(params, mesh, paged):
    kw = dict(paged=True) if paged else {}
    outs, outm, srv = _run_both(params, mesh, GREEDY, **kw)
    assert outs == outm
    assert srv._ep_axis == "tp" and srv._ep_size == 2


@pytest.mark.parametrize("paged", [False, True], ids=["dense", "paged"])
def test_sampled_matches_single_device(params, mesh, paged):
    kw = dict(paged=True) if paged else {}
    outs, outm, _ = _run_both(params, mesh, SAMPLED, **kw)
    assert outs == outm


@pytest.mark.parametrize("paged", [False, True], ids=["dense", "paged"])
def test_spec_matches_single_device(params, mesh, paged):
    """Speculative decode over expert-parallel MoE: the verify window
    routes every draft position through the same drop-free exchange,
    so accepts match the solo server exactly."""
    kw = dict(paged=True) if paged else {}
    reqs = GREEDY[:3] + SAMPLED[:1]
    outs, outm, srv = _run_both(params, mesh, reqs, spec=True,
                                spec_k=3, **kw)
    assert outs == outm
    assert srv.spec_stats()["steps"] > 0


# -- counters ----------------------------------------------------------------

def test_moe_counters_advance(params, mesh):
    from hpx_tpu.svc import performance_counters as pc
    _, _, srv = _run_both(params, mesh, GREEDY)
    inst = srv.counter_instance
    names = pc.discover_counters(f"/serving{{locality#*/{inst}}}/moe/*")
    leaves = {n.split("/moe/", 1)[1] for n in names}
    assert {"tokens-routed", "tokens-dropped"} <= leaves
    assert {f"expert#{e}/occupancy" for e in range(MOE.n_experts)} \
        <= leaves
    got = {n.split("/moe/", 1)[1]: pc.query_counter(n).value
           for n in names}
    # every decoded token claims top_k expert slots; auto capacity
    # (cf = n_experts) is drop-free
    assert got["tokens-routed"] > 0
    assert got["tokens-dropped"] == 0
    assert any(got[f"expert#{e}/occupancy"] > 0
               for e in range(MOE.n_experts))
    assert all(got[f"expert#{e}/occupancy"] <= 1.0 + 1e-6
               for e in range(MOE.n_experts))


# -- compile guard -----------------------------------------------------------

def test_capacity_pct_rekeys_bounded_programs(params, mesh):
    """Reloading hpx.serving.moe.capacity_factor re-keys ONLY the
    decode step program family (step/verify; chunk/probe/splice are
    knob-independent): a warm server picks up the knob at the flush
    boundary and mints at most 5 new programs."""
    rc = runtime_config()
    srv = ContinuousServer(params, MOE, slots=4, smax=64, mesh=mesh)
    for r in GREEDY:
        srv.submit(**r)
    base_out = srv.run()
    warm = srv._prog_misses
    rc.set("hpx.serving.moe.capacity_factor", "200")
    try:
        for r in GREEDY:
            srv.submit(**r)
        out2 = srv.run()
        assert srv._moe_capacity_pct == 200
        assert srv._prog_misses - warm <= 5
        # cf 2.0 with T=slots tokens per step never overflows here,
        # so tokens stay byte-identical to the drop-free run
        assert list(out2.values()) == list(base_out.values())
    finally:
        rc.set("hpx.serving.moe.capacity_factor", "0")


# -- autotune ----------------------------------------------------------------

def test_moe_capacity_tuner_accepts_and_replays():
    """The declared hpx.serving.moe.capacity_factor tunable, bound the
    way server_tuner binds it (hi capped at n_experts*100), accepts a
    probe on a favorable surface — compile cost measured and small —
    and the flight state replays to the identical decision log."""
    import dataclasses

    from hpx_tpu.core import config_schema
    from hpx_tpu.svc.autotune import (AdaptiveTuner, KnobBinding,
                                      TuneSignals, replay)

    entry = config_schema.tunable_keys()[
        "hpx.serving.moe.capacity_factor"]
    spec = dataclasses.replace(entry.tunable, hi=min(entry.tunable.hi,
                                                     400))
    cell = {"pct": 400}                      # auto = n_experts * 100
    knob = KnobBinding("hpx.serving.moe.capacity_factor", spec,
                       lambda: cell["pct"],
                       lambda v: cell.__setitem__("pct", max(1, v)))
    t = AdaptiveTuner([knob], interval_ticks=1, hysteresis_pct=1.0,
                      cooldown_ticks=0, compile_amortize_s=30.0)
    comp = {"s": 1.0}
    seen = set()

    def surface():
        if cell["pct"] not in seen:
            seen.add(cell["pct"])
            comp["s"] += 0.2          # each new pct mints one program
        # smaller capacity -> smaller expert exchange -> faster decode
        return TuneSignals(tok_rate=100.0 * (400.0 / cell["pct"]) ** 0.5,
                           stall_p99=0.0, queue_depth=0.0,
                           compile_s_total=comp["s"])

    for _ in range(12):
        t.maybe_tick(surface)
    assert t.accepts >= 1
    assert cell["pct"] < 400          # walked down toward cheaper routing
    assert spec.lo <= cell["pct"] <= spec.hi
    assert replay(t.flight_state()) == t.decisions()


def test_server_tuner_binds_moe_knob(params, mesh):
    """An MoE server's tuner includes the capacity knob with hi capped
    at n_experts*100; a dense server's tuner does not bind it."""
    from hpx_tpu.svc.autotune import server_tuner
    srv = ContinuousServer(params, MOE, slots=4, smax=64, mesh=mesh)
    t = server_tuner(srv)
    assert "hpx.serving.moe.capacity_factor" in t.knobs
    assert t.knobs["hpx.serving.moe.capacity_factor"].spec.hi \
        == MOE.n_experts * 100
    dense_cfg = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                      head_dim=8, n_layers=2, d_ff=64)
    dsrv = ContinuousServer(tfm.init_params(dense_cfg,
                                            jax.random.PRNGKey(1)),
                            dense_cfg, slots=2, smax=64)
    dt = server_tuner(dsrv)
    assert "hpx.serving.moe.capacity_factor" not in dt.knobs
