"""Fault-injected serving (svc/faultinject + ContinuousServer's
checkpoint/restore/shed ladder): a run with injected decode, chunked-
prefill, spec-verify and allocator-OOM faults must emit BYTE-IDENTICAL
tokens to the fault-free run (the differential contract makes restore
provable), leak zero KV blocks, and fail unrecoverable requests with
TYPED errors in `ContinuousServer.failed` instead of exceptions."""

import contextlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hpx_tpu.models import transformer as tfm
from hpx_tpu.models.serving import (
    ContinuousServer,
    DeadlineExceededError,
    RequestShedError,
    ServerClosedError,
)
from hpx_tpu.svc import faultinject

CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4, head_dim=8,
                            n_layers=2, d_ff=64)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.PRNGKey(0))


def _ref(params, cfg, prompt, max_new, eos_id=None):
    out = tfm.generate(params, cfg,
                       jnp.asarray([prompt], jnp.int32),
                       max_new=max_new, eos_id=eos_id)
    return [int(t) for t in np.asarray(out)[0]]


@contextlib.contextmanager
def _inject(**kw):
    fi = faultinject.install(faultinject.FaultInjector(**kw))
    try:
        yield fi
    finally:
        faultinject.uninstall()


REQS = [dict(prompt=[3, 1, 4, 1, 5], max_new=10),
        dict(prompt=[2, 7, 1], max_new=8),
        dict(prompt=[9, 9, 8, 2, 6, 5, 3], max_new=12),
        dict(prompt=[4, 4], max_new=6, temperature=0.9,
             key=jax.random.PRNGKey(7))]


def _serve(params, reqs=REQS, fi_kw=None, **srv_kw):
    srv = ContinuousServer(params, CFG, slots=2, smax=64, **srv_kw)
    for r in reqs:
        srv.submit(**r)
    if fi_kw is None:
        out = srv.run()
    else:
        with _inject(**fi_kw):
            out = srv.run()
    return out, srv


# -- kill-mid-decode ---------------------------------------------------------

def test_kill_mid_decode_dense_identical(params):
    base, _ = _serve(params)
    got, srv = _serve(params, fi_kw=dict(
        schedule={"decode": {2, 5, 9}}))
    assert got == base
    st = srv.fault_stats()
    assert st["injected"] == 3 and st["restored"] >= 3
    assert st["shed"] == 0
    assert srv.failed == {}


def test_kill_mid_decode_paged_identical_no_leak(params):
    kw = dict(paged=True, block_size=8, num_blocks=64)
    base, srv0 = _serve(params, **kw)
    free0 = srv0._alloc.stats()["free"]
    got, srv = _serve(params, fi_kw=dict(
        schedule={"decode": {3, 7}}), **kw)
    assert got == base
    assert srv._alloc.stats()["free"] == free0
    assert srv.fault_stats()["restored_by_site"].get("decode", 0) >= 1


# -- kill-mid-chunked-prefill ------------------------------------------------

def test_kill_mid_chunked_prefill_identical(params):
    # prefill_chunk=2 over a 7-token prompt: a chunk check faults
    # while the prefill is PENDING and another slot decodes live —
    # recovery restarts the pending from the prompt AND restores the
    # live slot; the final tokens must not change
    base, _ = _serve(params, prefill_chunk=2)
    got, srv = _serve(params, prefill_chunk=2, fi_kw=dict(
        schedule={"prefill": {3}}))
    assert got == base
    assert srv.fault_stats()["restored_by_site"].get("prefill", 0) >= 1


def test_kill_mid_chunked_prefill_paged_no_leak(params):
    kw = dict(paged=True, block_size=8, num_blocks=64, prefill_chunk=2)
    base, srv0 = _serve(params, **kw)
    free0 = srv0._alloc.stats()["free"]
    got, srv = _serve(params, fi_kw=dict(
        schedule={"prefill": {2, 4}}), **kw)
    assert got == base
    assert srv._alloc.stats()["free"] == free0


# -- kill-mid-spec-verify ----------------------------------------------------

def test_kill_mid_spec_verify_identical(params):
    base, _ = _serve(params, spec=True)
    got, srv = _serve(params, spec=True, fi_kw=dict(
        schedule={"verify": {2}}))
    assert got == base
    assert srv.fault_stats()["restored_by_site"].get("verify", 0) >= 1
    assert not srv._spec_degraded        # one fault: below the ladder


def test_repeated_verify_faults_degrade_spec_identically(params):
    # hpx.serving.spec.max_verify_faults (default 2) consecutive
    # verify faults turn speculation OFF; the sequential path emits
    # the same tokens, so output is unchanged while fault_stats
    # records the degradation
    base, _ = _serve(params, spec=True)
    got, srv = _serve(params, spec=True, fi_kw=dict(
        schedule={"verify": {1, 2}}))
    assert got == base
    assert srv._spec_degraded and not srv._spec
    assert srv.fault_stats()["degraded"] == 1


# -- OOM during admission ----------------------------------------------------

def test_oom_during_admit_defers_then_identical(params):
    # prefix_reuse off -> the radix holds nothing to evict, so the
    # injected admission OOM escalates to the defer ladder; the
    # deferred request admits on a later step and ends identical
    kw = dict(paged=True, block_size=8, num_blocks=64,
              prefix_reuse=False)
    base, _ = _serve(params, **kw)
    got, srv = _serve(params, fi_kw=dict(
        schedule={"alloc": {1}}), **kw)
    assert got == base
    assert srv.failed == {}
    st = srv.fault_stats()
    assert st["injected"] >= 1 and st["retried"] >= 1


def test_admit_oom_persisting_sheds_typed(params):
    # every alloc check faults and nothing is evictable: the
    # admission ladder exhausts hpx.serving.admit_retries and sheds
    # with a typed RequestShedError instead of raising
    kw = dict(paged=True, block_size=8, num_blocks=64,
              prefix_reuse=False)
    srv = ContinuousServer(params, CFG, slots=2, smax=64, **kw)
    rid = srv.submit([3, 1, 4], max_new=4)
    with _inject(rate=1.0, sites=["alloc"], seed=1):
        out = srv.run()
    assert out == {}
    assert isinstance(srv.failed[rid], RequestShedError)
    assert srv.failed[rid].rid == rid
    assert srv.fault_stats()["shed"] == 1
    # no block leaked by the repeatedly-failed admissions
    assert srv._alloc.stats()["in_use"] == 1   # the trash block only


# -- checkpoint refcount accounting ------------------------------------------

def test_checkpoint_pins_release_on_retire(params):
    # while a request is live its checkpoint pins blocks (extra
    # refs); after run() every pin must be gone — the free count
    # matches a fault-free server's and nothing is left pinned
    kw = dict(paged=True, block_size=4, num_blocks=64)
    base, srv0 = _serve(params, **kw)
    free0 = srv0._alloc.stats()["free"]
    got, srv = _serve(params, fi_kw=dict(
        schedule={"decode": {4}, "prefill": {1}}), **kw)
    assert got == base
    assert srv._ckpt == {}
    assert srv._alloc.stats()["free"] == free0


def test_mixed_sites_identical(params):
    # all four fault classes in one seeded run, spec + paged
    kw = dict(paged=True, block_size=8, num_blocks=64, spec=True,
              prefill_chunk=2)
    base, _ = _serve(params, **kw)
    got, srv = _serve(params, fi_kw=dict(
        schedule={"verify": {2}, "prefill": {2}, "alloc": {6}}), **kw)
    assert got == base
    assert srv.failed == {}


# -- typed errors: shutdown, deadlines, retry exhaustion ---------------------

def test_submit_after_shutdown_raises_typed(params):
    srv = ContinuousServer(params, CFG, slots=2, smax=64)
    a = srv.submit([3, 1, 4], max_new=4)
    srv.shutdown()
    with pytest.raises(ServerClosedError):
        srv.submit([2, 7], max_new=4)
    # graceful drain: the pre-shutdown request still completes
    out = srv.run()
    assert out[a] == _ref(params, CFG, [3, 1, 4], 4)


def test_submit_validation(params):
    srv = ContinuousServer(params, CFG, slots=2, smax=64)
    with pytest.raises(ValueError):
        srv.submit([3, 1], max_new=0)
    with pytest.raises(ValueError):
        srv.submit([3, 1], max_new=4, deadline_s=0.0)
    with pytest.raises(ValueError):
        srv.submit([3, 1], max_new=4, deadline_s=-1.0)


def test_deadline_sheds_queued_request(params):
    srv = ContinuousServer(params, CFG, slots=1, smax=64)
    a = srv.submit([3, 1, 4], max_new=8)
    b = srv.submit([2, 7], max_new=8, deadline_s=1e-6)
    out = srv.run()
    assert out[a] == _ref(params, CFG, [3, 1, 4], 8)
    assert b not in out
    err = srv.failed[b]
    assert isinstance(err, DeadlineExceededError)
    assert isinstance(err, RequestShedError)   # one except clause
    assert err.rid == b and err.deadline_s == 1e-6


def test_step_retry_exhaustion_sheds_everything_typed(params):
    # every decode check faults: the sync_replay budget
    # (hpx.serving.step_retries) exhausts and ALL in-flight/queued
    # requests shed typed — run() terminates instead of spinning
    srv = ContinuousServer(params, CFG, slots=2, smax=64)
    rids = [srv.submit(r["prompt"], max_new=r["max_new"])
            for r in REQS[:3]]
    with _inject(rate=1.0, sites=["decode"], seed=3):
        out = srv.run()
    assert out == {}
    for rid in rids:
        assert isinstance(srv.failed[rid], RequestShedError)
    assert srv.fault_stats()["shed"] == len(rids)


def test_no_injector_zero_overhead_path(params):
    # sanity: with nothing installed check() is a no-op and stats are
    # all zero — the hot loop pays one global read
    out, srv = _serve(params)
    st = srv.fault_stats()
    assert st["injected"] == 0 and st["restored"] == 0
    assert st["shed"] == 0 and st["restore_p99_s"] == 0.0
    for rid, r in enumerate(REQS):
        if r.get("temperature", 0.0) == 0.0:
            assert out[rid] == _ref(params, CFG, r["prompt"],
                                    r["max_new"])


# -- injector unit behavior --------------------------------------------------

def test_injector_deterministic_and_capped():
    fi = faultinject.FaultInjector(seed=42, rate=0.5, max_faults=3)
    hits = []
    for i in range(50):
        try:
            fi.check("decode")
        except faultinject.InjectedFault as e:
            hits.append((i, e.nth))
    assert fi.total_injected == 3 and len(hits) == 3
    # same seed -> same schedule
    fi2 = faultinject.FaultInjector(seed=42, rate=0.5, max_faults=3)
    hits2 = []
    for i in range(50):
        try:
            fi2.check("decode")
        except faultinject.InjectedFault as e:
            hits2.append((i, e.nth))
    assert hits2 == hits


def test_injector_typed_by_site():
    from hpx_tpu.cache.block_allocator import CacheOOM
    from hpx_tpu.core.errors import NetworkError
    fi = faultinject.FaultInjector(schedule={"alloc": {1},
                                             "locality": {1}})
    with pytest.raises(CacheOOM) as ei:
        fi.check("alloc")
    assert isinstance(ei.value, faultinject.InjectedFault)
    with pytest.raises(NetworkError) as ei:
        fi.check("locality", locality=2)
    assert ei.value.locality == 2
    stats = fi.stats()
    assert stats["alloc"]["injected"] == 1
    assert stats["locality"]["injected"] == 1
