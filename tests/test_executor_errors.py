"""Where errors land in eager vs watched device futures (VERDICT r2/r3
weak item: the one place the core future contract diverges from HPX).

The contract, pinned here and documented in exec/tpu.py + README:

  * trace/compile-time failures (bad shapes, dtype errors) surface as an
    EXCEPTIONAL FUTURE in both modes — async_execute never leaks a raise
    to the caller.
  * post-dispatch (device-side) failures:
      - watched mode: the watcher's block_until_ready observes the
        failure, so the future itself completes exceptionally — .get()
        raises. HPX semantics exactly.
      - eager mode: the future is READY the moment dispatch succeeds
        (it holds the in-flight array) — the failure surfaces at the
        first MATERIALIZATION (np.asarray / block_until_ready /
        target.synchronize), not at .get(). This is the documented
        price of zero-sync dispatch (exec/tpu.py module docstring).

On the CPU test backend, jit execution is synchronous, so real
device-side failures raise AT dispatch (async_execute catches them →
exceptional future — also pinned below). The genuinely-asynchronous
watcher path is driven with a duck-typed device value whose
block_until_ready fails, which is exactly the interface the watcher
consumes; `pytest -m tpu` (test_tpu_kernels.py) repeats the real-chip
variant.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from hpx_tpu.exec.tpu import TpuExecutor, get_future


class _FailingDeviceValue:
    """Duck-typed dispatched value whose completion fails (the watcher
    calls jax.block_until_ready, which defers to this method)."""

    def block_until_ready(self):
        raise RuntimeError("simulated device-side failure")


class TestTraceErrors:
    @pytest.mark.parametrize("eager", [True, False])
    def test_trace_error_becomes_exceptional_future(self, eager):
        ex = TpuExecutor(eager=eager)

        def bad(x):
            return jnp.dot(x, jnp.ones((7, 7)))      # shape mismatch

        fut = ex.async_execute(bad, jnp.ones((3,)))
        assert fut.has_exception()
        with pytest.raises(TypeError):
            fut.get()

    @pytest.mark.parametrize("eager", [True, False])
    def test_host_raise_in_raw_dispatch(self, eager):
        ex = TpuExecutor(eager=eager)

        def boom():
            raise ValueError("host-side")

        fut = ex.async_execute_raw(boom)
        assert fut.has_exception()
        with pytest.raises(ValueError, match="host-side"):
            fut.get()


class TestWatchedMode:
    def test_device_failure_lands_in_future(self):
        fut = get_future(_FailingDeviceValue())
        with pytest.raises(RuntimeError, match="simulated device-side"):
            fut.get()
        assert fut.has_exception()

    def test_success_value_passes_through(self):
        ex = TpuExecutor(eager=False)
        fut = ex.async_execute(lambda x: x * 2, jnp.arange(4.0))
        np.testing.assert_allclose(np.asarray(fut.get()),
                                   [0.0, 2.0, 4.0, 6.0])

    def test_watched_future_not_poisoned_by_later_use(self):
        """A watched future's value is a COMPLETED array: materializing
        it cannot raise afterward."""
        ex = TpuExecutor(eager=False)
        v = ex.async_execute(lambda x: x + 1, jnp.zeros(3)).get()
        np.testing.assert_allclose(np.asarray(v), 1.0)


class TestEagerMode:
    def test_ready_immediately_with_inflight_value(self):
        ex = TpuExecutor(eager=True)
        fut = ex.async_execute(lambda x: x + 1, jnp.zeros(3))
        assert fut.is_ready()          # ready != computed: see docstring
        np.testing.assert_allclose(np.asarray(fut.get()), 1.0)

    def test_downstream_dataflow_correct(self):
        """Eager futures feed further dispatches; XLA orders the chain."""
        ex = TpuExecutor(eager=True)
        a = ex.async_execute(lambda x: x + 1, jnp.zeros(4)).get()
        b = ex.async_execute(lambda x: x * 3, a).get()
        np.testing.assert_allclose(np.asarray(b), 3.0)
