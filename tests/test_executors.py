"""Executor & execution-policy tests.

Reference analog: libs/core/executors/tests/unit (minimal_async_executor,
fork_join_executor, executor_parameters) and libs/core/async_cuda tests
(cuda_executor future completion) — here against TpuExecutor on the CPU
mesh backend.
"""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

import hpx_tpu as hpx
from hpx_tpu.exec.policies import par, seq
from hpx_tpu.native.loader import NativePool, native_lib


def test_sequenced_executor_inline():
    ex = hpx.SequencedExecutor()
    order = []
    ex.post(order.append, 1)
    order.append(2)
    assert order == [1, 2]
    assert ex.async_execute(lambda: 5).get() == 5


def test_parallel_executor_async():
    ex = hpx.ParallelExecutor()
    assert ex.async_execute(lambda a, b: a + b, 2, 3).get(timeout=5.0) == 5


def test_thread_pool_executor_private_pool():
    ex = hpx.ThreadPoolExecutor(num_threads=2)
    try:
        fs = [ex.async_execute(lambda i=i: i * i) for i in range(20)]
        assert sorted(f.get(timeout=5.0) for f in fs) == sorted(
            i * i for i in range(20))
    finally:
        ex.shutdown()


def test_bulk_async_execute():
    ex = hpx.ParallelExecutor()
    futs = ex.bulk_async_execute(lambda i: i + 100, range(8))
    assert [f.get(timeout=5.0) for f in futs] == [100 + i for i in range(8)]


def test_then_execute():
    ex = hpx.ParallelExecutor()
    f = hpx.make_ready_future(10)
    g = ex.then_execute(lambda fut: fut.get() * 3, f)
    assert g.get(timeout=5.0) == 30


def test_fork_join_bulk_sync():
    ex = hpx.ForkJoinExecutor(num_threads=2)
    try:
        out = ex.bulk_sync_execute(lambda i: i * 2, list(range(16)))
        assert out == [i * 2 for i in range(16)]
    finally:
        ex.shutdown()


def test_fork_join_propagates_exception():
    ex = hpx.ForkJoinExecutor(num_threads=2)
    try:
        def bad(i):
            if i == 3:
                raise ValueError("bulk failure")
            return i
        with pytest.raises(ValueError, match="bulk failure"):
            ex.bulk_sync_execute(bad, list(range(8)))
    finally:
        ex.shutdown()


# -- policies ---------------------------------------------------------------

def test_policy_rebinding():
    ex = hpx.SequencedExecutor()
    p = par.on(ex)
    assert p.get_executor() is ex
    assert par.get_executor() is not ex          # original unchanged
    pt = par.task
    assert pt.is_task and not par.is_task
    pc = par.with_(hpx.static_chunk_size(4))
    assert pc.chunking.size == 4


def test_policy_with_unknown_param_raises():
    from hpx_tpu.core.errors import BadParameter
    with pytest.raises(BadParameter):
        par.with_(object())


def test_chunk_size_params():
    assert hpx.static_chunk_size(4).chunks(10, 2) == [4, 4, 2]
    assert sum(hpx.auto_chunk_size().chunks(1000, 4)) == 1000
    assert hpx.dynamic_chunk_size(3).chunks(7, 2) == [3, 3, 1]
    g = hpx.guided_chunk_size(1).chunks(100, 2)
    assert sum(g) == 100 and g[0] >= g[-1]
    assert hpx.static_chunk_size().chunks(0, 4) == []


# -- native pool ------------------------------------------------------------

def test_native_lib_builds_and_pools_work():
    assert native_lib() is not None, "native runtime must build in CI"
    p = NativePool(2)
    try:
        ev = threading.Event()
        out = []
        for i in range(50):
            p.submit(out.append, i)
        p.submit(ev.set)
        assert ev.wait(5.0)
        # drain: helpers may still be finishing appends
        deadline = threading.Event()
        for _ in range(100):
            if len(out) == 50:
                break
            deadline.wait(0.01)
        assert sorted(out) == list(range(50))
        # the executed counter is incremented AFTER the task body returns,
        # so poll: side effects (out/ev) can be visible before the final
        # fetch_add lands
        st = p.stats()
        for _ in range(100):
            if st["executed"] >= 51:
                break
            deadline.wait(0.01)
            st = p.stats()
        assert st["executed"] >= 51 and st["threads"] == 2
    finally:
        p.shutdown()


def test_native_pool_help_one_from_external_thread():
    p = NativePool(1)
    try:
        hits = []
        block = threading.Event()
        p.submit(block.wait, 5.0)       # occupy the single worker
        p.submit(hits.append, 1)
        assert p.help_one()              # external thread runs the task
        assert hits == [1]
        block.set()
    finally:
        p.shutdown()


# -- tpu executor (CPU backend in tests; same code path on device) ----------

def test_tpu_targets():
    ts = hpx.get_targets()
    assert len(ts) == 8                  # virtual CPU mesh
    assert hpx.default_target() is ts[0]
    ts[0].synchronize()


def test_tpu_executor_async_execute():
    ex = hpx.TpuExecutor()
    x = jnp.arange(8, dtype=jnp.float32)
    f = ex.async_execute(lambda a: a * 2.0, x)
    assert f.is_ready()                  # eager mode
    np.testing.assert_allclose(np.asarray(f.get()), np.arange(8) * 2.0)


def test_tpu_executor_watched_mode():
    ex = hpx.TpuExecutor(eager=False)
    x = jnp.ones((16,), jnp.float32)
    f = ex.async_execute(lambda a: a + 1.0, x)
    v = f.get(timeout=30.0)
    np.testing.assert_allclose(np.asarray(v), np.full(16, 2.0))


def test_tpu_executor_compile_error_becomes_future_exception():
    ex = hpx.TpuExecutor()
    def bad(a):
        raise TypeError("not traceable")
    f = ex.async_execute(bad, jnp.zeros(4))
    assert f.has_exception()
    with pytest.raises(TypeError):
        f.get()


def test_tpu_executor_then_execute_chains_device_ops():
    ex = hpx.TpuExecutor()
    f = ex.async_execute(lambda a: a + 1.0, jnp.zeros(4, jnp.float32))
    g = ex.then_execute(lambda v: v * 10.0, f)
    np.testing.assert_allclose(np.asarray(g.get(timeout=30.0)),
                               np.full(4, 10.0))


def test_get_future_on_raw_value():
    f = hpx.get_future(jnp.arange(4))
    assert f.get(timeout=30.0).shape == (4,)


def test_policy_on_tpu_executor_roundtrip():
    p = par.on(hpx.TpuExecutor())
    assert isinstance(p.get_executor(), hpx.TpuExecutor)


def test_native_pool_safe_after_shutdown():
    # regression: method calls after shutdown must not touch freed memory
    import pytest as _pytest
    from hpx_tpu.core.errors import HpxError
    p = NativePool(1)
    p.submit(lambda: None)
    p.shutdown()
    assert p.stats().get("shutdown") is True
    assert p.help_one() is False
    assert p.in_worker() is False
    with _pytest.raises(HpxError):
        p.submit(lambda: None)
    p.shutdown()  # idempotent
