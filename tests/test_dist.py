"""Distributed runtime tests.

Reference analog: HPX's multi-locality tests run as real processes on
localhost via hpxrun.py (SURVEY.md §4 — 'no fake network backend');
same here: serialization unit tests in-process, action/AGAS semantics on
the single-locality fast path, and the full stack as N OS processes
wired over the native TCP parcelport.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import hpx_tpu as hpx
from hpx_tpu.dist.serialization import deserialize, serialize

REPO = os.path.join(os.path.dirname(__file__), "..")

import collections
Point = collections.namedtuple("Point", "x y")  # module level: picklable


# -- serialization ----------------------------------------------------------

def test_roundtrip_basic():
    for obj in [1, "x", None, [1, 2, {"a": (3, 4)}], {"k": b"bytes"}]:
        assert deserialize(serialize(obj)) == obj


def test_roundtrip_numpy_zero_copy():
    a = np.arange(10000, dtype=np.float64)
    out = deserialize(serialize({"arr": a, "tag": 7}))
    np.testing.assert_array_equal(out["arr"], a)
    assert out["tag"] == 7


def test_roundtrip_jax_array():
    import jax.numpy as jnp
    x = jnp.arange(16, dtype=jnp.float32)
    out = deserialize(serialize([x, 5]))
    import jax
    assert isinstance(out[0], jax.Array)
    np.testing.assert_array_equal(np.asarray(out[0]), np.arange(16))


def test_roundtrip_exception():
    err = ValueError("remote boom")
    out = deserialize(serialize(err))
    assert isinstance(out, ValueError) and str(out) == "remote boom"


# -- single-locality fast path ----------------------------------------------

@hpx.plain_action
def _double(x):
    return 2 * x


@hpx.plain_action(name="test.named")
def _named():
    return "named-ok"


def test_local_action_fast_path():
    f = hpx.async_action(_double, hpx.find_here(), 21)
    assert f.get(timeout=10.0) == 42


def test_named_action_and_registry():
    from hpx_tpu.dist.actions import resolve_action
    assert resolve_action("test.named")() == "named-ok"
    with pytest.raises(hpx.HpxError):
        resolve_action("no.such.action")


def test_duplicate_action_name_rejected():
    from hpx_tpu.core.errors import BadParameter
    with pytest.raises(BadParameter):
        @hpx.plain_action(name="test.named")  # already taken
        def clash():
            pass


def test_bad_locality_raises():
    with pytest.raises(hpx.HpxError):
        hpx.async_action(_double, 99, 1)


def test_locality_api_single():
    assert hpx.find_here() == 0
    assert hpx.find_all_localities() == [0]
    assert hpx.find_remote_localities() == []
    assert hpx.get_num_localities() == 1


def test_agas_local_roundtrip():
    from hpx_tpu.dist import agas
    assert agas.register_name("k1", 123).get(timeout=10.0)
    assert agas.resolve_name("k1").get(timeout=10.0) == 123
    assert agas.unregister_name("k1").get(timeout=10.0)
    with pytest.raises(KeyError):
        agas.resolve_name("k1").get(timeout=10.0)


def test_agas_rendezvous_wait():
    from hpx_tpu.dist import agas
    f = agas.resolve_name("late-key", wait=True)
    assert not f.is_ready()
    agas.register_name("late-key", "here").get(timeout=10.0)
    assert f.get(timeout=10.0) == "here"


# -- multi-process ----------------------------------------------------------

def test_multiprocess_smoke_2_localities():
    from hpx_tpu.run import launch
    rc = launch(os.path.join(REPO, "tests", "mp_scripts", "dist_smoke.py"),
                [], localities=2, timeout=420.0)
    assert rc == 0


def test_multiprocess_smoke_4_localities():
    from hpx_tpu.run import launch
    rc = launch(os.path.join(REPO, "tests", "mp_scripts", "dist_smoke.py"),
                [], localities=4, timeout=420.0)
    assert rc == 0


def test_roundtrip_namedtuple_preserved():
    # regression: tuple subclasses must survive the jax-encode tree walk
    out = deserialize(serialize({"p": Point(1, 2), "l": [Point(3, 4)]}))
    assert out["p"].x == 1 and out["l"][0].y == 4
    import jax.numpy as jnp
    out2 = deserialize(serialize(Point(jnp.arange(3), 5)))
    assert out2.y == 5 and np.asarray(out2.x).tolist() == [0, 1, 2]


def test_unserializable_result_still_unblocks_caller():
    # regression shape (in-process analog): reply fallback stringifies
    from hpx_tpu.dist.serialization import serialize as ser
    with pytest.raises(Exception):
        ser(lambda: 1)  # lambdas don't pickle — the fallback path exists
