"""Config layering tests (reference analog: libs/core/ini tests)."""

import pytest

from hpx_tpu.core.config import Configuration, _parse_ini_text
from hpx_tpu.core.errors import BadParameter


def test_defaults_present():
    cfg = Configuration(environ={})
    assert cfg.get("hpx.localities") == "1"
    assert cfg.get_int("hpx.parcel.port") == 7910
    assert cfg.get_bool("hpx.parcel.enable")


def test_ini_parse_sections():
    data = _parse_ini_text(
        """
        ; comment
        [hpx.parcel]
        port = 1234
        address=10.0.0.1
        [hpx]
        localities = 4
        """
    )
    assert data["hpx.parcel.port"] == "1234"
    assert data["hpx.parcel.address"] == "10.0.0.1"
    assert data["hpx.localities"] == "4"


def test_env_overlay():
    cfg = Configuration(environ={"HPX_TPU_PARCEL__PORT": "9999"})
    assert cfg.get_int("hpx.parcel.port") == 9999


def test_cli_overlay_and_remaining():
    cfg = Configuration(
        argv=["prog", "--hpx:threads=4", "--hpx:ini=hpx.queuing=static",
              "--user-arg", "--hpx:dump-config"],
        environ={},
    )
    assert cfg.os_threads() == 4
    assert cfg.get("hpx.queuing") == "static"
    assert cfg.get_bool("hpx.diagnostics.dump_config")
    assert cfg.remaining_argv == ["prog", "--user-arg"]


def test_cli_layer_beats_env():
    cfg = Configuration(
        argv=["--hpx:ini=hpx.parcel.port=42"],
        environ={"HPX_TPU_PARCEL__PORT": "9999"},
    )
    assert cfg.get_int("hpx.parcel.port") == 42


def test_unknown_hpx_flag_raises():
    with pytest.raises(BadParameter):
        Configuration(argv=["--hpx:bogus=1"], environ={})


def test_programmatic_override_wins():
    cfg = Configuration(environ={}, overrides={"hpx.localities": 8})
    assert cfg.get_int("hpx.localities") == 8


def test_section_query_and_dump():
    cfg = Configuration(environ={})
    sec = cfg.section("hpx.parcel")
    assert "port" in sec and "enable" in sec
    assert "hpx.parcel.port = 7910" in cfg.dump()
