"""Config layering tests (reference analog: libs/core/ini tests)."""

import pytest

from hpx_tpu.core.config import Configuration, _parse_ini_text
from hpx_tpu.core.errors import BadParameter


def test_defaults_present():
    cfg = Configuration(environ={})
    assert cfg.get("hpx.localities") == "1"
    assert cfg.get_int("hpx.parcel.port") == 7910
    assert cfg.get_bool("hpx.parcel.enable")


def test_ini_parse_sections():
    data = _parse_ini_text(
        """
        ; comment
        [hpx.parcel]
        port = 1234
        address=10.0.0.1
        [hpx]
        localities = 4
        """
    )
    assert data["hpx.parcel.port"] == "1234"
    assert data["hpx.parcel.address"] == "10.0.0.1"
    assert data["hpx.localities"] == "4"


def test_env_overlay():
    cfg = Configuration(environ={"HPX_TPU_PARCEL__PORT": "9999"})
    assert cfg.get_int("hpx.parcel.port") == 9999


def test_cli_overlay_and_remaining():
    cfg = Configuration(
        argv=["prog", "--hpx:threads=4", "--hpx:ini=hpx.queuing=static",
              "--user-arg", "--hpx:dump-config"],
        environ={},
    )
    assert cfg.os_threads() == 4
    assert cfg.get("hpx.queuing") == "static"
    assert cfg.get_bool("hpx.diagnostics.dump_config")
    assert cfg.remaining_argv == ["prog", "--user-arg"]


def test_cli_layer_beats_env():
    cfg = Configuration(
        argv=["--hpx:ini=hpx.parcel.port=42"],
        environ={"HPX_TPU_PARCEL__PORT": "9999"},
    )
    assert cfg.get_int("hpx.parcel.port") == 42


def test_unknown_hpx_flag_raises():
    with pytest.raises(BadParameter):
        Configuration(argv=["--hpx:bogus=1"], environ={})


def test_programmatic_override_wins():
    cfg = Configuration(environ={}, overrides={"hpx.localities": 8})
    assert cfg.get_int("hpx.localities") == 8


def test_section_query_and_dump():
    cfg = Configuration(environ={})
    sec = cfg.section("hpx.parcel")
    assert "port" in sec and "enable" in sec
    assert "hpx.parcel.port = 7910" in cfg.dump()


def test_strict_mode_rejects_undeclared_keys():
    cfg = Configuration(environ={}, strict=True)
    with pytest.raises(BadParameter, match="undeclared"):
        cfg.set("hpx.cache.kv_dytpe", "int8")   # transposed typo
    with pytest.raises(BadParameter, match="undeclared"):
        cfg.get("hpx.serving.paged_kernal")
    # non-hpx keys are application-private, never policed
    cfg.set("myapp.anything", "1")
    assert Configuration(environ={}).get("hpx.nope") is None  # lax: ok


def test_strict_mode_enforces_declared_choices():
    """Enumerated knobs (declared with choices=) reject out-of-set
    values at set() time with the valid set spelled out — a typo'd
    kv_dtype fails HERE, not as a downstream serving error."""
    cfg = Configuration(environ={}, strict=True)
    for ok in ("bf16", "int8", "fp8"):
        cfg.set("hpx.cache.kv_dtype", ok)
    for ok in ("auto", "gather", "fused", "fused_online"):
        cfg.set("hpx.serving.paged_kernel", ok)
    with pytest.raises(BadParameter, match="bf16.*int8.*fp8"):
        cfg.set("hpx.cache.kv_dtype", "fp8_e5m2")
    with pytest.raises(BadParameter, match="fused_online"):
        cfg.set("hpx.serving.paged_kernel", "online")
    # free-form str keys stay free-form under strict
    cfg.set("hpx.queuing", "whatever-scheduler")
    # lax mode: choices are documentation, not enforcement
    Configuration(environ={}).set("hpx.cache.kv_dtype", "fp8_e5m2")


def test_declare_validates_choices():
    from hpx_tpu.core import config_schema
    with pytest.raises(ValueError, match="choices"):
        config_schema.declare("hpx.test.bogus_choice_key", "str", "c",
                              "default outside its own choices",
                              choices=("a", "b"))
    assert not config_schema.is_declared("hpx.test.bogus_choice_key")
    key = config_schema.lookup("hpx.cache.kv_dtype")
    assert key.choices == ("bf16", "int8", "fp8")
    assert config_schema.lookup("hpx.serving.paged_kernel").choices == \
        ("auto", "gather", "fused", "fused_online")
