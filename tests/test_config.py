"""Config layering tests (reference analog: libs/core/ini tests)."""

import pytest

from hpx_tpu.core.config import Configuration, _parse_ini_text
from hpx_tpu.core.errors import BadParameter


def test_defaults_present():
    cfg = Configuration(environ={})
    assert cfg.get("hpx.localities") == "1"
    assert cfg.get_int("hpx.parcel.port") == 7910
    assert cfg.get_bool("hpx.parcel.enable")


def test_ini_parse_sections():
    data = _parse_ini_text(
        """
        ; comment
        [hpx.parcel]
        port = 1234
        address=10.0.0.1
        [hpx]
        localities = 4
        """
    )
    assert data["hpx.parcel.port"] == "1234"
    assert data["hpx.parcel.address"] == "10.0.0.1"
    assert data["hpx.localities"] == "4"


def test_env_overlay():
    cfg = Configuration(environ={"HPX_TPU_PARCEL__PORT": "9999"})
    assert cfg.get_int("hpx.parcel.port") == 9999


def test_cli_overlay_and_remaining():
    cfg = Configuration(
        argv=["prog", "--hpx:threads=4", "--hpx:ini=hpx.queuing=static",
              "--user-arg", "--hpx:dump-config"],
        environ={},
    )
    assert cfg.os_threads() == 4
    assert cfg.get("hpx.queuing") == "static"
    assert cfg.get_bool("hpx.diagnostics.dump_config")
    assert cfg.remaining_argv == ["prog", "--user-arg"]


def test_cli_layer_beats_env():
    cfg = Configuration(
        argv=["--hpx:ini=hpx.parcel.port=42"],
        environ={"HPX_TPU_PARCEL__PORT": "9999"},
    )
    assert cfg.get_int("hpx.parcel.port") == 42


def test_unknown_hpx_flag_raises():
    with pytest.raises(BadParameter):
        Configuration(argv=["--hpx:bogus=1"], environ={})


def test_programmatic_override_wins():
    cfg = Configuration(environ={}, overrides={"hpx.localities": 8})
    assert cfg.get_int("hpx.localities") == 8


def test_section_query_and_dump():
    cfg = Configuration(environ={})
    sec = cfg.section("hpx.parcel")
    assert "port" in sec and "enable" in sec
    assert "hpx.parcel.port = 7910" in cfg.dump()


def test_strict_mode_rejects_undeclared_keys():
    cfg = Configuration(environ={}, strict=True)
    with pytest.raises(BadParameter, match="undeclared"):
        cfg.set("hpx.cache.kv_dytpe", "int8")   # transposed typo
    with pytest.raises(BadParameter, match="undeclared"):
        cfg.get("hpx.serving.paged_kernal")
    # non-hpx keys are application-private, never policed
    cfg.set("myapp.anything", "1")
    assert Configuration(environ={}).get("hpx.nope") is None  # lax: ok


def test_strict_mode_enforces_declared_choices():
    """Enumerated knobs (declared with choices=) reject out-of-set
    values at set() time with the valid set spelled out — a typo'd
    kv_dtype fails HERE, not as a downstream serving error."""
    cfg = Configuration(environ={}, strict=True)
    for ok in ("bf16", "int8", "fp8"):
        cfg.set("hpx.cache.kv_dtype", ok)
    for ok in ("auto", "gather", "fused", "fused_online"):
        cfg.set("hpx.serving.paged_kernel", ok)
    with pytest.raises(BadParameter, match="bf16.*int8.*fp8"):
        cfg.set("hpx.cache.kv_dtype", "fp8_e5m2")
    with pytest.raises(BadParameter, match="fused_online"):
        cfg.set("hpx.serving.paged_kernel", "online")
    # free-form str keys stay free-form under strict
    cfg.set("hpx.logging.destination", "wherever.log")
    # lax mode: choices are documentation, not enforcement
    Configuration(environ={}).set("hpx.cache.kv_dtype", "fp8_e5m2")


def test_strict_mode_reserved_vs_unknown_are_distinct_errors():
    """A typo'd key and a declared-but-reserved key are different
    mistakes: the first needs a schema declaration, the second has no
    runtime reader so the write would be silently ignored. Strict
    set() raises a DISTINCT type for each so callers can tell them
    apart."""
    from hpx_tpu.core.errors import (ReservedConfigKey,
                                     UndeclaredConfigKey)
    cfg = Configuration(environ={}, strict=True)
    with pytest.raises(UndeclaredConfigKey, match="undeclared"):
        cfg.set("hpx.serving.prefil_chunk", "64")    # typo
    with pytest.raises(ReservedConfigKey, match="reserved"):
        cfg.set("hpx.queuing", "static")             # parity-only key
    # both are BadParameter subclasses: existing catch-alls still work
    assert issubclass(UndeclaredConfigKey, BadParameter)
    assert issubclass(ReservedConfigKey, BadParameter)
    # reserved keys still ARRIVE through the ini/CLI layers (reference
    # invocations keep working); only runtime set() is policed
    via_cli = Configuration(argv=["--hpx:queuing=static"], environ={},
                            strict=True)
    assert via_cli.get("hpx.queuing") == "static"
    # lax mode: unchanged (reserved set() stays a no-op-by-convention)
    Configuration(environ={}).set("hpx.queuing", "static")


def test_set_bumps_generation():
    """Every set() bumps the change counter a live server polls to
    re-read its tunable knobs at the next flush boundary."""
    cfg = Configuration(environ={})
    g0 = cfg.generation()
    cfg.set("hpx.serving.prefill_chunk", "64")
    assert cfg.generation() == g0 + 1
    cfg.set("hpx.serving.max_async_steps", "8")
    assert cfg.generation() == g0 + 2


def test_declare_validates_choices():
    from hpx_tpu.core import config_schema
    with pytest.raises(ValueError, match="choices"):
        config_schema.declare("hpx.test.bogus_choice_key", "str", "c",
                              "default outside its own choices",
                              choices=("a", "b"))
    assert not config_schema.is_declared("hpx.test.bogus_choice_key")
    key = config_schema.lookup("hpx.cache.kv_dtype")
    assert key.choices == ("bf16", "int8", "fp8")
    assert config_schema.lookup("hpx.serving.paged_kernel").choices == \
        ("auto", "gather", "fused", "fused_online")


def test_tunable_registry():
    """The tunable subset is the closed set of knobs the adaptive
    tuner may move; each carries bounds and a compile-cost flag."""
    from hpx_tpu.core import config_schema
    tk = config_schema.tunable_keys()
    assert "hpx.serving.prefill_chunk" in tk
    assert "hpx.serving.max_async_steps" in tk
    assert "hpx.serving.spec.k" in tk
    assert "hpx.cache.radix_budget_blocks" in tk
    spec = tk["hpx.serving.prefill_chunk"].tunable
    assert spec.compiles and spec.geometric and spec.lo <= 128 <= spec.hi
    assert not tk["hpx.serving.max_async_steps"].tunable.compiles
    # bool/float knobs have no bounded-step semantics
    with pytest.raises(ValueError, match="tunable"):
        config_schema.declare("hpx.test.bogus_tunable", "bool", "0",
                              "no step semantics",
                              tunable=config_schema.Tunable(lo=0, hi=1))
    assert not config_schema.is_declared("hpx.test.bogus_tunable")
