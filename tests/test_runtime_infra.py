"""Timing, topology, resource partitioner, batch environment tests.

Reference analogs: libs/core/timing + timed_execution, libs/core/topology,
libs/core/resource_partitioner, libs/core/batch_environments tests
(SURVEY.md §2.1, §2.5).
"""

import time

import pytest

import hpx_tpu as hpx
from hpx_tpu.runtime.batch_environments import (_expand_slurm_nodelist,
                                                detect)
from hpx_tpu.testing import HPX_TEST, HPX_TEST_EQ


# -- timing ------------------------------------------------------------------

class TestTiming:
    def test_high_resolution_timer(self):
        t = hpx.HighResolutionTimer()
        time.sleep(0.01)
        e = t.elapsed()
        HPX_TEST(0.005 < e < 5.0, e)
        HPX_TEST(t.elapsed_microseconds() >= 5000)
        t.restart()
        HPX_TEST(t.elapsed() < e)

    def test_clock_now_monotone(self):
        a = hpx.high_resolution_clock_now()
        b = hpx.high_resolution_clock_now()
        HPX_TEST(b >= a)

    def test_async_after(self):
        t0 = time.monotonic()
        f = hpx.async_after(0.05, lambda: "late")
        HPX_TEST_EQ(f.get(), "late")
        HPX_TEST(time.monotonic() - t0 >= 0.045)

    def test_async_after_ordering(self):
        out = []
        f1 = hpx.async_after(0.08, out.append, 2)
        f2 = hpx.async_after(0.02, out.append, 1)
        hpx.wait_all([f1, f2])
        HPX_TEST_EQ(out, [1, 2])

    def test_async_at(self):
        f = hpx.async_at(time.monotonic() + 0.03, lambda: 5)
        HPX_TEST_EQ(f.get(), 5)

    def test_timer_exception(self):
        def boom():
            raise ValueError("late boom")
        with pytest.raises(ValueError):
            hpx.async_after(0.01, boom).get()

    def test_timed_executor(self):
        ex = hpx.TimedExecutor()
        t0 = time.monotonic()
        HPX_TEST_EQ(ex.async_execute_after(0.03, lambda v: v + 1, 1).get(), 2)
        HPX_TEST(time.monotonic() - t0 >= 0.025)
        with pytest.raises(ValueError):
            ex.async_execute_after(
                0.01, lambda: (_ for _ in ()).throw(ValueError())).get()


# -- topology ----------------------------------------------------------------

class TestTopology:
    def test_host_counts(self):
        topo = hpx.get_topology()
        HPX_TEST(topo.number_of_cores() >= 1)
        HPX_TEST(topo.number_of_pus() >= 1)

    def test_device_counts(self, devices):
        topo = hpx.get_topology()
        HPX_TEST_EQ(topo.number_of_devices(), 8)
        HPX_TEST_EQ(topo.number_of_local_devices(), 8)
        HPX_TEST_EQ(topo.platform(), "cpu")
        HPX_TEST(isinstance(topo.device_kind(), str))
        HPX_TEST_EQ(topo.number_of_processes(), 1)
        HPX_TEST_EQ(topo.process_index(), 0)
        HPX_TEST_EQ(len(topo.devices_by_process()[0]), 8)
        # CPU devices expose no ICI coords
        HPX_TEST(topo.ici_shape() is None
                 or isinstance(topo.ici_shape(), tuple))
        HPX_TEST(isinstance(topo.device_memory_stats(), dict))


# -- resource partitioner ----------------------------------------------------

class TestResourcePartitioner:
    def test_pools_and_executors(self, devices):
        rp = hpx.ResourcePartitioner()
        rp.create_pool("io", 1)
        rp.create_pool("halo", 1, devices=devices[:2])
        try:
            io = rp.get_pool("io")
            HPX_TEST_EQ(io.num_threads, 1)
            HPX_TEST_EQ(io.executor().async_execute(lambda: 42).get(), 42)
            halo = rp.get_pool("halo")
            mesh = halo.mesh(axis_names=("ring",))
            HPX_TEST_EQ(mesh.shape["ring"], 2)
            default = rp.get_pool()
            HPX_TEST(default.num_threads >= 1)
            HPX_TEST_EQ(len(default.devices), 6)   # 8 - 2 assigned
            HPX_TEST_EQ(sorted(rp.pool_names()),
                        ["default", "halo", "io"])
        finally:
            rp.shutdown()

    def test_overcommit_threads_raises(self):
        rp = hpx.ResourcePartitioner()
        with pytest.raises(hpx.HpxError):
            rp.create_pool("huge", 10**6)

    def test_create_after_finalize_raises(self):
        rp = hpx.ResourcePartitioner()
        rp.get_pool()       # finalizes
        with pytest.raises(hpx.HpxError):
            rp.create_pool("late", 1)
        rp.shutdown()

    def test_duplicate_pool_raises(self):
        rp = hpx.ResourcePartitioner()
        rp.create_pool("a", 1)
        with pytest.raises(hpx.HpxError):
            rp.create_pool("a", 1)
        rp.shutdown()

    def test_pool_without_devices_mesh_raises(self):
        rp = hpx.ResourcePartitioner()
        rp.create_pool("cpuonly", 1)
        with pytest.raises(hpx.HpxError):
            rp.get_pool("cpuonly").mesh()
        rp.shutdown()


# -- batch environments ------------------------------------------------------

class TestBatchEnvironments:
    def test_none(self):
        be = detect({})
        HPX_TEST(not be.found())
        HPX_TEST_EQ(be.config_overrides(), {})

    def test_slurm(self):
        be = detect({
            "SLURM_JOB_ID": "123", "SLURM_NTASKS": "4",
            "SLURM_PROCID": "2",
            "SLURM_JOB_NODELIST": "nid[001-003],login1",
        })
        HPX_TEST_EQ(be.name, "slurm")
        HPX_TEST_EQ(be.num_localities, 4)
        HPX_TEST_EQ(be.this_locality, 2)
        HPX_TEST_EQ(be.node_list,
                    ["nid001", "nid002", "nid003", "login1"])
        ov = be.config_overrides()
        HPX_TEST_EQ(ov["hpx.localities"], "4")
        HPX_TEST_EQ(ov["hpx.locality"], "2")
        HPX_TEST_EQ(ov["hpx.parcel.address"], "nid001")

    def test_slurm_nodelist_forms(self):
        HPX_TEST_EQ(_expand_slurm_nodelist("n1"), ["n1"])
        HPX_TEST_EQ(_expand_slurm_nodelist("n[1,3]"), ["n1", "n3"])
        HPX_TEST_EQ(_expand_slurm_nodelist("n[08-10]"),
                    ["n08", "n09", "n10"])
        HPX_TEST_EQ(_expand_slurm_nodelist("a1,b[2-3]"),
                    ["a1", "b2", "b3"])

    def test_openmpi(self):
        be = detect({"OMPI_COMM_WORLD_SIZE": "8",
                     "OMPI_COMM_WORLD_RANK": "5"})
        HPX_TEST_EQ((be.name, be.num_localities, be.this_locality),
                    ("openmpi", 8, 5))

    def test_tpu_pod(self):
        be = detect({"TPU_WORKER_ID": "1",
                     "TPU_WORKER_HOSTNAMES": "h0,h1,h2,h3"})
        HPX_TEST_EQ((be.name, be.num_localities, be.this_locality),
                    ("tpu", 4, 1))

    def test_config_integration(self):
        cfg = hpx.Configuration(environ={
            "SLURM_JOB_ID": "1", "SLURM_NTASKS": "2", "SLURM_PROCID": "1",
        })
        HPX_TEST_EQ(cfg.get_int("hpx.localities"), 2)
        HPX_TEST_EQ(cfg.get_int("hpx.locality"), 1)

    def test_cli_beats_batch(self):
        cfg = hpx.Configuration(
            argv=["--hpx:localities=7"],
            environ={"SLURM_JOB_ID": "1", "SLURM_NTASKS": "2"})
        HPX_TEST_EQ(cfg.get_int("hpx.localities"), 7)


def test_late_join_attach():
    """--hpx:connect analog (SURVEY §5.3): a third process attaches to a
    running 2-locality job, gets locality id 2, and actions flow both
    ways (tests/mp_scripts/late_join_smoke.py)."""
    import os
    from hpx_tpu.run import launch
    repo = os.path.join(os.path.dirname(__file__), "..")
    rc = launch(os.path.join(repo, "tests", "mp_scripts",
                             "late_join_smoke.py"),
                [], localities=2, timeout=420.0)
    assert rc == 0


@pytest.mark.soak
def test_eight_locality_soak():
    """8 real processes: collectives generations, communication_set
    tree, channel soak, migrate-vs-invoke storm
    (tests/mp_scripts/eight_locality_smoke.py)."""
    import os
    from hpx_tpu.run import launch
    repo = os.path.join(os.path.dirname(__file__), "..")
    # 8 jax processes share one sandbox core: imports alone are ~5 min
    rc = launch(os.path.join(repo, "tests", "mp_scripts",
                             "eight_locality_smoke.py"),
                [], localities=8, timeout=900.0)
    assert rc == 0


def test_yield_while_mass_blocking_depth_bounded():
    """yield_while help chains are bounded by the same in-help_one
    depth cap as future waits (the cap lives in help_one, so every
    help site is covered)."""
    import threading
    import hpx_tpu as hpx
    n = 600
    gate = threading.Event()

    def task():
        hpx.exec.yield_while(lambda: not gate.is_set())

    hpx.post_many(task, [()] * n)
    import time
    time.sleep(0.3)                   # let the helpers dive
    gate.set()
    latch = hpx.Latch(2)
    hpx.post(lambda: latch.count_down(1))
    latch.arrive_and_wait()           # pool still functional afterwards
