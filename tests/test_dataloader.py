"""DeviceLoader (runtime/dataloader.py): prefetching host->device input
pipeline on the io_service substrate."""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hpx_tpu.runtime.dataloader import DeviceLoader


def test_batches_arrive_in_order_on_device():
    batches = [np.full((4,), i, np.float32) for i in range(10)]
    out = list(DeviceLoader(batches))
    assert len(out) == 10
    for i, x in enumerate(out):
        assert isinstance(x, jax.Array)
        np.testing.assert_array_equal(np.asarray(x), batches[i])


def test_sharded_placement(devices):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.array(devices), ("x",))
    sh = NamedSharding(mesh, P("x"))
    batches = [np.arange(16, dtype=np.float32) for _ in range(3)]
    for x in DeviceLoader(batches, sharding=sh):
        assert x.sharding == sh


def test_pytree_batches_and_transform():
    batches = [{"x": np.ones((2,), np.float32) * i,
                "y": np.int32(i)} for i in range(5)]
    loader = DeviceLoader(batches,
                          transform=lambda b: {**b, "x": b["x"] + 1})
    got = list(loader)
    assert float(got[3]["x"][0]) == 4.0
    assert int(got[4]["y"]) == 4


def test_backpressure_bounds_prefetch():
    produced = []

    def gen():
        for i in range(50):
            produced.append(i)
            yield np.float32(i)

    loader = DeviceLoader(gen(), prefetch_depth=2)
    it = iter(loader)
    next(it)
    time.sleep(0.3)                # give the producer time to run ahead
    # 1 consumed + 2 queued + at most a couple in flight
    assert len(produced) <= 6, len(produced)
    loader.stop()


def test_producer_exception_surfaces_at_pop():
    def gen():
        yield np.float32(1)
        raise RuntimeError("source broke")

    it = iter(DeviceLoader(gen()))
    next(it)
    with pytest.raises(RuntimeError, match="source broke"):
        next(it)


def test_training_loop_integration():
    """Feed a real train step from the loader (the three-stage overlap
    is behavioral here — CPU — but the wiring is end-to-end)."""
    import hpx_tpu.models.transformer as tfm
    cfg = tfm.TransformerConfig(vocab=32, d_model=16, n_heads=2,
                                head_dim=8, n_layers=1, d_ff=32, lr=0.05)
    mesh1 = tfm.make_mesh_3d(1)
    params = tfm.shard_params(tfm.init_params(cfg, jax.random.PRNGKey(0)),
                              cfg, mesh1)
    step = tfm.make_train_step(cfg, mesh1)

    rng = np.random.default_rng(0)

    def batches():
        for _ in range(6):
            t = rng.integers(0, 32, (2, 17)).astype(np.int32)
            yield t[:, :-1], t[:, 1:]

    losses = []
    from jax.sharding import NamedSharding, PartitionSpec as P
    sh = NamedSharding(mesh1, P("dp", "sp"))
    for toks, tgts in DeviceLoader(batches(), sharding=sh):
        params, loss = step(params, toks, tgts)
        losses.append(float(loss))
    assert len(losses) == 6 and np.isfinite(losses).all()


def test_stop_mid_stream():
    def gen():
        i = 0
        while True:
            yield np.float32(i)
            i += 1

    loader = DeviceLoader(gen(), prefetch_depth=2)
    it = iter(loader)
    for _ in range(3):
        next(it)
    loader.stop()          # must not hang or leak a spinning producer
    time.sleep(0.2)


def test_second_iteration_raises():
    loader = DeviceLoader([np.float32(1)])
    assert len(list(loader)) == 1
    with pytest.raises(RuntimeError, match="single-pass"):
        iter(loader).__next__()


def test_abandoned_loader_frees_the_pool():
    """Dropping a partially-consumed loader must not wedge the shared
    'data' pool: the producer holds no loader reference, so GC stops
    it and a NEW loader's stream still flows."""
    import gc

    def gen():
        i = 0
        while True:
            yield np.float32(i)
            i += 1

    loader = DeviceLoader(gen(), prefetch_depth=1)
    it = iter(loader)
    next(it)
    del it, loader
    gc.collect()
    time.sleep(0.3)                    # let the old producer notice
    fresh = list(DeviceLoader([np.float32(7), np.float32(8)]))
    assert [float(x) for x in fresh] == [7.0, 8.0]


def test_stop_wakes_blocked_consumer():
    def gen():
        yield np.float32(0)
        while True:                    # source never ends, never yields
            time.sleep(0.05)

    loader = DeviceLoader(gen())
    it = iter(loader)
    next(it)
    got = []

    def consume():
        got.extend(iter(it))           # blocks on the empty queue

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    time.sleep(0.2)
    loader.stop()
    t.join(5.0)
    assert not t.is_alive()


def test_two_concurrent_loaders():
    """Streaming loops get dedicated threads: two live loaders must
    interleave (a shared 1-thread pool would deadlock the second)."""
    a = DeviceLoader([np.float32(i) for i in range(4)])
    b = DeviceLoader([np.float32(10 + i) for i in range(4)])
    pairs = list(zip(iter(a), iter(b)))
    assert [(float(x), float(y)) for x, y in pairs] == \
        [(0.0, 10.0), (1.0, 11.0), (2.0, 12.0), (3.0, 13.0)]


def test_break_stops_the_producer():
    """Leaving the loop early behaves like stop(): the producer exits
    (and releases its queue slots) without stop() being called."""
    produced = []

    def gen():
        for i in range(100):
            produced.append(i)
            yield np.float32(i)

    loader = DeviceLoader(gen(), prefetch_depth=1)
    for x in loader:
        break                          # generator close -> finally
    time.sleep(0.4)
    n = len(produced)
    time.sleep(0.3)
    assert len(produced) == n          # production stopped
    assert loader._stop.is_set()
