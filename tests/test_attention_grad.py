"""Gradients through the pallas flash-attention kernels.

The round-2/3 verdicts' #1 item: training must be able to differentiate
through the flash path. flash_attention carries a jax.custom_vjp whose
backward runs the two-pass pallas kernels
(ops/attention_pallas.flash_attention_bwd); the ring path
(ops/attention._ring_flash) carries its own custom_vjp that replays the
ring, rotating dK/dV partials around with their chunks.

Oracle: the O(S^2) softmax written NaN-safely (stop-gradient row max,
zero rows with no visible keys) — reference_attention's plain softmax
NaNs on fully-masked rows and poisons every gradient, and
blockwise_attention's scan transpose does the same, so neither can
serve as a grad oracle for causal sq > sk.

All pallas runs here are interpret mode on the CPU mesh (same kernel
code the TPU compiles). The ring shard_map uses check_vma=False:
pallas interpret mode cannot run inside a vma-checked shard_map on CPU
(its interpreter loop mixes varying/unvarying dynamic_slices); the
vma-checked wiring is exercised on real TPU via `pytest -m tpu`.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hpx_tpu.ops.attention import (_ring_flash, reference_attention,
                                   ulysses_attention)
from hpx_tpu.ops.attention_pallas import flash_attention
from hpx_tpu.parallel import make_mesh


def grad_oracle(q, k, v, causal):
    """NaN-safe O(S^2) attention for gradient comparison. Rows with no
    visible keys output 0 and carry zero gradient (the flash kernels'
    convention)."""
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    h = q.shape[-1]
    s = jnp.einsum("bqnh,bknh->bnqk", qf, kf) / np.sqrt(h)
    sq, sk = s.shape[-2], s.shape[-1]
    mask = jnp.ones((sq, sk), bool)
    if causal:
        mask = jnp.tril(mask, k=sk - sq)
    s = jnp.where(mask, s, -jnp.inf)
    m = jax.lax.stop_gradient(jnp.max(s, axis=-1, keepdims=True))
    m = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(jnp.where(mask, s - m, -jnp.inf)) * mask
    den = p.sum(-1, keepdims=True)
    out = jnp.einsum("bnqk,bknh->bqnh", p / jnp.where(den > 0, den, 1.0),
                     vf)
    return out.astype(q.dtype)


def _rand(shape, seed, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(shape, np.float32), dtype)


def _grads(fn, q, k, v, w):
    return jax.grad(lambda q, k, v: jnp.sum(fn(q, k, v).astype(
        jnp.float32) * w), argnums=(0, 1, 2))(q, k, v)


def _cmp(got, want, tol):
    for name, a, b in zip("qkv", got, want):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=tol, atol=tol, err_msg=f"d{name}")


class TestFlashGrad:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("sq,sk", [(64, 64), (37, 53), (48, 16),
                                       (16, 48)])
    def test_matches_oracle(self, causal, sq, sk):
        B, N, H = 2, 2, 32
        q = _rand((B, sq, N, H), 0)
        k = _rand((B, sk, N, H), 1)
        v = _rand((B, sk, N, H), 2)
        w = _rand((B, sq, N, H), 3)
        want = _grads(lambda q, k, v: grad_oracle(q, k, v, causal),
                      q, k, v, w)
        got = _grads(
            lambda q, k, v: flash_attention(q, k, v, causal,
                                            block_q=16, block_k=16),
            q, k, v, w)
        _cmp(got, want, 3e-4)

    def test_bfloat16(self):
        B, S, N, H = 2, 64, 2, 32
        q, k, v, w = (_rand((B, S, N, H), i, jnp.bfloat16)
                      for i in range(4))
        wf = w.astype(jnp.float32)
        want = _grads(lambda q, k, v: grad_oracle(q, k, v, True),
                      q, k, v, wf)
        got = _grads(
            lambda q, k, v: flash_attention(q, k, v, True,
                                            block_q=16, block_k=16),
            q, k, v, wf)
        assert got[0].dtype == jnp.bfloat16
        _cmp(got, want, 5e-2)

    def test_value_and_grad_under_jit(self):
        B, S, N, H = 1, 32, 2, 16
        q, k, v = (_rand((B, S, N, H), i) for i in range(3))

        @jax.jit
        def f(q, k, v):
            return jax.value_and_grad(
                lambda q: jnp.sum(flash_attention(q, k, v, True,
                                                  block_q=8,
                                                  block_k=8)))(q)

        val, g = f(q, k, v)
        assert np.isfinite(float(val))
        assert g.shape == q.shape


class TestRingFlashGrad:
    """_ring_flash's custom_vjp: replayed ring with rotating dK/dV
    accumulators, against the oracle through real ppermute plumbing."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_oracle(self, causal, devices):
        from hpx_tpu.utils.jaxcompat import shard_map
        from jax.sharding import Mesh, PartitionSpec as P
        mesh = Mesh(np.array(devices[:4]), ("sp",))
        B, S, N, H = 2, 64, 2, 32
        q, k, v, w = (_rand((B, S, N, H), i + 10) for i in range(4))
        spec = P(None, "sp", None, None)

        def loss(q, k, v):
            def body(qc, kc, vc, wc):
                o = _ring_flash(qc, kc, vc, "sp", 4, causal)
                return jax.lax.psum(jnp.sum(o * wc), "sp")

            return jax.jit(shard_map(
                body, mesh=mesh, in_specs=(spec,) * 4, out_specs=P(),
                check_vma=False))(q, k, v, w)

        got = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        want = _grads(lambda q, k, v: grad_oracle(q, k, v, causal),
                      q, k, v, w)
        _cmp(got, want, 3e-4)

    def test_forward_value_matches(self, devices):
        from hpx_tpu.utils.jaxcompat import shard_map
        from jax.sharding import Mesh, PartitionSpec as P
        mesh = Mesh(np.array(devices[:4]), ("sp",))
        B, S, N, H = 2, 64, 2, 32
        q, k, v = (_rand((B, S, N, H), i + 20) for i in range(3))
        spec = P(None, "sp", None, None)
        out = jax.jit(shard_map(
            lambda qc, kc, vc: _ring_flash(qc, kc, vc, "sp", 4, True),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
            check_vma=False))(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(reference_attention(q, k, v,
                                                            True)),
            rtol=3e-4, atol=3e-4)


class TestUlyssesGrad:
    """Ulysses differentiates through the blockwise path on CPU (flash
    defaults on only for TPU, where its custom_vjp takes over)."""

    def test_matches_oracle(self):
        mesh = make_mesh((4,), ("sp",), jax.devices()[:4])
        B, S, N, H = 2, 64, 4, 16
        q, k, v, w = (_rand((B, S, N, H), i + 30) for i in range(4))
        got = _grads(
            lambda q, k, v: ulysses_attention(q, k, v, mesh, "sp", True),
            q, k, v, w)
        want = _grads(lambda q, k, v: grad_oracle(q, k, v, True),
                      q, k, v, w)
        _cmp(got, want, 3e-4)


class TestGQA:
    """Grouped-query attention: k/v carry fewer heads than q, shared
    per group via index remapping (no materialized repeat). Oracle:
    repeat kv heads and run the dense-head path; dK/dV oracle grads
    group-sum over the repeated heads."""

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("nq,nkv", [(4, 2), (4, 1), (8, 4)])
    def test_forward_matches_repeat_oracle(self, causal, nq, nkv):
        B, S, H = 2, 64, 16
        q = _rand((B, S, nq, H), 40)
        k = _rand((B, S, nkv, H), 41)
        v = _rand((B, S, nkv, H), 42)
        rep = nq // nkv
        got = flash_attention(q, k, v, causal, block_q=16, block_k=16)
        want = flash_attention(q, jnp.repeat(k, rep, axis=2),
                               jnp.repeat(v, rep, axis=2), causal,
                               block_q=16, block_k=16)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_repeat_oracle(self, causal):
        B, S, H, nq, nkv = 2, 64, 16, 4, 2
        rep = nq // nkv
        q = _rand((B, S, nq, H), 43)
        k = _rand((B, S, nkv, H), 44)
        v = _rand((B, S, nkv, H), 45)
        w = _rand((B, S, nq, H), 46)

        got = _grads(
            lambda q, k, v: flash_attention(q, k, v, causal,
                                            block_q=16, block_k=16),
            q, k, v, w)

        def oracle(q, k, v):
            return grad_oracle(q, jnp.repeat(k, rep, axis=2),
                               jnp.repeat(v, rep, axis=2), causal)

        # jnp.repeat lives INSIDE the oracle fn, so AD already
        # group-sums its transpose: oracle grads come back in
        # [B, S, nkv, H] directly comparable to the kernel's
        want = _grads(oracle, q, k, v, w)
        _cmp(got, want, 3e-4)

    def test_indivisible_heads_raises(self):
        q = _rand((1, 16, 3, 8), 47)
        k = _rand((1, 16, 2, 8), 48)
        with pytest.raises(ValueError, match="heads"):
            flash_attention(q, k, k)


class TestStripedRingGrad:
    """Striped causal ring (offsets in {0,-1}) must produce the
    reference gradients — both the custom_vjp flash path and AD
    through the XLA scan."""

    def _striped(self, q, k, v, w, devices, use_flash):
        from hpx_tpu.utils.jaxcompat import shard_map
        from jax.sharding import Mesh, PartitionSpec as P
        from hpx_tpu.ops.attention import (
            ring_attention_sharded, stripe_sequence)
        mesh = Mesh(np.array(devices[:4]), ("sp",))
        spec = P(None, "sp", None, None)

        def loss(q, k, v):
            qs, ks, vs, ws = (stripe_sequence(x, 4)
                              for x in (q, k, v, w))

            def body(qc, kc, vc, wc):
                o = ring_attention_sharded(qc, kc, vc, "sp", 4,
                                           causal=True,
                                           use_flash=use_flash,
                                           striped=True)
                return jax.lax.psum(jnp.sum(o * wc), "sp")

            return jax.jit(shard_map(
                body, mesh=mesh, in_specs=(spec,) * 4, out_specs=P(),
                check_vma=False))(qs, ks, vs, ws)

        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    @pytest.mark.parametrize("use_flash", [False, True])
    def test_matches_oracle(self, use_flash, devices):
        B, S, N, H = 2, 64, 2, 32
        q, k, v, w = (_rand((B, S, N, H), i + 30) for i in range(4))
        got = self._striped(q, k, v, w, devices, use_flash)
        want = _grads(lambda q, k, v: grad_oracle(q, k, v, True),
                      q, k, v, w)
        _cmp(got, want, 3e-4)


class TestRingFlashGQAGrad:
    """GQA through the flash ring with GROUPED chunks on the wire: the
    backward's dK/dV partials rotate in the kv-head layout. Grads must
    match the repeat-K/V oracle — contiguous AND striped layouts (the
    two features interact inside one _ring_flash fwd/bwd)."""

    @pytest.mark.parametrize("striped", [False, True])
    def test_matches_repeat_oracle(self, striped, devices):
        from hpx_tpu.utils.jaxcompat import shard_map
        from jax.sharding import Mesh, PartitionSpec as P
        from hpx_tpu.ops.attention import _ring_flash, stripe_sequence
        mesh = Mesh(np.array(devices[:4]), ("sp",))
        B, S, NQ, NKV, H = 2, 64, 4, 2, 32
        q = _rand((B, S, NQ, H), 40)
        k = _rand((B, S, NKV, H), 41)
        v = _rand((B, S, NKV, H), 42)
        w = _rand((B, S, NQ, H), 43)
        qs = P(None, "sp", None, None)

        def loss(q, k, v):
            if striped:
                q, k, v, wl = (stripe_sequence(x, 4)
                               for x in (q, k, v, w))
            else:
                wl = w

            def body(qc, kc, vc, wc):
                o = _ring_flash(qc, kc, vc, "sp", 4, True, striped)
                return jax.lax.psum(jnp.sum(o * wc), "sp")

            return jax.jit(shard_map(
                body, mesh=mesh, in_specs=(qs, qs, qs, qs),
                out_specs=P(), check_vma=False))(q, k, v, wl)

        got = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        kr = jnp.repeat(k, NQ // NKV, axis=2)
        vr = jnp.repeat(v, NQ // NKV, axis=2)

        def oref(q, kr, vr):
            return grad_oracle(q, kr, vr, True)

        wantq, wantkr, wantvr = _grads(oref, q, kr, vr, w)
        # repeat transposes to a group-sum on the kv side
        g = NQ // NKV
        wantk = wantkr.reshape(B, S, NKV, g, H).sum(axis=3)
        wantv = wantvr.reshape(B, S, NKV, g, H).sum(axis=3)
        _cmp(got, (wantq, wantk, wantv), 3e-4)

    def test_grouped_chunks_on_the_wire(self, devices):
        """The compiled program must ppermute KV-sized buffers, never
        q-head-expanded ones — the whole point of grouped GQA rings."""
        from hpx_tpu.utils.jaxcompat import shard_map
        from jax.sharding import Mesh, PartitionSpec as P
        from hpx_tpu.ops.attention import ring_attention_sharded
        mesh = Mesh(np.array(devices[:4]), ("sp",))
        B, S, NQ, NKV, H = 2, 64, 4, 1, 32
        q = _rand((B, S, NQ, H), 44)
        k = _rand((B, S, NKV, H), 45)
        v = _rand((B, S, NKV, H), 46)
        spec = P(None, "sp", None, None)

        def body(qc, kc, vc):
            return ring_attention_sharded(qc, kc, vc, "sp", 4,
                                          causal=True, use_flash=True)

        fn = shard_map(body, mesh=mesh, in_specs=(spec,) * 3,
                       out_specs=spec, check_vma=False)
        jaxpr = str(jax.make_jaxpr(fn)(q, k, v))
        sq = S // 4
        kv_shape = f"[{B * NKV},{sq},{H}]"        # kernel-layout rows
        exp_shape = f"[{B * NQ},{sq},{H}]"
        perm_lines = [ln for ln in jaxpr.splitlines()
                      if "ppermute" in ln]
        assert perm_lines, "no ppermute in the ring program?"
        assert any(kv_shape in ln for ln in perm_lines), \
            (kv_shape, perm_lines[:4])
        assert not any(exp_shape in ln for ln in perm_lines), \
            (exp_shape, perm_lines[:4])
