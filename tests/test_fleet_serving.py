"""Fleet serving (svc/fleet.py): prefix-cache-aware placement over
N prefill x M decode workers must stay BYTE-IDENTICAL to single-server
``tfm.generate`` — through mesh-sharded decode pools, prefix-seeded
prefills (the placement hit that SKIPS prompt compute), seeded
per-role worker kills, and autoscale up/down cycles — with zero KV
blocks leaked anywhere, including by workers the autoscaler retired.

The placement policy itself (digest pull, longest-match scoring,
eviction-rate pressure) is pinned by asserting a shared-prefix warm
wave lands digest-matched (``placed_prefix``) and actually saves
prefill tokens; ``placement=load`` degrades to the base least-loaded
router and must save nothing.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hpx_tpu.core.config import runtime_config
from hpx_tpu.models import transformer as tfm
from hpx_tpu.models.disagg import DecodeWorker
from hpx_tpu.svc import faultinject
from hpx_tpu.svc import performance_counters as pc
from hpx_tpu.svc import tracing
from hpx_tpu.svc.fleet import FleetRouter

CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4, head_dim=8,
                            n_layers=2, d_ff=64)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def mesh():
    return jax.sharding.Mesh(
        np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))


@pytest.fixture()
def fresh_digests(monkeypatch):
    """Digest freshness window 0: every placement re-pulls, so the
    tests see the workers' REAL trees, not a stale mirror."""
    monkeypatch.setitem(runtime_config()._data,
                        "hpx.serving.fleet.digest_refresh_s", "0")


def _ref(params, prompt, max_new, temperature=0.0, key=None):
    out = tfm.generate(params, CFG, jnp.asarray([prompt], jnp.int32),
                       max_new=max_new, temperature=temperature,
                       key=key)
    return [int(t) for t in np.asarray(out)[0]]


def _mix(n=6, seed=7, prefix=()):
    """Mixed greedy/sampled requests; a shared `prefix` models the
    Zipf head (system prompt) the placement policy routes on."""
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        tail = [int(t) for t in
                rng.integers(1, 64, int(rng.integers(3, 12)))]
        temp = 0.8 if i % 2 else 0.0
        key = jax.random.PRNGKey(100 + i) if temp else None
        reqs.append((list(prefix) + tail, 5 + i, temp, key))
    return reqs


def _submit_all(r, reqs):
    return [r.submit(p, mn, temperature=t, key=k)
            for (p, mn, t, k) in reqs]


def _check(out, rids, reqs, params):
    for rid, (p, mn, t, k) in zip(rids, reqs):
        assert out[rid] == _ref(params, p, mn, temperature=t, key=k)


# ---------------------------------------------------------------------------
# fault-free N x M identity: dense prefill -> mesh-sharded paged decode
# ---------------------------------------------------------------------------

def test_fleet_mesh_decode_matches_generate(params, mesh,
                                            fresh_digests):
    reqs = _mix(6)
    r = FleetRouter(params, CFG, prefill_workers=2, decode_workers=2,
                    slots=4, smax=64, decode_mesh=mesh)
    rids = _submit_all(r, reqs)
    out = r.run()
    _check(out, rids, reqs, params)
    st = r.stats()
    assert st["failovers"] == {"prefill": 0, "decode": 0}
    assert st["decode_pool"] == 2
    r.close()
    assert r.leaked_blocks() == 0


# ---------------------------------------------------------------------------
# the headline: shared-prefix traffic routes to its cached blocks and
# skips prefill compute — tokens unchanged
# ---------------------------------------------------------------------------

def test_warm_prefix_wave_places_by_digest_and_saves(params,
                                                     fresh_digests):
    shared = [7, 3, 1, 9, 2, 8, 4, 6, 5, 1, 2, 3, 9, 8, 7, 6, 5, 4,
              3, 2]
    r = FleetRouter(params, CFG, prefill_workers=2, decode_workers=2,
                    slots=4, smax=64)
    cold = _mix(4, seed=11, prefix=shared)
    rids = _submit_all(r, cold)
    _check(r.run(), rids, cold, params)
    st0 = r.stats()

    warm = _mix(4, seed=23, prefix=shared)
    rids = _submit_all(r, warm)
    _check(r.run(), rids, warm, params)
    st1 = r.stats()

    # every warm request shares >= 1 full cached block: digest-matched
    # placement, and the matched rows seeded the prefill
    assert st1["placed_prefix"] - st0["placed_prefix"] >= 3
    assert st1["prefill_tokens_saved"] > st0["prefill_tokens_saved"]
    r.close()
    assert r.leaked_blocks() == 0


def test_load_placement_mode_saves_nothing(params, monkeypatch,
                                           fresh_digests):
    monkeypatch.setitem(runtime_config()._data,
                        "hpx.serving.fleet.placement", "load")
    shared = [5, 5, 4, 4, 3, 3, 2, 2, 1, 1, 9, 9, 8, 8, 7, 7, 6, 6]
    r = FleetRouter(params, CFG, prefill_workers=2, decode_workers=2,
                    slots=4, smax=64)
    for wave_seed in (11, 23):
        reqs = _mix(4, seed=wave_seed, prefix=shared)
        rids = _submit_all(r, reqs)
        _check(r.run(), rids, reqs, params)
    st = r.stats()
    assert st["placed_prefix"] == 0
    assert st["prefill_tokens_saved"] == 0
    assert st["placed_load"] == 8
    r.close()
    assert r.leaked_blocks() == 0


def test_bad_placement_knob_rejected(params, monkeypatch):
    monkeypatch.setitem(runtime_config()._data,
                        "hpx.serving.fleet.placement", "random")
    with pytest.raises(ValueError):
        FleetRouter(params, CFG, prefill_workers=1, decode_workers=1,
                    slots=2, smax=64)


# ---------------------------------------------------------------------------
# failover: one seeded kill per role -> identical tokens, no leak
# ---------------------------------------------------------------------------

def _run_fleet(params, reqs, schedule=None, **fleet_kw):
    inj = None
    if schedule is not None:
        inj = faultinject.install(
            faultinject.FaultInjector(schedule=schedule))
    try:
        r = FleetRouter(params, CFG, prefill_workers=2,
                        decode_workers=2, slots=3, smax=64, **fleet_kw)
        rids = _submit_all(r, reqs)
        out = r.run()
        stats = r.stats()
        r.close()
        leak = r.leaked_blocks()
    finally:
        if inj is not None:
            faultinject.uninstall()
    return [out[rid] for rid in rids], stats, leak


def test_fleet_decode_worker_death_replays_identically(params,
                                                       fresh_digests):
    reqs = _mix(6)
    base, _, _ = _run_fleet(params, reqs)
    out, stats, leak = _run_fleet(
        params, reqs, schedule={"disagg.decode": {12}})
    assert out == base
    assert stats["failovers"]["decode"] >= 1
    assert not stats["degraded"]
    assert leak == 0


def test_fleet_prefill_worker_death_restarts_identically(params,
                                                         fresh_digests):
    reqs = _mix(6)
    base, _, _ = _run_fleet(params, reqs)
    out, stats, leak = _run_fleet(
        params, reqs, schedule={"disagg.prefill": {6}})
    assert out == base
    assert stats["failovers"]["prefill"] >= 1
    assert not stats["degraded"]
    assert leak == 0


# ---------------------------------------------------------------------------
# autoscaling: queue-depth up, idle-streak drain down — zero leaks
# either way, including blocks owned by RETIRED workers
# ---------------------------------------------------------------------------

def test_autoscale_up_on_queue_depth(params, monkeypatch,
                                     fresh_digests):
    for k, v in (("scale_high", "3"), ("decode_pool_max", "3")):
        monkeypatch.setitem(runtime_config()._data,
                            f"hpx.serving.fleet.{k}", v)
    reqs = _mix(6)
    r = FleetRouter(params, CFG, prefill_workers=2, decode_workers=2,
                    slots=3, smax=64)
    rids = _submit_all(r, reqs)
    out = r.run()
    _check(out, rids, reqs, params)
    st = r.stats()
    assert st["autoscale_up"] >= 1
    assert st["decode_pool"] == 3
    r.close()
    assert r.leaked_blocks() == 0


def test_autoscale_down_drains_idle_worker(params, monkeypatch,
                                           fresh_digests):
    monkeypatch.setitem(runtime_config()._data,
                        "hpx.serving.fleet.idle_ticks", "3")
    reqs = _mix(4)
    r = FleetRouter(params, CFG, prefill_workers=2, decode_workers=2,
                    slots=3, smax=64)
    rids = _submit_all(r, reqs)
    _check(r.run(), rids, reqs, params)
    assert r.stats()["decode_pool"] == 2
    # idle ticks accumulate only while the router steps; a few empty
    # ticks past the streak threshold drain the newest worker down to
    # the pool floor (decode_pool_min=1) and no further
    for _ in range(6):
        r.step()
    st = r.stats()
    assert st["autoscale_down"] == 1
    assert st["decode_pool"] == 1
    # the survivor still serves, and the retired worker's blocks are
    # in the ledger, not leaked
    more = _mix(2, seed=31)
    rids = _submit_all(r, more)
    _check(r.run(), rids, more, params)
    r.close()
    assert r.leaked_blocks() == 0


def test_drain_with_inflight_work_redispatches(params, fresh_digests):
    """The PR 8 rule on the autoscale drain path: _retire re-dispatches
    everything the draining worker owns through _failover_decode
    (router state commits before the risky send), so a drain with
    work in flight is just a failover with a planned death."""
    reqs = _mix(5)
    base, _, _ = _run_fleet(params, reqs)
    r = FleetRouter(params, CFG, prefill_workers=2, decode_workers=2,
                    slots=3, smax=64)
    rids = _submit_all(r, reqs)
    victim = None
    while victim is None:
        r.step()
        owned = [q for q in r._reqs.values()
                 if q.state in ("prefill", "decode")
                 and q.decode_h is not None]
        if owned:
            victim = owned[0].decode_h
    n_owned = len(owned)
    victim.draining = True
    r._retire(victim)
    # every request the victim owned re-homed onto the survivor (a
    # planned drain is not a failure, so `failovers` stays clean)
    assert victim not in r._decode
    rehomed = [q for q in r._reqs.values()
               if q.state in ("prefill", "decode")
               and q.decode_h is not None]
    assert len(rehomed) >= n_owned
    assert all(q.decode_h is not victim for q in rehomed)
    out = r.run()
    assert [out[rid] for rid in rids] == base
    st = r.stats()
    assert st["failovers"] == {"prefill": 0, "decode": 0}
    assert st["autoscale_down"] == 1
    assert st["decode_pool"] == 1
    r.close()
    assert r.leaked_blocks() == 0


# ---------------------------------------------------------------------------
# observability: /serving fleet counters + placement spans/flows
# ---------------------------------------------------------------------------

def test_fleet_counters_registered_and_stable(params, fresh_digests):
    r = FleetRouter(params, CFG, prefill_workers=1, decode_workers=2,
                    slots=2, smax=64)
    inst = r.counter_instance
    names = pc.discover_counters(f"/serving{{*{inst}}}*")
    short = {n.split("}/", 1)[1] for n in names}
    assert {"fleet/placed/prefix", "fleet/placed/load",
            "fleet/digest/staleness-s", "fleet/autoscale/up",
            "fleet/autoscale/down", "fleet/prefill-tokens/saved",
            "fleet/workers/decode",
            "fleet/queue/depth"} <= short
    # per-worker depth registers to the autoscale CEILING: indexes
    # past the live pool read 0 rather than vanishing from discovery
    depth_names = sorted(n for n in names if "worker#" in n)
    assert len(depth_names) == r._pool_max
    assert pc.query_counter(depth_names[-1]).value == 0.0
    rid = r.submit([1, 2, 3, 4, 5], 4)
    out = r.run()
    assert out[rid] == _ref(params, [1, 2, 3, 4, 5], 4)
    workers = [n for n in names if n.endswith("fleet/workers/decode")]
    assert pc.query_counter(workers[0]).value == 2.0
    r.close()


def test_placement_spans_and_flow_arrows(params, fresh_digests):
    shared = [9, 1, 8, 2, 7, 3, 6, 4, 5, 5, 4, 6, 3, 7, 2, 8, 1, 9]
    r = FleetRouter(params, CFG, prefill_workers=1, decode_workers=2,
                    slots=3, smax=64)
    cold = _mix(3, seed=5, prefix=shared)
    rids = _submit_all(r, cold)
    _check(r.run(), rids, cold, params)
    tr = tracing.start_tracing(sample_counters=False)
    try:
        warm = _mix(3, seed=6, prefix=shared)
        rids = _submit_all(r, warm)
        _check(r.run(), rids, warm, params)
    finally:
        tracing.stop_tracing()
    ev = tr.snapshot()
    names = [(e[0], e[1]) for e in ev]
    assert ("B", "serving.fleet.place") in names
    assert ("B", "serving.fleet.admit") in names
    placed = [e for e in ev
              if e[0] == "i" and e[1] == "serving.fleet.placed"]
    assert any(e[7]["by"] == "prefix" for e in placed)
    # the placement -> admit flow arrow: tail (s) in the place span,
    # head (f) bound at admit, same id
    tails = {e[5] for e in ev
             if e[0] == "s" and e[1] == "serving.fleet.place"}
    heads = {e[5] for e in ev
             if e[0] == "f" and e[1] == "serving.fleet.place"}
    assert tails and tails & heads
    r.close()
    assert r.leaked_blocks() == 0


# ---------------------------------------------------------------------------
# unified construction: DecodeWorker(mesh=) is ContinuousServer(mesh=)
# ---------------------------------------------------------------------------

def test_decode_worker_mesh_passthrough(params, mesh):
    solo = DecodeWorker(params, CFG, slots=2, smax=64)
    assert solo.srv.mesh is None and solo.srv.paged
    sharded = DecodeWorker(params, CFG, slots=2, smax=64, mesh=mesh)
    assert sharded.srv.mesh is mesh
    solo.close()
    sharded.close()
