"""Speculative decoding in ContinuousServer: the draft + window-verify
path must be BYTE-IDENTICAL to both plain generate() and the
non-speculative server — dense and paged, greedy and sampled, for every
draft source — because acceptance compares draft tokens against the
EXACT token the sequential step would have picked (same `_pick_row`
contract, same fold_in key schedule). Throughput may vary with draft
quality; tokens never do.

Also pins the compile story: verify programs ride the prefill bucket
ladder, so a spec workload builds O(buckets) programs, not O(distinct
window widths).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hpx_tpu.models import transformer as tfm
from hpx_tpu.models.serving import ContinuousServer
from hpx_tpu.utils.compilemon import count_compiles

CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4, head_dim=8,
                            n_layers=2, d_ff=64)
# a real (smaller) draft checkpoint over the same vocab
DCFG = tfm.TransformerConfig(vocab=64, d_model=16, n_heads=2, head_dim=8,
                             n_layers=1, d_ff=32)

REQS = [dict(prompt=[3, 1, 4], max_new=9),
        dict(prompt=[2, 7], max_new=5),
        dict(prompt=[5, 6, 7, 8, 9], max_new=12),
        dict(prompt=[1], max_new=7),
        dict(prompt=[9, 9, 2, 1], max_new=3),
        dict(prompt=[4, 4], max_new=10)]

SAMPLED = [dict(prompt=[3, 1, 4], max_new=8, temperature=0.9,
                key=jax.random.PRNGKey(7)),
           dict(prompt=[2, 7, 9], max_new=8, temperature=0.7,
                key=jax.random.PRNGKey(8)),
           dict(prompt=[5, 5], max_new=6, temperature=1.3,
                key=jax.random.PRNGKey(9))]


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def draft_params():
    return tfm.init_params(DCFG, jax.random.PRNGKey(1))


def _ref(params, cfg, prompt, max_new, eos_id=None):
    out = tfm.generate(params, cfg,
                       jnp.asarray([prompt], jnp.int32),
                       max_new=max_new, eos_id=eos_id)
    return [int(t) for t in np.asarray(out)[0]]


def _serve(params, reqs, *, smax=64, slots=3, **kw):
    srv = ContinuousServer(params, CFG, slots=slots, smax=smax, **kw)
    for r in reqs:
        srv.submit(**r)
    return srv.run(), srv


# -- equivalence sweep -------------------------------------------------------

@pytest.mark.parametrize("paged", [False, True],
                         ids=["dense", "paged"])
@pytest.mark.parametrize("k", [1, 2, 4])
def test_greedy_matches_nonspec_and_generate(params, paged, k):
    base, _ = _serve(params, REQS, paged=paged)
    spec, srv = _serve(params, REQS, paged=paged, spec=True, spec_k=k)
    assert spec == base
    for rid, r in enumerate(REQS):
        assert spec[rid] == _ref(params, CFG, r["prompt"], r["max_new"])
    st = srv.spec_stats()
    assert st["steps"] > 0 and st["emitted"] > 0
    # every spec step emits at least the sequential token
    assert st["tokens_per_step"] >= 1.0


@pytest.mark.parametrize("paged", [False, True],
                         ids=["dense", "paged"])
@pytest.mark.parametrize("k", [1, 2, 4])
def test_sampled_matches_nonspec(params, paged, k):
    """temperature > 0: acceptance still reduces to exact token match
    because `_sample_row` is deterministic given (key, pos, row)."""
    base, _ = _serve(params, SAMPLED, slots=2, paged=paged)
    spec, _ = _serve(params, SAMPLED, slots=2, paged=paged,
                     spec=True, spec_k=k)
    assert spec == base


@pytest.mark.parametrize("paged", [False, True],
                         ids=["dense", "paged"])
def test_eos_inside_window(params, paged):
    """An eos accepted mid-window must truncate the emission exactly
    where the sequential server would have stopped."""
    probe = _ref(params, CFG, [3, 1, 4], 9)
    eos = probe[3]
    reqs = [dict(prompt=[3, 1, 4], max_new=9, eos_id=eos),
            dict(prompt=[2, 7], max_new=5)]
    base, _ = _serve(params, reqs, slots=2, paged=paged)
    spec, _ = _serve(params, reqs, slots=2, paged=paged,
                     spec=True, spec_k=4)
    assert spec == base
    assert spec[0] == _ref(params, CFG, [3, 1, 4], 9, eos_id=eos)


@pytest.mark.parametrize("paged", [False, True],
                         ids=["dense", "paged"])
def test_rejection_at_first_token(params, draft_params, paged):
    """A deliberately bad draft model (random tiny checkpoint): most
    windows reject at the first draft, yet output stays identical and
    every step still lands the sequential token."""
    base, _ = _serve(params, REQS, paged=paged)
    spec, srv = _serve(params, REQS, paged=paged, spec=True, spec_k=4,
                       draft_params=draft_params, draft_cfg=DCFG)
    assert spec == base
    st = srv.spec_stats()
    assert st["drafted"] > 0
    assert st["acceptance_rate"] < 0.5      # it IS a bad draft model
    assert st["tokens_per_step"] >= 1.0     # but never below sequential


@pytest.mark.parametrize("paged", [False, True],
                         ids=["dense", "paged"])
def test_draft_model_vs_prompt_lookup_same_tokens(params, draft_params,
                                                  paged):
    """The two draft sources may accept wildly different fractions,
    but both must decode the exact same tokens."""
    lookup, _ = _serve(params, REQS, paged=paged, spec=True, spec_k=3)
    model, _ = _serve(params, REQS, paged=paged, spec=True, spec_k=3,
                      draft_params=draft_params, draft_cfg=DCFG)
    assert lookup == model


def test_self_draft_full_acceptance(params):
    """Draft == target: every draft token matches, so acceptance is
    1.0 and steps emit full windows (the speedup upper bound)."""
    spec, srv = _serve(params, REQS, spec=True, spec_k=4,
                       draft_params=params, draft_cfg=CFG)
    for rid, r in enumerate(REQS):
        assert spec[rid] == _ref(params, CFG, r["prompt"], r["max_new"])
    st = srv.spec_stats()
    assert st["acceptance_rate"] == pytest.approx(1.0)
    assert st["tokens_per_step"] > 1.5


def test_max_new_one_and_tiny_k(params):
    """Edge: nothing to draft (max_new=1) and k=1 windows."""
    reqs = [dict(prompt=[3, 1, 4], max_new=1),
            dict(prompt=[2, 7], max_new=2)]
    base, _ = _serve(params, reqs, slots=2)
    spec, _ = _serve(params, reqs, slots=2, spec=True, spec_k=1)
    assert spec == base


def test_spec_k_validation(params):
    with pytest.raises(ValueError):
        ContinuousServer(params, CFG, spec=True, spec_k=0)
    with pytest.raises(ValueError):
        ContinuousServer(params, CFG, spec=True, spec_draft="oracle")


def test_rollback_frees_rejected_blocks(params):
    """Paged spec serving must not leak pool blocks on rejection:
    rollback decrefs every block the rejected window had appended, so
    the post-run pool state matches the non-speculative run exactly."""
    base, bsrv = _serve(params, REQS, paged=True)
    spec, srv = _serve(params, REQS, paged=True, spec=True, spec_k=4)
    assert len(spec) == len(REQS)
    bst, st = bsrv.cache_stats(), srv.cache_stats()
    assert st["in_use"] == bst["in_use"]
    assert st["blocks_held"] == bst["blocks_held"]


# -- compile guard: verify programs are O(buckets) ---------------------------

GUARD_CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                  head_dim=8, n_layers=2, d_ff=56)


def test_spec_programs_o_buckets():
    """Mixed adaptive-k workload: verify windows bucket on the prefill
    ladder, so program builds stay O(buckets) — one verify program per
    rung touched, NOT one per distinct (1 + k) width."""
    params = tfm.init_params(GUARD_CFG, jax.random.PRNGKey(2))
    r = np.random.RandomState(3)
    reqs = [dict(prompt=[int(t) for t in r.randint(1, 64, p)],
                 max_new=8) for p in (3, 5, 9, 12, 4, 8)]
    with count_compiles() as c:
        srv = ContinuousServer(params, GUARD_CFG, slots=4, smax=64,
                               prefill_chunk=8, prefill_buckets="4,8",
                               spec=True, spec_k=4)
        out = {}
        for req in reqs:
            srv.submit(**req)
        out = srv.run()
    assert len(out) == len(reqs)
    buckets = len(srv.prefill_buckets)
    # chunk-per-bucket + probe + splice + step + one verify program
    # per rung a window landed on (≤ buckets)
    assert srv._prog_misses <= 2 * buckets + 3
    assert int(c) <= 2 * buckets + 24
    # warm server, fresh lengths: everything reuses
    with count_compiles() as c2:
        srv2 = ContinuousServer(params, GUARD_CFG, slots=4, smax=64,
                                prefill_chunk=8, prefill_buckets="4,8",
                                spec=True, spec_k=4)
        for p in (7, 11):
            srv2.submit([int(t) for t in r.randint(1, 64, p)],
                        max_new=6)
        out2 = srv2.run()
    assert len(out2) == 2
    assert srv2._prog_misses == 0
    assert int(c2) <= 2
