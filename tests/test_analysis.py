"""hpxlint static-analysis framework tests.

Each rule gets a minimal fixture that fires exactly once, plus the
corrected form of the same code that stays silent — the pair pins both
the detection AND the fix the rule's message recommends. The suite also
covers the suppression directives, the baseline mechanism, and (as the
lint gate) runs the real CLI over the real tree: a new finding anywhere
in hpx_tpu/ fails this file.
"""

import json
import os
import subprocess
import sys

import pytest

from hpx_tpu.analysis import (
    Finding,
    all_rules,
    apply_baseline,
    lint_paths,
    lint_source,
    lint_sources,
)
from hpx_tpu.analysis.cli import main as cli_main
from hpx_tpu.analysis.engine import Suppressions, load_baseline, parse_count

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def findings(source, path="hpx_tpu/exec/fixture.py", select=None):
    res = lint_source(source, path, rules=all_rules(select))
    return res.findings


def rules_of(fs):
    return [f.rule for f in fs]


# ---------------------------------------------------------------------------
# HPX001 — future wait under a registered lock
# ---------------------------------------------------------------------------

HPX001_BAD = """\
from hpx_tpu.synchronization import Mutex

_lock = Mutex()

def drain(f):
    with _lock:
        return f.get()
"""

HPX001_GOOD = """\
from hpx_tpu.synchronization import Mutex

_lock = Mutex()

def drain(f):
    with _lock:
        pending = f
    return pending.get()
"""


def test_hpx001_fires_once():
    fs = findings(HPX001_BAD)
    assert rules_of(fs) == ["HPX001"]
    assert "_lock" in fs[0].message


def test_hpx001_silent_after_fix():
    assert findings(HPX001_GOOD) == []


def test_hpx001_self_attribute_lock():
    src = (
        "from hpx_tpu.synchronization import Spinlock\n"
        "class Q:\n"
        "    def __init__(self):\n"
        "        self._mu = Spinlock()\n"
        "    def pop(self, f):\n"
        "        with self._mu:\n"
        "            f.wait()\n"
    )
    assert rules_of(findings(src)) == ["HPX001"]


def test_hpx001_ignores_unregistered_lock():
    # only Mutex/Spinlock/SharedMutex register with VERIFY_LOCKS; a
    # plain object with a context manager is out of scope (HPX004's job)
    src = "with open('x') as fh:\n    f.get()\n"
    assert findings(src) == []


# ---------------------------------------------------------------------------
# HPX002 — host-device sync in hot-path modules
# ---------------------------------------------------------------------------

HPX002_BAD = """\
import numpy as np

def gather(device_arr):
    return np.asarray(device_arr)
"""

HPX002_GOOD = """\
import jax.numpy as jnp

def gather(device_arr):
    return jnp.asarray(device_arr)
"""


def test_hpx002_fires_once():
    fs = findings(HPX002_BAD, path="hpx_tpu/algo/fixture.py")
    assert rules_of(fs) == ["HPX002"]


def test_hpx002_jnp_asarray_is_not_numpy():
    # alias resolution must distinguish np->numpy from jnp->jax.numpy
    assert findings(HPX002_GOOD, path="hpx_tpu/algo/fixture.py") == []


def test_hpx002_only_in_hot_subpaths():
    assert findings(HPX002_BAD, path="hpx_tpu/svc/fixture.py") == []


def test_hpx002_block_until_ready_and_item():
    src = "def f(x):\n    x.block_until_ready()\n    return x.item()\n"
    fs = findings(src, path="hpx_tpu/futures/fixture.py")
    assert rules_of(fs) == ["HPX002", "HPX002"]


# ---------------------------------------------------------------------------
# HPX003 — dropped future
# ---------------------------------------------------------------------------

HPX003_BAD = """\
from hpx_tpu.futures.async_ import async_

def kick(fn):
    async_(fn)
"""

HPX003_GOOD = """\
from hpx_tpu.futures.async_ import async_

def kick(fn):
    return async_(fn)
"""


def test_hpx003_fires_once():
    assert rules_of(findings(HPX003_BAD)) == ["HPX003"]


def test_hpx003_silent_when_kept():
    assert findings(HPX003_GOOD) == []


def test_hpx003_dropped_then():
    src = "def chain(f):\n    f.then(print)\n"
    assert rules_of(findings(src)) == ["HPX003"]


def test_hpx003_post_is_fire_and_forget():
    # post() returns None by design — not a dropped future
    src = ("from hpx_tpu.futures.async_ import post\n"
           "def kick(fn):\n"
           "    post(fn)\n")
    assert findings(src) == []


# ---------------------------------------------------------------------------
# HPX004 — raw primitives where registered ones are required
# ---------------------------------------------------------------------------

HPX004_BAD = """\
import threading

_lock = threading.Lock()
"""

HPX004_GOOD = """\
from hpx_tpu.synchronization import Mutex

_lock = Mutex()
"""


def test_hpx004_fires_once():
    fs = findings(HPX004_BAD, path="hpx_tpu/svc/fixture.py")
    assert rules_of(fs) == ["HPX004"]
    assert "Mutex" in fs[0].message


def test_hpx004_silent_after_fix():
    assert findings(HPX004_GOOD, path="hpx_tpu/svc/fixture.py") == []


def test_hpx004_exempt_below_synchronization():
    # futures/runtime/core sit BELOW synchronization in the import graph
    # and must keep raw primitives
    assert findings(HPX004_BAD, path="hpx_tpu/futures/fixture.py") == []
    assert findings(HPX004_BAD, path="hpx_tpu/runtime/fixture.py") == []


def test_hpx004_time_sleep():
    src = "import time\n\ndef nap():\n    time.sleep(1)\n"
    fs = findings(src, path="hpx_tpu/dist/fixture.py")
    assert rules_of(fs) == ["HPX004"]


# ---------------------------------------------------------------------------
# HPX005 — jit in a loop
# ---------------------------------------------------------------------------

HPX005_BAD = """\
import jax

def run(xs):
    for x in xs:
        y = jax.jit(lambda v: v + 1)(x)
    return y
"""

HPX005_GOOD = """\
import jax

def run(xs):
    step = jax.jit(lambda v: v + 1)
    for x in xs:
        y = step(x)
    return y
"""


def test_hpx005_fires_once():
    fs = findings(HPX005_BAD)
    assert rules_of(fs) == ["HPX005"]
    assert fs[0].severity == "warning"


def test_hpx005_silent_when_hoisted():
    assert findings(HPX005_GOOD) == []


# ---------------------------------------------------------------------------
# HPX006 — bare except
# ---------------------------------------------------------------------------

HPX006_BAD = "try:\n    x = 1\nexcept:\n    pass\n"
HPX006_GOOD = "try:\n    x = 1\nexcept Exception:\n    pass\n"


def test_hpx006_fires_once():
    assert rules_of(findings(HPX006_BAD)) == ["HPX006"]


def test_hpx006_silent_with_type():
    assert findings(HPX006_GOOD) == []


# ---------------------------------------------------------------------------
# HPX007 — span context manager discarded
# ---------------------------------------------------------------------------

HPX007_BAD = """\
from hpx_tpu.svc import tracing

def phase():
    tracing.span("phase", "serving", step=1)
    work()
"""

HPX007_GOOD = """\
from hpx_tpu.svc import tracing

def phase():
    with tracing.span("phase", "serving", step=1):
        work()
    tracing.instant("phase.done", "serving")
"""


def test_hpx007_fires_once():
    assert rules_of(findings(HPX007_BAD)) == ["HPX007"]


def test_hpx007_silent_with_with():
    assert findings(HPX007_GOOD) == []


def test_hpx007_annotate_statement():
    src = ("from hpx_tpu.svc.profiling import annotate\n"
           "def f():\n"
           "    annotate('region')\n")
    assert rules_of(findings(src)) == ["HPX007"]


def test_hpx007_kept_result_is_silent():
    # binding the manager (entered later / passed on) is fine
    src = ("def f(tracer):\n"
           "    s = tracer.span('x')\n"
           "    return s\n")
    assert findings(src) == []


# ---------------------------------------------------------------------------
# HPX008 — program cache keyed on a raw dynamic length
# ---------------------------------------------------------------------------

HPX008_BAD = """\
from hpx_tpu.models.transformer import _cached_program
def prefill(params, prompt, cfg):
    plen = len(prompt)
    ck = ("prefill", cfg, plen)
    return _cached_program(ck, lambda: None)
"""

HPX008_GOOD = """\
from hpx_tpu.models.transformer import _cached_program
def prefill(params, prompt, cfg, buckets):
    width = next(w for w in buckets if w >= len(prompt))
    ck = ("prefill", cfg, width)
    return _cached_program(ck, lambda: None)
"""


def test_hpx008_len_keyed_cache_fires():
    fs = findings(HPX008_BAD)
    assert rules_of(fs) == ["HPX008"]
    assert "'plen'" in fs[0].message


def test_hpx008_bucketed_key_is_silent():
    assert findings(HPX008_GOOD) == []


def test_hpx008_shape_unpack_and_inline_tuple():
    # `b, n = x.shape` taints both names; the key tuple may also be
    # passed inline and carry a bare `.shape` read
    src = ("from hpx_tpu.core.programs import cached_program\n"
           "P = {}\n"
           "def run(x, cfg):\n"
           "    b, n = x.shape\n"
           "    return cached_program(P, (cfg, n, x.shape),\n"
           "                          lambda: None)\n")
    fs = findings(src)
    assert rules_of(fs) == ["HPX008", "HPX008"]


def test_hpx008_two_call_sites_report_once():
    # one key construction feeding mesh/no-mesh branches is ONE finding
    src = ("from hpx_tpu.models.transformer import _cached_program\n"
           "def gen(params, prompt, cfg, mesh):\n"
           "    plen = len(prompt)\n"
           "    ck = ('gen', cfg, plen)\n"
           "    if mesh is None:\n"
           "        return _cached_program(ck, lambda: None)\n"
           "    return _cached_program(ck, lambda: None)\n")
    assert rules_of(findings(src)) == ["HPX008"]


def test_hpx008_static_key_is_silent():
    src = ("from hpx_tpu.core.programs import cached_program\n"
           "P = {}\n"
           "def run(v, mesh, axis):\n"
           "    return cached_program(P, ('sort', mesh, axis),\n"
           "                          lambda: None)\n")
    assert findings(src) == []


# ---------------------------------------------------------------------------
# HPX009 — host sync on draft/verify intermediates in the serving hot loop
# ---------------------------------------------------------------------------

SERVING_PATH = "hpx_tpu/models/serving.py"

HPX009_BAD = """\
import numpy as np
class ContinuousServer:
    def _spec_step(self, live):
        packed = self._verify_prog(4)(None)
        vals = np.asarray(packed)
        return vals
"""

HPX009_GOOD = """\
import numpy as np
class ContinuousServer:
    def _finish_prefill(self, slot, req):
        # outside the hot set: prefill boundary syncs are expected
        first = np.asarray(req.first_logits)
        return first
"""


def test_hpx009_asarray_in_hot_loop_fires():
    fs = findings(HPX009_BAD, path=SERVING_PATH)
    assert rules_of(fs) == ["HPX009"]
    assert "_spec_step()" in fs[0].message


def test_hpx009_item_and_device_get_fire():
    src = ("import jax\n"
           "class ContinuousServer:\n"
           "    def step(self):\n"
           "        acc = self._acc_dev.item()\n"
           "        tgt = jax.device_get(self._tgt_dev)\n"
           "        return acc, tgt\n")
    fs = findings(src, path=SERVING_PATH)
    assert rules_of(fs) == ["HPX009", "HPX009"]


def test_hpx009_non_hot_function_is_silent():
    assert findings(HPX009_GOOD, path=SERVING_PATH) == []


def test_hpx009_outside_serving_path_is_silent():
    assert findings(HPX009_BAD, path="hpx_tpu/models/other.py") == []


def test_hpx009_nested_def_not_attributed_to_hot_parent():
    # a helper DEFINED inside a hot function is not the hot loop
    # itself (it runs wherever it is called; builders run at compile)
    src = ("import numpy as np\n"
           "class ContinuousServer:\n"
           "    def _spec_step(self, live):\n"
           "        def build():\n"
           "            return np.asarray([1, 2])\n"
           "        return build\n")
    assert findings(src, path=SERVING_PATH) == []


# ---------------------------------------------------------------------------
# HPX010 — full-pool gather outside the paged-attention oracle module
# ---------------------------------------------------------------------------

HPX010_BAD = """\
def decode_rows(x, k_pool, v_pool, table):
    k = k_pool[table]
    v = v_pool[table]
    return x, k, v
"""

HPX010_GOOD = """\
from hpx_tpu.ops.paged_attention import paged_decode_attention

def decode_rows(x, k_pool, v_pool, table, pos):
    return paged_decode_attention(x, k_pool, v_pool, table, pos,
                                  fused=True)
"""


def test_hpx010_fires_per_gather():
    fs = findings(HPX010_BAD, path=SERVING_PATH)
    assert rules_of(fs) == ["HPX010", "HPX010"]
    assert "'k_pool[table]'" in fs[0].message


def test_hpx010_fused_route_is_silent():
    assert findings(HPX010_GOOD, path=SERVING_PATH) == []


def test_hpx010_bounded_reads_are_silent():
    # plural `pools` is the host per-layer list; constant subscripts
    # read O(1) blocks; `.at[...]` chains are scatters, not gathers
    src = ("def f(pools, pool, bidx, vals):\n"
           "    kp, vp = pools[0]\n"
           "    head = pool[0]\n"
           "    return kp, vp, head, pool.at[bidx].set(vals)\n")
    assert findings(src, path=SERVING_PATH) == []


def test_hpx010_outside_paged_hot_paths_is_silent():
    assert findings(HPX010_BAD, path="hpx_tpu/svc/fixture.py") == []


def test_hpx010_oracle_sites_are_baselined():
    # the oracle module's two gathers (reference gather + quantized
    # frontier RMW) fire and are absorbed — with justification — by
    # the shipped baseline; a third would fail the gate
    res = lint_paths(
        [os.path.join(REPO, "hpx_tpu", "ops", "paged_attention.py")],
        rules=all_rules(["HPX010"]))
    assert len(res.findings) == 2
    new, matched = apply_baseline(res.findings, load_baseline())
    assert new == [] and matched == 2


# ---------------------------------------------------------------------------
# HPX011 — naked retry loops / broad-except swallowing in models+dist
# ---------------------------------------------------------------------------

HPX011_RETRY_BAD = """\
def fetch(conn):
    for attempt in range(5):
        try:
            return conn.read()
        except IOError:
            continue
"""

HPX011_RETRY_GOOD = """\
from hpx_tpu.exec.execution_base import suspend

def fetch(conn):
    for attempt in range(5):
        try:
            return conn.read()
        except IOError:
            suspend(0.01 * attempt)
            continue
"""

HPX011_SWALLOW_BAD = """\
def close(srv):
    try:
        srv.stop()
    except Exception:
        pass
"""


def test_hpx011_retry_without_backoff_fires():
    fs = findings(HPX011_RETRY_BAD, path="hpx_tpu/models/fixture.py")
    assert rules_of(fs) == ["HPX011"]
    assert "fetch()" in fs[0].message and "backoff" in fs[0].message


def test_hpx011_backoff_between_attempts_is_silent():
    assert findings(HPX011_RETRY_GOOD,
                    path="hpx_tpu/models/fixture.py") == []


def test_hpx011_sync_replay_route_is_silent():
    src = ("from hpx_tpu.svc.resiliency import sync_replay\n"
           "def fetch(conn):\n"
           "    return sync_replay(5, conn.read, backoff_s=0.01)\n")
    assert findings(src, path="hpx_tpu/models/fixture.py") == []


def test_hpx011_while_retry_fires():
    src = ("def poke(res):\n"
           "    while True:\n"
           "        try:\n"
           "            return res.acquire_()\n"
           "        except KeyError:\n"
           "            continue\n")
    fs = findings(src, path="hpx_tpu/dist/fixture.py")
    assert rules_of(fs) == ["HPX011"]


def test_hpx011_data_loop_error_isolation_is_silent():
    # a for over a DATA collection with per-item try is isolation,
    # not a retry of the same operation (dist.runtime's counter dump)
    src = ("def dump(patterns):\n"
           "    for p in patterns:\n"
           "        try:\n"
           "            print(p)\n"
           "        except ValueError:\n"
           "            continue\n")
    assert findings(src, path="hpx_tpu/dist/fixture.py") == []


def test_hpx011_broad_swallow_fires():
    fs = findings(HPX011_SWALLOW_BAD, path="hpx_tpu/models/fixture.py")
    assert rules_of(fs) == ["HPX011"]
    assert "close()" in fs[0].message


def test_hpx011_typed_or_handled_except_is_silent():
    # a typed except, and a broad one that actually DOES something,
    # are both fine — only pass-only Exception swallows fire
    src = ("def close(srv, log):\n"
           "    try:\n"
           "        srv.stop()\n"
           "    except ValueError:\n"
           "        pass\n"
           "    try:\n"
           "        srv.join()\n"
           "    except Exception as e:\n"
           "        log.warn(e)\n")
    assert findings(src, path="hpx_tpu/models/fixture.py",
                    select=["HPX011"]) == []


def test_hpx011_outside_resiliency_layers_is_silent():
    assert findings(HPX011_RETRY_BAD,
                    path="hpx_tpu/svc/fixture.py") == []
    assert findings(HPX011_SWALLOW_BAD,
                    path="hpx_tpu/algo/fixture.py",
                    select=["HPX011"]) == []


# ---------------------------------------------------------------------------
# engine: suppressions, syntax errors, baseline
# ---------------------------------------------------------------------------

def test_suppress_same_line():
    src = "try:\n    x = 1\nexcept:  # hpxlint: disable=HPX006 — why\n    pass\n"
    assert findings(src) == []


def test_suppress_next_line():
    src = ("try:\n    x = 1\n"
           "# hpxlint: disable-next=HPX006 — reason\n"
           "except:\n    pass\n")
    assert findings(src) == []


def test_suppress_next_skips_continuation_comments():
    # a multi-line justification must not swallow the directive
    src = ("try:\n    x = 1\n"
           "# hpxlint: disable-next=HPX006 — a justification that\n"
           "# spans several comment lines before the code\n"
           "except:\n    pass\n")
    assert findings(src) == []


def test_suppress_whole_file():
    src = "# hpxlint: disable-file=HPX006\ntry:\n    x=1\nexcept:\n    pass\n"
    assert findings(src) == []


def test_suppress_by_rule_name_and_all():
    by_name = "try:\n    x=1\nexcept:  # hpxlint: disable=bare-except\n    pass\n"
    assert findings(by_name) == []
    by_all = "try:\n    x=1\nexcept:  # hpxlint: disable=all\n    pass\n"
    assert findings(by_all) == []


def test_suppress_wrong_rule_does_not_apply():
    src = "try:\n    x=1\nexcept:  # hpxlint: disable=HPX004\n    pass\n"
    assert rules_of(findings(src)) == ["HPX006"]


def test_suppressions_counted():
    src = "try:\n    x=1\nexcept:  # hpxlint: disable=HPX006\n    pass\n"
    res = lint_source(src, "hpx_tpu/fixture.py", rules=all_rules())
    assert res.suppressed == 1


def test_syntax_error_is_a_finding():
    fs = findings("def broken(:\n")
    assert rules_of(fs) == ["HPX000"]


def test_baseline_roundtrip(tmp_path):
    fs = findings(HPX006_BAD)
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps({"entries": [{
        "path": "hpx_tpu/exec/fixture.py",
        "rule": "HPX006",
        "message": fs[0].message,
        "count": 1,
        "justification": "fixture",
    }]}))
    new, matched = apply_baseline(fs, load_baseline(str(path)))
    assert new == [] and matched == 1
    # a second identical finding exceeds the baselined count -> new
    new2, matched2 = apply_baseline(fs + fs, load_baseline(str(path)))
    assert len(new2) == 1 and matched2 == 1


def test_baseline_does_not_match_other_files(tmp_path):
    fs = findings(HPX006_BAD, path="hpx_tpu/other.py")
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps({"entries": [{
        "path": "hpx_tpu/exec/fixture.py", "rule": "HPX006",
        "message": fs[0].message, "count": 1,
        "justification": "fixture"}]}))
    new, matched = apply_baseline(fs, load_baseline(str(path)))
    assert len(new) == 1 and matched == 0


def test_select_rules():
    src = HPX006_BAD + "\nimport threading\n_l = threading.Lock()\n"
    only6 = findings(src, path="hpx_tpu/svc/fixture.py", select=["HPX006"])
    assert rules_of(only6) == ["HPX006"]


def test_finding_format():
    f = Finding(rule="HPX006", severity="error", path="a/b.py",
                line=3, col=0, message="m")
    assert f.format() == "a/b.py:3:0: HPX006 [error] m"


# ---------------------------------------------------------------------------
# HPX012 — unbounded get() on a remote action future
# ---------------------------------------------------------------------------

HPX012_BAD_CHAINED = """\
from hpx_tpu.dist.actions import async_action

def fetch(loc):
    return async_action("act", loc, 1).get()
"""

HPX012_BAD_VIA_NAME = """\
from hpx_tpu.dist.actions import async_action

def fetch(loc):
    f = async_action("act", loc, 1)
    prep()
    return f.get()
"""

HPX012_GOOD = """\
from hpx_tpu.dist.actions import async_action, resilient_action

def fetch(loc):
    a = async_action("act", loc, 1).get(5.0)       # bounded
    b = resilient_action("act", loc, 1,
                         timeout_s=5.0).get()      # policy owns it
    f = make_future()
    return a, b, f.get()                           # not a remote send
"""


def test_hpx012_flags_chained_unbounded_get():
    fs = findings(HPX012_BAD_CHAINED, path="hpx_tpu/svc/fixture.py")
    assert rules_of(fs) == ["HPX012"]
    assert "resilient_action" in fs[0].message


def test_hpx012_flags_named_future_get():
    fs = findings(HPX012_BAD_VIA_NAME, path="hpx_tpu/svc/fixture.py")
    assert rules_of(fs) == ["HPX012"]


def test_hpx012_clean_shapes():
    assert findings(HPX012_GOOD, path="hpx_tpu/svc/fixture.py") == []


def test_hpx012_skips_tests():
    assert findings(HPX012_BAD_CHAINED,
                    path="tests/test_fixture.py") == []


# ---------------------------------------------------------------------------
# HPX016 — counter-name grammar + dropped histogram timers
# ---------------------------------------------------------------------------

HPX016_BAD_NAME = """\
from hpx_tpu.svc.performance_counters import query_counter

def scrape():
    return query_counter("/serving/locality#0/ttft-p99")
"""

HPX016_BAD_FRAGMENT = """\
from hpx_tpu.svc.performance_counters import counter_name

def name():
    return counter_name("serving", "latency/{oops}")
"""

HPX016_BAD_DROPPED = """\
def observe(h):
    h.record()
    return h
"""

HPX016_GOOD = """\
from hpx_tpu.svc.performance_counters import counter_name, query_counter

def scrape():
    return query_counter("/serving{locality#0/total}/latency/ttft-s/p99")

def name():
    return counter_name("serving", "latency/ttft-s")

def observe(h):
    h.record(0.25)
    with h.record():
        pass
    return h
"""


def test_hpx016_malformed_full_name():
    fs = findings(HPX016_BAD_NAME, path="hpx_tpu/svc/fixture.py")
    assert rules_of(fs) == ["HPX016"]
    assert "grammar" in fs[0].message or "counter name" in fs[0].message


def test_hpx016_malformed_fragments():
    fs = findings(HPX016_BAD_FRAGMENT, path="hpx_tpu/svc/fixture.py")
    assert rules_of(fs) == ["HPX016"]


def test_hpx016_dropped_timer():
    fs = findings(HPX016_BAD_DROPPED, path="hpx_tpu/svc/fixture.py")
    assert rules_of(fs) == ["HPX016"]
    assert "record" in fs[0].message


def test_hpx016_silent_after_fix():
    assert findings(HPX016_GOOD, path="hpx_tpu/svc/fixture.py") == []


def test_hpx016_skips_tests():
    assert findings(HPX016_BAD_DROPPED,
                    path="tests/test_fixture.py") == []


# ---------------------------------------------------------------------------
# HPX017 — raw jit outside the profiled program-cache funnel
# ---------------------------------------------------------------------------

HPX017_BAD = """\
import jax

def decode_step(params, tok):
    prog = jax.jit(lambda p, t: p @ t)
    return prog(params, tok)
"""

HPX017_BAD_DECORATOR = """\
import jax

@jax.jit
def decode_step(params, tok):
    return params @ tok
"""

HPX017_GOOD = """\
import jax
from hpx_tpu.core.programs import cached_program

_PROGRAMS = {}

def _cached_program(key, build):
    return cached_program(_PROGRAMS, key, build)

def decode_step_lambda(params, tok):
    prog = _cached_program(("step", 128),
                           lambda: jax.jit(lambda p, t: p @ t))
    return prog(params, tok)

def decode_step_named(params, tok):
    def build():
        def step(p, t):
            return p @ t
        return jax.jit(step, donate_argnums=(0,))
    prog = cached_program(_PROGRAMS, ("step2",), build)
    return prog(params, tok)
"""


def test_hpx017_raw_jit_call():
    fs = findings(HPX017_BAD, path="hpx_tpu/models/fixture.py")
    assert rules_of(fs) == ["HPX017"]
    assert "decode_step" in fs[0].message


def test_hpx017_raw_jit_decorator():
    fs = findings(HPX017_BAD_DECORATOR,
                  path="hpx_tpu/models/fixture.py")
    assert rules_of(fs) == ["HPX017"]


def test_hpx017_silent_through_cache_funnel():
    assert findings(HPX017_GOOD,
                    path="hpx_tpu/models/fixture.py") == []


def test_hpx017_scoped_to_models_and_ops():
    # same source outside models//ops/ is silent — the funnel is a
    # serving-hot-path discipline, not a repo-wide jit ban
    assert findings(HPX017_BAD, path="hpx_tpu/svc/fixture.py") == []
    fs = findings(HPX017_BAD, path="hpx_tpu/ops/fixture.py")
    assert rules_of(fs) == ["HPX017"]


# ---------------------------------------------------------------------------
# HPX018 — tuner-owned knob mutated outside the config actuation path
# ---------------------------------------------------------------------------

HPX018_BAD = """\
class Server:
    def __init__(self):
        self.prefill_chunk = 64

    def go_faster(self):
        self.prefill_chunk = 512
        self._spec_k += 1
"""

HPX018_GOOD = """\
class Server:
    def __init__(self):
        self.prefill_chunk = 64
        self._spec_k = 4

    def _reload_knobs(self):
        self.prefill_chunk = 512
        self._spec_k = 5

    def go_faster(self, rc):
        rc.set("hpx.serving.prefill_chunk", "512")
"""


def test_hpx018_fires_on_unsanctioned_write():
    fs = findings(HPX018_BAD, path="hpx_tpu/models/fixture.py")
    assert rules_of(fs) == ["HPX018", "HPX018"]
    assert "prefill_chunk" in fs[0].message
    assert "hpx.serving.prefill_chunk" in fs[0].message
    assert "go_faster" in fs[0].message
    assert "_spec_k" in fs[1].message


def test_hpx018_silent_on_actuation_path():
    assert findings(HPX018_GOOD,
                    path="hpx_tpu/models/fixture.py") == []
    assert findings(HPX018_GOOD, path="hpx_tpu/svc/fixture.py") == []


def test_hpx018_scope_and_autotune_exemption():
    # svc/ is in scope; the tuner's own KnobBinding setters are the
    # actuation path and stay exempt; layers outside models//svc/
    # (e.g. cache/radix's budget_blocks __init__) are out of scope
    fs = findings(HPX018_BAD, path="hpx_tpu/svc/fixture.py")
    assert rules_of(fs) == ["HPX018", "HPX018"]
    assert findings(HPX018_BAD, path="hpx_tpu/svc/autotune.py") == []
    assert findings(HPX018_BAD, path="hpx_tpu/cache/fixture.py") == []


def test_hpx018_real_tree_is_clean():
    # ground truth for the rule shipping with an empty baseline: the
    # only in-tree writes to tunable-backed attrs are construction and
    # _reload_knobs
    res = lint_paths([os.path.join(REPO, "hpx_tpu")],
                     rules=all_rules(["HPX018"]))
    assert [f.rule for f in res.findings] == []


def test_hpx017_github_gate_on_real_tree(capsys):
    # the tier-1 gate invocation CI uses: the shipped tree must be
    # clean under the baseline with --format=github (annotations would
    # otherwise land on the PR)
    assert cli_main([os.path.join(REPO, "hpx_tpu"),
                     "--format=github"]) == 0
    assert capsys.readouterr().out == ""


HPX024_BAD = """\
def make_worker(params, cfg, block_size=16):
    return Worker(params, cfg, block_size)

def boot(params, cfg):
    return Server(params, cfg, spec_k=8,
                  prefill_buckets=[8, 16, 32, 64, 128])
"""

HPX024_GOOD = """\
def make_worker(params, cfg, block_size=None):
    if block_size is None:
        block_size = resolve_paged_block(cfg.head_dim)
    return Worker(params, cfg, block_size)

def boot(params, cfg, rc, chunk):
    k = rc.get_int("hpx.serving.spec.k", 4)
    return Server(params, cfg, spec_k=k,
                  prefill_buckets=_resolve_buckets("auto", chunk))
"""


def test_hpx024_fires_on_baked_shape_literals():
    fs = findings(HPX024_BAD, path="hpx_tpu/models/fixture.py")
    assert rules_of(fs) == ["HPX024", "HPX024", "HPX024"]
    assert "block_size" in fs[0].message
    assert "make_worker" in fs[0].message
    assert "resolve_paged_block" in fs[0].message
    assert "spec_k" in fs[1].message
    assert "prefill_buckets" in fs[2].message


def test_hpx024_silent_on_resolver_chain():
    assert findings(HPX024_GOOD,
                    path="hpx_tpu/models/fixture.py") == []


def test_hpx024_scope():
    # models/, svc/ and ops/ carry the serving geometry; layers
    # outside them (exec/, algo/) may bake shapes freely
    assert rules_of(findings(
        HPX024_BAD, path="hpx_tpu/svc/fixture.py")) == ["HPX024"] * 3
    assert rules_of(findings(
        HPX024_BAD, path="hpx_tpu/ops/fixture.py")) == ["HPX024"] * 3
    assert findings(HPX024_BAD) == []  # default exec/ path


def test_hpx024_real_tree_is_clean():
    # ground truth: the shipped models//svc//ops layers resolve every
    # shape knob through the config/perfdb chain (PrefillWorker's
    # block_size routes through resolve_paged_block)
    res = lint_paths([os.path.join(REPO, "hpx_tpu")],
                     rules=all_rules(["HPX024"]))
    assert [f.rule for f in res.findings] == []


def test_all_rules_registry():
    ids = sorted(r.id for r in all_rules())
    assert ids == ["HPX001", "HPX002", "HPX003", "HPX004",
                   "HPX005", "HPX006", "HPX007", "HPX008",
                   "HPX009", "HPX010", "HPX011", "HPX012",
                   "HPX013", "HPX014", "HPX015", "HPX016",
                   "HPX017", "HPX018", "HPX019", "HPX020",
                   "HPX021", "HPX022", "HPX023", "HPX024"]


def test_rule_registry_completeness(capsys):
    """Every rule must document itself consistently in all four places
    a reader finds it: the class docstring, the README lint table,
    --list-rules output, and the project/file tier split."""
    readme = open(os.path.join(REPO, "README.md"), encoding="utf-8").read()
    assert cli_main(["--list-rules"]) == 0
    listed = capsys.readouterr().out
    for rule in all_rules():
        doc = (type(rule).__doc__ or "")
        assert doc.strip().startswith(f"{rule.id}: "), rule.id
        assert f"| {rule.id} | {rule.name} |" in readme, \
            f"{rule.id} missing from the README lint table"
        assert rule.id in listed
    project_ids = {r.id for r in all_rules() if r.scope == "project"}
    assert project_ids == {"HPX013", "HPX014", "HPX015", "HPX023"}
    dataflow_ids = {r.id for r in all_rules() if r.scope == "dataflow"}
    assert dataflow_ids == {"HPX019", "HPX020", "HPX021", "HPX022"}


# ---------------------------------------------------------------------------
# HPX013 — cross-module lock-order inversion (whole-program tier)
# ---------------------------------------------------------------------------

HPX013_A = """\
from hpx_tpu.synchronization import Mutex
from hpx_tpu.svc import b

_a = Mutex()

def outer():
    with _a:
        b.grab()

def touch():
    with _a:
        pass
"""

HPX013_B_CYCLE = """\
from hpx_tpu.synchronization import Mutex
from hpx_tpu.svc import a

_b = Mutex()

def grab():
    with _b:
        pass

def reverse():
    with _b:
        a.touch()
"""

HPX013_B_ORDERED = """\
from hpx_tpu.synchronization import Mutex

_b = Mutex()

def grab():
    with _b:
        pass
"""


def test_hpx013_two_file_cycle_fires_with_both_witnesses():
    res = lint_sources({"hpx_tpu/svc/a.py": HPX013_A,
                        "hpx_tpu/svc/b.py": HPX013_B_CYCLE},
                       rules=all_rules(["HPX013"]))
    assert rules_of(res.findings) == ["HPX013"]
    msg = res.findings[0].message
    # both witness call chains, each naming the functions on the path
    assert "hpx_tpu.svc.a:outer -> hpx_tpu.svc.b:grab" in msg
    assert "hpx_tpu.svc.b:reverse -> hpx_tpu.svc.a:touch" in msg


def test_hpx013_consistent_order_is_silent():
    res = lint_sources({"hpx_tpu/svc/a.py": HPX013_A,
                        "hpx_tpu/svc/b.py": HPX013_B_ORDERED},
                       rules=all_rules(["HPX013"]))
    assert res.findings == []


def test_hpx013_single_file_nested_inversion_fires():
    src = """\
from hpx_tpu.synchronization import Mutex

_x = Mutex()
_y = Mutex()

def forward():
    with _x:
        with _y:
            pass

def backward():
    with _y:
        with _x:
            pass
"""
    res = lint_sources({"hpx_tpu/svc/m.py": src},
                       rules=all_rules(["HPX013"]))
    assert rules_of(res.findings) == ["HPX013"]


# ---------------------------------------------------------------------------
# HPX012/HPX013 coverage over the fleet module's shapes
# ---------------------------------------------------------------------------

HPX012_FLEET_BAD = """\
from hpx_tpu.dist.actions import async_action

class Router:
    def _digest(self, loc):
        # the placement-loop digest pull: a hung worker must not
        # wedge the router, so a bare get() is exactly the bug
        return async_action("prefix_digest", loc, 64).get()
"""

HPX012_FLEET_GOOD = """\
from hpx_tpu.dist.actions import async_action

class Router:
    def _digest(self, loc):
        return async_action("prefix_digest", loc, 64).get(0.25)
"""


def test_hpx012_flags_fleet_style_digest_pull():
    fs = findings(HPX012_FLEET_BAD, path="hpx_tpu/svc/fleet_fx.py")
    assert rules_of(fs) == ["HPX012"]
    assert findings(HPX012_FLEET_GOOD,
                    path="hpx_tpu/svc/fleet_fx.py") == []


def test_hpx013_fleet_instance_lock_inversion_fires():
    # fleet-shaped: the router's bookkeeping lock (an instance-attr
    # Mutex, like FleetRouter._fl_lock) inverted against a worker
    # module's lock must still be a whole-tree lock identity
    src = """\
from hpx_tpu.synchronization import Mutex

class Router:
    def __init__(self):
        self._fl_lock = Mutex()
        self._pool_lock = Mutex()

    def place(self):
        with self._fl_lock:
            with self._pool_lock:
                pass

    def retire(self):
        with self._pool_lock:
            with self._fl_lock:
                pass
"""
    res = lint_sources({"hpx_tpu/svc/fleet_fx.py": src},
                       rules=all_rules(["HPX013"]))
    assert rules_of(res.findings) == ["HPX013"]


def test_project_index_has_fleet_router_lock():
    # the real tree: HPX013's index must see svc/fleet's bookkeeping
    # lock, so fleet code is inside the lock-order contract
    from hpx_tpu.analysis.engine import FileContext
    from hpx_tpu.analysis.project import ProjectIndex
    path = os.path.join(REPO, "hpx_tpu", "svc", "fleet.py")
    with open(path) as fh:
        ctx = FileContext(fh.read(), "hpx_tpu/svc/fleet.py")
    index = ProjectIndex([ctx])
    assert "hpx_tpu.svc.fleet.FleetRouter._fl_lock" in index.locks


# ---------------------------------------------------------------------------
# HPX014 — config keys must be declared in core/config_schema.py
# ---------------------------------------------------------------------------

HPX014_SCHEMA = """\
def declare(key, type, default=None, doc="", reserved=False):
    pass

declare("hpx.fix.workers", "int", "4", "worker count")
declare("hpx.fix.trace", "bool", "0", "tracing toggle")
declare("hpx.fix.dead", "str", "x", "never read anywhere")
declare("hpx.fix.parity", "str", None, "HPX parity", reserved=True)
"""

HPX014_READER = """\
def setup(cfg):
    n = cfg.get_int("hpx.fix.workers")
    t = cfg.get_int("hpx.fix.trace")
    z = cfg.get("hpx.fix.typo_key")
    return n, t, z
"""


def _hpx014(sources):
    res = lint_sources(sources, rules=all_rules(["HPX014"]))
    return res.findings


def test_hpx014_undeclared_read_type_mismatch_and_dead_key():
    fs = _hpx014({"hpx_tpu/core/config_schema.py": HPX014_SCHEMA,
                  "hpx_tpu/svc/reader.py": HPX014_READER})
    msgs = sorted(f.message for f in fs)
    assert len(fs) == 3
    assert any("'hpx.fix.typo_key' read via get() is not declared"
               in m for m in msgs)
    assert any("'hpx.fix.trace' is declared 'bool' but read via "
               "get_int()" in m for m in msgs)
    assert any("'hpx.fix.dead' is declared but never read" in m
               for m in msgs)


def test_hpx014_declared_and_reserved_keys_are_silent():
    clean = """\
def setup(cfg):
    n = cfg.get_int("hpx.fix.workers")
    t = cfg.get_bool("hpx.fix.trace")
    d = cfg.get("hpx.fix.dead")
    return n, t, d
"""
    assert _hpx014({"hpx_tpu/core/config_schema.py": HPX014_SCHEMA,
                    "hpx_tpu/svc/reader.py": clean}) == []


def test_hpx014_real_tree_schema_is_exhaustive():
    # the shipped registry declares every key the tree reads, exactly:
    # no undeclared reads, no dead keys (modulo reserved= parity keys)
    res = lint_paths([os.path.join(REPO, "hpx_tpu")],
                     rules=all_rules(["HPX014"]))
    assert res.findings == [], "\n".join(f.format() for f in res.findings)


# ---------------------------------------------------------------------------
# HPX015 — incref/pin balance on every exit path (cache/ + models/)
# ---------------------------------------------------------------------------

def _hpx015(source):
    res = lint_sources({"hpx_tpu/cache/fixture.py": source},
                       rules=all_rules(["HPX015"]))
    return res.findings


def test_hpx015_early_return_leak_fires():
    fs = _hpx015("""\
class Pool:
    def take(self, alloc, bid):
        alloc.incref(bid)
        if bid < 0:
            return None
        v = self.read(bid)
        alloc.decref(bid)
        return v
""")
    assert rules_of(fs) == ["HPX015"]
    assert "incref(bid) in Pool.take" in fs[0].message


def test_hpx015_try_finally_balance_is_silent():
    assert _hpx015("""\
class Pool:
    def take(self, alloc, bid):
        alloc.incref(bid)
        try:
            return self.read(bid)
        finally:
            alloc.decref(bid)
""") == []


def test_hpx015_leak_inside_try_still_fires():
    # the finally here does NOT release; the early return leaks
    fs = _hpx015("""\
class Pool:
    def take(self, alloc, bid):
        alloc.incref(bid)
        try:
            if bid < 0:
                return None
            v = self.read(bid)
        finally:
            self.log(bid)
        alloc.decref(bid)
        return v
""")
    assert rules_of(fs) == ["HPX015"]


def test_hpx015_pure_ownership_transfer_is_silent():
    # acquire-only functions hand the references to an owner that
    # retires them elsewhere (the _capture_slot / _restore_slot shape)
    assert _hpx015("""\
class Pool:
    def capture(self, alloc, pins):
        for bid in pins:
            alloc.incref(bid)
        return list(pins)
""") == []


def test_hpx015_loop_acquire_release_pairs_by_iterable():
    # pinning loop + releasing loop over DIFFERENT iterables: the keys
    # ("new.pins" vs "old.pins") keep the transfer exemption intact
    assert _hpx015("""\
class Pool:
    def swap(self, alloc, new, old):
        for bid in new.pins:
            alloc.incref(bid)
        for bid in old.pins:
            alloc.decref(bid)
""") == []


def test_hpx015_outside_scoped_layers_is_silent():
    res = lint_sources({"hpx_tpu/svc/fixture.py": """\
class Pool:
    def take(self, alloc, bid):
        alloc.incref(bid)
        return bid
"""}, rules=all_rules(["HPX015"]))
    assert res.findings == []


# the host-tier checkout family (cache/tier.py): checkout() acquires an
# entry, checkin() retires it, putback() is the abort-path release —
# same balance discipline, one tier down from incref/decref

def _hpx015_tier(source):
    res = lint_sources({"hpx_tpu/cache/tier.py": source},
                       rules=all_rules(["HPX015"]))
    return res.findings


def test_hpx015_tier_checkout_leak_fires():
    fs = _hpx015_tier("""\
class Promoter:
    def restore(self, tier, h, bad):
        tier.checkout(h)
        if bad:
            return 0
        tier.checkin(h)
        return 1
""")
    assert rules_of(fs) == ["HPX015"]
    assert "checkout(h) in Promoter.restore" in fs[0].message
    assert "checkin()" in fs[0].message


def test_hpx015_tier_putback_on_abort_is_silent():
    # putback balances the checkout on the abort path exactly like
    # checkin does on the success path
    assert _hpx015_tier("""\
class Promoter:
    def restore(self, tier, h, bad):
        tier.checkout(h)
        if bad:
            tier.putback(h)
            return 0
        tier.checkin(h)
        return 1
""") == []


def test_hpx015_tier_checkout_transfer_is_silent():
    # the real promotion shape: checkout(hash) returns an ENTRY that
    # is checked in under its own name — the differing operand keys
    # keep the ownership-transfer exemption intact
    assert _hpx015_tier("""\
class Promoter:
    def promote(self, tier, h, bad):
        e = tier.checkout(h)
        if e is None:
            return None
        if bad:
            tier.putback(e)
            return None
        tier.checkin(e)
        return e
""") == []


def test_hpx016_tier_counter_namespace_is_stable():
    """The /cache{...}/tier/* namespace is an observability contract:
    every leaf cache/counters.py registers for a tiered server must
    (a) still be registered under exactly that name and (b) parse
    under the HPX016 counter grammar — base names and the derived pNN
    quantile counters alike."""
    from hpx_tpu.analysis.rules import _COUNTER_NAME_RE
    from hpx_tpu.svc.metrics import configured_quantiles, quantile_label
    from hpx_tpu.svc.performance_counters import counter_name

    leaves = ["tier/bytes-held", "tier/entries",
              "tier/count/demoted", "tier/count/promoted",
              "tier/count/dropped", "tier/count/declined",
              "tier/hit-depth-blocks"]
    src = open(os.path.join(REPO, "hpx_tpu", "cache", "counters.py"),
               encoding="utf-8").read()
    for leaf in leaves + ["tier/promote-latency-s"]:
        assert f'"{leaf}"' in src, \
            f"{leaf!r} gone from cache/counters.py — the tier " \
            "counter namespace is pinned; rename both sides or don't"
    hist = ["tier/promote-latency-s"] + [
        f"tier/promote-latency-s/{quantile_label(q)}"
        for q in configured_quantiles()]
    for leaf in leaves + hist:
        name = counter_name("cache", leaf, "server#0", locality=0)
        assert _COUNTER_NAME_RE.match(name), name
    # and the literal form stays HPX016-clean at a query site
    assert findings(
        "from hpx_tpu.svc.performance_counters import query_counter\n"
        "def scrape():\n"
        "    return query_counter(\n"
        '        "/cache{locality#0/server#0}/tier/count/promoted")\n',
        path="hpx_tpu/svc/fixture.py") == []


def test_hpx016_moe_counter_namespace_is_stable():
    """The /serving{...}/moe/* namespace is an observability contract:
    the MoE decode counters cache/counters.py registers for an
    expert-routed server must (a) still be registered under exactly
    those names and (b) parse under the HPX016 counter grammar,
    including the per-expert `expert#e` instance fragment."""
    from hpx_tpu.analysis.rules import _COUNTER_NAME_RE
    from hpx_tpu.svc.performance_counters import counter_name

    src = open(os.path.join(REPO, "hpx_tpu", "cache", "counters.py"),
               encoding="utf-8").read()
    for lit in ('"moe/tokens-routed"', '"moe/tokens-dropped"',
                'f"moe/expert#{e}/occupancy"'):
        assert lit in src, \
            f"{lit} gone from cache/counters.py — the MoE counter " \
            "namespace is pinned; rename both sides or don't"
    leaves = ["moe/tokens-routed", "moe/tokens-dropped",
              "moe/expert#0/occupancy", "moe/expert#7/occupancy"]
    for leaf in leaves:
        name = counter_name("serving", leaf, "server#0", locality=0)
        assert _COUNTER_NAME_RE.match(name), name
    # and the literal form stays HPX016-clean at a query site
    assert findings(
        "from hpx_tpu.svc.performance_counters import query_counter\n"
        "def scrape():\n"
        "    return query_counter(\n"
        '        "/serving{locality#0/server#0}/moe/tokens-dropped")\n',
        path="hpx_tpu/svc/fixture.py") == []


# ---------------------------------------------------------------------------
# HPX023 — quantile scans reachable from the serving hot path
# ---------------------------------------------------------------------------

def test_hpx023_quantile_reachable_from_step_fires():
    res = lint_sources({"hpx_tpu/svc/srv.py": """\
class Server:
    def step(self):
        self._tick()

    def _tick(self):
        return self.hist.quantile(0.99)
"""}, rules=all_rules(["HPX023"]))
    assert rules_of(res.findings) == ["HPX023"]
    assert "quantile()" in res.findings[0].message
    assert "Server._tick" in res.findings[0].message


def test_hpx023_detached_snapshot_is_silent():
    # the sanctioned shape: scan a detached from_snapshot() copy, not
    # the live histogram — the call-result base is off the hot path's
    # shared structure so it carries no per-step lock cost
    res = lint_sources({"hpx_tpu/svc/srv.py": """\
from hpx_tpu.svc.metrics import HistogramCounter

class Server:
    def step(self):
        self._tick()

    def _tick(self):
        snap = self.hist.delta(self.prev)
        return HistogramCounter.from_snapshot(snap).quantile(0.99)
"""}, rules=all_rules(["HPX023"]))
    assert res.findings == []


def test_hpx023_cold_path_quantile_is_silent():
    # same scan in a debug/stats method nothing on the hot path
    # reaches — reporting endpoints may walk buckets freely
    res = lint_sources({"hpx_tpu/svc/srv.py": """\
class Server:
    def step(self):
        self.tokens += 1

    def stats(self):
        return self.hist.quantile(0.99)
"""}, rules=all_rules(["HPX023"]))
    assert res.findings == []


def test_hpx023_cross_module_merged_hist_fires():
    # reachability crosses modules through import aliases: the router
    # pump calls a helper whose module-level merged_hist() scan is the
    # violation
    res = lint_sources({
        "hpx_tpu/svc/a.py": """\
from hpx_tpu.svc.b import summarize

class Router:
    def _pump_decodes(self):
        return summarize(self.hists)
""",
        "hpx_tpu/svc/b.py": """\
from hpx_tpu.svc.metrics import merged_hist

def summarize(hists):
    return merged_hist(hists)
"""}, rules=all_rules(["HPX023"]))
    assert rules_of(res.findings) == ["HPX023"]
    assert "merged_hist()" in res.findings[0].message
    assert res.findings[0].path == "hpx_tpu/svc/b.py"


# ---------------------------------------------------------------------------
# suppression on a multi-line statement's header line
# ---------------------------------------------------------------------------

def test_suppress_on_header_reaches_continuation_lines():
    src = """\
import numpy as np

def f(x):
    y = compute(  # hpxlint: disable=HPX002 — pinned fixture
        np.asarray(x))
    return y
"""
    res = lint_source(src, "hpx_tpu/exec/fixture.py",
                      rules=all_rules(["HPX002"]))
    assert res.findings == [] and res.suppressed == 1
    # same code without the directive fires on the continuation line
    bare = src.replace("  # hpxlint: disable=HPX002 — pinned fixture", "")
    res2 = lint_source(bare, "hpx_tpu/exec/fixture.py",
                       rules=all_rules(["HPX002"]))
    assert [(f.line, f.rule) for f in res2.findings] == [(5, "HPX002")]


def test_suppress_on_with_header_does_not_blanket_body():
    # directive on the `with` header suppresses findings on the
    # header's continuation lines only — the block body still fires
    header_only = """\
import threading

def setup():
    with wrap(  # hpxlint: disable=HPX004 — bootstrap substrate
            threading.Lock()):
        pass
"""
    res = lint_source(header_only, "hpx_tpu/svc/fixture.py",
                      rules=all_rules(["HPX004"]))
    assert res.findings == [] and res.suppressed == 1
    body = """\
import threading

def setup():
    with wrap(  # hpxlint: disable=HPX004 — bootstrap substrate
            make()):
        lock = threading.Lock()
"""
    res2 = lint_source(body, "hpx_tpu/svc/fixture.py",
                       rules=all_rules(["HPX004"]))
    assert [(f.line, f.rule) for f in res2.findings] == [(6, "HPX004")]


# ---------------------------------------------------------------------------
# --update-baseline / stale-entry gate / --format=github
# ---------------------------------------------------------------------------

def test_update_baseline_keeps_justifications_prunes_stale(tmp_path):
    bad = tmp_path / "mod.py"
    bad.write_text(HPX006_BAD)
    bl = str(tmp_path / "baseline.json")
    assert cli_main([str(bad), "--baseline", bl, "--write-baseline"]) == 0
    rec = json.loads(open(bl).read())
    rec["entries"][0]["justification"] = "hand-written why"
    rec["entries"].append({"path": "gone.py", "rule": "HPX006",
                           "message": "m", "count": 1,
                           "justification": "stale"})
    with open(bl, "w") as f:
        json.dump(rec, f)
    # the gate fails while a stale entry lingers...
    assert cli_main([str(bad), "--baseline", bl]) == 1
    # ...--update-baseline prunes it and keeps the edited justification
    assert cli_main([str(bad), "--baseline", bl, "--update-baseline"]) == 0
    rec2 = json.loads(open(bl).read())
    assert [e["justification"] for e in rec2["entries"]] \
        == ["hand-written why"]
    assert cli_main([str(bad), "--baseline", bl]) == 0


def test_format_github_annotations(tmp_path, capsys):
    bad = tmp_path / "mod.py"
    bad.write_text(HPX006_BAD)
    assert cli_main([str(bad), "--no-baseline", "--format=github"]) == 1
    out = capsys.readouterr().out
    assert out.startswith("::error file=")
    assert "title=HPX006::" in out


def test_format_json(tmp_path, capsys):
    bad = tmp_path / "mod.py"
    bad.write_text(HPX006_BAD)
    assert cli_main([str(bad), "--no-baseline", "--format=json"]) == 1
    rec = json.loads(capsys.readouterr().out)
    assert rec["checked_files"] == 1
    assert [f["rule"] for f in rec["findings"]] == ["HPX006"]
    assert rec["stale_baseline_entries"] == []


# ---------------------------------------------------------------------------
# the lint gate: the real tree must be clean under the shipped baseline
# ---------------------------------------------------------------------------

def test_cli_gate_on_real_tree():
    res = lint_paths([os.path.join(REPO, "hpx_tpu")], rules=all_rules())
    # display paths are repo-relative, so the shipped baseline applies
    assert all(f.path.startswith("hpx_tpu") for f in res.findings)
    new, _ = apply_baseline(res.findings, load_baseline())
    assert new == [], "\n".join(f.format() for f in new)


def test_full_run_parses_once_and_stays_fast():
    # the project and dataflow tiers share the per-file tier's parsed
    # trees: a full three-tier run over N files costs exactly N
    # ast.parse calls, and the whole pass (all 22 rules, cross-module
    # index and def-use chains included) must stay inside the tier-1
    # perf budget
    import time
    before = parse_count()
    t0 = time.monotonic()
    res = lint_paths([os.path.join(REPO, "hpx_tpu")], rules=all_rules())
    elapsed = time.monotonic() - t0
    assert parse_count() - before == res.checked_files
    assert elapsed < 10.0, f"full hpxlint run took {elapsed:.1f}s"


def test_cli_exit_codes(tmp_path):
    bad = tmp_path / "mod.py"
    bad.write_text(HPX006_BAD)
    assert cli_main([str(bad), "--no-baseline"]) == 1
    bad.write_text(HPX006_GOOD)
    assert cli_main([str(bad), "--no-baseline"]) == 0


def test_cli_list_rules(capsys):
    assert cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "HPX001" in out and "HPX006" in out


def test_module_smoke():
    # the documented invocation, end to end, from the repo root
    proc = subprocess.run(
        [sys.executable, "-m", "hpx_tpu.analysis", "hpx_tpu/"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
