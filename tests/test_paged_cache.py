"""Host-side paged KV-cache bookkeeping (hpx_tpu/cache): block
allocator ref counts and copy-on-write, page tables, and the radix
prefix tree's match/insert/evict contract. Pure Python — no jax
arrays; the device side is exercised by test_paged_serving.py."""

import numpy as np
import pytest

from hpx_tpu.cache import (BlockAllocator, CacheOOM, PageTable,
                           RadixCache, materialize, prefix_hashes)


# -- BlockAllocator ----------------------------------------------------------

def test_alloc_is_deterministic_and_exhausts():
    a = BlockAllocator(num_blocks=3, block_size=4)
    assert [a.alloc() for _ in range(3)] == [0, 1, 2]
    assert a.free_count == 0 and a.in_use == 3
    with pytest.raises(CacheOOM):
        a.alloc()


def test_decref_returns_block_to_pool():
    a = BlockAllocator(4, 8)
    b = a.alloc()
    assert a.refcount(b) == 1
    assert a.decref(b) is True           # freed
    assert a.free_count == 4
    # freed block is reusable (LIFO: comes straight back)
    assert a.alloc() == b


def test_shared_block_survives_one_holder():
    a = BlockAllocator(4, 8)
    b = a.alloc()
    a.incref(b)                          # second holder
    assert a.refcount(b) == 2
    assert a.decref(b) is False          # still held
    assert a.refcount(b) == 1
    assert a.decref(b) is True


def test_ref_misuse_raises():
    a = BlockAllocator(2, 4)
    with pytest.raises(ValueError):
        a.incref(0)                      # never allocated
    with pytest.raises(ValueError):
        a.decref(1)
    with pytest.raises(ValueError):
        a.fork(0)


def test_cow_fork_exclusive_is_in_place():
    a = BlockAllocator(4, 8)
    b = a.alloc()
    nb, copied = a.fork(b)
    assert (nb, copied) == (b, False)    # refcount 1: write in place
    assert a.total_cow_copies == 0


def test_cow_fork_shared_allocates_fresh():
    a = BlockAllocator(4, 8)
    b = a.alloc()
    a.incref(b)                          # shared with the radix tree
    nb, copied = a.fork(b)
    assert copied and nb != b
    assert a.refcount(b) == 1            # other holder keeps the old
    assert a.refcount(nb) == 1           # forker owns the new
    assert a.total_cow_copies == 1


def test_cow_fork_oom_when_pool_full():
    a = BlockAllocator(2, 4)
    b0, b1 = a.alloc(), a.alloc()
    a.incref(b0)
    with pytest.raises(CacheOOM):
        a.fork(b0)
    assert a.refcount(b0) == 2           # failed fork changed nothing
    del b1


# -- PageTable ---------------------------------------------------------------

def test_page_table_capacity_and_mapping():
    pt = PageTable(block_size=4)
    assert pt.capacity == 0
    pt.append_block(7)
    pt.append_block(2)
    assert pt.capacity == 8
    assert pt.block_of(0) == 7 and pt.block_of(3) == 7
    assert pt.block_of(4) == 2
    assert pt.blocks_for(5) == 2
    assert pt.blocks_for(8) == 2
    assert pt.blocks_for(9) == 3


def test_page_table_as_row_pads():
    pt = PageTable(4)
    pt.append_block(3)
    row = pt.as_row(max_blocks=4, pad=9)
    assert row.dtype == np.int32
    assert row.tolist() == [3, 9, 9, 9]


def test_materialize_handles_dead_slots():
    pt = PageTable(4)
    pt.append_block(5)
    tab = materialize([pt, None], max_blocks=3, pad=0)
    assert tab.shape == (2, 3) and tab.dtype == np.int32
    assert tab.tolist() == [[5, 0, 0], [0, 0, 0]]


# -- RadixCache --------------------------------------------------------------

def _chain(alloc, n):
    return [alloc.alloc() for _ in range(n)]


def test_radix_match_is_block_granular():
    a = BlockAllocator(8, 4)
    r = RadixCache(a)
    toks = list(range(10))               # 2 full blocks + ragged tail
    bids = _chain(a, 2)
    assert r.insert(toks, bids) == 2     # tail ignored
    assert r.blocks_held == 2
    assert a.refcount(bids[0]) == 2      # caller + tree

    m, got = r.match(toks)
    assert m == 8 and got == bids
    assert a.refcount(bids[0]) == 3      # match took a read lease

    m2, got2 = r.match(toks[:6])         # only 1 full block of it
    assert m2 == 4 and got2 == [bids[0]]
    m3, got3 = r.match([99, 98, 97, 96])
    assert (m3, got3) == (0, [])


def test_radix_insert_dedups_by_content():
    a = BlockAllocator(8, 4)
    r = RadixCache(a)
    toks = list(range(8))
    first = _chain(a, 2)
    r.insert(toks, first)
    dup = _chain(a, 2)                   # same tokens, different blocks
    assert r.insert(toks, dup) == 0      # nothing newly retained
    assert r.blocks_held == 2
    # the duplicate chain stays wholly the caller's to free
    assert a.refcount(dup[0]) == 1 and a.refcount(first[0]) == 2


def test_radix_divergent_suffixes_share_prefix_node():
    a = BlockAllocator(8, 4)
    r = RadixCache(a)
    pre = [1, 2, 3, 4]
    ca = _chain(a, 2)
    cb = [ca[0]] + _chain(a, 1)          # same prefix block, new tail
    r.insert(pre + [5, 6, 7, 8], ca)
    a.incref(ca[0])                      # second publisher's lease
    r.insert(pre + [9, 9, 9, 9], cb)
    assert r.blocks_held == 3            # 1 shared prefix + 2 tails
    m, got = r.match(pre + [9, 9, 9, 9])
    assert m == 8 and got == cb


def _publish(r, a, toks, n):
    """Insert then drop the publisher's own refs, as retire does —
    leaves the tree holding the only reference (the idle state)."""
    chain = _chain(a, n)
    r.insert(toks, chain)
    for b in chain:
        a.decref(b)
    return chain


def test_radix_evict_lru_skips_live_readers():
    a = BlockAllocator(8, 4)
    r = RadixCache(a)
    _publish(r, a, [1, 1, 1, 1], 1)          # older
    _publish(r, a, [2, 2, 2, 2], 1)          # newer
    m, lease = r.match([2, 2, 2, 2])         # newer becomes MRU + leased
    assert m == 4

    # also lease the older chain: now nothing is evictable
    _, old_lease = r.match([1, 1, 1, 1])
    assert r.evict(2) == (0, 0)
    for b in old_lease:
        a.decref(b)                          # reader retires

    # LRU idle leaf goes first; no demote hook -> (demoted, dropped)
    assert r.evict(1) == (0, 1)
    assert r.match([1, 1, 1, 1])[0] == 0     # the older one is gone
    assert r.blocks_held == 1
    for b in lease:
        a.decref(b)


def test_radix_budget_trims_on_insert():
    a = BlockAllocator(8, 4)
    r = RadixCache(a, budget_blocks=2)
    _publish(r, a, list(range(8)), 2)        # exactly at budget
    _publish(r, a, [9] * 4, 1)               # pushes over -> trim
    assert r.blocks_held == 2
    assert r.total_evictions == 1


def test_oom_evict_retry_loop():
    """The serving loop's recovery path: pool exhausted, idle radix
    chains give their blocks back, retry succeeds."""
    a = BlockAllocator(2, 4)
    r = RadixCache(a)
    chain = _chain(a, 2)
    r.insert(list(range(8)), chain)
    for b in chain:
        a.decref(b)                          # publisher retired: idle
    with pytest.raises(CacheOOM):
        a.alloc()
    assert r.evict(1) == (0, 1)
    a.alloc()                                # retry succeeds


def test_prefix_digest_mirrors_prefix_hashes():
    # the fleet-placement contract: a retained chain's digest entries
    # are exactly the prompt-side chain hashes of its whole-block
    # prefixes, so longest-match scoring needs no token lists
    a = BlockAllocator(8, 4)
    r = RadixCache(a)
    toks = list(range(9))                    # 2 full blocks + tail
    r.insert(toks, _chain(a, 2))
    hs = prefix_hashes(toks, 4)
    assert len(hs) == 2
    assert set(r.prefix_digest()) == set(hs)
    # a different chain that shares block 0's TOKENS at a different
    # depth must not alias: chain hashing is positional
    other = [9, 9, 9, 9] + toks[:4]
    r.insert(other, [_chain(a, 1)[0], a.alloc()])
    dg = set(r.prefix_digest())
    assert prefix_hashes(other, 4)[1] in dg
    assert prefix_hashes(toks[:4], 4)[0] in dg
    # same token block, different depth -> different chain hash
    assert prefix_hashes(other, 4)[1] != prefix_hashes(toks[:4], 4)[0]


def test_prefix_digest_truncates_mru_first():
    a = BlockAllocator(16, 4)
    r = RadixCache(a)
    cold = [50, 51, 52, 53]
    hot = [60, 61, 62, 63]
    r.insert(cold, _chain(a, 1))
    r.insert(hot, _chain(a, 1))
    r.match(hot)                             # touch: hot is MRU
    dg = r.prefix_digest(max_entries=1)
    assert dg == [prefix_hashes(hot, 4)[0]]
    # takes no leases and mutates nothing
    assert r.prefix_digest() and r.blocks_held == 2
    assert r.prefix_digest(max_entries=0) == []


def test_prefix_hashes_short_and_ragged():
    assert prefix_hashes([1, 2, 3], 4) == []
    one = prefix_hashes([1, 2, 3, 4], 4)
    assert len(one) == 1
    assert prefix_hashes([1, 2, 3, 4, 9], 4) == one  # tail ignored


def test_match_updates_hit_rate():
    a = BlockAllocator(8, 4)
    r = RadixCache(a)
    assert r.hit_rate() == 0.0
    r.insert(list(range(4)), _chain(a, 1))
    r.match(list(range(4)))                  # 4 requested, 4 matched
    r.match([7, 7, 7, 7])                    # 4 requested, 0 matched
    assert r.hit_rate() == pytest.approx(0.5)
    st = r.stats()
    assert st["tokens_requested"] == 8 and st["tokens_matched"] == 4
