"""In-jit pipeline parallelism (the pp axis): parallel/pipeline_spmd +
models/transformer.make_pipelined_train_step.

Oracle: the unpipelined dp x sp x tp train step on a 1-device mesh —
GPipe is an exact schedule (no accumulation-order looseness beyond
float addition), so pipelined loss and updated params must match to
float tolerance, for any microbatch count.
"""

import dataclasses
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import hpx_tpu.models.transformer as tfm

CFG = tfm.TransformerConfig(vocab=32, d_model=16, n_heads=2, head_dim=8,
                            n_layers=4, d_ff=32, lr=0.05)


def _batch(key, batch=4, seq=8):
    return tfm.sample_batch(CFG, batch, seq, key)


def _oracle_step(toks, tgts):
    mesh1 = Mesh(np.array(jax.devices()[:1]).reshape(1, 1, 1),
                 ("dp", "sp", "tp"))
    params = tfm.init_params(CFG, jax.random.PRNGKey(0))
    params = tfm.shard_params(params, CFG, mesh1)
    step = tfm.make_train_step(CFG, mesh1)
    t, g = tfm.shard_batch(toks, tgts, mesh1)
    new_params, loss = step(params, t, g)
    return jax.device_get(new_params), float(loss)


def _pipelined_step(toks, tgts, mesh, n_microbatches):
    from jax.sharding import NamedSharding, PartitionSpec as P
    params = tfm.init_params(CFG, jax.random.PRNGKey(0))
    stacked = tfm.stack_pipeline_params(params)
    stacked = tfm.shard_pipeline_params(stacked, mesh)
    step = tfm.make_pipelined_train_step(CFG, mesh, n_microbatches)
    sh = NamedSharding(mesh, P("dp", None))
    t = jax.device_put(toks, sh)
    g = jax.device_put(tgts, sh)
    new_params, loss = step(stacked, t, g)
    return jax.device_get(new_params), float(loss)


@pytest.mark.parametrize("n_microbatches", [1, 2])
def test_pp_matches_unpipelined(devices, n_microbatches):
    toks, tgts = _batch(jax.random.PRNGKey(1))
    ref_params, ref_loss = _oracle_step(toks, tgts)
    mesh = Mesh(np.array(devices[:4]).reshape(2, 2), ("dp", "pp"))
    pp_params, pp_loss = _pipelined_step(toks, tgts, mesh, n_microbatches)
    assert pp_loss == pytest.approx(ref_loss, abs=1e-5)
    ref_stacked = tfm.stack_pipeline_params(ref_params)
    for a, b in zip(jax.tree.leaves(ref_stacked),
                    jax.tree.leaves(pp_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5)


def test_pp_with_tp(devices):
    """dp x pp x tp = 2 x 2 x 2 over the full 8-device mesh."""
    toks, tgts = _batch(jax.random.PRNGKey(2))
    _, ref_loss = _oracle_step(toks, tgts)
    mesh = Mesh(np.array(devices).reshape(2, 2, 2), ("dp", "pp", "tp"))
    pp_params, pp_loss = _pipelined_step(toks, tgts, mesh, 2)
    assert pp_loss == pytest.approx(ref_loss, abs=1e-5)
    assert all(np.all(np.isfinite(np.asarray(x)))
               for x in jax.tree.leaves(pp_params))


def test_pp_trains(devices):
    """Loss decreases over a few pipelined steps (pp=4, dp=2, M=4)."""
    mesh = Mesh(np.array(devices).reshape(2, 4), ("dp", "pp"))
    toks, tgts = _batch(jax.random.PRNGKey(3), batch=8, seq=8)
    from jax.sharding import NamedSharding, PartitionSpec as P
    params = tfm.stack_pipeline_params(
        tfm.init_params(CFG, jax.random.PRNGKey(0)))
    params = tfm.shard_pipeline_params(params, mesh)
    step = tfm.make_pipelined_train_step(CFG, mesh, 4)
    sh = NamedSharding(mesh, P("dp", None))
    t, g = jax.device_put(toks, sh), jax.device_put(tgts, sh)
    params, l0 = step(params, t, g)
    for _ in range(3):
        params, l1 = step(params, t, g)
    assert float(l1) < float(l0)


def test_pp_rejects_bad_config(devices):
    mesh = Mesh(np.array(devices[:4]).reshape(1, 4), ("dp", "pp"))
    bad = tfm.TransformerConfig(vocab=32, d_model=16, n_heads=2,
                                head_dim=8, n_layers=3, d_ff=32)
    with pytest.raises(ValueError, match="divisible"):
        tfm.make_pipelined_train_step(bad, mesh, 2)
    moe = tfm.TransformerConfig(vocab=32, d_model=16, n_heads=2,
                                head_dim=8, n_layers=4, d_ff=32,
                                n_experts=2)
    with pytest.raises(NotImplementedError):
        tfm.make_pipelined_train_step(moe, mesh, 2)


def test_pp_optax(devices):
    """Adam via optax in the pipelined step; opt state sharded like
    the stacked params."""
    import optax
    mesh = Mesh(np.array(devices[:4]).reshape(2, 2), ("dp", "pp"))
    opt = optax.adam(1e-2)
    params = tfm.shard_pipeline_params(
        tfm.stack_pipeline_params(
            tfm.init_params(CFG, jax.random.PRNGKey(0))), mesh)
    state = tfm.make_pipelined_opt_state(params, CFG, mesh, opt)
    step = tfm.make_pipelined_train_step(CFG, mesh, 2, optimizer=opt)
    toks, tgts = _batch(jax.random.PRNGKey(9))
    from jax.sharding import NamedSharding, PartitionSpec as P
    sh = NamedSharding(mesh, P("dp", None))
    t, g = jax.device_put(toks, sh), jax.device_put(tgts, sh)
    losses = []
    for _ in range(5):
        params, state, l = step(params, state, t, g)
        losses.append(float(l))
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))


CFG8 = tfm.TransformerConfig(vocab=32, d_model=16, n_heads=2, head_dim=8,
                             n_layers=8, d_ff=32, lr=0.05)


@pytest.mark.parametrize("n_microbatches", [2, 4])
def test_interleaved_matches_unpipelined(devices, n_microbatches):
    """interleave=2: pp*V=4 virtual stages round-robin over pp=2
    devices must reproduce the unpipelined loss and updates exactly
    (M must divide by pp — Megatron slot grouping)."""
    toks, tgts = tfm.sample_batch(CFG8, 2 * n_microbatches, 8,
                                  jax.random.PRNGKey(1))
    mesh1 = Mesh(np.array(jax.devices()[:1]).reshape(1, 1, 1),
                 ("dp", "sp", "tp"))
    params = tfm.init_params(CFG8, jax.random.PRNGKey(0))
    ref_step = tfm.make_train_step(CFG8, mesh1)
    t1, g1 = tfm.shard_batch(toks, tgts, mesh1)
    ref_params, ref_loss = ref_step(
        tfm.shard_params(params, CFG8, mesh1), t1, g1)

    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = Mesh(np.array(devices[:4]).reshape(2, 2), ("dp", "pp"))
    V = 2
    stacked = tfm.prepare_pipeline_params(params, mesh, interleave=V)
    step = tfm.make_pipelined_train_step(CFG8, mesh, n_microbatches,
                                         interleave=V)
    sh = NamedSharding(mesh, P("dp", None))
    t, g = jax.device_put(toks, sh), jax.device_put(tgts, sh)
    new_stacked, loss = step(stacked, t, g)
    assert float(loss) == pytest.approx(float(ref_loss), abs=1e-5)

    got = tfm.deinterleave_pipeline_params(
        jax.device_get(new_stacked), 2, V)
    want = tfm.stack_pipeline_params(jax.device_get(ref_params))
    for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5)


def test_interleave_order_roundtrip():
    stacked = tfm.stack_pipeline_params(
        tfm.init_params(CFG8, jax.random.PRNGKey(2)))
    inter = tfm.interleave_pipeline_params(stacked, 2, 2)
    back = tfm.deinterleave_pipeline_params(inter, 2, 2)
    for a, b in zip(jax.tree.leaves(stacked), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the permutation actually moves layers (device 0: stages 0,2 ->
    # layers [0,1] and [4,5])
    l0 = np.asarray(jax.tree.leaves(stacked["layers"])[0])
    li = np.asarray(jax.tree.leaves(inter["layers"])[0])
    np.testing.assert_array_equal(li[2], l0[4])


def test_interleaved_rejects_bad_layer_count(devices):
    mesh = Mesh(np.array(devices[:4]).reshape(2, 2), ("dp", "pp"))
    with pytest.raises(ValueError, match="divisible"):
        tfm.make_pipelined_train_step(CFG, mesh, 2, interleave=3)


def test_interleave_params_rejects_indivisible():
    stacked = tfm.stack_pipeline_params(
        tfm.init_params(dataclasses.replace(CFG8, n_layers=6),
                        jax.random.PRNGKey(0)))
    with pytest.raises(ValueError, match="divisible"):
        tfm.interleave_pipeline_params(stacked, 2, 2)
    with pytest.raises(ValueError, match="divisible"):
        tfm.deinterleave_pipeline_params(stacked, 2, 2)
