"""Causal task tracer (svc/tracing + svc/trace_export).

Contracts under test: the disabled path is a structural no-op (no
tracer, no hooks, one shared null span object); spans nest and record
causal parents; parents and flow arrows propagate across async_ /
.then() / when_all joins; the ring drops oldest at capacity; exported
Chrome-trace JSON always validates (matched B/E, resolving flows,
monotonic ts); counter samples interleave on the same timeline; and the
ContinuousServer emits the admit -> prefill / decode -> retire causal
chain end to end (the CI smoke).
"""

import json
import time

import jax
import pytest

import hpx_tpu as hpx
from hpx_tpu.futures import future as future_mod
from hpx_tpu.models import transformer as tfm
from hpx_tpu.models.serving import ContinuousServer
from hpx_tpu.runtime import threadpool
from hpx_tpu.svc import profiling, tracing
from hpx_tpu.svc.performance_counters import query_counter
from hpx_tpu.svc.trace_export import (
    load_chrome_trace,
    to_chrome_trace,
    validate_chrome_trace,
    write_chrome_trace,
)

# snapshot tuples: (ph, name, cat, ts, tid, id, parent, args)
PH, NAME, CAT, TS, TID, ID, PARENT, ARGS = range(8)

CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4, head_dim=8,
                            n_layers=2, d_ff=64)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.PRNGKey(0))


@pytest.fixture(autouse=True)
def _no_leaked_tracer():
    """Every test must leave the process untraced."""
    yield
    assert tracing.active_tracer() is None, "test leaked an active tracer"
    tracing.stop_tracing()          # defensive cleanup anyway


def spans_named(events, name):
    return [e for e in events if e[PH] == "B" and e[NAME] == name]


def _wait_for(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() >= deadline:
            return False
        time.sleep(0.001)
    return True


# ---------------------------------------------------------------------------
# disabled path: structurally zero work
# ---------------------------------------------------------------------------

class TestDisabled:
    def test_no_tracer_no_hooks(self):
        assert tracing.active_tracer() is None
        assert tracing.current_span_id() is None
        assert threadpool._trace_submit is None
        assert threadpool._trace_pending is None
        assert future_mod._trace_continuation is None

    def test_span_is_shared_null_object(self):
        # module-level span() off the fast path returns ONE immortal
        # no-op — no allocation, args never touched
        a = tracing.span("x", "user", heavy=object())
        b = tracing.span("y")
        assert a is b is tracing._NULL_SPAN
        with a:
            assert a.id is None

    def test_instant_is_noop(self):
        tracing.instant("nothing", "user", k=1)   # must not raise

    def test_hooks_detached_after_stop(self):
        with tracing.trace(sample_counters=False):
            assert threadpool._trace_submit is not None
            assert future_mod._trace_continuation is not None
        assert threadpool._trace_submit is None
        assert threadpool._trace_pending is None
        assert future_mod._trace_continuation is None

    def test_double_start_raises(self):
        with tracing.trace(sample_counters=False):
            with pytest.raises(RuntimeError):
                tracing.start_tracing()


# ---------------------------------------------------------------------------
# span recording + nesting
# ---------------------------------------------------------------------------

class TestSpans:
    def test_nesting_parents(self):
        with tracing.trace(sample_counters=False) as tr:
            with tracing.span("outer", "user", k=1) as outer:
                with tracing.span("inner") as inner:
                    assert tracing.current_span_id() == inner.id
                assert tracing.current_span_id() == outer.id
            assert tracing.current_span_id() is None
        ev = tr.snapshot()
        (ob,) = spans_named(ev, "outer")
        (ib,) = spans_named(ev, "inner")
        assert ob[PARENT] is None
        assert ib[PARENT] == ob[ID]
        assert ob[ARGS] == {"k": 1}
        ends = [e for e in ev if e[PH] == "E"]
        assert {e[ID] for e in ends} == {ob[ID], ib[ID]}

    def test_instant_parented(self):
        with tracing.trace(sample_counters=False) as tr:
            with tracing.span("phase") as sp:
                tracing.instant("tick", "user", n=3)
        (i,) = [e for e in tr.snapshot() if e[PH] == "i"]
        assert i[PARENT] == sp.id and i[ARGS] == {"n": 3}

    def test_module_span_is_real_when_active(self):
        with tracing.trace(sample_counters=False) as tr:
            s = tracing.span("live")
            assert s is not tracing._NULL_SPAN
            with s:
                pass
        assert spans_named(tr.snapshot(), "live")


# ---------------------------------------------------------------------------
# causal propagation across futures
# ---------------------------------------------------------------------------

class TestCausality:
    def test_async_task_parented_to_submit_site(self):
        with tracing.trace(sample_counters=False) as tr:
            with tracing.span("submit-site") as site:
                hpx.async_(lambda: 42).get(timeout=5.0)
            ev = tr.snapshot()
        tasks = [e for e in ev if e[PH] == "B" and e[CAT] == "task"]
        assert tasks, "pool task recorded no span"
        assert any(e[PARENT] == site.id for e in tasks)

    def test_async_flow_arrow_resolves(self):
        with tracing.trace(sample_counters=False) as tr:
            with tracing.span("root"):
                hpx.async_(lambda: 1).get(timeout=5.0)
            ev = tr.snapshot()
        s_ids = {e[ID] for e in ev if e[PH] == "s"}
        f_ids = {e[ID] for e in ev if e[PH] == "f"}
        assert s_ids and s_ids & f_ids, (s_ids, f_ids)

    def test_submit_outside_span_has_no_parent(self):
        with tracing.trace(sample_counters=False) as tr:
            hpx.async_(lambda: 1).get(timeout=5.0)
            ev = tr.snapshot()
        tasks = [e for e in ev if e[PH] == "B" and e[CAT] == "task"]
        assert tasks and all(e[PARENT] is None for e in tasks)

    def test_then_chain_parented_to_attach_site(self):
        with tracing.trace(sample_counters=False) as tr:
            with tracing.span("attach-site") as site:
                f = hpx.async_(lambda: 2)
                g = f.then(lambda fut: fut.get() * 3)
            assert g.get(timeout=5.0) == 6
            assert _wait_for(lambda: any(
                e[PH] == "B" and e[CAT] == "continuation"
                for e in tr.snapshot()))
            ev = tr.snapshot()
        conts = [e for e in ev
                 if e[PH] == "B" and e[CAT] == "continuation"]
        assert any(e[PARENT] == site.id for e in conts)
        assert all(e[NAME].startswith("then:") for e in conts)

    def test_when_all_join_parented(self):
        with tracing.trace(sample_counters=False) as tr:
            with tracing.span("join-site") as site:
                fs = [hpx.async_(lambda i=i: i) for i in range(3)]
                g = hpx.when_all(*fs).then(
                    lambda fut: sum(f.get() for f in fut.get()))
            assert g.get(timeout=5.0) == 3
            assert _wait_for(lambda: any(
                e[PH] == "B" and e[CAT] == "continuation"
                and e[PARENT] == site.id for e in tr.snapshot()))

    def test_tracer_stop_leaves_pending_continuations_runnable(self):
        # a continuation attached while tracing may run after stop()
        with tracing.trace(sample_counters=False):
            f = hpx.async_(lambda: time.sleep(0.05) or 5)
            g = f.then(lambda fut: fut.get() + 1)
        assert g.get(timeout=5.0) == 6


# ---------------------------------------------------------------------------
# ring buffer
# ---------------------------------------------------------------------------

class TestRing:
    def test_overflow_drops_oldest(self):
        tr = tracing.Tracer(capacity=8, sample_counters=False)
        for i in range(20):
            tr.instant(f"i{i}")
        ev = tr.snapshot()
        assert len(ev) == 8
        assert tr.dropped == 12
        assert [e[NAME] for e in ev] == [f"i{i}" for i in range(12, 20)]

    def test_capacity_floor(self):
        with pytest.raises(ValueError):
            tracing.Tracer(capacity=1)


# ---------------------------------------------------------------------------
# export schema
# ---------------------------------------------------------------------------

class TestExport:
    def test_artifact_validates_and_loads(self, tmp_path):
        path = str(tmp_path / "trace.json")
        with tracing.trace(sample_counters=False) as tr:
            with tracing.span("work", "user", step=1):
                hpx.async_(lambda: 1).get(timeout=5.0)
                tracing.instant("mark")
            tr.counter("/custom/depth", 2.0)
        doc = tr.export(path)
        assert validate_chrome_trace(doc) == []
        loaded = load_chrome_trace(path)
        assert loaded == json.loads(json.dumps(doc))
        names = {e["name"] for e in loaded["traceEvents"]}
        assert {"process_name", "work", "mark", "/custom/depth"} <= names
        assert loaded["otherData"]["format"] == "hpx_tpu.svc.tracing"

    def test_open_spans_closed_at_export(self):
        tr = tracing.Tracer(sample_counters=False)
        outer = tr._begin("outer", "user", None)
        tr._begin("inner", "user", None)
        doc = to_chrome_trace(tr.snapshot(), tr.thread_names(), tr.t0,
                              tr.dropped)
        assert validate_chrome_trace(doc) == []
        ends = [e for e in doc["traceEvents"] if e["ph"] == "E"]
        # innermost closes first so the synthetic E's nest correctly
        assert [e["name"] for e in ends] == ["inner", "outer"]
        del outer

    def test_orphan_halves_are_dropped(self):
        # an E whose B was evicted and a dangling s must not survive
        tr = tracing.Tracer(sample_counters=False)
        tr._record(("E", "ghost", "task", tr.t0 + 1.0, 7, 99, None,
                    None))
        tr._record(("s", "queued", "flow", tr.t0 + 2.0, 7, 42, None,
                    None))
        doc = to_chrome_trace(tr.snapshot(), {}, tr.t0, tr.dropped)
        assert validate_chrome_trace(doc) == []
        assert [e for e in doc["traceEvents"] if e["ph"] != "M"] == []

    def test_thread_metadata_rows(self):
        with tracing.trace(sample_counters=False) as tr:
            with tracing.span("here"):
                pass
        doc = to_chrome_trace(tr.snapshot(), tr.thread_names(), tr.t0)
        rows = [e for e in doc["traceEvents"]
                if e["ph"] == "M" and e["name"] == "thread_name"]
        assert rows and all(e["args"]["name"] for e in rows)

    def test_write_is_atomic(self, tmp_path):
        path = tmp_path / "out.json"
        with tracing.trace(sample_counters=False) as tr:
            with tracing.span("x"):
                pass
        write_chrome_trace(str(path), tr)
        assert path.exists() and not (tmp_path / "out.json.tmp").exists()

    def test_validator_catches_breakage(self):
        bad = {"traceEvents": [
            {"ph": "B", "pid": 1, "tid": 1, "ts": 2.0, "name": "a",
             "cat": "u"},
            {"ph": "E", "pid": 1, "tid": 1, "ts": 1.0, "name": "a"},
            {"ph": "s", "pid": 1, "tid": 1, "ts": 3.0, "name": "q",
             "cat": "flow", "id": 9},
        ]}
        problems = validate_chrome_trace(bad)
        assert any("not monotonically ordered" in p for p in problems)
        assert any("flow id 9" in p for p in problems)


# ---------------------------------------------------------------------------
# counter sampling
# ---------------------------------------------------------------------------

class TestCounters:
    def test_samples_interleave(self):
        with tracing.trace(counter_interval=0.01,
                           counter_patterns=["/runtime*"]) as tr:
            with tracing.span("while-sampling"):
                time.sleep(0.05)
        # stop() takes one final sample, so >=1 even on a loaded host
        cs = [e for e in tr.snapshot() if e[PH] == "C"]
        assert cs and all(e[NAME].startswith("/runtime") for e in cs)
        assert all(isinstance(e[ARGS], float) for e in cs)

    def test_config_defaults_flow_into_tracer(self):
        from hpx_tpu.core.config import runtime_config
        rc = runtime_config()
        old = rc.get("hpx.trace.buffer_events")
        rc.set("hpx.trace.buffer_events", "128")
        try:
            tr = tracing.start_tracing(sample_counters=False)
            assert tr.capacity == 128
            assert tr.counter_patterns == ["/serving*", "/cache*",
                                           "/threads*", "/programs*"]
        finally:
            tracing.stop_tracing()
            rc.set("hpx.trace.buffer_events", old)

    def test_start_if_configured_respects_gate(self):
        from hpx_tpu.core.config import runtime_config
        rc = runtime_config()
        assert tracing.start_if_configured() is None   # off by default
        rc.set("hpx.trace.enabled", "1")
        try:
            tr = tracing.start_if_configured()
            assert tr is not None and tracing.active_tracer() is tr
            assert tracing.start_if_configured() is tr  # idempotent
        finally:
            rc.set("hpx.trace.enabled", "0")
            tracing.stop_tracing()


# ---------------------------------------------------------------------------
# profiling: swallowed observer exceptions are counted
# ---------------------------------------------------------------------------

class TestDroppedCallbacks:
    def test_broken_hook_is_counted_not_fatal(self):
        class Bad:
            def on_stop(self, fn, seconds):
                raise RuntimeError("boom")

        profiling.reset_dropped_callbacks()
        bad = Bad()
        profiling.register_external_timer(bad)
        try:
            assert hpx.async_(lambda: 7).get(timeout=5.0) == 7
            assert _wait_for(lambda: profiling.dropped_callbacks() >= 1)
        finally:
            profiling.unregister_external_timer(bad)
        cv = query_counter("/runtime{locality#0/total}/count/"
                           "dropped-observer-callbacks")
        assert cv.value >= 1
        profiling.reset_dropped_callbacks()
        assert profiling.dropped_callbacks() == 0


# ---------------------------------------------------------------------------
# CI smoke: a traced ContinuousServer run emits the causal chain
# ---------------------------------------------------------------------------

class TestServingSmoke:
    def test_admit_prefill_decode_retire_chain(self, params):
        with tracing.trace(sample_counters=False) as tr:
            srv = ContinuousServer(params, CFG, slots=2, smax=32)
            # prefill yields token 1, so max_new=3 -> two decode steps
            a = srv.submit([3, 1, 4], max_new=3)
            b = srv.submit([2, 7], max_new=3)
            out = srv.run()
            ev = tr.snapshot()
        assert set(out) == {a, b}

        admits = spans_named(ev, "serving.admit")
        prefills = spans_named(ev, "serving.prefill")
        decodes = spans_named(ev, "serving.decode")
        retires = spans_named(ev, "serving.retire")
        assert len(admits) == 2 and len(prefills) == 2
        assert len(decodes) >= 2          # two decode steps minimum
        assert len(retires) == 2

        # causal edges: prefill nests under its admit, retire under a
        # decode step
        admit_ids = {e[ID] for e in admits}
        decode_ids = {e[ID] for e in decodes}
        assert all(e[PARENT] in admit_ids for e in prefills)
        assert all(e[PARENT] in decode_ids for e in retires)
        # rid args connect admit to its retire
        rids = {e[ARGS]["rid"] for e in admits}
        assert rids == {a, b}
        assert {e[ARGS]["rid"] for e in retires} == rids

        # the whole artifact still validates
        doc = to_chrome_trace(ev, tr.thread_names(), tr.t0, tr.dropped)
        assert validate_chrome_trace(doc) == []

    def test_paged_serving_records_cache_instants(self, params):
        with tracing.trace(sample_counters=False) as tr:
            srv = ContinuousServer(params, CFG, slots=1, smax=48,
                                   paged=True)
            shared = list(range(1, 17))    # one full 16-token block
            r1 = srv.submit(shared + [21, 22], max_new=2)
            r2 = srv.submit(shared + [31, 32], max_new=2)
            out = srv.run()
            ev = tr.snapshot()
        assert set(out) == {r1, r2}
        matches = [e for e in ev
                   if e[PH] == "i" and e[NAME] == "cache.match"]
        assert len(matches) == 2
        # slots=1 serializes the requests, so the second admission
        # matches the prefix the first one published at retire
        assert matches[-1][ARGS]["matched"] >= 16

    def test_untraced_serving_output_identical(self, params):
        srv = ContinuousServer(params, CFG, slots=2, smax=32)
        r = srv.submit([3, 1, 4], max_new=2)
        base = srv.run()[r]
        with tracing.trace(sample_counters=False):
            srv2 = ContinuousServer(params, CFG, slots=2, smax=32)
            r2 = srv2.submit([3, 1, 4], max_new=2)
            traced = srv2.run()[r2]
        assert traced == base
