"""Parcelport security: auth handshake, bind policy, stale-.so guard,
and backend gating — regression tests for the round-2/3 advisor
findings (VERDICT.md weak #5).

The core property under test: bytes from an unauthenticated connection
must NEVER reach pickle. A raw TCP client sends a pickled payload whose
deserialization would have an observable side effect; with a secret
configured it must be dropped, while a client that completes the HMAC
handshake (dist/auth.py) bootstraps normally.
"""

import os
import pickle
import socket
import struct
import threading
import time

import pytest

from hpx_tpu.dist import auth

SECRET = "test-secret-1234"


class TestAuthFrames:
    def test_roundtrip(self):
        nonce = os.urandom(auth.NONCE_LEN)
        assert auth.parse(auth.hello_frame(nonce)) == (auth.T_HELLO,
                                                       nonce)
        m = auth.mac(SECRET, nonce, b"srv")
        t, got_m, got_n = auth.parse(auth.reply_frame(m, nonce))
        assert (t, got_m, got_n) == (auth.T_REPLY, m, nonce)
        assert auth.parse(auth.final_frame(m)) == (auth.T_FINAL, m)

    @pytest.mark.parametrize("junk", [
        b"", b"HPX", b"HPXA", b"HPXA\x07" + b"x" * 16,
        b"HPXA\x01short", b"HPXA\x02" + b"x" * 10,
        b"\x80\x04pickle-looking-bytes", b"HPXB\x01" + b"x" * 16,
    ])
    def test_malformed_dropped(self, junk):
        assert auth.parse(junk) is None

    def test_wrong_secret_fails_verify(self):
        nonce = os.urandom(auth.NONCE_LEN)
        m = auth.mac("other-secret", nonce, b"srv")
        assert not auth.verify(m, SECRET, nonce, b"srv")
        assert auth.verify(auth.mac(SECRET, nonce, b"srv"),
                           SECRET, nonce, b"srv")

    def test_role_separation(self):
        """A reflected srv proof must not pass as a cli proof."""
        nonce = os.urandom(auth.NONCE_LEN)
        assert not auth.verify(auth.mac(SECRET, nonce, b"srv"),
                               SECRET, nonce, b"cli")


class TestStaleSoGuard:
    def test_missing_symbol_raises(self):
        from hpx_tpu.native.loader import _bind_net

        class FakeLib:           # no hpxrt_net_* symbols at all
            pass

        with pytest.raises(RuntimeError, match="stale"):
            _bind_net(FakeLib())


class TestBackendGates:
    """Mosaic-only kernels must not be dispatched on a GPU backend
    (advisor r2: `not in ('cpu',)` misrouted rocm/cuda into pallas)."""

    def test_stencil_gpu_takes_xla_path(self, monkeypatch):
        import jax
        import jax.numpy as jnp
        from hpx_tpu.ops import stencil
        monkeypatch.setattr(jax, "default_backend", lambda: "gpu")
        u = jnp.arange(256, dtype=jnp.float32)
        got = stencil.heat_step_best(u, jnp.float32(0.25))
        want = stencil.heat_step(u, jnp.float32(0.25))
        import numpy as np
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))
        got2 = stencil.multistep(u, jnp.float32(0.25), 3)
        want2 = stencil.xla_multistep(u, jnp.float32(0.25), 3)
        np.testing.assert_allclose(np.asarray(got2), np.asarray(want2),
                                   rtol=1e-6)

    def test_flash_gpu_interprets(self, monkeypatch):
        import jax
        import jax.numpy as jnp
        import numpy as np
        import hpx_tpu.ops.attention_pallas as ap
        monkeypatch.setattr(jax, "default_backend", lambda: "gpu")
        rng = np.random.default_rng(0)
        q, k, v = (jnp.asarray(rng.standard_normal((1, 16, 2, 16),
                                                   np.float32))
                   for _ in range(3))
        out = ap.flash_attention(q, k, v, True, block_q=8, block_k=8)
        assert out.shape == q.shape    # interpret path, no Mosaic crash


class TestMultiNodePolicy:
    def test_multinode_without_secret_raises(self):
        from hpx_tpu.core.config import Configuration
        from hpx_tpu.core.errors import HpxError
        from hpx_tpu.dist.runtime import Runtime
        cfg = Configuration(overrides={
            "hpx.localities": "2", "hpx.locality": "0",
            "hpx.parcel.address": "203.0.113.7",   # not loopback
            "hpx.parcel.port": "0",
        })
        with pytest.raises(HpxError, match="secret"):
            Runtime(cfg)

    def test_multinode_allow_insecure_optout(self):
        """The explicit opt-out must get PAST the secret check (it then
        fails later trying to bind the non-local address — proving the
        policy gate, not the transport, was the decision point)."""
        from hpx_tpu.core.config import Configuration
        from hpx_tpu.dist.runtime import Runtime
        cfg = Configuration(overrides={
            "hpx.localities": "2", "hpx.locality": "0",
            "hpx.parcel.address": "203.0.113.7",
            "hpx.parcel.port": "0",
            "hpx.parcel.allow_insecure": "1",
        })
        with pytest.raises(OSError, match="203.0.113.7"):
            Runtime(cfg)


def _frame(payload: bytes) -> bytes:
    return struct.pack("<I", len(payload)) + payload


def _read_frame(sock: socket.socket, timeout: float = 10.0) -> bytes:
    sock.settimeout(timeout)
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            raise EOFError
        hdr += chunk
    (n,) = struct.unpack("<I", hdr)
    body = b""
    while len(body) < n:
        chunk = sock.recv(n - len(body))
        if not chunk:
            raise EOFError
        body += chunk
    return body


class _Bomb:
    """Pickled payload with an observable deserialization side effect."""

    def __init__(self, path):
        self.path = path

    def __reduce__(self):
        return (open, (self.path, "w"))


class TestHandshakeEndToEnd:
    """Console runtime with a secret; a raw TCP client plays attacker
    then legitimate worker against the REAL endpoint + runtime."""

    @pytest.fixture()
    def console(self, tmp_path):
        from hpx_tpu.core.config import Configuration
        from hpx_tpu.dist.runtime import Runtime
        port = _free_port()
        cfg = Configuration(overrides={
            "hpx.localities": "2", "hpx.locality": "0",
            "hpx.parcel.address": "127.0.0.1",
            "hpx.parcel.port": str(port),
            "hpx.parcel.secret": SECRET,
            "hpx.startup_timeout": "20",
        })
        holder = {}

        def boot():
            holder["rt"] = Runtime(cfg)

        t = threading.Thread(target=boot, daemon=True)
        t.start()
        # wait for the listener
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                s = socket.create_connection(("127.0.0.1", port), 0.2)
                s.close()
                break
            except OSError:
                time.sleep(0.05)
        yield port, holder, t
        rt = holder.get("rt")
        if rt is not None:
            rt._stopped = True
            rt._endpoint.close()

    def test_unauth_pickle_dropped_then_handshake_boots(
            self, console, tmp_path):
        from hpx_tpu.dist.plugins import decode_payload, encode_payload
        from hpx_tpu.dist.serialization import deserialize, serialize

        def wire(msg):           # what _send_raw puts on the socket
            return encode_payload(serialize(msg), None)

        port, holder, boot_thread = console
        bomb_path = str(tmp_path / "pwned")

        # --- attacker: raw pickled parcel, no handshake ---------------
        atk = socket.create_connection(("127.0.0.1", port), 5)
        atk.sendall(_frame(b"\x00" + pickle.dumps(_Bomb(bomb_path))))
        # also a malformed auth frame for good measure
        atk.sendall(_frame(b"HPXA\x01short"))
        time.sleep(0.7)
        assert not os.path.exists(bomb_path), \
            "unauthenticated pickle was deserialized"
        assert holder.get("rt") is None, "bootstrap should still wait"
        atk.close()

        # --- wrong secret: REPLY comes, our FINAL check fails ---------
        bad = socket.create_connection(("127.0.0.1", port), 5)
        nonce = os.urandom(auth.NONCE_LEN)
        bad.sendall(_frame(auth.hello_frame(nonce)))
        body = _read_frame(bad)
        t, mac_srv, nonce_srv = auth.parse(body)
        assert t == auth.T_REPLY
        assert not auth.verify(mac_srv, "wrong-secret", nonce, b"srv")
        # (a real client would abort here; the server has not authed us:
        # a pickled hello must still be ignored)
        bad.sendall(_frame(wire(("hello", 1, "127.0.0.1", 1))))
        time.sleep(0.5)
        assert holder.get("rt") is None
        bad.close()

        # --- correct handshake, then HELLO -> TABLE -------------------
        cli = socket.create_connection(("127.0.0.1", port), 5)
        nonce = os.urandom(auth.NONCE_LEN)
        cli.sendall(_frame(auth.hello_frame(nonce)))
        t, mac_srv, nonce_srv = auth.parse(_read_frame(cli))
        assert t == auth.T_REPLY
        assert auth.verify(mac_srv, SECRET, nonce, b"srv")
        cli.sendall(_frame(auth.final_frame(
            auth.mac(SECRET, nonce_srv, b"cli"))))
        my_port = _free_port()
        cli.sendall(_frame(wire(("hello", 1, "127.0.0.1", my_port))))
        table = deserialize(decode_payload(_read_frame(cli)))
        assert table[0] == "table"
        assert set(table[1]) == {0, 1}
        boot_thread.join(10)
        assert holder.get("rt") is not None, "console failed to boot"
        cli.close()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p
