"""hpxlint tier-3 (dataflow) tests: the def-use core, the four rules
HPX019–HPX022 (positive + negative fixture per rule), the CLI fast
paths (``--changed``, ``--only``), the decorated-function suppression
reach, baseline ordering, the per-rule JSON counts, and the CI gate
script — including its perf budget (one parse per file, <15s for the
full three-tier sweep).
"""

import ast
import json
import os
import subprocess
import sys
import time

from hpx_tpu.analysis import all_rules, lint_sources, lint_paths
from hpx_tpu.analysis.cli import main as cli_main
from hpx_tpu.analysis.dataflow import (
    DataflowIndex,
    DefUse,
    classify_origin,
    provably_host,
)
from hpx_tpu.analysis.engine import FileContext, parse_count
from hpx_tpu.analysis.project import ProjectIndex

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(sources, select):
    return lint_sources(sources, rules=all_rules(select)).findings


def rules_of(fs):
    return [f.rule for f in fs]


def _du(src):
    """DefUse over the first function in `src`."""
    fn = ast.parse(src).body[0]
    return DefUse(fn)


def _uses_of(du, name):
    return [u for u in du.uses if u.name == name]


# ---------------------------------------------------------------------------
# Def-use core: forks, loops, try/finally, augmented assignment
# ---------------------------------------------------------------------------

def test_defuse_if_fork_merges_both_arms():
    du = _du(
        "def f(c):\n"
        "    if c:\n"
        "        x = 1\n"
        "    else:\n"
        "        x = 2\n"
        "    return x\n")
    (use,) = _uses_of(du, "x")
    assert sorted(d.node.lineno for d in use.defs) == [3, 5]


def test_defuse_if_without_else_keeps_prior_def():
    du = _du(
        "def f(c):\n"
        "    x = 1\n"
        "    if c:\n"
        "        x = 2\n"
        "    return x\n")
    (use,) = _uses_of(du, "x")
    assert sorted(d.node.lineno for d in use.defs) == [2, 4]


def test_defuse_loop_back_edge_reaches_first_iteration():
    du = _du(
        "def f(xs):\n"
        "    y = 0\n"
        "    for v in xs:\n"
        "        z = y\n"
        "        y = 1\n"
        "    return y\n")
    # the in-loop read must see BOTH the pre-loop def and the
    # back-edge def from the previous iteration
    in_loop = [u for u in _uses_of(du, "y") if u.node.lineno == 4]
    assert in_loop
    lines = set()
    for u in in_loop:
        lines |= {d.node.lineno for d in u.defs}
    assert lines == {2, 5}
    # and the post-loop read sees the zero-iteration path too
    (after,) = [u for u in _uses_of(du, "y") if u.node.lineno == 6]
    assert {d.node.lineno for d in after.defs} == {2, 5}


def test_defuse_try_handler_sees_every_body_state():
    du = _du(
        "def f():\n"
        "    x = 1\n"
        "    try:\n"
        "        x = 2\n"
        "        risky()\n"
        "        x = 3\n"
        "    except ValueError:\n"
        "        h = x\n"
        "    return x\n")
    # the handler can run after any prefix of the body: all three
    # definitions reach the read at line 8
    (handler_use,) = [u for u in _uses_of(du, "x")
                      if u.node.lineno == 8]
    assert {d.node.lineno for d in handler_use.defs} == {2, 4, 6}


def test_defuse_finally_sees_normal_and_escaping_states():
    du = _du(
        "def f():\n"
        "    x = 1\n"
        "    try:\n"
        "        x = 2\n"
        "    finally:\n"
        "        g = x\n"
        "    return x\n")
    (fin_use,) = [u for u in _uses_of(du, "x") if u.node.lineno == 6]
    assert {d.node.lineno for d in fin_use.defs} == {2, 4}


def test_defuse_augmented_assignment_reads_then_rebinds():
    du = _du(
        "def f():\n"
        "    x = 1\n"
        "    x += 2\n"
        "    return x\n")
    aug_use, ret_use = _uses_of(du, "x")
    assert {d.node.lineno for d in aug_use.defs} == {2}
    (ret_def,) = ret_use.defs
    assert ret_def.kind == "aug" and ret_def.node.lineno == 3


def test_defuse_return_kills_fallthrough():
    du = _du(
        "def f(c):\n"
        "    if c:\n"
        "        x = 1\n"
        "        return x\n"
        "    x = 2\n"
        "    return x\n")
    last = [u for u in _uses_of(du, "x") if u.node.lineno == 6]
    (use,) = last
    # the early-returning arm cannot fall through to line 6
    assert {d.node.lineno for d in use.defs} == {5}


# ---------------------------------------------------------------------------
# HPX019 — unguarded shared state (inferred guarded-by)
# ---------------------------------------------------------------------------

HPX019_BAD = """\
from hpx_tpu.synchronization import Mutex

class Stats:
    def __init__(self):
        self._lock = Mutex()
        self.count = 0

    def bump(self):
        with self._lock:
            self.count += 1

    def bump2(self):
        with self._lock:
            self.count += 2

    def sloppy(self):
        self.count += 3
"""

HPX019_GOOD = HPX019_BAD.replace(
    "    def sloppy(self):\n        self.count += 3\n",
    "    def sloppy(self):\n"
    "        with self._lock:\n"
    "            self.count += 3\n")


def test_hpx019_bare_minority_write_fires():
    fs = _lint({"hpx_tpu/svc/fix19.py": HPX019_BAD}, ["HPX019"])
    assert rules_of(fs) == ["HPX019"]
    assert "self.count is mutated in Stats.sloppy()" in fs[0].message
    assert "2 of 3 mutation sites" in fs[0].message


def test_hpx019_silent_when_every_site_holds_the_lock():
    assert _lint({"hpx_tpu/svc/fix19.py": HPX019_GOOD},
                 ["HPX019"]) == []


def test_hpx019_no_majority_means_no_contract():
    # 1 held / 1 bare: no strict majority, nothing inferable
    src = HPX019_BAD.replace(
        "    def bump2(self):\n"
        "        with self._lock:\n"
        "            self.count += 2\n\n", "")
    assert _lint({"hpx_tpu/svc/fix19.py": src}, ["HPX019"]) == []


def test_hpx019_init_only_and_single_method_attrs_exempt():
    src = """\
from hpx_tpu.synchronization import Mutex

class Worker:
    def __init__(self):
        self._lock = Mutex()
        self.name = "w"          # __init__-only: exempt

    def step(self):
        self._scratch = 0        # single-method scratch: exempt
        with self._lock:
            self._scratch += 1
"""
    assert _lint({"hpx_tpu/svc/fix19.py": src}, ["HPX019"]) == []


def test_hpx019_scoped_to_shared_state_layers():
    # same race pattern outside svc/models/cache/dist: out of scope
    assert _lint({"hpx_tpu/algo/fix19.py": HPX019_BAD},
                 ["HPX019"]) == []


def test_hpx019_caller_held_lock_counts_via_call_graph():
    # the bare-looking helper is only ever called with the lock held:
    # its effective held-set comes from the one-level caller summary
    src = """\
from hpx_tpu.synchronization import Mutex

class Stats:
    def __init__(self):
        self._lock = Mutex()
        self.count = 0

    def bump(self):
        with self._lock:
            self._bump_locked()

    def bump2(self):
        with self._lock:
            self._bump_locked()

    def _bump_locked(self):
        self.count += 1

    def other(self):
        with self._lock:
            self.count += 5
"""
    assert _lint({"hpx_tpu/svc/fix19.py": src}, ["HPX019"]) == []


# ---------------------------------------------------------------------------
# HPX020 — donation use-after-donate
# ---------------------------------------------------------------------------

HPX020_BAD = """\
import jax

def step(fn, pool, tok):
    prog = jax.jit(fn, donate_argnums=(0,))
    out = prog(pool, tok)
    return pool + out
"""

HPX020_GOOD = """\
import jax

def step(fn, pool, tok):
    prog = jax.jit(fn, donate_argnums=(0,))
    pool = prog(pool, tok)
    return pool
"""


def test_hpx020_use_after_donate_fires():
    fs = _lint({"hpx_tpu/models/fix20.py": HPX020_BAD}, ["HPX020"])
    assert rules_of(fs) == ["HPX020"]
    assert "`pool` is used after being donated" in fs[0].message
    assert fs[0].line == 6


def test_hpx020_rebinding_the_result_is_silent():
    assert _lint({"hpx_tpu/models/fix20.py": HPX020_GOOD},
                 ["HPX020"]) == []


def test_hpx020_direct_jit_call_and_loop_rebind():
    bad = """\
import jax

def run(fn, state, xs):
    out = jax.jit(fn, donate_argnums=(0,))(state, xs)
    state.block_until_ready()
    return out
"""
    fs = _lint({"hpx_tpu/models/fix20.py": bad}, ["HPX020"])
    assert rules_of(fs) == ["HPX020"]
    good = """\
import jax

def run(fn, state, xs):
    prog = jax.jit(fn, donate_argnums=(0,))
    for x in xs:
        state = prog(state, x)
    return state
"""
    assert _lint({"hpx_tpu/models/fix20.py": good}, ["HPX020"]) == []


def test_hpx020_non_donated_positions_are_silent():
    src = """\
import jax

def step(fn, pool, tok):
    prog = jax.jit(fn, donate_argnums=(0,))
    out = prog(pool, tok)
    return tok + out
"""
    assert _lint({"hpx_tpu/models/fix20.py": src}, ["HPX020"]) == []


# ---------------------------------------------------------------------------
# HPX021 — mesh-axis consistency inside shard_map bodies
# ---------------------------------------------------------------------------

HPX021_BAD = """\
import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

def build(devs):
    mesh = Mesh(devs, ("dp", "sp"))

    def body(x):
        return jax.lax.psum(x, "tp")

    return shard_map(body, mesh=mesh,
                     in_specs=P("dp"), out_specs=P("dp"))
"""

HPX021_GOOD = HPX021_BAD.replace('jax.lax.psum(x, "tp")',
                                 'jax.lax.psum(x, "dp")')


def test_hpx021_undeclared_axis_fires():
    fs = _lint({"hpx_tpu/models/fix21.py": HPX021_BAD}, ["HPX021"])
    assert rules_of(fs) == ["HPX021"]
    assert "psum() over axis 'tp'" in fs[0].message
    assert "(dp, sp)" in fs[0].message


def test_hpx021_declared_axis_is_silent():
    assert _lint({"hpx_tpu/models/fix21.py": HPX021_GOOD},
                 ["HPX021"]) == []


def test_hpx021_specs_fallback_when_mesh_is_opaque():
    src = """\
import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

def build(mesh):
    def body(x):
        return jax.lax.psum(x, "tp")
    return shard_map(body, mesh=mesh,
                     in_specs=P("dp"), out_specs=P("dp"))
"""
    fs = _lint({"hpx_tpu/models/fix21.py": src}, ["HPX021"])
    assert rules_of(fs) == ["HPX021"]
    assert "(dp)" in fs[0].message


def test_hpx021_opaque_mesh_and_specs_skip_not_guess():
    # mesh is a parameter and one spec fragment is a variable: the
    # declared set cannot be resolved, so the site is skipped even
    # though "tp" looks suspicious
    src = """\
import jax
from jax.experimental.shard_map import shard_map

def build(mesh, pspecs):
    def body(x):
        return jax.lax.psum(x, "tp")
    return shard_map(body, mesh=mesh,
                     in_specs=pspecs, out_specs=pspecs)
"""
    assert _lint({"hpx_tpu/models/fix21.py": src}, ["HPX021"]) == []


def test_hpx021_partition_spec_fragment_in_body():
    src = """\
import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

def build(devs):
    mesh = Mesh(devs, ("dp",))

    def body(x):
        s = P("tp")
        return jax.lax.psum(x, "dp"), s

    return shard_map(body, mesh=mesh,
                     in_specs=P("dp"), out_specs=P("dp"))
"""
    fs = _lint({"hpx_tpu/models/fix21.py": src}, ["HPX021"])
    assert rules_of(fs) == ["HPX021"]
    assert "PartitionSpec axis 'tp'" in fs[0].message


# The expert-parallel decode shape (models/moe.moe_ffn_decode): a
# same-file helper carrying axis_index / tiled all_to_all / psum over
# the expert axis, called from the shard_map body.  The helper-chasing
# path must CHECK these collectives, not skip them.
HPX021_EP = """\
import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

def _moe(x):
    i = jax.lax.axis_index("ep")
    x = jax.lax.all_to_all(x, "ep", split_axis=0, concat_axis=2,
                           tiled=True)
    return jax.lax.psum(x, "ep") + i

def build(devs):
    mesh = Mesh(devs, ("dp", "ep"))

    def body(x):
        return _moe(x)

    return shard_map(body, mesh=mesh,
                     in_specs=P("dp"), out_specs=P("dp"))
"""


def test_hpx021_ep_axis_declared_is_silent():
    assert _lint({"hpx_tpu/models/fix21.py": HPX021_EP},
                 ["HPX021"]) == []


def test_hpx021_ep_axis_undeclared_fires_in_chased_helper():
    # the same body on a mesh WITHOUT "ep" (the dp/tp serving default
    # before an ep axis is declared): every "ep" collective in the
    # chased helper flags, including the tiled all_to_all exchange
    src = HPX021_EP.replace('("dp", "ep")', '("dp", "tp")')
    fs = _lint({"hpx_tpu/models/fix21.py": src}, ["HPX021"])
    assert rules_of(fs) == ["HPX021"] * 3
    msgs = "\n".join(f.message for f in fs)
    assert "axis_index() over axis 'ep'" in msgs
    assert "all_to_all() over axis 'ep'" in msgs
    assert "psum() over axis 'ep'" in msgs
    assert "(dp, tp)" in msgs


def test_hpx021_registry_covers_moe_decode_collectives():
    # pin: every collective moe_ffn / moe_ffn_decode use inside
    # shard_map bodies stays in the axis-arg registry with the right
    # position, so their axis literals are checked rather than skipped
    from hpx_tpu.analysis.dataflow import _COLLECTIVE_AXIS_ARG
    assert _COLLECTIVE_AXIS_ARG["all_to_all"] == 1
    assert _COLLECTIVE_AXIS_ARG["axis_index"] == 0
    assert _COLLECTIVE_AXIS_ARG["psum"] == 1
    assert _COLLECTIVE_AXIS_ARG["pmean"] == 1


# ---------------------------------------------------------------------------
# HPX022 — flow-sensitive host sync
# ---------------------------------------------------------------------------

HPX022_BAD = """\
import jax.numpy as jnp

def mean_loss(x):
    s = jnp.sum(x)
    return float(s)
"""

HPX022_GOOD = """\
import numpy as np

def host_mean(x):
    n = len(x)
    m = np.mean(x)
    return float(n) + float(m)
"""


def test_hpx022_device_origin_sync_fires():
    fs = _lint({"hpx_tpu/exec/fix22.py": HPX022_BAD}, ["HPX022"])
    assert rules_of(fs) == ["HPX022"]
    assert "float(s)" in fs[0].message


def test_hpx022_host_origin_is_silent():
    assert _lint({"hpx_tpu/exec/fix22.py": HPX022_GOOD},
                 ["HPX022"]) == []


def test_hpx022_outside_hot_subpaths_is_silent():
    assert _lint({"hpx_tpu/svc/fix22.py": HPX022_BAD},
                 ["HPX022"]) == []


def test_hpx022_disagreeing_branches_stay_silent():
    # one branch host, one device: the reaching definitions disagree,
    # so the may-analysis refuses to speak (no false positive on the
    # host-only execution)
    src = """\
import jax.numpy as jnp

def maybe(x, flag):
    if flag:
        s = jnp.sum(x)
    else:
        s = 0.0
    return float(s)
"""
    assert _lint({"hpx_tpu/exec/fix22.py": src}, ["HPX022"]) == []


def test_hpx022_arithmetic_promotion_flags():
    # device + host scalar arithmetic yields a jax.Array — the BinOp
    # join promotes to device and the sink is flagged
    src = """\
import jax.numpy as jnp

def norm(x):
    s = jnp.sum(x) + 1.0
    return float(s)
"""
    fs = _lint({"hpx_tpu/exec/fix22.py": src}, ["HPX022"])
    assert rules_of(fs) == ["HPX022"]


def test_hpx022_unknown_origin_stays_silent():
    # a def-use chain that bottoms out in an unknown call must NOT be
    # guessed device — may-analysis only speaks with proof
    src = """\
import jax.numpy as jnp

def route(handle):
    s = handle.pull()
    return float(s)
"""
    assert _lint({"hpx_tpu/exec/fix22.py": src}, ["HPX022"]) == []


def test_hpx002_prover_drops_host_subscript_false_positive():
    # the historical HPX002 token-match false positive: int() over a
    # numpy (host) subscript — provably host, no finding, no
    # suppression comment needed anymore
    src = """\
import numpy as np

def pick(xs):
    idx = np.flatnonzero(xs)
    return int(idx[0])
"""
    assert _lint({"hpx_tpu/algo/fix02.py": src}, ["HPX002"]) == []


def test_hpx002_keeps_unproven_subscript_sync():
    src = """\
def pick(dev):
    out = dev.compute()
    return int(out[0])
"""
    fs = _lint({"hpx_tpu/algo/fix02.py": src}, ["HPX002"])
    assert rules_of(fs) == ["HPX002"]


def test_classify_origin_api():
    src = ("import jax.numpy as jnp\n"
           "import numpy as np\n"
           "def f(x):\n"
           "    a = jnp.dot(x, x)\n"
           "    b = np.arange(4)\n"
           "    c = x.shape[0]\n"
           "    return a, b, c\n")
    ctx = FileContext(src, "hpx_tpu/exec/fix.py")
    fn = ctx.tree.body[2]
    du = DefUse(fn)
    ret = fn.body[-1].value
    a, b, c = ret.elts
    assert classify_origin(a, du, ctx) == "device"
    assert classify_origin(b, du, ctx) == "host"
    assert provably_host(c, ctx)


# ---------------------------------------------------------------------------
# Suppression reach for decorated functions
# ---------------------------------------------------------------------------

HPX017_DECORATED = """\
import jax

@jax.jit  # hpxlint: disable=HPX017 — fixture: decorator-line directive
def tiny_kernel(x):
    return x + 1
"""


def test_suppression_on_decorator_line_reaches_def_finding():
    res = lint_sources({"hpx_tpu/models/fixsup.py": HPX017_DECORATED},
                       rules=all_rules(["HPX017"]))
    assert res.findings == []
    assert res.suppressed == 1
    assert res.suppressed_by_rule == {"HPX017": 1}


def test_decorated_finding_fires_without_directive():
    src = HPX017_DECORATED.replace(
        "  # hpxlint: disable=HPX017 — fixture: decorator-line "
        "directive", "")
    res = lint_sources({"hpx_tpu/models/fixsup.py": src},
                       rules=all_rules(["HPX017"]))
    assert rules_of(res.findings) == ["HPX017"]


def test_directive_on_decorator_does_not_blanket_body():
    src = """\
import jax

@jax.jit  # hpxlint: disable=HPX017 — fixture
def tiny_kernel(x):
    y = jax.jit(lambda v: v)(x)
    return y
"""
    res = lint_sources({"hpx_tpu/models/fixsup.py": src},
                       rules=all_rules(["HPX017"]))
    # the def-line finding is suppressed; the body one is not
    assert len(res.findings) == 1
    assert res.findings[0].line == 5


# ---------------------------------------------------------------------------
# Real tree: the shared-state contract of the serving plane
# ---------------------------------------------------------------------------

def _real_ctx(rel):
    path = os.path.join(REPO, *rel.split("/"))
    with open(path, encoding="utf-8") as fh:
        return FileContext(fh.read(), rel)


def test_real_tree_tuner_arbiter_fleet_shared_state_guarded():
    """AdaptiveTuner/TuneArbiter/FleetRouter shared state is either
    lock-guarded (verified by HPX019's inference over the real files)
    or explicitly justified (the tuner's single-threaded contract)."""
    srcs = {}
    for rel in ("hpx_tpu/svc/autotune.py", "hpx_tpu/svc/fleet.py"):
        with open(os.path.join(REPO, *rel.split("/")),
                  encoding="utf-8") as fh:
            srcs[rel] = fh.read()
    res = lint_sources(srcs, rules=all_rules(["HPX019"]))
    assert res.findings == [], \
        "\n".join(f.format() for f in res.findings)
    # the justification HPX019 relies on for the tuner's bare counters
    # must stay written down next to the code
    assert "single-threaded by contract" in srcs["hpx_tpu/svc/autotune.py"]


def test_real_tree_arbiter_grant_table_mutations_hold_lock():
    # every write to TuneArbiter._holders happens with the arbiter
    # mutex held — checked on the raw attr_ops, not just via HPX019's
    # majority heuristic
    ctx = _real_ctx("hpx_tpu/svc/autotune.py")
    index = ProjectIndex([ctx])
    writes = []
    for q, info in index.functions.items():
        if info.cls != "TuneArbiter" or info.node.name == "__init__":
            continue
        for kind, attr, _node, held in info.attr_ops:
            if attr == "_holders" and kind == "write":
                writes.append((q, held))
    assert writes, "TuneArbiter._holders mutation sites not indexed"
    for q, held in writes:
        assert held, f"{q} mutates _holders without the arbiter lock"


def test_real_tree_fleet_router_counters_consistent():
    # FleetRouter: every _fl_lock-guarded counter is guarded at ALL
    # its mutation sites — HPX019 stays silent because the contract
    # is consistent, not because the index missed the class
    ctx = _real_ctx("hpx_tpu/svc/fleet.py")
    index = ProjectIndex([ctx])
    per_attr = {}
    for q, info in index.functions.items():
        if info.cls != "FleetRouter" or info.node.name == "__init__":
            continue
        for kind, attr, _node, held in info.attr_ops:
            if kind == "write":
                per_attr.setdefault(attr, []).append(bool(held))
    assert "prefill_tokens_saved" in per_attr
    for attr, held_flags in per_attr.items():
        assert len(set(held_flags)) == 1, \
            f"FleetRouter.{attr} mixes locked and bare mutation"


# ---------------------------------------------------------------------------
# CLI fast paths, per-rule counts, baseline ordering
# ---------------------------------------------------------------------------

BAD_MIXED = """\
import jax

def build(fs):
    for f in fs:
        g = jax.jit(f)
    try:
        return g
    except:
        pass
"""


def test_cli_only_filters_to_requested_rule(tmp_path, capsys):
    bad = tmp_path / "mod.py"
    bad.write_text(BAD_MIXED)
    assert cli_main([str(bad), "--no-baseline"]) == 1
    full = capsys.readouterr().out
    assert "HPX006" in full and "HPX005" in full
    assert cli_main([str(bad), "--no-baseline", "--only",
                     "HPX006"]) == 1
    only = capsys.readouterr().out
    assert "HPX006" in only and "HPX005" not in only


def test_cli_only_skips_stale_check_for_rule_subset(tmp_path, capsys):
    # a baseline carrying other rules' entries must not read as stale
    # under a partial --only scan
    bad = tmp_path / "mod.py"
    bad.write_text(BAD_MIXED)
    base = tmp_path / "base.json"
    assert cli_main([str(bad), "--baseline", str(base),
                     "--write-baseline"]) == 0
    capsys.readouterr()
    assert cli_main([str(bad), "--baseline", str(base),
                     "--only", "HPX006"]) == 0
    assert "stale baseline entry (" not in capsys.readouterr().out


def test_cli_changed_lints_only_git_dirty_files(tmp_path):
    subprocess.run(["git", "init", "-q", str(tmp_path)], check=True)
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    env = dict(os.environ,
               GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
               GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t")
    subprocess.run(["git", "add", "-A"], cwd=tmp_path, check=True)
    subprocess.run(["git", "commit", "-qm", "seed"], cwd=tmp_path,
                   env=env, check=True)
    run = [sys.executable, "-m", "hpx_tpu.analysis", "--changed",
           "--no-baseline"]
    pristine = subprocess.run(run, cwd=tmp_path, capture_output=True,
                              text=True, env=dict(env, PYTHONPATH=REPO))
    assert pristine.returncode == 0
    assert "no changed Python files" in pristine.stdout
    (tmp_path / "dirty.py").write_text(
        "def f():\n    try:\n        pass\n    except:\n        pass\n")
    dirty = subprocess.run(run, cwd=tmp_path, capture_output=True,
                           text=True, env=dict(env, PYTHONPATH=REPO))
    assert dirty.returncode == 1
    assert "HPX006" in dirty.stdout
    assert "clean.py" not in dirty.stdout


def test_json_report_has_per_rule_counts(tmp_path, capsys):
    bad = tmp_path / "mod.py"
    bad.write_text(BAD_MIXED + "\n# hpxlint: disable-file=HPX005\n")
    base = tmp_path / "base.json"
    assert cli_main([str(bad), "--baseline", str(base),
                     "--write-baseline"]) == 0
    capsys.readouterr()
    assert cli_main([str(bad), "--baseline", str(base),
                     "--format=json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["findings"] == []
    assert rep["suppressed_by_rule"] == {"HPX005": 1}
    assert rep["baselined_by_rule"] == {"HPX006": 1}


def test_update_baseline_entries_sorted_by_path_rule_key(tmp_path):
    bad_a = tmp_path / "a_mod.py"
    bad_b = tmp_path / "b_mod.py"
    bad_b.write_text(BAD_MIXED)
    bad_a.write_text(BAD_MIXED)
    base = tmp_path / "base.json"
    # feed paths b-first: the emitted entries must still come out in
    # (path, rule, message) order so baseline diffs are reviewable
    assert cli_main([str(bad_b), str(bad_a), "--baseline", str(base),
                     "--update-baseline"]) == 0
    entries = json.loads(base.read_text())["entries"]
    keys = [(e["path"], e["rule"], e["message"]) for e in entries]
    assert keys == sorted(keys)
    assert len({e["path"] for e in entries}) == 2


# ---------------------------------------------------------------------------
# The CI gate script + its perf budget
# ---------------------------------------------------------------------------

def test_lint_gate_script_passes_on_real_tree():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py")],
        cwd=os.path.dirname(REPO) or "/", capture_output=True,
        text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # github format on a clean tree: no annotations at all
    assert proc.stdout.strip() == ""


def test_three_tier_run_one_parse_per_file_under_budget():
    before = parse_count()
    t0 = time.monotonic()
    res = lint_paths([os.path.join(REPO, "hpx_tpu")],
                     rules=all_rules())
    elapsed = time.monotonic() - t0
    assert parse_count() - before == res.checked_files
    assert elapsed < 15.0, f"three-tier run took {elapsed:.1f}s"


def test_dataflow_index_shares_parsed_trees():
    srcs = {"hpx_tpu/svc/fix.py": HPX019_BAD,
            "hpx_tpu/models/fix.py": HPX020_BAD}
    ctxs = [FileContext(s, p) for p, s in srcs.items()]
    before = parse_count()
    dfx = DataflowIndex(ProjectIndex(ctxs))
    for p in srcs:
        dfx.file_dataflow(p)
    assert parse_count() == before  # def-use built on the shared ASTs
