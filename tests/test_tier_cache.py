"""Host-RAM KV tier (hpx_tpu/cache/tier.py) and its serving splice:
demote/probe/checkout bookkeeping, the byte budget's LRU-to-oblivion
final tier, the restore-vs-recompute crossover gate, the radix tree's
(demoted, dropped) eviction split and two-tier match, and the full
ContinuousServer promote path — tier-on output must be byte-identical
to tier-off (greedy AND sampled) while strictly increasing prefill
tokens saved, with zero leaked device blocks and zero in-flight host
buffers at drain. Flight bundles and /cache{...}/tier/* counters ride
the same fixtures."""

import gc

import jax
import numpy as np
import pytest

from hpx_tpu.cache import BlockAllocator, RadixCache
from hpx_tpu.cache.tier import HostTier, RestoreGate, flight_snapshot
from hpx_tpu.core.config import runtime_config
from hpx_tpu.models import transformer as tfm
from hpx_tpu.models.serving import ContinuousServer

CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=4, head_dim=8,
                            n_layers=2, d_ff=64)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(CFG, jax.random.PRNGKey(0))


def _rows(fill=1, shape=(2, 2, 4, 2, 4)):
    return np.full(shape, fill, np.uint8)


def _scales(fill=0.5, shape=(2, 2, 2)):
    return np.full(shape, fill, np.float32)


# -- HostTier bookkeeping ----------------------------------------------------

def test_demote_probe_checkout_checkin_roundtrip():
    t = HostTier(budget_bytes=1 << 20, block_size=4)
    rows, scs = _rows(7), _scales(0.25)
    assert t.demote(11, 0, (1, 2, 3, 4), rows, scs)
    nb = rows.nbytes + scs.nbytes
    assert t.probe(11, (1, 2, 3, 4)) == nb
    # collision guard: same chain hash, different token chunk -> miss
    assert t.probe(11, (1, 2, 3, 9)) is None
    assert t.probe(12, (1, 2, 3, 4)) is None
    e = t.checkout(11)
    assert e is not None and t.leaked_buffers() == 1
    # the tier holds COPIES: mutating the caller's array after demote
    # must not reach the entry
    rows[:] = 0
    assert (np.asarray(e.rows) == 7).all()
    assert (np.asarray(e.scales) == 0.25).all()
    assert t.probe(11, (1, 2, 3, 4)) is None    # checked out = gone
    t.checkin(e)
    assert t.leaked_buffers() == 0
    st = t.stats()
    assert st["tier_demoted"] == 1 and st["tier_promoted"] == 1
    assert st["tier_entries"] == 0 and st["tier_bytes_held"] == 0


def test_putback_restores_the_entry():
    t = HostTier(1 << 20, 4)
    t.demote(5, 0, (9, 9, 9, 9), _rows(3), None)
    e = t.checkout(5)
    assert t.leaked_buffers() == 1
    t.putback(e)
    assert t.leaked_buffers() == 0
    assert t.probe(5, (9, 9, 9, 9)) is not None
    assert t.stats()["tier_promoted"] == 0      # an abort is not a hit


def test_budget_lru_to_oblivion_and_oversize_reject():
    one = _rows().nbytes                        # no scales: rows only
    t = HostTier(budget_bytes=2 * one, block_size=4)
    t.demote(1, 0, (1,) * 4, _rows(1), None)
    t.demote(2, 1, (2,) * 4, _rows(2), None)
    t.probe(1, (1,) * 4)                        # touch: 2 becomes LRU
    t.demote(3, 2, (3,) * 4, _rows(3), None)    # over budget -> evict 2
    assert t.probe(2, (2,) * 4) is None
    assert t.probe(1, (1,) * 4) is not None
    assert t.probe(3, (3,) * 4) is not None
    assert t.stats()["tier_dropped"] == 1
    # an entry larger than the whole budget is refused outright
    assert not t.demote(4, 3, (4,) * 4, np.zeros(3 * one, np.uint8),
                        None)
    assert t.stats()["tier_dropped"] == 2
    assert t.stats()["tier_entries"] == 2


def test_replace_same_chain_keeps_one_entry():
    t = HostTier(1 << 20, 4)
    t.demote(7, 0, (1, 2, 3, 4), _rows(1), None)
    t.demote(7, 0, (1, 2, 3, 4), _rows(9), None)
    st = t.stats()
    assert st["tier_entries"] == 1 and st["tier_demoted"] == 2
    assert st["tier_bytes_held"] == _rows().nbytes
    e = t.checkout(7)
    assert (np.asarray(e.rows) == 9).all()      # latest bytes win
    t.checkin(e)


def test_buffer_pool_recycles_across_demotions():
    t = HostTier(1 << 20, 4)
    t.demote(1, 0, (1,) * 4, _rows(1), None)
    e = t.checkout(1)
    buf = e.rows
    t.checkin(e)                                # buf -> free list
    t.demote(2, 0, (2,) * 4, _rows(2), None)
    e2 = t.checkout(2)
    assert e2.rows is buf                       # pooled, not realloc'd
    assert (np.asarray(e2.rows) == 2).all()     # and rewritten
    t.checkin(e2)


def test_digest_is_mru_first():
    t = HostTier(1 << 20, 4)
    for c in (1, 2, 3):
        t.demote(c, 0, (c,) * 4, _rows(c), None)
    t.probe(1, (1,) * 4)
    assert t.digest()[:2] == [1, 3]
    assert set(t.digest()) == {1, 2, 3}
    assert t.digest(max_entries=1) == [1]


# -- RestoreGate: the crossover estimator ------------------------------------

def test_gate_fast_link_promotes_slow_link_declines():
    fast = RestoreGate(min_speedup=1.0, prefill_cost_us=50.0,
                       overhead_us=200.0, probe_fn=lambda n: 1e12)
    ok, est = fast.should_promote(ntok=48, nbytes=4096)
    assert ok
    assert est["prefill_s"] == pytest.approx(48 * 50e-6)
    assert est["restore_s"] < est["prefill_s"]
    slow = RestoreGate(min_speedup=1.0, prefill_cost_us=50.0,
                       overhead_us=200.0, probe_fn=lambda n: 1.0)
    ok, est = slow.should_promote(ntok=48, nbytes=4096)
    assert not ok
    assert est["restore_s"] > est["prefill_s"]


def test_gate_bandwidth_is_measured_once():
    calls = []

    def probe(nbytes):
        calls.append(nbytes)
        return 1e9

    g = RestoreGate(probe_mb=2, probe_fn=probe)
    g.should_promote(16, 1024)
    g.should_promote(16, 1024)
    assert g.bandwidth() == 1e9
    assert calls == [2 << 20]                   # lazy, exactly once


def test_gate_prefill_fallback_without_profiler():
    g = RestoreGate(prefill_cost_us=80.0, probe_fn=lambda n: 1e9)
    assert g.prefill_s_per_token() == pytest.approx(80e-6)


def test_gate_min_speedup_raises_the_bar():
    # restore_s is pinned at exactly the overhead (infinite bandwidth);
    # prefill_s = 2x restore_s, so 1x promotes but 3x declines
    g1 = RestoreGate(min_speedup=1.0, prefill_cost_us=100.0,
                     overhead_us=800.0, probe_fn=lambda n: 1e15)
    g3 = RestoreGate(min_speedup=3.0, prefill_cost_us=100.0,
                     overhead_us=800.0, probe_fn=lambda n: 1e15)
    assert g1.should_promote(16, 64)[0]
    assert not g3.should_promote(16, 64)[0]


# -- RadixCache: eviction split + two-tier match -----------------------------

def _tiered_radix(nblocks=8, bs=4, budget=None, tier=None):
    """A radix tree whose demote hook snapshots dummy rows into
    `tier` keyed exactly like serving's _demote_block (minus pools)."""
    a = BlockAllocator(nblocks, bs)
    r = RadixCache(a, budget)
    if tier is not None:
        r.demote_hook = lambda ch, par, key, bid: tier.demote(
            ch, par, key, _rows(bid + 1), None)
    return a, r


def test_evict_returns_demoted_dropped_split():
    tier = HostTier(1 << 20, 4)
    a, r = _tiered_radix(tier=tier)
    toks = list(range(12))                      # 3 full blocks
    bids = [a.alloc() for _ in range(3)]
    assert r.insert(toks, bids) == 3
    for b in bids:
        a.decref(b)                             # tree holds the only ref
    assert r.evict(3) == (3, 0)
    assert tier.stats()["tier_demoted"] == 3
    # a refusing hook counts the same evictions as dropped
    a2, r2 = _tiered_radix()
    r2.demote_hook = lambda *args: False
    bids = [a2.alloc() for _ in range(2)]
    r2.insert(list(range(8)), bids)
    for b in bids:
        a2.decref(b)
    assert r2.evict(2) == (0, 2)


def test_match_tiered_extends_hot_match_and_stops_at_gap():
    tier = HostTier(1 << 20, 4)
    a, r = _tiered_radix(tier=tier)
    toks = list(range(12))
    bids = [a.alloc() for _ in range(3)]
    r.insert(toks, bids)
    for b in bids:
        a.decref(b)
    assert r.evict(1) == (1, 0)                 # deepest leaf demotes
    matched, mbids, ext = r.match_tiered(toks, tier)
    assert matched == 8 and len(mbids) == 2
    assert [e[1] for e in ext] == [(8, 9, 10, 11)]
    for b in mbids:
        a.decref(b)                             # drop the match leases
    # demote the rest; a gap (checked-out middle block) stops the run
    assert r.evict(2) == (2, 0)
    matched, mbids, ext = r.match_tiered(toks, tier)
    assert matched == 0 and mbids == []
    assert [e[1] for e in ext] == [(0, 1, 2, 3), (4, 5, 6, 7),
                                   (8, 9, 10, 11)]
    gone = tier.checkout(ext[1][0])             # hole at block 1
    matched, mbids, ext = r.match_tiered(toks, tier)
    assert [e[1] for e in ext] == [(0, 1, 2, 3)]
    tier.putback(gone)


# -- serving integration: the promote path -----------------------------------

def _tier_reqs():
    """Two 48-token (6-block) shared prefixes ALTERNATING over one
    slot under a 4-block radix budget: each retire's budget sweep
    evicts the other (reader-free) chain wholesale, so the next
    admission of that prefix is restorable only from the host tier —
    tier-off saves zero prefill tokens, tier-on promotes the full
    prefix back every time. Deterministic by construction, not by
    scheduling luck."""
    rng = np.random.default_rng(42)
    prefixes = [[int(x) for x in rng.integers(1, 64, 48)]
                for _ in range(2)]
    reqs = []
    for i in range(6):
        tail = [int(x) for x in rng.integers(1, 64, 4)]
        r = dict(prompt=prefixes[i % 2] + tail, max_new=5)
        if i % 3 == 2:
            r.update(temperature=0.8, key=jax.random.PRNGKey(100 + i))
        reqs.append(r)
    return reqs


def _run_wave(params, tier_on, probe_bw=1e12, kv_dtype="fp8"):
    """One alternating-prefix wave (see _tier_reqs). Returns
    (outputs, cache_stats, device_leak, host_leak)."""
    rc = runtime_config()
    rc.set("hpx.cache.tier.enable", "1" if tier_on else "0")
    try:
        srv = ContinuousServer(params, CFG, slots=1, smax=64,
                               paged=True, block_size=8,
                               kv_dtype=kv_dtype,
                               radix_budget_blocks=4)
        if tier_on:
            # injectable probe: pin the gate's verdict, never touch
            # the device from the estimator
            srv._tier_gate = RestoreGate(min_speedup=1.0,
                                         probe_fn=lambda n: probe_bw)
        free0 = srv._alloc.stats()["free"]
        for r in _tier_reqs():
            srv.submit(**r)
        out = srv.run()
        st = srv.cache_stats()
        while sum(srv._radix.evict(1)):
            pass
        dev_leak = free0 - srv._alloc.stats()["free"]
        host_leak = (srv._tier.leaked_buffers()
                     if srv._tier is not None else 0)
        return out, st, dev_leak, host_leak
    finally:
        rc.set("hpx.cache.tier.enable", "0")


@pytest.mark.parametrize("kvd", ["fp8", "bf16"])
def test_tier_on_is_byte_identical_and_saves_more(params, kvd):
    """The acceptance wave: small HBM budget, shared prefix bigger
    than it. Tier-on must emit exactly the tier-off tokens (greedy
    and sampled) while strictly increasing prefill tokens saved, and
    drain with zero device-block and host-buffer leaks."""
    out_off, st_off, dl_off, hl_off = _run_wave(params, False,
                                                kv_dtype=kvd)
    out_on, st_on, dl_on, hl_on = _run_wave(params, True,
                                            kv_dtype=kvd)
    assert out_on == out_off
    assert st_on["prefill_tokens_saved"] > st_off["prefill_tokens_saved"]
    assert st_on["tier_demoted"] > 0
    assert st_on["tier_promoted"] > 0
    assert (dl_off, hl_off) == (0, 0)
    assert (dl_on, hl_on) == (0, 0)


def test_slow_probe_declines_but_stays_identical(params):
    """The other side of the crossover: a 1 B/s link makes every
    restore lose to re-prefill — zero promotions, declines counted,
    and the outputs are STILL byte-identical (a declined hit just
    recomputes)."""
    out_off, st_off, _, _ = _run_wave(params, False)
    out_slow, st_slow, dl, hl = _run_wave(params, True, probe_bw=1.0)
    assert out_slow == out_off
    assert st_slow["tier_promoted"] == 0
    assert st_slow["tier_declined"] > 0
    assert st_slow["prefill_tokens_saved"] == \
        st_off["prefill_tokens_saved"]
    assert (dl, hl) == (0, 0)


def test_budget_knob_reloads_live(params):
    rc = runtime_config()
    rc.set("hpx.cache.tier.enable", "1")
    try:
        srv = ContinuousServer(params, CFG, slots=2, smax=64,
                               paged=True, block_size=8)
        assert srv._tier.budget_bytes == 256 << 20      # default
        rc.set("hpx.cache.tier.host_budget_mb", 7)
        srv._reload_knobs()
        assert srv._tier.budget_bytes == 7 << 20
    finally:
        rc.set("hpx.cache.tier.host_budget_mb", "auto")
        rc.set("hpx.cache.tier.enable", "0")


# -- observability: counters + flight bundles --------------------------------

def test_tier_counters_registered_and_queryable(params):
    from hpx_tpu.svc import performance_counters as pc
    rc = runtime_config()
    rc.set("hpx.cache.tier.enable", "1")
    try:
        srv = ContinuousServer(params, CFG, slots=1, smax=64,
                               paged=True, block_size=8,
                               kv_dtype="fp8", radix_budget_blocks=4)
        srv._tier_gate = RestoreGate(min_speedup=1.0,
                                     probe_fn=lambda n: 1e12)
        inst = srv.counter_instance
        for r in _tier_reqs()[:4]:
            srv.submit(**r)
        srv.run()
        for leaf, want in [
                ("tier/count/demoted", srv._tier.total_demoted),
                ("tier/count/promoted", srv._tier.total_promoted),
                ("tier/count/declined", srv._tier.total_declined),
                ("tier/hit-depth-blocks", srv._tier.hit_depth_blocks),
                ("tier/bytes-held",
                 srv._tier.stats()["tier_bytes_held"]),
                ("tier/entries", srv._tier.stats()["tier_entries"])]:
            got = pc.query_counter(
                pc.counter_name("cache", leaf, inst)).value
            assert got == want, leaf
        assert srv._tier.total_promoted > 0
        # the promotion-latency histogram exports its base counter
        # (mean seconds, sample count) plus the derived pNN quantiles
        base = pc.counter_name("cache", "tier/promote-latency-s", inst)
        cv = pc.query_counter(base)
        assert cv.count >= 1 and cv.value > 0
        from hpx_tpu.svc.metrics import configured_quantiles, \
            quantile_label
        for q in configured_quantiles():
            derived = pc.counter_name(
                "cache", f"tier/promote-latency-s/{quantile_label(q)}",
                inst)
            assert pc.query_counter(derived).value >= 0
        name = pc.counter_name("cache", "tier/count/demoted", inst)
        del srv
        gc.collect()
        assert name not in pc.discover_counters("/cache{locality#*/*}/*")
    finally:
        rc.set("hpx.cache.tier.enable", "0")


def test_flight_bundle_carries_tier_state(params):
    from hpx_tpu.svc import flight
    rc = runtime_config()
    rc.set("hpx.cache.tier.enable", "1")
    try:
        srv = ContinuousServer(params, CFG, slots=1, smax=64,
                               paged=True, block_size=8,
                               radix_budget_blocks=4)
        srv._tier_gate = RestoreGate(min_speedup=1.0,
                                     probe_fn=lambda n: 1e12)
        for r in _tier_reqs()[:3]:
            srv.submit(**r)
        srv.run()
        doc = flight.build_bundle("manual")
        assert doc["tier"].get("tiers", 0) >= 1
        assert doc["tier"]["tier_demoted"] >= srv._tier.total_demoted
        assert flight.validate_bundle(doc) == []
        bad = dict(doc, tier=3)
        assert any("tier" in e for e in flight.validate_bundle(bad))
    finally:
        rc.set("hpx.cache.tier.enable", "0")


def test_flight_snapshot_shape():
    t = HostTier(1 << 20, 4)
    t.demote(1, 0, (1,) * 4, _rows(1), None)
    snap = flight_snapshot()
    assert snap["tiers"] >= 1
    assert snap["tier_demoted"] >= 1
    assert "tier_budget_bytes" not in snap      # budgets don't sum


def test_worker_digest_exposes_cold_chains(params):
    """The fleet-routing feed: a tiered DecodeWorker's prefix digest
    carries the host tier's chain hashes next to the hot ones."""
    from hpx_tpu.models.disagg import DecodeWorker
    rc = runtime_config()
    rc.set("hpx.cache.tier.enable", "1")
    try:
        w = DecodeWorker(params, CFG, slots=1, smax=64, block_size=8,
                         radix_budget_blocks=4)
        w.srv._tier_gate = RestoreGate(min_speedup=1.0,
                                       probe_fn=lambda n: 1e12)
        for r in _tier_reqs()[:2]:
            w.srv.submit(**r)
        w.srv.run()
        d = w.prefix_digest()
        assert d["tier_hashes"]                 # demotions happened
        assert set(d["tier_hashes"]).isdisjoint(d["hashes"])
        assert w.leaked_blocks() == 0
        assert w.srv._tier.leaked_buffers() == 0
    finally:
        rc.set("hpx.cache.tier.enable", "0")
