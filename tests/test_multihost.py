"""Multi-host wiring (parallel/multihost.py): batch-env resolution and
global mesh construction. jax.distributed itself is exercised at
num_processes=1 (a real initialize over localhost)."""

import numpy as np
import pytest

import jax

from hpx_tpu.parallel import multihost


def test_resolve_single_host_is_none():
    assert multihost.resolve(environ={}) is None


def test_resolve_from_slurm_env():
    env = {"SLURM_JOB_ID": "1", "SLURM_NTASKS": "4", "SLURM_PROCID": "2",
           "SLURM_JOB_NODELIST": "node[1-4]"}
    coord, n, pid = multihost.resolve(environ=env)
    assert n == 4 and pid == 2
    assert coord.startswith("node1:")


def test_resolve_bare_allocation_is_none():
    # ntasks known but no per-task rank: salloc without srun
    env = {"SLURM_JOB_ID": "1", "SLURM_NTASKS": "4"}
    assert multihost.resolve(environ=env) is None


def test_resolve_explicit_env_wins():
    env = {"JAX_COORDINATOR_ADDRESS": "10.0.0.1:1234",
           "JAX_NUM_PROCESSES": "2", "JAX_PROCESS_ID": "1",
           "SLURM_JOB_ID": "1", "SLURM_NTASKS": "8",
           "SLURM_PROCID": "7"}
    assert multihost.resolve(environ=env) == ("10.0.0.1:1234", 2, 1)


def test_resolve_openmpi():
    env = {"OMPI_COMM_WORLD_SIZE": "2", "OMPI_COMM_WORLD_RANK": "1"}
    coord, n, pid = multihost.resolve(environ=env)
    assert (n, pid) == (2, 1) and coord is None


def test_global_mesh_shapes(devices):
    m = multihost.global_mesh(devices=devices)
    assert m.shape["dp"] == 8
    m2 = multihost.global_mesh((2, None), ("dp", "tp"), devices=devices)
    assert dict(m2.shape) == {"dp": 2, "tp": 4}
    with pytest.raises(ValueError, match="divisible"):
        multihost.global_mesh((3, None), ("a", "b"), devices=devices)
    with pytest.raises(ValueError, match="!="):
        multihost.global_mesh((2, 2), ("a", "b"), devices=devices)


def test_init_single_process_real():
    """A REAL jax.distributed.initialize at num_processes=1 over
    localhost — the same call a pod makes, world size 1. Runs in a
    FRESH interpreter: initialize must precede any backend use, and
    this pytest process already created devices."""
    import os
    import subprocess
    import sys
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "from hpx_tpu.parallel import multihost\n"
        "ok = multihost.init(coordinator_address='127.0.0.1:12357',\n"
        "                    num_processes=1, process_id=0)\n"
        "assert ok and multihost.is_initialized()\n"
        "assert jax.process_count() == 1\n"
        "assert len(jax.devices()) >= 1\n"
        "assert multihost.init() is True   # idempotent\n"
        "print('MULTIHOST_OK')\n")
    p = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=240,
                       cwd=os.path.join(os.path.dirname(__file__), ".."))
    assert p.returncode == 0 and "MULTIHOST_OK" in p.stdout, \
        p.stdout + p.stderr


def test_resolve_tpu_pod_without_hostnames():
    """A pod worker id with no hostname list must still resolve (jax
    self-configures from the metadata server) — returning None here
    would silently train on one host of the pod."""
    env = {"TPU_WORKER_ID": "3"}
    assert multihost.resolve(environ=env) == (None, None, 3)


def test_resolve_partial_jax_env_merges_with_scheduler():
    env = {"JAX_COORDINATOR_ADDRESS": "10.0.0.9:9999",
           "SLURM_JOB_ID": "1", "SLURM_NTASKS": "4",
           "SLURM_PROCID": "2"}
    assert multihost.resolve(environ=env) == ("10.0.0.9:9999", 4, 2)


def test_global_mesh_uses_make_mesh_cache(devices):
    from hpx_tpu.parallel.mesh import make_mesh
    # all-device construction shares the cached Mesh object
    a = multihost.global_mesh((2, 4), ("dp", "pp"))
    b = make_mesh((2, 4), ("dp", "pp"))
    assert a is b
