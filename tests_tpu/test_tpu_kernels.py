"""Compile-and-check every pallas kernel on the real chip.

Numerics oracles are the XLA formulations (blockwise attention, roll
stencil) computed ON THE SAME CHIP, so assertions isolate kernel bugs
from backend-numerics differences. bf16 tolerances follow
tests/test_attention.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.tpu


def _qkv(b, s, n, h, dtype=jnp.bfloat16, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(
        rng.standard_normal((b, s, n, h), np.float32), dtype)
        for _ in range(3))


def _close(a, b, tol):
    np.testing.assert_allclose(np.asarray(a, np.float32),
                               np.asarray(b, np.float32),
                               rtol=tol, atol=tol)


class TestFlashForward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_blockwise(self, causal):
        from hpx_tpu.ops.attention import blockwise_attention
        from hpx_tpu.ops.attention_pallas import flash_attention
        q, k, v = _qkv(2, 1024, 4, 64)
        got = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal)
                      )(q, k, v)
        want = jax.jit(lambda q, k, v: blockwise_attention(q, k, v,
                                                           causal)
                       )(q, k, v)
        _close(got, want, 3e-2)

    def test_f32_tighter(self):
        from hpx_tpu.ops.attention import blockwise_attention
        from hpx_tpu.ops.attention_pallas import flash_attention
        q, k, v = _qkv(1, 512, 2, 128, dtype=jnp.float32)
        got = flash_attention(q, k, v, True)
        want = blockwise_attention(q, k, v, True)
        _close(got, want, 2e-4)


class TestFlashBackward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_blockwise(self, causal):
        from hpx_tpu.ops.attention import blockwise_attention
        from hpx_tpu.ops.attention_pallas import flash_attention
        q, k, v = _qkv(2, 1024, 4, 64)
        w = _qkv(2, 1024, 4, 64, seed=9)[0].astype(jnp.float32)

        def loss(fn):
            return lambda q, k, v: jnp.sum(
                fn(q, k, v, causal).astype(jnp.float32) * w)

        gf = jax.jit(jax.grad(loss(flash_attention), argnums=(0, 1, 2))
                     )(q, k, v)
        gb = jax.jit(jax.grad(loss(blockwise_attention),
                              argnums=(0, 1, 2)))(q, k, v)
        for name, a, b in zip("qkv", gf, gb):
            _close(a, b, 5e-2)


class TestChunkKernel:
    def test_host_simulated_ring(self):
        """flash_attention_chunk (scalar-prefetch d) compiled by Mosaic:
        fold all chunks of a 4-way ring on-chip, compare to the
        reference O(S^2) oracle."""
        from hpx_tpu.ops.attention import reference_attention
        from hpx_tpu.ops.attention_pallas import flash_attention_chunk
        B, S, N, H = 1, 512, 2, 64
        q, k, v = _qkv(B, S, N, H, dtype=jnp.float32, seed=3)
        want = reference_attention(q, k, v, True)
        nsh, sq = 4, S // 4
        outs = []
        for i in range(nsh):
            qc = jnp.moveaxis(q[:, i * sq:(i + 1) * sq], 2, 1
                              ).reshape(B * N, sq, H)
            acc = jnp.zeros((B * N, sq, H), jnp.float32)
            m = jnp.full((B * N, sq, 128), -1e30, jnp.float32)
            l = jnp.zeros((B * N, sq, 128), jnp.float32)
            for j in range(nsh):
                kc = jnp.moveaxis(k[:, j * sq:(j + 1) * sq], 2, 1
                                  ).reshape(B * N, sq, H)
                vc = jnp.moveaxis(v[:, j * sq:(j + 1) * sq], 2, 1
                                  ).reshape(B * N, sq, H)
                acc, m, l = flash_attention_chunk(
                    qc, kc, vc, acc, m, l,
                    jnp.int32(i * sq - j * sq), causal=True,
                    block_q=128, block_k=128)
            den = jnp.where(l[:, :, :1] > 0, l[:, :, :1], 1.0)
            o = (acc / den).reshape(B, N, sq, H)
            outs.append(jnp.moveaxis(o, 1, 2))
        got = jnp.concatenate(outs, axis=1).astype(q.dtype)
        _close(got, want, 3e-4)


class TestRingInShardMap:
    def test_vma_checked_shard_map_single_chip(self):
        """The exact wiring the training step uses — _ring_flash inside
        a vma-checked shard_map (degenerate 1-device mesh on one chip;
        multi-chip runs the same code over real ICI)."""
        from jax import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        from hpx_tpu.ops.attention import (_ring_flash,
                                           blockwise_attention)
        mesh = Mesh(np.array(jax.devices()[:1]), ("sp",))
        q, k, v = _qkv(1, 256, 2, 64, dtype=jnp.float32, seed=5)
        spec = P(None, "sp", None, None)
        out = jax.jit(shard_map(
            lambda qc, kc, vc: _ring_flash(qc, kc, vc, "sp", 1, True),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec))(q, k, v)
        _close(out, blockwise_attention(q, k, v, True), 3e-4)

    def test_grad_through_shard_map(self):
        from jax import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        from hpx_tpu.ops.attention import (_ring_flash,
                                           blockwise_attention)
        mesh = Mesh(np.array(jax.devices()[:1]), ("sp",))
        q, k, v = _qkv(1, 256, 2, 64, dtype=jnp.float32, seed=6)
        spec = P(None, "sp", None, None)

        def loss(q, k, v):
            def body(qc, kc, vc):
                o = _ring_flash(qc, kc, vc, "sp", 1, True)
                return jax.lax.psum(jnp.sum(o), "sp")
            return jax.jit(shard_map(body, mesh=mesh,
                                     in_specs=(spec,) * 3,
                                     out_specs=P()))(q, k, v)

        got = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(lambda q, k, v: jnp.sum(
            blockwise_attention(q, k, v, True)), argnums=(0, 1, 2)
            )(q, k, v)
        for a, b in zip(got, want):
            _close(a, b, 3e-4)


class TestStencilKernels:
    def test_blocked_step_with_seams(self):
        from hpx_tpu.ops.stencil import heat_step, pallas_heat_step
        n = 1 << 21
        u = jnp.asarray(np.random.default_rng(0).random(n, np.float32))
        _close(pallas_heat_step(u, jnp.float32(0.25)),
               heat_step(u, jnp.float32(0.25)), 1e-6)

    def test_fused_multistep(self):
        from hpx_tpu.ops.stencil import pallas_multistep, xla_multistep
        n = 1 << 16
        u = jnp.asarray(np.random.default_rng(1).random(n, np.float32))
        _close(pallas_multistep(u, jnp.float32(0.25), 32),
               xla_multistep(u, jnp.float32(0.25), 32), 1e-4)


class TestTrainStepOnChip:
    def test_flash_vs_blockwise_trajectories(self):
        """Two full train steps through each attention path must agree —
        the end-to-end guard for the custom_vjp wiring."""
        import hpx_tpu.ops.attention as att
        from hpx_tpu.models import transformer as tfm

        def run(use_flash):
            orig = att.ring_attention_sharded

            def patched(qc, kc, vc, axis, nshards, causal=False):
                return orig(qc, kc, vc, axis, nshards, causal,
                            use_flash=use_flash)

            att.ring_attention_sharded = patched
            tfm.ring_attention_sharded = patched
            try:
                cfg = tfm.TransformerConfig(
                    vocab=128, d_model=64, n_heads=2, head_dim=32,
                    n_layers=2, d_ff=128, lr=0.05, dtype=jnp.bfloat16)
                mesh = tfm.make_mesh_3d(1)
                params = tfm.shard_params(
                    tfm.init_params(cfg, jax.random.PRNGKey(0)), cfg,
                    mesh)
                step = tfm.make_train_step(cfg, mesh)
                toks, tgts = tfm.sample_batch(
                    cfg, batch=2, seq=128, key=jax.random.PRNGKey(1))
                toks, tgts = tfm.shard_batch(toks, tgts, mesh)
                losses = []
                for _ in range(3):
                    params, loss = step(params, toks, tgts)
                    losses.append(float(loss))
                return losses
            finally:
                att.ring_attention_sharded = orig
                tfm.ring_attention_sharded = orig

        lf, lb = run(True), run(False)
        np.testing.assert_allclose(lf, lb, rtol=2e-3, atol=2e-3)


class TestGQAOnChip:
    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_and_grads_match_repeat(self, causal):
        """GQA via index-remapped K/V tiles, Mosaic-compiled: must equal
        the dense path on repeated heads, values and grads."""
        from hpx_tpu.ops.attention_pallas import flash_attention
        B, S, H, nq, nkv = 2, 512, 64, 8, 2
        rep = nq // nkv
        q = _qkv(B, S, nq, H, seed=21)[0]
        k, v = _qkv(B, S, nkv, H, seed=22)[:2]
        w = _qkv(B, S, nq, H, seed=23)[0].astype(jnp.float32)

        def loss_gqa(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal).astype(
                jnp.float32) * w)

        def loss_rep(q, k, v):
            return jnp.sum(flash_attention(
                q, jnp.repeat(k, rep, axis=2),
                jnp.repeat(v, rep, axis=2), causal).astype(
                    jnp.float32) * w)

        got = jax.jit(jax.value_and_grad(loss_gqa, argnums=(0, 1, 2))
                      )(q, k, v)
        want = jax.jit(jax.value_and_grad(loss_rep, argnums=(0, 1, 2))
                       )(q, k, v)
        _close(got[0], want[0], 2e-2)
        for a, b in zip(got[1], want[1]):
            _close(a, b, 5e-2)


class TestTransformerShapeOnChip:
    def test_flash_head_dim_64(self):
        """The bench transformer's attention shape (H=64 heads): flash
        kernels must stay numerically tight at the narrow head dim the
        train step actually uses."""
        from hpx_tpu.ops.attention import blockwise_attention
        from hpx_tpu.ops.attention_pallas import flash_attention
        q, k, v = _qkv(4, 1024, 8, 64, seed=3)
        got = flash_attention(q, k, v, causal=True)
        want = blockwise_attention(q, k, v, causal=True)
        _close(got, want, 2e-2)

    def test_flash_head_dim_64_grads(self):
        from hpx_tpu.ops.attention import blockwise_attention
        from hpx_tpu.ops.attention_pallas import flash_attention
        q, k, v = _qkv(2, 512, 4, 64, seed=4)

        def loss(f):
            return lambda a, b, c: jnp.sum(
                f(a, b, c, True).astype(jnp.float32) ** 2)
        g1 = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss(blockwise_attention),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            _close(a, b, 6e-2)


class TestFftOnChip:
    def test_local_fft_matches_numpy(self):
        """XLA's TPU fft lowering (algo/fft's local transforms) against
        numpy — guards the distributed FFT on real hardware."""
        rng = np.random.default_rng(5)
        a = (rng.standard_normal((64, 256)) +
             1j * rng.standard_normal((64, 256))).astype(np.complex64)
        got = jax.jit(lambda x: jnp.fft.fft(x, axis=1))(jnp.asarray(a))
        ref = np.fft.fft(a.astype(np.complex128), axis=1)
        rel = (np.linalg.norm(np.asarray(got) - ref)
               / np.linalg.norm(ref))
        assert rel < 1e-4, rel

    def test_fft_sharded_single_chip(self):
        """fft_sharded on a 1-device mesh (degenerate all_to_all) —
        compiles the whole four-step program through the TPU backend."""
        from jax.sharding import Mesh
        from hpx_tpu.algo import fft as dfft
        mesh = Mesh(np.array(jax.devices()[:1]), ("x",))
        rng = np.random.default_rng(6)
        v = (rng.standard_normal(4096) +
             1j * rng.standard_normal(4096)).astype(np.complex64)
        got = dfft.fft_sharded(jnp.asarray(v), mesh)
        ref = np.fft.fft(v.astype(np.complex128))
        rel = (np.linalg.norm(np.asarray(got) - ref)
               / np.linalg.norm(ref))
        assert rel < 1e-4, rel


class TestServingOnChip:
    def test_quantized_decode_matches_dense(self):
        """int8 weight-only decode on the real chip: XLA must fuse the
        dequant into the matmul and tokens should match dense for a
        small model."""
        import hpx_tpu.models.transformer as tfm
        from hpx_tpu.models import quant
        cfg = tfm.TransformerConfig(vocab=128, d_model=128, n_heads=8,
                                    head_dim=16, n_layers=2, d_ff=256,
                                    dtype=jnp.bfloat16)
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        prompt = jnp.array([[5, 9, 2, 7]], jnp.int32)
        dense = np.asarray(tfm.generate(params, cfg, prompt, max_new=8))
        q = np.asarray(tfm.generate(quant.quantize_params(params), cfg,
                                    prompt, max_new=8))
        assert (dense == q).mean() >= 0.75, (dense, q)

    def test_beam_search_compiles_on_chip(self):
        import hpx_tpu.models.transformer as tfm
        cfg = tfm.TransformerConfig(vocab=64, d_model=64, n_heads=4,
                                    head_dim=16, n_layers=2, d_ff=128,
                                    dtype=jnp.bfloat16)
        params = tfm.init_params(cfg, jax.random.PRNGKey(1))
        out = tfm.beam_search(params, cfg,
                              jnp.array([[1, 2, 3]], jnp.int32),
                              max_new=6, beam_width=4)
        assert out.shape == (1, 6)


class TestTunedBlocks:
    """Whatever block sizes resolve_blocks picks (tuned table, env, or
    default) must Mosaic-compile and agree with the XLA oracle — run
    after benchmarks/flash_tune.py writes a table to catch a tuned
    shape that compiles differently than it benched."""

    def test_resolved_blocks_compile_and_match(self):
        import functools
        import numpy as np
        import jax
        import jax.numpy as jnp
        from hpx_tpu.ops.attention import blockwise_attention
        from hpx_tpu.ops.attention_pallas import (flash_attention,
                                                  resolve_blocks)
        B, S, N, H = 1, 2048, 4, 128
        bq, bk = resolve_blocks(S, S, True)
        rng = np.random.default_rng(0)
        mk = lambda: jnp.asarray(  # noqa: E731
            rng.standard_normal((B, S, N, H), np.float32), jnp.bfloat16)
        q, k, v = mk(), mk(), mk()
        got = jax.jit(functools.partial(flash_attention, causal=True))(
            q, k, v)
        want = blockwise_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            atol=3e-2, rtol=3e-2)
        assert bq >= 8 and bk >= 8


class TestStripedAndGQAChunks:
    """Round-5 ring upgrades through Mosaic on the real chip: striped
    offsets (d in {0,-1}) and GQA row-remapped K/V tiles in
    flash_attention_chunk."""

    def test_striped_chunk_fold(self):
        from hpx_tpu.ops.attention import (reference_attention,
                                           stripe_sequence,
                                           unstripe_sequence)
        from hpx_tpu.ops.attention_pallas import flash_attention_chunk
        B, S, N, H = 1, 512, 2, 64
        q, k, v = _qkv(B, S, N, H, dtype=jnp.float32, seed=9)
        want = reference_attention(q, k, v, True)
        nsh, sq = 4, S // 4
        qs, ks, vs = (stripe_sequence(x, nsh) for x in (q, k, v))
        outs = []
        for i in range(nsh):
            qc = jnp.moveaxis(qs[:, i * sq:(i + 1) * sq], 2, 1
                              ).reshape(B * N, sq, H)
            acc = jnp.zeros((B * N, sq, H), jnp.float32)
            m = jnp.full((B * N, sq, 128), -1e30, jnp.float32)
            l = jnp.zeros((B * N, sq, 128), jnp.float32)
            for j in range(nsh):
                kc = jnp.moveaxis(ks[:, j * sq:(j + 1) * sq], 2, 1
                                  ).reshape(B * N, sq, H)
                vc = jnp.moveaxis(vs[:, j * sq:(j + 1) * sq], 2, 1
                                  ).reshape(B * N, sq, H)
                acc, m, l = flash_attention_chunk(
                    qc, kc, vc, acc, m, l,
                    jnp.int32(0 if j <= i else -1), causal=True,
                    block_q=128, block_k=128)
            den = jnp.where(l[:, :, :1] > 0, l[:, :, :1], 1.0)
            o = (acc / den).reshape(B, N, sq, H)
            outs.append(jnp.moveaxis(o, 1, 2))
        got = unstripe_sequence(jnp.concatenate(outs, axis=1),
                                nsh).astype(q.dtype)
        _close(got, want, 3e-4)

    def test_gqa_grouped_chunk_fold(self):
        """Grouped K/V rows through the chunk kernel's BlockSpec remap
        (the grouped-wire ring path) vs the repeat oracle."""
        from hpx_tpu.ops.attention import reference_attention
        from hpx_tpu.ops.attention_pallas import flash_attention_chunk
        B, S, NQ, NKV, H = 1, 512, 4, 2, 64
        q, _, _ = _qkv(B, S, NQ, H, dtype=jnp.float32, seed=10)
        _, k, v = _qkv(B, S, NKV, H, dtype=jnp.float32, seed=11)
        want = reference_attention(
            q, jnp.repeat(k, NQ // NKV, 2), jnp.repeat(v, NQ // NKV, 2),
            True)
        nsh, sq = 4, S // 4
        outs = []
        for i in range(nsh):
            qc = jnp.moveaxis(q[:, i * sq:(i + 1) * sq], 2, 1
                              ).reshape(B * NQ, sq, H)
            acc = jnp.zeros((B * NQ, sq, H), jnp.float32)
            m = jnp.full((B * NQ, sq, 128), -1e30, jnp.float32)
            l = jnp.zeros((B * NQ, sq, 128), jnp.float32)
            for j in range(nsh):
                kc = jnp.moveaxis(k[:, j * sq:(j + 1) * sq], 2, 1
                                  ).reshape(B * NKV, sq, H)
                vc = jnp.moveaxis(v[:, j * sq:(j + 1) * sq], 2, 1
                                  ).reshape(B * NKV, sq, H)
                acc, m, l = flash_attention_chunk(
                    qc, kc, vc, acc, m, l,
                    jnp.int32(i * sq - j * sq), causal=True,
                    block_q=128, block_k=128, q_heads=NQ,
                    kv_heads=NKV)
            den = jnp.where(l[:, :, :1] > 0, l[:, :, :1], 1.0)
            o = (acc / den).reshape(B, NQ, sq, H)
            outs.append(jnp.moveaxis(o, 1, 2))
        got = jnp.concatenate(outs, axis=1).astype(q.dtype)
        _close(got, want, 3e-4)
