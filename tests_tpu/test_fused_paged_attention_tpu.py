"""Real-chip leg of the fused paged-attention contract (ROADMAP item
3): the Pallas block-table kernels compiled by Mosaic must match the
XLA gather-oracle formulation ON THE SAME TPU — decode and verify
windows; bf16, int8 and fp8 (e4m3) pools; the bitwise `fused` kernel
AND the O(block)-scratch `fused_online` online-softmax kernel. tests/
covers interpret mode on CPU; this is the only place the actual
Mosaic lowering (incl. the double-buffered online carry) is checked,
so a regression fails a test instead of silently showing up as a
serving numerics drift. Skips cleanly off-chip (see conftest)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.tpu


def _pools(nb, bs, nkv, hd, dtype=jnp.bfloat16, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(
        rng.standard_normal((nb, bs, nkv, hd), np.float32), dtype)
        for _ in range(2))


def _table(b, maxb, nb, seed=1):
    rng = np.random.default_rng(seed)
    ids = rng.permutation(nb)[:b * maxb].reshape(b, maxb)
    return jnp.asarray(ids, jnp.int32)


def _close(a, b, tol):
    np.testing.assert_allclose(np.asarray(a, np.float32),
                               np.asarray(b, np.float32),
                               rtol=tol, atol=tol)


class TestFusedPagedDecode:
    def test_matches_gather_bf16(self):
        from hpx_tpu.ops.paged_attention import paged_decode_attention
        B, nb, bs, maxb, nkv, nq, hd = 2, 16, 16, 4, 2, 4, 64
        kp, vp = _pools(nb, bs, nkv, hd)
        table = _table(B, maxb, nb)
        pos = jnp.asarray([37, 22], jnp.int32)
        rng = np.random.default_rng(2)
        q = jnp.asarray(rng.standard_normal((B, 1, nq, hd), np.float32),
                        jnp.bfloat16)
        kn, vn = (jnp.asarray(
            rng.standard_normal((B, nkv, hd), np.float32), jnp.bfloat16)
            for _ in range(2))

        def run(fused):
            att, *_ = jax.jit(
                lambda q, kn, vn, kp, vp: paged_decode_attention(
                    q, kn, vn, kp, vp, table, pos, fused=fused)
            )(q, kn, vn, kp, vp)
            return att
        _close(run(True), run(False), 3e-2)
        # the online kernel's tolerance budget is O(eps * num_blocks)
        # past the bitwise kernel's — identical bf16 tolerance here
        _close(run("online"), run(False), 3e-2)

    def test_matches_gather_int8(self):
        """int8 pools + absmax scale sidecars: both paths dequantize
        the SAME stored bytes, so they agree to bf16 tolerance."""
        from hpx_tpu.ops.paged_attention import (paged_decode_attention,
                                                 quantize_blocks)
        B, nb, bs, maxb, nkv, nq, hd = 2, 16, 32, 2, 2, 4, 64
        kf, vf = _pools(nb, bs, nkv, hd, seed=3)
        kp, ks = quantize_blocks(kf)
        vp, vs = quantize_blocks(vf)
        table = _table(B, maxb, nb, seed=4)
        pos = jnp.asarray([51, 9], jnp.int32)
        rng = np.random.default_rng(5)
        q = jnp.asarray(rng.standard_normal((B, 1, nq, hd), np.float32),
                        jnp.bfloat16)
        kn, vn = (jnp.asarray(
            rng.standard_normal((B, nkv, hd), np.float32), jnp.bfloat16)
            for _ in range(2))

        def run(fused):
            att, *_ = jax.jit(
                lambda q, kn, vn, kp, vp, ks, vs: paged_decode_attention(
                    q, kn, vn, kp, vp, table, pos, k_scale=ks,
                    v_scale=vs, fused=fused)
            )(q, kn, vn, kp, vp, ks, vs)
            return att
        _close(run(True), run(False), 3e-2)
        _close(run("online"), run(False), 3e-2)

    def test_matches_gather_fp8(self):
        """fp8 (e4m3) pools + the same f32 scale sidecars: the Mosaic
        lowering of the in-kernel float8 dequant must agree with the
        gather formulation over the same stored bytes — both fused
        kernels."""
        from hpx_tpu.ops.paged_attention import (paged_decode_attention,
                                                 quantize_blocks)
        B, nb, bs, maxb, nkv, nq, hd = 2, 16, 32, 2, 2, 4, 64
        kf, vf = _pools(nb, bs, nkv, hd, seed=9)
        kp, ks = quantize_blocks(kf, jnp.float8_e4m3fn)
        vp, vs = quantize_blocks(vf, jnp.float8_e4m3fn)
        table = _table(B, maxb, nb, seed=10)
        pos = jnp.asarray([44, 17], jnp.int32)
        rng = np.random.default_rng(11)
        q = jnp.asarray(rng.standard_normal((B, 1, nq, hd), np.float32),
                        jnp.bfloat16)
        kn, vn = (jnp.asarray(
            rng.standard_normal((B, nkv, hd), np.float32), jnp.bfloat16)
            for _ in range(2))

        def run(fused):
            att, *_ = jax.jit(
                lambda q, kn, vn, kp, vp, ks, vs: paged_decode_attention(
                    q, kn, vn, kp, vp, table, pos, k_scale=ks,
                    v_scale=vs, fused=fused)
            )(q, kn, vn, kp, vp, ks, vs)
            return att
        _close(run(True), run(False), 3e-2)
        _close(run("online"), run(False), 3e-2)


class TestFusedPagedWindow:
    def test_matches_gather_bf16(self):
        """The verify-window horizon (row i attends <= pos0+i) must
        agree between the kernel's per-row mask and the gather mask."""
        from hpx_tpu.ops.paged_attention import paged_window_attention
        B, W, nb, bs, maxb, nkv, nq, hd = 2, 4, 16, 16, 4, 2, 4, 64
        kp, vp = _pools(nb, bs, nkv, hd, seed=6)
        table = _table(B, maxb, nb, seed=7)
        pos0 = jnp.asarray([29, 12], jnp.int32)
        rng = np.random.default_rng(8)
        q = jnp.asarray(rng.standard_normal((B, W, nq, hd), np.float32),
                        jnp.bfloat16)
        kn, vn = (jnp.asarray(
            rng.standard_normal((B, W, nkv, hd), np.float32),
            jnp.bfloat16) for _ in range(2))

        def run(fused):
            att, *_ = jax.jit(
                lambda q, kn, vn, kp, vp: paged_window_attention(
                    q, kn, vn, kp, vp, table, pos0, fused=fused)
            )(q, kn, vn, kp, vp)
            return att
        _close(run(True), run(False), 3e-2)
        # per-window-row horizon under the online (acc, m, l) carry
        _close(run("online"), run(False), 3e-2)
