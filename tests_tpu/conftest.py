"""Real-chip kernel tests (SURVEY.md §4; VERDICT r3 weak #7).

Unlike tests/conftest.py this does NOT force the CPU platform — the
whole point is compiling the pallas kernels through Mosaic on the real
TPU, so a Mosaic regression fails a test instead of silently showing up
as a bench drop. Every test is marked `tpu` and auto-skips off-chip.

Run on the bench host:  python -m pytest tests_tpu -q
"""

import jax
import pytest


def pytest_collection_modifyitems(config, items):
    on_tpu = False
    try:
        on_tpu = jax.default_backend() == "tpu"
    except Exception:  # noqa: BLE001
        pass
    if on_tpu:
        return
    skip = pytest.mark.skip(reason="real TPU chip not available")
    for item in items:
        if "tpu" in item.keywords:
            item.add_marker(skip)
