"""Real-chip kernel tests (SURVEY.md §4; VERDICT r3 weak #7).

Unlike tests/conftest.py this does NOT force the CPU platform — the
whole point is compiling the pallas kernels through Mosaic on the real
TPU, so a Mosaic regression fails a test instead of silently showing up
as a bench drop. Every test is marked `tpu` and auto-skips off-chip.

Run on the bench host:  python -m pytest tests_tpu -q
"""

import subprocess
import sys

import pytest


def _chip_responds(timeout_s: float = 120.0) -> bool:
    """Probe the accelerator in a THROWAWAY subprocess: a wedged device
    tunnel hangs jax.devices() forever inside whatever process asks
    (observed repeatedly this round) — probing in-process would wedge
    pytest collection itself."""
    import os
    forced = os.environ.get("JAX_PLATFORMS",
                            os.environ.get("JAX_PLATFORM_NAME", ""))
    if forced and "tpu" not in forced and "axon" not in forced:
        return False          # explicitly non-TPU env: skip the probe
    try:
        p = subprocess.run(
            [sys.executable, "-c",
             "import jax; import sys; "
             "sys.stdout.write(jax.default_backend())"],
            capture_output=True, text=True, timeout=timeout_s)
        return p.returncode == 0 and p.stdout.strip() == "tpu"
    except Exception:  # noqa: BLE001
        return False


def pytest_collection_modifyitems(config, items):
    if not any("tpu" in item.keywords for item in items):
        return
    if _chip_responds():
        return
    skip = pytest.mark.skip(
        reason="real TPU chip not available (or tunnel unresponsive)")
    for item in items:
        if "tpu" in item.keywords:
            item.add_marker(skip)
