"""Real-chip leg of the expert-parallel MoE decode contract: the
(dp, tp)-mesh MoE server must emit byte-identical tokens to the
single-device MoE server ON THE REAL TPU MESH — the tiled all_to_all
exchange compiled for the actual interconnect, not the CPU-smoke
host-device emulation tests/test_sharded_moe_serving.py pins.

Skips cleanly off-chip (see conftest).  Each identity run prints a
provenance line stamped with the live backend — while the device
tunnel is down these rows can only ever say ``"onchip": false`` (the
CPU smoke already covers that case), so the BENCH trajectory stays
honest: no MoE mesh number claims chip provenance until a run on real
hardware banks one.
"""

import json

import jax
import numpy as np
import pytest

pytestmark = pytest.mark.tpu


def _provenance(engine, **extra):
    line = {"engine": engine,
            "onchip": jax.default_backend() == "tpu"}
    line.update(extra)
    print(json.dumps(line), flush=True)


class TestExpertParallelDecodeOnChip:
    @pytest.mark.parametrize("paged", [False, True],
                             ids=["dense", "paged"])
    def test_mesh_matches_single_device(self, paged):
        from hpx_tpu.models import transformer as tfm
        from hpx_tpu.models.serving import ContinuousServer
        if len(jax.devices()) < 4:
            pytest.skip("needs >=4 TPU devices for the 2x2 mesh")
        cfg = tfm.TransformerConfig(
            vocab=256, d_model=128, n_heads=8, head_dim=16,
            n_layers=2, d_ff=256, n_experts=4, moe_top_k=2,
            moe_capacity=4.0)
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        mesh = jax.sharding.Mesh(
            np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
        reqs = [dict(prompt=[3, 1, 4], max_new=9),
                dict(prompt=[2, 7], max_new=5),
                dict(prompt=[5, 6, 7, 8, 9], max_new=12),
                dict(prompt=[3, 1, 4], max_new=8, temperature=0.9,
                     key=jax.random.PRNGKey(7))]
        kw = dict(paged=True) if paged else {}
        outs = {}
        for name, m in (("single", None), ("mesh", mesh)):
            srv = ContinuousServer(params, cfg, slots=4, smax=64,
                                   mesh=m, **kw)
            for r in reqs:
                srv.submit(**r)
            outs[name] = srv.run()
            if m is not None:
                assert srv._ep_axis == "tp" and srv._ep_size == 2
                assert srv._moe_routed > 0
                assert srv._moe_dropped == 0     # auto = drop-free
        assert outs["single"] == outs["mesh"]
        _provenance("serving_moe_tpu_identity",
                    paged=paged, identical=True)
