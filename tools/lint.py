#!/usr/bin/env python
"""The hpxlint CI gate: one full three-tier sweep of the tree.

Runs every registered rule (file, project, and dataflow tiers) over
``hpx_tpu/`` with ``--format=github`` so findings render as inline PR
annotations, and exits non-zero on any unjustified finding OR any
stale baseline entry — the baseline only burns down, it never rots.

Invoked by the tier-1 test battery (``tests/test_dataflow.py``) and
usable standalone::

    python tools/lint.py            # gate: github annotations, exit 1 on dirt
    python tools/lint.py --text     # same gate, human-readable output

Always scans from the repo root so the committed baseline's relative
paths match regardless of the caller's cwd.
"""

from __future__ import annotations

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    fmt = "text" if "--text" in argv else "github"
    os.chdir(REPO_ROOT)
    sys.path.insert(0, REPO_ROOT)
    from hpx_tpu.analysis.cli import main as hpxlint
    return hpxlint(["--format", fmt, "hpx_tpu"])


if __name__ == "__main__":
    sys.exit(main())
