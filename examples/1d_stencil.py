"""1d_stencil — the heat-equation workload family (config #2).

Reference analog: examples/1d_stencil/1d_stencil_{1,4}.cpp. Three
variants, same physics:
  serial    — whole-array jit step loop (1d_stencil_1)
  dataflow  — per-partition futures DAG via hpx.dataflow (1d_stencil_4)
  fused     — T steps fused per dispatch, pallas in-VMEM where it fits
              (the TPU-first production configuration)

Usage: python examples/1d_stencil.py [nx] [np] [nt]
"""

import sys

sys.path.insert(0, ".")
from examples._common import setup_platform  # noqa: E402

argv = setup_platform()

import numpy as np  # noqa: E402

import hpx_tpu as hpx  # noqa: E402
from hpx_tpu.models.stencil1d import (  # noqa: E402
    StencilParams, gather_dataflow_result, init_domain, print_time_results,
    stencil_dataflow, stencil_fused, stencil_serial)


def main() -> int:
    nx = int(argv[0]) if argv else 1 << 14
    np_ = int(argv[1]) if len(argv) > 1 else 8
    nt = int(argv[2]) if len(argv) > 2 else 64
    p = StencilParams(nx=nx, np_=np_, nt=nt)
    u0 = init_domain(p)

    t = hpx.HighResolutionTimer()
    ref = np.asarray(stencil_serial(p, u0))
    print_time_results("serial", t.elapsed(), p)

    t.restart()
    out = gather_dataflow_result(stencil_dataflow(p, u0=u0))
    print_time_results("dataflow", t.elapsed(), p)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)

    t.restart()
    fused = stencil_fused(p, u0)
    print_time_results("fused", t.elapsed(), p)
    np.testing.assert_allclose(np.asarray(fused), ref, rtol=1e-4,
                               atol=1e-5)
    print("all variants agree")
    return 0


if __name__ == "__main__":
    sys.exit(main())
